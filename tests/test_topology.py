"""Topology zoo parity tests (reference model: test/torch_basics_test.py)."""

import numpy as np
import networkx as nx
import pytest

from bluefog_tpu import topology_util as tu


class TestStaticGraphs:
    def test_expo2_neighbors(self):
        # reference asserts expo2 in-neighbors of rank r are r - 2^k
        # (torch_basics_test.py topology tests)
        size = 8
        topo = tu.ExponentialTwoGraph(size)
        for r in range(size):
            expected_in = sorted({(r - 2 ** k) % size for k in range(3)})
            assert tu.in_neighbor_ranks(topo, r) == expected_in
            expected_out = sorted({(r + 2 ** k) % size for k in range(3)})
            assert tu.out_neighbor_ranks(topo, r) == expected_out

    def test_expo2_weights_uniform(self):
        topo = tu.ExponentialTwoGraph(8)
        sw, nw = tu.GetRecvWeights(topo, 0)
        assert sw == pytest.approx(0.25)
        assert all(w == pytest.approx(0.25) for w in nw.values())
        assert len(nw) == 3

    def test_expo_graph_nonpow2_size(self):
        topo = tu.ExponentialGraph(12)
        # distances 1, 2, 4, 8 are powers of two within 12 nodes
        assert tu.out_neighbor_ranks(topo, 0) == [1, 2, 4, 8]

    def test_symmetric_exponential(self):
        topo = tu.SymmetricExponentialGraph(12, base=4)
        # distances d with d or (12-d) in {1, 4}: 1, 4, 8, 11
        assert tu.out_neighbor_ranks(topo, 0) == [1, 4, 8, 11]

    def test_ring_styles(self):
        bi = tu.RingGraph(8, connect_style=0)
        assert tu.in_neighbor_ranks(bi, 0) == [1, 7]
        left = tu.RingGraph(8, connect_style=1)
        assert tu.out_neighbor_ranks(left, 2) == [1]
        right = tu.RingGraph(8, connect_style=2)
        assert tu.out_neighbor_ranks(right, 2) == [3]

    def test_ring_small_sizes(self):
        assert tu.RingGraph(1).number_of_nodes() == 1
        W = tu.weight_matrix(tu.RingGraph(2))
        np.testing.assert_allclose(W, [[0.5, 0.5], [0.5, 0.5]])

    def test_mesh_grid_doubly_stochastic(self):
        W = tu.weight_matrix(tu.MeshGrid2DGraph(6))
        np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=1e-12)
        np.testing.assert_allclose(W.sum(axis=1), 1.0, atol=1e-12)

    def test_mesh_grid_explicit_shape(self):
        topo = tu.MeshGrid2DGraph(6, shape=(2, 3))
        assert set(tu.out_neighbor_ranks(topo, 0)) == {1, 3}

    def test_star(self):
        topo = tu.StarGraph(8)
        assert tu.in_neighbor_ranks(topo, 3) == [0]
        assert tu.in_neighbor_ranks(topo, 0) == [1, 2, 3, 4, 5, 6, 7]
        W = tu.weight_matrix(topo)
        np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=1e-12)

    def test_fully_connected(self):
        topo = tu.FullyConnectedGraph(5)
        W = tu.weight_matrix(topo)
        np.testing.assert_allclose(W, np.full((5, 5), 0.2))

    def test_column_stochastic_all(self):
        # every graph's combine matrix must preserve the global average
        for builder in (
            tu.ExponentialTwoGraph,
            tu.ExponentialGraph,
            lambda n: tu.SymmetricExponentialGraph(n, 2),
            tu.MeshGrid2DGraph,
            tu.StarGraph,
            tu.RingGraph,
            tu.FullyConnectedGraph,
        ):
            W = tu.weight_matrix(builder(8))
            np.testing.assert_allclose(
                W.sum(axis=1), 1.0, atol=1e-12,
                err_msg=f"{builder} rows must sum to 1",
            )

    def test_equivalence(self):
        assert tu.IsTopologyEquivalent(
            tu.ExponentialTwoGraph(8), tu.ExponentialGraph(8, 2)
        )
        assert not tu.IsTopologyEquivalent(
            tu.RingGraph(8), tu.ExponentialTwoGraph(8)
        )
        assert not tu.IsTopologyEquivalent(None, tu.RingGraph(4))

    def test_is_regular(self):
        assert tu.IsRegularGraph(tu.RingGraph(8))
        assert not tu.IsRegularGraph(tu.StarGraph(8))


class TestCombinePlans:
    def test_shift_support_expo2(self):
        W = tu.weight_matrix(tu.ExponentialTwoGraph(8))
        assert tu.shift_support(W) == [1, 2, 4]

    def test_shift_support_ring(self):
        W = tu.weight_matrix(tu.RingGraph(8))
        assert tu.shift_support(W) == [1, 7]

    def test_dynamic_weight_matrix_uniform(self):
        sends = {0: [1], 1: [2], 2: [3], 3: [0]}
        W = tu.dynamic_weight_matrix(4, sends)
        # each rank receives from exactly one peer: 0.5 / 0.5 split
        np.testing.assert_allclose(np.diag(W), 0.5)
        assert W[0, 1] == pytest.approx(0.5)
        np.testing.assert_allclose(W.sum(axis=1), 1.0)


class TestDynamicIterators:
    def test_dynamic_send_recv_consistency(self):
        # the send/recv sets of all ranks must mirror each other every step
        topo = tu.ExponentialTwoGraph(8)
        gens = [tu.GetDynamicSendRecvRanks(topo, r) for r in range(8)]
        for _ in range(12):
            steps = [next(g) for g in gens]
            for r, (send, _recv) in enumerate(steps):
                assert len(send) == 1
                dst = send[0]
                assert r in steps[dst][1], f"rank {dst} must expect recv from {r}"

    def test_dynamic_send_recv_cycles_through_neighbors(self):
        topo = tu.ExponentialTwoGraph(8)
        gen = tu.GetDynamicSendRecvRanks(topo, 0)
        sends = [next(gen)[0][0] for _ in range(3)]
        assert sorted(sends) == [1, 2, 4]  # out-neighbors, clockwise order

    def test_exp2_machine_ranks(self):
        gen = tu.GetExp2DynamicSendRecvMachineRanks(
            world_size=16, local_size=4, self_rank=5, local_rank=1
        )
        (s0, r0) = next(gen)
        (s1, r1) = next(gen)
        # machine 1 of 4: distances cycle 1, 2
        assert s0 == [2] and r0 == [0]
        assert s1 == [3] and r1 == [3]

    def test_inner_outer_ring_consistency(self):
        world, local = 12, 4
        gens = [
            tu.GetInnerOuterRingDynamicSendRecvRanks(world, local, r)
            for r in range(world)
        ]
        for _ in range(10):
            steps = [next(g) for g in gens]
            for r, (send, recv) in enumerate(steps):
                dst, src = send[0], recv[0]
                assert steps[dst][1] == [r], "receiver must expect this sender"
                assert steps[src][0] == [r], "sender must target this receiver"

    def test_inner_outer_expo2_consistency(self):
        world, local = 16, 4
        gens = [
            tu.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
            for r in range(world)
        ]
        for _ in range(16):
            steps = [next(g) for g in gens]
            for r, (send, recv) in enumerate(steps):
                dst, src = send[0], recv[0]
                assert steps[dst][1] == [r]
                assert steps[src][0] == [r]
