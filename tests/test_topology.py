"""Topology zoo parity tests (reference model: test/torch_basics_test.py)."""

import numpy as np
import networkx as nx
import pytest

from bluefog_tpu import topology_util as tu


class TestStaticGraphs:
    def test_expo2_neighbors(self):
        # reference asserts expo2 in-neighbors of rank r are r - 2^k
        # (torch_basics_test.py topology tests)
        size = 8
        topo = tu.ExponentialTwoGraph(size)
        for r in range(size):
            expected_in = sorted({(r - 2 ** k) % size for k in range(3)})
            assert tu.in_neighbor_ranks(topo, r) == expected_in
            expected_out = sorted({(r + 2 ** k) % size for k in range(3)})
            assert tu.out_neighbor_ranks(topo, r) == expected_out

    def test_expo2_weights_uniform(self):
        topo = tu.ExponentialTwoGraph(8)
        sw, nw = tu.GetRecvWeights(topo, 0)
        assert sw == pytest.approx(0.25)
        assert all(w == pytest.approx(0.25) for w in nw.values())
        assert len(nw) == 3

    def test_expo_graph_nonpow2_size(self):
        topo = tu.ExponentialGraph(12)
        # distances 1, 2, 4, 8 are powers of two within 12 nodes
        assert tu.out_neighbor_ranks(topo, 0) == [1, 2, 4, 8]

    def test_symmetric_exponential(self):
        topo = tu.SymmetricExponentialGraph(12, base=4)
        # distances d with d or (12-d) in {1, 4}: 1, 4, 8, 11
        assert tu.out_neighbor_ranks(topo, 0) == [1, 4, 8, 11]

    def test_ring_styles(self):
        bi = tu.RingGraph(8, connect_style=0)
        assert tu.in_neighbor_ranks(bi, 0) == [1, 7]
        left = tu.RingGraph(8, connect_style=1)
        assert tu.out_neighbor_ranks(left, 2) == [1]
        right = tu.RingGraph(8, connect_style=2)
        assert tu.out_neighbor_ranks(right, 2) == [3]

    def test_ring_small_sizes(self):
        assert tu.RingGraph(1).number_of_nodes() == 1
        W = tu.weight_matrix(tu.RingGraph(2))
        np.testing.assert_allclose(W, [[0.5, 0.5], [0.5, 0.5]])

    def test_mesh_grid_doubly_stochastic(self):
        W = tu.weight_matrix(tu.MeshGrid2DGraph(6))
        np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=1e-12)
        np.testing.assert_allclose(W.sum(axis=1), 1.0, atol=1e-12)

    def test_mesh_grid_explicit_shape(self):
        topo = tu.MeshGrid2DGraph(6, shape=(2, 3))
        assert set(tu.out_neighbor_ranks(topo, 0)) == {1, 3}

    def test_star(self):
        topo = tu.StarGraph(8)
        assert tu.in_neighbor_ranks(topo, 3) == [0]
        assert tu.in_neighbor_ranks(topo, 0) == [1, 2, 3, 4, 5, 6, 7]
        W = tu.weight_matrix(topo)
        np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=1e-12)

    def test_fully_connected(self):
        topo = tu.FullyConnectedGraph(5)
        W = tu.weight_matrix(topo)
        np.testing.assert_allclose(W, np.full((5, 5), 0.2))

    def test_column_stochastic_all(self):
        # every graph's combine matrix must preserve the global average
        for builder in (
            tu.ExponentialTwoGraph,
            tu.ExponentialGraph,
            lambda n: tu.SymmetricExponentialGraph(n, 2),
            tu.MeshGrid2DGraph,
            tu.StarGraph,
            tu.RingGraph,
            tu.FullyConnectedGraph,
        ):
            W = tu.weight_matrix(builder(8))
            np.testing.assert_allclose(
                W.sum(axis=1), 1.0, atol=1e-12,
                err_msg=f"{builder} rows must sum to 1",
            )

    def test_equivalence(self):
        assert tu.IsTopologyEquivalent(
            tu.ExponentialTwoGraph(8), tu.ExponentialGraph(8, 2)
        )
        assert not tu.IsTopologyEquivalent(
            tu.RingGraph(8), tu.ExponentialTwoGraph(8)
        )
        assert not tu.IsTopologyEquivalent(None, tu.RingGraph(4))

    def test_is_regular(self):
        assert tu.IsRegularGraph(tu.RingGraph(8))
        assert not tu.IsRegularGraph(tu.StarGraph(8))


class TestCombinePlans:
    def test_shift_support_expo2(self):
        W = tu.weight_matrix(tu.ExponentialTwoGraph(8))
        assert tu.shift_support(W) == [1, 2, 4]

    def test_shift_support_ring(self):
        W = tu.weight_matrix(tu.RingGraph(8))
        assert tu.shift_support(W) == [1, 7]

    def test_dynamic_weight_matrix_uniform(self):
        sends = {0: [1], 1: [2], 2: [3], 3: [0]}
        W = tu.dynamic_weight_matrix(4, sends)
        # each rank receives from exactly one peer: 0.5 / 0.5 split
        np.testing.assert_allclose(np.diag(W), 0.5)
        assert W[0, 1] == pytest.approx(0.5)
        np.testing.assert_allclose(W.sum(axis=1), 1.0)


class TestDynamicIterators:
    def test_dynamic_send_recv_consistency(self):
        # the send/recv sets of all ranks must mirror each other every step
        topo = tu.ExponentialTwoGraph(8)
        gens = [tu.GetDynamicSendRecvRanks(topo, r) for r in range(8)]
        for _ in range(12):
            steps = [next(g) for g in gens]
            for r, (send, _recv) in enumerate(steps):
                assert len(send) == 1
                dst = send[0]
                assert r in steps[dst][1], f"rank {dst} must expect recv from {r}"

    def test_dynamic_send_recv_cycles_through_neighbors(self):
        topo = tu.ExponentialTwoGraph(8)
        gen = tu.GetDynamicSendRecvRanks(topo, 0)
        sends = [next(gen)[0][0] for _ in range(3)]
        assert sorted(sends) == [1, 2, 4]  # out-neighbors, clockwise order

    def test_exp2_machine_ranks(self):
        gen = tu.GetExp2DynamicSendRecvMachineRanks(
            world_size=16, local_size=4, self_rank=5, local_rank=1
        )
        (s0, r0) = next(gen)
        (s1, r1) = next(gen)
        # machine 1 of 4: distances cycle 1, 2
        assert s0 == [2] and r0 == [0]
        assert s1 == [3] and r1 == [3]

    def test_inner_outer_ring_consistency(self):
        world, local = 12, 4
        gens = [
            tu.GetInnerOuterRingDynamicSendRecvRanks(world, local, r)
            for r in range(world)
        ]
        for _ in range(10):
            steps = [next(g) for g in gens]
            for r, (send, recv) in enumerate(steps):
                dst, src = send[0], recv[0]
                assert steps[dst][1] == [r], "receiver must expect this sender"
                assert steps[src][0] == [r], "sender must target this receiver"

    def test_inner_outer_expo2_consistency(self):
        world, local = 16, 4
        gens = [
            tu.GetInnerOuterExpo2DynamicSendRecvRanks(world, local, r)
            for r in range(world)
        ]
        for _ in range(16):
            steps = [next(g) for g in gens]
            for r, (send, recv) in enumerate(steps):
                dst, src = send[0], recv[0]
                assert steps[dst][1] == [r]
                assert steps[src][0] == [r]


class TestPruneReadmit:
    """prune_dead_ranks edge cases + readmit_ranks inversion (ISSUE r9)."""

    def _column_sums(self, G):
        return nx.to_numpy_array(G).sum(axis=0)

    def test_prune_to_world_of_one(self):
        G = tu.ExponentialTwoGraph(8)
        Gp = tu.prune_dead_ranks(G, set(range(8)) - {3})
        W = nx.to_numpy_array(Gp)
        # sole survivor holds its value; corpses keep unit self-loops
        assert W[3, 3] == pytest.approx(1.0)
        assert np.allclose(np.diag(W), 1.0)
        assert np.count_nonzero(W - np.diag(np.diag(W))) == 0

    def test_prune_everyone_raises(self):
        G = tu.RingGraph(4)
        with pytest.raises(ValueError, match="every rank is dead"):
            tu.prune_dead_ranks(G, {0, 1, 2, 3})

    def test_prune_star_center(self):
        """Killing the StarGraph center leaves every spoke holding its own
        value (all their in-edges pointed at the corpse) with column sums
        preserved — degraded but well-formed, never NaN."""
        G = tu.StarGraph(6)
        Gp = tu.prune_dead_ranks(G, {0})
        W = nx.to_numpy_array(Gp)
        assert np.isfinite(W).all()
        assert np.allclose(self._column_sums(Gp), self._column_sums(G))
        for j in range(1, 6):
            # spoke j's only in-neighbor was the center: self weight
            # re-absorbs the whole column mass
            assert W[j, j] == pytest.approx(1.0)
            assert np.count_nonzero(W[:, j]) == 1

    def test_double_prune_idempotent(self):
        G = tu.ExponentialTwoGraph(8)
        once = tu.prune_dead_ranks(G, {2, 5})
        twice = tu.prune_dead_ranks(once, {2, 5})
        assert tu.IsTopologyEquivalent(once, twice)

    def test_prune_composes_on_original(self):
        """prune(prune(G, a), b) == prune(G, a | b): the stashed record
        keeps renormalization anchored to the ORIGINAL weights."""
        G = tu.ExponentialTwoGraph(8)
        chained = tu.prune_dead_ranks(tu.prune_dead_ranks(G, {1}), {6})
        direct = tu.prune_dead_ranks(G, {1, 6})
        assert np.allclose(nx.to_numpy_array(chained),
                           nx.to_numpy_array(direct))

    @pytest.mark.parametrize("factory", [
        tu.ExponentialTwoGraph, tu.RingGraph, tu.StarGraph,
        tu.FullyConnectedGraph,
    ])
    def test_readmit_roundtrip(self, factory):
        G = factory(8)
        dead = {2, 5}
        back = tu.readmit_ranks(tu.prune_dead_ranks(G, dead), dead)
        assert tu.IsTopologyEquivalent(back, G)
        assert np.allclose(nx.to_numpy_array(back), nx.to_numpy_array(G))

    def test_partial_readmit(self):
        G = tu.ExponentialTwoGraph(8)
        pruned = tu.prune_dead_ranks(G, {2, 5})
        part = tu.readmit_ranks(pruned, {5})
        assert tu.IsTopologyEquivalent(part, tu.prune_dead_ranks(G, {2}))

    def test_readmit_from_original_without_record(self):
        """A pruned matrix that lost its stash (serialization strips graph
        attributes) still readmits exactly when the original is supplied."""
        G = tu.ExponentialTwoGraph(8)
        pruned = tu.prune_dead_ranks(G, {2, 5})
        stripped = nx.from_numpy_array(nx.to_numpy_array(pruned),
                                       create_using=nx.DiGraph)
        back = tu.readmit_ranks(stripped, {2, 5}, original=G)
        assert tu.IsTopologyEquivalent(back, G)

    def test_readmit_rejects_unknown_ranks(self):
        G = tu.ExponentialTwoGraph(8)
        pruned = tu.prune_dead_ranks(G, {2})
        with pytest.raises(ValueError, match="not in the pruned set"):
            tu.readmit_ranks(pruned, {3})
        with pytest.raises(ValueError, match="no prune record"):
            tu.readmit_ranks(G, {2})
