"""Failure detection + coordinated shutdown over the control plane.

SURVEY §5.3 / VERDICT A3: the reference detects stalled/missing ranks
(operations.cc:387-432) and coordinates shutdown via a SHUTDOWN broadcast
(operations.cc:1074-1095). Here two PeerMonitors — standing in for two
controller processes — exchange heartbeats through one control-plane server:
a stopped heart is detected, a resumed one clears, and the shutdown flag
published by one side is seen by the other.
"""

import socket
import time

import pytest

from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import heartbeat, native

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def two_clients():
    port = _free_port()
    server = native.ControlPlaneServer(2, port)
    a = native.ControlPlaneClient("127.0.0.1", port, 0)
    b = native.ControlPlaneClient("127.0.0.1", port, 1)
    yield a, b
    a.close()
    b.close()
    server.stop()


def _attach(monkeypatch, client):
    """Point the control_plane module-level singleton at a raw client."""
    monkeypatch.setattr(cp, "_client", client)


def test_peer_failure_detected_and_gated_recovery(two_clients, monkeypatch):
    """Death is detected; a raw heartbeat resume alone does NOT re-admit
    (the flapping-peer hole, ISSUE r9) — the peer becomes a suspect and
    only returns to live membership once a new incarnation registered and
    its quarantine completed."""
    a, b = two_clients
    _attach(monkeypatch, a)
    mon = heartbeat.PeerMonitor(0, 2, interval_sec=0.05, timeout_sec=0.3)

    # peer 1 beats by hand (its "process" is client b)
    def beat():
        b.put("bf.hb.1", int(time.monotonic_ns()))

    beat()
    mon._tick()
    assert mon.dead_peers() == set()
    epoch0 = mon.membership_epoch

    deadline = time.monotonic() + 5.0
    # silence: tick until the monitor declares peer 1 dead
    while time.monotonic() < deadline and 1 not in mon.dead_peers():
        time.sleep(0.05)
        mon._tick()
    assert mon.dead_peers() == {1}
    assert mon.membership_epoch > epoch0  # death bumped the epoch

    # resumed heartbeat does NOT clear the failure: dead_ranks() must never
    # shrink from a flapping peer's raw resume (stale params, stale
    # server-side identity) — it becomes a suspect instead
    beat()
    mon._tick()
    assert mon.dead_peers() == {1}
    assert mon.suspect_peers() == {1}
    beat()
    mon._tick()  # still gated on later ticks
    assert mon.dead_peers() == {1}

    # the re-admission gate: a NEW incarnation registers (normally the
    # server's kAttach handler writes these) and completes quarantine
    b.put("bf.inc.1", 1)
    beat()
    mon._tick()
    assert mon.dead_peers() == {1}, "registration alone must not re-admit"
    b.put("bf.q.1.1", 2)
    epoch1 = mon.membership_epoch
    beat()
    mon._tick()
    assert mon.dead_peers() == set()
    assert mon.suspect_peers() == set()
    assert mon.membership_epoch > epoch1  # re-admission bumped the epoch


def test_shutdown_flag_propagates_and_acks(two_clients, monkeypatch):
    a, b = two_clients
    _attach(monkeypatch, a)
    mon = heartbeat.PeerMonitor(0, 2, interval_sec=0.05, timeout_sec=10.0)
    mon._tick()
    assert not mon.shutdown_seen

    # "process 1" announces shutdown through its own client
    b.put("bf.shutdown.flag.1", 1)
    mon._tick()
    assert mon.shutdown_seen
    # the monitor acked, so the announcer's bounded wait can return
    assert b.get("bf.shutdown.ack.0") == 1


def test_announcer_waits_for_ack_then_returns(two_clients, monkeypatch):
    a, b = two_clients
    _attach(monkeypatch, a)
    # peer already acked: announce returns immediately
    b.put("bf.shutdown.ack.1", 1)
    t0 = time.monotonic()
    heartbeat.announce_shutdown(0, 2, grace_sec=5.0)
    assert time.monotonic() - t0 < 1.0
    assert a.get("bf.shutdown.flag.0") == 1


def test_announcer_grace_bounds_the_wait(two_clients, monkeypatch):
    a, b = two_clients
    _attach(monkeypatch, a)
    # nobody ever acks: the wait must end at the grace bound, not hang
    t0 = time.monotonic()
    heartbeat.announce_shutdown(0, 2, grace_sec=0.3)
    dt = time.monotonic() - t0
    assert 0.25 <= dt < 3.0


def test_second_announcer_skips_the_wait(two_clients, monkeypatch):
    a, b = two_clients
    _attach(monkeypatch, a)
    b.put("bf.shutdown.flag.1", 1)  # peer announced first
    t0 = time.monotonic()
    heartbeat.announce_shutdown(0, 2, grace_sec=5.0)
    assert time.monotonic() - t0 < 1.0


def test_monitor_thread_lifecycle(two_clients, monkeypatch):
    a, b = two_clients
    _attach(monkeypatch, a)
    mon = heartbeat.PeerMonitor(0, 2, interval_sec=0.02, timeout_sec=10.0)
    mon.start()
    try:
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and b.get("bf.hb.0") == 0:
            time.sleep(0.02)
        assert b.get("bf.hb.0") != 0, "monitor never published a heartbeat"
    finally:
        mon.stop()


def test_announce_shutdown_noop_without_control_plane(monkeypatch):
    _attach(monkeypatch, None)
    heartbeat.announce_shutdown(0, 2)  # must not raise
    assert heartbeat.shutdown_requested() in (False,)
