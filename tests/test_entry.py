"""Driver-contract tests: __graft_entry__ must keep working.

Round-1 lesson (VERDICT #1): the driver's multi-chip dryrun failed on device
pinning while the suite stayed green, because nothing tested the driver-facing
entry points. These tests exercise exactly what the driver runs: ``entry()``
traceability and ``dryrun_multichip(8)`` end-to-end on the CPU mesh.
"""

import os
import sys

import jax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import __graft_entry__ as graft  # noqa: E402


@pytest.mark.slow  # ResNet-50 trace+lower is minutes-scale on 1 core
def test_entry_traces():
    fn, args = graft.entry()
    # The driver compile-checks single-chip; tracing catches API breakage
    # without paying a full ResNet-50 CPU compile in the suite.
    lowered = jax.jit(fn).lower(*args)
    assert lowered is not None


def test_dryrun_multichip_8():
    # No device precondition: the dryrun re-execs itself in a CPU-pinned
    # subprocess that forces its own 8-device mesh, independent of this
    # process's backend (the round-3 tunnel-hang fix).
    graft.dryrun_multichip(8)
