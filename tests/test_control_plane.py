"""Window scalar protocols over the native control plane.

Round-1 gap (VERDICT #3): the TCP control plane existed but nothing used it.
These tests run the WINDOW API — not the raw client — against a live
control-plane server: versions and push-sum p live in the shared KV
(reference: version windows, mpi_controller.cc:1281-1393), mutexes in the
server's lock table (fetch-and-op locks, mpi_controller.cc:1532-1602), and an
external actor (a second client, standing in for another controller process)
must observe and exclude the window ops.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import bluefog_tpu as bf
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import native

from conftest import cpu_devices

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def bf_cp():
    """bf over 8 CPU devices with a forced control plane (world=1)."""
    port = _free_port()
    env = {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active(), "control plane must attach for this test"
    yield port
    bf.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    cp.reset_for_test()


def test_window_backend_is_control_plane(bf_cp):
    x = jnp.arange(8.0).reshape(8, 1)
    assert bf.win_create(x, "cp.backend")
    win = win_ops._get_window("cp.backend")
    assert isinstance(win.host, win_ops._ControlPlaneWinHost)
    bf.win_free("cp.backend")


def test_versions_through_window_api(bf_cp):
    x = jnp.ones((8, 3))
    assert bf.win_create(x, "cp.ver")
    # put bumps every touched in-edge's version...
    bf.win_put(x, "cp.ver")
    for r in range(8):
        vers = bf.get_win_version("cp.ver", rank=r)
        assert vers, f"rank {r} has no in-neighbors?"
        assert all(v == 1 for v in vers.values()), vers
    bf.win_put(x, "cp.ver")
    assert all(v == 2 for v in bf.get_win_version("cp.ver", rank=3).values())
    # ...and update resets the read buffers' versions to 0.
    bf.win_update("cp.ver")
    for r in range(8):
        assert all(v == 0 for v in bf.get_win_version("cp.ver", rank=r).values())
    bf.win_free("cp.ver")


def test_update_values_match_local_backend(bf_cp):
    """The CP backend must not change numerics: compare against local."""
    x = jnp.arange(8.0).reshape(8, 1) + 1.0
    assert bf.win_create(x, "cp.num")
    bf.win_put(x, "cp.num")
    got = np.asarray(bf.win_update("cp.num"))

    topo = bf.load_topology()
    expect = np.zeros((8, 1))
    for r in range(8):
        nbrs = bf.topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        expect[r] = u * (r + 1) + u * sum(s + 1.0 for s in nbrs)
    np.testing.assert_allclose(got, expect, rtol=1e-6)
    bf.win_free("cp.num")


def test_push_sum_invariant_on_control_plane(bf_cp):
    """Total mass (sum of numerators) and sum of p stay conserved."""
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = jnp.arange(8.0).reshape(8, 1) + 1.0
        assert bf.win_create(x, "cp.ps", zero_init=True)
        topo = bf.load_topology()
        outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
                for r in range(8)}
        sw = {r: 1.0 / (outd[r] + 1) for r in range(8)}
        dw = {r: {d: 1.0 / (outd[r] + 1)
                  for d in bf.topology_util.out_neighbor_ranks(topo, r)}
              for r in range(8)}
        val = x
        for _ in range(5):
            bf.win_accumulate(val, "cp.ps", self_weight=sw, dst_weights=dw,
                              require_mutex=True)
            val = bf.win_update_then_collect("cp.ps")
            p = bf.win_associated_p_all("cp.ps")
            total = float(np.asarray(val).sum())
            assert abs(total - 36.0) < 1e-3          # sum(1..8) preserved
            assert abs(p.sum() - 8.0) < 1e-9         # p mass preserved
        # de-biased estimate converges toward the average 4.5
        est = np.asarray(val)[:, 0] / p
        assert np.abs(est - 4.5).max() < 2.0
        bf.win_free("cp.ps")
    finally:
        bf.turn_off_win_ops_with_associated_p()


def test_external_actor_mutex_excludes_window_op(bf_cp):
    """A second client (≈ another controller) holding a rank's mutex blocks
    require_mutex window ops until it releases — MPI fetch-and-op lock
    semantics over the shared server."""
    port = bf_cp
    x = jnp.ones((8, 2))
    assert bf.win_create(x, "cp.mu")

    actor = native.ControlPlaneClient("127.0.0.1", port, rank=1)
    try:
        # the actor grabs every rank's window mutex (key scheme is part of
        # the backend contract: w.<name>.mu.<rank>)
        for r in range(8):
            actor.lock(f"w.cp.mu.mu.{r}")
        done = threading.Event()

        def do_put():
            bf.win_put(x, "cp.mu", require_mutex=True)
            done.set()

        t = threading.Thread(target=do_put, daemon=True)
        t.start()
        time.sleep(0.4)
        assert not done.is_set(), "win_put proceeded through a held mutex"
        for r in range(8):
            actor.unlock(f"w.cp.mu.mu.{r}")
        assert done.wait(10.0), "win_put never completed after release"
        t.join(5.0)
    finally:
        actor.close()
    bf.win_free("cp.mu")


def test_win_mutex_context_on_control_plane(bf_cp):
    """bf.win_mutex must take the shared locks so an external trylock fails."""
    port = bf_cp
    x = jnp.ones((8, 2))
    assert bf.win_create(x, "cp.ctx")
    actor = native.ControlPlaneClient("127.0.0.1", port, rank=1)
    try:
        got = {}

        def try_grab():
            # lock blocks server-side; run it in a thread with a timeout
            actor.lock("w.cp.ctx.mu.1")
            got["locked"] = True
            actor.unlock("w.cp.ctx.mu.1")

        with bf.win_mutex("cp.ctx", ranks=[1]):
            t = threading.Thread(target=try_grab, daemon=True)
            t.start()
            t.join(0.4)
            assert "locked" not in got, "external actor acquired a held mutex"
        t.join(10.0)
        assert got.get("locked"), "external actor never got the mutex back"
    finally:
        actor.close()
    bf.win_free("cp.ctx")


# ---------------------------------------------------------------------------
# authenticated control plane (reference: HMAC-signed driver/task messages,
# run/horovodrun/common/util/network.py:69-86)
# ---------------------------------------------------------------------------

def test_auth_roundtrip_with_shared_secret():
    srv = native.ControlPlaneServer(1, _free_port(), secret="job-secret")
    try:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                       secret="job-secret")
        cl.put("auth.k", 41)
        assert cl.fetch_add("auth.k", 1) == 41
        assert cl.get("auth.k") == 42
        cl.put_bytes("auth.b", b"tensor bytes")
        assert cl.get_bytes("auth.b") == b"tensor bytes"
        cl.close()
    finally:
        srv.stop()


def test_auth_rejects_wrong_secret():
    srv = native.ControlPlaneServer(1, _free_port(), secret="right")
    try:
        with pytest.raises(OSError):
            native.ControlPlaneClient("127.0.0.1", srv.port, 0, secret="wrong")
    finally:
        srv.stop()


def test_auth_rejects_unauthenticated_client():
    """A client that never handshakes must not reach any server op: its
    first call fails instead of reading/writing KV or mutex state."""
    srv = native.ControlPlaneServer(1, _free_port(), secret="right")
    try:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0)  # no secret
        with pytest.raises(OSError):
            cl.put("stolen.key", 1)
        cl.close()
        # the authenticated path still works and saw none of the above
        good = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                         secret="right")
        assert good.get("stolen.key") == 0
        good.close()
    finally:
        srv.stop()


def test_mailbox_byte_cap_rejects_then_recovers():
    """ADVICE r3: deposit mailboxes must be bounded — a full mailbox is a
    targeted error, and draining makes it writable again."""
    srv = native.ControlPlaneServer(1, _free_port(), max_mailbox_bytes=1024)
    try:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0)
        cl.append_bytes("box", b"x" * 800)
        with pytest.raises(RuntimeError, match="full"):
            cl.append_bytes("box", b"y" * 800)
        # an oversized FIRST record still moves (cap bounds the backlog,
        # not the record size — mirroring kMaxTakeReply's one-record rule)
        cl.append_bytes("box2", b"z" * 2048)
        assert cl.take_bytes("box") == [b"x" * 800]
        cl.append_bytes("box", b"y" * 800)  # drained -> accepted again
        assert cl.take_bytes("box") == [b"y" * 800]
        assert cl.take_bytes("box2") == [b"z" * 2048]
        cl.close()
    finally:
        srv.stop()


def test_fetch_add_many_batches_version_bumps():
    srv = native.ControlPlaneServer(1, _free_port())
    try:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0)
        pre = cl.fetch_add_many(["v.a", "v.b", "v.a"])
        assert pre == [0, 0, 1]  # pipelined in order, fetch-THEN-add
        assert cl.get("v.a") == 2 and cl.get("v.b") == 1
        pre = cl.fetch_add_many(["v.a", "v.b"], deltas=[10, -1])
        assert pre == [2, 1]
        assert cl.get("v.a") == 12 and cl.get("v.b") == 0
        cl.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# topo-check re-arm (VERDICT r3 #5: the cache blind spot)
# ---------------------------------------------------------------------------

@pytest.fixture()
def bf_cp_world2(monkeypatch):
    """bf over 8 CPU devices with a forced TWO-controller control plane:
    this process is controller 0; the test plays controller 1 through a raw
    client (pre-posting its rendezvous check-ins)."""
    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "2",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_TOPO_CHECK_REARM": "4",
        "BLUEFOG_TOPO_CHECK_TIMEOUT": "1",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active() and cp.world() == 2
    peer = native.ControlPlaneClient("127.0.0.1", port, rank=1)
    yield peer
    peer.close()
    bf.shutdown()
    cp.reset_for_test()


def _peer_rearm_checkin(peer, world: int, h: str) -> None:
    """Play controller 1's half of the re-arm rendezvous: take a ticket
    from the shared counter and post (round+1, hash-prefix) under the fixed
    per-rank key — exactly what _rearm_rendezvous does."""
    from bluefog_tpu.ops import neighbors as nbr

    rnd = peer.fetch_add("tc.rearm.tickets", 1) // world
    h40 = int(h[:10], 16) & nbr._H40_MASK
    peer.put("tc.rearm.1", ((rnd + 1) << 40) | h40)


def test_topo_check_rearm_catches_desynced_schedule(bf_cp_world2):
    """Two controllers at different positions of the SAME cyclic schedule
    both hold previously-agreed matrices; pre-r4 both cache-hit forever and
    the divergence was never re-detected (VERDICT r3 weak #4). The periodic
    re-arm pairs controllers up at a shared ticket-counter round, so the
    de-sync RAISES at the next re-arm round — and the round number comes
    from the server, not local call counts (ADVICE r4)."""
    from bluefog_tpu.ops import neighbors as nbr

    peer = bf_cp_world2
    x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((8, 2)))

    def step_args(shift):
        sends = {r: [(r + shift) % 8] for r in range(8)}
        nw = {r: {(r - shift) % 8: 0.5} for r in range(8)}
        return dict(self_weight=0.5, neighbor_weights=nw,
                    send_neighbors=sends)

    def w_hash(shift):
        a = step_args(shift)
        W = nbr._dynamic_weight_matrix(
            8, a["send_neighbors"], a["self_weight"], a["neighbor_weights"],
            enable_topo_check=False)  # hash only; no rendezvous
        return nbr._w_hash(W)

    h1, h2 = w_hash(1), w_hash(2)
    # the peer agrees both steps of the schedule once (calls 1 and 2)
    peer.put(f"tc.{h1}.1", 1)
    peer.put(f"tc.{h2}.1", 1)
    bf.neighbor_allreduce(x, **step_args(1))  # call 1: agreed, cached
    bf.neighbor_allreduce(x, **step_args(2))  # call 2: agreed, cached
    bf.neighbor_allreduce(x, **step_args(1))  # call 3: warm cache-hit, free
    # call 4 = our re-arm trigger. The peer is DE-SYNCED: it sits at step 2
    # of the schedule and checks in h2 at the shared round; we dispatch
    # step 1 -> same round, different hash -> raise.
    _peer_rearm_checkin(peer, 2, h2)
    with pytest.raises(RuntimeError, match="topology re-check failed"):
        bf.neighbor_allreduce(x, **step_args(1))
    # recovery: in-sync peers agree at the NEXT re-arm round (call 8) and
    # warm steps in between stay free
    for c, shift in [(5, 1), (6, 2), (7, 1)]:
        bf.neighbor_allreduce(x, **step_args(shift))
    _peer_rearm_checkin(peer, 2, h2)
    bf.neighbor_allreduce(x, **step_args(2))  # call 8: re-arm agrees
    # bounded storage: re-arms reuse ONE key per controller + the ticket
    # counter — no per-round key accumulation (ADVICE r4)
    assert peer.get("tc.rearm.tickets") == 4
    assert peer.get("tc.rearm.0") and peer.get("tc.rearm.1")


# ---------------------------------------------------------------------------
# shard router unit behaviors (sharded control plane, ISSUE r14)
# ---------------------------------------------------------------------------

from bluefog_tpu.runtime.router import (ShardRouter, is_replicated_key,  # noqa: E402
                                        parse_endpoints)


def test_parse_endpoints_grammar():
    assert parse_endpoints("a:1,b:2") == [("a", 1), ("b", 2)]
    assert parse_endpoints(" a:1 , b:2 ") == [("a", 1), ("b", 2)]
    assert parse_endpoints("") == []
    with pytest.raises(ValueError):
        parse_endpoints("nocolon")
    with pytest.raises(ValueError):
        parse_endpoints("a:not_a_port")


def test_replicated_key_classes():
    """The replication boundary is load-bearing: membership-critical keys
    must survive a shard death, everything else is routed. A key family
    moving between classes is a protocol change, not a refactor."""
    for k in ("bf.membership.epoch", "bf.inc.3", "bf.q.2.5",
              "bf.shutdown.flag.1", "bf.shutdown.ack.0",
              "bf.cp.mailbox_cap_bytes", "bf.cp.shard_dead.0"):
        assert is_replicated_key(k), k
    for k in ("bf.hb.0", "bf.metrics.1", "bf.flight.0",
              "w.opt.ver.3", "w.opt.dep.1.0", "w.opt.self.2"):
        assert not is_replicated_key(k), k


@pytest.fixture()
def shard_trio():
    servers = [native.ControlPlaneServer(1, _free_port()) for _ in range(3)]
    yield servers
    for s in servers:
        s.stop()


def test_router_routing_is_stable_and_spread(shard_trio):
    r = ShardRouter([("127.0.0.1", s.port) for s in shard_trio], 0,
                    streams=1)
    names = [f"ob.{i}" for i in range(64)]
    owners = [r.shard_of(n) for n in names]
    assert owners == [r.shard_of(n) for n in names]  # pure + stable
    assert set(owners) == {0, 1, 2}                  # spread over all shards
    r.close()


def test_router_batches_preserve_caller_order(shard_trio):
    """Batch ops partition per shard and scatter results back by POSITION:
    callers must see results aligned with their name order regardless of
    how the names spread across shards."""
    r = ShardRouter([("127.0.0.1", s.port) for s in shard_trio], 0,
                    streams=1)
    names = [f"ob.{i}" for i in range(40)]
    r.put_many(names, list(range(40)))
    assert r.get_many(names) == list(range(40))
    assert r.fetch_add_many(names, deltas=[2] * 40) == list(range(40))
    assert r.get_many(names) == [i + 2 for i in range(40)]
    r.append_bytes_many(names, [str(i).encode() for i in range(40)])
    assert r.box_bytes_many(names) == [len(str(i)) for i in range(40)]
    recs = r.take_bytes_many(names)
    assert [lst[0] for lst in recs] == [str(i).encode() for i in range(40)]
    recs, owner = r.take_bytes_many_views(names)
    assert all(lst == [] for lst in recs)  # already drained
    owner.close()
    r.close()


def test_router_replicated_write_lands_on_every_shard(shard_trio):
    r = ShardRouter([("127.0.0.1", s.port) for s in shard_trio], 0,
                    streams=1)
    r.put("bf.q.4.2", 2)
    e = r.fetch_add("bf.membership.epoch", 1)
    for s in shard_trio:
        probe = native.ControlPlaneClient("127.0.0.1", s.port, 9, streams=1)
        assert probe.get("bf.q.4.2") == 2
        assert probe.get("bf.membership.epoch") >= e + 1
        probe.close()
    # monotone merge: a delayed lower write cannot regress the phase
    r.put("bf.q.4.2", 1)
    assert r.get("bf.q.4.2") == 2
    r.close()


def test_single_endpoint_attach_stays_plain_client(monkeypatch):
    """Satellite guarantee: the world-1 single-endpoint path keeps the
    plain ControlPlaneClient, byte for byte — no router in the loop."""
    srv = native.ControlPlaneServer(1, _free_port())
    try:
        for k, v in {
            "BLUEFOG_CP_HOST": "127.0.0.1",
            "BLUEFOG_CP_PORT": str(srv.port),
            "BLUEFOG_CP_WORLD": "1",
            "BLUEFOG_CP_RANK": "0",
            "BLUEFOG_CP_SERVE": "0",
        }.items():
            monkeypatch.setenv(k, v)
        cp.reset_for_test()
        cl = cp.attach()
        assert isinstance(cl, native.ControlPlaneClient)
        assert not isinstance(cl, ShardRouter)
    finally:
        cp.reset_for_test()
        srv.stop()
