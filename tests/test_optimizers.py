"""Optimizer-wrapper tests: exact-value assertions against numpy simulations.

Mirrors the reference test style (torch_ops_test.py: known-graph exact
averages) applied to the training-loop layer. Consensus behavior is isolated
with a zero-gradient loss so each step is purely the communication matrix.
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import bluefog_tpu as bf
from bluefog_tpu import topology as topology_util

N = 8


def zero_loss(p, b):
    # Traced from params so jax.grad yields exact zeros: a step is then
    # exactly one application of the communication matrix.
    return 0.0 * sum(jnp.sum(x) for x in jax.tree_util.tree_leaves(p))


def quad_loss(p, b):
    # 0.5 * ||w - t||^2 per rank; b carries the per-rank target.
    return 0.5 * jnp.sum((p["w"] - b) ** 2)


def stacked_params(seed=0):
    rng = np.random.RandomState(seed)
    return {"w": jnp.asarray(rng.randn(N, 4).astype(np.float32))}


def manual_state(opt, params_stacked):
    """TrainState from explicitly different per-rank params."""
    single = {"w": params_stacked["w"][0]}
    st = opt.init(single)
    return bf.TrainState(
        params=jax.device_put(params_stacked, bf.rank_sharding(bf.mesh())),
        opt_state=st.opt_state,
        model_state=None,
    )


def uniform_W(topo):
    n = topo.number_of_nodes()
    W = np.zeros((n, n))
    for r in range(n):
        nbrs = topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        W[r, r] = u
        for s in nbrs:
            W[s, r] = u
    return W


def test_gradient_allreduce_exact(bf8):
    opt = bf.DistributedGradientAllreduceOptimizer(optax.sgd(0.5), quad_loss)
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 4))
    state = opt.init({"w": jnp.zeros(4, jnp.float32)})
    state, metrics = opt.step(state, targets)
    # grad_r = (0 - t_r); pmean grad = -mean(t); w1 = 0.5 * mean(t) everywhere
    expect = 0.5 * np.mean(np.arange(N)) * np.ones(4)
    got = np.asarray(state.params["w"])
    for r in range(N):
        np.testing.assert_allclose(got[r], expect, rtol=1e-6)
    assert metrics["loss"].shape == (N,)


def test_allreduce_params_exact(bf8):
    opt = bf.DistributedAllreduceOptimizer(optax.sgd(1.0), quad_loss)
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 4))
    state = opt.init({"w": jnp.zeros(4, jnp.float32)})
    state, _ = opt.step(state, targets)
    # local: w_r = t_r ; then pmean -> mean(t) everywhere
    expect = np.mean(np.arange(N)) * np.ones(4)
    got = np.asarray(state.params["w"])
    for r in range(N):
        np.testing.assert_allclose(got[r], expect, rtol=1e-6)


def test_neighbor_allreduce_consensus_matches_matrix(bf8):
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1), zero_loss)
    x0 = stacked_params()
    state = manual_state(opt, x0)
    W = uniform_W(bf.load_topology())
    batch = jnp.zeros((N, 1), jnp.float32)
    expect = np.asarray(x0["w"], dtype=np.float64)
    for _ in range(3):
        state, _ = opt.step(state, batch)
        expect = W.T @ expect
    np.testing.assert_allclose(np.asarray(state.params["w"]), expect, atol=1e-5)


def test_neighbor_allreduce_dynamic_topology(bf8):
    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1), zero_loss)
    x0 = stacked_params(1)
    state = manual_state(opt, x0)
    gens = [
        topology_util.GetDynamicSendRecvRanks(bf.load_topology(), r)
        for r in range(N)
    ]
    batch = jnp.zeros((N, 1), jnp.float32)
    expect = np.asarray(x0["w"], dtype=np.float64)
    for _ in range(4):
        sends = {}
        for r, g in enumerate(gens):
            to, _ = next(g)
            sends[r] = to
        recv_from = {r: [] for r in range(N)}
        for s, dsts in sends.items():
            for d in dsts:
                recv_from[d].append(s)
        opt.send_neighbors = sends
        opt.self_weight = {r: 1.0 / (len(recv_from[r]) + 1) for r in range(N)}
        opt.neighbor_weights = {
            r: {s: 1.0 / (len(recv_from[r]) + 1) for s in recv_from[r]}
            for r in range(N)
        }
        state, _ = opt.step(state, batch)
        W = topology_util.dynamic_weight_matrix(N, sends)
        expect = W.T @ expect
    np.testing.assert_allclose(np.asarray(state.params["w"]), expect, atol=1e-5)


def test_hierarchical_neighbor_allreduce_consensus(bf8):
    opt = bf.DistributedHierarchicalNeighborAllreduceOptimizer(
        optax.sgd(0.1), zero_loss)
    x0 = stacked_params(2)
    single = {"w": x0["w"][0]}
    st0 = opt.init(single)
    state = bf.TrainState(
        params=jax.device_put(
            x0, jax.sharding.NamedSharding(
                bf.machine_mesh(), jax.sharding.PartitionSpec(("machine", "local")))),
        opt_state=st0.opt_state,
        model_state=None,
    )
    batch = jnp.zeros((N, 1), jnp.float32)
    state, _ = opt.step(state, batch)
    # phase 1: per-machine mean (local_size=4); phase 2: 2-machine expo2 =
    # 0.5/0.5 mix -> global mean everywhere.
    expect = np.mean(np.asarray(x0["w"], dtype=np.float64), axis=0)
    got = np.asarray(state.params["w"])
    for r in range(N):
        np.testing.assert_allclose(got[r], expect, atol=1e-5)


def test_num_steps_per_communication(bf8):
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1), zero_loss, num_steps_per_communication=2)
    x0 = stacked_params(3)
    state = manual_state(opt, x0)
    batch = jnp.zeros((N, 1), jnp.float32)
    state, _ = opt.step(state, batch)  # no comm: zero grads -> unchanged
    np.testing.assert_allclose(np.asarray(state.params["w"]),
                               np.asarray(x0["w"]), atol=1e-6)
    state, _ = opt.step(state, batch)  # comm step
    W = uniform_W(bf.load_topology())
    expect = W.T @ np.asarray(x0["w"], dtype=np.float64)
    np.testing.assert_allclose(np.asarray(state.params["w"]), expect, atol=1e-5)


def test_win_put_optimizer_consensus(bf8):
    from bluefog_tpu.runtime.state import _global_state
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zero_loss)
    x0 = stacked_params(4)
    st0 = opt.init({"w": x0["w"][0]})  # registers windows (replicated values)
    # install the true per-rank values in params and window storage
    for nm in opt._win_names:
        _global_state().windows[nm].self_value = x0["w"]
    state = bf.TrainState(
        params=jax.device_put(x0, bf.rank_sharding(bf.mesh())),
        opt_state=st0.opt_state, model_state=None)
    batch = jnp.zeros((N, 1), jnp.float32)
    for _ in range(20):
        state, _ = opt.step(state, batch)
    got = np.asarray(state.params["w"])
    # doubly-stochastic mixing -> consensus at the initial average
    # (win mailboxes started from replicated x0[0]; consensus value is some
    # convex combination — assert ranks agree, the decentralized invariant)
    for r in range(1, N):
        np.testing.assert_allclose(got[r], got[0], atol=1e-3)
    opt.free()


def test_push_sum_optimizer_consensus(bf8):
    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), zero_loss)
    x0 = stacked_params(5)
    st0 = opt.init({"w": x0["w"][0]})
    # replace window numerators with per-rank values so consensus target is
    # the true average
    import bluefog_tpu.ops.windows as W_
    for nm in opt._win_names:
        from bluefog_tpu.runtime.state import _global_state
        _global_state().windows[nm].self_value = x0["w"]
    state = bf.TrainState(
        params=jax.device_put(x0, bf.rank_sharding(bf.mesh())),
        opt_state=st0.opt_state, model_state=None)
    batch = jnp.zeros((N, 1), jnp.float32)
    for _ in range(40):
        state, _ = opt.step(state, batch)
    got = np.asarray(state.params["w"])
    expect = np.mean(np.asarray(x0["w"], dtype=np.float64), axis=0)
    for r in range(N):
        np.testing.assert_allclose(got[r], expect, atol=1e-2)
    opt.free()
    bf.turn_off_win_ops_with_associated_p()


def test_mlp_trains_loss_decreases(bf8):
    model = bf.models.MLP(features=(16, 2))
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (N, 8, 4))
    y = (jax.random.normal(jax.random.PRNGKey(1), (N, 8)) > 0).astype(jnp.int32)

    params = model.init(rng, x[0])["params"]

    def loss_fn(p, batch):
        bx, by = batch
        logits = model.apply({"params": p}, bx)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, by).mean()

    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.5), loss_fn)
    state = opt.init(params)
    losses = []
    for _ in range(10):
        state, m = opt.step(state, (x, y))
        losses.append(float(np.mean(np.asarray(m["loss"]))))
    assert losses[-1] < losses[0], f"loss did not decrease: {losses}"


def test_resnet_forward_shape():
    # shape-only contract: eval_shape skips the ResNet compile (the numeric
    # forward is covered by the slow-marked model/interop oracles)
    model = bf.models.ResNet18(num_classes=10, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    x = jnp.zeros((2, 32, 32, 3))
    variables = jax.eval_shape(lambda k: model.init(k, x, train=False), rng)
    out = jax.eval_shape(
        lambda v: model.apply(v, x, train=False), variables)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32


def test_broadcast_and_allreduce_parameters(bf8):
    x0 = stacked_params(6)
    stacked = jax.device_put(x0, bf.rank_sharding(bf.mesh()))
    b = bf.broadcast_parameters(stacked, root_rank=3)
    got = np.asarray(b["w"])
    for r in range(N):
        np.testing.assert_allclose(got[r], np.asarray(x0["w"][3]), rtol=1e-6)
    a = bf.allreduce_parameters(stacked)
    expect = np.mean(np.asarray(x0["w"]), axis=0)
    got = np.asarray(a["w"])
    for r in range(N):
        np.testing.assert_allclose(got[r], expect, rtol=1e-5)


# ---------------------------------------------------------------------------
# ZeRO-1 sharded allreduce (net-new; no reference analog)
# ---------------------------------------------------------------------------

def multi_leaf_loss(p, b):
    # two leaves with total size 7 (not divisible by 8) to exercise padding
    return 0.5 * jnp.sum((p["w"] - b) ** 2) + 0.5 * jnp.sum((p["b"] - 1.0) ** 2)


def test_sharded_allreduce_matches_gradient_allreduce(bf8):
    params = {"w": jnp.zeros(4, jnp.float32), "b": jnp.full(3, 2.0, jnp.float32)}
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 4))

    ref = bf.DistributedGradientAllreduceOptimizer(
        optax.adam(0.1), multi_leaf_loss)
    zero1 = bf.DistributedShardedAllreduceOptimizer(
        optax.adam(0.1), multi_leaf_loss)
    s_ref, s_z = ref.init(params), zero1.init(params)
    for _ in range(5):
        s_ref, m_ref = ref.step(s_ref, targets)
        s_z, m_z = zero1.step(s_z, targets)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(s_z.params[k]), np.asarray(s_ref.params[k]), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(m_z["loss"]), np.asarray(m_ref["loss"]), atol=1e-5)


def test_sharded_opt_state_is_actually_sharded(bf8):
    params = {"w": jnp.zeros(10, jnp.float32), "b": jnp.zeros(3, jnp.float32)}
    zero1 = bf.DistributedShardedAllreduceOptimizer(
        optax.adam(0.1), multi_leaf_loss)
    state = zero1.init(params)
    # total=13 -> shard size ceil(13/8)=2: adam mu/nu are [N, 2] flat shards,
    # not [N, 10]/[N, 3] replicated leaves
    shapes = {l.shape for l in jax.tree_util.tree_leaves(state.opt_state)
              if hasattr(l, "shape") and l.ndim >= 1 and l.size > N}
    assert shapes == {(N, 2)}, shapes
    # one step keeps the sharded layout and still updates replicated params
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 10))
    state, _ = zero1.step(state, targets)
    got = np.asarray(state.params["w"])
    for r in range(1, N):
        np.testing.assert_allclose(got[r], got[0], rtol=1e-6)


def test_sharded_rejects_local_steps(bf8):
    with pytest.raises(ValueError, match="num_steps_per_communication"):
        bf.DistributedShardedAllreduceOptimizer(
            optax.sgd(0.1), multi_leaf_loss, num_steps_per_communication=2)


def test_win_put_optimizer_single_program_pair(bf8, tmp_path):
    """r6 acceptance: at the default fusion threshold (8 MB) a window
    optimizer packs the WHOLE parameter tree into one flat window, so a
    gossip step dispatches exactly ONE win_put + ONE win_update program
    pair — asserted via timeline span counts. The tree here is ~12 MB
    across 3 leaves, which the r5 per-8MB-group packing split into 2+
    windows (2+ pairs per step)."""
    import json as _json
    from bluefog_tpu.runtime.state import _global_state

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zero_loss)
    big = {f"w{i}": jnp.ones((1_000_000,), jnp.float32) for i in range(3)}
    state = opt.init(big)
    assert len(opt._win_names) == 1, \
        "12 MB of leaves must pack into ONE window at the default threshold"
    batch = jnp.zeros((N, 1), jnp.float32)
    state, _ = opt.step(state, batch)  # compile outside the trace
    prefix = str(tmp_path / "pair_")
    assert bf.start_timeline(prefix)
    steps = 3
    for _ in range(steps):
        state, _ = opt.step(state, batch)
    path = _global_state().timeline.path
    assert bf.stop_timeline()
    events = _json.load(open(path))
    spans = [e["name"] for e in events if e.get("ph") == "B"]
    assert spans.count("WIN_PUT") == steps, spans
    assert spans.count("WIN_UPDATE") == steps, spans
    opt.free()
