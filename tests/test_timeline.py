"""Timeline op-coverage parity tests.

Port of the reference's timeline test (/root/reference/test/timeline_test.py
:1-141): run real ops with the timeline enabled, parse the resulting
chrome-tracing JSON, and assert the op activities actually landed in the
file. Covers BOTH writer backends — the pure-Python fallback (daemon thread
+ queue) and, when built, the native C++ spsc writer — plus the
BLUEFOG_TIMELINE env path through init.
"""

import json
import os

import jax.numpy as jnp
import pytest

import bluefog_tpu as bf
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.state import _global_state
from bluefog_tpu.runtime.timeline import Timeline

from conftest import cpu_devices


def _events(path):
    with open(path) as f:
        return json.load(f)


def _run_ops_and_collect(tmp_path, use_native):
    bf.init(devices=cpu_devices(8))
    st = _global_state()
    prefix = str(tmp_path / ("native_" if use_native else "py_"))
    st.timeline = Timeline(prefix, use_native=use_native)
    try:
        x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((8, 4)))
        bf.allreduce(x, name="t.ar")
        bf.neighbor_allreduce(x, name="t.nar")
        bf.win_create(x, name="t.win")
        bf.win_put(x, name="t.win")
        bf.win_update(name="t.win")
        bf.win_free("t.win")
        with bf.timeline_context("t.manual", "GRADIENT_COMPUTATION"):
            pass
    finally:
        path = st.timeline.path
        bf.shutdown()  # closes the timeline
    return _events(path)


@pytest.mark.parametrize("use_native", [False, True])
def test_op_activities_land_in_file(tmp_path, use_native):
    if use_native and native.load() is None:
        pytest.skip("native runtime not built")
    events = _run_ops_and_collect(tmp_path, use_native)
    starts = [e for e in events if e.get("ph") == "B"]
    names = {e["name"] for e in starts}
    # every op family emitted its activity, under the tensor name it was
    # called with (the reference asserts the same structure per tensor)
    for activity, tensor in [
        ("ALLREDUCE", "t.ar"),
        ("NEIGHBOR_ALLREDUCE", "t.nar"),
        ("WIN_CREATE", "t.win"),
        ("WIN_PUT", "t.win"),
        ("WIN_UPDATE", "t.win"),
        ("GRADIENT_COMPUTATION", "t.manual"),
    ]:
        assert activity in names, f"missing activity {activity}"
        assert any(e["name"] == activity and e["cat"] == tensor
                   for e in starts), f"{activity} not tagged {tensor}"
    # per-op completion phase (reference NEGOTIATE/COMMUNICATE attribution,
    # mpi_controller.cc:276-292): every dispatched op opens a COMMUNICATE
    # span closed at completion (poll/synchronize/watchdog sweep) on a
    # dedicated tid lane; balance is asserted by the loop below
    assert "COMMUNICATE" in names
    comm = [e for e in starts if e["name"] == "COMMUNICATE"]
    assert all(e["tid"] >= 1000 for e in comm)
    # spans balance: every B has a matching E per (cat, tid) lane
    open_spans = {}
    for e in events:
        key = (e.get("cat"), e.get("tid"))
        if e.get("ph") == "B":
            open_spans[key] = open_spans.get(key, 0) + 1
        elif e.get("ph") == "E":
            open_spans[key] = open_spans.get(key, 0) - 1
            assert open_spans[key] >= 0, f"E without B for {key}"
    assert all(v == 0 for v in open_spans.values())


def test_env_var_enables_timeline(tmp_path, monkeypatch):
    prefix = str(tmp_path / "envtl_")
    monkeypatch.setenv("BLUEFOG_TIMELINE", prefix)
    bf.init(devices=cpu_devices(8))
    try:
        assert _global_state().timeline is not None
        x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((8, 2)))
        bf.neighbor_allreduce(x, name="env.t")
    finally:
        path = _global_state().timeline.path
        bf.shutdown()
    events = _events(path)
    assert any(e.get("name") == "NEIGHBOR_ALLREDUCE" and e.get("cat") == "env.t"
               for e in events)
    assert os.path.basename(path).startswith("envtl_")


def test_manual_activity_api(tmp_path):
    bf.init(devices=cpu_devices(8))
    st = _global_state()
    st.timeline = Timeline(str(tmp_path / "manual_"), use_native=False)
    try:
        assert bf.timeline_start_activity("w.0", "COMPUTE")
        assert bf.timeline_end_activity("w.0")
    finally:
        path = st.timeline.path
        bf.shutdown()
    events = _events(path)
    assert any(e.get("name") == "COMPUTE" and e.get("cat") == "w.0"
               for e in events)


def test_start_stop_timeline_runtime_toggle(tmp_path):
    """bf.start_timeline/bf.stop_timeline work mid-run (basics.py parity)."""
    bf.init(devices=cpu_devices(8))
    try:
        prefix = str(tmp_path / "toggle_")
        assert bf.start_timeline(prefix)
        assert not bf.start_timeline(prefix)  # double-start refused
        x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((8, 2)))
        bf.allreduce(x, name="toggle.t")
        path = _global_state().timeline.path
        assert bf.stop_timeline()
        assert not bf.stop_timeline()  # double-stop refused
        events = _events(path)
        assert any(e.get("name") == "ALLREDUCE" for e in events)
        # ops after stop don't crash and don't write
        bf.allreduce(x, name="toggle.after")
    finally:
        bf.shutdown()


@pytest.mark.parametrize("use_native", [False, True])
def test_counter_and_flow_events_both_writers(tmp_path, use_native):
    """r10 trace correlation: counter tracks (ph 'C'), flow start/finish
    (ph 's'/'f' with a binding id), and the wall-clock sync anchor as the
    FIRST event — identical structure from both writer backends."""
    if use_native and native.load() is None:
        pytest.skip("native runtime not built")
    tl = Timeline(str(tmp_path / ("cfn_" if use_native else "cfp_")),
                  process_index=3, use_native=use_native)
    tl.counter("mailbox.depth", 17)
    fid = (5 << 32) | 99
    tl.flow_start("WIN_DEPOSIT", fid)
    tl.flow_finish("WIN_DEPOSIT", fid)
    tl.close()
    events = _events(tl.path)
    assert events[0]["name"] == "bf.clock_sync_us"
    assert events[0]["ph"] == "C" and events[0]["args"]["value"] > 0
    counters = [e for e in events if e["ph"] == "C"
                and e["name"] == "mailbox.depth"]
    assert counters and counters[0]["args"]["value"] == 17
    s = [e for e in events if e["ph"] == "s"]
    f = [e for e in events if e["ph"] == "f"]
    assert len(s) == 1 and len(f) == 1
    assert s[0]["id"] == fid and f[0]["id"] == fid
    assert f[0]["bp"] == "e"
    assert all(e["pid"] == 3 for e in events)


def test_watchdog_and_heartbeat_instants_reach_timeline(tmp_path,
                                                        monkeypatch):
    """Satellite: stall warnings land in the trace as instant events (and
    in the metrics registry), not just on stderr."""
    from bluefog_tpu.runtime import handles as handles_mod
    from bluefog_tpu.runtime import metrics as metrics_mod
    from bluefog_tpu.runtime.watchdog import StallWatchdog

    bf.init(devices=cpu_devices(8))
    st = _global_state()
    st.timeline = Timeline(str(tmp_path / "stall_"), use_native=False)
    stalls0 = metrics_mod.counter("watchdog.stalls").value
    try:
        class _NeverReady:
            def is_ready(self):
                return False

        h = handles_mod.allocate("stalled.op", _NeverReady())
        wd = StallWatchdog(warning_sec=0.0, cycle_ms=1.0)
        wd._stop.wait(0.01)
        wd.start()
        deadline = 50
        while metrics_mod.counter("watchdog.stalls").value == stalls0 \
                and deadline:
            import time as _t
            _t.sleep(0.1)
            deadline -= 1
        wd.stop()
        assert metrics_mod.counter("watchdog.stalls").value > stalls0
        handles_mod._handle_map.pop(h, None)  # unhook the fake handle
    finally:
        path = st.timeline.path
        bf.shutdown()
    events = _events(path)
    assert any(e.get("ph") == "i" and e.get("name") == "STALL"
               and e.get("cat") == "stalled.op" for e in events)


def test_phase_subspans_land_in_file(tmp_path):
    """Reference phase granularity (VERDICT r3 #8): dynamic plan
    construction (PLAN_BUILD) and fusion-buffer copies (PACK/UNPACK — the
    MEMCPY_IN/OUT_FUSION_BUFFER analog, common/timeline.cc usage in
    mpi_controller.cc:276-292) must be visible as their own sub-spans."""
    import optax

    bf.init(devices=cpu_devices(8))
    st = _global_state()
    st.timeline = Timeline(str(tmp_path / "phase_"), use_native=False)
    try:
        x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((8, 4)))
        sends = {r: [(r + 1) % 8] for r in range(8)}
        nw = {r: {(r - 1) % 8: 0.5} for r in range(8)}
        # first dynamic call: builds (and caches) the plan -> PLAN_BUILD
        bf.neighbor_allreduce(x, self_weight=0.5, neighbor_weights=nw,
                              send_neighbors=sends, name="t.dyn")
        # warm call: plan cache hit -> NO second PLAN_BUILD
        bf.neighbor_allreduce(x, self_weight=0.5, neighbor_weights=nw,
                              send_neighbors=sends, name="t.dyn2")
        # a window-optimizer step exercises the fusion pack/unpack path
        def zl(p, b):
            return 0.0 * jnp.sum(p["w"])
        opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zl,
                                            window_prefix="tl.phase")
        state = opt.init({"w": jnp.ones((4,), jnp.float32)})
        opt.step(state, jnp.zeros((8, 1), jnp.float32))
        opt.free()
    finally:
        path = st.timeline.path
        bf.shutdown()
    events = _events(path)
    starts = [e for e in events if e.get("ph") == "B"]
    plan_builds = [e for e in starts if e["name"] == "PLAN_BUILD"]
    assert any(e["cat"] == "t.dyn" for e in plan_builds)
    assert not any(e["cat"] == "t.dyn2" for e in plan_builds), \
        "plan cache missed on an identical dynamic step"
    names = {e["name"] for e in starts}
    assert "PACK" in names and "UNPACK" in names


# ---------------------------------------------------------------------------
# merge_timelines: clock-sync anchors, missing-anchor fallback (ISSUE r12)
# ---------------------------------------------------------------------------

def _merge_mod():
    import importlib
    import sys

    scripts = os.path.join(os.path.dirname(__file__), "..", "scripts")
    sys.path.insert(0, scripts)
    try:
        return importlib.import_module("merge_timelines")
    finally:
        sys.path.pop(0)


def _trace(path, events):
    with open(path, "w") as f:
        json.dump(events, f)
    return str(path)


def _anchor(ts, wall_us, pid):
    return {"name": "bf.clock_sync_us", "cat": "bf", "ph": "C", "ts": ts,
            "pid": pid, "tid": 0, "args": {"value": wall_us}}


def test_merge_missing_anchor_warns_and_falls_back(tmp_path, capsys):
    mt = _merge_mod()
    a = _trace(tmp_path / "a.json", [
        _anchor(0.0, 1_000_000.0, 0),
        {"name": "x", "cat": "t", "ph": "i", "s": "t", "ts": 50.0,
         "pid": 0, "tid": 0}])
    # rank 1's trace lost its anchor (old build / truncated file)
    b = _trace(tmp_path / "b.json", [
        {"name": "y", "cat": "t", "ph": "i", "s": "t", "ts": 10.0,
         "pid": 1, "tid": 0}])
    merged = mt.merge([a, b])
    err = capsys.readouterr().err
    assert "clock-sync anchor" in err and "UNSHIFTED" in err
    # anchored file rebases to its own offset (sole anchor -> shift 0);
    # the anchorless file keeps raw timestamps instead of crashing
    ys = [e for e in merged if e.get("name") == "y"]
    assert ys and ys[0]["ts"] == 10.0
    xs = [e for e in merged if e.get("name") == "x"]
    assert xs and xs[0]["ts"] == 50.0
    # process metadata still emitted for both pids
    assert {e["pid"] for e in merged if e.get("ph") == "M"} == {0, 1}


def test_merge_all_anchorless_is_identity(tmp_path, capsys):
    mt = _merge_mod()
    a = _trace(tmp_path / "a.json", [
        {"name": "x", "cat": "t", "ph": "i", "s": "t", "ts": 5.0,
         "pid": 0, "tid": 0}])
    b = _trace(tmp_path / "b.json", [
        {"name": "y", "cat": "t", "ph": "i", "s": "t", "ts": 7.0,
         "pid": 1, "tid": 0}])
    merged = mt.merge([a, b])
    assert capsys.readouterr().err.count("UNSHIFTED") == 2
    assert [e["ts"] for e in merged if "ts" in e][:2] == [5.0, 7.0]


def test_merge_large_skew_still_aligns(tmp_path):
    """Two ranks whose perf_counter origins differ by ~an hour (3.6e9 us)
    must land on one axis: the anchors carry the skew, the merge removes
    it. The drain event (wall 1000s + 100us) must sort AFTER the deposit
    (wall 1000s + 50us) even though its raw trace ts is far smaller."""
    mt = _merge_mod()
    wall = 1_000_000_000.0  # shared wall clock at trace start, us
    a = _trace(tmp_path / "a.json", [
        _anchor(3_600_000_000.0, wall, 0),  # origin 1h before its anchor
        {"name": "deposit", "cat": "t", "ph": "i", "s": "t",
         "ts": 3_600_000_050.0, "pid": 0, "tid": 0}])
    b = _trace(tmp_path / "b.json", [
        _anchor(0.0, wall, 1),
        {"name": "drain", "cat": "t", "ph": "i", "s": "t", "ts": 100.0,
         "pid": 1, "tid": 0}])
    merged = mt.merge([a, b])
    dep = next(e for e in merged if e.get("name") == "deposit")
    dra = next(e for e in merged if e.get("name") == "drain")
    # on the common axis the pair is 50us apart, drain after deposit —
    # the raw traces had them 3.6e9us apart in the WRONG order
    assert dra["ts"] - dep["ts"] == 50.0
    assert merged.index(dep) < merged.index(dra)
