"""Context-parallel attention: exact agreement with dense attention."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu import parallel as bfp

N = 8


def make_qkv(seed, B=2, S=32, H=8, D=16, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    return tuple(jax.random.normal(k, shape, dtype) for k in ks)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(bf8, causal):
    q, k, v = make_qkv(0)
    want = bfp.reference_attention(q, k, v, causal=causal)
    got = bfp.ring_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_dense(bf8, causal):
    q, k, v = make_qkv(1)
    want = bfp.reference_attention(q, k, v, causal=causal)
    got = bfp.ulysses_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_cross_attention_lengths(bf8):
    # Sq != Sk (cross attention), non-causal
    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (2, 16, 4, 8))
    k = jax.random.normal(ks[1], (2, 64, 4, 8))
    v = jax.random.normal(ks[2], (2, 64, 4, 8))
    want = bfp.reference_attention(q, k, v)
    got = bfp.ring_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ring_attention_bf16(bf8):
    q, k, v = make_qkv(3, dtype=jnp.bfloat16)
    want = bfp.reference_attention(q, k, v, causal=True)
    got = bfp.ring_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2)
    assert got.dtype == jnp.bfloat16


def test_ring_attention_rejects_bad_seq(bf8):
    q = jnp.zeros((1, 12, 4, 8))
    with pytest.raises(ValueError, match="divide"):
        bfp.ring_attention(q, q, q)
