"""Cross-framework weight loading: torch-format ResNet -> flax, exact.

The oracle is a faithful torch replica of torchvision's BasicBlock ResNet
(same module/parameter names, strides, and padding as
torchvision.models.resnet18 — torchvision itself is not installed in this
image). Random torch weights converted through
utils/torch_interop.resnet_from_torch must reproduce the torch forward
numerically in the flax model: this pins kernel transposition, BN
affine/stats splitting, block ordering, AND the conv/pool padding geometry
(models/resnet.py uses torch-compatible explicit padding precisely so
stride-2 layers line up).
"""

import numpy as np
import pytest

import jax.numpy as jnp

torch = pytest.importorskip("torch")
import torch.nn as tnn  # noqa: E402

from bluefog_tpu import models  # noqa: E402
from bluefog_tpu.utils.torch_interop import resnet_from_torch  # noqa: E402


class TorchBasicBlock(tnn.Module):
    """torchvision.models.resnet.BasicBlock, reproduced name-for-name."""

    def __init__(self, cin, cout, stride=1):
        super().__init__()
        self.conv1 = tnn.Conv2d(cin, cout, 3, stride, 1, bias=False)
        self.bn1 = tnn.BatchNorm2d(cout)
        self.relu = tnn.ReLU(inplace=True)
        self.conv2 = tnn.Conv2d(cout, cout, 3, 1, 1, bias=False)
        self.bn2 = tnn.BatchNorm2d(cout)
        self.downsample = None
        if stride != 1 or cin != cout:
            self.downsample = tnn.Sequential(
                tnn.Conv2d(cin, cout, 1, stride, bias=False),
                tnn.BatchNorm2d(cout))

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return self.relu(out + identity)


class TorchResNet18(tnn.Module):
    """torchvision.models.resnet18 layout, name-for-name."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.conv1 = tnn.Conv2d(3, 64, 7, 2, 3, bias=False)
        self.bn1 = tnn.BatchNorm2d(64)
        self.relu = tnn.ReLU(inplace=True)
        self.maxpool = tnn.MaxPool2d(3, 2, 1)
        widths = [64, 128, 256, 512]
        cin = 64
        for s, w in enumerate(widths, start=1):
            blocks = []
            for b in range(2):
                stride = 2 if (s > 1 and b == 0) else 1
                blocks.append(TorchBasicBlock(cin, w, stride))
                cin = w
            setattr(self, f"layer{s}", tnn.Sequential(*blocks))
        self.avgpool = tnn.AdaptiveAvgPool2d((1, 1))
        self.fc = tnn.Linear(512, num_classes)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        for s in range(1, 5):
            x = getattr(self, f"layer{s}")(x)
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


@pytest.mark.slow  # numeric oracle kept in the full suite
def test_resnet18_forward_matches_torch_oracle():
    torch.manual_seed(0)
    tmodel = TorchResNet18(num_classes=10).eval()
    # make running stats non-trivial so the BN mapping is actually exercised
    with torch.no_grad():
        tmodel(torch.randn(4, 3, 64, 64))
        tmodel.eval()

    variables = resnet_from_torch(tmodel.state_dict(), 18)
    fmodel = models.ResNet18(num_classes=10, dtype=jnp.float32)

    x = np.random.RandomState(1).randn(2, 64, 64, 3).astype(np.float32)
    with torch.no_grad():
        want = tmodel(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(fmodel.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=1e-4)


@pytest.mark.slow  # ResNet-50 compile on 1 core
def test_resnet50_mapping_covers_full_tree():
    """Bottleneck mapping: a synthetic torchvision-format state_dict built
    from the flax template round-trips to the exact same tree structure."""
    import jax

    fmodel = models.ResNet50(num_classes=7, dtype=jnp.float32)
    template = fmodel.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=True)

    # invert the mapping: torch names/shapes derived from the flax tree
    sd = {}
    stages = [3, 4, 6, 3]
    sd["conv1.weight"] = np.zeros(np.asarray(
        template["params"]["conv_init"]["kernel"]).transpose(3, 2, 0, 1).shape)
    for bnp, tp in (("bn_init", "bn1"),):
        sd[f"{tp}.weight"] = np.asarray(template["params"][bnp]["scale"])
        sd[f"{tp}.bias"] = np.asarray(template["params"][bnp]["bias"])
        sd[f"{tp}.running_mean"] = np.asarray(
            template["batch_stats"][bnp]["mean"])
        sd[f"{tp}.running_var"] = np.asarray(
            template["batch_stats"][bnp]["var"])
    idx = 0
    for s, count in enumerate(stages, start=1):
        for b in range(count):
            fb = template["params"][f"BottleneckBlock_{idx}"]
            fs = template["batch_stats"][f"BottleneckBlock_{idx}"]
            for c in range(3):
                sd[f"layer{s}.{b}.conv{c + 1}.weight"] = np.asarray(
                    fb[f"Conv_{c}"]["kernel"]).transpose(3, 2, 0, 1)
                sd[f"layer{s}.{b}.bn{c + 1}.weight"] = np.asarray(
                    fb[f"BatchNorm_{c}"]["scale"])
                sd[f"layer{s}.{b}.bn{c + 1}.bias"] = np.asarray(
                    fb[f"BatchNorm_{c}"]["bias"])
                sd[f"layer{s}.{b}.bn{c + 1}.running_mean"] = np.asarray(
                    fs[f"BatchNorm_{c}"]["mean"])
                sd[f"layer{s}.{b}.bn{c + 1}.running_var"] = np.asarray(
                    fs[f"BatchNorm_{c}"]["var"])
            if "conv_proj" in fb:
                sd[f"layer{s}.{b}.downsample.0.weight"] = np.asarray(
                    fb["conv_proj"]["kernel"]).transpose(3, 2, 0, 1)
                sd[f"layer{s}.{b}.downsample.1.weight"] = np.asarray(
                    fb["norm_proj"]["scale"])
                sd[f"layer{s}.{b}.downsample.1.bias"] = np.asarray(
                    fb["norm_proj"]["bias"])
                sd[f"layer{s}.{b}.downsample.1.running_mean"] = np.asarray(
                    fs["norm_proj"]["mean"])
                sd[f"layer{s}.{b}.downsample.1.running_var"] = np.asarray(
                    fs["norm_proj"]["var"])
            idx += 1
    sd["fc.weight"] = np.asarray(template["params"]["head"]["kernel"]).T
    sd["fc.bias"] = np.asarray(template["params"]["head"]["bias"])

    got = resnet_from_torch(sd, 50)
    want_struct = jax.tree_util.tree_structure(
        {"params": template["params"], "batch_stats": template["batch_stats"]})
    assert jax.tree_util.tree_structure(got) == want_struct
    # and values survive the double transpose
    np.testing.assert_allclose(
        np.asarray(got["params"]["BottleneckBlock_3"]["Conv_1"]["kernel"]),
        np.asarray(template["params"]["BottleneckBlock_3"]["Conv_1"]["kernel"]))


def test_unsupported_depth_rejected():
    with pytest.raises(ValueError, match="depth"):
        resnet_from_torch({}, 77)


def test_depth_mismatch_rejected():
    torch.manual_seed(0)
    tmodel = TorchResNet18(num_classes=10)
    sd = dict(tmodel.state_dict())
    # graft an extra block as if this were a deeper net
    for k in list(sd):
        if k.startswith("layer4.1."):
            sd[k.replace("layer4.1.", "layer4.2.")] = sd[k]
    with pytest.raises(ValueError, match="beyond a depth-18"):
        resnet_from_torch(sd, 18)


def test_shallow_checkpoint_rejected_loudly():
    torch.manual_seed(0)
    sd = dict(TorchResNet18(num_classes=10).state_dict())
    with pytest.raises(ValueError, match="matching depth"):
        resnet_from_torch(sd, 34)  # resnet34 expects layer1.2.* etc.


def _make_torch_vgg(cfg, batch_norm, num_classes=7):
    """torchvision.models.vgg.VGG reproduced name-for-name (features /
    avgpool / classifier, make_layers module ordering)."""
    layers = []
    cin = 3
    for v in cfg:
        if v == "M":
            layers.append(tnn.MaxPool2d(2, 2))
        else:
            layers.append(tnn.Conv2d(cin, v, 3, padding=1))
            if batch_norm:
                layers.append(tnn.BatchNorm2d(v))
            layers.append(tnn.ReLU(inplace=True))
            cin = v

    class TorchVGG(tnn.Module):
        def __init__(self):
            super().__init__()
            self.features = tnn.Sequential(*layers)
            self.avgpool = tnn.AdaptiveAvgPool2d((7, 7))
            self.classifier = tnn.Sequential(
                tnn.Linear(512 * 7 * 7, 4096), tnn.ReLU(True), tnn.Dropout(),
                tnn.Linear(4096, 4096), tnn.ReLU(True), tnn.Dropout(),
                tnn.Linear(4096, num_classes))

        def forward(self, x):
            x = self.features(x)
            x = self.avgpool(x)
            x = torch.flatten(x, 1)
            return self.classifier(x)

    return TorchVGG()


@pytest.mark.slow  # two VGG-11 forwards (torch + flax) at 224^2 on 1 core
def test_vgg11_bn_forward_matches_torch_oracle():
    from bluefog_tpu.utils.torch_interop import vgg_from_torch

    from bluefog_tpu.models.vgg import _CFGS
    tm = _make_torch_vgg(_CFGS[11], batch_norm=True)
    tm.eval()
    # non-trivial running stats so the BN mapping can't pass by accident
    with torch.no_grad():
        for mod in tm.modules():
            if isinstance(mod, tnn.BatchNorm2d):
                mod.running_mean.uniform_(-0.3, 0.3)
                mod.running_var.uniform_(0.7, 1.4)

    x = np.random.default_rng(0).standard_normal((1, 224, 224, 3),
                                                 dtype=np.float32)
    with torch.no_grad():
        want = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()

    variables = vgg_from_torch(tm.state_dict(), 11)
    model = models.VGG11(num_classes=7, dropout_rate=0.0,
                         dtype=jnp.float32)
    got = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4)


def test_vgg_from_torch_plain_structure_and_errors():
    from bluefog_tpu.utils.torch_interop import vgg_from_torch

    from bluefog_tpu.models.vgg import _CFGS
    tm = _make_torch_vgg(_CFGS[11], batch_norm=False)
    variables = vgg_from_torch(tm.state_dict(), 11)
    assert "batch_stats" not in variables  # plain variant detected
    convs = [k for k in variables["params"] if k.startswith("conv_")]
    assert len(convs) == 8
    assert variables["params"]["fc_0"]["kernel"].shape == (25088, 4096)
    # depth mismatch is loud, not silently wrong
    with pytest.raises(ValueError):
        vgg_from_torch(tm.state_dict(), 16)
    with pytest.raises(ValueError):
        vgg_from_torch({}, 13)
