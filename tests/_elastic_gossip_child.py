"""Child for the kill-and-respawn-mid-gossip elastic test (ISSUE r9).

Four controllers, two devices each, running push-sum window gossip under
``bfrun --elastic``. Controller 3 hard-exits mid-loop at incarnation 0 —
the launcher respawns it with ``BLUEFOG_INCARNATION=1``, the control plane
fences its zombie and GCs its queued deposits, and the respawn rejoins
through quarantined state transfer (donor mass split for push-sum).
Survivors must (a) detect {3} dead and keep bounded gossip steps on the
renormalized graph, (b) observe its RE-ADMISSION after quarantine
completes, and (c) finish with finite, converging parameters; the
rejoiner asserts its quarantine completed and that it trains on.

NOTE: like every multi-process slow test in this tree, this needs a jax
build with CPU multiprocess collectives (this image lacks them), plus a
jax.distributed coordinator that tolerates a process re-initializing with
the same process id. The control-plane half of the protocol (fencing, GC,
quarantine, mass split) is covered by fast in-process tests in
tests/test_chaos.py.
"""

import os
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf

N = 8
DEAD_PID = 3


def main() -> None:
    inc = int(os.environ.get("BLUEFOG_INCARNATION", "0") or 0)
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == N, bf.size()

    def loss_fn(params, batch):
        return jnp.sum((params["w"] - 3.0) ** 2)

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((4,), jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))

    if pid == DEAD_PID and inc == 0:
        for _ in range(3):
            state, _ = opt.step(state, batch)
        print(f"HEALTHY {pid}", flush=True)
        os._exit(17)  # SIGKILL shape: no announce, no atexit — respawned

    if inc > 0:
        # the respawned rank: opt.init above already ran quarantined state
        # transfer (donor mass split); prove it trains on
        assert not bf.runtime.heartbeat.quarantine_pending()
        print(f"REJOINED {pid} inc={inc}", flush=True)
        for _ in range(5):
            state, _ = opt.step(state, batch)
        for shard in state.params["w"].addressable_shards:
            assert np.isfinite(np.asarray(shard.data)).all()
        print(f"REJOIN_STEPS_OK {pid}", flush=True)
        os._exit(0)

    for _ in range(3):
        state, _ = opt.step(state, batch)
    print(f"HEALTHY {pid}", flush=True)

    detected = readmitted = False
    deadline = time.monotonic() + 180
    while time.monotonic() < deadline and not (detected and readmitted):
        t0 = time.monotonic()
        state, _ = opt.step(state, batch)
        step_s = time.monotonic() - t0
        assert step_s < 30, f"step took {step_s:.1f}s"
        if not detected and bf.dead_controllers() == {DEAD_PID}:
            detected = True
            assert bf.dead_ranks() == {6, 7}, bf.dead_ranks()
            print(f"DEAD_DETECTED {pid}", flush=True)
        if detected and not bf.dead_controllers():
            readmitted = True
            print(f"READMITTED {pid}", flush=True)
    if not (detected and readmitted):
        print(f"SURVIVOR_TIMEOUT {pid} detected={detected} "
              f"readmitted={readmitted}", flush=True)
        os._exit(3)
    for _ in range(3):  # post-readmission: full-graph gossip again
        state, _ = opt.step(state, batch)
    for shard in state.params["w"].addressable_shards:
        assert np.isfinite(np.asarray(shard.data)).all()
    print(f"SURVIVOR_STEPS_OK {pid}", flush=True)

    # rendezvous so process 0 (coordinator + control-plane host) exits last
    from bluefog_tpu.runtime import control_plane
    cl = control_plane.client()
    cl.put(f"eg.done.{pid}", 1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(cl.get(f"eg.done.{i}") for i in range(3)):
            break
        time.sleep(0.05)
    print(f"CHILD_OK {pid}", flush=True)
    if pid == 0:
        time.sleep(2.0)
    os._exit(0)


if __name__ == "__main__":
    main()
