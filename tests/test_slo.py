"""Request-path tracing + SLO plane (docs/slo.md).

Covers the r21 observability surface end to end at unit granularity:
the ``BLUEFOG_SLO`` grammar, the multi-window burn-rate engine fed by
synthetic series, the per-request span analyzer's disjoint phase
buckets, the heartbeat-slot reclaim that keeps ``bf.serve.client.<cid>``
bounded, the zero-touch pin (knobs unset -> wire bytes and flight ring
untouched), and — behind the native skipif — the acceptance demo: a
served request whose client + publisher flight rings merge into ONE
chrome trace with a cross-process stripe flow pair, phase buckets
summing to the request latency, and the snapshot lineage resolving to
its exact producing train step.
"""

import json
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from bluefog_tpu.runtime import flight
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime import timeseries as ts
from bluefog_tpu.serving import snapshot as snap

TESTS = Path(__file__).resolve().parent
PUB_CHILD = TESTS / "_serve_pub_child.py"

needs_native = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable (no g++?)")


class FakeKV:
    """In-memory stand-in for the scalar+bytes KV surface the snapshot
    protocol uses (same shape as test_serving's; wire-free unit tests)."""

    def __init__(self):
        self.b = {}
        self.s = {}

    def put_bytes(self, k, v):
        self.b[k] = bytes(v)

    def get_bytes(self, k):
        return self.b.get(k, b"")

    def bytes_len(self, k):
        return len(self.b.get(k, b""))

    def put_bytes_many(self, ks, vs):
        for k, v in zip(ks, vs):
            self.put_bytes(k, v)

    def get_bytes_many(self, ks):
        return [self.get_bytes(k) for k in ks]

    def put(self, k, v):
        self.s[k] = int(v)

    def get(self, k):
        return self.s.get(k, 0)

    def put_max(self, k, v):
        self.s[k] = max(self.s.get(k, 0), int(v))
        return self.s[k]

    def fetch_add(self, k, d=1):
        old = self.s.get(k, 0)
        self.s[k] = old + d
        return old


def _leaves():
    rng = np.random.default_rng(5)
    return [rng.standard_normal(400).astype(np.float32),
            rng.standard_normal(77).astype(np.float32)]


# ---------------------------------------------------------------------------
# BLUEFOG_SLO grammar
# ---------------------------------------------------------------------------

def test_parse_slos_grammar():
    objs = ts.parse_slos(
        "serve_p99:50ms@5m, serve_avail:99.9@1h,serve_staleness:3ver@5m")
    assert [o.name for o in objs] == ["serve_p99", "serve_avail",
                                      "serve_staleness"]
    p99, avail, stale = objs
    assert p99.target == pytest.approx(50000.0)     # microseconds
    assert p99.window_s == pytest.approx(300.0)
    assert p99.budget == pytest.approx(0.01)
    assert avail.target == pytest.approx(99.9)
    assert avail.window_s == pytest.approx(3600.0)
    assert avail.budget == pytest.approx(1e-3)
    assert stale.target == pytest.approx(3.0)       # snapshot versions
    assert stale.budget == pytest.approx(0.01)


def test_parse_slos_defaults_and_p50():
    (obj,) = ts.parse_slos("serve_p50:2ms")
    assert obj.window_s == pytest.approx(300.0)     # default fast window
    assert obj.target == pytest.approx(2000.0)
    assert obj.budget == pytest.approx(0.5)         # p50 -> 50% allowed


def test_parse_slos_malformed_terms_never_raise():
    assert ts.parse_slos(None) == ()
    assert ts.parse_slos("") == ()
    # unknown kind / unparseable target: warned and skipped, valid
    # terms survive (telemetry config must never take a job down)
    objs = ts.parse_slos("bogus:1@5m,serve_p99:zz@5m,serve_p99:9ms@10s")
    assert len(objs) == 1
    assert objs[0].target == pytest.approx(9000.0)
    assert objs[0].window_s == pytest.approx(10.0)


# ---------------------------------------------------------------------------
# multi-window burn-rate engine (synthetic series; no serving stack)
# ---------------------------------------------------------------------------

def _seeded_store(monkeypatch, spec, burn="2.0"):
    monkeypatch.setenv("BLUEFOG_SLO", spec)
    monkeypatch.setenv("BLUEFOG_SLO_BURN", burn)
    return ts.TimeSeriesStore()


def test_burn_rate_fires_on_both_windows_and_clears_on_fast(monkeypatch):
    """Timeline clean -> storm -> clean over 1 s samples: the alert
    fires only when BOTH the fast (10 s) and slow (120 s) burn rates
    exceed the threshold, reports the exhausted budget, and clears as
    soon as the fast window drains — no for_sec, the windows sustain."""
    store = _seeded_store(monkeypatch, "serve_p99:50ms@10s")
    req = store.series("slo.requests", "counter", "last")
    err = store.series("slo.breach.serve_p99", "counter", "last")
    t0 = 1000.0
    nerr = 0
    # clean minute: 10 req/s, zero breaches
    for i in range(60):
        req.add(t0 + i, 10.0 * i)
        err.add(t0 + i, 0.0)
    store._evaluate_slos(t0 + 59)
    (st,) = store.slo_status()
    assert st["name"] == "serve_p99" and not st["active"]
    assert store.active_alerts() == []
    # storm: every request breaches for 20 s
    for i in range(60, 80):
        nerr += 10
        req.add(t0 + i, 10.0 * i)
        err.add(t0 + i, float(nerr))
    store._evaluate_slos(t0 + 79)
    (st,) = store.slo_status()
    assert st["active"], "both burn windows over threshold: must fire"
    assert st["burn_fast"] >= 2.0 and st["burn_slow"] >= 2.0
    assert st["budget_remaining"] <= 0.0, \
        "a full-window 100% breach storm must exhaust the budget"
    alerts = store.active_alerts()
    assert any(a["name"] == "slo.serve_p99" for a in alerts)
    # the published ts doc carries the alert to --top / bf.alerts.<rank>
    doc = store.build_doc(4096, 0, t0 + 79, 1.0)
    assert any(a["name"] == "slo.serve_p99" for a in doc["alerts"])
    # recovery: requests keep flowing, breaches stop; the fast window
    # drains and the alert clears even while the slow window still burns
    for i in range(80, 100):
        req.add(t0 + i, 10.0 * i)
        err.add(t0 + i, float(nerr))
    store._evaluate_slos(t0 + 99)
    (st,) = store.slo_status()
    assert not st["active"], "fast-window recovery must clear the alert"
    assert st["burn_fast"] == pytest.approx(0.0)


def test_burn_rate_fast_only_spike_does_not_page(monkeypatch):
    """A short spike saturates the fast window but not the 12x slow
    window: no alert (the classic multi-window guarantee)."""
    store = _seeded_store(monkeypatch, "serve_p99:50ms@10s")
    req = store.series("slo.requests", "counter", "last")
    err = store.series("slo.breach.serve_p99", "counter", "last")
    t0 = 2000.0
    # 10 clean minutes so the slow window is well covered...
    for i in range(600):
        req.add(t0 + i, 100.0 * i)
        err.add(t0 + i, 0.0)
    # ...then a 2 s total-breach spike
    for i in range(600, 602):
        req.add(t0 + i, 100.0 * i)
        err.add(t0 + i, float((i - 599) * 100))
    store._evaluate_slos(t0 + 601)
    (st,) = store.slo_status()
    assert st["burn_fast"] >= 2.0, "spike must saturate the fast window"
    assert st["burn_slow"] < 2.0
    assert not st["active"], "fast-only spike must not page"


def test_serve_avail_burns_on_shed_series(monkeypatch):
    """Availability objectives read ``slo.shed`` as the error series."""
    store = _seeded_store(monkeypatch, "serve_avail:99@10s")
    req = store.series("slo.requests", "counter", "last")
    shed = store.series("slo.shed", "counter", "last")
    t0 = 3000.0
    for i in range(30):     # 10% of requests shed, budget is 1%
        req.add(t0 + i, 10.0 * i)
        shed.add(t0 + i, 1.0 * i)
    store._evaluate_slos(t0 + 29)
    (st,) = store.slo_status()
    assert st["active"] and st["burn_fast"] == pytest.approx(10.0, rel=0.2)


# ---------------------------------------------------------------------------
# per-request span analyzer
# ---------------------------------------------------------------------------

def _doc(rows):
    names, idx = [], {}
    ev = {"kind": [], "name": [], "t_wall_us": [], "a": [], "b": []}
    for k, name, t, a, b in rows:
        if name not in idx:
            idx[name] = len(names)
            names.append(name)
        ev["kind"].append(k)
        ev["name"].append(idx[name])
        ev["t_wall_us"].append(float(t))
        ev["a"].append(float(a))
        ev["b"].append(int(b))
    return {"names": names, "events": ev}


def test_analyze_serve_disjoint_phase_buckets():
    """Hand-built trace: the queue time a swap pull was blocking is
    carved into ``swap_blocked``, ``reply`` is the post-decode tail, and
    the six buckets sum exactly to the request duration."""
    B, E = flight.SPAN_B, flight.SPAN_E
    rep = flight.analyze_serve(_doc([
        (B, "serve.req", 1000, 0, 7),
        (B, "serve.admit", 1000, 0, 7), (E, "serve.admit", 1010, 0, 7),
        (B, "serve.queue", 1010, 0, 7),
        (B, "serve.pull", 1200, 0, 3),
        (B, "serve.pull.ep", 1210, 0, 0),
        (B, "serve.failover", 1300, 0, 3),
        (E, "serve.failover", 1400, 0, 3),
        (E, "serve.pull.ep", 1390, 12345, 0),
        (E, "serve.pull", 1400, 1, 3),
        (E, "serve.queue", 1500, 0, 7),
        (B, "serve.linger", 1500, 0, 7), (E, "serve.linger", 1600, 0, 7),
        (B, "serve.decode", 1600, 0, 7), (E, "serve.decode", 1900, 0, 7),
        (E, "serve.req", 2000, 5, 7),
        (B, "serve.req", 5000, 0, 8),   # incomplete: ignored
    ]))
    assert rep["requests"] == 1
    (tr,) = rep["traces"]
    assert tr["tid"] == 7 and tr["ver"] == 5 and tr["dur_us"] == 1000
    ph = tr["phases"]
    assert ph["admit"] == pytest.approx(10.0)
    assert ph["swap_blocked"] == pytest.approx(200.0)  # queue ∩ pull
    assert ph["queue"] == pytest.approx(290.0)         # 490 - blocked
    assert ph["linger"] == pytest.approx(100.0)
    assert ph["decode"] == pytest.approx(300.0)
    assert ph["reply"] == pytest.approx(100.0)         # decode end -> req end
    assert sum(ph.values()) == pytest.approx(tr["dur_us"])
    assert tr["coverage"] == pytest.approx(1.0)
    assert rep["pulls"] == 1 and rep["failovers"] == 1
    assert rep["endpoints"]["0"]["pulls"] == 1
    assert rep["endpoints"]["0"]["bytes"] == pytest.approx(12345.0)


def test_analyze_serve_none_without_request_spans():
    assert flight.analyze_serve(_doc([])) is None
    B = flight.SPAN_B
    assert flight.analyze_serve(
        _doc([(B, "serve.req", 100, 0, 1)])) is None  # never completed


# ---------------------------------------------------------------------------
# heartbeat-slot reclaim: bf.serve.client.<cid> keys stay bounded
# ---------------------------------------------------------------------------

def test_client_slots_reclaimed_not_grown_forever(monkeypatch):
    """The r18 regression: every client generation used to fetch_add a
    fresh cid, so ``bf.serve.client.<cid>`` keys were never reclaimed.
    Now a clean release frees the slot immediately and a crashed
    client's slot expires through the TTL."""
    monkeypatch.setenv("BLUEFOG_SERVE_CLIENT_TTL_S", "30")
    cl = FakeKV()
    assert snap.claim_client_slot(cl) == 0
    assert snap.claim_client_slot(cl) == 1
    assert cl.s[snap.CLIENTS_KEY] == 2
    # clean close -> immediate reuse, the key set stays at the peak
    snap.release_client_slot(cl, 0)
    assert snap.claim_client_slot(cl) == 0
    assert cl.s[snap.CLIENTS_KEY] == 2
    # a crashed client (stale beat) expires through the TTL
    snap._put_float(cl, snap.CLIENT_HB_FMT.format(cid=1),
                    time.time() - 120.0)
    assert snap.claim_client_slot(cl) == 1
    assert cl.s[snap.CLIENTS_KEY] == 2


def test_client_slot_ttl_zero_disables_stale_reuse(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SERVE_CLIENT_TTL_S", "0")
    cl = FakeKV()
    assert snap.claim_client_slot(cl) == 0
    snap._put_float(cl, snap.CLIENT_HB_FMT.format(cid=0),
                    time.time() - 1e6)  # ancient but non-zero beat
    assert snap.claim_client_slot(cl) == 1, \
        "TTL 0 must never reclaim a live-looking slot"
    # ...while an explicit release still frees it
    snap.release_client_slot(cl, 0)
    assert snap.claim_client_slot(cl) == 0


def test_live_client_ids_tracks_beats(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SERVE_CLIENT_TTL_S", "30")
    cl = FakeKV()
    a = snap.claim_client_slot(cl)
    b = snap.claim_client_slot(cl)
    assert snap.live_client_ids(cl, hb_window_s=5.0) == [a, b]
    snap.release_client_slot(cl, b)
    assert snap.live_client_ids(cl, hb_window_s=5.0) == [a]
    snap._put_float(cl, snap.CLIENT_HB_FMT.format(cid=a),
                    time.time() - 60.0)
    assert snap.live_client_ids(cl, hb_window_s=5.0) == []


# ---------------------------------------------------------------------------
# the zero-touch pin: knobs unset -> wire and ring byte-identical
# ---------------------------------------------------------------------------

def test_untraced_publish_touches_neither_wire_nor_ring(monkeypatch):
    monkeypatch.delenv("BLUEFOG_TRACE_SERVE", raising=False)
    rec = flight.recorder()
    before = rec.snapshot()["recorded"]
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=3)
    pub.publish(_leaves(), 1, step=7)
    pub.publish(_leaves(), 2, step=8)
    assert rec.snapshot()["recorded"] == before, \
        "untraced publish must not record a single ring event"
    assert not any(k.startswith("bf.serve.lineage.") for k in cl.b), \
        "untraced publish must not stamp lineage sidecars"
    for k, blob in cl.b.items():
        if k.startswith("bf.serve.snap."):
            assert blob[5] == 0, f"{k}: flags byte set without tracing"


def test_traced_publish_differs_only_in_flags_plus_lineage(monkeypatch):
    """Same leaves published traced and untraced: the shard payloads are
    byte-identical except the header flags byte, and only the traced run
    stamps a lineage record resolving to the exact producing step."""
    leaves = _leaves()
    monkeypatch.delenv("BLUEFOG_TRACE_SERVE", raising=False)
    plain = FakeKV()
    snap.SnapshotPublisher(plain, shards=3).publish(leaves, 1, step=41)
    monkeypatch.setenv("BLUEFOG_TRACE_SERVE", "1")
    traced = FakeKV()
    snap.SnapshotPublisher(traced, shards=3).publish(leaves, 1, step=41)
    for k in plain.b:
        if not k.startswith("bf.serve.snap."):
            continue
        a, b = plain.b[k], traced.b[k]
        assert len(a) == len(b)
        assert a[:5] == b[:5] and a[6:] == b[6:], f"{k}: payload drifted"
        assert a[5] == 0 and b[5] == snap.FLAG_LINEAGE
    lin = snap.read_lineage(traced, 1)
    assert lin is not None
    assert lin["ver"] == 1 and lin["step"] == 41 and lin["fmt"] == 1
    assert snap.read_lineage(plain, 1) is None


def test_lineage_gc_rides_the_keep_window(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TRACE_SERVE", "1")
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=2, keep=2)
    for v in range(1, 5):
        pub.publish(_leaves(), v, step=v)
    assert snap.read_lineage(cl, 1) is None, "GC'd with its version"
    assert snap.read_lineage(cl, 2) is None
    assert snap.read_lineage(cl, 4)["step"] == 4


# ---------------------------------------------------------------------------
# acceptance demo: ONE merged chrome trace across client + publisher
# ---------------------------------------------------------------------------

def _flow_pairs_across_pids(merged):
    starts, ends = {}, {}
    for e in merged:
        if e.get("cat") == "bf.flow":
            (starts if e["ph"] == "s" else ends).setdefault(
                e["id"], set()).add(e["pid"])
    return [fid for fid, sp in starts.items() if ends.get(fid, set()) - sp]


@needs_native
def test_e2e_merged_trace_lineage_and_phase_sum(monkeypatch, tmp_path):
    """THE acceptance demo, pinned: serve requests against a live
    publisher child, then merge the two processes' flight rings into one
    chrome trace — at least one stripe-pull flow pair must connect the
    publisher's FLOW_S to this process's FLOW_F, the phase buckets must
    sum to the request latency within 10%, and the answering snapshot's
    lineage must resolve to its exact producing train step."""
    from bluefog_tpu.serving.client import ServeClient

    monkeypatch.setenv("BLUEFOG_SERVE_POLL_S", "0.05")
    monkeypatch.setenv("BLUEFOG_TRACE_SERVE", "1")
    flight.reset_for_job()
    dump = tmp_path / "pub_flight.json"
    try:
        with native.ControlPlaneServer(world=2) as srv:
            proc = subprocess.Popen(
                [sys.executable, str(PUB_CHILD), "--port", str(srv.port),
                 "--shards", "4", "--elems", "4000", "--period-ms", "100",
                 "--keep", "4", "--flight-dump", str(dump),
                 "--flight-rank", "1"],
                stdout=subprocess.DEVNULL)
            cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0)
            sc = ServeClient([("127.0.0.1", srv.port)],
                             model_fn=lambda params, xs: xs + params[0][0])
            try:
                assert sc.wait_ready(timeout=15), "no snapshot pulled"
                for _ in range(5):
                    lo = sc.version()
                    out = sc.infer(np.zeros(3, np.float32), timeout=10)
                    # the child publishes all-equal-to-version leaves
                    assert float(lo) <= float(out[0]), \
                        "answer older than the already-seen fence"
                ver = sc.version()
                lin = snap.read_lineage(cl, ver)
                assert lin is not None, "traced publish without lineage"
                assert lin["ver"] == ver and lin["fmt"] == 1
                assert lin["step"] == ver, \
                    "lineage must name the exact producing train step"
                time.sleep(0.5)   # fresh paced publishes -> fresh pulls
                proc.terminate()  # SIGTERM: child writes its ring, exits 0
                assert proc.wait(timeout=15) == 0
            finally:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
                sc.close()
                cl.close()
        client_doc = flight.build_dump("slo-e2e-test")
        pub_doc = json.loads(dump.read_text())
        assert pub_doc["meta"]["rank"] == 1
        merged = flight.merge_dumps([client_doc, pub_doc])
        pids = {e["pid"] for e in merged}
        assert len(pids) >= 2, "merged trace must span both processes"
        assert _flow_pairs_across_pids(merged), \
            "no cross-process stripe flow pair in the merged trace"
        rep = flight.analyze_serve(client_doc)
        assert rep is not None and rep["requests"] >= 5
        covs = sorted(t["coverage"] for t in rep["traces"])
        assert 0.9 <= covs[len(covs) // 2] <= 1.1, \
            f"phase buckets must sum to the request latency (got {covs})"
        assert all(t["ver"] >= 1 for t in rep["traces"]), \
            "every trace must carry its answering snapshot version"
    finally:
        flight.reset_for_job()
