"""Compile-time scaling evidence: the HLO-level communication contract.

These tests AOT-lower the PRODUCTION train-step programs
(optimizers.build_fused_step / build_sharded_step) for abstract TPU meshes
of 8-128 devices and assert the collective structure the >95 %@128 scaling
claim rests on (reference docs/performance.rst:44-48). No devices needed:
lowering is pure compilation, so the 128-chip program is checked on this
dev box exactly as XLA would receive it on a pod.
"""

import math

import pytest

from bluefog_tpu import scaling


NS = (8, 16, 64, 128)


@pytest.mark.parametrize("n", NS)
def test_static_expo2_step_is_logn_permutes_no_allreduce(n):
    c = scaling.count_step_collectives("neighbor_static_expo2", n)
    assert c["collective_permute"] == math.ceil(math.log2(n))
    assert c["all_reduce"] == 0
    assert c["all_gather"] == 0 and c["reduce_scatter"] == 0


@pytest.mark.parametrize("n", NS)
def test_dynamic_onepeer_step_is_one_permute_no_allreduce(n):
    c = scaling.count_step_collectives("neighbor_dynamic_onepeer", n)
    assert c["collective_permute"] == 1
    assert c["all_reduce"] == 0
    assert c["all_gather"] == 0 and c["reduce_scatter"] == 0


def test_dynamic_onepeer_every_step_in_cycle_is_one_shift():
    # the one-peer schedule stays one-permute-per-step across its whole
    # cycle, not just at step 0 (each step is a distinct edge set / plan)
    n = 16
    for step in range(math.ceil(math.log2(n))):
        plan = scaling.dynamic_onepeer_plan(n, step=step)
        assert len(plan.shifts) == 1, (step, plan.shifts)


@pytest.mark.parametrize("n", NS)
def test_hierarchical_allreduce_is_local_axis_only(n):
    local = 4
    txt = scaling.lower_train_step("hierarchical", n, local_size=local)
    c = scaling.collective_counts(txt)
    m = n // local
    assert c["all_reduce"] == 1  # the intra-machine pmean
    assert c["collective_permute"] == (math.ceil(math.log2(m)) if m > 1 else 0)
    # the all_reduce's replica groups span local_size devices, never n:
    # machine-crossing traffic is exclusively the permute ops
    import re
    ar = txt[txt.index("stablehlo.all_reduce"):]
    shape = re.search(
        r"replica_groups\s*=\s*dense<.*?>\s*:\s*tensor<(\d+)x(\d+)xi64>",
        ar, re.S)
    n_groups, group_size = int(shape.group(1)), int(shape.group(2))
    assert group_size == local and n_groups == n // local


@pytest.mark.parametrize("n", NS)
def test_zero1_is_reduce_scatter_plus_all_gather(n):
    c = scaling.count_step_collectives("zero1", n)
    assert c["reduce_scatter"] == 1 and c["all_gather"] == 1
    assert c["all_reduce"] == 0


@pytest.mark.parametrize("n", NS)
def test_global_allreduce_baselines(n):
    for kind in ("allreduce", "gradient_allreduce"):
        c = scaling.count_step_collectives(kind, n)
        assert c["all_reduce"] == 1
        assert c["collective_permute"] == 0


def test_permute_count_is_per_leaf_linear():
    # StableHLO emits one permute per shift per leaf; XLA's collective
    # combiner merges them downstream. Lock the per-leaf contract so a
    # regression to e.g. per-element permutes cannot hide.
    n = 8
    one = scaling.count_step_collectives(
        "neighbor_static_expo2", n, n_leaves=1)["collective_permute"]
    three = scaling.count_step_collectives(
        "neighbor_static_expo2", n, n_leaves=3)["collective_permute"]
    assert three == 3 * one == 9


def test_wire_bytes_model_dynamic_beats_allreduce_everywhere():
    for n in NS:
        dyn, rounds = scaling.wire_bytes_per_chip(
            "neighbor_dynamic_onepeer", n, scaling.RESNET50_BYTES)
        ar, ar_rounds = scaling.wire_bytes_per_chip(
            "allreduce", n, scaling.RESNET50_BYTES)
        assert dyn < ar and rounds == 1 and ar_rounds == 2 * (n - 1)


def test_scaling_md_is_current(tmp_path):
    # regenerating the checked-in artifact must reproduce it (table drift
    # against the lowered HLO fails here, not in review)
    import pathlib
    out = tmp_path / "SCALING.md"
    scaling.write_scaling_md(str(out))
    committed = (pathlib.Path(__file__).parent.parent /
                 "SCALING.md").read_text()
    assert out.read_text() == committed


@pytest.mark.parametrize("n", NS)
def test_hlo_measured_bytes_dynamic_onepeer_is_one_param_copy(n):
    """The reference's 'one parameter-size transmit per step' claim, read
    off the lowered program itself: the dynamic one-peer step hands exactly
    one copy of the parameter leaf (64x64 f32 = 16384 B) to exactly one
    collective-permute, at every mesh size."""
    txt = scaling.lower_train_step("neighbor_dynamic_onepeer", n)
    b = scaling.collective_bytes(txt)
    assert b["collective_permute"] == 64 * 64 * 4
    assert sum(v for k, v in b.items() if k != "collective_permute") == 0


@pytest.mark.parametrize("n", NS)
def test_hlo_measured_bytes_static_expo2_is_logn_copies(n):
    txt = scaling.lower_train_step("neighbor_static_expo2", n)
    b = scaling.collective_bytes(txt)
    assert b["collective_permute"] == math.ceil(math.log2(n)) * 64 * 64 * 4


def test_hlo_measured_bytes_scale_with_model_size():
    small = scaling.collective_bytes(
        scaling.lower_train_step("neighbor_dynamic_onepeer", 8, d=64))
    big = scaling.collective_bytes(
        scaling.lower_train_step("neighbor_dynamic_onepeer", 8, d=128))
    assert big["collective_permute"] == 4 * small["collective_permute"]


@pytest.mark.parametrize("n", NS)
def test_ring_attention_hlo_two_permutes_linear_block_shrink(n):
    """Long-context axis: the ring forward's scan body holds exactly TWO
    collective-permutes (K and V block hops, one-neighbor ICI traffic),
    zero all-reduces, and the per-ring-step permute bytes shrink linearly
    with the mesh (each hop carries one [B, S/n, H, D] bf16 block)."""
    S, H, D = 1024, 8, 64
    txt = scaling.lower_cp_forward(n, seq=S, heads=H, d_head=D)
    c = scaling.collective_counts(txt)
    assert c["collective_permute"] == 2
    assert c["all_reduce"] == 0
    b = scaling.collective_bytes(txt)
    assert b["collective_permute"] == 2 * (S // n) * H * D * 2  # bf16


@pytest.mark.parametrize("n", (8, 16, 64, 128))
def test_moe_lm_grad_is_constant_all_to_all(n):
    """Expert parallelism at the HLO level: the expert-parallel MoE-LM
    gradient lowers to exactly 2 all_to_all per MoE layer forward + 2 in
    the backward (their transposes) — a count INDEPENDENT of mesh size,
    with zero collective-permutes (dispatch is all_to_all, not a ring)."""
    hlo = scaling.lower_moe_lm_grad(n, n_layers=2, moe_every=2)  # 1 MoE
    counts = scaling.collective_counts(hlo)
    assert counts["all_to_all"] == 4, counts
    assert counts["collective_permute"] == 0, counts
    assert counts["reduce_scatter"] == 0 and counts["all_gather"] == 0


def test_moe_lm_grad_all_to_all_scales_per_layer():
    """Two MoE layers -> twice the all_to_all, still mesh-size free."""
    hlo = scaling.lower_moe_lm_grad(8, n_layers=2, moe_every=1)  # 2 MoE
    assert scaling.collective_counts(hlo)["all_to_all"] == 8


def test_moe_lm_grad_payload_constant_per_chip():
    """The per-chip all_to_all payload stays ~constant as the mesh grows:
    capacity shrinks as 1/n while the expert fan-out grows as n, so each
    chip hands the interconnect ~2 x its local token bytes regardless of
    scale (the GShard property that makes MoE wiring pod-viable). Holds
    exactly while the capacity bound has not floored at one token — the
    default seq keeps ceil(cf*seq/n) > 1 through n=128, so this measures
    the real scaling regime, not the floor (each chip's buffer is
    [n, ceil(2*seq/n), d]: 8->64, 128->4 slots)."""
    per_chip = {}
    for n in (8, 16, 64, 128):
        hlo = scaling.lower_moe_lm_grad(n, n_layers=2, moe_every=2)
        per_chip[n] = scaling.collective_bytes(hlo)["all_to_all"]
    base = per_chip[8]
    for n, b in per_chip.items():
        assert 0.8 * base <= b <= 1.25 * base, per_chip
