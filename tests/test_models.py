"""Model-zoo sanity: shapes, dtypes, and the space-to-depth stem option."""

import jax
import jax.numpy as jnp
import pytest

from bluefog_tpu import models


@pytest.mark.slow  # ResNet compilation on the CPU backend is minutes-scale
@pytest.mark.parametrize("cls", [models.ResNet18, models.ResNet50])
def test_resnet_forward_shapes(cls):
    model = cls(num_classes=10)
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    v = model.init(jax.random.PRNGKey(0), x, train=True)
    logits, updates = model.apply(v, x, train=True, mutable=["batch_stats"])
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32  # head output cast back to f32
    assert "batch_stats" in updates


@pytest.mark.slow  # two ResNet-50 compiles
def test_space_to_depth_stem_matches_output_geometry():
    """The MLPerf-style stem must produce the same downstream shapes as the
    7x7/2 conv stem (112x112 pre-pool at 224 input), differing only in the
    stem parameters themselves."""
    x = jnp.zeros((1, 64, 64, 3), jnp.float32)
    conv_model = models.ResNet50(num_classes=7, stem="conv")
    s2d_model = models.ResNet50(num_classes=7, stem="space_to_depth")
    vc = conv_model.init(jax.random.PRNGKey(0), x, train=True)
    vs = s2d_model.init(jax.random.PRNGKey(0), x, train=True)
    lc, _ = conv_model.apply(vc, x, train=True, mutable=["batch_stats"])
    ls, _ = s2d_model.apply(vs, x, train=True, mutable=["batch_stats"])
    assert lc.shape == ls.shape == (1, 7)
    # stem params: 7x7x3->64 vs 4x4x12->64, same output channel count
    assert vc["params"]["conv_init"]["kernel"].shape == (7, 7, 3, 64)
    assert vs["params"]["conv_init_s2d"]["kernel"].shape == (4, 4, 12, 64)
    # everything downstream is architecturally identical
    assert set(vc["params"].keys()) - {"conv_init"} == \
        set(vs["params"].keys()) - {"conv_init_s2d"}


def test_odd_input_rejected_by_s2d():
    model = models.ResNet18(num_classes=3, stem="space_to_depth")
    x = jnp.zeros((1, 33, 33, 3), jnp.float32)
    with pytest.raises(Exception):
        model.init(jax.random.PRNGKey(0), x, train=True)


@pytest.mark.slow  # VGG compile is minutes-scale on 1 core
def test_vgg_forward_bn_and_plain():
    x = jnp.zeros((2, 32, 32, 3), jnp.float32)
    bn = models.VGG11(num_classes=10)
    v = bn.init(jax.random.PRNGKey(0), x, train=True)
    logits, updates = bn.apply(v, x, train=True, mutable=["batch_stats"],
                               rngs={"dropout": jax.random.PRNGKey(1)})
    assert logits.shape == (2, 10) and logits.dtype == jnp.float32
    assert "batch_stats" in updates

    plain = models.VGG11(num_classes=10, batch_norm=False)
    v = plain.init(jax.random.PRNGKey(0), x, train=False)
    assert "batch_stats" not in v
    logits = plain.apply(v, x, train=False)
    assert logits.shape == (2, 10)


def test_vgg16_config_matches_torchvision_layout():
    # config D: 13 convs + 3 dense; conv widths per stage 2,2,3,3,3.
    # Shape-only assertions: eval_shape skips the minutes-scale compile.
    x = jnp.zeros((1, 32, 32, 3), jnp.float32)
    model = models.VGG16(num_classes=5, dropout_rate=0.0)
    v = jax.eval_shape(lambda k: model.init(k, x, train=False),
                       jax.random.PRNGKey(0))
    convs = [k for k in v["params"] if k.startswith("conv_")]
    assert len(convs) == 13
    widths = [v["params"][k]["kernel"].shape[-1] for k in sorted(
        convs, key=lambda s: int(s.split("_")[1]))]
    assert widths == [64, 64, 128, 128, 256, 256, 256, 512, 512, 512,
                      512, 512, 512]
    assert v["params"]["fc_0"]["kernel"].shape[-1] == 4096
    assert v["params"]["head"]["kernel"].shape == (4096, 5)
    # torchvision keeps conv biases even under batch norm; the interop
    # contract (a future vgg_from_torch) needs the same parameter set
    assert all("bias" in v["params"][k] for k in convs)


@pytest.mark.slow
def test_vgg_resolution_portability_via_7x7_pool():
    # 224-class resolutions (multiples of 7 post-conv) share classifier shapes
    model = models.VGG11(num_classes=3, dropout_rate=0.0, batch_norm=False)
    v224 = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 224, 224, 3)),
                      train=False)
    v448 = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 448, 448, 3)),
                      train=False)
    assert v224["params"]["fc_0"]["kernel"].shape == \
        v448["params"]["fc_0"]["kernel"].shape == (7 * 7 * 512, 4096)
    # params from one resolution apply at the other
    out = model.apply(v224, jnp.zeros((1, 448, 448, 3)), train=False)
    assert out.shape == (1, 3)


def test_fold_batchnorm_exact_inference():
    """models.fold_batchnorm: the fold_bn=True variant with folded params
    reproduces the eval-mode forward of the unfolded model (the torch
    fuse_conv_bn_eval contract) without any batch_stats collection."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from bluefog_tpu.models import ResNet18, fold_batchnorm

    # f32 end-to-end: the check is the algebraic identity of the fold, and
    # bf16 would hide fold mistakes inside rounding noise
    model = ResNet18(num_classes=10, dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 64, 64, 3))
    variables = model.init(rng, x, train=True)
    # make the BN statistics non-trivial (fresh init is mean 0 var 1)
    _, upd = model.apply(variables, x, train=True, mutable=["batch_stats"])
    stats = upd["batch_stats"]
    ref = model.apply(
        {"params": variables["params"], "batch_stats": stats},
        x, train=False)

    folded = fold_batchnorm(variables["params"], stats)
    fmodel = ResNet18(num_classes=10, dtype=jnp.float32, fold_bn=True)
    got = fmodel.apply({"params": folded}, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
    # no BN params survive the fold; every conv gained a bias
    flat = jax.tree_util.tree_leaves_with_path(folded)
    names = {"/".join(str(k.key) for k in path) for path, _ in flat}
    assert not any("BatchNorm" in n or "bn_init" in n or "norm_proj" in n
                   for n in names), names
    assert any(n.endswith("Conv_0/bias") for n in names)
    # training with the folded variant is rejected
    import pytest as _pytest
    with _pytest.raises(ValueError, match="inference-only"):
        fmodel.apply({"params": folded}, x, train=True)


def test_fold_batchnorm_bottleneck_resnet50():
    """Same identity on the BottleneckBlock path (ResNet50): pins the
    BatchNorm_2->Conv_2 and bottleneck conv_proj/norm_proj pairing that
    the PERF.md / fold.py ResNet50 usage depends on, and the stats-
    mismatch guard."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import pytest as _pytest
    from bluefog_tpu.models import fold_batchnorm
    from bluefog_tpu.models.resnet import ResNet50

    model = ResNet50(num_classes=4, num_filters=8, dtype=jnp.float32)
    x = jax.random.normal(jax.random.PRNGKey(2), (2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    _, upd = model.apply(variables, x, train=True, mutable=["batch_stats"])
    stats = upd["batch_stats"]
    ref = model.apply(
        {"params": variables["params"], "batch_stats": stats},
        x, train=False)
    folded = fold_batchnorm(variables["params"], stats)
    fmodel = ResNet50(num_classes=4, num_filters=8, dtype=jnp.float32,
                      fold_bn=True)
    got = fmodel.apply({"params": folded}, x, train=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
    # mismatched stats raise at fold time, not as a flax apply error later
    with _pytest.raises(ValueError, match="no matching batch_stats"):
        fold_batchnorm(variables["params"], {})
