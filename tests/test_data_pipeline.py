"""Host input pipeline: prefetch_to_device semantics."""

import numpy as np
import pytest

import jax

import bluefog_tpu as bf
from bluefog_tpu.utils import prefetch_to_device


def test_prefetch_yields_all_batches_in_order(bf8):
    sh = bf.rank_sharding(bf.mesh())
    batches = [(np.full((8, 2), i, np.float32), np.full((8,), i, np.int32))
               for i in range(5)]
    out = list(prefetch_to_device(iter(batches), size=2, sharding=sh))
    assert len(out) == 5
    for i, (x, y) in enumerate(out):
        assert isinstance(x, jax.Array) and x.sharding == sh
        np.testing.assert_array_equal(np.asarray(x), batches[i][0])
        np.testing.assert_array_equal(np.asarray(y), batches[i][1])


def test_prefetch_keeps_transfers_in_flight(bf8):
    """With size=k, the iterator stays k ahead of the consumer (the
    double-buffering contract): after pulling batch 0, batches 0..k have
    already been submitted to the device."""
    sh = bf.rank_sharding(bf.mesh())
    submitted = []

    def producer():
        for i in range(6):
            submitted.append(i)
            yield np.full((8, 1), i, np.float32)

    it = prefetch_to_device(producer(), size=3, sharding=sh)
    first = next(it)
    assert float(np.asarray(first)[0, 0]) == 0.0
    # batch 0 consumed; the queue was filled `size` deep before yielding
    assert submitted == [0, 1, 2]
    rest = list(it)
    assert len(rest) == 5 and submitted == list(range(6))


def test_prefetch_size_validation(bf8):
    # raises at the call site, not deferred to the first next()
    with pytest.raises(ValueError):
        prefetch_to_device(iter([]), size=0)


def test_prefetch_short_iterator_drains(bf8):
    sh = bf.rank_sharding(bf.mesh())
    out = list(prefetch_to_device(
        iter([np.ones((8, 1), np.float32)]), size=4, sharding=sh))
    assert len(out) == 1
