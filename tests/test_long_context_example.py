"""Smoke the long-context LM example end-to-end on the CPU mesh.

examples/long_context_lm.py is the sequence-parallel flagship (ring /
Ulysses CP + single-chip flash); until now only manual runs covered it.
Tiny shapes, few steps: the assertion is that each attention mode trains
(loss decreases) through the real example code path.
"""

import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _run(attention: str, extra=()):
    env = {
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": str(REPO),
        "PATH": "/usr/bin:/bin",
        "HOME": "/tmp",
    }
    out = subprocess.run(
        [sys.executable, str(REPO / "examples" / "long_context_lm.py"),
         "--attention", attention, "--seq-len", "64", "--batch-size", "2",
         "--d-model", "32", "--num-layers", "1", "--num-heads", "8",
         "--vocab", "32", "--steps", "6", *extra],
        env=env, capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    return out.stdout


@pytest.mark.parametrize("attention", ["ring", "ulysses"])
def test_cp_example_trains(attention):
    stdout = _run(attention)
    losses = [float(line.rsplit("loss ", 1)[1])
              for line in stdout.splitlines() if "loss " in line]
    assert len(losses) >= 2 and losses[-1] < losses[0], stdout


@pytest.mark.slow  # interpret-mode flash is the slow path on CPU
def test_flash_example_trains():
    stdout = _run("flash")
    assert "full-sequence on one chip" in stdout
    losses = [float(line.rsplit("loss ", 1)[1])
              for line in stdout.splitlines() if "loss " in line]
    assert len(losses) >= 2 and losses[-1] < losses[0], stdout
