"""Checkpoint save/restore roundtrip (net-new vs the reference, SURVEY §5.4)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import bluefog_tpu as bf

N = 8


def loss_fn(p, b):
    return 0.5 * jnp.sum((p["w"] - b) ** 2)


def test_checkpoint_roundtrip(bf8, tmp_path):
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1, momentum=0.9), loss_fn)
    state = opt.init({"w": jnp.zeros(4, jnp.float32)})
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 4))
    for _ in range(3):
        state, _ = opt.step(state, targets)

    path = str(tmp_path / "ckpt")
    bf.checkpoint.save(path, state, step=3)

    template = opt.init({"w": jnp.zeros(4, jnp.float32)})
    restored, step = bf.checkpoint.restore(path, template=template)
    assert step == 3
    np.testing.assert_allclose(np.asarray(restored.params["w"]),
                               np.asarray(state.params["w"]), rtol=1e-6)
    # momentum buffers restored too
    got_leaves = jax.tree_util.tree_leaves(restored.opt_state)
    want_leaves = jax.tree_util.tree_leaves(state.opt_state)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
    # training continues from the restored state
    restored2, _ = opt.step(restored, targets)
    jax.block_until_ready(restored2.params)


def test_async_save_roundtrip(bf8, tmp_path):
    """save_async keeps training unblocked; wait_pending commits; restore
    sees the exact state. A second async save serializes behind the first."""
    from bluefog_tpu import checkpoint as ck

    x = bf.shard_rank_stacked(bf.mesh(),
                              np.arange(16.0, dtype=np.float32).reshape(8, 2))
    st0 = bf.TrainState(params={"w": x}, opt_state={"m": x * 2.0},
                        model_state=None)
    p1 = tmp_path / "a1"
    ck.save_async(str(p1), st0, step=5)
    # back-to-back async saves must serialize, not corrupt each other
    p2 = tmp_path / "a2"
    ck.save_async(str(p2), st0, step=6)
    ck.wait_pending()

    for p, step in ((p1, 5), (p2, 6)):
        restored, got_step = ck.restore(str(p), template=st0)
        assert got_step == step
        np.testing.assert_allclose(np.asarray(restored.params["w"]),
                                   np.asarray(x))
        np.testing.assert_allclose(np.asarray(restored.opt_state["m"]),
                                   2.0 * np.asarray(x))
