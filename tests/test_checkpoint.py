"""Checkpoint save/restore roundtrip (net-new vs the reference, SURVEY §5.4)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import bluefog_tpu as bf

N = 8


def loss_fn(p, b):
    return 0.5 * jnp.sum((p["w"] - b) ** 2)


def test_checkpoint_roundtrip(bf8, tmp_path):
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1, momentum=0.9), loss_fn)
    state = opt.init({"w": jnp.zeros(4, jnp.float32)})
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 4))
    for _ in range(3):
        state, _ = opt.step(state, targets)

    path = str(tmp_path / "ckpt")
    bf.checkpoint.save(path, state, step=3)

    template = opt.init({"w": jnp.zeros(4, jnp.float32)})
    restored, step = bf.checkpoint.restore(path, template=template)
    assert step == 3
    np.testing.assert_allclose(np.asarray(restored.params["w"]),
                               np.asarray(state.params["w"]), rtol=1e-6)
    # momentum buffers restored too
    got_leaves = jax.tree_util.tree_leaves(restored.opt_state)
    want_leaves = jax.tree_util.tree_leaves(state.opt_state)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
    # training continues from the restored state
    restored2, _ = opt.step(restored, targets)
    jax.block_until_ready(restored2.params)
