"""Checkpoint save/restore roundtrip (net-new vs the reference, SURVEY §5.4)."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import bluefog_tpu as bf

N = 8


def loss_fn(p, b):
    return 0.5 * jnp.sum((p["w"] - b) ** 2)


def test_checkpoint_roundtrip(bf8, tmp_path):
    opt = bf.DistributedNeighborAllreduceOptimizer(
        optax.sgd(0.1, momentum=0.9), loss_fn)
    state = opt.init({"w": jnp.zeros(4, jnp.float32)})
    targets = jnp.arange(N, dtype=jnp.float32).reshape(N, 1) * jnp.ones((N, 4))
    for _ in range(3):
        state, _ = opt.step(state, targets)

    path = str(tmp_path / "ckpt")
    bf.checkpoint.save(path, state, step=3)

    template = opt.init({"w": jnp.zeros(4, jnp.float32)})
    restored, step = bf.checkpoint.restore(path, template=template)
    assert step == 3
    np.testing.assert_allclose(np.asarray(restored.params["w"]),
                               np.asarray(state.params["w"]), rtol=1e-6)
    # momentum buffers restored too
    got_leaves = jax.tree_util.tree_leaves(restored.opt_state)
    want_leaves = jax.tree_util.tree_leaves(state.opt_state)
    assert len(got_leaves) == len(want_leaves)
    for g, w in zip(got_leaves, want_leaves):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), rtol=1e-6)
    # training continues from the restored state
    restored2, _ = opt.step(restored, targets)
    jax.block_until_ready(restored2.params)


def test_async_save_roundtrip(bf8, tmp_path):
    """save_async keeps training unblocked; wait_pending commits; restore
    sees the exact state. A second async save serializes behind the first."""
    from bluefog_tpu import checkpoint as ck

    x = bf.shard_rank_stacked(bf.mesh(),
                              np.arange(16.0, dtype=np.float32).reshape(8, 2))
    st0 = bf.TrainState(params={"w": x}, opt_state={"m": x * 2.0},
                        model_state=None)
    p1 = tmp_path / "a1"
    ck.save_async(str(p1), st0, step=5)
    # back-to-back async saves must serialize, not corrupt each other
    p2 = tmp_path / "a2"
    ck.save_async(str(p2), st0, step=6)
    ck.wait_pending()

    for p, step in ((p1, 5), (p2, 6)):
        restored, got_step = ck.restore(str(p), template=st0)
        assert got_step == step
        np.testing.assert_allclose(np.asarray(restored.params["w"]),
                                   np.asarray(x))
        np.testing.assert_allclose(np.asarray(restored.opt_state["m"]),
                                   2.0 * np.asarray(x))


def test_meta_sidecar_records_world_identity(bf8, tmp_path):
    """save records world size + topology fingerprint + membership epoch in
    a sidecar; restore onto the SAME world passes silently (ISSUE r9)."""
    from bluefog_tpu import checkpoint as ck

    x = bf.shard_rank_stacked(bf.mesh(),
                              np.arange(8.0, dtype=np.float32).reshape(8, 1))
    st = bf.TrainState(params={"w": x}, opt_state={}, model_state=None)
    path = str(tmp_path / "meta_ck")
    ck.save(path, st, step=4)
    meta = ck.read_meta(path)
    assert meta is not None
    assert meta["world"] == N
    assert meta["step"] == 4
    assert "topology_crc" in meta and "membership_epoch" in meta
    restored, step = ck.restore(path, template=st, strict=True)
    assert step == 4


def test_meta_mismatch_warns_and_strict_raises(bf8, tmp_path):
    """A checkpoint whose sidecar names a DIFFERENT world warns on restore
    (and raises with strict=True) instead of silently resuming rank-stacked
    state onto the wrong world."""
    import json
    import logging

    from bluefog_tpu import checkpoint as ck
    from bluefog_tpu.runtime.logging import logger as bflog

    x = bf.shard_rank_stacked(bf.mesh(),
                              np.arange(8.0, dtype=np.float32).reshape(8, 1))
    st = bf.TrainState(params={"w": x}, opt_state={}, model_state=None)
    path = str(tmp_path / "mismatch_ck")
    ck.save(path, st, step=1)
    # tamper: pretend the checkpoint came from a 16-rank world with another
    # topology
    meta = ck.read_meta(path)
    meta["world"] = 16
    meta["topology_crc"] = (meta.get("topology_crc", 0) + 1) & 0xFFFFFFFF
    with open(ck._meta_path(path), "w") as f:
        json.dump(meta, f)

    # the package logger sets propagate=False: capture with our own handler
    records = []
    cap = logging.Handler(level=logging.WARNING)
    cap.emit = records.append
    bflog.addHandler(cap)
    try:
        restored, _ = ck.restore(path, template=st)  # warns, succeeds
    finally:
        bflog.removeHandler(cap)
    assert any("different world" in r.getMessage() for r in records)

    with pytest.raises(RuntimeError, match="different world"):
        ck.restore(path, template=st, strict=True)


def test_meta_absent_is_tolerated(bf8, tmp_path):
    """Pre-r9 checkpoints (no sidecar) restore without checks or warnings."""
    import os

    from bluefog_tpu import checkpoint as ck

    x = bf.shard_rank_stacked(bf.mesh(),
                              np.arange(8.0, dtype=np.float32).reshape(8, 1))
    st = bf.TrainState(params={"w": x}, opt_state={}, model_state=None)
    path = str(tmp_path / "old_ck")
    ck.save(path, st, step=2)
    os.unlink(ck._meta_path(path))
    restored, step = ck.restore(path, template=st, strict=True)
    assert step == 2


def test_latest_path_picks_newest(tmp_path):
    import os
    import time

    from bluefog_tpu import checkpoint as ck

    assert ck.latest_path(str(tmp_path)) is None
    for name in ("ck1", "ck2", "ck3"):
        os.mkdir(tmp_path / name)
        time.sleep(0.01)
    os.utime(tmp_path / "ck2")  # freshest mtime
    assert ck.latest_path(str(tmp_path)) == str(tmp_path / "ck2")
    assert ck.latest_path(str(tmp_path / "missing")) is None
