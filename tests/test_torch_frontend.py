"""Live torch-tensor frontend: collectives, windows, module hooks.

The reference's torch op suite (torch_ops_test.py / torch_win_ops_test.py)
drives every op with live torch tensors; these tests hold the new
``bluefog_tpu.torch`` frontend to the same exactness oracles as the jax
surface — same values, torch tensors in and out, dtypes preserved
(incl. bfloat16, which crosses the bridge as a bit-view).
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import bluefog_tpu as bf  # noqa: E402
import bluefog_tpu.torch as bft  # noqa: E402
from bluefog_tpu import topology as topology_util  # noqa: E402

N = 8


def rank_t(width=3, dtype=torch.float32):
    return (torch.arange(N, dtype=torch.float32)[:, None]
            * torch.ones(1, width)).to(dtype)


def test_roundtrip_dtypes(bf8):
    for dt in (torch.float32, torch.int32, torch.bfloat16, torch.float16):
        t = rank_t(dtype=dt)
        back = bft.to_torch(bft.to_jax(t))
        assert back.dtype == dt
        assert torch.equal(back.float(), t.float())
    # float64: JAX computes in f32 by default (jax_enable_x64 unset); the
    # raw bridge surfaces that, the OP wrappers restore the caller's dtype
    t64 = rank_t(dtype=torch.float64)
    assert bft.to_torch(bft.to_jax(t64)).dtype == torch.float32
    out = bft.allreduce(t64, average=True)
    assert out.dtype == torch.float64
    np.testing.assert_allclose(out.numpy(), 3.5, atol=1e-6)


def test_allreduce_torch(bf8):
    out = bft.allreduce(rank_t(), average=True)
    assert isinstance(out, torch.Tensor)
    np.testing.assert_allclose(out.numpy(), 3.5, atol=1e-6)


def test_neighbor_allreduce_torch_matches_oracle(bf8):
    bf.set_topology(topology_util.RingGraph(N))
    out = bft.neighbor_allreduce(rank_t())
    for r in range(N):
        exp = (r + (r - 1) % N + (r + 1) % N) / 3.0
        np.testing.assert_allclose(out[r].numpy(), exp, atol=1e-5)


def test_dynamic_neighbor_allreduce_torch(bf8):
    sends = {r: [(r + 1) % N] for r in range(N)}
    out = bft.neighbor_allreduce(
        rank_t(), self_weight=0.5,
        neighbor_weights={r: {(r - 1) % N: 0.5} for r in range(N)},
        send_neighbors=sends)
    for r in range(N):
        exp = 0.5 * r + 0.5 * ((r - 1) % N)
        np.testing.assert_allclose(out[r].numpy(), exp, atol=1e-5)


def test_broadcast_allgather_torch(bf8):
    b = bft.broadcast(rank_t(), root_rank=3)
    np.testing.assert_allclose(b.numpy(), 3.0, atol=1e-6)
    g = bft.allgather(rank_t(width=2))
    assert g.shape == (N, N * 2)


def test_bf16_neighbor_allreduce_preserves_dtype(bf8):
    out = bft.neighbor_allreduce(torch.ones(N, 4, dtype=torch.bfloat16))
    assert out.dtype == torch.bfloat16
    np.testing.assert_allclose(out.float().numpy(), 1.0, atol=1e-2)


def test_windows_torch(bf8):
    x = rank_t(width=2)
    assert bft.win_create(x, "t.win", zero_init=True)
    try:
        bft.win_put(x, "t.win")
        out = bft.win_update("t.win")
        assert isinstance(out, torch.Tensor)
        topo = bf.load_topology()
        for r in range(N):
            nbrs = topology_util.in_neighbor_ranks(topo, r)
            want = (x[r] + sum(x[s] for s in nbrs)) / (len(nbrs) + 1)
            np.testing.assert_allclose(out[r].numpy(), want.numpy(),
                                       atol=1e-5)
    finally:
        bft.win_free("t.win")


def _make_modules(seed=0):
    mods = []
    for r in range(N):
        torch.manual_seed(seed + r)
        mods.append(torch.nn.Linear(4, 2))
    return mods


def test_broadcast_parameters(bf8):
    mods = _make_modules()
    want = {nm: p.data.clone() for nm, p in mods[2].named_parameters()}
    bft.broadcast_parameters(mods, root_rank=2)
    for m in mods:
        for nm, p in m.named_parameters():
            np.testing.assert_allclose(p.data.numpy(), want[nm].numpy(),
                                       atol=1e-6)


def test_distributed_torch_optimizer_mixes_params(bf8):
    """A real torch loop: per-rank Linear modules, SGD steps, neighbor
    mixing after each step drives the ranks toward consensus — the
    reference's decentralized-optimizer contract, live torch end to end."""
    bf.set_topology(topology_util.ExponentialTwoGraph(N))
    mods = _make_modules(seed=42)
    params = [p for m in mods for p in m.parameters()]
    opt = bft.DistributedTorchOptimizer(
        torch.optim.SGD(params, lr=0.0), mods)
    x = torch.randn(16, 4)
    for _ in range(25):
        opt.zero_grad()
        loss = sum(m(x).square().mean() for m in mods)
        loss.backward()
        opt.step()  # lr=0 -> pure consensus dynamics
    w = torch.stack([m.weight.data for m in mods])
    spread = (w - w.mean(dim=0, keepdim=True)).abs().max()
    assert float(spread) < 1e-3, float(spread)


def test_device_resident_matches_host_path(bf8):
    """ISSUE r13 satellite: the device-resident fast path (jax-owned
    buffers + dlpack views) must be numerically identical to the legacy
    stack/scatter host path, and the module parameters must really alias
    the jax rows (an optimizer update through the view is visible to the
    next communicate without any stack)."""
    bf.set_topology(topology_util.ExponentialTwoGraph(N))
    runs = {}
    for resident in (False, True):
        mods = _make_modules(seed=11)
        params = [p for m in mods for p in m.parameters()]
        opt = bft.DistributedTorchOptimizer(
            torch.optim.SGD(params, lr=0.05), mods,
            device_resident=resident)
        x = torch.randn(16, 4, generator=torch.Generator().manual_seed(5))
        for _ in range(4):
            opt.zero_grad()
            loss = sum(m(x).square().mean() for m in mods)
            loss.backward()
            opt.step()
        runs[resident] = torch.stack([m.weight.data.float()
                                      for m in mods]).numpy()
        if resident:
            plan = bft._comm_plan(mods)
            assert plan.device is not None, "residency failed to install"
            # the parameter IS the dlpack view of the jax row buffer
            p0 = mods[0].weight
            v0 = plan.device.views["weight"][0]
            assert p0.data.data_ptr() == v0.data_ptr()
            # write through the view; the jax-owned row must see it
            with torch.no_grad():
                p0.data.fill_(7.0)
            row = np.asarray(plan.device.rows["weight"][0])
            np.testing.assert_allclose(row[0], 7.0)
    np.testing.assert_allclose(runs[True], runs[False], rtol=1e-6,
                               atol=1e-6)


def test_device_resident_survives_data_rebinding(bf8):
    """User code that rebinds ``p.data`` (the plan-cache hazard the host
    path handles by re-reading ``.data``) must not silently diverge: the
    next communicate re-anchors the value into the jax row."""
    mods = _make_modules(seed=13)
    params = [p for m in mods for p in m.parameters()]
    opt = bft.DistributedTorchOptimizer(
        torch.optim.SGD(params, lr=0.0), mods)
    opt.step()  # installs residency + one mixing
    plan = bft._comm_plan(mods)
    assert plan.device is not None
    with torch.no_grad():
        mods[3].weight.data = torch.full_like(mods[3].weight.data, 2.5)
    opt.step()  # re-anchors, then mixes the rebound value
    # rank 3's 2.5s entered the average: its in-neighbors see a blend,
    # and rank 3's own row is no longer all-2.5
    assert not torch.allclose(mods[3].weight.data,
                              torch.full_like(mods[3].weight.data, 2.5))
    assert mods[3].weight.data.data_ptr() == \
        plan.device.views["weight"][3].data_ptr()


def test_optimizer_num_steps_per_communication(bf8):
    mods = _make_modules(seed=7)
    params = [p for m in mods for p in m.parameters()]
    opt = bft.DistributedTorchOptimizer(
        torch.optim.SGD(params, lr=0.0), mods,
        num_steps_per_communication=3)
    w0 = mods[0].weight.data.clone()
    for i in range(2):
        opt.step()  # steps 1-2: no communication
        assert torch.equal(mods[0].weight.data, w0)
    opt.step()  # step 3: mixing happens
    assert not torch.equal(mods[0].weight.data, w0)


def test_broadcast_optimizer_state(bf8):
    """Momentum buffers really move: divergent per-rank SGD momenta are
    replaced by root_rank's (the r5 review caught a no-op version that
    stacked the LOCAL tensor and broadcast it to itself)."""
    mods = _make_modules(seed=3)
    params = [p for m in mods for p in m.parameters()]
    opt = torch.optim.SGD(params, lr=0.1, momentum=0.9)
    for r, m in enumerate(mods):  # divergent grads -> divergent momenta
        loss = (m(torch.full((4, 4), float(r + 1))) ** 2).mean()
        loss.backward()
    opt.step()
    named = [dict(m.named_parameters()) for m in mods]
    key = "weight"
    mom = lambda r: opt.state[named[r][key]]["momentum_buffer"]  # noqa: E731
    assert not torch.allclose(mom(0), mom(5))
    bft.broadcast_optimizer_state(opt, mods, root_rank=5)
    want = mom(5).clone()
    for r in range(N):
        np.testing.assert_allclose(mom(r).numpy(), want.numpy(), atol=1e-6)


def test_broadcast_optimizer_state_adam_step_not_aliased(bf8):
    """Adam's 0-dim 'step' tensors must be CLONED per rank: a shared
    tensor would advance N times per step (r5 review finding)."""
    mods = _make_modules(seed=9)
    params = [p for m in mods for p in m.parameters()]
    opt = torch.optim.Adam(params, lr=0.01)
    for r, m in enumerate(mods):
        ((m(torch.randn(4, 4)) * (r + 1)) ** 2).mean().backward()
    opt.step()
    bft.broadcast_optimizer_state(opt, mods, root_rank=0)
    named = [dict(m.named_parameters()) for m in mods]
    steps = [opt.state[named[r]["weight"]]["step"] for r in range(N)]
    assert len({id(s) for s in steps}) == N  # distinct tensor objects
    for _ in range(2):  # further steps advance every rank's counter by 1
        for r, m in enumerate(mods):
            ((m(torch.randn(4, 4)) * (r + 1)) ** 2).mean().backward()
        opt.step()
    for r in range(N):
        assert float(opt.state[named[r]["weight"]]["step"]) == 3.0
