"""Sharded-model gossip (ISSUE r17): FSDP-style window rows.

Pins the tentpole's contracts: the partition-rule layer (regex rules →
per-leaf shard cuts, the auto largest-axis rule, the size floor), the
sharded fusion layer (pack_row/assemble_rows roundtrips across shard
factors × codecs × dtype mixes, S=1 byte-identity with the legacy wire),
the compiled pack/scatter rotation inside the window optimizers
(consensus + exact S=1 parity), the deposit wire's shard guard (a
drifted rotation's coordinates are dropped, its exact p mass folds), and
the acceptance demo: a window plane that fails replicated packing under
an RSS rlimit trains sharded (slow, subprocess).
"""

import contextlib
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu.ops import codec as cd
from bluefog_tpu.ops import fusion as _fusion
from bluefog_tpu.ops import partition as _partition
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import metrics as bf_metrics
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.state import _global_state

from conftest import cpu_devices

N = 8


# ---------------------------------------------------------------------------
# partition rules (no mesh needed)
# ---------------------------------------------------------------------------

def lm_tree(n=N, vocab=50, d=12):
    """LM-shaped param tree: embedding + attention-block + norm leaves —
    the realistic shapes the partition rules must handle."""
    rng = np.random.RandomState(3)
    return {
        "embedding": jnp.asarray(rng.randn(n, vocab, d).astype(np.float32)),
        "block0": {
            "qkv": jnp.asarray(rng.randn(n, d, 3 * d).astype(np.float32)),
            "proj": jnp.asarray(rng.randn(n, d, d).astype(np.float32)),
            "mlp_up": jnp.asarray(rng.randn(n, d, 4 * d).astype(np.float32)),
            "ln_scale": jnp.asarray(rng.randn(n, d).astype(np.float32)),
        },
        "head_bias": jnp.asarray(rng.randn(n, vocab).astype(np.float32)),
    }


def test_parse_rules_grammar_and_fallback():
    rules = _partition.parse_rules("embedding=0, qkv=1, norm=none, .*=largest")
    # 4 parsed terms + the auto backstop
    assert len(rules) == 5
    assert rules[0][1] == 0 and rules[1][1] == 1 and rules[2][1] == "none"
    # malformed terms degrade (skipped with a warning), never raise
    rules = _partition.parse_rules("oops, [=bad, x=seven")
    assert rules[-1][1] == "largest"
    # unset → the auto rule alone
    assert [r[1] for r in _partition.parse_rules(None)] == ["largest"]


def test_match_partition_rules_first_match_and_scalars():
    names = ["embedding", "block0/qkv", "block0/ln_scale", "scalar"]
    shapes = [(50, 12), (12, 36), (12,), ()]
    axes = _partition.match_partition_rules(
        _partition.parse_rules("qkv=1,ln=none,.*=0"), names, shapes)
    assert axes == [0, 1, None, None]  # scalar never partitions
    # auto rule: largest axis
    axes = _partition.match_partition_rules(
        _partition.parse_rules(None), names, shapes)
    assert axes == [0, 1, 0, None]


def test_build_shard_spec_floor_and_balance():
    tree = lm_tree()
    leaves = jax.tree_util.tree_leaves(tree)
    shapes = [tuple(x.shape[1:]) for x in leaves]
    dtypes = [x.dtype for x in leaves]
    names = _partition.leaf_names(tree)
    sh = _partition.build_shard_spec(shapes, dtypes, 4, names=names,
                                     floor_bytes=256)
    assert sh.factor == 4 and len(sh.pieces) == 4
    # every element lands in exactly one piece
    assert sum(sh.totals) == sum(int(np.prod(s)) if s else 1 for s in shapes)
    # balance: shards within ~2x of each other for this tree
    assert max(sh.totals) < 2 * min(sh.totals)
    # the floor keeps the small ln_scale leaf whole (one piece, axis -1)
    ln_i = names.index("block0/ln_scale")
    ln_pieces = [p for ps in sh.pieces for p in ps if p[0] == ln_i]
    assert len(ln_pieces) == 1 and ln_pieces[0][1] == -1


# ---------------------------------------------------------------------------
# sharded fusion: property roundtrips (satellite 3)
# ---------------------------------------------------------------------------

def mixed_dtype_leaves(rng, n=N):
    import ml_dtypes

    return [
        rng.randn(n, 7, 5).astype(np.float32),
        (rng.randn(n, 33) * 3).astype(ml_dtypes.bfloat16),
        rng.randn(n, 4, 3, 2).astype(np.float32),
        rng.randn(n).astype(np.float32),
    ]


@pytest.mark.parametrize("factor", [1, 2, 4])
@pytest.mark.parametrize("codec_spec", [None, "int8", "topk:0.1"])
def test_pack_row_roundtrip_shard_x_codec_x_dtypes(factor, codec_spec):
    """Property: for every (shard factor, codec, dtype mix), per-shard
    pack_row → assemble_rows reproduces exactly what the codec pipeline
    itself would — and with no codec, reassembly is bit-exact."""
    rng = np.random.RandomState(10 + factor)
    leaves = mixed_dtype_leaves(rng)
    shapes = [tuple(x.shape[1:]) for x in leaves]
    dtypes = [x.dtype for x in leaves]
    sh = _partition.build_shard_spec(shapes, dtypes, factor)
    spec = _fusion.make_spec([jnp.asarray(x) for x in leaves], shard=sh)
    codec = cd.resolve(codec_spec)
    for r in range(0, N, 3):
        rows = [_fusion.pack_row([x[r] for x in leaves], spec,
                                 codec=codec, shard=s)
                for s in range(factor)]
        back = _fusion.assemble_rows(rows, spec, codec=codec)
        if codec is None:
            for a, b in zip(leaves, back):
                np.testing.assert_array_equal(np.asarray(a[r]), b)
        else:
            # wiring property: assembling the DECODED shard rows equals
            # decoding each shard row and assembling raw — the codec's
            # own error is not under test here
            raw_rows = [codec.decode(
                rows[s].reshape(-1).view(np.uint8),
                np.dtype(spec.buffer_dtype), sh.row_len)
                for s in range(factor)]
            expect = _fusion.assemble_rows(raw_rows, spec)
            for a, b in zip(expect, back):
                np.testing.assert_array_equal(a, b)


def test_shard_factor_1_wire_byte_identity():
    """Legacy byte-identity: a factor-1 sharded spec packs the EXACT
    bytes the r15 wire packs — sharding off is not approximately off."""
    rng = np.random.RandomState(5)
    leaves = mixed_dtype_leaves(rng)
    sh = _partition.build_shard_spec(
        [tuple(x.shape[1:]) for x in leaves], [x.dtype for x in leaves], 1)
    spec = _fusion.make_spec([jnp.asarray(x) for x in leaves], shard=sh)
    assert sh.totals == (spec.total,) and sh.row_len == spec.total
    for r in range(N):
        legacy = _fusion.pack_row([x[r] for x in leaves], spec)
        sharded = _fusion.pack_row([x[r] for x in leaves], spec, shard=0)
        assert legacy.tobytes() == sharded.tobytes()
        # and under a codec the encoded payloads match byte for byte
        c = cd.Int8Codec()
        assert _fusion.pack_row([x[r] for x in leaves], spec,
                                codec=c).tobytes() == \
            _fusion.pack_row([x[r] for x in leaves], spec, codec=c,
                             shard=0).tobytes()


@pytest.mark.parametrize("factor", [2, 4])
def test_compiled_pack_scatter_roundtrip(factor):
    """The jitted rotation: pack every shard, scatter into zeroed leaves,
    recover the tree bit for bit (pad tail ignored)."""
    tree = lm_tree()
    sh = _partition.spec_for_tree(tree, factor, floor_bytes=64)
    spec = _fusion.make_spec(tree, shard=sh)
    leaves = jax.tree_util.tree_leaves(tree)
    out = [jnp.zeros_like(x) for x in leaves]
    for s in range(factor):
        buf = _fusion.pack_shard_jit(tree, spec, s)
        assert buf.shape == (N, sh.row_len)
        out = list(_fusion.scatter_shard_jit(out, buf, spec, s))
    for a, b in zip(leaves, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scatter_shard_donate_knob(monkeypatch):
    """BLUEFOG_WIN_SHARD_DONATE=0 opts out of leaf donation (the caller
    keeps pre-step aliases readable) and produces the same result as the
    donating default (docs/sharded_windows.md, donation contract)."""
    rng = np.random.RandomState(4)
    tree = [jnp.asarray(rng.randn(N, 6, 4).astype(np.float32))]
    sh = _partition.build_shard_spec([(6, 4)], [np.dtype(np.float32)], 2)
    spec = _fusion.make_spec(tree, shard=sh)
    buf = _fusion.pack_shard_jit(tree, spec, 0)
    monkeypatch.setenv("BLUEFOG_WIN_SHARD_DONATE", "0")
    leaves = [jnp.zeros_like(tree[0])]
    out_nd = _fusion.scatter_shard_jit(leaves, buf, spec, 0)
    # non-donating path: the input leaves stay valid and untouched
    np.testing.assert_array_equal(np.asarray(leaves[0]), 0.0)
    monkeypatch.delenv("BLUEFOG_WIN_SHARD_DONATE")
    out_d = _fusion.scatter_shard_jit([jnp.zeros_like(tree[0])], buf,
                                      spec, 0)
    for a, b in zip(out_nd, out_d):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ---------------------------------------------------------------------------
# optimizer rotation (collective plane, single controller)
# ---------------------------------------------------------------------------

def zero_loss(p, b):
    return 0.0 * sum(jnp.sum(x) for x in jax.tree_util.tree_leaves(p))


def _run_winput(shard_env, steps=10, seed=2, monkeypatch=None):
    if shard_env is not None:
        monkeypatch.setenv("BLUEFOG_WIN_SHARD", str(shard_env))
    monkeypatch.setenv("BLUEFOG_FUSION_THRESHOLD", str(8 << 20))
    bf.init(devices=cpu_devices(N))
    try:
        rng = np.random.RandomState(seed)
        params0 = {
            f"l{i}": {"w": jnp.asarray(rng.randn(N, 6, 4).astype(np.float32)),
                      "b": jnp.asarray(rng.randn(N, 4).astype(np.float32))}
            for i in range(4)
        }
        opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zero_loss)
        single = jax.tree_util.tree_map(lambda x: x[0], params0)
        st0 = opt.init(single)
        state = bf.TrainState(
            params=jax.device_put(params0, bf.rank_sharding(bf.mesh())),
            opt_state=st0.opt_state, model_state=None)
        batch = jnp.zeros((N, 1), jnp.float32)
        for _ in range(steps):
            state, _ = opt.step(state, batch)
        out = jax.tree_util.tree_map(np.asarray, state.params)
        factor = opt._shard_factor
        opt.free()
        return out, factor
    finally:
        bf.shutdown()


def test_sharded_winput_reaches_consensus_and_s1_is_exact(monkeypatch):
    """S=1 must be the legacy path bit for bit; S∈{2,4} rotations must
    still drive every rank to consensus (each shard mixes every S-th
    step — block-coordinate gossip)."""
    base, f0 = _run_winput(None, monkeypatch=monkeypatch)
    assert f0 == 1
    s1, f1 = _run_winput(1, monkeypatch=monkeypatch)
    assert f1 == 1
    for a, b in zip(jax.tree_util.tree_leaves(base),
                    jax.tree_util.tree_leaves(s1)):
        np.testing.assert_array_equal(a, b)  # bit-exact at factor 1
    for S in (2, 4):
        got, f = _run_winput(S, steps=6 * S, monkeypatch=monkeypatch)
        assert f == S
        for leaf in jax.tree_util.tree_leaves(got):
            spread = np.abs(leaf - leaf.mean(axis=0, keepdims=True)).max()
            assert spread < 5e-2, f"S={S}: no consensus, spread {spread}"


def test_sharded_push_sum_exact_mean(monkeypatch):
    """Push-sum under rotation: each block's gossip is a valid push-sum
    step with the CURRENT p (numerator rebuilt from params every step),
    so consensus still lands on the exact initial mean."""
    monkeypatch.setenv("BLUEFOG_FUSION_THRESHOLD", str(8 << 20))
    monkeypatch.setenv("BLUEFOG_WIN_SHARD", "2")
    bf.init(devices=cpu_devices(N))
    try:
        rng = np.random.RandomState(7)
        params0 = {"w": jnp.asarray(rng.randn(N, 40).astype(np.float32)),
                   "v": jnp.asarray(rng.randn(N, 9, 3).astype(np.float32))}
        opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), zero_loss)
        single = jax.tree_util.tree_map(lambda x: x[0], params0)
        st0 = opt.init(single)
        assert opt._shard_factor == 2
        leaves = jax.tree_util.tree_leaves(
            jax.device_put(params0, bf.rank_sharding(bf.mesh())))
        # install true per-rank values into the packed window numerator
        # (shard 0 is the window's bound rotation at creation)
        win = _global_state().windows[opt._win_names[0]]
        assert win.shard_factor == 2
        state = bf.TrainState(
            params=jax.device_put(params0, bf.rank_sharding(bf.mesh())),
            opt_state=st0.opt_state, model_state=None)
        batch = jnp.zeros((N, 1), jnp.float32)
        for _ in range(80):
            state, _ = opt.step(state, batch)
        got = jax.tree_util.tree_map(np.asarray, state.params)
        for leaf0, leafN in zip(jax.tree_util.tree_leaves(params0),
                                jax.tree_util.tree_leaves(got)):
            expect = np.mean(np.asarray(leaf0, dtype=np.float64), axis=0)
            for r in range(N):
                np.testing.assert_allclose(leafN[r], expect, atol=2e-2)
        opt.free()
        bf.turn_off_win_ops_with_associated_p()
    finally:
        bf.shutdown()


# ---------------------------------------------------------------------------
# hosted wire: the shard guard + sidx publish (world-1 control plane)
# ---------------------------------------------------------------------------

def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture()
def bf_hosted(monkeypatch):
    monkeypatch.setenv("BLUEFOG_CP_HOST", "127.0.0.1")
    monkeypatch.setenv("BLUEFOG_CP_PORT", str(_free_port()))
    monkeypatch.setenv("BLUEFOG_CP_WORLD", "1")
    monkeypatch.setenv("BLUEFOG_CP_RANK", "0")
    monkeypatch.setenv("BLUEFOG_WIN_PLANE", "hosted")
    cp.reset_for_test()
    bf.init(devices=cpu_devices(N))
    assert cp.active()
    yield bf
    bf.shutdown()
    cp.reset_for_test()


pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


def test_deposit_shard_guard_drops_drifted_value_keeps_p(bf_hosted):
    """The wire's rotation guard: a deposit carrying shard index s ≠ the
    owner's active shard folds its exact p mass but NOT its value (wrong
    subspace's coordinates), and win.shard_stale_drops counts it. A
    matching shard folds normally."""
    elems = 64
    x = jnp.zeros((N, elems), jnp.float32)
    assert bf.win_create(x, "sx.guard", zero_init=True)
    win = win_ops._get_window("sx.guard")
    win.bind_shard(2)
    win.set_active_shard(0)
    bf.turn_on_win_ops_with_associated_p()
    try:
        dst, src = 0, sorted(win.in_neighbors[0])[0]
        k = win.layout.slot_of[dst][src]
        payload = np.arange(elems, dtype=np.float32)
        cl = cp.client()

        def deposit(shard, seq, pc):
            recs = win_ops._pack_deposit(win_ops._DEP_ACC, 1, pc, payload,
                                         shard=shard)
            cl.append_bytes_tagged_many(
                [win._dep_key(dst, k)] * len(recs), recs,
                win_ops._deposit_tags(seq, len(recs)))

        drops0 = bf_metrics.snapshot()["counters"].get(
            "win.shard_stale_drops", 0)
        deposit(shard=1, seq=1, pc=0.25)   # drifted: value dropped
        deposit(shard=0, seq=2, pc=0.5)    # aligned: value folds
        win._drain_deposits()
        drops1 = bf_metrics.snapshot()["counters"].get(
            "win.shard_stale_drops", 0)
        assert drops1 - drops0 == 1
        # only the aligned deposit's value landed...
        np.testing.assert_array_equal(win._mail_rows[dst][k], payload)
        # ...but BOTH deposits' p mass folded (conservation under drift)
        assert win.host.read_p_mail()[dst, k] == pytest.approx(0.75)
    finally:
        bf.turn_off_win_ops_with_associated_p()
        bf.win_free("sx.guard")


def test_deposit_shard_guard_put_mode_drops_whole_pair(bf_hosted):
    """Put-mode drift discards the WHOLE (value, p) pair: overwriting
    only p against the slot's retained previous-rotation value would
    leave a torn pair (stale value, fresh weight) that biases the
    combine. The slot keeps the last same-shard pair instead."""
    elems = 64
    x = jnp.zeros((N, elems), jnp.float32)
    assert bf.win_create(x, "sx.putguard", zero_init=True)
    win = win_ops._get_window("sx.putguard")
    win.bind_shard(2)
    win.set_active_shard(0)
    bf.turn_on_win_ops_with_associated_p()
    try:
        dst, src = 0, sorted(win.in_neighbors[0])[0]
        k = win.layout.slot_of[dst][src]
        cl = cp.client()

        def deposit(shard, seq, pc, payload):
            recs = win_ops._pack_deposit(win_ops._DEP_PUT, 1, pc, payload,
                                         shard=shard)
            cl.append_bytes_tagged_many(
                [win._dep_key(dst, k)] * len(recs), recs,
                win_ops._deposit_tags(seq, len(recs)))

        aligned = np.arange(elems, dtype=np.float32)
        drifted = np.full(elems, 7.0, np.float32)
        drops0 = bf_metrics.snapshot()["counters"].get(
            "win.shard_stale_drops", 0)
        deposit(shard=0, seq=1, pc=0.25, payload=aligned)
        deposit(shard=1, seq=2, pc=0.9, payload=drifted)
        win._drain_deposits()
        drops1 = bf_metrics.snapshot()["counters"].get(
            "win.shard_stale_drops", 0)
        assert drops1 - drops0 == 1
        # the drifted put changed NEITHER half of the pair
        np.testing.assert_array_equal(win._mail_rows[dst][k], aligned)
        assert win.host.read_p_mail()[dst, k] == pytest.approx(0.25)
    finally:
        bf.turn_off_win_ops_with_associated_p()
        bf.win_free("sx.putguard")


def test_published_shard_index_rides_publish(bf_hosted):
    """Sharded publishes carry the rotation index next to the row:
    read_published_shard returns (row, sidx) a rejoiner can collect
    shard-by-shard across the donor's steps."""
    elems = 32
    x = jnp.asarray(np.arange(N * elems, dtype=np.float32).reshape(N, elems))
    assert bf.win_create(x, "sx.sidx")
    win = win_ops._get_window("sx.sidx")
    win.bind_shard(3)
    win.set_active_shard(2)
    win._publish_selves(win.owned)
    row, sidx = win.read_published_shard(1)
    assert sidx == 2
    np.testing.assert_array_equal(row, np.asarray(x)[1])
    # unsharded windows report no index
    assert bf.win_create(jnp.zeros((N, 4)), "sx.plain")
    assert win_ops._get_window("sx.plain").read_published_shard(1)[1] is None
    bf.win_free("sx.sidx")
    bf.win_free("sx.plain")


def test_sharded_rows_reassemble_from_published_shards(bf_hosted,
                                                       monkeypatch):
    """The rejoin reassembly contract end-to-end on the hosted plane:
    with IDENTICAL params (gossip = identity), polling a rank's
    published (row, sidx) across S steps collects every shard, and
    assemble_rows rebuilds the exact parameter leaves — what
    _transfer_rank_sharded + _adopt_window_rows do for a quarantined
    rejoiner."""
    monkeypatch.setenv("BLUEFOG_FUSION_THRESHOLD", str(8 << 20))
    monkeypatch.setenv("BLUEFOG_WIN_SHARD", "2")
    rng = np.random.RandomState(11)
    single = {"w": jnp.asarray(rng.randn(10, 6).astype(np.float32)),
              "b": jnp.asarray(rng.randn(6).astype(np.float32))}
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zero_loss)
    state = opt.init(single)
    batch = jnp.zeros((N, 1), jnp.float32)
    win = _global_state().windows[opt._win_names[0]]
    spec = opt._specs[0]
    got = {}
    for _ in range(2):
        state, _ = opt.step(state, batch)
        row, sidx = win.read_published_shard(3)
        assert sidx is not None
        got.setdefault(sidx, row)
    assert sorted(got) == [0, 1]
    back = _fusion.assemble_rows([got[0], got[1]], spec)
    for leaf, b in zip(jax.tree_util.tree_leaves(single), back):
        np.testing.assert_allclose(np.asarray(leaf), b, atol=1e-6)
    opt.free()


def test_rejoin_realigns_rotation_with_stepping_peers(bf_hosted):
    """A rejoiner that adopts a donor's step counter must ALSO re-derive
    its comm-round count, or its active shard stays phase-shifted from
    every peer forever (the wire guard would then discard all its
    deposits). _realign_rotation restores the stepping invariant
    _comm_rounds == _counter // num_steps_per_communication."""
    peer = bf.DistributedWinPutOptimizer(
        optax.sgd(0.1), zero_loss, num_steps_per_communication=3)
    # a peer that stepped normally: the invariant holds at any counter
    peer._shard_factor = 4
    for c in (1, 2, 3, 7, 21, 22):
        peer._counter = c
        peer._comm_rounds = c // 3  # what stepping maintains
        rejoiner = bf.DistributedWinPutOptimizer(
            optax.sgd(0.1), zero_loss, num_steps_per_communication=3)
        rejoiner._shard_factor = 4
        rejoiner._counter = c       # adopted from the donor's publish
        assert rejoiner._comm_rounds == 0  # init-time value: misaligned
        rejoiner._realign_rotation()
        assert rejoiner._comm_rounds == peer._comm_rounds
        assert rejoiner._active_shard() == peer._active_shard()


def test_sharded_transfer_does_not_mix_donors(bf_hosted, monkeypatch):
    """A retry with a NEW donor must not top up a partial shard
    collection left by a failed previous donor: assemble_rows may only
    stitch a rank's tree from a single donor's rotation."""
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zero_loss)
    opt._shard_factor = 2
    opt._win_names = ["sx.fake"]
    # donor A died after contributing shard 0
    opt._rejoin_shards[("sx.fake", 0)] = {0: np.zeros(3, np.float32)}

    class _FakeWin:
        # donor B is stalled on shard 1 and never rotates
        def read_published_shard(self, donor):
            return np.ones(3, np.float32), 1

    monkeypatch.setattr(win_ops, "_get_window", lambda nm: _FakeWin())
    monkeypatch.setattr(
        win_ops, "win_mutex",
        lambda nm, ranks=None: contextlib.nullcontext())
    ok = opt._transfer_rank_sharded(0, 1, deadline=time.monotonic() + 0.3)
    # donor B never published shard 0 before the deadline: the transfer
    # must FAIL rather than silently stitch donor A's shard 0 to donor
    # B's shard 1
    assert not ok
    assert 0 not in opt._rejoin_shards[("sx.fake", 0)]


# ---------------------------------------------------------------------------
# acceptance demo: replicated packing OOMs under rlimit, sharded trains
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_rlimit_sharded_trains_where_replicated_ooms():
    """ISSUE r17 acceptance: under an RSS rlimit sized to the SHARDED
    window plane, the replicated (S=1) plane fails to even create its
    full-row window, while S=8 completes 20 gossip steps with a finite
    decreasing loss. Subprocess child so the rlimit (and any allocator
    fallout) cannot poison the test process."""
    child = os.path.join(os.path.dirname(__file__),
                         "_sharded_rlimit_child.py")

    def run(shard):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("BLUEFOG_WIN_SHARD", None)
        r = subprocess.run(
            [sys.executable, child, "--shard", str(shard)],
            capture_output=True, text=True, timeout=600, env=env)
        return r

    r8 = run(8)
    assert "SHARDED_TRAIN_OK" in r8.stdout, (r8.stdout + r8.stderr)[-2000:]
    r1 = run(1)
    assert "REPLICATED_OOM" in r1.stdout, (r1.stdout + r1.stderr)[-2000:]
