"""Child program for the 2-process ``bfrun`` smoke test.

Launched (twice) by tests/test_launcher.py through
``python -m bluefog_tpu.launcher -np 2 --coordinator ... --process-id i``.
Each process brings 2 forced CPU devices, so the job is a 2-process x
2-device, size-4 deployment — the smallest real multi-controller layout.
Exercises: jax.distributed bootstrap from the launcher env, control-plane
attach, truthful rank/local_rank introspection, and cross-process compiled
collectives (gloo) through the public op surface.
"""

import os
import time

import jax
import numpy as np

import bluefog_tpu as bf
from bluefog_tpu.runtime import control_plane


def main() -> None:
    # bfrun exported the whole env (-np 2 --simulate 2): init() joins the
    # distributed job FIRST (no jax call may precede it), then ranks over
    # the aggregated 2x2 CPU device set. The default backend may be a
    # different, single-process platform, which is exactly what the
    # platform-aware introspection must see through.
    bf.init()
    pid = jax.process_index("cpu")
    assert jax.process_count("cpu") == 2, jax.process_count("cpu")
    assert bf.size() == 4, bf.size()
    assert bf.rank() == pid, (bf.rank(), pid)
    assert bf.local_size() == 2, bf.local_size()
    assert bf.num_machines() == 2, bf.num_machines()
    # Both processes run on THIS host: local_rank must tell them apart
    # (pre-fix it lied 0 for every controller).
    assert control_plane.active(), "control plane did not attach"
    assert bf.local_rank() == pid, (bf.local_rank(), pid)

    # A real cross-process compiled collective through the public surface.
    global_np = np.arange(8, dtype=np.float32).reshape(4, 2)
    sh = bf.rank_sharding(bf.mesh())
    x = jax.make_array_from_callback(
        global_np.shape, sh, lambda idx: global_np[idx])
    y = bf.allreduce(x, average=True)
    expect = global_np.mean(axis=0)
    for s in y.addressable_shards:
        np.testing.assert_allclose(np.asarray(s.data)[0], expect, atol=1e-6)

    # Ring neighbor averaging crosses the process boundary (ranks 1<->2).
    bf.set_topology(bf.topology_util.RingGraph(4))
    z = bf.neighbor_allreduce(x)
    for s in z.addressable_shards:
        r = s.index[0].start
        want = (global_np[r] + global_np[(r - 1) % 4] + global_np[(r + 1) % 4]) / 3.0
        np.testing.assert_allclose(np.asarray(s.data)[0], want, atol=1e-6)

    # Hierarchical averaging with machine == process: the local pmean stays
    # intra-process, the machine-graph ppermute crosses the process boundary.
    h = bf.hierarchical_neighbor_allreduce(x)
    for s in h.addressable_shards:
        r = s.index[0].start
        machine = r // 2
        local_mean = (global_np[2 * machine] + global_np[2 * machine + 1]) / 2
        other = (global_np[2 * (1 - machine)] + global_np[2 * (1 - machine) + 1]) / 2
        np.testing.assert_allclose(
            np.asarray(s.data)[0], (local_mean + other) / 2.0, atol=1e-6)

    # One-sided windows on a multi-controller GLOBAL array (win_create must
    # not materialize the non-addressable input on the host).
    bf.win_create(x, name="smoke.win", zero_init=True)
    bf.win_put(x, "smoke.win")
    got = bf.win_update(name="smoke.win")
    assert got.shape == global_np.shape
    bf.win_free("smoke.win")

    # Multi-controller checkpointing: on a real pod (mesh backend == default
    # backend) orbax's primary-host path applies; in THIS mixed-backend env
    # (CPU mesh, accelerator plugin default) the library must fail fast with
    # the documented error instead of racing on the commit rename.
    ckdir = os.environ.get("SMOKE_CKPT_DIR")
    if ckdir:
        from bluefog_tpu import checkpoint as ck
        from bluefog_tpu.optimizers import TrainState

        st0 = TrainState(params={"w": x}, opt_state={"m": x * 0.5},
                         model_state=None)
        if jax.process_count() == jax.process_count("cpu"):
            ck.save(ckdir, st0, step=3)
            restored, step = ck.restore(ckdir, template=st0)
            assert step == 3
            for s in restored.params["w"].addressable_shards:
                r = s.index[0].start
                np.testing.assert_allclose(np.asarray(s.data),
                                           global_np[r:r + 1], atol=1e-6)
            # opt_state carries deliberately DIFFERENT values (x * 0.5) so a
            # params/opt_state key mix-up in restore cannot pass silently
            for s in restored.opt_state["m"].addressable_shards:
                r = s.index[0].start
                np.testing.assert_allclose(np.asarray(s.data),
                                           0.5 * global_np[r:r + 1],
                                           atol=1e-6)
        else:
            try:
                ck.save(ckdir, st0, step=3)
                raise AssertionError("expected mixed-backend save to refuse")
            except RuntimeError as e:
                assert "default backend" in str(e), e

    # Live-torch frontend across controllers: each controller holds only
    # ITS ranks' rows (local stack), the op runs globally, and the result
    # comes back as the local view — the reference's per-rank torch API
    # restated for the multi-controller layout. torch is optional to the
    # core launcher smoke: environments without it skip the phase.
    try:
        import torch
    except ImportError:
        torch = None
    if torch is not None:
        import bluefog_tpu.torch as bft

        owned = bft.owned_ranks()
        assert owned == [2 * pid, 2 * pid + 1], (owned, pid)
        local = torch.tensor(global_np[owned[0]:owned[-1] + 1])
        tz = bft.neighbor_allreduce(local)  # ring(4) set above
        assert tz.shape == local.shape
        for i, r in enumerate(owned):
            want = (global_np[r] + global_np[(r - 1) % 4]
                    + global_np[(r + 1) % 4]) / 3.0
            np.testing.assert_allclose(tz[i].numpy(), want, atol=1e-6)
        ta = bft.allreduce(local, average=True)
        for i in range(len(owned)):
            np.testing.assert_allclose(ta[i].numpy(),
                                       global_np.mean(axis=0), atol=1e-6)
        print(f"TORCH_MC_OK {pid}", flush=True)
    else:  # pragma: no cover - torch always present in CI image
        print(f"TORCH_MC_SKIP {pid}", flush=True)

    # Keras frontend across controllers (opt-in: the parent must export
    # KERAS_BACKEND=jax — keras would otherwise try its default backend).
    try:
        import keras
    except ImportError:  # pragma: no cover - keras present in CI image
        keras = None
    if keras is not None and os.environ.get("KERAS_BACKEND") == "jax":
        import bluefog_tpu.keras as bfk
        from bluefog_tpu.utils.local_view import owned_ranks

        owned_k = owned_ranks()
        kms = []
        for r in owned_k:
            keras.utils.set_random_seed(100 + r)  # divergent across ranks
            m = keras.Sequential([keras.layers.Dense(2)])
            m.build((None, 3))
            kms.append(m)
        bfk.broadcast_variables(kms, root_rank=1)
        # rank 1's kernel everywhere: rebuild it on every controller for
        # the oracle (same seed recipe, global rank 1)
        keras.utils.set_random_seed(101)
        ref = keras.Sequential([keras.layers.Dense(2)])
        ref.build((None, 3))
        want = np.asarray(ref.trainable_variables[0])
        for m in kms:
            np.testing.assert_allclose(
                np.asarray(m.trainable_variables[0]), want, atol=1e-6)
        print(f"KERAS_MC_OK {pid}", flush=True)
    else:  # pragma: no cover - keras present in CI image
        print(f"KERAS_MC_SKIP {pid}", flush=True)

    # Control-plane primitives are live across the two controllers.
    cl = control_plane.client()
    total = cl.fetch_add("smoke.counter", 1)
    assert total in (0, 1)
    bf.barrier()

    # Coordinated shutdown, end to end: process 1 leaves first; process 0
    # (which hosts the control-plane server) must observe the announcement
    # through its heartbeat monitor before tearing anything down. The
    # deadline is deliberately short: if process 1 died earlier for an
    # unrelated reason, failing fast here keeps the report pointed at the
    # real root cause instead of a 30 s shutdown-protocol red herring.
    if pid == 1:
        bf.shutdown()
    else:
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline and not bf.shutdown_requested():
            time.sleep(0.1)
        assert bf.shutdown_requested(), "shutdown announcement never seen"
        bf.shutdown()
    print(f"CHILD_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
