"""Child for the cross-controller dynamic topo-check test (2 processes).

Reference parity: ``enable_topo_check`` allgathers the send/recv pattern
across processes and fails on mismatch (mpi_controller.cc:296-345). Here the
controllers first run one AGREED dynamic step (must pass, and its repeat must
be a cached no-op), then deliberately compute DIVERGENT send_neighbors —
every controller must raise instead of dispatching garbage ppermutes.
"""

import os

import numpy as np

import jax

import bluefog_tpu as bf

os.environ["BLUEFOG_TOPO_CHECK_TIMEOUT"] = "3"


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    n = bf.size()
    assert n == 4

    x = np.arange(n, dtype=np.float32).reshape(n, 1)
    sh = bf.rank_sharding(bf.mesh())
    xg = jax.make_array_from_callback(x.shape, sh, lambda i: x[i])

    # agreed dynamic step: ring shift by one, identical on both controllers
    send = {r: [(r + 1) % n] for r in range(n)}
    sw = {r: 0.5 for r in range(n)}
    nw = {r: {(r - 1) % n: 0.5} for r in range(n)}
    y = bf.neighbor_allreduce(xg, self_weight=sw, neighbor_weights=nw,
                              send_neighbors=send, enable_topo_check=True)
    for s in y.addressable_shards:
        r = s.index[0].start or 0
        want = 0.5 * x[r] + 0.5 * x[(r - 1) % n]
        np.testing.assert_allclose(np.asarray(s.data)[0], want, atol=1e-6)
    # warm repeat: cached agreement, no rendezvous cost, same result
    bf.neighbor_allreduce(xg, self_weight=sw, neighbor_weights=nw,
                          send_neighbors=send, enable_topo_check=True)
    print(f"AGREED_OK {pid}", flush=True)
    bf.barrier()

    # divergent step: BOTH controllers move to edge sets that are new to the
    # agreement cache (shift 3 vs shift 2) but different from each other —
    # each waits on its own hash rendezvous, times out, and raises
    shift = 3 if pid == 0 else 2
    bad_send = {r: [(r + shift) % n] for r in range(n)}
    bad_nw = {r: {(r - shift) % n: 0.5} for r in range(n)}
    try:
        bf.neighbor_allreduce(xg, self_weight=sw, neighbor_weights=bad_nw,
                              send_neighbors=bad_send, enable_topo_check=True)
        raise AssertionError("divergent edge sets were not detected")
    except RuntimeError as e:
        assert "DIFFERENT dynamic edge sets" in str(e), e
    print(f"DIVERGENT_RAISED {pid}", flush=True)
    bf.barrier()
    bf.shutdown()
    print(f"CHILD_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
