"""Child program for the 4-controller harness (VERDICT r3 #4).

Launched 4x (2 forced CPU devices each -> a 4-controller, size-8 job) by
tests/test_launcher.py, either as four explicit ``-np 4`` processes or
through one ``bfrun -H localhost:4`` fan-out. The reference CI ran its
whole suite at np=4 (reference Makefile:1); this child packs the
equivalent multi-controller coverage the 2-process children cannot give:

  A. hosted windows at 4 owners: exact put/accumulate/update values over a
     ring, every controller folding deposits from two distinct peers;
  B. window-mutex contention from 4 clients: concurrent require_mutex
     accumulates (strict mode armed) conserve mass exactly;
  C. skewed push-sum: one deliberately slow controller, three fast ones —
     no rate coupling, global mass + p-mass invariants after final drain;
  D. dynamic topo-check at world=4: agreement, then 4-way divergence
     (every controller picks a different edge set) raises everywhere;
  E. win_fence across 4 controllers: deposits issued before the fence are
     visible in the very next update.
"""

import os
import time

import numpy as np

import jax

import bluefog_tpu as bf
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane

os.environ["BLUEFOG_TOPO_CHECK_TIMEOUT"] = "3"

N = 8  # 4 controllers x 2 devices


def owned_rows(arr, owned):
    rows = {}
    for s in arr.addressable_shards:
        rows[s.index[0].start or 0] = np.asarray(s.data)[0]
    return {r: rows[r] for r in owned}


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == N, bf.size()
    bf.set_topology(bf.topology_util.RingGraph(N))
    assert control_plane.active() and control_plane.world() == 4
    cl = control_plane.client()
    owned = [2 * pid, 2 * pid + 1]

    x_np = (np.arange(N, dtype=np.float32) + 1.0).reshape(N, 1)
    topo = bf.load_topology()
    in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
               for r in range(N)}

    # ---- Phase A: exact hosted values with 4 owners ---------------------
    assert bf.win_create(x_np, "q.a", zero_init=True)
    win = win_ops._get_window("q.a")
    assert win.hosted and win.owned == owned, (win.owned, owned)
    bf.win_put(x_np, "q.a")
    bf.win_accumulate(x_np, "q.a")  # put then += : mail slot holds 2*x[src]
    bf.barrier()  # all deposits on the server before anyone drains
    got = owned_rows(bf.win_update("q.a"), owned)
    for r in owned:
        u = 1.0 / (len(in_nbrs[r]) + 1)
        want = u * (x_np[r] + sum(2.0 * x_np[s] for s in in_nbrs[r]))
        np.testing.assert_allclose(got[r], want, rtol=1e-6)
    print(f"PHASE_A_OK {pid}", flush=True)
    bf.barrier()
    bf.win_free("q.a")

    # ---- Phase B: 4-client mutex contention, strict mode armed ----------
    os.environ["BLUEFOG_WIN_STRICT"] = "1"
    assert bf.win_create(x_np, "q.mu", zero_init=True)
    rounds = 6
    for _ in range(rounds):
        bf.win_accumulate(x_np, "q.mu", require_mutex=True)
    # fence so every controller's deposits (bump-before-deposit under the
    # rank mutexes) are folded before the accounting read below
    bf.win_fence("q.mu")
    collected = owned_rows(
        bf.win_update_then_collect("q.mu"), owned)
    part = sum(float((collected[r] - x_np[r])[0]) for r in owned)
    control_plane.put_float(cl, f"q.mu.part.{pid}", part)
    bf.barrier()
    if pid == 0:
        total = sum(control_plane.get_float(cl, f"q.mu.part.{i}")
                    for i in range(4))
        # every rank accumulated x[src] to both ring out-neighbors, rounds
        # times: total neighbor mass = rounds * 2 * sum(x)  (36 = sum 1..8)
        want = rounds * 2 * 36.0
        assert abs(total - want) < 1e-3, (total, want)
        print(f"PHASE_B_MASS {total:.1f}", flush=True)
    os.environ.pop("BLUEFOG_WIN_STRICT")
    bf.barrier()
    bf.win_free("q.mu")

    # ---- Phase C: skewed push-sum (controller 3 is slow) ----------------
    bf.turn_on_win_ops_with_associated_p()
    assert bf.win_create(x_np, "q.ps", zero_init=True)
    outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
            for r in range(N)}
    sw = {r: 1.0 / (outd[r] + 1) for r in range(N)}
    dw = {r: {d: 1.0 / (outd[r] + 1)
              for d in bf.topology_util.out_neighbor_ranks(topo, r)}
          for r in range(N)}
    est = {r: float(x_np[r, 0]) for r in owned}
    # generous margin for loaded CI hosts: the fast controllers' 20 rounds
    # of contended server round-trips must comfortably beat the slow one's
    # 8 x 2.5 s floor, or the uncoupling assert below flakes (observed at
    # 1.0 s when the full suite shares this box's single core: 20 rounds
    # can exceed 8 s under that contention)
    rounds = 8 if pid == 3 else 20
    for _ in range(rounds):
        if pid == 3:
            time.sleep(2.5)  # the slow controller
        p_all = bf.win_associated_p_all("q.ps")
        numer = np.zeros((N, 1), np.float32)
        for r in owned:
            numer[r, 0] = est[r] * p_all[r]
        bf.win_accumulate(numer, "q.ps", self_weight=sw, dst_weights=dw,
                          require_mutex=True)
        coll = owned_rows(bf.win_update_then_collect("q.ps"), owned)
        p_new = bf.win_associated_p_all("q.ps")
        for r in owned:
            est[r] = float(coll[r][0]) / p_new[r]
    if pid == 0:
        assert cl.get("q.ps.done3") == 0, \
            "fast controllers were rate-limited by the slow one"
        print("PHASE_C_UNCOUPLED", flush=True)
    if pid == 3:
        cl.put("q.ps.done3", 1)
    bf.barrier()
    coll = owned_rows(bf.win_update_then_collect("q.ps"), owned)
    part = sum(float(coll[r][0]) for r in owned)
    control_plane.put_float(cl, f"q.ps.part.{pid}", part)
    bf.barrier()
    if pid == 0:
        total = sum(control_plane.get_float(cl, f"q.ps.part.{i}")
                    for i in range(4))
        p_final = bf.win_associated_p_all("q.ps")
        assert abs(total - 36.0) < 1e-3, f"mass not conserved: {total}"
        assert abs(p_final.sum() - 8.0) < 1e-9, f"p mass: {p_final}"
        print(f"PHASE_C_INVARIANT {total:.4f}", flush=True)
    bf.barrier()
    bf.win_free("q.ps")
    bf.turn_off_win_ops_with_associated_p()

    # ---- Phase D: topo-check at world=4 ---------------------------------
    sh = bf.rank_sharding(bf.mesh())
    xg = jax.make_array_from_callback(x_np.shape, sh, lambda i: x_np[i])
    send = {r: [(r + 1) % N] for r in range(N)}
    swt = {r: 0.5 for r in range(N)}
    nwt = {r: {(r - 1) % N: 0.5} for r in range(N)}
    y = bf.neighbor_allreduce(xg, self_weight=swt, neighbor_weights=nwt,
                              send_neighbors=send, enable_topo_check=True)
    for s in y.addressable_shards:
        r = s.index[0].start or 0
        np.testing.assert_allclose(
            np.asarray(s.data)[0], 0.5 * x_np[r] + 0.5 * x_np[(r - 1) % N],
            atol=1e-6)
    print(f"PHASE_D_AGREED {pid}", flush=True)
    bf.barrier()
    # 4-way divergence: each controller picks a DIFFERENT shift
    shift = pid + 2
    bad_send = {r: [(r + shift) % N] for r in range(N)}
    bad_nw = {r: {(r - shift) % N: 0.5} for r in range(N)}
    try:
        bf.neighbor_allreduce(xg, self_weight=swt, neighbor_weights=bad_nw,
                              send_neighbors=bad_send, enable_topo_check=True)
        raise AssertionError("4-way divergent edge sets were not detected")
    except RuntimeError as e:
        assert "DIFFERENT dynamic edge sets" in str(e), e
    print(f"PHASE_D_DIVERGENT_RAISED {pid}", flush=True)
    bf.barrier()

    # ---- Phase E: win_fence epoch visibility ----------------------------
    assert bf.win_create(np.zeros((N, 1), np.float32), "q.f", zero_init=True)
    if pid == 1:
        bf.win_put(x_np, "q.f")  # only ONE controller writes this epoch
    assert bf.win_fence("q.f")  # collective: everyone fences
    got = owned_rows(bf.win_update("q.f", clone=True), owned)
    for r in owned:
        # fence folded controller 1's deposits: slots from sources 2 and 3
        # (ranks owned by pid 1) carry x; others are zero. The put also
        # replaced the origin's own stored rows (post-send self scaling,
        # sw=1), so ranks 2 and 3 combine a self term on top.
        u = 1.0 / (len(in_nbrs[r]) + 1)
        self_term = float(x_np[r, 0]) if r in (2, 3) else 0.0
        want = u * (self_term + sum(
            float(x_np[s, 0]) for s in in_nbrs[r] if s in (2, 3)))
        np.testing.assert_allclose(got[r][0], want, rtol=1e-6)
    print(f"PHASE_E_FENCE_OK {pid}", flush=True)
    bf.barrier()
    bf.win_free("q.f")

    bf.shutdown()
    print(f"CHILD_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
