"""Child program for the high-degree window harness (VERDICT r4 #5).

Launched N_CTL times by tests/test_launcher.py (8 controllers x 1 simulated
device). The quad harness covers the ring (d=2); this child stretches the
hosted window plane where the reference's window tests lived
(torch_win_ops_test.py:268-845): high-degree and RAGGED in-degrees, the
chunked deposit wire, and the server mailbox byte cap under real
cross-controller contention.

  A. expo2 window (d_max=3 at n=8): exact put -> update values;
  B. star window (center in-degree n-1, leaves 1): ragged mailbox layout,
     put + accumulate -> exact update at every rank;
  C. chunked deposits: BLUEFOG_MAX_WIN_SENT_LENGTH=64Ki with a 160 KB row
     -> every cross-controller deposit ships as 3 wire records and
     reassembles exactly;
  D. mailbox byte cap: leaves flood the center's slots without a drain
     until the server cap rejects with the targeted "mailbox full" error;
     the successfully-deposited mass is then collected exactly once.
"""

import os

import numpy as np

import jax

import bluefog_tpu as bf
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane


def owned_rows(arr, owned):
    rows = {}
    for s in arr.addressable_shards:
        rows[s.index[0].start or 0] = np.asarray(s.data)[0]
    return {r: rows[r] for r in owned}


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    n = bf.size()
    cl = control_plane.client()
    n_ctl = control_plane.world()
    per = n // n_ctl
    owned = list(range(per * pid, per * (pid + 1)))
    x_np = (np.arange(n, dtype=np.float32) + 1.0).reshape(n, 1)

    # ---- Phase A: expo2, d_max = log2-degree ----------------------------
    bf.set_topology(bf.topology_util.ExponentialTwoGraph(n))
    topo = bf.load_topology()
    in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
               for r in range(n)}
    assert bf.win_create(x_np, "d.a", zero_init=True)
    win = win_ops._get_window("d.a")
    assert win.hosted and win.layout.d_max == len(in_nbrs[0]), (
        win.layout.d_max, in_nbrs[0])
    bf.win_put(x_np, "d.a")
    bf.barrier()
    got = owned_rows(bf.win_update("d.a"), owned)
    for r in owned:
        u = 1.0 / (len(in_nbrs[r]) + 1)
        want = u * (x_np[r] + sum(x_np[s] for s in in_nbrs[r]))
        np.testing.assert_allclose(got[r], want, rtol=1e-6)
    print(f"PHASE_A_OK {pid}", flush=True)
    bf.barrier()
    bf.win_free("d.a")

    # ---- Phase B: star — ragged in-degrees (center n-1, leaves 1) -------
    bf.set_topology(bf.topology_util.StarGraph(n))
    topo = bf.load_topology()
    in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
               for r in range(n)}
    assert bf.win_create(x_np, "d.b", zero_init=True)
    win = win_ops._get_window("d.b")
    assert win.layout.d_max == n - 1, win.layout.d_max
    bf.win_put(x_np, "d.b")
    bf.win_accumulate(x_np, "d.b")  # slot value = 2*x[src]
    bf.barrier()
    got = owned_rows(bf.win_update("d.b"), owned)
    for r in owned:
        u = 1.0 / (len(in_nbrs[r]) + 1)
        want = u * (x_np[r] + sum(2.0 * x_np[s] for s in in_nbrs[r]))
        np.testing.assert_allclose(got[r], want, rtol=1e-6)
    print(f"PHASE_B_OK {pid}", flush=True)
    bf.barrier()
    bf.win_free("d.b")

    # ---- Phase C: chunked deposits over the ring ------------------------
    os.environ["BLUEFOG_MAX_WIN_SENT_LENGTH"] = str(1 << 16)
    try:
        bf.set_topology(bf.topology_util.RingGraph(n))
        topo = bf.load_topology()
        in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
                   for r in range(n)}
        elems = 40_000  # 160 KB row -> 3 chunks of <= 64 KiB
        big = np.arange(n, dtype=np.float32)[:, None] + np.linspace(
            0.0, 1.0, elems, dtype=np.float32)[None, :]
        assert bf.win_create(big, "d.c", zero_init=True)
        bf.win_put(big, "d.c")
        bf.barrier()
        got = owned_rows(bf.win_update("d.c"), owned)
        for r in owned:
            u = 1.0 / (len(in_nbrs[r]) + 1)
            want = u * (big[r] + sum(big[s] for s in in_nbrs[r]))
            np.testing.assert_allclose(got[r], want, rtol=1e-5)
        print(f"PHASE_C_OK {pid}", flush=True)
        bf.barrier()
        bf.win_free("d.c")
    finally:
        os.environ.pop("BLUEFOG_MAX_WIN_SENT_LENGTH", None)

    # ---- Phase D: mailbox byte cap under contention ---------------------
    # Parent set BLUEFOG_CP_MAILBOX_MAX_MB=1. Each leaf floods its center
    # slot with 256 KB accumulates and NO owner drain: the 4th-ish op hits
    # the server cap and raises the targeted error. Center rank = 0.
    bf.set_topology(bf.topology_util.StarGraph(n))
    elems = 65_536  # 256 KB per deposit
    flood = np.full((n, elems), 1.0, np.float32) * (
        np.arange(n, dtype=np.float32)[:, None] + 1.0)
    assert bf.win_create(flood, "d.d", zero_init=True)
    landed = 0
    hit_cap = False
    if 0 not in owned:
        for _ in range(64):
            try:
                bf.win_accumulate(flood, "d.d")
                landed += 1
            except RuntimeError as e:
                assert "mailbox full" in str(e), e
                hit_cap = True
                break
        assert hit_cap, "server byte cap never engaged"
        # landed mass from MY owned leaves: each op deposits x[src] to the
        # center for every owned src (weight 1)
        mass = sum(landed * float(flood[src, 0]) for src in owned)
        control_plane.put_float(cl, f"d.d.mass.{pid}", mass * float(elems))
        print(f"PHASE_D_CAP {pid} landed={landed}", flush=True)
    bf.barrier()
    if 0 in owned:
        got = owned_rows(bf.win_update_then_collect("d.d"), owned)
        total = float(got[0].astype(np.float64).sum()) \
            - float(flood[0].astype(np.float64).sum())
        want = sum(
            control_plane.get_float(cl, f"d.d.mass.{p}")
            for p in range(n_ctl) if p != pid)
        assert abs(total - want) / max(want, 1.0) < 1e-5, (total, want)
        print(f"PHASE_D_MASS_OK {total:.0f}", flush=True)
    bf.barrier()
    bf.win_free("d.d")

    bf.shutdown()
    print(f"CHILD_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
