"""Snapshot-publisher child for the serving chaos tests (SIGKILL bait).

Loops publishing versioned snapshots whose every element equals the
version number — so a torn read (shards from two versions stitched
together) is detectable as a value mismatch on the reader side. An
``--inter-shard-ms`` sleep stretches the publish window (shards land one
by one) to make a SIGKILL reliably land MID-publish: after every shard
write but before the ``bf.serve.ver`` fence move.

Lean bootstrap (no jax) — the publisher wire is numpy-only by contract.

    python tests/_serve_pub_child.py --host H --port P --start-ver V \
        [--shards S] [--elems N] [--inter-shard-ms MS] [--codec C] \
        [--period-ms MS] [--flight-dump PATH --flight-rank R]

Prints ``PUB <ver>`` after each committed version; runs until killed.
``--period-ms`` paces publishes (default: tight loop). ``--flight-dump``
makes SIGTERM a clean exit that first writes this process's flight ring
(request-path trace spans/flows when BLUEFOG_TRACE_SERVE=1) to PATH with
``meta.rank`` overridden to ``--flight-rank``, so a parent can merge it
with other processes' rings into one chrome trace.
"""

import argparse
import json
import os
import signal
import sys
import threading
import types

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)
for _name in ("bluefog_tpu", "bluefog_tpu.runtime", "bluefog_tpu.ops"):
    _mod = types.ModuleType(_name)
    _mod.__path__ = [os.path.join(_REPO, _name.replace(".", os.sep))]
    sys.modules[_name] = _mod

import numpy as np  # noqa: E402

from bluefog_tpu.ops import codec as codec_mod  # noqa: E402
from bluefog_tpu.runtime.native import ControlPlaneClient  # noqa: E402
from bluefog_tpu.serving.snapshot import SnapshotPublisher  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--start-ver", type=int, default=1)
    p.add_argument("--shards", type=int, default=4)
    p.add_argument("--elems", type=int, default=5000)
    p.add_argument("--inter-shard-ms", type=float, default=0.0)
    p.add_argument("--codec", default=None)
    p.add_argument("--keep", type=int, default=2)
    p.add_argument("--period-ms", type=float, default=0.0)
    p.add_argument("--flight-dump", default=None)
    p.add_argument("--flight-rank", type=int, default=1)
    args = p.parse_args()

    stop = threading.Event()
    if args.flight_dump:
        signal.signal(signal.SIGTERM, lambda *_: stop.set())

    cl = ControlPlaneClient(args.host, args.port, 0,
                            secret=os.environ.get("BLUEFOG_CP_SECRET", ""),
                            streams=1)
    codec = codec_mod.state_codec_for(
        codec_mod.resolve(args.codec)) if args.codec else None
    pub = SnapshotPublisher(cl, shards=args.shards, codec=codec,
                            keep=args.keep)
    pub._inter_shard_sleep = args.inter_shard_ms / 1e3
    ver = args.start_ver
    while not stop.is_set():
        leaves = [np.full(args.elems, float(ver), np.float32),
                  np.full(args.elems // 3 + 1, float(ver), np.float32)]
        pub.publish(leaves, ver, step=ver)
        print(f"PUB {ver}", flush=True)
        ver += 1
        if args.period_ms > 0:
            stop.wait(args.period_ms / 1e3)
    if args.flight_dump:
        from bluefog_tpu.runtime import flight
        doc = flight.build_dump("pub-exit")
        doc["meta"]["rank"] = args.flight_rank
        with open(args.flight_dump, "w") as f:
            json.dump(doc, f)
    return 0


if __name__ == "__main__":
    sys.exit(main())
