"""Benchmark-harness smoke on the 8-device CPU mesh.

Runs examples/benchmark.py end to end (mlp model, tiny batch) through the
``bfrun --simulate`` launch path, so collective-overhead regressions in the
fused optimizer step show up in CI rather than only on hardware. The analog
of running the reference's examples/pytorch_benchmark.py under mpirun.
"""

import json
import os
import re
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _scrubbed_env():
    env = os.environ.copy()
    # BLUEFOG_CP_FAULT: a fault spec leaked from the operator's shell must
    # never poison a benchmark run — throughput under injected connection
    # drops is not a benchmark (asserted below)
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "BLUEFOG_CP_FAULT"):
        env.pop(k, None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    # CI smoke runs on the simulated CPU mesh; don't let children probe a
    # possibly-down accelerator tunnel (multi-minute timeout per process)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def test_fault_injection_disarmed_in_benchmark_env(monkeypatch):
    """Fault injection stays OFF in benchmark runs by default: the bench
    harness env scrubs any inherited BLUEFOG_CP_FAULT spec, and the native
    injector in THIS process is disarmed unless a test armed it."""
    monkeypatch.setenv("BLUEFOG_CP_FAULT", "drop_after=5,seed=1")
    env = _scrubbed_env()
    assert "BLUEFOG_CP_FAULT" not in env
    from bluefog_tpu.runtime import native

    if native.load() is not None:
        native.fault_disarm()
        assert native.fault_stats() == {"ops": 0, "drops": 0}


@pytest.mark.slow
@pytest.mark.parametrize("dist_opt", ["neighbor_allreduce", "win_put"])
def test_benchmark_mlp_smoke(dist_opt):
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--simulate", "8", "--",
         sys.executable, str(REPO / "examples" / "benchmark.py"),
         "--model", "mlp", "--batch-size", "8",
         "--num-warmup-batches", "1", "--num-batches-per-iter", "2",
         "--num-iters", "2", "--dist-optimizer", dist_opt],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # the harness prints "Total img/sec on N chip(s): <mean> +-<ci>" like
    # the reference (:118-124); a parseable positive number means a full run
    m = re.search(r"Total img/sec on \d+ chip\(s\):\s*([0-9.]+)", out.stdout)
    assert m, f"no throughput line in:\n{out.stdout}"
    assert float(m.group(1)) > 0


@pytest.mark.slow
def test_win_microbench_quick():
    """scripts/win_microbench.py --quick: the 4-controller hosted-plane
    drain/get pipeline (put, accumulate, pipelined update drain, win_get,
    fold-vs-stream probe) runs end to end at tiny sizes — the new drain
    paths are CI-exercised, not hand-run only. The r7 raw-ceiling probe
    rows (raw put/get at the full striped pool AND pinned to one stream)
    must be present with positive throughput, so a striped-transport
    regression surfaces in-tree rather than only in manual PERF.md runs."""
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "win_microbench.py"),
         "--quick"],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "WIN_MICROBENCH_OK" in out.stdout, out.stdout + out.stderr
    rows = [json.loads(l) for l in out.stdout.splitlines()
            if l.startswith("{")]
    ops = {r["op"] for r in rows}
    assert {"win_put", "win_update", "win_get", "drain_stream",
            "drain_fold", "raw_put_bytes", "raw_get_bytes",
            "raw_put_bytes_1s", "raw_get_bytes_1s"} <= ops, out.stdout
    for r in rows:
        if r["op"].startswith("raw_"):
            assert r["mbps"] and r["mbps"] > 0, r


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["win_put", "sharded_allreduce"])
def test_opt_matrix_bench_quick(mode):
    """scripts/opt_matrix_bench.py --quick on the two modes the r6
    acceptance compares: a parseable throughput JSON line per mode."""
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "opt_matrix_bench.py"),
         "--quick", "--modes", mode],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    res = json.loads(out.stdout.splitlines()[-1])
    assert res["mode"] == mode and res.get("img_per_sec", 0) > 0, res
