"""Native host-runtime extension: timeline writer + control plane.

The control-plane tests exercise the distributed mutex / fetch-and-op /
barrier semantics the reference implements with MPI RMA windows
(mpi_controller.cc:1532-1602, version windows :1281-1393) — here over the
TCP control plane with multiple client threads standing in for controller
processes.
"""

import json
import threading
import time

import pytest

from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.timeline import Timeline

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable (no g++?)")


def test_native_timeline_roundtrip(tmp_path):
    prefix = str(tmp_path / "tl")
    tl = Timeline(prefix, process_index=0)
    assert tl._native is not None, "native writer should be active"
    with tl.activity("tensor.a", "NEIGHBOR_ALLREDUCE"):
        tl.instant("tensor.a", "ENQUEUE")
    tl.activity_start("tensor.b", "WIN_PUT", tid=3)
    tl.activity_end("tensor.b", tid=3)
    tl.close()
    events = json.load(open(prefix + "0.json"))
    names = [e.get("name") for e in events]
    assert "NEIGHBOR_ALLREDUCE" in names
    assert "ENQUEUE" in names
    assert "WIN_PUT" in names
    phases = [e["ph"] for e in events]
    assert phases.count("B") == 2 and phases.count("E") == 2
    b = next(e for e in events if e.get("name") == "WIN_PUT")
    assert b["tid"] == 3 and b["cat"] == "tensor.b"


def test_control_plane_fetch_add_and_kv():
    with native.ControlPlaneServer(world=2) as srv:
        with native.ControlPlaneClient("127.0.0.1", srv.port, rank=0) as c:
            assert c.fetch_add("ver.x", 1) == 0
            assert c.fetch_add("ver.x", 5) == 1
            assert c.get("ver.x") == 6
            c.put("p.3", 42)
            assert c.get("p.3") == 42
            assert c.get("missing") == 0


def test_control_plane_barrier_and_mutex():
    with native.ControlPlaneServer(world=3) as srv:
        clients = [
            native.ControlPlaneClient("127.0.0.1", srv.port, rank=r)
            for r in range(3)
        ]
        order = []
        times = {}

        def worker(r):
            clients[r].barrier("start")
            times[r] = time.monotonic()
            clients[r].lock("m")
            order.append(r)
            time.sleep(0.02)
            clients[r].unlock("m")

        threads = [threading.Thread(target=worker, args=(r,)) for r in range(3)]
        threads[0].start()
        time.sleep(0.1)  # barrier must hold rank 0 until all arrive
        assert 0 not in times
        for t in threads[1:]:
            t.start()
        for t in threads:
            t.join(timeout=10)
        assert sorted(order) == [0, 1, 2]  # mutex serialized all three
        spread = max(times.values()) - min(times.values())
        assert spread < 0.5, "barrier released ranks together"
        for c in clients:
            c.close()


def test_mutex_blocks_second_holder():
    with native.ControlPlaneServer(world=2) as srv:
        c0 = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        c1 = native.ControlPlaneClient("127.0.0.1", srv.port, rank=1)
        c0.lock("w")
        acquired = []

        def try_lock():
            c1.lock("w")
            acquired.append(time.monotonic())
            c1.unlock("w")

        t = threading.Thread(target=try_lock)
        t0 = time.monotonic()
        t.start()
        time.sleep(0.15)
        assert not acquired, "rank 1 must block while rank 0 holds the lock"
        c0.unlock("w")
        t.join(timeout=10)
        assert acquired and acquired[0] - t0 >= 0.1
        c0.close()
        c1.close()


def test_bulk_bytes_roundtrip_and_bounded_take():
    """Bytes transport: append/take record framing, put/get slots, and the
    bounded take reply (a >64 MiB backlog drains over multiple takes with
    deposit order preserved)."""
    with native.ControlPlaneServer(world=1, port=0) as srv:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        cl.append_bytes("box", b"a")
        cl.append_bytes("box", b"bb" * 500)
        assert cl.take_bytes("box") == [b"a", b"bb" * 500]
        assert cl.take_bytes("box") == []

        cl.put_bytes("slot", b"\x07" * 4096)
        assert cl.get_bytes("slot") == b"\x07" * 4096
        assert cl.get_bytes("never") == b""

        # 3 x 30 MiB > the 64 MiB per-reply cap: the first take returns a
        # bounded prefix, later takes the rest, order intact
        big = [bytes([i]) * (30 << 20) for i in range(3)]
        for b in big:
            cl.append_bytes("deep", b)
        drained = []
        takes = 0
        while True:
            recs = cl.take_bytes("deep")
            if not recs:
                break
            takes += 1
            drained.extend(recs)
        assert takes >= 2, "oversized backlog must need multiple takes"
        assert [r[:1] for r in drained] == [b"\x00", b"\x01", b"\x02"]
        assert [len(r) for r in drained] == [30 << 20] * 3

        # batched pipelined ops
        cl.put_many(["k.0", "k.1", "k.2"], [10, 11, 12])
        assert cl.get_many(["k.2", "k.0", "k.1"]) == [12, 10, 11]

        # oversized payloads are rejected client-side, connection intact
        import pytest as _pt
        with _pt.raises(ValueError):
            cl.append_bytes("box", b"\x00" * (1 << 30))
        assert cl.get("k.0") == 10  # connection still healthy
        cl.close()


def test_append_bytes_tagged_prefixes_records():
    """kAppendBytesTagged: each record's int64 tag is prefixed to the
    stored record server-side, and untagged appends interleave on the same
    key untouched (the window drain's orphan-discard wire contract)."""
    with native.ControlPlaneServer(world=1, port=0) as srv:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        tags = [(5 << 24) | 0, (5 << 24) | 1]
        cl.append_bytes_tagged_many(["tg", "tg"], [b"head", b"cont"], tags)
        recs = cl.take_bytes("tg")
        assert [int.from_bytes(r[:8], "little") for r in recs] == tags
        assert [r[8:] for r in recs] == [b"head", b"cont"]
        cl.close()


def test_take_bytes_many_views_zero_copy_drain():
    """take_bytes_many_views: record memoryviews alias ONE native reply
    buffer; contents match the copying take_bytes_many exactly."""
    with native.ControlPlaneServer(world=1, port=0) as srv:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        cl.append_bytes("v.0", b"aa")
        cl.append_bytes("v.0", b"b" * 4096)
        cl.append_bytes("v.2", b"ccc")
        batches, owner = cl.take_bytes_many_views(["v.0", "v.1", "v.2"])
        try:
            assert [bytes(r) for r in batches[0]] == [b"aa", b"b" * 4096]
            assert batches[1] == []
            assert [bytes(r) for r in batches[2]] == [b"ccc"]
            assert all(isinstance(r, memoryview)
                       for recs in batches for r in recs)
        finally:
            owner.close()
        # close() invalidates the owner view (backstop against dangling use)
        assert len(owner.view) == 0
        cl.close()


def test_bounded_inflight_multi_out_no_deadlock():
    """Regression (ADVICE r5): a bytes batch with tens of thousands of
    records deadlocked — the server's 12-byte replies filled both socket
    buffers while the client was still blocked writing payload, parking
    each side in a write the other would never drain. CallBytesMultiOutV
    now bounds unread replies at 128 in flight; this record count (50k)
    reproduced the hang before the fix."""
    n = 50_000
    with native.ControlPlaneServer(world=1, port=0) as srv:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        names = [f"dl.{i % 7}" for i in range(n)]
        blobs = [b"x" * 16] * n
        done = []
        t = threading.Thread(
            target=lambda: done.append(cl.append_bytes_many(names, blobs)),
            daemon=True)
        t.start()
        t.join(timeout=120)
        assert done, "bytes batch deadlocked (unbounded in-flight replies)"
        assert len(done[0]) == n and all(r >= 1 for r in done[0])
        total = 0
        for k in range(7):
            while True:
                recs = cl.take_bytes(f"dl.{k}")
                if not recs:
                    break
                total += len(recs)
        assert total == n
        cl.close()
