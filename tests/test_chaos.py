"""Deterministic chaos: the control plane under injected faults (ISSUE r8).

The fault layer (``BLUEFOG_CP_FAULT`` / ``native.fault_arm``) makes
connection drops, truncated requests, lost replies, and slow peers
reproducible in-process, so every fault-tolerance behavior is a plain unit
test:

  * reconnecting transport — striped put/get round-trips and multi-round
    deposit/drain cycles are BIT-IDENTICAL to the fault-free run while
    connections are being killed under them (the acceptance criterion);
  * exactly-once non-idempotent ops — fetch_add under drops never
    double-applies (server-side per-client op-sequence dedup);
  * leased blocking primitives — dead lock holders, lease expiry, and
    barrier deadlines wake waiters with a typed ``PeerLostError`` instead
    of hanging (no wait path is unbounded);
  * the fault layer itself is OFF by default, so benches are unaffected.

The 4-process SIGKILL-mid-gossip end-to-end lives in
``test_kill_peer_mid_gossip_self_heals`` (slow-marked), reusing the
``tests/_fault_child.py`` launcher machinery via ``_gossip_fault_child.py``.
"""

import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import heartbeat, native

TESTS = Path(__file__).resolve().parent

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _fault_disarmed():
    """Every test starts AND ends with injection off (process-global state)."""
    native.fault_disarm()
    yield
    native.fault_disarm()


@pytest.fixture()
def server():
    srv = native.ControlPlaneServer(2, _free_port())
    yield srv
    native.fault_disarm()  # never let a slow-delay knob wedge teardown
    srv.stop()


# ---------------------------------------------------------------------------
# the fault layer itself
# ---------------------------------------------------------------------------

def test_fault_layer_off_by_default(server):
    """Benches must be unaffected: without BLUEFOG_CP_FAULT (or an explicit
    arm), no op is ever counted, dropped, or delayed."""
    assert "BLUEFOG_CP_FAULT" not in os.environ, \
        "test env leaked a fault spec"
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    for i in range(20):
        cl.put(f"off.{i}", i)
    assert cl.get("off.7") == 7
    assert native.fault_stats() == {"ops": 0, "drops": 0}
    cl.close()


_SPEC_DEFAULTS = {"drop_after": 0, "delay_ms": 0, "trunc": 0, "seed": 0,
                  "delay_edges": {}, "partition": None, "part_after": 0.0,
                  "heal_after": 0.0}


def _spec(**over):
    return {**_SPEC_DEFAULTS, **over}


def test_parse_fault_spec_grammar():
    assert native.parse_fault_spec("drop_after=37,delay_ms=50,trunc=1,seed=7") \
        == _spec(drop_after=37, delay_ms=50, trunc=1, seed=7)
    assert native.parse_fault_spec("drop_after=5") == _spec(drop_after=5)
    assert native.parse_fault_spec("")["drop_after"] == 0
    with pytest.raises(ValueError):
        native.parse_fault_spec("drop_every=5")
    with pytest.raises(ValueError):
        native.parse_fault_spec("drop_after")


def test_parse_fault_spec_partition():
    """ISSUE r20: the partition clause — `|` sides with bare comma
    continuation, part_after/heal_after floats, composition with the
    scalar knobs — and the malformed-spec red paths."""
    cfg = native.parse_fault_spec("partition=0,1|2,3")
    assert cfg["partition"] == [[0, 1], [2, 3]]
    assert cfg["part_after"] == 0.0 and cfg["heal_after"] == 0.0
    cfg = native.parse_fault_spec(
        "partition=0,1,2|3,heal_after=2.5,part_after=1")
    assert cfg["partition"] == [[0, 1, 2], [3]]
    assert cfg["part_after"] == 1.0 and cfg["heal_after"] == 2.5
    # composes with the scalar knobs in either order
    cfg = native.parse_fault_spec("drop_after=4,partition=0|1,seed=9")
    assert cfg["drop_after"] == 4 and cfg["seed"] == 9
    assert cfg["partition"] == [[0], [1]]
    for bad in ("partition=0,1", "partition=0,1|1,2", "partition=a|b",
                "heal_after=2.5"):  # heal without a partition spec is fine
        if bad == "heal_after=2.5":
            assert native.parse_fault_spec(bad)["heal_after"] == 2.5
            continue
        with pytest.raises(ValueError):
            native.parse_fault_spec(bad)


def test_parse_fault_spec_delay_edges():
    """ISSUE r16: the per-edge asymmetric-delay clause — `;`/`|`
    separators, comma continuation after the clause, composition with
    the scalar knobs — and the malformed-term red path."""
    assert native.parse_fault_spec("delay_edges=0>1:80") == \
        _spec(delay_edges={(0, 1): 80})
    # multi-edge: `;` and `|` separators, plus bare comma continuation
    assert native.parse_fault_spec("delay_edges=0>1:80;2>3:40") \
        ["delay_edges"] == {(0, 1): 80, (2, 3): 40}
    assert native.parse_fault_spec("delay_edges=0>1:80|2>3:40") \
        ["delay_edges"] == {(0, 1): 80, (2, 3): 40}
    assert native.parse_fault_spec("delay_edges=0>1:80,2>3:40") \
        ["delay_edges"] == {(0, 1): 80, (2, 3): 40}
    # composes with the scalar knobs in either order
    cfg = native.parse_fault_spec("drop_after=9,delay_edges=1>0:25,seed=3")
    assert cfg["drop_after"] == 9 and cfg["seed"] == 3
    assert cfg["delay_edges"] == {(1, 0): 25}
    for bad in ("delay_edges=0-1:80", "delay_edges=0>1", "delay_edges=x>y:5"):
        with pytest.raises(ValueError):
            native.parse_fault_spec(bad)


def test_edge_delays_accessor_off_and_armed(monkeypatch):
    """edge_delays() is the deposit site's view: empty unless armed, in
    sync with fault_arm/fault_disarm, env-lazy for library-less use."""
    native.fault_disarm()
    assert native.edge_delays() == {}
    native.fault_arm("delay_edges=0>1:15,drop_after=0")
    assert native.edge_delays() == {(0, 1): 15}
    native.fault_disarm()
    assert native.edge_delays() == {}
    # env-lazy path (no explicit arm): honored after a cache reset
    monkeypatch.setenv("BLUEFOG_CP_FAULT", "delay_edges=2>0:5")
    native._edge_delays = None
    assert native.edge_delays() == {(2, 0): 5}
    monkeypatch.delenv("BLUEFOG_CP_FAULT")
    native._edge_delays = None
    assert native.edge_delays() == {}


def test_asymmetric_edge_delay_at_deposit_site(monkeypatch):
    """ISSUE r16 asymmetric-delay case: with ``delay_edges`` armed, the
    hosted deposit batch partitions by per-edge delay — undelayed edges
    ship immediately, the slow edge's records land only after its
    injected delay, and every reply maps back to its original record
    slot. This is the deterministic bandwidth-asymmetry fixture the
    self-tuner's slow-edge detector trains against."""
    from bluefog_tpu.ops import windows as win_mod

    sent = []  # (elapsed_ms, names, tags) per wire batch

    class _Client:
        def append_bytes_tagged_many(self, names, blobs, tags):
            sent.append((1e3 * (time.perf_counter() - t0),
                         list(names), list(tags)))
            return [100 + int(t) for t in tags]

    monkeypatch.setattr(win_mod._cp, "client", lambda: _Client())
    names = [f"dep.{i}" for i in range(4)]
    blobs = [b"x"] * 4
    tags = list(range(4))
    edge_of = [(0, 1), (2, 3), (0, 1), (3, 0)]  # 0->1 is the slow edge
    t0 = time.perf_counter()
    replies = win_mod._send_deposits_delayed(
        names, blobs, tags, edge_of, {(0, 1): 60})
    # replies land in ORIGINAL record order despite the regrouped send
    assert replies == [100, 101, 102, 103]
    assert len(sent) == 2
    fast, slow = sent
    assert fast[1] == ["dep.1", "dep.3"] and fast[0] < 45.0
    assert slow[1] == ["dep.0", "dep.2"] and slow[0] >= 55.0


# ---------------------------------------------------------------------------
# reconnecting transport: exactly-once + bit-identical under drops
# ---------------------------------------------------------------------------

def test_fetch_add_exactly_once_under_drops(server):
    """Non-idempotent ops must never double-apply across retries: a reply
    lost in flight is replayed from the server's per-client dedup table."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    native.fault_arm(f"drop_after=4,seed={_seed(1)}")
    seen = [cl.fetch_add("ctr", 1) for _ in range(40)]
    drops = native.fault_stats()["drops"]
    native.fault_disarm()
    assert drops >= 3, f"only {drops} drops injected"
    # pre-add values are exactly 0..39: no add lost, none applied twice
    assert seen == list(range(40))
    assert cl.get("ctr") == 40
    cl.close()


def test_batched_fetch_add_exactly_once_under_drops(server):
    """The pipelined batch path (fetch_add_many — the hosted version-bump
    hot path) resends whole batches under one seq; the server replays the
    applied prefix."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    native.fault_arm(f"drop_after=3,seed={_seed(0)},trunc=1")
    total = 0
    for _ in range(12):
        pre = cl.fetch_add_many(["a", "b", "c"], deltas=[1, 2, 3])
        assert pre == [total, 2 * total, 3 * total], (pre, total)
        total += 1
    drops = native.fault_stats()["drops"]
    native.fault_disarm()
    assert drops >= 3
    assert cl.get_many(["a", "b", "c"]) == [12, 24, 36]
    cl.close()


def test_fault_metrics_match_injected_drops(server):
    """Telemetry closes the "did the fault actually fire" blind spot:
    every injected connection drop must show up as a client redial, the
    reply-lost half as server-side dedup replays, and the registry
    snapshot must surface the injector's own counts."""
    from bluefog_tpu.runtime import metrics as metrics_mod

    base = native.client_stats()
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    native.fault_arm(f"drop_after=4,seed={_seed(13)}")
    for _ in range(40):
        cl.fetch_add("fm.ctr", 1)
    drops = native.fault_stats()["drops"]
    snap = metrics_mod.snapshot()
    native.fault_disarm()
    assert drops >= 5, f"only {drops} drops injected"
    assert cl.get("fm.ctr") == 40  # exactly-once held while we counted

    after = native.client_stats()
    redials = after["redials"] - base["redials"]
    # every drop kills the connection -> the op's retry must redial
    assert redials >= drops, (redials, drops)
    # the reply-lost half of the drops was answered from the dedup table
    assert server.stats()["dedup_replays"] >= 1
    # and the registry snapshot carries the injector's own counters, so a
    # chaos run's scrape proves the faults fired
    assert snap["counters"]["cp.fault.drops"] == drops
    assert snap["counters"]["cp.fault.ops"] > 0
    assert "cp.client.redials" in snap["counters"]
    cl.close()


def _striped_roundtrip(port: int, streams: int, rounds: int = 10):
    """put_bytes/get_bytes cycle of striping-sized payloads; returns the
    bytes read back each round (for cross-run comparison)."""
    cl = native.ControlPlaneClient("127.0.0.1", port, 0, streams=streams)
    rng = np.random.default_rng(7)
    out = []
    for r in range(rounds):
        payload = rng.integers(0, 256, size=768 * 1024, dtype=np.uint8)
        cl.put_bytes(f"blob.{r % 2}", payload.tobytes())
        out.append(cl.get_bytes(f"blob.{r % 2}"))
    cl.close()
    return out


@pytest.mark.parametrize("streams", [4, 1])
def test_striped_roundtrip_bit_identical_under_drops(streams):
    """Acceptance: >= 3 connection drops across a multi-round striped
    put/get cycle, results bit-identical to the fault-free run. At
    streams=4 the payloads (above BLUEFOG_CP_STRIPE_MIN_MB=0.5 here) move
    as concurrent byte-range stripes over the pool; each pool connection
    reconnects and retries independently."""
    os.environ["BLUEFOG_CP_STRIPE_MIN_MB"] = "0.5"
    try:
        srv = native.ControlPlaneServer(2, _free_port())
        try:
            baseline = _striped_roundtrip(srv.port, streams)
            native.fault_arm(f"drop_after=3,seed={_seed(2)},trunc=1")
            faulted = _striped_roundtrip(srv.port, streams)
            drops = native.fault_stats()["drops"]
            native.fault_disarm()
        finally:
            srv.stop()
        assert drops >= 3, f"only {drops} drops injected"
        assert len(baseline) == len(faulted)
        for b, f in zip(baseline, faulted):
            assert b == f, "striped round-trip diverged under faults"
    finally:
        del os.environ["BLUEFOG_CP_STRIPE_MIN_MB"]


def _deposit_drain_cycle(port: int, streams: int, rounds: int = 6):
    """Multi-round tagged deposit + drain over 3 mailbox keys; returns
    (per-round drained record lists, total bytes in, total bytes out)."""
    cl = native.ControlPlaneClient("127.0.0.1", port, 0, streams=streams)
    rng = np.random.default_rng(13)
    transcript, bytes_in, bytes_out = [], 0, 0
    seq = 0
    for r in range(rounds):
        names, blobs, tags = [], [], []
        for k in range(3):
            for rec in range(4):
                seq += 1
                body = rng.integers(0, 256, size=int(rng.integers(64, 2048)),
                                    dtype=np.uint8).tobytes()
                names.append(f"box.{k}")
                blobs.append(body)
                tags.append(seq << 24)  # header-index tags, single-record
                bytes_in += len(body)
        counts = cl.append_bytes_tagged_many(names, blobs, tags)
        assert all(c >= 1 for c in counts)
        drained = cl.take_bytes_many([f"box.{k}" for k in range(3)])
        # strip the server's 8-byte tag prefix; keep per-key record order
        recs = [[bytes(x)[8:] for x in lst] for lst in drained]
        bytes_out += sum(len(x) for lst in recs for x in lst)
        transcript.append(recs)
    cl.close()
    return transcript, bytes_in, bytes_out


@pytest.mark.parametrize("streams", [4, 1])
def test_deposit_drain_mass_conserved_under_drops(streams):
    """Acceptance: the deposit/drain cycle — the hosted window plane's wire
    discipline — conserves mass exactly under >= 3 injected drops, and the
    drained transcript is bit-identical to the fault-free run (lost take
    replies are replayed from the dedup record, never re-drained or lost)."""
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        base, base_in, base_out = _deposit_drain_cycle(srv.port, streams)
        assert base_in == base_out  # sanity: fault-free mass conservation
    finally:
        srv.stop()
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        native.fault_arm(f"drop_after=5,seed={_seed(3)}")
        got, got_in, got_out = _deposit_drain_cycle(srv.port, streams)
        drops = native.fault_stats()["drops"]
        native.fault_disarm()
    finally:
        srv.stop()
    assert drops >= 3, f"only {drops} drops injected"
    assert got_in == got_out == base_in, "deposit mass not conserved"
    assert got == base, "drained transcript diverged under faults"


def test_server_drop_conns_hook_reconnects(server):
    """The server-side kill hook severs every live connection; clients
    reconnect (re-handshaking) transparently on their next op."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    cl.put("pre.kill", 1)
    server.drop_connections()
    time.sleep(0.05)
    cl.put("post.kill", 2)  # transparent reconnect
    assert cl.get("pre.kill") == 1 and cl.get("post.kill") == 2
    cl.close()


def test_retries_zero_disables_reconnect(server, monkeypatch):
    """BLUEFOG_CP_RETRIES=0 is the strict legacy wire: a severed connection
    is a hard OSError, exactly the pre-r8 behavior."""
    monkeypatch.setenv("BLUEFOG_CP_RETRIES", "0")
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    cl.put("x", 1)
    server.drop_connections()
    time.sleep(0.05)
    with pytest.raises(OSError):
        cl.put("x", 2)
    cl.close()


# ---------------------------------------------------------------------------
# leased blocking primitives: no wait path is unbounded
# ---------------------------------------------------------------------------

def test_lock_dead_holder_wakes_waiter_typed(server):
    """A lock whose holder's connection closes is force-released with an
    epoch bump; the blocked waiter wakes with PeerLostError (not a silent
    grant, not a hang) and a fresh acquire then succeeds."""
    holder = native.ControlPlaneClient("127.0.0.1", server.port, 1, streams=1)
    waiter = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    holder.lock("L")
    result = {}

    def wait_for_lock():
        try:
            waiter.lock("L")
            result["outcome"] = "granted"
        except native.PeerLostError as exc:
            result["outcome"] = "peerlost"
            result["msg"] = str(exc)

    t = threading.Thread(target=wait_for_lock, daemon=True)
    t.start()
    time.sleep(0.4)
    assert "outcome" not in result, "waiter got the lock through a holder"
    holder.close()  # connection closes while holding -> force release
    t.join(10.0)
    assert result.get("outcome") == "peerlost", result
    assert "force-released" in result["msg"]
    waiter.lock("L")  # the lock was left free: re-acquire works
    waiter.unlock("L")
    waiter.close()


def test_lock_lease_expiry_and_broken_unlock(monkeypatch):
    """The lease is the backstop for a wedged-but-connected holder: a
    waiter force-releases the lock at expiry (PeerLostError), and the
    original holder's eventual unlock reports the broken section instead
    of silently succeeding."""
    monkeypatch.setenv("BLUEFOG_CP_LOCK_LEASE", "0.4")
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        holder = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                           streams=1)
        waiter = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                           streams=1)
        holder.lock("M")
        t0 = time.monotonic()
        with pytest.raises(native.PeerLostError, match="force-released"):
            waiter.lock("M")
        assert time.monotonic() - t0 < 5.0  # bounded by the lease, not ∞
        waiter.lock("M")  # free after the force-release
        waiter.unlock("M")
        # the wedged holder finally releases: its section was broken
        with pytest.raises(native.PeerLostError, match="critical section"):
            holder.unlock("M")
        holder.close()
        waiter.close()
    finally:
        srv.stop()


def test_barrier_deadline_is_bounded(monkeypatch):
    """A barrier with an absent participant wakes at
    BLUEFOG_CP_BARRIER_TIMEOUT with PeerLostError instead of hanging."""
    monkeypatch.setenv("BLUEFOG_CP_BARRIER_TIMEOUT", "0.5")
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0, streams=1)
        t0 = time.monotonic()
        with pytest.raises(native.PeerLostError, match="never arrived"):
            cl.barrier("lonely")
        assert time.monotonic() - t0 < 5.0
        # the timed-out arrival was withdrawn: a later full barrier works
        other = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                          streams=1)
        done = []
        t = threading.Thread(target=lambda: done.append(cl.barrier("b2")),
                             daemon=True)
        t.start()
        other.barrier("b2")
        t.join(5.0)
        assert done, "paired barrier did not complete"
        cl.close()
        other.close()
    finally:
        srv.stop()


def test_barrier_survives_drop_and_retry(server):
    """A barrier participant whose connection drops mid-wait withdraws its
    arrival server-side; the transparent retry re-enters, and the barrier
    still completes exactly once for both parties."""
    a = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    b = native.ControlPlaneClient("127.0.0.1", server.port, 1, streams=1)
    results = {}

    def enter(name, cl):
        results[name] = cl.barrier("chaos.bar")

    ta = threading.Thread(target=enter, args=("a", a), daemon=True)
    ta.start()
    time.sleep(0.3)  # a is parked in the barrier wait
    server.drop_connections()  # severs a's (and b's idle) connection
    tb = threading.Thread(target=enter, args=("b", b), daemon=True)
    tb.start()
    ta.join(15.0)
    tb.join(15.0)
    assert results.get("a") == results.get("b") == 1, results
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# heartbeat stop() under an unresponsive control plane (satellite)
# ---------------------------------------------------------------------------

def test_heartbeat_stop_wedged_thread_no_double_close(server, monkeypatch):
    """The wedged-thread path in PeerMonitor.stop() ('leaving its
    connection open'): with the fault delay knob making every control-plane
    op multi-second, stop() must return at its 2 s join bound, must NOT
    close the native client under the live thread (use-after-free), and a
    second stop() is a no-op. After the delay clears the thread exits on
    its own."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    monkeypatch.setattr(cp, "_client", cl)
    monkeypatch.setattr(cp, "_conn_params",
                        ("127.0.0.1", server.port, 0, ""))
    mon = heartbeat.PeerMonitor(0, 2, interval_sec=0.05, timeout_sec=30.0)
    mon.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not native.fault_stats()["ops"]:
        time.sleep(0.02)  # monitor thread is live and ticking
    native.fault_arm("delay_ms=1500")
    time.sleep(0.2)  # let the next tick park inside a delayed op
    thread = mon._thread
    assert thread is not None and thread.is_alive()
    t0 = time.monotonic()
    mon.stop()
    dt = time.monotonic() - t0
    assert dt < 10.0, f"stop() hung {dt:.1f}s on a wedged control plane"
    # wedged path: the dedicated connection is NOT closed under the thread
    assert mon._cl is None
    assert thread.is_alive(), "expected the tick to still be wedged"
    mon.stop()  # idempotent: no double-close of a shared native handle
    native.fault_disarm()
    thread.join(15.0)
    assert not thread.is_alive(), "wedged tick never drained after disarm"
    # the leaked-by-design connection is reclaimed at process exit only;
    # the SHARED client must still be usable (nothing closed it)
    assert cl.get("anything") == 0
    cl.close()


# ---------------------------------------------------------------------------
# attach() must not silently degrade a multi-process job (satellite)
# ---------------------------------------------------------------------------

def test_attach_raises_when_multiprocess_connect_fails(monkeypatch):
    dead_port = _free_port()  # nothing listens here
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(dead_port),
        "BLUEFOG_CP_WORLD": "2",
        "BLUEFOG_CP_RANK": "1",   # not the serving rank
        "BLUEFOG_CP_CONNECT_TIMEOUT": "0.5",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    try:
        with pytest.raises(RuntimeError, match="refusing to degrade"):
            cp.attach()
    finally:
        cp.reset_for_test()


def test_attach_soft_fallback_for_single_controller(monkeypatch):
    """world == 1 keeps the soft local fallback: a forced-env dev run
    without a reachable server degrades with a warning, not an error."""
    dead_port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(dead_port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_CP_SERVE": "0",
        "BLUEFOG_CP_CONNECT_TIMEOUT": "0.5",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    try:
        assert cp.attach() is None
        assert not cp.active()
    finally:
        cp.reset_for_test()


# ---------------------------------------------------------------------------
# hosted windows: mass conservation under drops (fast, in-process)
# ---------------------------------------------------------------------------

@pytest.fixture()
def bf_hosted_cp(monkeypatch):
    """bf over 8 CPU devices, forced control plane + hosted window plane."""
    import bluefog_tpu as bf
    from conftest import cpu_devices

    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active()
    yield bf
    native.fault_disarm()
    bf.shutdown()
    cp.reset_for_test()


def test_hosted_pushsum_mass_conserved_under_drops(bf_hosted_cp):
    """End-to-end through the window API: a push-sum accumulate/update
    cycle on the hosted plane keeps total mass and p mass EXACTLY
    conserved while the transport is dropping connections under it."""
    import jax.numpy as jnp

    bf = bf_hosted_cp
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = jnp.arange(8.0).reshape(8, 1) + 1.0
        assert bf.win_create(x, "chaos.ps", zero_init=True)
        topo = bf.load_topology()
        outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
                for r in range(8)}
        sw = {r: 1.0 / (outd[r] + 1) for r in range(8)}
        dw = {r: {d: 1.0 / (outd[r] + 1)
                  for d in bf.topology_util.out_neighbor_ranks(topo, r)}
              for r in range(8)}
        native.fault_arm(f"drop_after=15,seed={_seed(5)}")
        val = x
        for _ in range(4):
            bf.win_accumulate(val, "chaos.ps", self_weight=sw,
                              dst_weights=dw, require_mutex=True)
            val = bf.win_update_then_collect("chaos.ps")
            p = bf.win_associated_p_all("chaos.ps")
            assert abs(float(np.asarray(val).sum()) - 36.0) < 1e-3
            assert abs(p.sum() - 8.0) < 1e-9
        drops = native.fault_stats()["drops"]
        native.fault_disarm()
        assert drops >= 3, f"only {drops} drops injected"
        bf.win_free("chaos.ps")
    finally:
        bf.turn_off_win_ops_with_associated_p()


# ---------------------------------------------------------------------------
# self-healing gossip: dead ranks excluded, weights renormalized, retry-once
# ---------------------------------------------------------------------------

def test_gossip_weights_renormalize_around_dead_ranks(bf_hosted_cp,
                                                      monkeypatch):
    """The window optimizer consults the dead set EVERY gossip step: with
    ranks {6, 7} reported dead, sends to them stop, the combine weights
    renormalize to 1/(live_indegree + 1), and the mixed parameters match a
    numpy oracle of the shrunken-graph average exactly."""
    import jax.numpy as jnp
    import optax

    bf = bf_hosted_cp
    from bluefog_tpu.runtime import heartbeat as hb

    dead = {6, 7}
    monkeypatch.setattr(hb, "dead_ranks", lambda: set(dead))

    def loss_fn(params, batch):
        return jnp.sum((params["w"] - batch) ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf.shard_rank_stacked(
        bf.mesh(), np.arange(8, dtype=np.float32).reshape(8, 1))
    try:
        topo = bf.load_topology()
        in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
                   for r in range(8)}
        live_in = {r: [s for s in in_nbrs[r] if s not in dead]
                   for r in range(8)}
        w = np.zeros((8, 2), np.float64)  # oracle state
        for _ in range(2):
            state, _ = opt.step(state, batch)
            # oracle: per-rank sgd step, then the healed uniform average
            wl = w - 0.1 * 2.0 * (w - np.arange(8.0).reshape(8, 1))
            mixed = np.zeros_like(wl)
            for r in range(8):
                u = 1.0 / (len(live_in[r]) + 1)
                mixed[r] = u * (wl[r] + sum(wl[s] for s in live_in[r]))
            w = mixed
        got = np.asarray(state.params["w"])
        # live rows only: a dead rank's own row is don't-care (nobody
        # deposits to it and nobody reads it — live combines use only
        # live sources, which is exactly what this asserts)
        live = sorted(set(range(8)) - dead)
        np.testing.assert_allclose(got[live], w[live], rtol=1e-5, atol=1e-6)
        # live ranks never averaged with a dead rank's value: rank 6/7's
        # distinct targets (6.0/7.0) must not have leaked into rank 0's
        # combine beyond its live in-set
        assert not np.allclose(got[0], got[6])
    finally:
        opt.free()


def test_peer_death_demotes_edges_to_hosted_partition(monkeypatch):
    """ISSUE r13: under the hybrid per-edge plane (BLUEFOG_WIN_PLANE=auto),
    an injected peer death re-plans the partition — the dead ranks' edges
    leave the COMPILED set (no compiled program may name a dead rank),
    land on the hosted residual, get dropped there by the healed tables,
    and the step COMPLETES on the healed partition matching the
    shrunken-graph numpy oracle."""
    import bluefog_tpu as bf
    import jax.numpy as jnp
    import optax

    from bluefog_tpu.ops import windows as W
    from bluefog_tpu.runtime import heartbeat as hb
    from conftest import cpu_devices

    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
        "BLUEFOG_WIN_PLANE": "auto",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active()
    try:
        def loss_fn(params, batch):
            return jnp.sum((params["w"] - batch) ** 2)

        opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), loss_fn=loss_fn)
        state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
        batch = bf.shard_rank_stacked(
            bf.mesh(), np.arange(8, dtype=np.float32).reshape(8, 1))
        try:
            win = W._get_window(opt._win_names[0])
            state, _ = opt.step(state, batch)  # healthy: all compiled
            part0 = win.plane_partition(set())
            assert part0 is not None and not part0.hosted

            dead = {6, 7}
            monkeypatch.setattr(hb, "dead_ranks", lambda: set(dead))
            ep = hb.membership_epoch()
            monkeypatch.setattr(hb, "membership_epoch", lambda: ep + 1)

            topo = bf.load_topology()
            live_in = {r: [s for s in
                           bf.topology_util.in_neighbor_ranks(topo, r)
                           if s not in dead] for r in range(8)}
            w = np.asarray(state.params["w"], np.float64)
            for _ in range(2):
                state, _ = opt.step(state, batch)  # must complete, no hang
                wl = w - 0.1 * 2.0 * (w - np.arange(8.0).reshape(8, 1))
                mixed = np.zeros_like(wl)
                for r in range(8):
                    u = 1.0 / (len(live_in[r]) + 1)
                    mixed[r] = u * (wl[r] + sum(wl[s] for s in live_in[r]))
                w = mixed
            # the healed partition: no compiled edge names a dead rank
            part = win._planner.partition(frozenset(dead), ep + 1)
            assert part.compiled, "live-live edges must stay compiled"
            assert all(s not in dead and d not in dead
                       for s, d in part.compiled)
            assert all((s, d) in part.hosted
                       for s, d in win._planner.edges
                       if s in dead or d in dead)
            got = np.asarray(state.params["w"])
            live = sorted(set(range(8)) - dead)
            np.testing.assert_allclose(got[live], w[live],
                                       rtol=1e-5, atol=1e-6)
        finally:
            opt.free()
    finally:
        bf.shutdown()
        cp.reset_for_test()


def test_gossip_step_retries_after_dead_mutex_holder(bf_hosted_cp):
    """End-to-end PeerLostError recovery: an external actor dies while
    holding a window mutex the optimizer's hoisted acquisition needs. The
    blocked step must surface the force-release as PeerLostError
    internally, retry once, and COMPLETE — no hang, no leaked mutexes (a
    second step still acquires everything)."""
    import jax.numpy as jnp
    import optax

    bf = bf_hosted_cp
    port = int(os.environ["BLUEFOG_CP_PORT"])

    def loss_fn(params, batch):
        return jnp.sum(params["w"] ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))
    try:
        state, _ = opt.step(state, batch)  # healthy warm-up
        actor = native.ControlPlaneClient("127.0.0.1", port, rank=9,
                                          streams=1)
        actor.lock(f"w.{opt._win_names[0]}.mu.5")

        def die_holding():
            time.sleep(0.6)
            actor.close()  # connection closes while holding -> force release

        killer = threading.Thread(target=die_holding, daemon=True)
        killer.start()
        t0 = time.monotonic()
        state, _ = opt.step(state, batch)  # blocks, PeerLostError, retries
        assert time.monotonic() - t0 < 30
        killer.join(5.0)
        state, _ = opt.step(state, batch)  # no mutex leaked by the retry
    finally:
        opt.free()


def test_flight_dump_after_injected_peer_lost_under_drops(
        bf_hosted_cp, tmp_path, monkeypatch):
    """ISSUE r12 satellite: an injected PeerLostError under armed
    BLUEFOG_CP_FAULT leaves a parseable flight dump — fatal instant in the
    tail, the drop-churn transport events spliced in from the native ring.
    Rides `make chaos`: the armed drop points shift with the seed offset,
    so the dump is produced under different wire damage each replay."""
    import json

    import jax.numpy as jnp

    from bluefog_tpu.runtime import flight as flight_mod
    from bluefog_tpu.runtime import handles

    bf = bf_hosted_cp
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("BLUEFOG_FLIGHT_MIN_INTERVAL", "0")
    flight_mod.reset_for_job()

    # hosted gossip traffic while connections are being killed under it:
    # the transparent redials land in the NATIVE flight ring every dump
    # splices in
    x = jnp.arange(8.0).reshape(8, 1) + 1.0
    assert bf.win_create(x, "chaos.fl", zero_init=True)
    native.fault_arm(f"drop_after=5,seed={_seed(17)}")
    for _ in range(3):
        bf.win_accumulate(x, "chaos.fl")
        bf.win_update("chaos.fl")
    drops = native.fault_stats()["drops"]
    native.fault_disarm()
    assert drops >= 2, f"only {drops} drops injected"
    bf.win_free("chaos.fl")

    # injected PeerLostError through the runtime's own synchronize path:
    # a handle that can never complete while the failure detector names a
    # dead controller — the typed raise must leave a dump behind
    class _NeverReady:
        def is_ready(self):
            return False

    monkeypatch.setattr(heartbeat, "dead_controllers", lambda: {1})
    h = handles.allocate("op.fl", _NeverReady())
    try:
        with pytest.raises(native.PeerLostError):
            handles.synchronize(h, timeout=0.1)
        path = tmp_path / "bf_flight_0.json"
        assert path.exists(), "injected PeerLostError left no flight dump"
        doc = json.loads(path.read_text())
        assert "PeerLostError" in doc["meta"]["exception"]
        names = doc["names"]
        instants = [names[n]
                    for k, n in zip(doc["events"]["kind"],
                                    doc["events"]["name"])
                    if k == flight_mod.INSTANT]
        assert "fatal.synchronize" in instants
        # the spliced native ring carries the redial churn the armed
        # drops just caused (kind 1 = attempt, 2 = success)
        kinds = {row[1] for row in doc["native"]}
        assert kinds & {1, 2}, f"native ring missing redials: {kinds}"
        # (cp.fault.* counters reset on disarm by design — the drops>=2
        # assertion above is the churn evidence)
    finally:
        handles.clear()
        flight_mod.reset_for_job()


# ---------------------------------------------------------------------------
# kill a peer mid-gossip: survivors renormalize and keep training (slow)
# ---------------------------------------------------------------------------

def _scrubbed_env():
    env = os.environ.copy()
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT", "BLUEFOG_CP_FAULT"):
        env.pop(k, None)
    env["PYTHONPATH"] = str(TESTS.parent) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


@pytest.mark.slow
def test_kill_peer_mid_gossip_self_heals():
    """4 controllers x 2 devices running window-optimizer gossip; controller
    3 is hard-killed MID-STEP. Every survivor must (a) detect {3} dead
    within the heartbeat timeout, (b) keep completing bounded gossip steps
    on the renormalized topology (dead ranks {6, 7} excluded), and (c)
    exit cleanly — the ISSUE's 'keeps training on the shrunken graph'
    acceptance, at the reference CI's np=4 scale."""
    port = _free_port()
    env = _scrubbed_env()
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.2"
    env["BLUEFOG_HEARTBEAT_TIMEOUT"] = "1.5"
    env["BLUEFOG_CP_LOCK_LEASE"] = "20"

    def cmd(i):
        return [sys.executable, "-m", "bluefog_tpu.launcher", "-np", "4",
                "--coordinator", f"127.0.0.1:{port}", "--process-id", str(i),
                "--simulate", "2",
                "--", sys.executable, str(TESTS / "_gossip_fault_child.py")]

    procs = [subprocess.Popen(cmd(i), env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(4)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert procs[3].returncode == 17, f"faulty process:\n{outs[3]}"
    for i in range(3):
        assert procs[i].returncode == 0, f"survivor {i} failed:\n{outs[i]}"
        assert f"DEAD_DETECTED {i}" in outs[i], outs[i]
        assert f"SURVIVOR_STEPS_OK {i}" in outs[i], outs[i]
        assert f"CHILD_OK {i}" in outs[i], outs[i]
    for i in range(4):
        assert f"HEALTHY {i}" in outs[i]


# ---------------------------------------------------------------------------
# incarnation fencing: zombie rejection + server-side GC (ISSUE r9)
# ---------------------------------------------------------------------------

def _seed(base: int) -> int:
    """Deterministic seed, shiftable job-wide by `make chaos` so the whole
    suite replays its drop points at a second offset (BLUEFOG_CHAOS_SEED)."""
    return base + int(os.environ.get("BLUEFOG_CHAOS_SEED", "0") or 0)


@pytest.mark.parametrize("streams", [4, 1])
def test_zombie_gets_typed_stale_rejections(streams):
    """Acceptance (c): after a rank re-attaches with a bumped incarnation,
    its old incarnation's client receives typed StaleIncarnationError on
    EVERY op class — scalar, blocking, pipelined, and bulk — and the
    server retains zero dedup/mailbox state for the dead incarnation."""
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        old = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                        streams=streams, incarnation=0)
        # seed server-side identity for the old incarnation
        old.fetch_add("z.ctr", 1)                       # dedup entry
        old.append_bytes_tagged_many(
            ["z.box"], [b"stale-parameters"],
            [(((1 & 0x7F) << 32) | 7) << 24])           # origin-tagged record
        old.lock("z.lock")                               # held lock
        assert srv.incarnation_of(1) == 0
        assert srv.mailbox_records_from(1) == 1
        assert srv.dedup_entries() >= 1

        # the respawn attaches with incarnation+1: fence + GC
        new = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                        streams=streams, incarnation=1)
        assert srv.incarnation_of(1) == 1
        assert srv.mailbox_records_from(1) == 0, \
            "dead incarnation's queued deposits survived the GC"
        assert srv.dedup_entries() == 0, \
            "dead incarnation's dedup records survived the GC"
        new.lock("z.lock")  # force-released from the zombie: re-acquirable
        new.unlock("z.lock")

        zombie_ops = [
            lambda: old.put("z.x", 1),
            lambda: old.get("z.x"),
            lambda: old.fetch_add("z.ctr", 1),
            lambda: old.barrier("z.bar"),
            lambda: old.lock("z.lock2"),
            lambda: old.unlock("z.lock"),
            lambda: old.append_bytes("z.box", b"more"),
            lambda: old.take_bytes("z.box"),
            lambda: old.put_bytes("z.blob", b"payload"),
            lambda: old.get_bytes("z.blob"),
            lambda: old.get_many(["z.x", "z.y"]),
            lambda: old.put_many(["z.x"], [2]),
            lambda: old.fetch_add_many(["z.c2"]),
            lambda: old.box_bytes_many(["z.box"]),
            lambda: old.take_bytes_many(["z.box"]),
            lambda: old.get_bytes_many(["z.blob"]),
            lambda: old.append_bytes_many(["z.box"], [b"r"]),
            lambda: old.bytes_len("z.blob"),
        ]
        for op in zombie_ops:
            with pytest.raises(native.StaleIncarnationError,
                               match="superseded"):
                op()
        # the new incarnation is unaffected
        new.put("z.alive", 5)
        assert new.get("z.alive") == 5
        old.close()
        new.close()
    finally:
        srv.stop()


def test_stale_attach_rejected_at_connect():
    """A zombie that reconnects AFTER its replacement registered is
    rejected at construction time with the typed error (never admitted)."""
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        fresh = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                          streams=1, incarnation=3)
        with pytest.raises(native.StaleIncarnationError):
            native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                      streams=1, incarnation=2)
        # equal incarnation is NOT stale (pool connections of the same
        # process attach with the same value)
        peer = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                         streams=1, incarnation=3)
        peer.put("ok", 1)
        peer.close()
        fresh.close()
    finally:
        srv.stop()


@pytest.mark.parametrize("streams", [4, 1])
def test_zombie_fenced_while_transport_drops(streams):
    """Fencing composes with the reconnecting transport: with fault
    injection killing connections under BOTH clients, the zombie still
    gets typed rejections (a reconnect re-registers and is re-fenced, so
    drops can never let it slip back in) and the live incarnation's ops
    stay exactly-once."""
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        old = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                        streams=streams, incarnation=0)
        old.put("f.pre", 1)
        new = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                        streams=streams, incarnation=1)
        native.fault_arm(f"drop_after=4,seed={_seed(11)}")
        seen = [new.fetch_add("f.ctr", 1) for _ in range(30)]
        for _ in range(10):
            with pytest.raises(native.StaleIncarnationError):
                old.fetch_add("f.ctr", 1)
            with pytest.raises(native.StaleIncarnationError):
                old.put("f.pre", 2)
        drops = native.fault_stats()["drops"]
        native.fault_disarm()
        assert drops >= 3, f"only {drops} drops injected"
        assert seen == list(range(30)), "live incarnation lost exactly-once"
        assert new.get("f.ctr") == 30
        assert new.get("f.pre") == 1, "zombie write leaked through"
        old.close()
        new.close()
    finally:
        srv.stop()


def test_membership_epoch_bumps_on_joins():
    """The server advances the membership-epoch KV on every first join and
    every incarnation bump — the signal window optimizers key their
    neighbor-table rebuilds on."""
    srv = native.ControlPlaneServer(4, _free_port())
    try:
        a = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                      streams=1, incarnation=0)
        e0 = a.get("bf.membership.epoch")
        assert e0 >= 1  # a's own join bumped it
        b = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                      streams=1, incarnation=0)
        assert a.get("bf.membership.epoch") == e0 + 1
        # same-rank same-incarnation reattach (pool conn) does NOT bump
        b2 = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                       streams=1, incarnation=0)
        assert a.get("bf.membership.epoch") == e0 + 1
        # incarnation bump (rejoin) bumps
        c = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                      streams=1, incarnation=1)
        assert a.get("bf.membership.epoch") == e0 + 2
        assert a.get("bf.inc.1") == 1
        for cl in (a, b, b2, c):
            cl.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# rejoin protocol: state transfer + push-sum mass split (in-process)
# ---------------------------------------------------------------------------

def test_rejoin_transfer_adopts_donor_row_and_step(bf_hosted_cp):
    """The base (non-push-sum) transfer: a rank adopts a donor's published
    packed window row under the donor's mutex, and _adopt_window_rows
    rebuilds the rank-stacked params from the windows — the rejoiner's
    parameters become the donor's current values."""
    import jax.numpy as jnp
    import optax
    import time as _t

    bf = bf_hosted_cp
    from bluefog_tpu.ops import windows as W

    def loss_fn(params, batch):
        return jnp.sum(params["w"] ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.arange(3.0, dtype=jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))
    try:
        for _ in range(2):
            state, _ = opt.step(state, batch)
        win = W._get_window(opt._win_names[0])
        donor_row = win._rows[3].copy()
        assert not np.allclose(win._rows[0], donor_row) or True
        ok = opt._transfer_rank(0, 3, _t.monotonic() + 10)
        assert ok
        np.testing.assert_array_equal(win._rows[0], donor_row)
        # the published copy moved too (survivors' win_get sees it)
        np.testing.assert_array_equal(win.read_published_row(0), donor_row)
        # params rebuilt from windows: rank 0's leaf row == donor's values
        state2 = opt._adopt_window_rows(state)
        got = np.asarray(state2.params["w"])
        np.testing.assert_allclose(got[0], got[3], rtol=0, atol=0)
        # step counter adoption: published by gossip steps
        from bluefog_tpu.runtime import control_plane as _cpm
        cl = _cpm.client()
        assert cl.get(opt._step_counter_key(0)) == opt._counter
    finally:
        opt.free()


def test_rejoin_transfer_fails_over_dead_donor(bf_hosted_cp):
    """A donor whose published slot is absent/mis-sized is skipped: the
    transfer returns False so the caller tries the next candidate."""
    import jax.numpy as jnp
    import optax
    import time as _t

    bf = bf_hosted_cp
    from bluefog_tpu.ops import windows as W
    from bluefog_tpu.runtime import control_plane as _cpm

    def loss_fn(params, batch):
        return jnp.sum(params["w"] ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    try:
        win = W._get_window(opt._win_names[0])
        # clear donor 3's published tensor (a dead controller's slot after
        # win_free cleanup, or one that never published)
        _cpm.client().put_bytes(win._self_key(3), b"")
        assert win.read_published_row(3) is None
        assert not opt._transfer_rank(0, 3, _t.monotonic() + 5)
        # a healthy donor still works
        assert opt._transfer_rank(0, 5, _t.monotonic() + 5)
    finally:
        opt.free()


def test_pushsum_mass_split_bit_exact(bf_hosted_cp):
    """Acceptance (b), the donor side of it: the push-sum mass split moves
    EXACTLY half the donor's numerator and p to the rejoiner — total mass
    over the job is bit-exactly unchanged, and both parties' de-biased
    parameters equal the donor's pre-split values."""
    import jax.numpy as jnp
    import optax
    import time as _t

    bf = bf_hosted_cp
    from bluefog_tpu.ops import windows as W
    from bluefog_tpu.runtime import control_plane as _cpm

    def loss_fn(params, batch):
        return jnp.sum((params["w"] - batch) ** 2)

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf.shard_rank_stacked(
        bf.mesh(), np.arange(8, dtype=np.float32).reshape(8, 1))
    try:
        for _ in range(3):
            state, _ = opt.step(state, batch)
        nm = opt._win_names[0]
        win = W._get_window(nm)
        p_before = win.host.read_p()
        rows_before = {r: win._rows[r].copy() for r in range(8)}
        total_before = sum(float(rows_before[r].sum()) for r in range(8))
        donor = 3
        x_donor = rows_before[donor] / p_before[donor]

        # rejoiner side posts the request on a thread; donor side serves
        result = {}

        def rejoin():
            result["ok"] = opt._transfer_rank(0, donor,
                                              _t.monotonic() + 20)

        t = threading.Thread(target=rejoin, daemon=True)
        t.start()
        deadline = _t.monotonic() + 10
        cl = _cpm.client()
        while _t.monotonic() < deadline and not cl.get(f"w.{nm}.msreq.0"):
            _t.sleep(0.01)
        opt._serve_epoch = None  # force the scan (epoch mirror is static)
        opt._serve_rejoin_requests()
        t.join(20)
        assert result.get("ok") is True

        p_after = win.host.read_p()
        # donor halved; rejoiner holds the other half — bit-exact
        assert p_after[donor] == p_before[donor] * 0.5
        assert p_after[0] == p_before[donor] * 0.5
        assert float(p_after.sum()) == float(
            p_before.sum() - p_before[0])  # rank 0's stale mass replaced
        np.testing.assert_array_equal(
            win._rows[donor] + win._rows[0],
            rows_before[donor])  # numerator halves sum back exactly
        # de-biased parameters: both equal the donor's pre-split x
        np.testing.assert_allclose(
            win._rows[0] / p_after[0], x_donor, rtol=1e-6)
        np.testing.assert_allclose(
            win._rows[donor] / p_after[donor], x_donor, rtol=1e-6)
        # request/serve keys cleaned up
        assert cl.get(f"w.{nm}.msreq.0") == 0
        assert cl.get(f"w.{nm}.msdone.0") == 0
    finally:
        opt.free()


def test_healed_tables_cached_per_dead_set(bf_hosted_cp, monkeypatch):
    """The healed edge tables are derived ONCE per dead set (the membership
    epoch gates the rebuild), not re-derived every gossip step."""
    import jax.numpy as jnp
    import optax

    bf = bf_hosted_cp
    import bluefog_tpu.optimizers as O
    from bluefog_tpu.runtime import heartbeat as hb

    monkeypatch.setattr(hb, "dead_ranks", lambda: {6, 7})
    calls = [0]
    real = O._healed_recv_weights

    def counting(*a, **k):
        calls[0] += 1
        return real(*a, **k)

    monkeypatch.setattr(O, "_healed_recv_weights", counting)

    def loss_fn(params, batch):
        return jnp.sum(params["w"] ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))
    try:
        for _ in range(4):
            state, _ = opt.step(state, batch)
        assert calls[0] == 1, (
            f"healed tables derived {calls[0]}x for one unchanged dead set")
        # membership change -> rebuild once more
        monkeypatch.setattr(hb, "dead_ranks", lambda: {7})
        for _ in range(3):
            state, _ = opt.step(state, batch)
        assert calls[0] == 2
    finally:
        opt.free()


# ---------------------------------------------------------------------------
# sharded control plane: routing, replication, SIGKILL failover (ISSUE r14)
# ---------------------------------------------------------------------------

import signal  # noqa: E402 — grouped with the shard helpers that use it

SHARD_SERVER = TESTS.parent / "bluefog_tpu" / "runtime" / "shard_server.py"


def _spawn_shard(i: int, world: int = 1):
    proc = subprocess.Popen(
        [sys.executable, str(SHARD_SERVER), "--port", "0",
         "--world", str(world), "--shard", str(i)],
        stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    assert line.startswith("BF_SHARD_READY"), f"shard {i}: {line!r}"
    return proc, int(line.split()[1])


def _stop_shards(servers):
    for proc, _ in servers:
        if proc.poll() is None:
            proc.terminate()
    for proc, _ in servers:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()


@pytest.fixture()
def shard_pair(monkeypatch):
    """Two real shard server PROCESSES (SIGKILL-able) + fast reconnects."""
    monkeypatch.setenv("BLUEFOG_CP_BACKOFF_MS", "20")
    servers = [_spawn_shard(i) for i in range(2)]
    yield servers
    native.fault_disarm()
    _stop_shards(servers)


def _endpoints(servers):
    return [("127.0.0.1", port) for _, port in servers]


def test_shard_failover_fetch_add_exactly_once(shard_pair):
    """Acceptance: fetch_add stays exactly-once ACROSS the failover
    boundary, composed with wire-drop injection. Pre-kill the victim
    shard's counter hands out contiguous pre-add values under drops (the
    r8 dedup); the SIGKILL reroutes the key to the replica where the era
    restarts at 0 and stays contiguous — a double-apply would skip a
    value, a lost apply would repeat one, on either side of the kill."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(shard_pair), 0, streams=1)
    key = next(f"fo.ctr.{j}" for j in range(64)
               if r.shard_of(f"fo.ctr.{j}") == 1)
    native.fault_arm(f"drop_after=6,seed={_seed(23)}")
    pre = [r.fetch_add(key, 1) for _ in range(25)]
    assert pre == list(range(25)), "pre-kill era lost exactly-once"
    proc, _ = shard_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    post = [r.fetch_add(key, 1) for _ in range(25)]
    drops = native.fault_stats()["drops"]
    native.fault_disarm()
    assert drops >= 3, f"only {drops} drops injected"
    # typed degradation: the shard is named dead, nothing raised
    assert r.dead_shards() == {1}
    assert post == list(range(25)), "failover era lost exactly-once"
    assert r.get(key) == 25
    r.close()


def test_shard_mailbox_failover_mass_conserved(shard_pair):
    """Deposit/drain cycles across a shard SIGKILL conserve mass exactly
    when the kill lands between drains (the documented failover window):
    every acked byte is drained, including the cycles whose mailboxes
    rerouted to the replica."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(shard_pair), 0, streams=1)
    rng = np.random.default_rng(_seed(29))
    boxes = [f"mb.{k}" for k in range(6)]
    assert {r.shard_of(b) for b in boxes} == {0, 1}, \
        "want mailboxes on both shards"
    acked = drained = 0

    def cycle():
        nonlocal acked, drained
        names, blobs = [], []
        for b in boxes:
            for _ in range(2):
                names.append(b)
                blobs.append(bytes(rng.integers(
                    0, 256, size=int(rng.integers(64, 2048)),
                    dtype=np.uint8)))
        replies = r.append_bytes_many(names, blobs)
        acked += sum(len(b) for b, rep in zip(blobs, replies) if rep >= 1)
        drained += sum(len(x) for lst in r.take_bytes_many(boxes)
                       for x in lst)

    for _ in range(3):
        cycle()
    proc, _ = shard_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    for _ in range(3):
        cycle()
    assert r.dead_shards() == {1}
    assert acked == drained, \
        f"deposit mass not conserved across failover: {acked} != {drained}"
    r.close()


def test_shard_replicated_membership_state_survives_kill(shard_pair):
    """The membership-critical keys — epoch, quarantine phases, the
    incarnation table — are replicated on every shard: a SIGKILL loses
    none of them, and a zombie incarnation is still fenced by the
    survivor alone."""
    from bluefog_tpu.runtime.router import ShardRouter

    eps = _endpoints(shard_pair)
    fresh = ShardRouter(eps, 7, streams=1, incarnation=1)
    r = ShardRouter(eps, 0, streams=1)
    r.put("bf.q.7.1", 1)
    r.put("bf.q.7.1", 2)        # quarantine phases are monotone
    e0 = r.get("bf.membership.epoch")
    e1 = r.fetch_add("bf.membership.epoch", 1)
    assert e1 >= e0
    proc, _ = shard_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    assert r.get("bf.q.7.1") == 2, "quarantine phase lost with the shard"
    assert r.get("bf.membership.epoch") >= e1 + 1, \
        "membership epoch regressed after failover"
    # the survivor's incarnation table still fences the zombie on its own
    with pytest.raises(native.StaleIncarnationError):
        ShardRouter(eps, 7, streams=1, incarnation=0)
    fresh.close()
    r.close()


def test_shard_attach_strictness_vs_flagged_death(shard_pair):
    """A FRESH job must not attach with a down, unflagged shard (it would
    run with less replication than configured); once a survivor has
    flagged the death, a (re)attach into the degraded cluster succeeds —
    the elastic-respawn path."""
    from bluefog_tpu.runtime.router import ShardRouter

    eps = _endpoints(shard_pair)
    proc, _ = shard_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    with pytest.raises(OSError, match="not flagged dead"):
        ShardRouter(eps, 0, streams=1)
    cl = native.ControlPlaneClient("127.0.0.1", shard_pair[0][1], 9,
                                   streams=1)
    cl.put_max("bf.cp.shard_dead.1", 1)
    cl.close()
    r = ShardRouter(eps, 0, streams=1)
    assert r.dead_shards() == {1}
    r.put("deg.x", 5)
    assert r.get("deg.x") == 5
    r.close()


def test_shard_kill_mid_gossip_run_completes(monkeypatch):
    """Survivability demo (acceptance): a window-optimizer gossip run over
    a 2-shard control plane completes its steps after one shard is
    SIGKILLed mid-run, with ZERO lost deposits — every rank's mixed
    parameters match the fault-free numpy oracle exactly (the oracle IS
    the mass-conservation check: a lost deposit would break the uniform
    average), and the dead shard is reported typed instead of raising."""
    import bluefog_tpu as bf
    import jax.numpy as jnp
    import optax

    from conftest import cpu_devices

    servers = [_spawn_shard(i) for i in range(2)]
    try:
        eps = ",".join(f"127.0.0.1:{p}" for _, p in servers)
        for k, v in {
            "BLUEFOG_CP_HOSTS": eps,
            "BLUEFOG_CP_WORLD": "1",
            "BLUEFOG_CP_RANK": "0",
            "BLUEFOG_CP_BACKOFF_MS": "20",
            # pure hosted plane: every gossip edge rides the (sharded)
            # control-plane wire, so the failover is actually load-bearing
            "BLUEFOG_WIN_PLANE": "hosted",
            "BLUEFOG_WIN_HOST_PLANE": "1",
        }.items():
            monkeypatch.setenv(k, v)
        cp.reset_for_test()
        bf.init(devices=cpu_devices(8))
        assert cp.active()
        assert getattr(cp.client(), "shard_count", 1) == 2

        def loss_fn(params, batch):
            return jnp.sum((params["w"] - batch) ** 2)

        opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), loss_fn=loss_fn)
        state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
        batch = bf.shard_rank_stacked(
            bf.mesh(), np.arange(8, dtype=np.float32).reshape(8, 1))
        try:
            topo = bf.load_topology()
            in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
                       for r in range(8)}
            w = np.zeros((8, 2), np.float64)  # fault-free oracle state

            def oracle_step():
                nonlocal w
                wl = w - 0.1 * 2.0 * (w - np.arange(8.0).reshape(8, 1))
                mixed = np.zeros_like(wl)
                for r in range(8):
                    u = 1.0 / (len(in_nbrs[r]) + 1)
                    mixed[r] = u * (wl[r] + sum(wl[s] for s in in_nbrs[r]))
                w = mixed

            for _ in range(2):  # healthy warm-up over both shards
                state, _ = opt.step(state, batch)
                oracle_step()
            proc, _ = servers[1]
            proc.send_signal(signal.SIGKILL)  # mid-run: between steps,
            proc.wait()                       # mailboxes drained
            for _ in range(2):  # must complete after failover — no hang
                state, _ = opt.step(state, batch)
                oracle_step()
            got = np.asarray(state.params["w"])
            np.testing.assert_allclose(got, w, rtol=1e-5, atol=1e-6)
            assert cp.client().dead_shards() == {1}
        finally:
            opt.free()
    finally:
        bf.shutdown()
        cp.reset_for_test()
        _stop_shards(servers)


# ---------------------------------------------------------------------------
# durable control plane (r16): WAL replication, lock handoff, shard rejoin
# ---------------------------------------------------------------------------

def _spawn_shard_repl(i: int, port: int = 0, rejoin: bool = False,
                      world: int = 1, env=None):
    """Phase 1 of a replicated shard spawn: returns (proc, port) after the
    BF_SHARD_PORT line; finish with :func:`_finish_repl_spawn`. ``env``
    replaces the child environment (server-only knobs like a
    ``BLUEFOG_CP_FAULT`` partition spec that must NOT leak into the test
    process); None inherits."""
    cmd = [sys.executable, str(SHARD_SERVER), "--port", str(port),
           "--world", str(world), "--shard", str(i), "--expect-peers"]
    if rejoin:
        cmd.append("--rejoin")
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stdin=subprocess.PIPE, text=True, env=env)
    line = proc.stdout.readline()
    assert line.startswith("BF_SHARD_PORT"), f"shard {i}: {line!r}"
    return proc, int(line.split()[1])


def _finish_repl_spawn(servers) -> None:
    ring = ",".join(f"127.0.0.1:{port}" for _, port in servers)
    for proc, _ in servers:
        proc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
        proc.stdin.flush()
    for i, (proc, _) in enumerate(servers):
        line = proc.stdout.readline()
        assert line.startswith("BF_SHARD_READY"), f"shard {i}: {line!r}"


@pytest.fixture()
def repl_pair(monkeypatch):
    """Two real shard server PROCESSES with WAL replication wired
    (SIGKILL-able) + fast reconnects."""
    monkeypatch.setenv("BLUEFOG_CP_BACKOFF_MS", "20")
    servers = [_spawn_shard_repl(i) for i in range(2)]
    _finish_repl_spawn(servers)
    yield servers
    native.fault_disarm()
    _stop_shards(servers)


def test_repl_deposit_zero_loss_on_shard_kill(repl_pair):
    """THE tentpole acceptance: SIGKILL a shard with NON-EMPTY undrained
    mailboxes — every acked deposit is drained from the promoted ring
    successor, byte for byte. Not a 'documented one-cycle window': zero
    lost deposits."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(repl_pair), 0, streams=1)
    rng = np.random.default_rng(_seed(41))
    box = next(f"zl.box.{j}" for j in range(64)
               if r.shard_of(f"zl.box.{j}") == 1)
    blobs = [bytes(rng.integers(0, 256, size=int(rng.integers(200, 4000)),
                                dtype=np.uint8)) for _ in range(12)]
    replies = r.append_bytes_many([box] * len(blobs), blobs)
    assert all(rep >= 1 for rep in replies)
    proc, _ = repl_pair[1]
    proc.send_signal(signal.SIGKILL)   # dies holding 12 undrained records
    proc.wait()
    drained = [bytes(x) for lst in r.take_bytes_many([box]) for x in lst]
    assert drained == blobs, (
        f"lost deposits across the kill: {len(drained)}/{len(blobs)} "
        "records survived")
    assert r.dead_shards() == {1}
    r.close()


def test_repl_fetch_add_continuous_across_kill(repl_pair):
    """With WAL replication the counter CONTINUES on the successor — the
    r14 'era restarts at 0' contract is upgraded to cross-era continuity:
    a skipped or repeated pre-add value on either side of the SIGKILL
    would be a double- or lost apply."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(repl_pair), 0, streams=1)
    key = next(f"cc.ctr.{j}" for j in range(64)
               if r.shard_of(f"cc.ctr.{j}") == 1)
    native.fault_arm(f"drop_after=6,seed={_seed(43)}")
    pre = [r.fetch_add(key, 1) for _ in range(25)]
    assert pre == list(range(25))
    proc, _ = repl_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    post = [r.fetch_add(key, 1) for _ in range(25)]
    native.fault_disarm()
    assert post == list(range(25, 50)), \
        f"counter not continuous across failover: {post[:5]}..."
    assert r.get(key) == 50
    assert r.dead_shards() == {1}
    r.close()


def test_repl_lock_handoff_on_shard_kill(repl_pair):
    """Satellite acceptance: the lock holder's shard is SIGKILLed
    mid-critical-section; a waiter acquires on the promoted successor
    WITHOUT PeerLostError, and the holder's unlock hands off cleanly
    (the successor adopted holder state via the WAL)."""
    from bluefog_tpu.runtime.router import ShardRouter

    eps = _endpoints(repl_pair)
    holder = ShardRouter(eps, 0, streams=1)
    waiter = ShardRouter(eps, 1, streams=1)
    key = next(f"lh.lock.{j}" for j in range(64)
               if holder.shard_of(f"lh.lock.{j}") == 1)
    holder.lock(key)
    time.sleep(0.2)  # let the grant replicate
    acquired = threading.Event()

    def wait_lock():
        waiter.lock(key)   # blocks on shard 1, dies with it, fails over
        acquired.set()

    th = threading.Thread(target=wait_lock, daemon=True)
    th.start()
    time.sleep(0.3)
    proc, _ = repl_pair[1]
    proc.send_signal(signal.SIGKILL)   # mid-critical-section
    proc.wait()
    time.sleep(0.5)
    assert not acquired.is_set(), \
        "waiter acquired while the holder still held the handoff lock"
    holder.unlock(key)     # fails over; the replica knows the holder
    th.join(timeout=20)
    assert acquired.is_set(), \
        "waiter never acquired on the promoted successor"
    waiter.unlock(key)
    holder.close()
    waiter.close()


def test_repl_shard_rejoin_catches_up(repl_pair):
    """Shard rejoin within a job: the restarted process catches up from
    its successor's snapshot + WAL, publishes an even liveness
    generation, and the routers move the keyspace back — counters stay
    continuous and failover-era deposits survive the whole lifecycle."""
    from bluefog_tpu.runtime.router import ShardRouter

    eps = _endpoints(repl_pair)
    r = ShardRouter(eps, 0, streams=1)
    key = next(f"rj.ctr.{j}" for j in range(64)
               if r.shard_of(f"rj.ctr.{j}") == 1)
    box = next(f"rj.box.{j}" for j in range(64)
               if r.shard_of(f"rj.box.{j}") == 1)
    assert [r.fetch_add(key, 1) for _ in range(10)] == list(range(10))
    proc, port = repl_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    # failover era: counter continues, deposits land on the survivor
    assert [r.fetch_add(key, 1) for _ in range(5)] == list(range(10, 15))
    r.append_bytes_many([box] * 2, [b"alpha" * 40, b"beta" * 30])
    # restart IN PLACE on the same port with snapshot catch-up
    nproc, nport = _spawn_shard_repl(1, port=port, rejoin=True)
    repl_pair[1] = (nproc, nport)
    ring = ",".join(f"127.0.0.1:{p}" for _, p in
                    [repl_pair[0], (nproc, port)])
    nproc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
    nproc.stdin.flush()
    assert nproc.stdout.readline().startswith("BF_SHARD_READY")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and r.poll_shard_health():
        time.sleep(0.2)
    assert r.dead_shards() == set(), "routers never moved the ring back"
    # the rejoined shard serves its keyspace with full state
    assert [r.fetch_add(key, 1) for _ in range(5)] == list(range(15, 20))
    drained = [bytes(x) for lst in r.take_bytes_many([box]) for x in lst]
    assert drained == [b"alpha" * 40, b"beta" * 30], \
        "failover-era deposits lost across the rejoin"
    r.close()


def test_repl_shard_rejoin_on_new_port(repl_pair):
    """Satellite (r19): a restarted shard may land on a NEW ephemeral
    port. The rejoiner publishes its endpoint under
    ``bf.cp.shard_addr.<i>`` (generation-stamped put_max) through its
    ring successor; routers consult the key before the rejoin re-dial and
    adopt the moved endpoint — lifting the r16 'must reuse its old
    host:port' limit for the router plane. State must survive exactly as
    in the same-port rejoin."""
    from bluefog_tpu.runtime.router import ShardRouter

    eps = _endpoints(repl_pair)
    r = ShardRouter(eps, 0, streams=1)
    key = next(f"npj.ctr.{j}" for j in range(64)
               if r.shard_of(f"npj.ctr.{j}") == 1)
    box = next(f"npj.box.{j}" for j in range(64)
               if r.shard_of(f"npj.box.{j}") == 1)
    assert [r.fetch_add(key, 1) for _ in range(10)] == list(range(10))
    proc, old_port = repl_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    # failover era: counter continues, deposits land on the survivor
    assert [r.fetch_add(key, 1) for _ in range(5)] == list(range(10, 15))
    r.append_bytes_many([box] * 2, [b"alpha" * 40, b"beta" * 30])
    # restart on an EPHEMERAL port; the peer ring still names the OLD
    # endpoint for shard 1 (exactly what a respawn-anywhere scheduler
    # hands the new process)
    nproc, nport = _spawn_shard_repl(1, port=0, rejoin=True)
    repl_pair[1] = (nproc, nport)
    ring = ",".join(f"127.0.0.1:{p}"
                    for p in (repl_pair[0][1], old_port))
    nproc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
    nproc.stdin.flush()
    assert nproc.stdout.readline().startswith("BF_SHARD_READY")
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline and r.poll_shard_health():
        time.sleep(0.2)
    assert r.dead_shards() == set(), \
        "routers never adopted the published rejoin address"
    if nport != old_port:  # ephemeral could in principle recycle old_port
        assert r.endpoints[1] == ("127.0.0.1", nport), \
            f"endpoint table not re-pointed: {r.endpoints[1]}"
    # the moved shard serves its keyspace with full state
    assert [r.fetch_add(key, 1) for _ in range(5)] == list(range(15, 20))
    drained = [bytes(x) for lst in r.take_bytes_many([box]) for x in lst]
    assert drained == [b"alpha" * 40, b"beta" * 30], \
        "failover-era deposits lost across the new-port rejoin"
    r.close()


def test_repl_status_reports_degraded_survivor(repl_pair):
    """After the kill the survivor serves UNREPLICATED (its successor is
    gone): its stats block must say so (repl_status == 2) — the signal
    `bfrun --status --strict` turns into an under-replication finding
    with exit 2."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(repl_pair), 0, streams=1)
    r.put("ds.x", 1)  # traffic so both replicators are live
    for name, st in r.server_stats_all():
        assert st["repl_status"] == 1, (name, st["repl_status"])
    proc, _ = repl_pair[1]
    proc.send_signal(signal.SIGKILL)
    proc.wait()
    # drive some writes so the survivor notices its dead successor
    for i in range(20):
        r.put(f"ds.k{i}", i)
    deadline = time.monotonic() + 10
    degraded = False
    while time.monotonic() < deadline and not degraded:
        for name, st in r.server_stats_all():
            if st is not None and st["repl_status"] == 2:
                degraded = True
        time.sleep(0.1)
    assert degraded, "survivor never reported itself under-replicated"
    r.close()


def test_repl_rejoin_churn_both_shards(repl_pair):
    """Stale-fence regression: shard 1 rejoins (adopting a fence over
    shard 0's WAL stream), THEN shard 0 dies and rejoins. The restarted
    shard 0 must RESUME its WAL numbering from the fence its successor
    holds — a restart back at zero would leave every post-rejoin record
    at or below that stale fence, silently dropped-and-acked by shard 1,
    i.e. shard 0's next death loses acked writes."""
    from bluefog_tpu.runtime.router import ShardRouter

    eps = _endpoints(repl_pair)
    ring = ",".join(f"127.0.0.1:{p}" for _, p in repl_pair)
    r = ShardRouter(eps, 0, streams=1)
    k0 = next(f"bb.ctr.{j}" for j in range(64)
              if r.shard_of(f"bb.ctr.{j}") == 0)
    box0 = next(f"bb.box.{j}" for j in range(64)
                if r.shard_of(f"bb.box.{j}") == 0)

    def rejoin(slot: int):
        proc, port = repl_pair[slot]
        nproc, _ = _spawn_shard_repl(slot, port=port, rejoin=True)
        nproc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
        nproc.stdin.flush()
        assert nproc.stdout.readline().startswith("BF_SHARD_READY"), \
            f"shard {slot} failed to rejoin"
        repl_pair[slot] = (nproc, port)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and r.poll_shard_health():
            time.sleep(0.2)
        assert r.dead_shards() == set(), \
            f"routers never moved the ring back after shard {slot} rejoin"

    # era 0: advance shard 0's WAL well past the record count of era 3 —
    # the stale fence must be LARGER than what a zero-based restart would
    # silently drop for the regression to bite
    assert [r.fetch_add(k0, 1) for _ in range(40)] == list(range(40))
    # era 1: shard 1 dies; traffic degrades shard 0's stream, then
    # shard 1 rejoins — its snapshot fences shard 0's stream at ~43
    p1, _ = repl_pair[1]
    p1.send_signal(signal.SIGKILL)
    p1.wait()
    assert [r.fetch_add(k0, 1) for _ in range(3)] == [40, 41, 42]
    rejoin(1)
    # post-rejoin records ride shard 0's re-armed stream to shard 1
    assert [r.fetch_add(k0, 1) for _ in range(4)] == [43, 44, 45, 46]
    # era 2: shard 0 dies; its keyspace fails over to the REJOINED
    # shard 1, which must hold the full replicated counter
    p0, _ = repl_pair[0]
    p0.send_signal(signal.SIGKILL)
    p0.wait()
    assert [r.fetch_add(k0, 1) for _ in range(2)] == [47, 48], \
        "replicated state missing on the rejoined successor"
    # era 3: shard 0 restarts in place — THE regression window: every
    # record it now acks must land above shard 1's fence
    rejoin(0)
    assert [r.fetch_add(k0, 1) for _ in range(5)] == list(range(49, 54))
    blobs = [b"era3-%d" % i * 30 for i in range(6)]
    assert all(n >= 1 for n in r.append_bytes_many([box0] * len(blobs),
                                                   blobs))
    # era 4: shard 0 dies AGAIN — everything it acked in era 3 must
    # drain from shard 1, byte for byte
    np0, _ = repl_pair[0]
    np0.send_signal(signal.SIGKILL)
    np0.wait()
    assert [r.fetch_add(k0, 1) for _ in range(2)] == [54, 55], \
        "era-3 counter records were dropped by a stale replication fence"
    drained = [bytes(x) for lst in r.take_bytes_many([box0]) for x in lst]
    assert drained == blobs, (
        f"era-3 deposits lost across the second death: {len(drained)}/"
        f"{len(blobs)} records survived (stale repl_fence ate the "
        "rejoined shard's WAL stream)")
    r.close()


def test_repl_degraded_stream_not_rearmed_by_diagnostic_snapshot(repl_pair):
    """A snapshot pull that is NOT the stream receiver's rejoin catch-up
    (a diagnostic unfiltered pull, or a rejoiner fetching its OWN
    keyspace) must leave a degraded stream degraded: the real receiver
    never loads that cut, so resuming would hide the degrade-era drops
    as a silent mid-stream gap."""
    from bluefog_tpu.runtime.router import ShardRouter

    (p0, _), (_, port1) = repl_pair
    r = ShardRouter(_endpoints(repl_pair), 0, streams=1)
    r.put("dg.seed", 1)
    p0.send_signal(signal.SIGKILL)   # shard 1's successor dies
    p0.wait()
    k1 = next(f"dg.k.{j}" for j in range(64)
              if r.shard_of(f"dg.k.{j}") == 1)
    deadline = time.monotonic() + 10
    degraded = False
    while time.monotonic() < deadline and not degraded:
        r.put(k1, 1)   # traffic so the survivor notices its dead successor
        degraded = any(st is not None and st["repl_status"] == 2
                       for _, st in r.server_stats_all())
        time.sleep(0.05)
    assert degraded, "survivor never degraded"
    cl = native.ControlPlaneClient("127.0.0.1", port1, 0, streams=1)
    assert len(cl.snapshot()) >= 16        # diagnostic unfiltered pull
    assert len(cl.snapshot(2, 1)) >= 16    # own-keyspace (non-receiver)
    cl.close()
    # read stats BEFORE any further write: an erroneous re-arm is only
    # observable until the next record send re-degrades the stream (the
    # write it drops in between is exactly the silent gap at stake)
    for _, st in r.server_stats_all():
        if st is not None:
            assert st["repl_status"] == 2, \
                "a non-receiver snapshot pull re-armed the degraded stream"
    r.close()


def test_repl_newline_key_survives_kill(repl_pair):
    """Control-plane keys embed user-derived queue/collective names — a
    '\\n' in one must not corrupt the WAL batch framing (keys ride the
    record body, length-prefixed): every record in the batch must land
    on its own key on the replica."""
    (_, port0), (p1, port1) = repl_pair
    cl = native.ControlPlaneClient("127.0.0.1", port1, 0, streams=1)
    nl = "nl.q.job\nevil"
    cl.put(nl, 77)
    assert cl.append_bytes(nl + ".box", b"payload-1" * 20) == 1
    # rides the same replicator batch window as the newline records: a
    # mis-split would shift these onto the wrong keys
    cl.put("nl.plain", 88)
    assert cl.append_bytes("nl.plain.box", b"payload-2" * 20) == 1
    cl.close()
    p1.send_signal(signal.SIGKILL)
    p1.wait()
    sv = native.ControlPlaneClient("127.0.0.1", port0, 0, streams=1)
    assert sv.get(nl) == 77
    assert sv.get("nl.plain") == 88
    assert [bytes(x) for x in sv.take_bytes(nl + ".box")] == \
        [b"payload-1" * 20]
    assert [bytes(x) for x in sv.take_bytes("nl.plain.box")] == \
        [b"payload-2" * 20]
    sv.close()


def test_repl_serve_trace_survives_shard_kill_failover(repl_pair,
                                                       monkeypatch):
    """r21 satellite: a TRACED serve client rides a shard SIGKILL ->
    rejoin failover with an UNBROKEN request trace. Requests keep
    completing against the held snapshot through the outage, the ring
    records a ``serve.failover`` span (opened on the first failed stripe
    pull, closed when the rejoined shard answers), the client swaps to
    the post-failover version, and the snapshot lineage still resolves
    to its exact producing train step."""
    from bluefog_tpu.runtime import flight
    from bluefog_tpu.runtime.router import ShardRouter
    from bluefog_tpu.serving import snapshot as snap
    from bluefog_tpu.serving.client import ServeClient

    monkeypatch.setenv("BLUEFOG_TRACE_SERVE", "1")
    monkeypatch.setenv("BLUEFOG_SERVE_POLL_S", "0.05")
    flight.reset_for_job()
    eps = _endpoints(repl_pair)
    pub_r = ShardRouter(eps, 0, streams=1)
    pub = snap.SnapshotPublisher(pub_r, shards=4)
    pub.publish([np.full(500, 1.0, np.float32)], 1, step=1)
    sc = ServeClient(eps, model_fn=lambda params, xs: xs + params[0][0])
    try:
        assert sc.wait_ready(timeout=15), "first snapshot never pulled"
        out = sc.infer(np.zeros(2, np.float32), timeout=10)
        np.testing.assert_array_equal(out, np.ones(2, np.float32))
        proc, port = repl_pair[1]
        proc.send_signal(signal.SIGKILL)
        proc.wait()
        # the WAL-replicated survivor keeps committing versions while
        # half the client's stripe-pull groups point at a corpse
        pub.publish([np.full(500, 2.0, np.float32)], 2, step=2)
        deadline = time.monotonic() + 20
        while sc.stats()["pull_failures"] == 0 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert sc.stats()["pull_failures"] >= 1, \
            "the kill never surfaced as a failed stripe pull"
        # trace continuity: requests complete on the held snapshot
        # DURING the outage (same traced ring, no gap)
        out = sc.infer(np.zeros(2, np.float32), timeout=10)
        assert float(out[0]) >= 1.0
        # rejoin in place: the bulk pullers re-dial, the open failover
        # span closes on the next successful pull
        nproc, nport = _spawn_shard_repl(1, port=port, rejoin=True)
        repl_pair[1] = (nproc, nport)
        ring = ",".join(f"127.0.0.1:{p}"
                        for p in (repl_pair[0][1], port))
        nproc.stdin.write(f"BF_SHARD_PEERS {ring}\n")
        nproc.stdin.flush()
        assert nproc.stdout.readline().startswith("BF_SHARD_READY")
        pub.publish([np.full(500, 3.0, np.float32)], 3, step=3)
        deadline = time.monotonic() + 25
        while sc.version() < 3 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert sc.version() >= 3, "client never swapped past the failover"
        out = sc.infer(np.zeros(2, np.float32), timeout=10)
        assert float(out[0]) >= 3.0
        rep = flight.serve_report()
        assert rep is not None and rep["requests"] >= 3, \
            "request traces broke across the kill"
        assert rep["failovers"] >= 1, \
            "no closed serve.failover span in the ring"
        lin = snap.read_lineage(pub_r, 3)
        assert lin is not None and lin["step"] == 3, \
            "lineage must survive the failover and name the exact step"
    finally:
        sc.close()
        pub_r.close()
        flight.reset_for_job()


def test_repl_published_row_survives_kill_mid_publish(repl_pair):
    """ISSUE r17 satellite: published window rows (raw byte values,
    ``kPutBytes``) ride the WAL now — SIGKILL the shard right after a
    publish acks, and the promoted ring successor serves the row BYTE
    FOR BYTE. Before this record class a shard death lost the exposed
    window until the owner's next publish (ROADMAP "replicating
    published window rows"); win_get pulls and rejoin donor reads hit
    that gap. Both publish shapes are pinned: the single-message
    kPutBytes and the striped kPutBytesPart assembly (which replicates
    as ONE record at the stripe that completed the value)."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(repl_pair), 0, streams=4)
    rng = np.random.default_rng(_seed(47))
    small_key = next(f"w.pub.self.{j}" for j in range(64)
                     if r.shard_of(f"w.pub.self.{j}") == 1)
    big_key = next(f"w.pub.big.{j}" for j in range(64)
                   if r.shard_of(f"w.pub.big.{j}") == 1)
    small = bytes(rng.integers(0, 256, size=200_000, dtype=np.uint8))
    # above the stripe threshold: fans out as kPutBytesPart stripes
    big = bytes(rng.integers(0, 256, size=5 << 20, dtype=np.uint8))
    r.put_bytes(small_key, small)
    r.put_bytes(big_key, big)
    proc, _ = repl_pair[1]
    proc.send_signal(signal.SIGKILL)  # dies holding both published rows
    proc.wait()
    assert bytes(r.get_bytes(small_key)) == small, \
        "published row lost across the kill (kPutBytes not replicated)"
    assert bytes(r.get_bytes(big_key)) == big, \
        "striped published row lost across the kill"
    assert r.dead_shards() == {1}
    r.close()


def test_repl_failover_primary_sweeps_adopted_keyspace_on_attach():
    """Incarnation-GC scope under failover: a direct kAttach on a
    replicating shard must also sweep mailboxes of a keyspace it serves
    as FAILOVER primary (its preferred shard is dead and will never WAL
    the sweep) — otherwise a churned client's stale deposits linger and
    the owner can drain them, exactly what incarnation GC prevents."""
    s0 = native.ControlPlaneServer(1, _free_port())
    s1 = native.ControlPlaneServer(1, _free_port())
    try:
        s0.set_successor("127.0.0.1", s1.port, 2, 0)
        s1.set_successor("127.0.0.1", s0.port, 2, 1)
        from bluefog_tpu.runtime.router import _fnv64
        box = next(f"fg.box.{j}" for j in range(64)
                   if _fnv64(f"fg.box.{j}") % 2 == 1)
        # rank 3 (incarnation 1) registers on BOTH shards — what a
        # router's per-shard attach does — and deposits into a shard-1
        # box; chain commit replicates the record to shard 0
        dep0 = native.ControlPlaneClient("127.0.0.1", s0.port, 3,
                                         streams=1, incarnation=1)
        dep1 = native.ControlPlaneClient("127.0.0.1", s1.port, 3,
                                         streams=1, incarnation=1)
        dep1.append_bytes_tagged_many(
            [box], [b"stale-parameters"], [(((3 & 0x7F) << 32) | 1) << 24])
        assert s0.mailbox_records_from(3) == 1   # the replica copy
        # shard 1 dies; a router publishes the odd liveness generation
        s1.stop()
        cl0 = native.ControlPlaneClient("127.0.0.1", s0.port, 0, streams=1)
        cl0.put_max("bf.cp.shard_dead.1", 1)
        # churn: rank 3 restarts and attaches DIRECTLY to the failover
        # primary — the dead preferred shard can never WAL this sweep
        fresh = native.ControlPlaneClient("127.0.0.1", s0.port, 3,
                                          streams=1, incarnation=2)
        assert s0.mailbox_records_from(3) == 0, \
            "failover-adopted keyspace kept the dead incarnation's deposits"
        fresh.close()
        cl0.close()
        dep0.close()
        dep1.close()
    finally:
        s1.stop()
        s0.stop()


def test_single_endpoint_plane_r8_semantics_pinned(monkeypatch):
    """Satellite regression pin: an UNSHARDED (single-endpoint) plane
    keeps the r8 lease/force-release behavior byte-identical — no WAL
    machinery engages (repl_status 0), a lease expiry wakes the waiter
    with PeerLostError, the broken holder's unlock reports
    PeerLostError, and a connection-close force-releases instantly."""
    monkeypatch.setenv("BLUEFOG_CP_LOCK_LEASE", "1.0")
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        assert srv.stats()["repl_status"] == 0
        holder = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                           streams=1)
        waiter = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                           streams=1)
        holder.lock("pin.lease")
        t0 = time.monotonic()
        with pytest.raises(native.PeerLostError):
            waiter.lock("pin.lease")   # lease expiry wakes it typed
        assert time.monotonic() - t0 < 30
        with pytest.raises(native.PeerLostError):
            holder.unlock("pin.lease")  # broken critical section, typed
        # connection-close force-release: instant (not lease-bound), and
        # the blocked waiter wakes TYPED — it never silently inherits the
        # possibly-torn critical section; the lock is left free and a
        # fresh acquire succeeds
        holder.lock("pin.close")
        closer = threading.Thread(target=lambda: (time.sleep(0.3),
                                                  holder.close()),
                                  daemon=True)
        closer.start()
        t0 = time.monotonic()
        with pytest.raises(native.PeerLostError):
            waiter.lock("pin.close")   # woken the moment the fd closes
        assert time.monotonic() - t0 < 5
        waiter.lock("pin.close")       # left free: clean re-acquire
        waiter.unlock("pin.close")
        closer.join()
        waiter.close()
        assert srv.stats()["wal_enqueued"] == 0
    finally:
        srv.stop()


def test_repl_kill_with_undrained_mailboxes_mid_optimizer(monkeypatch):
    """Chaos demo (acceptance): a hosted-window job over a REPLICATED
    shard pair wins a SIGKILL landing while deposit mailboxes are
    NON-EMPTY — win_put deposits to every out-neighbor, the shard dies
    undrained, and win_update drains everything from the promoted
    successor: the all-rank result matches the numpy oracle exactly
    (zero lost deposits — a lost record would break the average)."""
    import bluefog_tpu as bf
    import jax.numpy as jnp

    from conftest import cpu_devices

    servers = [_spawn_shard_repl(i) for i in range(2)]
    _finish_repl_spawn(servers)
    try:
        eps = ",".join(f"127.0.0.1:{p}" for _, p in servers)
        for k, v in {
            "BLUEFOG_CP_HOSTS": eps,
            "BLUEFOG_CP_WORLD": "1",
            "BLUEFOG_CP_RANK": "0",
            "BLUEFOG_CP_BACKOFF_MS": "20",
            "BLUEFOG_WIN_PLANE": "hosted",
            "BLUEFOG_WIN_HOST_PLANE": "1",
        }.items():
            monkeypatch.setenv(k, v)
        cp.reset_for_test()
        bf.init(devices=cpu_devices(8))
        assert cp.active()
        xs = (np.arange(16, dtype=np.float64) ** 2).reshape(8, 2)
        x = jnp.asarray(xs, jnp.float32)
        assert bf.win_create(x, "r16.demo")
        try:
            bf.win_put(x, "r16.demo")   # deposits queued, NOT drained
            proc, _ = servers[1]
            proc.send_signal(signal.SIGKILL)  # dies with full mailboxes
            proc.wait()
            got = np.asarray(bf.win_update("r16.demo"))
            topo = bf.load_topology()
            want = np.zeros_like(xs)
            for rk in range(8):
                nbrs = bf.topology_util.in_neighbor_ranks(topo, rk)
                want[rk] = (xs[rk] + sum(xs[s] for s in nbrs)) / (
                    len(nbrs) + 1)
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
            assert cp.client().dead_shards() == {1}
        finally:
            bf.win_free("r16.demo")
    finally:
        bf.shutdown()
        cp.reset_for_test()
        _stop_shards(servers)


# ---------------------------------------------------------------------------
# quorum durability (r20): replication factor R, correlated-failure
# survival, partition-aware fencing
# ---------------------------------------------------------------------------

def _quorum_warm(r, deadline_s: float = 25.0) -> None:
    """Drive writes until the survivor re-admits them. After a correlated
    R-1 kill the survivor's WAL targets pass through SUSPECT before the
    definitive socket errors classify them DOWN; mutating ops in that
    window are rejected typed, and only the DOWN verdicts shrink the
    effective quorum back under what is still standing."""
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            r.put("bf.t.quorum.warm", 1)
            return
        except native.QuorumLostError:
            assert time.monotonic() < deadline, \
                "survivor never re-admitted writes after the correlated kill"
            time.sleep(0.05)


@pytest.fixture()
def quorum_trio(monkeypatch):
    """Three real shard server PROCESSES at ``BLUEFOG_CP_REPLICATION=3``
    (SIGKILL-able): every acked record is committed on ALL three shards
    (quorum = 2 remote acks), so ANY two simultaneous deaths lose
    nothing. The env is set before the spawn so the children arm their
    quorum WAL streams AND the test process's routers walk the R-aware
    failover chain (two hops past a run of consecutive dead shards)."""
    monkeypatch.setenv("BLUEFOG_CP_BACKOFF_MS", "20")
    monkeypatch.setenv("BLUEFOG_CP_REPLICATION", "3")
    servers = [_spawn_shard_repl(i) for i in range(3)]
    _finish_repl_spawn(servers)
    yield servers
    native.fault_disarm()
    _stop_shards(servers)


def test_quorum_pair_kill_zero_loss(quorum_trio):
    """THE r20 tentpole acceptance: SIGKILL a shard AND its ring
    successor in the same instant — the r16 chain's unsurvivable case
    (both copies of the dead shard's keyspace gone). At R=3 the acked
    state lives on all three shards, so the single survivor serves every
    undrained deposit byte for byte and continues the counter with
    exactly-once semantics across BOTH deaths; the router walks the
    two-hop failover chain past the run of consecutive dead shards."""
    from bluefog_tpu.runtime.router import ShardRouter

    r = ShardRouter(_endpoints(quorum_trio), 0, streams=1)
    rng = np.random.default_rng(_seed(53))
    # an undrained mailbox on EACH doomed shard + a counter on shard 1
    boxes = {s: next(f"qp.box.{j}" for j in range(64)
                     if r.shard_of(f"qp.box.{j}") == s) for s in (1, 2)}
    ctr = next(f"qp.ctr.{j}" for j in range(64)
               if r.shard_of(f"qp.ctr.{j}") == 1)
    blobs = {s: [bytes(rng.integers(0, 256,
                                    size=int(rng.integers(200, 4000)),
                                    dtype=np.uint8)) for _ in range(8)]
             for s in (1, 2)}
    for s in (1, 2):
        assert all(rep >= 1 for rep in
                   r.append_bytes_many([boxes[s]] * 8, blobs[s]))
    assert [r.fetch_add(ctr, 1) for _ in range(20)] == list(range(20))
    p1, _ = quorum_trio[1]
    p2, _ = quorum_trio[2]
    p1.send_signal(signal.SIGKILL)   # the shard AND its ring successor,
    p2.send_signal(signal.SIGKILL)   # dying with full mailboxes
    p1.wait()
    p2.wait()
    _quorum_warm(r)
    assert [r.fetch_add(ctr, 1) for _ in range(20)] == list(range(20, 40)), \
        "counter not exactly-once across the correlated pair kill"
    for s in (1, 2):
        drained = [bytes(x) for lst in r.take_bytes_many([boxes[s]])
                   for x in lst]
        assert drained == blobs[s], (
            f"shard {s}: lost deposits across the pair kill — "
            f"{len(drained)}/{len(blobs[s])} records survived")
    assert r.dead_shards() == {1, 2}
    r.close()


def test_quorum_kill_pair_mid_optimizer_oracle_exact(monkeypatch):
    """Chaos demo (acceptance): a hosted-window job over THREE quorum-
    replicated shards (R=3) loses a shard and its ring successor in the
    same instant while every deposit mailbox is NON-EMPTY — win_put
    queued deposits across all three shards, nothing drained. win_update
    must drain everything from the single survivor: the all-rank result
    matches the fault-free numpy oracle EXACTLY (a lost record would
    break the uniform average), and both deaths are reported typed."""
    import bluefog_tpu as bf
    import jax.numpy as jnp

    from conftest import cpu_devices

    monkeypatch.setenv("BLUEFOG_CP_REPLICATION", "3")
    monkeypatch.setenv("BLUEFOG_CP_BACKOFF_MS", "20")
    servers = [_spawn_shard_repl(i) for i in range(3)]
    _finish_repl_spawn(servers)
    try:
        eps = ",".join(f"127.0.0.1:{p}" for _, p in servers)
        for k, v in {
            "BLUEFOG_CP_HOSTS": eps,
            "BLUEFOG_CP_WORLD": "1",
            "BLUEFOG_CP_RANK": "0",
            "BLUEFOG_WIN_PLANE": "hosted",
            "BLUEFOG_WIN_HOST_PLANE": "1",
        }.items():
            monkeypatch.setenv(k, v)
        cp.reset_for_test()
        bf.init(devices=cpu_devices(8))
        assert cp.active()
        assert getattr(cp.client(), "shard_count", 1) == 3
        xs = (np.arange(16, dtype=np.float64) ** 2).reshape(8, 2)
        x = jnp.asarray(xs, jnp.float32)
        assert bf.win_create(x, "r20.demo")
        try:
            bf.win_put(x, "r20.demo")   # deposits queued, NOT drained
            for s in (1, 2):
                doomed, _ = servers[s]
                doomed.send_signal(signal.SIGKILL)
            for s in (1, 2):
                servers[s][0].wait()
            _quorum_warm(cp.client())
            got = np.asarray(bf.win_update("r20.demo"))
            topo = bf.load_topology()
            want = np.zeros_like(xs)
            for rk in range(8):
                nbrs = bf.topology_util.in_neighbor_ranks(topo, rk)
                want[rk] = (xs[rk] + sum(xs[s] for s in nbrs)) / (
                    len(nbrs) + 1)
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
            assert cp.client().dead_shards() == {1, 2}
        finally:
            bf.win_free("r20.demo")
    finally:
        bf.shutdown()
        cp.reset_for_test()
        _stop_shards(servers)


def test_quorum_partition_minority_typed_rejection():
    """Partition fencing, in-process: four quorum-replicated servers
    (R=3) under an asymmetric 3|1 cut. Ring geometry decides survival —
    only shard 0 keeps BOTH ring successors (1, 2) on its side; shards 1
    and 2 each lose one WAL stream across the cut and shard 3 (the true
    minority) loses both, so all three fall below the 2-ack commit
    quorum and degrade to READ-ONLY with the typed error while shard 0
    serves uninterrupted. A cut classifies targets SUSPECT, never DOWN
    (the relaxation that would split-brain a symmetric cut), so the
    quorum requirement never shrinks while the cut stands. Healing lets
    the idle-probe dials reconnect the streams and every shard
    re-admits writes, with the cut trail preserved in the cumulative
    ``partition_rejects`` counter."""
    servers = [native.ControlPlaneServer(1, _free_port())
               for _ in range(4)]
    cls = []
    try:
        ports = [s.port for s in servers]
        for i, s in enumerate(servers):
            s.set_successors(
                [((i + k) % 4, "127.0.0.1", ports[(i + k) % 4])
                 for k in (1, 2)], 4, i)
        cls = [native.ControlPlaneClient("127.0.0.1", p, 0, streams=1)
               for p in ports]
        for i, cl in enumerate(cls):
            cl.put(f"mn.seed.{i}", i + 10)
        for i, s in enumerate(servers):
            assert s.stats()["quorum_state"] == 1, f"shard {i} not at quorum"
        native.partition_arm({ports[0]: 0, ports[1]: 0, ports[2]: 0,
                              ports[3]: 1})
        assert native.partition_active()

        def drive_until_fenced(i: int) -> str:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                try:
                    cls[i].put(f"mn.k{i}", 1)
                    time.sleep(0.05)
                except native.QuorumLostError as exc:
                    return str(exc)
            raise AssertionError(f"shard {i} never fenced its writes")

        msg = drive_until_fenced(3)
        assert "quorum" in msg
        # below quorum is READ-ONLY, not dead: reads stay served
        assert cls[3].get("mn.seed.3") == 13
        for i in (1, 2):
            drive_until_fenced(i)
        # shard 0 never notices: both its streams are on-side
        for n in range(20):
            cls[0].put("mn.k0", n)
        assert cls[0].get("mn.k0") == 19
        st = [s.stats() for s in servers]
        assert st[0]["quorum_state"] == 1
        assert [st[i]["quorum_state"] for i in (1, 2, 3)] == [2, 2, 2]
        assert sum(s["partition_rejects"] for s in st) >= 3
        assert native.partition_cuts() > 0
        native.partition_heal()
        assert not native.partition_active()
        for i in (1, 2, 3):
            deadline = time.monotonic() + 20
            while True:
                try:
                    cls[i].put(f"mn.heal{i}", i)
                    break
                except native.QuorumLostError:
                    assert time.monotonic() < deadline, \
                        f"shard {i} never re-admitted writes after the heal"
                    time.sleep(0.05)
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(
                s.stats()["quorum_state"] != 1 for s in servers):
            time.sleep(0.05)
        assert all(s.stats()["quorum_state"] == 1 for s in servers), \
            "a shard stayed below quorum after the heal"
        # the reject counter is a cumulative trail, not live state
        # (bfrun --status --strict keys off quorum_state, not this)
        assert sum(s.stats()["partition_rejects"] for s in servers) >= 3
    finally:
        native.partition_disarm()
        for cl in cls:
            cl.close()
        for s in servers:
            s.stop()


def test_quorum_partition_heal_exactly_once_counter(monkeypatch):
    """End-to-end partition-then-heal over real shard PROCESSES: four
    shards at R=3 arm the deterministic injector from a server-only
    ``BLUEFOG_CP_FAULT`` partition spec (the cp_soak --partition wire).
    At a symmetric 2|2 cut EVERY shard has a successor on each side, so
    all four fall below quorum — the client sees typed rejections, and
    because the gate fires BEFORE the mutation, a rejection consumes
    NOTHING: once the cut self-heals, the fetch_add cursor continues
    exactly where the successes left off. The full success sequence must
    be one contiguous range — a gap is a rejected-but-applied op, a
    repeat is a lost apply."""
    from bluefog_tpu.runtime.router import ShardRouter

    monkeypatch.setenv("BLUEFOG_CP_BACKOFF_MS", "20")
    monkeypatch.setenv("BLUEFOG_CP_REPLICATION", "3")
    # a WIDE cut window (10 s): a sanitizer build under full-suite load
    # can take several seconds to spawn + attach, and the cut clock
    # starts at server arming — the window must comfortably outlive it
    fault = "partition=0,1|2,3,part_after=2,heal_after=10"
    env = dict(os.environ, BLUEFOG_CP_FAULT=fault)
    servers = [_spawn_shard_repl(i, env=env) for i in range(4)]
    _finish_repl_spawn(servers)
    try:
        r = ShardRouter(_endpoints(servers), 0, streams=1)
        vals, rejects, post = [], 0, 0
        # healthy phase: drive the counter until the cut engages. The
        # replicator's idle-probe dials flip quorum_state server-side
        # without any client help, so an attach slow enough to miss the
        # whole pre-cut phase still synchronizes here instead of racing
        # the heal clock.
        engaged = False
        deadline = time.monotonic() + 90
        while not engaged and time.monotonic() < deadline:
            try:
                vals.append(r.fetch_add("ph.ctr", 1))
            except native.QuorumLostError:
                rejects += 1
                break
            engaged = any(st is not None and st["quorum_state"] == 2
                          for _, st in r.server_stats_all())
        assert engaged or rejects, "the injected cut never engaged"
        # fenced phase through the self-heal: rejections consume nothing
        deadline = time.monotonic() + 90
        while post < 25 and time.monotonic() < deadline:
            try:
                vals.append(r.fetch_add("ph.ctr", 1))
                if rejects:
                    post += 1
            except native.QuorumLostError:
                rejects += 1
                time.sleep(0.05)
        assert rejects, "no typed rejection while below quorum"
        assert post >= 25, "writes never resumed after the self-heal"
        assert vals == list(range(len(vals))), \
            "fetch_add not exactly-once across the partition episode"
        # the episode left a server-side trail; the cluster healed above
        # quorum (drive a little traffic while the streams re-arm)
        deadline = time.monotonic() + 20
        healed = False
        while time.monotonic() < deadline and not healed:
            try:
                r.put("ph.tick", 1)
            except native.QuorumLostError:
                pass  # a sibling shard's streams may re-arm a beat later
            stats = [st for _, st in r.server_stats_all() if st is not None]
            healed = (len(stats) == 4 and
                      all(st["quorum_state"] == 1 for st in stats))
            time.sleep(0.1)
        assert healed, "a shard stayed below quorum after the heal"
        assert sum(st["partition_rejects"] for _, st in r.server_stats_all()
                   if st is not None) > 0
        assert r.dead_shards() == set()
        r.close()
    finally:
        native.fault_disarm()
        _stop_shards(servers)


def test_quorum_r2_single_target_wire_identical_to_chain():
    """R=2 regression pin: ``set_successors`` with ONE target (what
    shard_server issues at the default ``BLUEFOG_CP_REPLICATION=2``) IS
    the r16 chain — same wire, quorum machinery disarmed. Drive an
    identical deterministic op sequence through a legacy
    ``set_successor`` ring and a single-target ``set_successors`` ring:
    the server telemetry must be IDENTICAL (every op/WAL counter, with
    ``quorum_state`` 0 and zero quorum acks on both) and the replica's
    snapshot blob byte-identical — any divergence means the quorum
    generalization changed the default wire."""
    def drive(wire):
        s0 = native.ControlPlaneServer(1, _free_port())
        s1 = native.ControlPlaneServer(1, _free_port())
        try:
            if wire == "chain":
                s0.set_successor("127.0.0.1", s1.port, 2, 0)
            else:
                s0.set_successors([(1, "127.0.0.1", s1.port)], 2, 0)
            cl = native.ControlPlaneClient("127.0.0.1", s0.port, 0,
                                           streams=1)
            for i in range(30):
                cl.put(f"pin.k{i}", i * 7)
            assert [cl.fetch_add("pin.ctr", 3) for _ in range(10)] == \
                [3 * i for i in range(10)]
            assert cl.put_max("pin.gen", 8) == 8
            assert cl.append_bytes("pin.box", b"record-" + bytes(64)) == 1
            cl.put_bytes("pin.row", b"\x01\x02" * 512)
            cl.close()
            # chain commit: client replies already waited for the ack.
            # Only the close itself is async — wait for the connection
            # reap so live_connections compares deterministically.
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline and \
                    s0.stats()["live_connections"]:
                time.sleep(0.02)
            st0, st1 = s0.stats(), s1.stats()
            assert st0["wal_acked"] == st0["wal_enqueued"] > 0
            assert st0["repl_status"] == 1
            assert st0["quorum_state"] == st1["quorum_state"] == 0, \
                "quorum machinery armed on the default R=2 wire"
            assert st0["quorum_acks"] == 0
            rep = native.ControlPlaneClient("127.0.0.1", s1.port, 1,
                                            streams=1)
            blob = bytes(rep.snapshot())
            rep.close()
            return st0, blob
        finally:
            s1.stop()
            s0.stop()

    chain_stats, chain_blob = drive("chain")
    quorum_stats, quorum_blob = drive("single-target")
    assert quorum_stats == chain_stats, \
        "R=2 single-target telemetry diverged from the legacy chain"
    assert quorum_blob == chain_blob, \
        "R=2 single-target replica snapshot diverged from the legacy chain"


# ---------------------------------------------------------------------------
# end-to-end quarantined rejoin through bf.init (subprocess)
# ---------------------------------------------------------------------------

def test_quarantined_rejoin_end_to_end(tmp_path):
    """Full lifecycle against one live server: run + checkpoint at
    incarnation 0, then 'respawn' with BLUEFOG_INCARNATION=1 — the rejoin
    attaches fenced, enters quarantine, restores the newest checkpoint
    (no remote donor in a world of one), adopts the step counter, resumes
    training, and publishes quarantine completion."""
    srv = native.ControlPlaneServer(1, _free_port())
    try:
        env = _scrubbed_env()
        env.update({
            "BLUEFOG_CP_HOST": "127.0.0.1",
            "BLUEFOG_CP_PORT": str(srv.port),
            "BLUEFOG_CP_RANK": "0",
            "BLUEFOG_CP_WORLD": "1",
            "BLUEFOG_CP_SERVE": "0",
            "BLUEFOG_WIN_HOST_PLANE": "1",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "BLUEFOG_CHECKPOINT_DIR": str(tmp_path),
        })

        def run(phase, extra):
            e = dict(env)
            e.update(extra)
            return subprocess.run(
                [sys.executable, str(TESTS / "_rejoin_child.py"), phase,
                 str(tmp_path)],
                env=e, capture_output=True, text=True, timeout=240)

        first = run("first", {})
        assert first.returncode == 0, first.stdout + first.stderr
        assert "FIRST_OK" in first.stdout
        assert srv.incarnation_of(0) == 0

        rejoin = run("rejoin", {"BLUEFOG_INCARNATION": "1"})
        assert rejoin.returncode == 0, rejoin.stdout + rejoin.stderr
        assert "REJOIN_OK" in rejoin.stdout
        assert srv.incarnation_of(0) == 1
    finally:
        srv.stop()


@pytest.mark.slow
def test_kill_and_respawn_mid_gossip_rejoins():
    """4 controllers under the elastic supervisor; controller 3 hard-exits
    mid-gossip and is respawned with BLUEFOG_INCARNATION=1. Survivors must
    detect the death, keep bounded steps on the shrunken graph, then
    observe RE-ADMISSION once the respawn's quarantined state transfer
    (push-sum donor mass split) completes; the rejoiner must train on.
    Needs a jax build with CPU multiprocess collectives (slow-marked; the
    control-plane half is covered by the fast tests above)."""
    port = _free_port()
    env = _scrubbed_env()
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.2"
    env["BLUEFOG_HEARTBEAT_TIMEOUT"] = "1.5"
    env["BLUEFOG_CP_LOCK_LEASE"] = "20"
    env["BLUEFOG_CP_QUARANTINE_TIMEOUT"] = "60"

    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:4", "--elastic=1",
         "--coordinator", f"127.0.0.1:{port}", "--simulate", "2",
         "--", sys.executable, str(TESTS / "_elastic_gossip_child.py")],
        env=env, capture_output=True, text=True, timeout=420)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "respawning as incarnation 1" in out.stderr
    assert f"REJOINED {3} inc=1" in out.stdout
    assert "REJOIN_STEPS_OK 3" in out.stdout
    for i in range(3):
        assert f"DEAD_DETECTED {i}" in out.stdout, out.stdout
        assert f"READMITTED {i}" in out.stdout, out.stdout
        assert f"SURVIVOR_STEPS_OK {i}" in out.stdout, out.stdout
