"""Deterministic chaos: the control plane under injected faults (ISSUE r8).

The fault layer (``BLUEFOG_CP_FAULT`` / ``native.fault_arm``) makes
connection drops, truncated requests, lost replies, and slow peers
reproducible in-process, so every fault-tolerance behavior is a plain unit
test:

  * reconnecting transport — striped put/get round-trips and multi-round
    deposit/drain cycles are BIT-IDENTICAL to the fault-free run while
    connections are being killed under them (the acceptance criterion);
  * exactly-once non-idempotent ops — fetch_add under drops never
    double-applies (server-side per-client op-sequence dedup);
  * leased blocking primitives — dead lock holders, lease expiry, and
    barrier deadlines wake waiters with a typed ``PeerLostError`` instead
    of hanging (no wait path is unbounded);
  * the fault layer itself is OFF by default, so benches are unaffected.

The 4-process SIGKILL-mid-gossip end-to-end lives in
``test_kill_peer_mid_gossip_self_heals`` (slow-marked), reusing the
``tests/_fault_child.py`` launcher machinery via ``_gossip_fault_child.py``.
"""

import os
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import heartbeat, native

TESTS = Path(__file__).resolve().parent

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture(autouse=True)
def _fault_disarmed():
    """Every test starts AND ends with injection off (process-global state)."""
    native.fault_disarm()
    yield
    native.fault_disarm()


@pytest.fixture()
def server():
    srv = native.ControlPlaneServer(2, _free_port())
    yield srv
    native.fault_disarm()  # never let a slow-delay knob wedge teardown
    srv.stop()


# ---------------------------------------------------------------------------
# the fault layer itself
# ---------------------------------------------------------------------------

def test_fault_layer_off_by_default(server):
    """Benches must be unaffected: without BLUEFOG_CP_FAULT (or an explicit
    arm), no op is ever counted, dropped, or delayed."""
    assert "BLUEFOG_CP_FAULT" not in os.environ, \
        "test env leaked a fault spec"
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    for i in range(20):
        cl.put(f"off.{i}", i)
    assert cl.get("off.7") == 7
    assert native.fault_stats() == {"ops": 0, "drops": 0}
    cl.close()


def test_parse_fault_spec_grammar():
    assert native.parse_fault_spec("drop_after=37,delay_ms=50,trunc=1,seed=7") \
        == {"drop_after": 37, "delay_ms": 50, "trunc": 1, "seed": 7}
    assert native.parse_fault_spec("drop_after=5") == \
        {"drop_after": 5, "delay_ms": 0, "trunc": 0, "seed": 0}
    assert native.parse_fault_spec("")["drop_after"] == 0
    with pytest.raises(ValueError):
        native.parse_fault_spec("drop_every=5")
    with pytest.raises(ValueError):
        native.parse_fault_spec("drop_after")


# ---------------------------------------------------------------------------
# reconnecting transport: exactly-once + bit-identical under drops
# ---------------------------------------------------------------------------

def test_fetch_add_exactly_once_under_drops(server):
    """Non-idempotent ops must never double-apply across retries: a reply
    lost in flight is replayed from the server's per-client dedup table."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    native.fault_arm("drop_after=4,seed=1")
    seen = [cl.fetch_add("ctr", 1) for _ in range(40)]
    drops = native.fault_stats()["drops"]
    native.fault_disarm()
    assert drops >= 3, f"only {drops} drops injected"
    # pre-add values are exactly 0..39: no add lost, none applied twice
    assert seen == list(range(40))
    assert cl.get("ctr") == 40
    cl.close()


def test_batched_fetch_add_exactly_once_under_drops(server):
    """The pipelined batch path (fetch_add_many — the hosted version-bump
    hot path) resends whole batches under one seq; the server replays the
    applied prefix."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    native.fault_arm("drop_after=3,seed=0,trunc=1")
    total = 0
    for _ in range(12):
        pre = cl.fetch_add_many(["a", "b", "c"], deltas=[1, 2, 3])
        assert pre == [total, 2 * total, 3 * total], (pre, total)
        total += 1
    drops = native.fault_stats()["drops"]
    native.fault_disarm()
    assert drops >= 3
    assert cl.get_many(["a", "b", "c"]) == [12, 24, 36]
    cl.close()


def _striped_roundtrip(port: int, streams: int, rounds: int = 10):
    """put_bytes/get_bytes cycle of striping-sized payloads; returns the
    bytes read back each round (for cross-run comparison)."""
    cl = native.ControlPlaneClient("127.0.0.1", port, 0, streams=streams)
    rng = np.random.default_rng(7)
    out = []
    for r in range(rounds):
        payload = rng.integers(0, 256, size=768 * 1024, dtype=np.uint8)
        cl.put_bytes(f"blob.{r % 2}", payload.tobytes())
        out.append(cl.get_bytes(f"blob.{r % 2}"))
    cl.close()
    return out


@pytest.mark.parametrize("streams", [4, 1])
def test_striped_roundtrip_bit_identical_under_drops(streams):
    """Acceptance: >= 3 connection drops across a multi-round striped
    put/get cycle, results bit-identical to the fault-free run. At
    streams=4 the payloads (above BLUEFOG_CP_STRIPE_MIN_MB=0.5 here) move
    as concurrent byte-range stripes over the pool; each pool connection
    reconnects and retries independently."""
    os.environ["BLUEFOG_CP_STRIPE_MIN_MB"] = "0.5"
    try:
        srv = native.ControlPlaneServer(2, _free_port())
        try:
            baseline = _striped_roundtrip(srv.port, streams)
            native.fault_arm("drop_after=3,seed=2,trunc=1")
            faulted = _striped_roundtrip(srv.port, streams)
            drops = native.fault_stats()["drops"]
            native.fault_disarm()
        finally:
            srv.stop()
        assert drops >= 3, f"only {drops} drops injected"
        assert len(baseline) == len(faulted)
        for b, f in zip(baseline, faulted):
            assert b == f, "striped round-trip diverged under faults"
    finally:
        del os.environ["BLUEFOG_CP_STRIPE_MIN_MB"]


def _deposit_drain_cycle(port: int, streams: int, rounds: int = 6):
    """Multi-round tagged deposit + drain over 3 mailbox keys; returns
    (per-round drained record lists, total bytes in, total bytes out)."""
    cl = native.ControlPlaneClient("127.0.0.1", port, 0, streams=streams)
    rng = np.random.default_rng(13)
    transcript, bytes_in, bytes_out = [], 0, 0
    seq = 0
    for r in range(rounds):
        names, blobs, tags = [], [], []
        for k in range(3):
            for rec in range(4):
                seq += 1
                body = rng.integers(0, 256, size=int(rng.integers(64, 2048)),
                                    dtype=np.uint8).tobytes()
                names.append(f"box.{k}")
                blobs.append(body)
                tags.append(seq << 24)  # header-index tags, single-record
                bytes_in += len(body)
        counts = cl.append_bytes_tagged_many(names, blobs, tags)
        assert all(c >= 1 for c in counts)
        drained = cl.take_bytes_many([f"box.{k}" for k in range(3)])
        # strip the server's 8-byte tag prefix; keep per-key record order
        recs = [[bytes(x)[8:] for x in lst] for lst in drained]
        bytes_out += sum(len(x) for lst in recs for x in lst)
        transcript.append(recs)
    cl.close()
    return transcript, bytes_in, bytes_out


@pytest.mark.parametrize("streams", [4, 1])
def test_deposit_drain_mass_conserved_under_drops(streams):
    """Acceptance: the deposit/drain cycle — the hosted window plane's wire
    discipline — conserves mass exactly under >= 3 injected drops, and the
    drained transcript is bit-identical to the fault-free run (lost take
    replies are replayed from the dedup record, never re-drained or lost)."""
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        base, base_in, base_out = _deposit_drain_cycle(srv.port, streams)
        assert base_in == base_out  # sanity: fault-free mass conservation
    finally:
        srv.stop()
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        native.fault_arm("drop_after=5,seed=3")
        got, got_in, got_out = _deposit_drain_cycle(srv.port, streams)
        drops = native.fault_stats()["drops"]
        native.fault_disarm()
    finally:
        srv.stop()
    assert drops >= 3, f"only {drops} drops injected"
    assert got_in == got_out == base_in, "deposit mass not conserved"
    assert got == base, "drained transcript diverged under faults"


def test_server_drop_conns_hook_reconnects(server):
    """The server-side kill hook severs every live connection; clients
    reconnect (re-handshaking) transparently on their next op."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    cl.put("pre.kill", 1)
    server.drop_connections()
    time.sleep(0.05)
    cl.put("post.kill", 2)  # transparent reconnect
    assert cl.get("pre.kill") == 1 and cl.get("post.kill") == 2
    cl.close()


def test_retries_zero_disables_reconnect(server, monkeypatch):
    """BLUEFOG_CP_RETRIES=0 is the strict legacy wire: a severed connection
    is a hard OSError, exactly the pre-r8 behavior."""
    monkeypatch.setenv("BLUEFOG_CP_RETRIES", "0")
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    cl.put("x", 1)
    server.drop_connections()
    time.sleep(0.05)
    with pytest.raises(OSError):
        cl.put("x", 2)
    cl.close()


# ---------------------------------------------------------------------------
# leased blocking primitives: no wait path is unbounded
# ---------------------------------------------------------------------------

def test_lock_dead_holder_wakes_waiter_typed(server):
    """A lock whose holder's connection closes is force-released with an
    epoch bump; the blocked waiter wakes with PeerLostError (not a silent
    grant, not a hang) and a fresh acquire then succeeds."""
    holder = native.ControlPlaneClient("127.0.0.1", server.port, 1, streams=1)
    waiter = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    holder.lock("L")
    result = {}

    def wait_for_lock():
        try:
            waiter.lock("L")
            result["outcome"] = "granted"
        except native.PeerLostError as exc:
            result["outcome"] = "peerlost"
            result["msg"] = str(exc)

    t = threading.Thread(target=wait_for_lock, daemon=True)
    t.start()
    time.sleep(0.4)
    assert "outcome" not in result, "waiter got the lock through a holder"
    holder.close()  # connection closes while holding -> force release
    t.join(10.0)
    assert result.get("outcome") == "peerlost", result
    assert "force-released" in result["msg"]
    waiter.lock("L")  # the lock was left free: re-acquire works
    waiter.unlock("L")
    waiter.close()


def test_lock_lease_expiry_and_broken_unlock(monkeypatch):
    """The lease is the backstop for a wedged-but-connected holder: a
    waiter force-releases the lock at expiry (PeerLostError), and the
    original holder's eventual unlock reports the broken section instead
    of silently succeeding."""
    monkeypatch.setenv("BLUEFOG_CP_LOCK_LEASE", "0.4")
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        holder = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                           streams=1)
        waiter = native.ControlPlaneClient("127.0.0.1", srv.port, 0,
                                           streams=1)
        holder.lock("M")
        t0 = time.monotonic()
        with pytest.raises(native.PeerLostError, match="force-released"):
            waiter.lock("M")
        assert time.monotonic() - t0 < 5.0  # bounded by the lease, not ∞
        waiter.lock("M")  # free after the force-release
        waiter.unlock("M")
        # the wedged holder finally releases: its section was broken
        with pytest.raises(native.PeerLostError, match="critical section"):
            holder.unlock("M")
        holder.close()
        waiter.close()
    finally:
        srv.stop()


def test_barrier_deadline_is_bounded(monkeypatch):
    """A barrier with an absent participant wakes at
    BLUEFOG_CP_BARRIER_TIMEOUT with PeerLostError instead of hanging."""
    monkeypatch.setenv("BLUEFOG_CP_BARRIER_TIMEOUT", "0.5")
    srv = native.ControlPlaneServer(2, _free_port())
    try:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, 0, streams=1)
        t0 = time.monotonic()
        with pytest.raises(native.PeerLostError, match="never arrived"):
            cl.barrier("lonely")
        assert time.monotonic() - t0 < 5.0
        # the timed-out arrival was withdrawn: a later full barrier works
        other = native.ControlPlaneClient("127.0.0.1", srv.port, 1,
                                          streams=1)
        done = []
        t = threading.Thread(target=lambda: done.append(cl.barrier("b2")),
                             daemon=True)
        t.start()
        other.barrier("b2")
        t.join(5.0)
        assert done, "paired barrier did not complete"
        cl.close()
        other.close()
    finally:
        srv.stop()


def test_barrier_survives_drop_and_retry(server):
    """A barrier participant whose connection drops mid-wait withdraws its
    arrival server-side; the transparent retry re-enters, and the barrier
    still completes exactly once for both parties."""
    a = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    b = native.ControlPlaneClient("127.0.0.1", server.port, 1, streams=1)
    results = {}

    def enter(name, cl):
        results[name] = cl.barrier("chaos.bar")

    ta = threading.Thread(target=enter, args=("a", a), daemon=True)
    ta.start()
    time.sleep(0.3)  # a is parked in the barrier wait
    server.drop_connections()  # severs a's (and b's idle) connection
    tb = threading.Thread(target=enter, args=("b", b), daemon=True)
    tb.start()
    ta.join(15.0)
    tb.join(15.0)
    assert results.get("a") == results.get("b") == 1, results
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# heartbeat stop() under an unresponsive control plane (satellite)
# ---------------------------------------------------------------------------

def test_heartbeat_stop_wedged_thread_no_double_close(server, monkeypatch):
    """The wedged-thread path in PeerMonitor.stop() ('leaving its
    connection open'): with the fault delay knob making every control-plane
    op multi-second, stop() must return at its 2 s join bound, must NOT
    close the native client under the live thread (use-after-free), and a
    second stop() is a no-op. After the delay clears the thread exits on
    its own."""
    cl = native.ControlPlaneClient("127.0.0.1", server.port, 0, streams=1)
    monkeypatch.setattr(cp, "_client", cl)
    monkeypatch.setattr(cp, "_conn_params",
                        ("127.0.0.1", server.port, 0, ""))
    mon = heartbeat.PeerMonitor(0, 2, interval_sec=0.05, timeout_sec=30.0)
    mon.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and not native.fault_stats()["ops"]:
        time.sleep(0.02)  # monitor thread is live and ticking
    native.fault_arm("delay_ms=1500")
    time.sleep(0.2)  # let the next tick park inside a delayed op
    thread = mon._thread
    assert thread is not None and thread.is_alive()
    t0 = time.monotonic()
    mon.stop()
    dt = time.monotonic() - t0
    assert dt < 10.0, f"stop() hung {dt:.1f}s on a wedged control plane"
    # wedged path: the dedicated connection is NOT closed under the thread
    assert mon._cl is None
    assert thread.is_alive(), "expected the tick to still be wedged"
    mon.stop()  # idempotent: no double-close of a shared native handle
    native.fault_disarm()
    thread.join(15.0)
    assert not thread.is_alive(), "wedged tick never drained after disarm"
    # the leaked-by-design connection is reclaimed at process exit only;
    # the SHARED client must still be usable (nothing closed it)
    assert cl.get("anything") == 0
    cl.close()


# ---------------------------------------------------------------------------
# attach() must not silently degrade a multi-process job (satellite)
# ---------------------------------------------------------------------------

def test_attach_raises_when_multiprocess_connect_fails(monkeypatch):
    dead_port = _free_port()  # nothing listens here
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(dead_port),
        "BLUEFOG_CP_WORLD": "2",
        "BLUEFOG_CP_RANK": "1",   # not the serving rank
        "BLUEFOG_CP_CONNECT_TIMEOUT": "0.5",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    try:
        with pytest.raises(RuntimeError, match="refusing to degrade"):
            cp.attach()
    finally:
        cp.reset_for_test()


def test_attach_soft_fallback_for_single_controller(monkeypatch):
    """world == 1 keeps the soft local fallback: a forced-env dev run
    without a reachable server degrades with a warning, not an error."""
    dead_port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(dead_port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_CP_SERVE": "0",
        "BLUEFOG_CP_CONNECT_TIMEOUT": "0.5",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    try:
        assert cp.attach() is None
        assert not cp.active()
    finally:
        cp.reset_for_test()


# ---------------------------------------------------------------------------
# hosted windows: mass conservation under drops (fast, in-process)
# ---------------------------------------------------------------------------

@pytest.fixture()
def bf_hosted_cp(monkeypatch):
    """bf over 8 CPU devices, forced control plane + hosted window plane."""
    import bluefog_tpu as bf
    from conftest import cpu_devices

    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active()
    yield bf
    native.fault_disarm()
    bf.shutdown()
    cp.reset_for_test()


def test_hosted_pushsum_mass_conserved_under_drops(bf_hosted_cp):
    """End-to-end through the window API: a push-sum accumulate/update
    cycle on the hosted plane keeps total mass and p mass EXACTLY
    conserved while the transport is dropping connections under it."""
    import jax.numpy as jnp

    bf = bf_hosted_cp
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = jnp.arange(8.0).reshape(8, 1) + 1.0
        assert bf.win_create(x, "chaos.ps", zero_init=True)
        topo = bf.load_topology()
        outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
                for r in range(8)}
        sw = {r: 1.0 / (outd[r] + 1) for r in range(8)}
        dw = {r: {d: 1.0 / (outd[r] + 1)
                  for d in bf.topology_util.out_neighbor_ranks(topo, r)}
              for r in range(8)}
        native.fault_arm("drop_after=15,seed=5")
        val = x
        for _ in range(4):
            bf.win_accumulate(val, "chaos.ps", self_weight=sw,
                              dst_weights=dw, require_mutex=True)
            val = bf.win_update_then_collect("chaos.ps")
            p = bf.win_associated_p_all("chaos.ps")
            assert abs(float(np.asarray(val).sum()) - 36.0) < 1e-3
            assert abs(p.sum() - 8.0) < 1e-9
        drops = native.fault_stats()["drops"]
        native.fault_disarm()
        assert drops >= 3, f"only {drops} drops injected"
        bf.win_free("chaos.ps")
    finally:
        bf.turn_off_win_ops_with_associated_p()


# ---------------------------------------------------------------------------
# self-healing gossip: dead ranks excluded, weights renormalized, retry-once
# ---------------------------------------------------------------------------

def test_gossip_weights_renormalize_around_dead_ranks(bf_hosted_cp,
                                                      monkeypatch):
    """The window optimizer consults the dead set EVERY gossip step: with
    ranks {6, 7} reported dead, sends to them stop, the combine weights
    renormalize to 1/(live_indegree + 1), and the mixed parameters match a
    numpy oracle of the shrunken-graph average exactly."""
    import jax.numpy as jnp
    import optax

    bf = bf_hosted_cp
    from bluefog_tpu.runtime import heartbeat as hb

    dead = {6, 7}
    monkeypatch.setattr(hb, "dead_ranks", lambda: set(dead))

    def loss_fn(params, batch):
        return jnp.sum((params["w"] - batch) ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf.shard_rank_stacked(
        bf.mesh(), np.arange(8, dtype=np.float32).reshape(8, 1))
    try:
        topo = bf.load_topology()
        in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
                   for r in range(8)}
        live_in = {r: [s for s in in_nbrs[r] if s not in dead]
                   for r in range(8)}
        w = np.zeros((8, 2), np.float64)  # oracle state
        for _ in range(2):
            state, _ = opt.step(state, batch)
            # oracle: per-rank sgd step, then the healed uniform average
            wl = w - 0.1 * 2.0 * (w - np.arange(8.0).reshape(8, 1))
            mixed = np.zeros_like(wl)
            for r in range(8):
                u = 1.0 / (len(live_in[r]) + 1)
                mixed[r] = u * (wl[r] + sum(wl[s] for s in live_in[r]))
            w = mixed
        got = np.asarray(state.params["w"])
        # live rows only: a dead rank's own row is don't-care (nobody
        # deposits to it and nobody reads it — live combines use only
        # live sources, which is exactly what this asserts)
        live = sorted(set(range(8)) - dead)
        np.testing.assert_allclose(got[live], w[live], rtol=1e-5, atol=1e-6)
        # live ranks never averaged with a dead rank's value: rank 6/7's
        # distinct targets (6.0/7.0) must not have leaked into rank 0's
        # combine beyond its live in-set
        assert not np.allclose(got[0], got[6])
    finally:
        opt.free()


def test_gossip_step_retries_after_dead_mutex_holder(bf_hosted_cp):
    """End-to-end PeerLostError recovery: an external actor dies while
    holding a window mutex the optimizer's hoisted acquisition needs. The
    blocked step must surface the force-release as PeerLostError
    internally, retry once, and COMPLETE — no hang, no leaked mutexes (a
    second step still acquires everything)."""
    import jax.numpy as jnp
    import optax

    bf = bf_hosted_cp
    port = int(os.environ["BLUEFOG_CP_PORT"])

    def loss_fn(params, batch):
        return jnp.sum(params["w"] ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))
    try:
        state, _ = opt.step(state, batch)  # healthy warm-up
        actor = native.ControlPlaneClient("127.0.0.1", port, rank=9,
                                          streams=1)
        actor.lock(f"w.{opt._win_names[0]}.mu.5")

        def die_holding():
            time.sleep(0.6)
            actor.close()  # connection closes while holding -> force release

        killer = threading.Thread(target=die_holding, daemon=True)
        killer.start()
        t0 = time.monotonic()
        state, _ = opt.step(state, batch)  # blocks, PeerLostError, retries
        assert time.monotonic() - t0 < 30
        killer.join(5.0)
        state, _ = opt.step(state, batch)  # no mutex leaked by the retry
    finally:
        opt.free()


# ---------------------------------------------------------------------------
# kill a peer mid-gossip: survivors renormalize and keep training (slow)
# ---------------------------------------------------------------------------

def _scrubbed_env():
    env = os.environ.copy()
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT", "BLUEFOG_CP_FAULT"):
        env.pop(k, None)
    env["PYTHONPATH"] = str(TESTS.parent) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


@pytest.mark.slow
def test_kill_peer_mid_gossip_self_heals():
    """4 controllers x 2 devices running window-optimizer gossip; controller
    3 is hard-killed MID-STEP. Every survivor must (a) detect {3} dead
    within the heartbeat timeout, (b) keep completing bounded gossip steps
    on the renormalized topology (dead ranks {6, 7} excluded), and (c)
    exit cleanly — the ISSUE's 'keeps training on the shrunken graph'
    acceptance, at the reference CI's np=4 scale."""
    port = _free_port()
    env = _scrubbed_env()
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.2"
    env["BLUEFOG_HEARTBEAT_TIMEOUT"] = "1.5"
    env["BLUEFOG_CP_LOCK_LEASE"] = "20"

    def cmd(i):
        return [sys.executable, "-m", "bluefog_tpu.launcher", "-np", "4",
                "--coordinator", f"127.0.0.1:{port}", "--process-id", str(i),
                "--simulate", "2",
                "--", sys.executable, str(TESTS / "_gossip_fault_child.py")]

    procs = [subprocess.Popen(cmd(i), env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(4)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=420)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert procs[3].returncode == 17, f"faulty process:\n{outs[3]}"
    for i in range(3):
        assert procs[i].returncode == 0, f"survivor {i} failed:\n{outs[i]}"
        assert f"DEAD_DETECTED {i}" in outs[i], outs[i]
        assert f"SURVIVOR_STEPS_OK {i}" in outs[i], outs[i]
        assert f"CHILD_OK {i}" in outs[i], outs[i]
    for i in range(4):
        assert f"HEALTHY {i}" in outs[i]
