"""Pipeline parallelism: GPipe schedule exactness vs the dense oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu.parallel import pipeline as pp
from bluefog_tpu.models import TransformerLM

from conftest import cpu_devices


def make_lm(layers=4, heads=2, d_model=16, d_ff=32, vocab=32, batch=4, seq=8):
    model = TransformerLM(vocab_size=vocab, num_layers=layers,
                          num_heads=heads, d_model=d_model, d_ff=d_ff)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, vocab)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params, tokens


@pytest.mark.parametrize("n_stages,n_micro", [
    pytest.param(4, 2, marks=pytest.mark.slow),
    (2, 4),
    pytest.param(8, 4, marks=pytest.mark.slow),
])
def test_pp_matches_single_device(n_stages, n_micro):
    model, params, tokens = make_lm(layers=8, batch=4)
    oracle = model.apply({"params": params}, tokens)
    mesh = pp.pp_mesh(n_stages, cpu_devices(8))
    out = pp.pp_apply(model, params, tokens, mesh, n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), atol=1e-4)


def test_pp_stage_stack_layout():
    model, params, tokens = make_lm(layers=4)
    stacked, rest = pp.pp_stack_params(params, 2)
    qkv = stacked["qkv"]["kernel"]
    # [n_stages, layers_per_stage, d_model, 3*d_model]
    assert qkv.shape == (2, 2, 16, 48)
    # stage 0 holds blocks 0-1 in order, stage 1 holds 2-3
    np.testing.assert_array_equal(
        np.asarray(qkv[1, 0]), np.asarray(params["block_2"]["qkv"]["kernel"]))
    assert set(rest) == {"embed", "final_norm", "lm_head"}


def test_pp_params_actually_distributed():
    model, params, tokens = make_lm(layers=8)
    mesh = pp.pp_mesh(4, cpu_devices(8))
    stacked, _ = pp.pp_stack_params(params, 4)
    placed = jax.device_put(
        stacked, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("pipe")))
    qkv = placed["qkv"]["kernel"]
    # each stage device holds exactly its [1, 2, ...] layer chunk
    assert {s.data.shape for s in qkv.addressable_shards} == \
        {(1,) + qkv.shape[1:]}


def test_pp_bad_layer_count_rejected():
    model, params, tokens = make_lm(layers=4)
    with pytest.raises(ValueError, match="multiple of"):
        pp.pp_stack_params(params, 3)


def test_pp_bad_microbatch_rejected():
    model, params, tokens = make_lm(layers=4, batch=4)
    mesh = pp.pp_mesh(2, cpu_devices(8))
    with pytest.raises(ValueError, match="microbatch"):
        pp.pp_apply(model, params, tokens, mesh, n_micro=3)


def test_pp_forward_fn_reuses_placed_params():
    model, params, tokens = make_lm(layers=4, batch=4)
    oracle = model.apply({"params": params}, tokens)
    mesh = pp.pp_mesh(2, cpu_devices(8))
    stacked, rest = pp.pp_stack_params(params, 2)
    placed = pp.pp_place_params(stacked, mesh)
    fwd = pp.pp_forward_fn(model, mesh, n_micro=2)
    out1 = fwd(placed, rest, tokens)
    out2 = fwd(placed, rest, tokens)  # second step: no restack, same program
    np.testing.assert_allclose(np.asarray(out1), np.asarray(oracle), atol=1e-4)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out1), atol=0)
