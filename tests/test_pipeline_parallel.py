"""Pipeline parallelism: GPipe schedule exactness vs the dense oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu.parallel import pipeline as pp
from bluefog_tpu.models import TransformerLM

from conftest import cpu_devices


def make_lm(layers=4, heads=2, d_model=16, d_ff=32, vocab=32, batch=4, seq=8):
    model = TransformerLM(vocab_size=vocab, num_layers=layers,
                          num_heads=heads, d_model=d_model, d_ff=d_ff)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, vocab)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params, tokens


@pytest.mark.parametrize("n_stages,n_micro", [
    pytest.param(4, 2, marks=pytest.mark.slow),
    (2, 4),
    pytest.param(8, 4, marks=pytest.mark.slow),
])
def test_pp_matches_single_device(n_stages, n_micro):
    model, params, tokens = make_lm(layers=8, batch=4)
    oracle = model.apply({"params": params}, tokens)
    mesh = pp.pp_mesh(n_stages, cpu_devices(8))
    out = pp.pp_apply(model, params, tokens, mesh, n_micro=n_micro)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), atol=1e-4)


def test_pp_stage_stack_layout():
    model, params, tokens = make_lm(layers=4)
    stacked, rest = pp.pp_stack_params(params, 2)
    qkv = stacked["qkv"]["kernel"]
    # [n_stages, layers_per_stage, d_model, 3*d_model]
    assert qkv.shape == (2, 2, 16, 48)
    # stage 0 holds blocks 0-1 in order, stage 1 holds 2-3
    np.testing.assert_array_equal(
        np.asarray(qkv[1, 0]), np.asarray(params["block_2"]["qkv"]["kernel"]))
    assert set(rest) == {"embed", "final_norm", "lm_head"}


def test_pp_params_actually_distributed():
    model, params, tokens = make_lm(layers=8)
    mesh = pp.pp_mesh(4, cpu_devices(8))
    stacked, _ = pp.pp_stack_params(params, 4)
    placed = jax.device_put(
        stacked, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec("pipe")))
    qkv = placed["qkv"]["kernel"]
    # each stage device holds exactly its [1, 2, ...] layer chunk
    assert {s.data.shape for s in qkv.addressable_shards} == \
        {(1,) + qkv.shape[1:]}


def test_pp_bad_layer_count_rejected():
    model, params, tokens = make_lm(layers=4)
    with pytest.raises(ValueError, match="multiple of"):
        pp.pp_stack_params(params, 3)


def test_pp_bad_microbatch_rejected():
    model, params, tokens = make_lm(layers=4, batch=4)
    mesh = pp.pp_mesh(2, cpu_devices(8))
    with pytest.raises(ValueError, match="microbatch"):
        pp.pp_apply(model, params, tokens, mesh, n_micro=3)


def test_pp_forward_fn_reuses_placed_params():
    model, params, tokens = make_lm(layers=4, batch=4)
    oracle = model.apply({"params": params}, tokens)
    mesh = pp.pp_mesh(2, cpu_devices(8))
    stacked, rest = pp.pp_stack_params(params, 2)
    placed = pp.pp_place_params(stacked, mesh)
    fwd = pp.pp_forward_fn(model, mesh, n_micro=2)
    out1 = fwd(placed, rest, tokens)
    out2 = fwd(placed, rest, tokens)  # second step: no restack, same program
    np.testing.assert_allclose(np.asarray(out1), np.asarray(oracle), atol=1e-4)
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out1), atol=0)


def test_pp_training_matches_single_device_loss_curve():
    """VERDICT-r2 #4: a 2-stage LM TRAINS through the pipeline — gradients
    flow through the whole GPipe scan (remat'd blocks, ppermute handoffs)
    and the loss curve tracks the single-device step step-for-step."""
    import optax

    model, params, tokens = make_lm(layers=2, batch=4, seq=8)
    targets = jnp.roll(tokens, -1, axis=1)
    mesh = pp.pp_mesh(2, cpu_devices(2))
    optimizer = optax.adam(1e-2)

    # single-device oracle step over the SAME init
    def dense_loss(p, batch):
        toks, tgts = batch
        logits = model.apply({"params": p}, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, tgts[..., None], axis=-1).mean()

    @jax.jit
    def dense_step(p, opt_state, batch):
        l, g = jax.value_and_grad(dense_loss)(p, batch)
        updates, opt_state = optimizer.update(g, opt_state, p)
        return optax.apply_updates(p, updates), opt_state, l

    stacked, rest, pp_opt = pp.pp_train_init(model, mesh, params, optimizer)
    pp_step = pp.pp_train_step_fn(model, mesh, optimizer, n_micro=2)

    dense_p, dense_opt = params, optimizer.init(params)
    batch = (tokens, targets)
    pp_losses, dense_losses = [], []
    for _ in range(8):
        stacked, rest, pp_opt, lp = pp_step(stacked, rest, pp_opt, batch)
        dense_p, dense_opt, ld = dense_step(dense_p, dense_opt, batch)
        pp_losses.append(float(lp))
        dense_losses.append(float(ld))
    # training works...
    assert pp_losses[-1] < pp_losses[0]
    # ...and matches the single-device curve step for step (same function,
    # same grads up to fp reassociation)
    np.testing.assert_allclose(pp_losses, dense_losses, rtol=2e-4, atol=2e-4)
    # the final pipelined params reproduce the dense model's forward
    logits_pp = pp.pp_forward_fn(model, mesh, n_micro=2)(stacked, rest,
                                                         tokens)
    logits_dense = model.apply({"params": dense_p}, tokens)
    np.testing.assert_allclose(np.asarray(logits_pp),
                               np.asarray(logits_dense), atol=2e-3,
                               rtol=2e-3)


def test_pp_fused_loss_matches_plain_and_trains():
    """The activation-light fused-loss schedule (stage-0 embed ingest,
    last-stage immediate cross-entropy) computes the SAME loss as the
    plain pipelined forward and trains along the same curve."""
    import optax

    model, params, tokens = make_lm(layers=2, batch=4, seq=8)
    targets = jnp.roll(tokens, -1, axis=1)
    mesh = pp.pp_mesh(2, cpu_devices(2))
    batch = (tokens, targets)
    optimizer = optax.adam(1e-2)

    stacked, rest, opt_state = pp.pp_train_init(model, mesh, params,
                                                optimizer)
    plain_loss = pp.pp_loss_fn(model, mesh, n_micro=2)
    fused_loss = pp._pp_fused_loss(model, mesh, 2, 2)
    lp = float(jax.jit(plain_loss)(stacked, rest, batch))
    lf = float(jax.jit(fused_loss)(stacked, rest, batch))
    np.testing.assert_allclose(lf, lp, rtol=1e-5)

    # and it TRAINS: the fused step's losses track the plain step's
    step_f = pp.pp_train_step_fn(model, mesh, optimizer, n_micro=2,
                                 fused_loss=True)
    step_p = pp.pp_train_step_fn(model, mesh, optimizer, n_micro=2)
    sf, rf, of = stacked, rest, opt_state
    sp_, rp_, op_ = pp.pp_train_init(model, mesh, params, optimizer)
    for _ in range(5):
        sf, rf, of, loss_f = step_f(sf, rf, of, batch)
        sp_, rp_, op_, loss_p = step_p(sp_, rp_, op_, batch)
        np.testing.assert_allclose(float(loss_f), float(loss_p), rtol=2e-4,
                                   atol=2e-4)
    assert float(loss_f) < lf  # descended
