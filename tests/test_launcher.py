"""Launcher (bfrun) tests: env export, --simulate, and the 2-process smoke.

The reference's launcher path (run/run.py:257-280, mpirun assembly) is
covered in this stack by env export + jax.distributed bootstrap; the
2-process test is the analog of the reference's smallest mpirun job —
two controller processes on localhost stitched into one size-4 device mesh,
with cross-process collectives riding gloo.
"""

import os
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from bluefog_tpu import launcher

TESTS = Path(__file__).resolve().parent


def _scrubbed_env():
    env = os.environ.copy()
    # children pick their own platform/device forcing; drop the conftest's
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT"):
        env.pop(k, None)
    repo = str(TESTS.parent)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    # every launch here targets the simulated CPU mesh; don't let children
    # probe a possibly-wedged accelerator tunnel (multi-minute hang each)
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_parser_env_export(monkeypatch):
    """--timeline-filename/--verbose/--simulate export the documented env."""
    captured = {}

    def fake_exec(prog, args, env):
        captured.update(env=env, prog=prog, args=args)

    monkeypatch.setattr(os, "execvpe", fake_exec)
    launcher.main(["--timeline-filename", "/tmp/tl_", "--verbose",
                   "--simulate", "4", "--", "prog", "a1"])
    env = captured["env"]
    assert env["BLUEFOG_TIMELINE"] == "/tmp/tl_"
    assert env["BLUEFOG_LOG_LEVEL"] == "debug"
    assert "--xla_force_host_platform_device_count=4" in env["XLA_FLAGS"]
    assert captured["prog"] == "prog" and captured["args"] == ["prog", "a1"]


def test_multiproc_requires_coordinator():
    assert launcher.main(["-np", "2", "--", "prog"]) == 1
    assert launcher.main([]) == 1


def test_simulate_single_host():
    """bfrun --simulate N boots a usable N-device CPU job."""
    code = ("import jax, bluefog_tpu as bf; bf.init(); "
            "assert bf.size() == 4, bf.size(); "
            "assert bf.rank() == 0 and bf.local_rank() == 0; "
            "print('SIM_OK')")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--simulate", "4",
         "--", sys.executable, "-c", code],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "SIM_OK" in out.stdout


@pytest.mark.slow
def test_simulate_16_ranks():
    """A deeper mesh than the 8-device fixture: log2(16)=4 Expo-2 shifts
    and a 4x4 machine-by-local hierarchy, through the bfrun path."""
    code = (
        "import numpy as np, jax, bluefog_tpu as bf; "
        "bf.init(local_size=4); "
        "assert bf.size() == 16 and bf.num_machines() == 4; "
        "x = bf.shard_rank_stacked(bf.mesh(), "
        "np.arange(16, dtype=np.float32).reshape(16, 1)); "
        "y = x\n"
        "for _ in range(40): y = bf.neighbor_allreduce(y)\n"
        "np.testing.assert_allclose(np.asarray(y), 7.5, atol=1e-3); "
        "h = bf.hierarchical_neighbor_allreduce(x); "
        "assert h.shape == (16, 1); "
        "print('RANKS16_OK')"
    )
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--simulate", "16",
         "--", sys.executable, "-c", code],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "RANKS16_OK" in out.stdout


def _launch_n(child_script: str, env, nproc: int, timeout: int = 300,
              simulate: int = 2):
    """Run an nproc-process bfrun job of ``child_script`` (``simulate``
    devices each); return (procs, outs)."""
    port = _free_port()

    def cmd(i):
        return [sys.executable, "-m", "bluefog_tpu.launcher",
                "-np", str(nproc),
                "--coordinator", f"127.0.0.1:{port}", "--process-id", str(i),
                "--simulate", str(simulate),
                "--", sys.executable, str(TESTS / child_script)]

    procs = [subprocess.Popen(cmd(i), env=env, stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True)
             for i in range(nproc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def _launch_pair(child_script: str, env):
    """Run a 2-process bfrun job of ``child_script``; return (procs, outs)."""
    # 420 s: the child imports torch for the live-frontend phase (~10 s
    # cold each) and slow CI boxes run several of these harnesses back to
    # back on one core
    return _launch_n(child_script, env, 2, timeout=420)


@pytest.mark.slow
def test_two_process_launch_smoke(tmp_path):
    """bfrun -np 2 --coordinator: the full multi-controller bootstrap.

    Asserts (in the children, tests/_launch_child.py): distributed init,
    size/rank/local_size/local_rank truthfulness, cross-process allreduce +
    ring neighbor_allreduce + hierarchical correctness, windows on global
    arrays, a coordinated orbax checkpoint round-trip, and control-plane
    fetch_add/barrier.
    """
    env = _scrubbed_env()
    env["SMOKE_CKPT_DIR"] = str(tmp_path / "ck")
    env["KERAS_BACKEND"] = "jax"  # opt into the keras frontend phase
    # fast heartbeat cadence so the coordinated-shutdown observation at the
    # end of the child doesn't wait out the default 5 s interval
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.3"
    procs, outs = _launch_pair("_launch_child.py", env)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"CHILD_OK {i}" in out
        # live-torch frontend across 2 controllers (skipped if no torch)
        assert (f"TORCH_MC_OK {i}" in out or f"TORCH_MC_SKIP {i}" in out)
        # keras frontend across 2 controllers (skipped if no keras)
        assert (f"KERAS_MC_OK {i}" in out or f"KERAS_MC_SKIP {i}" in out)


def test_parse_hosts_formats(tmp_path):
    from bluefog_tpu.launcher import parse_hosts
    assert parse_hosts("h1:2,h2:2") == [("h1", 2), ("h2", 2)]
    assert parse_hosts("h1, h2:3") == [("h1", 1), ("h2", 3)]
    hf = tmp_path / "hosts"
    hf.write_text("# cluster\nh1 slots=4\nh2:2\nh3\n\n")
    assert parse_hosts(hostfile=str(hf)) == [("h1", 4), ("h2", 2), ("h3", 1)]
    with pytest.raises(ValueError):
        parse_hosts("h1:0")


@pytest.mark.slow
def test_hostfile_fanout_two_processes():
    """VERDICT-r2 #3: ONE bfrun command drives the whole 2-process job —
    automatic process ids + coordinator, aggregated exit codes. Runs the
    same full multi-controller child as the manual smoke."""
    env = _scrubbed_env()
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.3"
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:2", "--simulate", "2",
         "--", sys.executable, str(TESTS / "_launch_child.py")],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "CHILD_OK 0" in out.stdout and "CHILD_OK 1" in out.stdout


@pytest.mark.slow
def test_fanout_aggregates_failure():
    """A failing process makes the driver kill the job and report nonzero."""
    env = _scrubbed_env()
    code = ("import os, sys, time; "
            "sys.exit(7) if os.environ['JAX_PROCESS_ID'] == '1' "
            "else time.sleep(60)")
    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:2", "--", sys.executable, "-c", code],
        env=env, capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 7, (out.returncode, out.stdout + out.stderr)
    # the survivor slept 60s; first-failure kill must not wait it out
    assert time.monotonic() - t0 < 45


def test_fanout_rejects_np_slot_mismatch():
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "-np", "3",
         "-H", "localhost:2", "--", "true"],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 1
    assert "does not match" in out.stderr


@pytest.mark.slow
def test_one_sided_windows_across_controllers():
    """VERDICT-r2 #1: window gossip is truly one-sided across controllers.

    Process 1 sleeps inside its step while process 0 completes win_put +
    win_update in bounded time (phase A); then a push-sum run with
    deliberately skewed controller speeds conserves total mass and p mass
    after a final drain (phase B). See tests/_onesided_child.py.
    """
    env = _scrubbed_env()
    procs, outs = _launch_pair("_onesided_child.py", env)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"CHILD_OK {i}" in out
    assert "PHASE_A_BOUNDED" in outs[0]
    assert "PHASE_B_UNCOUPLED" in outs[0]
    assert "PHASE_B_INVARIANT" in outs[0]


@pytest.mark.slow
def test_cross_controller_topo_check():
    """VERDICT-r2 #7: divergent dynamic edge sets across controllers raise
    (hash rendezvous over the control plane) instead of silently producing
    garbage ppermutes. See tests/_topocheck_child.py."""
    procs, outs = _launch_pair("_topocheck_child.py", _scrubbed_env())
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        assert f"AGREED_OK {i}" in out
        assert f"DIVERGENT_RAISED {i}" in out
        assert f"CHILD_OK {i}" in out


@pytest.mark.slow
def test_peer_crash_detected():
    """Fault injection: a controller dies silently; the survivor's heartbeat
    monitor reports it as a DEAD peer (bf.dead_controllers()) instead of a
    coordinated shutdown, within the configured timeout. SURVEY §5.3: the
    reference only *warns* about missing ranks; this asserts the detection
    end-to-end across real processes."""
    env = _scrubbed_env()
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.2"
    env["BLUEFOG_HEARTBEAT_TIMEOUT"] = "1.5"
    procs, outs = _launch_pair("_fault_child.py", env)
    assert procs[1].returncode == 17, f"faulty process:\n{outs[1]}"
    assert procs[0].returncode == 0, f"survivor failed:\n{outs[0]}"
    assert "SURVIVOR_DETECTED 1" in outs[0]
    # VERDICT-r2 #8: the survivor's bounded synchronize raises within the
    # deadline, naming the dead peer, instead of hanging on the corpse
    assert "SURVIVOR_SYNC_RAISED 1" in outs[0]
    assert "HEALTHY 0" in outs[0] and "HEALTHY 1" in outs[1]


# ---------------------------------------------------------------------------
# 4-controller harness (VERDICT r3 #4; reference CI ran np=4, Makefile:1)
# ---------------------------------------------------------------------------

_QUAD_MARKERS = [
    "PHASE_A_OK", "PHASE_D_AGREED", "PHASE_D_DIVERGENT_RAISED",
    "PHASE_E_FENCE_OK", "CHILD_OK",
]


def _assert_quad_outputs(procs, outs):
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        for marker in _QUAD_MARKERS:
            assert f"{marker} {i}" in out, f"missing {marker} {i}:\n{out}"
    assert "PHASE_B_MASS" in outs[0]
    assert "PHASE_C_UNCOUPLED" in outs[0]
    assert "PHASE_C_INVARIANT" in outs[0]


@pytest.mark.slow
def test_four_controllers_windows_mutex_pushsum_topocheck():
    """4 controllers x 2 devices: hosted-window exact values with 4 owners,
    4-client mutex contention under strict mode, skewed push-sum mass
    conservation, 4-way topo-check divergence, and cross-controller
    win_fence. See tests/_quad_child.py."""
    procs, outs = _launch_n("_quad_child.py", _scrubbed_env(), 4,
                            timeout=420)
    _assert_quad_outputs(procs, outs)


@pytest.mark.slow
def test_eight_controller_high_degree_windows():
    """8 controllers x 1 device: hosted windows at high/ragged degrees
    (expo2 d=3, star d=7), chunked cross-controller deposits
    (BLUEFOG_MAX_WIN_SENT_LENGTH=64Ki), and the server mailbox byte cap
    engaging under real contention with exact mass accounting afterwards.
    See tests/_degree_child.py (VERDICT r4 #5)."""
    env = _scrubbed_env()
    env["BLUEFOG_CP_MAILBOX_MAX_MB"] = "1"  # phase D: cap engages fast
    procs, outs = _launch_n("_degree_child.py", env, 8, timeout=600,
                            simulate=1)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out}"
        for marker in ("PHASE_A_OK", "PHASE_B_OK", "PHASE_C_OK",
                       "CHILD_OK"):
            assert f"{marker} {i}" in out, f"missing {marker} {i}:\n{out}"
        if i != 0:
            assert f"PHASE_D_CAP {i}" in out, out
    assert "PHASE_D_MASS_OK" in outs[0]


@pytest.mark.slow
def test_four_process_fanout_one_command():
    """The same 4-controller job through ONE `bfrun -H localhost:4`
    command: fan-out assigns ids/coordinator and mints the control-plane
    secret for all four processes."""
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:4", "--simulate", "2",
         "--", sys.executable, str(TESTS / "_quad_child.py")],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=420,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    for i in range(4):
        assert f"CHILD_OK {i}" in out.stdout, out.stdout
    assert "PHASE_C_INVARIANT" in out.stdout


@pytest.mark.slow
def test_one_of_four_crash_detected_by_all_survivors():
    """Controller 3 of 4 dies silently; EVERY survivor's heartbeat monitor
    reports it dead and the bounded-wait synchronize raises naming it.
    See tests/_quad_fault_child.py."""
    env = _scrubbed_env()
    env["BLUEFOG_HEARTBEAT_INTERVAL"] = "0.2"
    env["BLUEFOG_HEARTBEAT_TIMEOUT"] = "1.5"
    procs, outs = _launch_n("_quad_fault_child.py", env, 4, timeout=300)
    assert procs[3].returncode == 17, f"faulty process:\n{outs[3]}"
    for i in range(3):
        assert procs[i].returncode == 0, f"survivor {i} failed:\n{outs[i]}"
        assert f"SURVIVOR_DETECTED {i}" in outs[i]
        assert f"SURVIVOR_SYNC_RAISED {i}" in outs[i]
    for i in range(4):
        assert f"HEALTHY {i}" in outs[i]


@pytest.mark.slow
def test_torch_frontend_example():
    """The live-torch-loop consensus example through bfrun --simulate 8."""
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--simulate", "8",
         "--", sys.executable,
         str(TESTS.parent / "examples" / "torch_average_consensus.py")],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "TORCH CONSENSUS OK" in out.stdout


@pytest.mark.slow
def test_keras_frontend_example():
    """The keras data-parallel training example through bfrun --simulate 8."""
    env = _scrubbed_env()
    env["KERAS_BACKEND"] = "jax"
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--simulate", "8",
         "--", sys.executable,
         str(TESTS.parent / "examples" / "keras_mnist.py")],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "KERAS TRAIN OK" in out.stdout


# ---------------------------------------------------------------------------
# bfrun --elastic: incarnation-bumped respawn supervision (ISSUE r9)
# ---------------------------------------------------------------------------

def test_elastic_parser_forms():
    p = launcher.build_parser()
    a = p.parse_args(["--elastic", "--", "prog"])
    assert a.elastic == 3  # bare flag: default budget
    a = p.parse_args(["--elastic=5", "--min-world", "2", "--", "prog"])
    assert a.elastic == 5 and a.min_world == 2
    a = p.parse_args(["--", "prog"])
    assert a.elastic is None


def test_elastic_respawns_with_bumped_incarnation(tmp_path):
    """A rank that crashes is respawned with BLUEFOG_INCARNATION bumped;
    the job succeeds once the respawn does (the probe exits 0 only at
    incarnation >= 1) — the crash is absorbed, not propagated."""
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import os, sys\n"
        "inc = int(os.environ.get('BLUEFOG_INCARNATION', '0'))\n"
        "print(f'probe pid={os.environ.get(\"JAX_PROCESS_ID\")} "
        "inc={inc}', flush=True)\n"
        "sys.exit(0 if inc >= 1 else 9)\n")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:2", "--elastic=2", "--",
         sys.executable, str(probe)],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=180)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "respawning as incarnation 1" in out.stderr
    assert "inc=1" in out.stdout


def test_elastic_budget_exhaustion_is_terminal(tmp_path):
    """A rank that keeps crashing past its restart budget propagates a
    terminal failure (nonzero job exit), with the budget respected."""
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:1", "--elastic=1", "--",
         sys.executable, "-c", "import sys; sys.exit(9)"],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=180)
    assert out.returncode == 9, out.stdout + out.stderr
    assert "exhausted its restart budget" in out.stderr
    assert out.stderr.count("respawning") == 1  # budget=1: exactly one


def test_elastic_min_world_teardown(tmp_path):
    """With --min-world equal to the full world, losing one rank for good
    tears the whole job down instead of limping along under-replicated."""
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "-H", "localhost:2", "--elastic=0", "--min-world", "2", "--",
         sys.executable, "-c",
         "import os, sys, time\n"
         "if os.environ.get('JAX_PROCESS_ID') == '1':\n"
         "    sys.exit(9)\n"
         "time.sleep(60)\n"],
        env=_scrubbed_env(), capture_output=True, text=True, timeout=180)
    assert out.returncode == 9, out.stdout + out.stderr
    assert "dropped below --min-world" in out.stderr
