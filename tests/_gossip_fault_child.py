"""Child for the kill-peer-mid-gossip self-healing test (ISSUE r8).

Four controllers, two devices each, running a real window-optimizer gossip
loop (DistributedWinPutOptimizer over the hosted plane). Controller 3 is
hard-killed mid-loop — possibly while holding window mutexes and with
deposits in flight. Survivors must keep completing bounded gossip steps:
the optimizer consults the heartbeat dead set each step, drops ranks
{6, 7} from its edge tables, renormalizes the averaging weights, and the
leased lock layer force-releases anything the corpse held (a blocked
acquire surfaces PeerLostError, which the optimizer retries once on the
shrunken topology).
"""

import os
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf

N = 8
DEAD_PID = 3


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == N, bf.size()

    def loss_fn(params, batch):
        return jnp.sum((params["w"] - 3.0) ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.zeros((4,), jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))

    for _ in range(3):
        state, _ = opt.step(state, batch)
    print(f"HEALTHY {pid}", flush=True)

    if pid == DEAD_PID:
        os._exit(17)  # silent SIGKILL shape: no announce, no atexit

    detected = False
    post_detect_steps = 0
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and post_detect_steps < 3:
        t0 = time.monotonic()
        state, _ = opt.step(state, batch)
        step_s = time.monotonic() - t0
        if not detected and bf.dead_controllers() == {DEAD_PID}:
            detected = True
            assert bf.dead_ranks() == {6, 7}, bf.dead_ranks()
            print(f"DEAD_DETECTED {pid}", flush=True)
        if detected:
            post_detect_steps += 1
            # bounded: a step on the healed topology must not wait on the
            # corpse (no unbounded lock/barrier/drain)
            assert step_s < 30, f"post-detection step took {step_s:.1f}s"
    if post_detect_steps < 3:
        print(f"SURVIVOR_TIMEOUT {pid}", flush=True)
        os._exit(3)
    for shard in state.params["w"].addressable_shards:
        assert np.isfinite(np.asarray(shard.data)).all()
    print(f"SURVIVOR_STEPS_OK {pid}", flush=True)

    # Survivor rendezvous (see _quad_fault_child.py): process 0 hosts both
    # the jax coordinator and the control-plane server, so it must leave
    # last; graceful teardown barriers would block on the corpse.
    from bluefog_tpu.runtime import control_plane
    cl = control_plane.client()
    cl.put(f"gf.done.{pid}", 1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(cl.get(f"gf.done.{i}") for i in range(3)):
            break
        time.sleep(0.05)
    print(f"CHILD_OK {pid}", flush=True)
    if pid == 0:
        time.sleep(2.0)
    os._exit(0)


if __name__ == "__main__":
    main()
