"""Test harness: 8 virtual CPU devices standing in for an 8-chip mesh.

The reference runs every test as a real multiprocess job under mpirun
(Makefile:9, test strategy in SURVEY.md §4). The TPU-native analog is an
8-device CPU-simulated mesh via --xla_force_host_platform_device_count:
the same SPMD programs, shardings, and collectives that run on a pod,
executed by the CPU backend. Must configure the env BEFORE jax is imported.
"""

import os

# 16 forced devices: suites mostly slice 8 of them, but the odd/non-power-
# of-2 world-size sweep (test_odd_world_sizes.py) also needs 12 — the
# reference ran at arbitrary np (its Makefile used np=2/np=4), so neighbor
# math must not silently assume power-of-2 sizes. Any caller-provided
# force flag (e.g. the Makefile's =8) is stripped so 16 actually wins.
_flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
          if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(
    _flags + ["--xla_force_host_platform_device_count=16"])
# The image's sitecustomize force-registers the axon TPU plugin; an empty
# JAX_PLATFORMS lets both backends register so jax.devices('cpu') works.
# BLUEFOG_TESTS_CPU_ONLY=1 pins strictly to CPU — the escape hatch for when
# the remote-TPU tunnel is down (its plugin init would hang EVERY test).
# An explicit JAX_PLATFORMS=cpu from the caller (the tier-1 runner's env)
# is honored for the same reason: the caller asked for a CPU-only run, and
# widening it to "" would re-probe a possibly-wedged accelerator tunnel.
os.environ["JAX_PLATFORMS"] = (
    "cpu" if (os.environ.get("BLUEFOG_TESTS_CPU_ONLY") == "1"
              or os.environ.get("JAX_PLATFORMS") == "cpu") else "")

# Flight-recorder dumps default to the cwd; tests that deliberately stall
# handles or crash optimizer steps would litter the repo root, so the
# suite's automatic dumps land in a throwaway dir instead (tests that
# assert on dump files monkeypatch their own BLUEFOG_FLIGHT_DIR).
if "BLUEFOG_FLIGHT_DIR" not in os.environ:
    import tempfile

    os.environ["BLUEFOG_FLIGHT_DIR"] = tempfile.mkdtemp(
        prefix="bf_flight_tests_")

import jax  # noqa: E402
import pytest  # noqa: E402

# Exact-value assertions: keep MXU matmuls in full f32 (the default TPU
# precision rounds operands to bf16, which breaks 1e-5-level oracles).
jax.config.update("jax_default_matmul_precision", "highest")

import bluefog_tpu as bf  # noqa: E402


def cpu_devices(n=8):
    devs = jax.devices("cpu")
    assert len(devs) >= n, f"need {n} cpu devices, got {len(devs)}"
    return devs[:n]


# The BLUEFOG_FLIGHT_DIR redirect above keeps this process's dumps out of
# the tree, but subprocess-spawning tests that scrub or rebuild their env
# could still let a crashing child dump into its cwd — the repo root. Any
# new bf_flight_*.json at the root after the run is a harness regression
# (and `make check`'s litter analyzer would flag the file as debris), so
# fail loudly here with the responsible pattern named.
_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _root_flight_dumps():
    import glob

    return set(glob.glob(os.path.join(_REPO_ROOT, "bf_flight_*.json")))


_flight_dumps_before = _root_flight_dumps()


def pytest_sessionfinish(session, exitstatus):
    leaked = _root_flight_dumps() - _flight_dumps_before
    if leaked:
        raise pytest.UsageError(
            "test run littered the repository root with flight-recorder "
            f"dump(s): {sorted(os.path.basename(p) for p in leaked)} — "
            "point the responsible test's BLUEFOG_FLIGHT_DIR at a temp "
            "dir (see conftest.py)")


@pytest.fixture()
def bf8():
    """bluefog_tpu initialized over 8 virtual devices, default Expo-2 topo."""
    bf.init(devices=cpu_devices(8), local_size=4)
    yield bf
    bf.shutdown()
