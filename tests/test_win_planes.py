"""Hybrid per-edge gossip plane (ISSUE r13).

The planner (ops/plan.py:PlanePlanner) splits a hosted window's frozen edge
set into a compiled partition (one fused shard_map/ppermute program per
step) and a hosted mailbox residual. These tests pin the contracts:

  * all edges compiled-eligible → the hybrid step is BIT-EXACT against the
    pure collective plane (same program ops, materialized through the same
    mail dtype);
  * BLUEFOG_WIN_PLANE=hosted → bit-identical to the legacy
    BLUEFOG_WIN_HOST_PLANE=1 wire (the r6/r7 oracle — the planner is off);
  * a mixed partition changes the execution split, never the semantics
    (numpy combine oracle);
  * partitions re-plan exactly on membership-epoch bumps / dead-set
    changes (cache keyed on (edge set, dead set, epoch));
  * the planner consumes a REAL scripts/step_attribution.py --json dump
    (stable schema_version — it is a machine interface now);
  * push-sum mass is conserved across the partition boundary (compiled
    edges move mass in-program, hosted edges via mailbox).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu.ops import plan as plan_mod
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import heartbeat as hb
from bluefog_tpu.runtime import native

from conftest import cpu_devices

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")

N = 8


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _set_cp_env(monkeypatch, plane=None, legacy=None, overlap=None):
    env = {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(_free_port()),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
    }
    if plane is not None:
        env["BLUEFOG_WIN_PLANE"] = plane
    if legacy is not None:
        env["BLUEFOG_WIN_HOST_PLANE"] = legacy
    if overlap is not None:
        env["BLUEFOG_WIN_OVERLAP"] = overlap
    for k in ("BLUEFOG_WIN_PLANE", "BLUEFOG_WIN_HOST_PLANE",
              "BLUEFOG_WIN_OVERLAP"):
        if k not in env:
            monkeypatch.delenv(k, raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, v)


@pytest.fixture()
def bf_hybrid(monkeypatch):
    """8 CPU ranks, world-1 control plane, hosted window WITH the per-edge
    planner: BLUEFOG_WIN_PLANE=auto + the legacy hosted force — the
    single-controller hybrid harness shape (docs/window_planes.md)."""
    _set_cp_env(monkeypatch, plane="auto", legacy="1")
    cp.reset_for_test()
    bf.init(devices=cpu_devices(N))
    assert cp.active()
    yield bf
    bf.shutdown()
    cp.reset_for_test()


def _quadratic_opt(bf_, cls=None, lr=0.05):
    target = jnp.asarray([1.0, -2.0, 3.0, 0.5])

    def loss(params, batch):
        return jnp.sum((params["w"] - target) ** 2)

    cls = cls or bf_.DistributedWinPutOptimizer
    opt = cls(optax.sgd(lr), loss_fn=loss)
    state = opt.init({"w": jnp.zeros(4)})
    return opt, state, jnp.zeros((N, 1))


def _run_steps(opt, state, batch, steps):
    for _ in range(steps):
        state, _ = opt.step(state, batch)
    return np.asarray(state.params["w"]).copy()


# ---------------------------------------------------------------------------
# planner unit tests (no runtime needed)
# ---------------------------------------------------------------------------

def _planner(owner_of=None, edges=None, **kw):
    edges = edges or [(0, 1), (1, 2), (2, 3), (3, 0)]
    owner_of = owner_of or {r: 0 for r in range(4)}
    return plan_mod.PlanePlanner(4, edges, owner_of, row_bytes=1 << 20, **kw)


def test_planner_mesh_local_and_dead_eligibility():
    pl = _planner(owner_of={0: 0, 1: 0, 2: 1, 3: 1})
    part = pl.partition()
    # (0,1) and (2,3) are mesh-local; (1,2)/(3,0) cross the controller
    # boundary and stay hosted
    assert part.compiled == frozenset({(0, 1), (2, 3)})
    assert part.hosted == frozenset({(1, 2), (3, 0)})
    # a dead-adjacent edge is demoted: no compiled program names rank 3
    part = pl.partition(dead={3})
    assert part.compiled == frozenset({(0, 1)})
    assert all(3 not in e or e in part.hosted for e in pl.edges)


def test_planner_size_floor_and_override():
    pl = _planner(min_bytes=2 << 20)  # floor above the 1 MB row
    assert not pl.partition().compiled
    pl = _planner(hosted_override={(0, 1)})
    part = pl.partition()
    assert (0, 1) in part.hosted and (1, 2) in part.compiled


def test_planner_per_edge_wire_scale_mixed_codec_floor():
    """ISSUE r16 regression: ``wire_scale`` is per-EDGE with the scalar
    as fallback. A mixed-codec window (per-edge BLUEFOG_WIN_CODEC
    grammar / tuner escalation) must floor-check each edge at ITS OWN
    codec's nominal ratio — the old scalar-only estimate either
    mis-compiled every compressed edge or mis-hosted every raw one."""
    owner_of = {r: 0 for r in range(4)}  # all mesh-local: floor decides
    # 1 MB rows, floor at 0.5 MB: raw edges clear it, a topk:0.01-scaled
    # edge (0.02x -> ~21 KB) lands far below it
    pl = _planner(owner_of=owner_of, min_bytes=1 << 19)
    assert pl.partition().compiled == pl.edges
    assert pl.set_edge_scale((0, 1), 0.02) is True  # verdict flips
    part = pl.partition()
    assert (0, 1) in part.hosted  # ITS codec's ratio, not the scalar's
    assert part.compiled == pl.edges - {(0, 1)}
    # every other edge still uses the scalar fallback
    assert pl.edge_cost((1, 2)) == 1 << 20
    assert pl.edge_cost((0, 1)) == (1 << 20) * 0.02
    # int8 on another edge (0.26x of 1 MB ~ 272 KB < 512 KB floor)
    assert pl.set_edge_scale((2, 3), 0.26) is True
    assert pl.partition().hosted >= {(0, 1), (2, 3)}
    # back to raw: exact scalar-fallback restoration, verdict flips back
    assert pl.set_edge_scale((0, 1), 1.0) is True
    assert (0, 1) in pl.partition().compiled


def test_planner_ingest_live_replans_only_on_verdict_flip():
    """The tuner's plane lever: measured per-edge bytes override the
    static estimate, but the partition cache is dropped ONLY when a
    size-floor verdict actually flips — steady measurements cost no
    re-jit."""
    owner_of = {r: 0 for r in range(4)}
    pl = _planner(owner_of=owner_of, min_bytes=1 << 19)
    pl.partition()
    assert pl.rebuilds == 1
    # live bytes above the floor on an already-compiled edge: no flip
    assert pl.ingest_live({(0, 1): float(1 << 20)}) is False
    pl.partition()
    assert pl.rebuilds == 1  # cache intact
    # live bytes below the floor: verdict flips -> re-plan scheduled
    assert pl.ingest_live({(0, 1): 1024.0}) is True
    part = pl.partition()
    assert pl.rebuilds == 2 and (0, 1) in part.hosted
    # live beats the static estimate AND the per-edge scale
    pl.set_edge_scale((0, 1), 0.5)
    assert pl.edge_cost((0, 1)) == 1024.0


def test_planner_policy_hosted_compiles_nothing():
    pl = _planner(policy="hosted")
    assert not pl.partition().compiled


def test_planner_cache_keyed_on_dead_set_and_epoch():
    pl = _planner()
    pl.partition(epoch=0)
    pl.partition(epoch=0)
    assert pl.rebuilds == 1  # cache hit on the unchanged key
    pl.partition(epoch=1)  # membership-epoch bump → re-plan
    assert pl.rebuilds == 2
    pl.partition(dead={2}, epoch=1)  # dead-set change → re-plan
    assert pl.rebuilds == 3
    pl.partition(dead={2}, epoch=1)
    assert pl.rebuilds == 3


def test_attribution_schema_is_validated():
    with pytest.raises(ValueError):
        plan_mod.load_attribution({"ranks": {}})
    with pytest.raises(ValueError):
        plan_mod.load_attribution({"schema_version": 999, "ranks": {}})
    hints = plan_mod.load_attribution({
        "schema_version": plan_mod.ATTRIBUTION_SCHEMA_VERSION,
        "ranks": {"0": {"edges": {"0->2": {"bytes": 64.0,
                                           "wire_sec_est": 0.25}}}}})
    assert hints[(0, 2)]["bytes"] == 64.0
    pl = _planner(edges=[(0, 2)])
    assert pl.edge_cost((0, 2)) == 1 << 20
    assert pl.ingest_attribution({
        "schema_version": 1,
        "ranks": {"0": {"edges": {"0->2": {"bytes": 64.0}}}}}) == 1
    assert pl.edge_cost((0, 2)) == 64.0


# ---------------------------------------------------------------------------
# equivalence: all-compiled hybrid ⇔ pure collective plane (bit-exact)
# ---------------------------------------------------------------------------

def test_all_compiled_hybrid_bitexact_vs_collective(monkeypatch):
    steps = 4
    # run 1: hybrid — hosted window, planner on, every edge mesh-local
    _set_cp_env(monkeypatch, plane="auto", legacy="1")
    cp.reset_for_test()
    bf.init(devices=cpu_devices(N))
    opt, state, batch = _quadratic_opt(bf)
    win = win_ops._get_window(opt._win_names[0])
    assert win.hosted and win._planner is not None
    part = win.plane_partition(set())
    assert part is not None and not part.hosted, \
        "static exp2 edges in a world-1 job must all be compiled-eligible"
    hybrid = _run_steps(opt, state, batch, steps)
    opt.free()
    bf.shutdown()
    cp.reset_for_test()

    # run 2: the pure collective plane (no control plane at all)
    for k in ("BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT", "BLUEFOG_CP_WORLD",
              "BLUEFOG_CP_RANK", "BLUEFOG_WIN_HOST_PLANE"):
        monkeypatch.delenv(k, raising=False)
    monkeypatch.setenv("BLUEFOG_WIN_PLANE", "compiled")
    bf.init(devices=cpu_devices(N))
    opt2, state2, batch2 = _quadratic_opt(bf)
    assert not win_ops._get_window(opt2._win_names[0]).hosted
    collective = _run_steps(opt2, state2, batch2, steps)
    opt2.free()
    bf.shutdown()
    cp.reset_for_test()

    np.testing.assert_array_equal(hybrid, collective)


def test_forced_hosted_reproduces_legacy_wire(monkeypatch):
    """BLUEFOG_WIN_PLANE=hosted must be the legacy BLUEFOG_WIN_HOST_PLANE=1
    path bit for bit — the planner stays off and every byte rides the
    r6/r7 mailbox wire."""
    steps = 3
    results = []
    for plane, legacy in (("hosted", None), (None, "1")):
        _set_cp_env(monkeypatch, plane=plane, legacy=legacy)
        cp.reset_for_test()
        bf.init(devices=cpu_devices(N))
        opt, state, batch = _quadratic_opt(bf)
        win = win_ops._get_window(opt._win_names[0])
        assert win.hosted and win._planner is None  # planner pinned off
        results.append(_run_steps(opt, state, batch, steps))
        opt.free()
        bf.shutdown()
        cp.reset_for_test()
    np.testing.assert_array_equal(results[0], results[1])


# ---------------------------------------------------------------------------
# mixed partition ⇔ numpy combine oracle (the split changes execution, not
# semantics)
# ---------------------------------------------------------------------------

def _winput_oracle(topo, w0, batch_targets, steps, lr=0.05,
                   target=np.asarray([1.0, -2.0, 3.0, 0.5])):
    in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
               for r in range(N)}
    w = np.asarray(w0, np.float64)
    for _ in range(steps):
        wl = w - lr * 2.0 * (w - target[None])
        mixed = np.zeros_like(wl)
        for r in range(N):
            u = 1.0 / (len(in_nbrs[r]) + 1)
            mixed[r] = u * (wl[r] + sum(wl[s] for s in in_nbrs[r]))
        w = mixed
    return w


def test_mixed_partition_matches_numpy_oracle(bf_hybrid):
    opt, state, batch = _quadratic_opt(bf_hybrid)
    win = win_ops._get_window(opt._win_names[0])
    # force a mixed partition: roughly half the edges demoted to hosted
    forced = frozenset(e for e in win._planner.edges
                       if (e[0] + e[1]) % 2 == 0)
    assert forced and forced != win._planner.edges
    win._planner.hosted_override = forced
    win._planner._cache.clear()
    try:
        part = win.plane_partition(set())
        assert part.compiled and part.hosted  # genuinely mixed
        got = _run_steps(opt, state, batch, 3)
        want = _winput_oracle(bf_hybrid.load_topology(),
                              np.zeros((N, 4)), batch, 3)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        opt.free()


def test_overlap_is_one_step_stale(monkeypatch):
    """BLUEFOG_WIN_OVERLAP=1: the hosted residual of step t folds into
    step t+1. With a mixed partition, step 1's result must MISS the
    hosted contributions entirely (nothing in flight yet) and step 2 must
    fold step 1's — the numpy oracle models exactly that lag."""
    _set_cp_env(monkeypatch, plane="auto", legacy="1", overlap="1")
    cp.reset_for_test()
    bf.init(devices=cpu_devices(N))
    try:
        opt, state, batch = _quadratic_opt(bf)
        win = win_ops._get_window(opt._win_names[0])
        forced = frozenset(e for e in win._planner.edges
                           if (e[0] + e[1]) % 2 == 0)
        win._planner.hosted_override = forced
        win._planner._cache.clear()
        try:
            topo = bf.load_topology()
            in_nbrs = {r: bf.topology_util.in_neighbor_ranks(topo, r)
                       for r in range(N)}
            target = np.asarray([1.0, -2.0, 3.0, 0.5])
            lr = 0.05
            w = np.zeros((N, 4))
            stale = np.zeros((N, 4))  # hosted contributions in flight
            for step in range(3):
                state, _ = opt.step(state, batch)
                wl = w - lr * 2.0 * (w - target[None])
                mixed = np.zeros_like(wl)
                fresh = np.zeros_like(wl)
                for r in range(N):
                    u = 1.0 / (len(in_nbrs[r]) + 1)
                    comp = sum(wl[s] for s in in_nbrs[r]
                               if (s, r) not in forced)
                    fresh[r] = u * sum(wl[s] for s in in_nbrs[r]
                                       if (s, r) in forced)
                    mixed[r] = u * (wl[r] + comp) + stale[r]
                stale = fresh
                w = mixed
                np.testing.assert_allclose(
                    np.asarray(state.params["w"]), w, rtol=1e-5, atol=1e-6,
                    err_msg=f"step {step}")
        finally:
            opt.free()
    finally:
        bf.shutdown()
        cp.reset_for_test()


# ---------------------------------------------------------------------------
# re-plan triggers + push-sum conservation + attribution consumption
# ---------------------------------------------------------------------------

def test_epoch_bump_invalidates_partition_cache(bf_hybrid, monkeypatch):
    opt, state, batch = _quadratic_opt(bf_hybrid)
    win = win_ops._get_window(opt._win_names[0])
    try:
        state, _ = opt.step(state, batch)
        r0 = win._planner.rebuilds
        state, _ = opt.step(state, batch)
        assert win._planner.rebuilds == r0  # same epoch, same dead set
        ep = hb.membership_epoch()
        monkeypatch.setattr(hb, "membership_epoch", lambda: ep + 1)
        state, _ = opt.step(state, batch)
        assert win._planner.rebuilds == r0 + 1  # epoch fence → re-plan
    finally:
        opt.free()


def test_pushsum_mass_conserved_across_partition_boundary(bf_hybrid):
    """Compiled edges move mass in-program, hosted edges via the mailbox;
    the sum over live ranks must stay exactly the minted total either
    way — asserted through the same r10 mass/minted gauges the health
    plane reads."""
    from bluefog_tpu.runtime import metrics as metrics_mod

    def loss(params, batch):
        return jnp.sum((params["w"] - batch) ** 2)

    opt = bf_hybrid.DistributedPushSumOptimizer(optax.sgd(0.1),
                                                loss_fn=loss)
    state = opt.init({"w": jnp.zeros((2,), jnp.float32)})
    batch = bf_hybrid.shard_rank_stacked(
        bf_hybrid.mesh(), np.arange(N, dtype=np.float32).reshape(N, 1))
    win = win_ops._get_window(opt._win_names[0])
    forced = frozenset(e for e in win._planner.edges
                       if (e[0] + e[1]) % 2 == 0)
    win._planner.hosted_override = forced
    win._planner._cache.clear()
    try:
        part = win.plane_partition(set())
        assert part.compiled and part.hosted
        for _ in range(4):
            state, _ = opt.step(state, batch)
            p = win.host.read_p()
            assert abs(float(np.sum(p)) - float(N)) < 1e-9
            assert metrics_mod.gauge("pushsum.mass").value == \
                pytest.approx(float(N), abs=1e-9)
        assert metrics_mod.gauge("pushsum.minted").value == float(N)
        # convergence sanity: de-biased params head toward the batch mean
        got = np.asarray(state.params["w"])
        assert np.isfinite(got).all()
    finally:
        opt.free()


def test_pullget_hybrid_matches_oracle(bf_hybrid):
    opt, state, batch = _quadratic_opt(
        bf_hybrid, cls=bf_hybrid.DistributedPullGetOptimizer)
    win = win_ops._get_window(opt._win_names[0])
    forced = frozenset(e for e in win._planner.edges
                       if e[0] % 3 == 0)
    win._planner.hosted_override = forced
    win._planner._cache.clear()
    try:
        got = _run_steps(opt, state, batch, 3)
        want = _winput_oracle(bf_hybrid.load_topology(),
                              np.zeros((N, 4)), batch, 3)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    finally:
        opt.free()


def test_planner_consumes_real_attribution_dump(bf_hybrid, monkeypatch,
                                                tmp_path):
    """End-to-end machine interface: real hosted-wire traffic → flight
    dump → scripts/step_attribution.py --json → PlanePlanner.
    Remote deposits (the flow-event source) are forced by shrinking this
    controller's owned set, exactly like the r12 split-ownership test."""
    from bluefog_tpu.runtime import flight as flight_mod

    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    flight_mod.reset_for_job()
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [0, 1, 2, 3])
    x = bf_hybrid.shard_rank_stacked(
        bf_hybrid.mesh(), np.ones((N, 16), np.float32))
    assert bf_hybrid.win_create(x, "planes.attr", zero_init=True)
    win = win_ops._get_window("planes.attr")
    assert set(win.owned) == {0, 1, 2, 3}
    # a fake "step" so the dump holds one complete opt.step span
    fl = flight_mod.recorder()
    with fl.span("opt.step", b=1):
        bf_hybrid.win_put(x, "planes.attr")  # deposits to ranks 4..7
    path = bf_hybrid.flight_dump(path=str(tmp_path / "dump.json"))
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "scripts",
                      "step_attribution.py"), path, "--json"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["schema_version"] == plan_mod.ATTRIBUTION_SCHEMA_VERSION
    pl = plan_mod.PlanePlanner(
        N, win._planner.edges if win._planner else
        [(s, d) for d, ss in win.in_neighbors.items() for s in ss],
        {r: 0 for r in range(N)}, row_bytes=64)
    n_hints = pl.ingest_attribution(doc)
    assert n_hints > 0, "no per-edge hints recovered from a real dump"
    hinted = next(iter(pl.hints))
    assert pl.edge_cost(hinted) == pl.hints[hinted]["bytes"]
    bf_hybrid.win_free("planes.attr")
