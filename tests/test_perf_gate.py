"""Perf regression gate: comparison logic + the seeded-slowdown knob.

Tier-1-safe: the expensive end (actually running win_microbench /
opt_matrix_bench) happens only in `make perf-gate`; here the gate's
decision logic runs over synthetic measurements, the committed baseline is
validated structurally, and the injected-delay knob is verified to bite at
the two injection points (optimizer step, hosted window op) — the
mechanism `BLUEFOG_PERF_GATE_DELAY_MS=50 make perf-gate` relies on to turn
the gate red.
"""

import importlib.util
import json
import os
import sys
import time


_REPO = os.path.join(os.path.dirname(__file__), "..")


def _load_perf_gate():
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(_REPO, "scripts", "perf_gate.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return mod


# ---------------------------------------------------------------------------
# comparison logic
# ---------------------------------------------------------------------------

def test_compare_passes_within_band():
    pg = _load_perf_gate()
    base = {"win.a.win_put.mbps": 100.0, "opt.x.img_per_sec": 50.0}
    run = {"win.a.win_put.mbps": 80.0, "opt.x.img_per_sec": 49.0}
    failures, lines = pg.compare(run, base, band=0.40)
    assert failures == []
    assert any("ok" in line for line in lines)


def test_compare_reds_on_regression_and_missing():
    pg = _load_perf_gate()
    base = {"win.a.win_put.mbps": 100.0, "opt.x.img_per_sec": 50.0,
            "win.gone.win_get.mbps": 10.0}
    run = {"win.a.win_put.mbps": 55.0,   # -45% < -40% band
           "opt.x.img_per_sec": 60.0}    # improvement: fine
    failures, lines = pg.compare(run, base, band=0.40)
    assert set(failures) == {"win.a.win_put.mbps", "win.gone.win_get.mbps"}
    assert any("REGRESSION" in line for line in lines)
    assert any("MISSING" in line for line in lines)


def test_compare_improvements_and_new_metrics_never_fail():
    pg = _load_perf_gate()
    base = {"opt.x.img_per_sec": 50.0}
    run = {"opt.x.img_per_sec": 500.0, "win.new.win_put.mbps": 1.0}
    failures, lines = pg.compare(run, base, band=0.40)
    assert failures == []
    assert any("info" in line for line in lines)


def test_gating_filter_keeps_stable_series_only():
    pg = _load_perf_gate()
    metrics = {
        "win.f32.win_put.mbps": 1.0,
        "win.f32.win_update.mbps": 1.0,
        "win.f32.raw_put_bytes.mbps": 1.0,   # noisy: out
        "win.f32.drain_fold.mbps": 1.0,      # noisy: out
        "opt.win_put.img_per_sec": 1.0,
        # r13 hybrid-plane series: GATING since r15 (two stable rounds
        # elapsed per the stable-series rule)
        "hybrid.win_put.auto.ov0.img_per_sec": 1.0,
        "hybrid.win_put.hosted.ov0.img_per_sec": 1.0,
        # r15 compressed-wire series: the stable window-op rates GATE
        # since r18 (two stable rounds elapsed, the same graduation
        # hybrid.* took in r15)...
        "codec.int8.f32.win_put.mbps": 1.0,
        "codec.topk:0.01.f32.win_update.mbps": 1.0,
        # ...but the codec wire-leg probes stay info-only (2x run-to-run
        # jitter measured at graduation time)
        "codec.int8.f32.drain_stream.mbps": 1.0,
        # r17 sharded-window series: GATING since r19 (two stable rounds
        # elapsed per the stable-series rule), including the
        # counter-delta wire_reduction_x ratios
        "sharded.f32.sharded_s2.win_put.mbps": 1.0,
        "sharded.f32.s4.wire_reduction_x": 4.0,
        # r18 serving plane: GATING since r20 — throughput / scaling /
        # wire-ratio rows gate; the lower-better latency rows stay info
        # (compare()'s band is higher-is-better)
        "serve.pull_mbps_4shard_net": 900.0,
        "serve.pull_scaling_x_net": 3.0,
        "serve.int8_wire_ratio": 4.0,
        "serve.p50_ms": 6.0,                 # latency: out
        "serve.p99_ms": 500.0,               # latency: out
        # r21 request-path attribution: serve.trace.* and slo.* are
        # INFO-ONLY (lower-better phase tails / run-length counters)
        "serve.trace.requests": 400.0,       # out
        "serve.trace.phase.queue.p99_us": 900.0,  # out
        "slo.requests": 400.0,               # out
        "slo.breach.serve_p99": 3.0,         # out
    }
    kept = pg.gating(metrics)
    assert set(kept) == {"win.f32.win_put.mbps", "win.f32.win_update.mbps",
                         "opt.win_put.img_per_sec",
                         "hybrid.win_put.auto.ov0.img_per_sec",
                         "hybrid.win_put.hosted.ov0.img_per_sec",
                         "codec.int8.f32.win_put.mbps",
                         "codec.topk:0.01.f32.win_update.mbps",
                         "sharded.f32.sharded_s2.win_put.mbps",
                         "sharded.f32.s4.wire_reduction_x",
                         "serve.pull_mbps_4shard_net",
                         "serve.pull_scaling_x_net",
                         "serve.int8_wire_ratio"}


# ---------------------------------------------------------------------------
# the committed baseline
# ---------------------------------------------------------------------------

def test_committed_baseline_is_sound():
    pg = _load_perf_gate()
    with open(os.path.join(_REPO, "PERF_BASELINE.json")) as f:
        doc = json.load(f)
    assert doc["meta"]["kind"] == "perf_gate"
    metrics = doc["metrics"]
    assert metrics, "empty baseline"
    # every baseline metric is a positive gating metric (no noisy series
    # baked in, nothing the gate would ignore)
    assert all(v > 0 for v in metrics.values())
    assert set(pg.gating(metrics)) == set(metrics)
    # the exact series make perf-gate red on a seeded slowdown
    assert any(k.startswith("opt.") for k in metrics)
    assert any(".win_put.mbps" in k for k in metrics)
    assert any(".win_update.mbps" in k for k in metrics)
    # codec.* graduated to gating in r18: measured rows committed
    assert any(k.startswith("codec.") and k.endswith(".win_put.mbps")
               for k in metrics)
    assert any(k.startswith("codec.") and k.endswith(".win_update.mbps")
               for k in metrics)
    # sharded.* graduated to gating in r19: measured mbps rows AND the
    # counter-delta wire-reduction ratios committed
    assert any(k.startswith("sharded.") and k.endswith(".win_put.mbps")
               for k in metrics)
    assert any(k.startswith("sharded.") and k.endswith(".wire_reduction_x")
               for k in metrics)
    # serve.* graduated to gating in r20: measured pull-throughput,
    # scaling, and wire-ratio rows committed; NO latency (lower-better)
    # row may ever be baked in under the higher-is-better band
    assert any(k.startswith("serve.pull_mbps_") for k in metrics)
    assert "serve.pull_scaling_x_net" in metrics
    assert "serve.int8_wire_ratio" in metrics
    assert not any(k.startswith("serve.") and k.endswith("_ms")
                   for k in metrics)
    # r21 request-path attribution rides along INFO-ONLY: no slo.* or
    # serve.trace.* key may ever be baked into the committed baseline
    assert not any(k.startswith(("slo.", "serve.trace."))
                   for k in metrics)


# ---------------------------------------------------------------------------
# seeded-slowdown knob (the red path's mechanism)
# ---------------------------------------------------------------------------

def test_delay_knob_bites_optimizer_step(monkeypatch):
    from bluefog_tpu import optimizers

    monkeypatch.setenv("BLUEFOG_PERF_GATE_DELAY_MS", "30")
    t0 = time.perf_counter()
    optimizers._perf_gate_delay()
    assert time.perf_counter() - t0 >= 0.025
    monkeypatch.delenv("BLUEFOG_PERF_GATE_DELAY_MS")
    t0 = time.perf_counter()
    optimizers._perf_gate_delay()
    assert time.perf_counter() - t0 < 0.02  # off: no sleep


def test_delay_knob_bites_window_op_timer(monkeypatch):
    from bluefog_tpu.ops import windows

    monkeypatch.setenv("BLUEFOG_PERF_GATE_DELAY_MS", "30")
    t0 = time.perf_counter()
    with windows._op_timer("WIN_PUT"):
        pass
    assert time.perf_counter() - t0 >= 0.025


def test_update_baseline_refuses_seeded_slowdown(monkeypatch, tmp_path):
    pg = _load_perf_gate()
    monkeypatch.setenv("BLUEFOG_PERF_GATE_DELAY_MS", "30")
    rc = pg.main(["--update-baseline",
                  "--baseline", str(tmp_path / "b.json")])
    assert rc == 2
    assert not (tmp_path / "b.json").exists()


def test_bench_doc_shape():
    pg = _load_perf_gate()
    doc = pg.bench_doc({"m": 1.0}, repeats=3, band=0.4)
    assert doc["meta"]["kind"] == "perf_gate"
    assert doc["metrics"] == {"m": 1.0}
    json.dumps(doc)  # serializable
