"""Telemetry plane: metrics registry, health aggregation, trace correlation.

Covers the r10 acceptance surface in-process:

  * registry instruments + packed-snapshot pack/unpack roundtrip;
  * Prometheus text-exposition lint;
  * ``bf.cluster_health()`` straggler + mass-drift detection (synthetic
    lagging snapshot) and the healthy-job conserved verdict on a real
    4-rank push-sum run;
  * ``bfrun --status`` (the launcher's ``_status``) printing the same
    view through a raw external control-plane client;
  * a merged two-rank timeline containing flow-event pairs that link a
    hosted-plane deposit to its drain — parsed, not eyeballed;
  * the ``[rank r / inc i]`` log-record prefix.
"""

import json
import os
import re
import socket
import struct
import time
import timeit

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import metrics as metrics_mod
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.state import _global_state
from bluefog_tpu.runtime.timeline import Timeline

from conftest import cpu_devices


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# registry + snapshot wire format
# ---------------------------------------------------------------------------

def test_instruments_and_snapshot():
    r = metrics_mod.Registry()
    c = r.counter("t.hits")
    c.inc()
    c.inc(4)
    assert c.value == 5
    assert r.counter("t.hits") is c  # same instrument back
    g = r.gauge("t.depth")
    g.set(3)
    g.add(2.5)
    assert g.value == 5.5
    h = r.histogram("t.lat", bounds=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 5.0):
        h.observe(v)
    assert h.counts == [1, 1, 1, 1] and h.count == 4
    snap = r.snapshot(include_native=False)
    assert snap["counters"]["t.hits"] == 5.0
    assert snap["gauges"]["t.depth"] == 5.5
    assert snap["hists"]["t.lat"]["count"] == 4
    # reset zeroes in place, instrument identity preserved
    r.reset()
    assert c.value == 0 and r.counter("t.hits") is c
    assert h.count == 0


def test_histogram_rejects_unsorted_bounds():
    r = metrics_mod.Registry()
    with pytest.raises(ValueError):
        r.histogram("bad", bounds=(1.0, 0.5))


def test_pack_unpack_roundtrip():
    r = metrics_mod.Registry()
    r.counter("a.b").inc(7)
    r.gauge("g").set(-2.25)
    h = r.histogram("lat")
    h.observe(0.002)
    h.observe(12.0)
    snap = r.snapshot(include_native=False)
    snap["meta"].update(rank=3, inc=2)
    blob = metrics_mod.pack_snapshot(snap)
    back = metrics_mod.unpack_snapshot(blob)
    assert back["meta"]["rank"] == 3 and back["meta"]["inc"] == 2
    assert back["meta"]["ts"] == pytest.approx(snap["meta"]["ts"])
    assert back["counters"] == snap["counters"]
    assert back["gauges"] == snap["gauges"]
    assert back["hists"]["lat"]["counts"] == snap["hists"]["lat"]["counts"]
    assert back["hists"]["lat"]["sum"] == pytest.approx(12.002)
    # garbage is rejected, not misparsed
    with pytest.raises(ValueError):
        metrics_mod.unpack_snapshot(b"XXXX" + blob[4:])
    with pytest.raises((ValueError, struct.error)):
        metrics_mod.unpack_snapshot(blob[:10])


def test_counter_hot_path_is_cheap():
    """The strict < 100 ns gate runs in `make metrics-smoke`; this is the
    in-suite sanity bound (CI boxes share cores with the test runner)."""
    c = metrics_mod.Registry().counter("bench")
    n = 100_000
    per = min(timeit.repeat("inc()", globals={"inc": c.inc},
                            number=n, repeat=5)) / n
    assert per < 500e-9, f"counter inc costs {per * 1e9:.0f} ns"


# ---------------------------------------------------------------------------
# prometheus exposition
# ---------------------------------------------------------------------------

_METRIC_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+-]+$")


def test_prometheus_exposition_lints():
    r = metrics_mod.Registry()
    r.counter("ops.total").inc(3)
    r.gauge("mailbox.bytes").set(1024)
    h = r.histogram("lat", bounds=(0.1, 1.0))
    h.observe(0.05)
    h.observe(2.0)
    snap = r.snapshot(include_native=False)
    snap["meta"]["rank"] = 1
    text = metrics_mod.prometheus_text(snap)
    lines = text.strip().splitlines()
    assert lines, "empty exposition"
    for i, line in enumerate(lines):
        if line.startswith("# TYPE"):
            assert re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* "
                            r"(counter|gauge|histogram)$", line), line
            # self-describing scrape: every family carries a HELP line
            m = line.split()[2]
            assert i > 0 and lines[i - 1].startswith(f"# HELP {m} "), \
                f"TYPE without HELP: {line!r}"
        elif line.startswith("#"):
            assert re.match(r"^# HELP [a-zA-Z_:][a-zA-Z0-9_:]* \S", line), \
                line
        else:
            assert _METRIC_RE.match(line), line
    # histogram structure: cumulative buckets + +Inf + sum/count
    assert 'bluefog_lat_bucket{rank="1",le="0.1"} 1' in lines
    assert 'bluefog_lat_bucket{rank="1",le="1"} 1' in lines
    assert 'bluefog_lat_bucket{rank="1",le="+Inf"} 2' in lines
    assert 'bluefog_lat_count{rank="1"} 2' in lines
    # name sanitization: dots become underscores, prefix applied
    assert any(l.startswith("bluefog_mailbox_bytes{") for l in lines)


# ---------------------------------------------------------------------------
# health aggregation logic (synthetic snapshots — no mesh needed)
# ---------------------------------------------------------------------------

def _snap(rank, step=None, mass=None, minted=None, ts=None, inc=0,
          epoch=0):
    gauges = {"membership.epoch": float(epoch)}
    if step is not None:
        gauges["opt.step"] = float(step)
    if mass is not None:
        gauges["pushsum.mass"] = float(mass)
    if minted is not None:
        gauges["pushsum.minted"] = float(minted)
    return {"meta": {"schema": 1, "rank": rank, "inc": inc,
                     "ts": time.time() if ts is None else ts},
            "counters": {}, "gauges": gauges, "hists": {}}


def test_health_flags_straggler_by_step_spread(monkeypatch):
    monkeypatch.setenv("BLUEFOG_STRAGGLER_STEPS", "3")
    snaps = {0: _snap(0, step=50), 1: _snap(1, step=49),
             2: _snap(2, step=40)}
    h = metrics_mod.health_from_snapshots(snaps, world=3, interval=1.0)
    assert h["stragglers"] == [2]
    assert h["ranks"][0]["step"] == 50 and h["ranks"][2]["step"] == 40
    assert h["missing"] == []


def test_health_staleness_and_missing():
    snaps = {0: _snap(0, step=10), 1: _snap(1, step=10, ts=time.time() - 60)}
    h = metrics_mod.health_from_snapshots(snaps, world=3, interval=1.0)
    assert h["ranks"][0]["alive"] and not h["ranks"][1]["alive"]
    assert h["missing"] == [2]


def test_health_mass_conservation_and_drift():
    ok = {0: _snap(0, mass=2.0, minted=2.0), 1: _snap(1, mass=2.0,
                                                      minted=2.0)}
    h = metrics_mod.health_from_snapshots(ok, world=2, interval=1.0)
    assert h["mass"]["conserved"] and h["mass"]["drift"] == 0.0
    # lost deposits: a rank's mass fell measurably below what was minted
    bad = {0: _snap(0, mass=1.25, minted=2.0), 1: _snap(1, mass=2.0,
                                                        minted=2.0)}
    h = metrics_mod.health_from_snapshots(bad, world=2, interval=1.0)
    assert not h["mass"]["conserved"]
    assert h["mass"]["drift"] == pytest.approx(-0.75)
    # a dead rank's snapshot drops out of BOTH sums (live-rank check)
    stale = {0: _snap(0, mass=2.0, minted=2.0),
             1: _snap(1, mass=2.0, minted=2.0, ts=time.time() - 600)}
    h = metrics_mod.health_from_snapshots(stale, world=2, interval=1.0)
    assert h["mass"]["conserved"] and h["mass"]["total"] == 2.0


def test_format_health_mentions_everything():
    snaps = {0: _snap(0, step=9, mass=1.0, minted=1.0),
             1: _snap(1, step=2)}
    h = metrics_mod.health_from_snapshots(snaps, world=3, interval=1.0)
    text = metrics_mod.format_health(h)
    assert "rank 0" in text and "rank 1" in text
    assert "STRAGGLER" in text
    assert "no snapshot published" in text  # rank 2
    assert "conserved" in text


# ---------------------------------------------------------------------------
# end-to-end through the control plane (real job, real KV)
# ---------------------------------------------------------------------------

@pytest.fixture()
def bf_hosted_metrics(monkeypatch):
    """4-rank job, forced control plane + hosted plane, publication on."""
    if native.load() is None:
        pytest.skip("native runtime unavailable")
    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
        "BLUEFOG_METRICS_INTERVAL": "1",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(4))
    assert cp.active()
    yield bf
    bf.shutdown()
    cp.reset_for_test()


def _run_pushsum_steps(bf_, steps=3, prefix="met.ps"):
    import jax.numpy as jnp
    import optax

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf_.DistributedPushSumOptimizer(optax.sgd(0.1), zloss,
                                          window_prefix=prefix)
    state = opt.init({"w": jnp.ones((4,), jnp.float32)})
    for _ in range(steps):
        state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))
    return opt


def test_cluster_health_end_to_end(bf_hosted_metrics):
    """Acceptance: a 4-rank in-process job reports per-rank step counters
    and push-sum total mass within the ulp-scaled tolerance of minted
    mass; an artificially-stalled rank is flagged a straggler; and
    ``bfrun --status`` prints the same view from a raw external client."""
    bf_ = bf_hosted_metrics
    opt = _run_pushsum_steps(bf_, steps=5)
    snap = metrics_mod.publish_now()
    assert snap is not None

    # published packed snapshot landed in the KV and unpacks
    blob = cp.client().get_bytes("bf.metrics.0")
    assert blob
    back = metrics_mod.unpack_snapshot(blob)
    assert back["gauges"]["opt.step"] == 5.0

    health = bf_.cluster_health()
    assert health["ranks"][0]["step"] == 5
    assert health["mass"] is not None
    assert health["mass"]["minted"] == pytest.approx(4.0)
    assert health["mass"]["conserved"], health["mass"]
    assert health["stragglers"] == []

    # artificially-stalled rank: a second controller's snapshot lagging
    # the fleet by more than the straggler threshold
    lag = _snap(1, step=1, ts=time.time())
    cp.client().put_bytes("bf.metrics.1", metrics_mod.pack_snapshot(lag))
    cp.client().put("bf.metrics.world", 2)  # the simulated job's world
    merged = metrics_mod.read_cluster_health(cp.client(), world=2)
    assert merged["stragglers"] == [1]
    assert merged["ranks"][1]["step"] == 1

    # bfrun --status: same view through a RAW external client (the
    # launcher's exact code path, no bf.init on that side)
    from bluefog_tpu import launcher

    class _Args:
        cp = f"127.0.0.1:{os.environ['BLUEFOG_CP_PORT']}"
        status = True

    import contextlib
    import io
    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = launcher._status(_Args())
    assert rc == 0
    text = out.getvalue()
    assert "rank 0" in text and "step 5" in text
    assert "STRAGGLER" in text  # the synthetic lagging rank 1
    assert "conserved" in text
    opt.free()


def test_publication_piggyback_and_prom_file(bf_hosted_metrics, tmp_path,
                                             monkeypatch):
    bf_ = bf_hosted_metrics
    prom = tmp_path / "scrape.prom"
    monkeypatch.setenv("BLUEFOG_METRICS_PROM", str(prom))
    opt = _run_pushsum_steps(bf_, steps=2, prefix="met.prom")
    snap = metrics_mod.publish_now()
    assert snap is not None
    text = prom.read_text()
    assert "bluefog_opt_step" in text
    assert "bluefog_pushsum_mass" in text
    # the interval gate: an immediate second maybe_publish is a no-op
    before = cp.client().bytes_len("bf.metrics.0")
    metrics_mod.maybe_publish()
    assert cp.client().bytes_len("bf.metrics.0") == before
    opt.free()


def test_win_op_histograms_and_drain_counters(bf_hosted_metrics):
    """Window data-plane instrumentation: op latency histograms fill and
    the drain counters move when deposits actually flow."""
    import jax.numpy as jnp

    bf_ = bf_hosted_metrics
    x = bf_.shard_rank_stacked(bf_.mesh(), jnp.ones((4, 8)))
    assert bf_.win_create(x, "met.win")
    h_put = metrics_mod.histogram("win.put_sec")
    h_upd = metrics_mod.histogram("win.update_sec")
    puts0, upds0 = h_put.count, h_upd.count
    bf_.win_put(x, "met.win")
    bf_.win_update(name="met.win")
    assert h_put.count == puts0 + 1
    assert h_upd.count == upds0 + 1
    bf_.win_free("met.win")


# ---------------------------------------------------------------------------
# cross-process trace correlation (acceptance: merged flow pair)
# ---------------------------------------------------------------------------

def test_merged_timeline_binds_deposit_to_drain(bf_hosted_metrics,
                                                tmp_path, monkeypatch):
    """Two in-process 'controllers' — origin owning ranks 0..1, owner
    owning ranks 2..3 — write separate per-rank trace files; the merged
    timeline must contain >= 1 flow pair (same id, 's' at the origin, 'f'
    at the drain), validated by parsing, plus balanced B/E spans."""
    import jax.numpy as jnp

    from bluefog_tpu.ops import windows as win_mod

    bf_ = bf_hosted_metrics
    st = _global_state()
    x = bf_.shard_rank_stacked(bf_.mesh(), jnp.ones((4, 16)))

    # controller A: owns ranks 0..1 (its window half); deposits to 2..3
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [0, 1])
    assert bf_.win_create(x, "flow.win", zero_init=True)
    win_a = st.windows["flow.win"]
    assert win_a.hosted and set(win_a.owned) == {0, 1}

    # controller B: a second Window object under the SAME name, owning
    # the other half — its mailbox keys are the ones A deposits into
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [2, 3])
    win_b = win_mod.Window("flow.win", np.ones((4, 16), np.float32),
                           zero_init=True)
    assert set(win_b.owned) == {2, 3}

    # rank-0 trace: the deposits (flow starts) happen under A
    st.timeline = Timeline(str(tmp_path / "tl_"), process_index=0,
                           use_native=False)
    bf_.win_put(x, "flow.win")
    st.timeline.close()
    path0 = st.timeline.path

    # rank-1 trace: B drains A's deposits (flow finishes)
    st.timeline = Timeline(str(tmp_path / "tl_"), process_index=1,
                           use_native=False)
    with win_b.state_mu:
        win_b._drain_deposits()
    st.timeline.close()
    path1 = st.timeline.path
    st.timeline = None

    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        import merge_timelines
        merged = merge_timelines.merge([path0, path1])
    finally:
        sys.path.pop(0)
    out = tmp_path / "merged.json"
    out.write_text(json.dumps(merged))
    events = json.loads(out.read_text())

    starts = {e["id"]: e for e in events if e.get("ph") == "s"}
    finishes = {e["id"]: e for e in events if e.get("ph") == "f"}
    pairs = set(starts) & set(finishes)
    assert pairs, "no flow pair linking a deposit to its drain"
    for fid in pairs:
        assert starts[fid]["pid"] == 0 and finishes[fid]["pid"] == 1
        assert starts[fid]["name"] == "WIN_DEPOSIT"
        # merged clock: the drain cannot precede its deposit
        assert finishes[fid]["ts"] >= starts[fid]["ts"]
    # chrome-tracing validity: balanced B/E per (pid, cat, tid) lane
    open_spans = {}
    for e in events:
        key = (e.get("pid"), e.get("cat"), e.get("tid"))
        if e.get("ph") == "B":
            open_spans[key] = open_spans.get(key, 0) + 1
        elif e.get("ph") == "E":
            open_spans[key] = open_spans.get(key, 0) - 1
            assert open_spans[key] >= 0, f"E without B for {key}"
    assert all(v == 0 for v in open_spans.values())
    # win_free must not trip over the second window's state: clean up the
    # registered one only
    bf_.win_free("flow.win")


# ---------------------------------------------------------------------------
# logging prefix satellite
# ---------------------------------------------------------------------------

def test_log_records_carry_rank_incarnation_prefix():
    from bluefog_tpu.runtime.logging import _RankPrefixFilter

    assert _RankPrefixFilter._prefix() == ""  # before init
    bf.init(devices=cpu_devices(4))
    try:
        assert _RankPrefixFilter._prefix() == "[rank 0 / inc 0] "
        import logging as _logging

        rec = _logging.LogRecord("bluefog_tpu", _logging.WARNING, __file__,
                                 1, "msg", (), None)
        assert _RankPrefixFilter().filter(rec)
        assert rec.bfprefix == "[rank 0 / inc 0] "
    finally:
        bf.shutdown()
    assert _RankPrefixFilter._prefix() == ""  # after shutdown
