"""Runtime lifecycle + topology management (model: test/torch_basics_test.py)."""

import jax.numpy as jnp
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology_util


class TestLifecycle:
    def test_init_size(self, bf8):
        assert bf8.size() == 8
        assert bf8.local_size() == 4
        assert bf8.num_machines() == 2
        assert bf8.is_homogeneous()

    def test_requires_init(self):
        bf.shutdown()
        with pytest.raises(RuntimeError, match="not initialized"):
            bf.size()

    def test_default_topology_is_expo2(self, bf8):
        assert topology_util.IsTopologyEquivalent(
            bf8.load_topology(), topology_util.ExponentialTwoGraph(8)
        )
        assert not bf8.is_topo_weighted()

    def test_set_topology_and_load(self, bf8):
        # parity: torch_basics_test.py set/load equivalence checks
        assert bf8.set_topology(topology_util.RingGraph(8))
        assert topology_util.IsTopologyEquivalent(
            bf8.load_topology(), topology_util.RingGraph(8)
        )

    def test_set_topology_wrong_size_rejected(self, bf8):
        assert not bf8.set_topology(topology_util.RingGraph(4))

    def test_set_topology_blocked_by_windows(self, bf8):
        # parity: torch_basics_test.py:63-78 — topology change must fail
        # while a window exists, succeed after win_free.
        x = jnp.ones((8, 4))
        assert bf8.win_create(x, "blocker")
        assert not bf8.set_topology(topology_util.RingGraph(8))
        assert bf8.win_free("blocker")
        assert bf8.set_topology(topology_util.RingGraph(8))

    def test_neighbor_queries(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))  # bidirectional
        assert bf8.in_neighbor_ranks(0) == [1, 7]
        assert bf8.out_neighbor_ranks(3) == [2, 4]

    def test_reinit(self, bf8):
        import jax

        bf.init(devices=jax.devices("cpu")[:4], local_size=2)
        assert bf.size() == 4
        bf.shutdown()
