"""Keras 3 (JAX backend) frontend: the TF-family migration target.

Holds bluefog_tpu.keras to the reference TF frontend's contracts
(tensorflow/optimizers.py): gradient averaging equals the mean-gradient
step, broadcast_variables equalizes replicas, and the decentralized mode
drives replicas toward consensus.
"""

import os

import numpy as np
import pytest

os.environ.setdefault("KERAS_BACKEND", "jax")
keras = pytest.importorskip("keras")
if keras.backend.backend() != "jax":  # pragma: no cover
    pytest.skip("keras must run the jax backend", allow_module_level=True)

import bluefog_tpu as bf  # noqa: E402
import bluefog_tpu.keras as bfk  # noqa: E402

N = 8


def _models(seed=0):
    out = []
    for r in range(N):
        keras.utils.set_random_seed(seed + r)
        m = keras.Sequential([keras.layers.Dense(2, use_bias=True)])
        m.build((None, 4))
        out.append(m)
    return out


def test_broadcast_variables(bf8):
    mods = _models()
    want = [np.asarray(v) for v in mods[3].trainable_variables]
    bfk.broadcast_variables(mods, root_rank=3)
    for m in mods:
        for v, w in zip(m.trainable_variables, want):
            np.testing.assert_allclose(np.asarray(v), w, atol=1e-6)


def test_allreduce_mode_matches_mean_gradient_step(bf8):
    """Reference TF DistributedOptimizer semantics: applying per-rank
    grads through the wrapper equals applying the rank-MEAN gradient."""
    mods = _models(seed=5)
    bfk.broadcast_variables(mods, root_rank=0)  # identical start
    opt = bfk.DistributedOptimizer(
        lambda: keras.optimizers.SGD(0.5), mods,
        communication_type="allreduce")
    rng = np.random.RandomState(0)
    grads_per_rank = [
        [rng.randn(*v.shape).astype(np.float32)
         for v in mods[r].trainable_variables]
        for r in range(N)]
    w0 = [np.asarray(v) for v in mods[0].trainable_variables]
    opt.apply_stacked(grads_per_rank)
    mean_g = [np.mean([grads_per_rank[r][i] for r in range(N)], axis=0)
              for i in range(len(w0))]
    for m in mods:  # every replica took the SAME mean-gradient step
        for v, w, g in zip(m.trainable_variables, w0, mean_g):
            np.testing.assert_allclose(np.asarray(v), w - 0.5 * g,
                                       atol=1e-5)


def test_neighbor_mode_drives_consensus(bf8):
    mods = _models(seed=11)
    opt = bfk.DistributedOptimizer(
        lambda: keras.optimizers.SGD(0.0), mods,
        communication_type="neighbor.allreduce")
    zero = [[np.zeros(v.shape, np.float32) for v in m.trainable_variables]
            for m in mods]
    for _ in range(25):
        opt.apply_stacked(zero)  # lr=0 -> pure consensus mixing
    w = np.stack([np.asarray(m.trainable_variables[0]) for m in mods])
    assert np.abs(w - w.mean(axis=0, keepdims=True)).max() < 1e-3


def test_device_resident_matches_host_path(bf8):
    """ISSUE r15 satellite (the torch r13 `_DevicePlan` pattern ported):
    the device-resident communicate must be numerically identical to the
    legacy host stack/scatter path, and the plan must really hold
    device-side rows (no host gather between steps)."""
    runs = {}
    for resident in (False, True):
        mods = _models(seed=21)
        bfk.broadcast_variables(mods, root_rank=0)
        # re-diverge deterministically so mixing has work to do
        for r, m in enumerate(mods):
            for v in m.trainable_variables:
                v.assign(np.asarray(v) + np.float32(r) * 0.1)
        opt = bfk.DistributedOptimizer(
            lambda: keras.optimizers.SGD(0.0), mods,
            communication_type="neighbor.allreduce",
            device_resident=resident)
        zero = [[np.zeros(v.shape, np.float32)
                 for v in m.trainable_variables] for m in mods]
        for _ in range(4):
            opt.apply_stacked(zero)  # lr=0 -> pure consensus mixing
        runs[resident] = np.stack(
            [np.asarray(m.trainable_variables[0]) for m in mods])
        if resident:
            plan = bfk._comm_plan(mods)
            assert plan.device is not None, "residency failed to install"
            assert plan.device.rows[0][0].shape[0] == 1  # [1, ...] rows
    np.testing.assert_allclose(runs[True], runs[False], rtol=1e-6,
                               atol=1e-6)


def test_device_resident_survives_variable_rebind(bf8):
    """A keras optimizer (or user code) assigning a fresh value mints a
    NEW jax array — the device plan's identity check must re-anchor it
    into the resident row before the next communicate, not mix a stale
    copy."""
    mods = _models(seed=23)
    opt = bfk.DistributedOptimizer(
        lambda: keras.optimizers.SGD(0.0), mods,
        communication_type="neighbor.allreduce")
    zero = [[np.zeros(v.shape, np.float32)
             for v in m.trainable_variables] for m in mods]
    opt.apply_stacked(zero)  # installs residency + one mixing
    plan = bfk._comm_plan(mods)
    assert plan.device is not None
    # rebind rank 3's kernel out-of-band
    v3 = mods[3].trainable_variables[0]
    v3.assign(np.full(v3.shape, 2.5, np.float32))
    opt.apply_stacked(zero)  # re-anchors, then mixes the rebound value
    # rank 3's 2.5s entered the average: its own row is a blend now
    assert not np.allclose(np.asarray(v3), 2.5)
    # and some in-neighbor of rank 3 moved toward 2.5 (got a share)
    import bluefog_tpu as _bf
    topo = _bf.load_topology()
    moved = [r for r in range(N)
             if 3 in _bf.topology_util.in_neighbor_ranks(topo, r)]
    assert any(
        np.asarray(mods[r].trainable_variables[0]).mean() > 0.1
        for r in moved)


def test_validations(bf8):
    mods = _models()
    with pytest.raises(ValueError, match="communication_type"):
        bfk.DistributedOptimizer(lambda: keras.optimizers.SGD(0.1), mods,
                                 communication_type="bogus")
    opt = bfk.DistributedOptimizer(lambda: keras.optimizers.SGD(0.1), mods)
    with pytest.raises(ValueError, match="factory"):
        bfk.DistributedOptimizer(keras.optimizers.SGD(0.1), mods)
    with pytest.raises(ValueError, match="one gradient list"):
        opt.apply_stacked([[np.zeros((4, 2), np.float32)]])
