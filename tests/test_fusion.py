"""Fusion: packed exchange buffers for window gossip + many-small-ops load.

Round-1 gap (VERDICT #4): ops/fusion.py existed with zero consumers. Now the
window optimizers batch parameter leaves into [n, total] buffers gated by
BLUEFOG_FUSION_THRESHOLD (reference: FusionBufferManager,
tensor_queue.cc:127-155; fusion tests torch_ops_test.py:210, 920, 962).
"""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

import bluefog_tpu as bf
from bluefog_tpu.ops import fusion
from bluefog_tpu.runtime.state import _global_state

from conftest import cpu_devices

N = 8


def deep_params(seed=0, leaves=12):
    """Many small leaves — the per-parameter-window pathological case."""
    rng = np.random.RandomState(seed)
    return {
        f"layer{i}": {"w": jnp.asarray(rng.randn(N, 3, 2).astype(np.float32)),
                      "b": jnp.asarray(rng.randn(N, 2).astype(np.float32))}
        for i in range(leaves // 2)
    }


def zero_loss(p, b):
    return 0.0 * sum(jnp.sum(x) for x in jax.tree_util.tree_leaves(p))


def test_pack_unpack_roundtrip():
    tree = deep_params(1)
    leaves = jax.tree_util.tree_leaves(tree)
    spec = fusion.make_spec(leaves)
    buf = fusion.pack_jit(leaves, spec)
    assert buf.shape == (N, spec.total)
    back = fusion.unpack_jit(buf, spec)
    for a, b in zip(leaves, back):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_group_leaves_threshold():
    leaves = [jnp.zeros((N, 100), jnp.float32) for _ in range(10)]
    per_leaf = 100 * 4  # threshold counts PER-RANK bytes (leading dim dropped)
    assert fusion.group_leaves(leaves, 0) == [[i] for i in range(10)]
    assert fusion.group_leaves(leaves, per_leaf * 10) == [list(range(10))]
    gs = fusion.group_leaves(leaves, per_leaf * 3)
    assert all(len(g) <= 3 for g in gs)
    assert sorted(i for g in gs for i in g) == list(range(10))


def test_group_leaves_does_not_mix_dtypes():
    leaves = [jnp.zeros((N, 4), jnp.float32), jnp.zeros((N, 4), jnp.bfloat16),
              jnp.zeros((N, 4), jnp.bfloat16)]
    gs = fusion.group_leaves(leaves, 1 << 30)
    assert gs == [[0], [1, 2]]


def _run_winput_consensus(threshold, monkeypatch):
    monkeypatch.setenv("BLUEFOG_FUSION_THRESHOLD", str(threshold))
    bf.init(devices=cpu_devices(8))
    try:
        params0 = deep_params(2)
        opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zero_loss)
        single = jax.tree_util.tree_map(lambda x: x[0], params0)
        st0 = opt.init(single)
        n_windows = len(_global_state().windows)
        state = bf.TrainState(
            params=jax.device_put(params0, bf.rank_sharding(bf.mesh())),
            opt_state=st0.opt_state, model_state=None)
        batch = jnp.zeros((N, 1), jnp.float32)
        for _ in range(5):
            state, _ = opt.step(state, batch)
        out = jax.tree_util.tree_map(np.asarray, state.params)
        opt.free()
        return n_windows, out
    finally:
        bf.shutdown()


@pytest.mark.slow  # window+compile heavy; fused_push_sum stays fast
def test_fused_gossip_one_window_and_same_numerics(monkeypatch):
    """Default threshold: 12 leaves -> ONE window (one compiled put+update
    per step); numerics identical to the unfused per-leaf path."""
    nw_fused, fused = _run_winput_consensus(8 << 20, monkeypatch)
    nw_per_leaf, per_leaf = _run_winput_consensus(0, monkeypatch)
    assert nw_fused == 1, f"expected 1 fused window, got {nw_fused}"
    assert nw_per_leaf == 12, f"expected 12 per-leaf windows, got {nw_per_leaf}"
    for a, b in zip(jax.tree_util.tree_leaves(fused),
                    jax.tree_util.tree_leaves(per_leaf)):
        np.testing.assert_allclose(a, b, atol=1e-6)


def test_fused_push_sum_consensus(monkeypatch):
    """Push-sum's associated-p channel must survive fusion (one p per
    window covers the whole packed group)."""
    monkeypatch.setenv("BLUEFOG_FUSION_THRESHOLD", str(8 << 20))
    bf.init(devices=cpu_devices(8))
    try:
        params0 = deep_params(3, leaves=6)
        opt = bf.DistributedPushSumOptimizer(optax.sgd(0.1), zero_loss)
        single = jax.tree_util.tree_map(lambda x: x[0], params0)
        st0 = opt.init(single)
        assert len(opt._win_names) == 1
        # install true per-rank values into the packed window numerator
        leaves = jax.tree_util.tree_leaves(
            jax.device_put(params0, bf.rank_sharding(bf.mesh())))
        packed = fusion.pack_jit(leaves, opt._specs[0])
        _global_state().windows[opt._win_names[0]].self_value = packed
        state = bf.TrainState(
            params=jax.device_put(params0, bf.rank_sharding(bf.mesh())),
            opt_state=st0.opt_state, model_state=None)
        batch = jnp.zeros((N, 1), jnp.float32)
        for _ in range(40):
            state, _ = opt.step(state, batch)
        got = jax.tree_util.tree_map(np.asarray, state.params)
        for leaf0, leafN in zip(jax.tree_util.tree_leaves(params0),
                                jax.tree_util.tree_leaves(got)):
            expect = np.mean(np.asarray(leaf0, dtype=np.float64), axis=0)
            for r in range(N):
                np.testing.assert_allclose(leafN[r], expect, atol=1e-2)
        opt.free()
        bf.turn_off_win_ops_with_associated_p()
    finally:
        bf.shutdown()


@pytest.mark.slow
def test_many_small_nonblocking_ops_then_synchronize(bf8):
    """Port of the reference's fusion-under-load pattern
    (torch_ops_test.py:920): launch many small nonblocking ops, then
    synchronize them all; every result must be exact."""
    topo = bf.load_topology()
    import bluefog_tpu.topology as topology_util
    W = np.zeros((N, N))
    for r in range(N):
        nbrs = topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        W[r, r] = u
        for s in nbrs:
            W[s, r] = u
    handles = []
    inputs = []
    for i in range(50):
        x = jnp.full((N, 3), float(i)) + jnp.arange(N)[:, None]
        inputs.append(np.asarray(x, dtype=np.float64))
        handles.append(bf.neighbor_allreduce_nonblocking(x, name=f"fuse.{i}"))
    for i, h in enumerate(handles):
        out = bf.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), W.T @ inputs[i], atol=1e-5)
