"""Transformer + context parallelism: sharded run == dense single-device run."""

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu import parallel as bfp
from bluefog_tpu.models import TransformerLM

N = 8
VOCAB = 64


def make_model():
    # 8 heads: divisible by the 8-device mesh so Ulysses can shard heads.
    return TransformerLM(vocab_size=VOCAB, num_layers=2, num_heads=8,
                         d_model=64, d_ff=128)


def make_batch(seed=0, B=2, S=32):
    key = jax.random.PRNGKey(seed)
    return jax.random.randint(key, (B, S), 0, VOCAB)


@pytest.mark.parametrize("kind", ["ring", "ulysses"])
def test_cp_apply_matches_dense(bf8, kind):
    model = make_model()
    tokens = make_batch()
    variables = model.init(jax.random.PRNGKey(1), tokens)
    want = model.apply(variables, tokens)
    got = bfp.cp_apply(model, variables, tokens, kind=kind)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


@pytest.mark.slow  # grad-of-ring-scan compile is minutes-scale on 1 core
def test_cp_loss_and_grads_match_dense(bf8):
    model = make_model()
    tokens = make_batch(1)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(2), tokens)["params"]

    def dense_loss(p, batch):
        toks, tgts = batch
        logits = model.apply({"params": p}, toks)
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.take_along_axis(logp, tgts[..., None], axis=-1).mean()

    cp_loss = bfp.cp_loss_fn(model)
    lw, gw = jax.value_and_grad(dense_loss)(params, (tokens, targets))
    lg, gg = jax.jit(jax.value_and_grad(cp_loss))(params, (tokens, targets))
    np.testing.assert_allclose(float(lg), float(lw), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(gg),
                    jax.tree_util.tree_leaves(gw)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-4, rtol=1e-3)


@pytest.mark.slow
def test_cp_training_step_decreases_loss(bf8):
    model = make_model()
    tokens = make_batch(3, B=2, S=64)
    targets = jnp.roll(tokens, -1, axis=1)
    params = model.init(jax.random.PRNGKey(4), tokens)["params"]
    loss_fn = bfp.cp_loss_fn(model)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s, batch):
        l, g = jax.value_and_grad(loss_fn)(p, batch)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, l

    losses = []
    for _ in range(10):
        params, opt_state, l = step(params, opt_state, (tokens, targets))
        losses.append(float(l))
    assert losses[-1] < losses[0]


def test_chunked_ce_loss_exact_and_grad_matches():
    """parallel.chunked_ce_loss computes the same loss AND gradients as
    the full-logits cross-entropy (it's a re-association of the same
    sums), while never materializing [S, V] logits."""
    import optax

    from bluefog_tpu import parallel as bfp
    from bluefog_tpu.models import TransformerLM

    model = TransformerLM(vocab_size=64, num_layers=2, num_heads=2,
                          d_model=32, d_ff=64)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 32), 0, 64)
    tgts = jnp.roll(toks, -1, axis=1)
    params = model.init(jax.random.PRNGKey(1), toks)["params"]

    def full_loss(p):
        logits = model.apply({"params": p}, toks)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, tgts).mean()

    def chunked(p):
        return bfp.chunked_ce_loss(model, p, toks, tgts, chunk=16)

    lf, gf = jax.value_and_grad(full_loss)(params)
    lc, gc = jax.value_and_grad(chunked)(params)
    np.testing.assert_allclose(float(lf), float(lc), rtol=1e-6)
    for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(gf),
            jax.tree_util.tree_leaves_with_path(gc)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4,
                                   err_msg=jax.tree_util.keystr(pa))
