"""Child for the sharded-OOM acceptance demo (ISSUE r17).

Builds a param tree whose REPLICATED hosted window plane (full-row window
rows + mailbox slots + published copies + packed buffer, ~20x the single
row) cannot fit under an RSS rlimit, then asserts:

* ``--shard 4``: the sharded plane (every window-plane object 1/4-sized)
  creates its window and completes 20 gossip steps with a decreasing
  loss → prints ``SHARDED_TRAIN_OK``.
* ``--shard 1``: replicated packing blows the same limit during window
  creation / the first gossip step → prints ``REPLICATED_OOM``.

The limit is RLIMIT_DATA anchored at the process's usage right before
optimizer init plus a fixed budget sized BETWEEN the two planes' needs,
so the verdict is a property of the window plane, not of the interpreter
baseline. Hosted world-1 plane: window rows and mailboxes are host numpy
(allocation failure is a catchable MemoryError, not an XLA abort).
"""

import argparse
import os
import resource
import sys

# Calibrated on the CI box: anchor-relative peak VmData over 20 gossip
# steps is ~450 MB sharded (S=8) vs ~1450 MB replicated — the window
# plane's rows/mailboxes/publishes/pack transients all scale with the
# row, so the budget sits between the two with ~250 MB margin each way.
BUDGET_MB = 700
ELEMS = 6_000_000  # 24 MB f32 per rank row
N = 4


def vm_data_bytes() -> int:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmData:"):
                return int(line.split()[1]) * 1024
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--shard", type=int, required=True)
    args = ap.parse_args()

    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    # deterministic baseline: exactly N host devices regardless of what
    # the spawning test harness forced (thread pools and per-device
    # buffers all count toward the data limit)
    os.environ["XLA_FLAGS"] = \
        f"--xla_force_host_platform_device_count={N}"
    os.environ.update({
        "JAX_PLATFORMS": "cpu",
        "BLUEFOG_FUSION_THRESHOLD": str(1 << 30),
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_PLANE": "hosted",
    })
    if args.shard > 1:
        os.environ["BLUEFOG_WIN_SHARD"] = str(args.shard)

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    import bluefog_tpu as bf

    bf.init(devices=jax.devices("cpu")[:N])
    rng = np.random.RandomState(0)
    single = {"w": jnp.asarray(rng.randn(ELEMS).astype(np.float32) * 0.1),
              "b": jnp.asarray(rng.randn(64).astype(np.float32))}
    target = 0.5

    def loss(p, b):
        return jnp.mean((p["w"] - target) ** 2) + jnp.mean(p["b"] ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.2), loss)
    # warm the WHOLE gossip path on a throwaway tiny window BEFORE the
    # limit: thread stacks (XLA dispatch pools, control-plane prefetch
    # threads) are private anonymous mmaps and count toward RLIMIT_DATA —
    # without this the run dies in pthread_create (an uncatchable C++
    # terminate) instead of a clean allocation failure at the plane
    # under test
    warm = bf.DistributedWinPutOptimizer(optax.sgd(0.2), loss,
                                         window_prefix="rlimit.warm")
    wstate = warm.init({"w": jnp.ones(2048), "b": jnp.ones(64)})
    for _ in range(2):
        wstate, _ = warm.step(wstate, jnp.zeros((N, 1), jnp.float32))
    warm.free()
    # anchor the limit NOW: everything allocated from here on is the
    # window plane under test (plus the step's compile, inside BUDGET)
    cur = vm_data_bytes()
    limit = cur + BUDGET_MB * (1 << 20)
    resource.setrlimit(resource.RLIMIT_DATA, (limit, limit))
    print(f"rlimit: VmData {cur >> 20} MB + {BUDGET_MB} MB budget "
          f"(shard={args.shard}, row {ELEMS * 4 >> 20} MB, world {N})",
          flush=True)
    try:
        state = opt.init(single)
        batch = jnp.zeros((N, 1), jnp.float32)
        losses = []
        for _ in range(20):
            state, m = opt.step(state, batch)
            losses.append(float(np.asarray(m["loss"]).mean()))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
        print(f"losses: {losses[0]:.4f} -> {losses[-1]:.4f}", flush=True)
        print("SHARDED_TRAIN_OK" if args.shard > 1 else "REPLICATED_FIT",
              flush=True)
        opt.free()
    except (MemoryError, RuntimeError, OSError) as exc:
        # jax CPU raises RuntimeError on allocation failure; numpy raises
        # MemoryError; a torn control-plane publish surfaces as OSError
        print(f"allocation failed: {type(exc).__name__}: "
              f"{str(exc)[:200]}", flush=True)
        print("REPLICATED_OOM" if args.shard == 1 else "SHARDED_OOM",
              flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
