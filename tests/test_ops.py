"""Collective + neighbor op correctness (model: test/torch_ops_test.py).

Same testing philosophy as the reference: exact-value assertions where each
rank's tensor is a rank-determined constant and expected outputs are computed
against the known graph.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from bluefog_tpu import topology_util


def rank_tensor(n=8, shape=(4,), dtype=jnp.float32):
    """x[r] = r (rank-determined constant, reference test style)."""
    base = jnp.arange(n, dtype=dtype).reshape((n,) + (1,) * len(shape))
    return jnp.broadcast_to(base, (n,) + shape)


class TestAllreduce:
    def test_inplace_name_parity_aliases(self, bf8):
        """allreduce_/broadcast_ (the reference's in-place variants) exist
        and return the op result; jax arrays are immutable, so rebinding +
        donation is the in-place analog (mpi_ops.py:150-201)."""
        x = rank_tensor()
        np.testing.assert_allclose(
            np.asarray(bf8.allreduce_(x)), np.asarray(bf8.allreduce(x)))
        np.testing.assert_allclose(
            np.asarray(bf8.broadcast_(x, 2)), np.asarray(bf8.broadcast(x, 2)))
        h = bf8.allreduce_nonblocking_(x)
        np.testing.assert_allclose(np.asarray(bf8.synchronize(h))[:, 0], 3.5)
        h2 = bf8.broadcast_nonblocking_(x, 1)
        np.testing.assert_allclose(np.asarray(bf8.synchronize(h2))[:, 0], 1.0)

    def test_average(self, bf8):
        x = rank_tensor()
        out = bf8.allreduce(x, average=True)
        np.testing.assert_allclose(np.asarray(out), 3.5, atol=1e-6)
        assert out.shape == x.shape

    def test_sum(self, bf8):
        out = bf8.allreduce(rank_tensor(), average=False)
        np.testing.assert_allclose(np.asarray(out), 28.0, atol=1e-6)

    def test_hierarchical_local(self, bf8):
        # local_size=4: machine 0 = ranks 0-3 (mean 1.5), machine 1 = 4-7 (5.5)
        out = bf8.allreduce(rank_tensor(), average=True, is_hierarchical_local=True)
        expected = np.repeat([1.5, 5.5], 4)[:, None] * np.ones((8, 4))
        np.testing.assert_allclose(np.asarray(out), expected, atol=1e-6)

    def test_pytree(self, bf8):
        tree = {"a": rank_tensor(), "b": rank_tensor(shape=(2, 3))}
        out = bf8.allreduce(tree)
        np.testing.assert_allclose(np.asarray(out["a"]), 3.5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out["b"]), 3.5, atol=1e-6)

    def test_nonblocking_poll_synchronize(self, bf8):
        handle = bf8.allreduce_nonblocking(rank_tensor())
        out = bf8.synchronize(handle)
        np.testing.assert_allclose(np.asarray(out), 3.5, atol=1e-6)
        with pytest.raises(ValueError):
            bf8.synchronize(handle)  # double-synchronize rejected

    def test_synchronize_with_deadline_completes(self, bf8):
        # bounded-wait path: a healthy op completes well inside the deadline
        # and the handle is consumed exactly like the unbounded path
        handle = bf8.allreduce_nonblocking(rank_tensor())
        out = bf8.synchronize(handle, timeout=30.0)
        np.testing.assert_allclose(np.asarray(out), 3.5, atol=1e-6)
        with pytest.raises(ValueError):
            bf8.synchronize(handle)

    def test_bf16_accumulation(self, bf8):
        x = rank_tensor(dtype=jnp.bfloat16)
        out = bf8.allreduce(x)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(np.asarray(out, dtype=np.float32), 3.5)


class TestBroadcast:
    def test_broadcast_root(self, bf8):
        out = bf8.broadcast(rank_tensor(), root_rank=3)
        np.testing.assert_allclose(np.asarray(out), 3.0, atol=1e-6)

    def test_bad_root(self, bf8):
        with pytest.raises(ValueError):
            bf8.broadcast(rank_tensor(), root_rank=9)


class TestAllgather:
    def test_allgather(self, bf8):
        x = rank_tensor(shape=(2,))  # [8, 2]
        out = bf8.allgather(x)
        assert out.shape == (8, 16)
        expected = np.repeat(np.arange(8.0), 2)
        for r in range(8):
            np.testing.assert_allclose(np.asarray(out[r]), expected)

    def test_allgather_v_ragged(self, bf8):
        parts = [jnp.full((r + 1, 2), float(r)) for r in range(8)]
        out = bf8.allgather_v(parts)
        assert out.shape == (36, 2)
        np.testing.assert_allclose(np.asarray(out[:1]), 0.0)
        np.testing.assert_allclose(np.asarray(out[-8:]), 7.0)
        # exact ragged concatenation: rank r contributes r+1 rows of value r
        expected = np.concatenate([np.full((r + 1, 2), float(r)) for r in range(8)])
        np.testing.assert_allclose(np.asarray(out), expected)

    def test_allgather_v_is_compiled_collective(self, bf8):
        """Ragged gather rides one padded all_gather program, trimmed statically."""
        from bluefog_tpu.ops import collectives as co

        co._allgather_v_fn.cache_clear()
        parts = [jnp.full((r % 3, 2), float(r)) for r in range(8)]  # incl. size-0 ranks
        out = bf8.allgather_v(parts)
        assert co._allgather_v_fn.cache_info().misses == 1
        expected = np.concatenate([np.full((r % 3, 2), float(r)) for r in range(8)])
        assert out.shape == expected.shape == (7, 2)
        np.testing.assert_allclose(np.asarray(out), expected)
        # same size signature reuses the compiled program
        bf8.allgather_v([jnp.ones((r % 3, 2)) for r in range(8)])
        assert co._allgather_v_fn.cache_info().misses == 1

    def test_allgather_v_all_empty_and_nonblocking(self, bf8):
        out = bf8.allgather_v([jnp.zeros((0, 3)) for _ in range(8)])
        assert out.shape == (0, 3)
        h = bf8.allgather_v_nonblocking([jnp.full((1,), float(r)) for r in range(8)])
        out = bf8.synchronize(h)
        np.testing.assert_allclose(np.asarray(out), np.arange(8.0))

    def test_allgather_v_mismatch_rejected(self, bf8):
        parts = [jnp.zeros((1, 2)) for _ in range(8)]
        parts[3] = jnp.zeros((1, 5))
        with pytest.raises(ValueError, match="trailing shape"):
            bf8.allgather_v(parts)
        with pytest.raises(ValueError, match="per-rank tensors"):
            bf8.allgather_v(parts[:4])


class TestNeighborAllreduce:
    def test_uniform_expo2(self, bf8):
        # expo2(8): rank r averages {r, r-1, r-2, r-4} with weight 1/4
        x = rank_tensor()
        out = bf8.neighbor_allreduce(x)
        for r in range(8):
            exp = (r + (r - 1) % 8 + (r - 2) % 8 + (r - 4) % 8) / 4.0
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_ring_uniform(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        out = bf8.neighbor_allreduce(rank_tensor())
        for r in range(8):
            exp = (r + (r - 1) % 8 + (r + 1) % 8) / 3.0
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_weighted_topology(self, bf8):
        bf8.set_topology(topology_util.MeshGrid2DGraph(8), is_weighted=True)
        W = topology_util.weight_matrix(bf8.load_topology())
        x = rank_tensor()
        out = bf8.neighbor_allreduce(x)
        expected = W.T @ np.arange(8.0)
        for r in range(8):
            np.testing.assert_allclose(np.asarray(out[r]), expected[r], atol=1e-5)

    def test_explicit_weights(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        out = bf8.neighbor_allreduce(
            rank_tensor(),
            self_weight=0.5,
            neighbor_weights={r: {(r - 1) % 8: 0.25, (r + 1) % 8: 0.25}
                              for r in range(8)},
        )
        for r in range(8):
            exp = 0.5 * r + 0.25 * ((r - 1) % 8) + 0.25 * ((r + 1) % 8)
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_invalid_weight_keys_rejected(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        with pytest.raises(ValueError, match="non-in-neighbor"):
            bf8.neighbor_allreduce(
                rank_tensor(), self_weight=0.5,
                neighbor_weights={r: {(r + 3) % 8: 0.5} for r in range(8)},
            )

    def test_dense_graph_gather_path(self, bf8):
        bf8.set_topology(topology_util.FullyConnectedGraph(8), is_weighted=True)
        out = bf8.neighbor_allreduce(rank_tensor())
        np.testing.assert_allclose(np.asarray(out), 3.5, atol=1e-5)

    def test_star_graph(self, bf8):
        bf8.set_topology(topology_util.StarGraph(8), is_weighted=True)
        x = rank_tensor()
        W = topology_util.weight_matrix(bf8.load_topology())
        out = bf8.neighbor_allreduce(x)
        expected = W.T @ np.arange(8.0)
        np.testing.assert_allclose(
            np.asarray(out)[:, 0], expected, atol=1e-5
        )

    def test_pytree(self, bf8):
        tree = {"w": rank_tensor(), "b": rank_tensor(shape=(3, 2))}
        out = bf8.neighbor_allreduce(tree)
        exp0 = (0 + 7 + 6 + 4) / 4.0
        np.testing.assert_allclose(np.asarray(out["w"][0]), exp0, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out["b"][0]), exp0, atol=1e-5)

    def test_float16_uses_f32_accumulation(self, bf8):
        """fp16 ops keep their dtype AND combine in f32 (C11 parity: the
        reference runs its op suite in fp16 too). The ring's 1/3 weights
        are not fp16-representable: accumulating in fp16 would give
        3 * fp16(1/3) = 0.99976 -> fp16 0.9995, while f32 accumulation
        rounds back to exactly 1.0."""
        bf8.set_topology(topology_util.RingGraph(8))
        x = jnp.ones((8, 4), jnp.float16)
        out = bf8.neighbor_allreduce(x)
        assert out.dtype == jnp.float16
        np.testing.assert_array_equal(np.asarray(out), np.float16(1.0))
        out2 = bf8.allreduce(x)
        assert out2.dtype == jnp.float16
        np.testing.assert_array_equal(np.asarray(out2), np.float16(1.0))

    def test_average_consensus_converges(self, bf8):
        # the reference's pytorch_average_consensus.py as a test: repeated
        # neighbor averaging over expo2 drives everyone to the global mean
        x = rank_tensor()
        target = 3.5
        for _ in range(30):
            x = bf8.neighbor_allreduce(x)
        np.testing.assert_allclose(np.asarray(x), target, atol=1e-4)


class TestDynamicNeighborAllreduce:
    def test_empty_send_neighbors(self, bf8):
        """Ranks with no outgoing (or incoming) edges this step keep their
        own value — the reference's empty-send-neighbor case
        (torch_ops_test.py dynamic variants)."""
        sends = {r: ([(r + 1) % 8] if r < 4 else []) for r in range(8)}
        recv = {r: [] for r in range(8)}
        for s, ds in sends.items():
            for d in ds:
                recv[d].append(s)
        sw = {r: 1.0 / (len(recv[r]) + 1) for r in range(8)}
        nw = {r: {s: 1.0 / (len(recv[r]) + 1) for s in recv[r]}
              for r in range(8)}
        out = bf8.neighbor_allreduce(
            rank_tensor(), self_weight=sw, neighbor_weights=nw,
            send_neighbors=sends)
        expected = [0.0, 0.5, 1.5, 2.5, 3.5, 5.0, 6.0, 7.0]
        np.testing.assert_allclose(np.asarray(out)[:, 0], expected, atol=1e-5)

    def test_one_peer_ring_step(self, bf8):
        # every rank sends to r+1; recv weight 0.5 / self 0.5
        sends = {r: [(r + 1) % 8] for r in range(8)}
        out = bf8.neighbor_allreduce(
            rank_tensor(),
            self_weight=0.5,
            neighbor_weights={r: {(r - 1) % 8: 0.5} for r in range(8)},
            send_neighbors=sends,
        )
        for r in range(8):
            exp = 0.5 * r + 0.5 * ((r - 1) % 8)
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_topo_check_mismatch(self, bf8):
        # parity: torch_ops_test.py:429 — mismatched send/recv detected
        sends = {r: [(r + 1) % 8] for r in range(8)}
        with pytest.raises(RuntimeError, match="dynamic topology mismatch"):
            bf8.neighbor_allreduce(
                rank_tensor(),
                self_weight=0.5,
                neighbor_weights={r: {(r - 2) % 8: 0.5} for r in range(8)},
                send_neighbors=sends,
            )

    def test_topo_check_disabled_runs(self, bf8):
        sends = {r: [(r + 1) % 8] for r in range(8)}
        out = bf8.neighbor_allreduce(
            rank_tensor(),
            self_weight=1.0,
            neighbor_weights={r: {} for r in range(8)},
            send_neighbors=sends,
            enable_topo_check=False,
        )
        np.testing.assert_allclose(
            np.asarray(out)[:, 0], np.arange(8.0), atol=1e-6
        )

    def test_dynamic_iterator_full_cycle(self, bf8):
        # drive the flagship dynamic schedule for several steps and check
        # the average is preserved every step (column-stochastic W)
        topo = topology_util.ExponentialTwoGraph(8)
        gens = [topology_util.GetDynamicSendRecvRanks(topo, r) for r in range(8)]
        x = rank_tensor()
        for _ in range(6):
            steps = [next(g) for g in gens]
            sends = {r: steps[r][0] for r in range(8)}
            recv = {r: steps[r][1] for r in range(8)}
            nw = {r: {src: 0.5 for src in recv[r]} for r in range(8)}
            sw = {r: 1.0 - 0.5 * len(recv[r]) for r in range(8)}
            x = bf8.neighbor_allreduce(
                x, self_weight=sw, neighbor_weights=nw, send_neighbors=sends,
                enable_topo_check=False,
            )
        # mean preserved requires column-stochasticity; here each rank sends
        # half its mass to one peer: columns sum to 1 by construction
        np.testing.assert_allclose(np.asarray(x).mean(), 3.5, atol=1e-4)


class TestHierarchicalNeighborAllreduce:
    def test_two_machine_default(self, bf8):
        # machines: [0-3] avg 1.5, [4-7] avg 5.5; expo2(2) = each machine
        # averages with the other -> everyone (1.5 + 5.5)/2 = 3.5
        out = bf8.hierarchical_neighbor_allreduce(rank_tensor())
        np.testing.assert_allclose(np.asarray(out), 3.5, atol=1e-5)

    def test_machine_weights(self, bf8):
        out = bf8.hierarchical_neighbor_allreduce(
            rank_tensor(),
            self_weight=0.75,
            neighbor_machine_weights={0: {1: 0.25}, 1: {0: 0.25}},
            send_neighbor_machines={0: [1], 1: [0]},
        )
        expected = np.repeat([0.75 * 1.5 + 0.25 * 5.5,
                              0.75 * 5.5 + 0.25 * 1.5], 4)
        np.testing.assert_allclose(np.asarray(out)[:, 0], expected, atol=1e-5)


class TestNeighborAllgather:
    def test_regular_graph(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor(shape=(2,))
        out = bf8.neighbor_allgather(x)
        assert out.shape == (8, 4)  # 2 neighbors * b=2
        # rank 0's in-neighbors sorted: [1, 7]
        np.testing.assert_allclose(np.asarray(out[0]), [1, 1, 7, 7])

    def test_irregular_graph_returns_list(self, bf8):
        bf8.set_topology(topology_util.StarGraph(8))
        out = bf8.neighbor_allgather(rank_tensor(shape=(2,)))
        assert isinstance(out, list)
        assert out[0].shape == (14, )  # center: 7 neighbors * 2
        assert out[3].shape == (2,)
        np.testing.assert_allclose(np.asarray(out[3]), 0.0)

    def test_compiled_exchange_is_used(self, bf8):
        """The gather is a compiled shard_map collective, not an eager take."""
        from bluefog_tpu.ops import neighbors as nb

        bf8.set_topology(topology_util.ExponentialTwoGraph(8))
        nb._gather_exchange_fn.cache_clear()
        x = rank_tensor(shape=(2,))
        out = bf8.neighbor_allgather(x)
        assert nb._gather_exchange_fn.cache_info().misses == 1
        # expo2: rank 0's sorted in-neighbors are [4, 6, 7]
        assert out.shape == (8, 6)
        np.testing.assert_allclose(np.asarray(out[0]), [4, 4, 6, 6, 7, 7])
        # output stays rank-sharded on the mesh (one slice per device)
        shard_devs = {s.device for s in out.addressable_shards}
        assert len(shard_devs) == 8
        # second call with the same topology reuses the compiled program
        bf8.neighbor_allgather(x)
        assert nb._gather_exchange_fn.cache_info().misses == 1


class TestPairGossip:
    def test_even_odd_pairs(self, bf8):
        peers = {r: r ^ 1 for r in range(8)}
        out = bf8.pair_gossip(rank_tensor(), peers)
        expected = np.repeat(np.arange(0.5, 8, 2), 2)
        np.testing.assert_allclose(np.asarray(out)[:, 0], expected, atol=1e-6)

    def test_asymmetric_pairs_rejected(self, bf8):
        peers = {r: (r + 1) % 8 for r in range(8)}
        with pytest.raises(ValueError, match="mutual"):
            bf8.pair_gossip(rank_tensor(), peers)

    def test_weights(self, bf8):
        peers = {r: r ^ 1 for r in range(8)}
        out = bf8.pair_gossip(rank_tensor(), peers, self_weight=0.75,
                              pair_weight=0.25)
        np.testing.assert_allclose(np.asarray(out[0]), 0.25, atol=1e-6)


class TestBarrier:
    def test_barrier(self, bf8):
        bf8.barrier()  # just must not deadlock/raise
