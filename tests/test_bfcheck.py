"""bfcheck self-tests: the real tree must be clean, and each analyzer must
catch its seeded violation with a file:line diagnostic.

The seeded fixtures are miniature repository roots written to tmp_path —
one violation each for: a C++ op missing its Python mirror, a code
mismatch, a retry-unsafe op absent from IsDedupOp, an undeclared knob
read, a per-site default contradicting the registry, a lock-order
inversion, a joinless daemon thread, a blocking call under a local lock,
and an unused import (the lint fallback).
"""

import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "scripts"))

import bfcheck  # noqa: E402
from bfcheck import (knob_check, lint_check, litter_check,  # noqa: E402
                     lock_check, metrics_check, protocol_check)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# the real tree is clean (tier-1's `make check` equivalence)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("analyzer", bfcheck.ANALYZERS)
def test_real_tree_is_clean(analyzer):
    findings = bfcheck.run(analyzer, ROOT)
    assert findings == [], "\n".join(str(d) for d in findings)


def test_cli_runs_clean():
    import subprocess

    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "bfcheck")],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "clean" in r.stdout


# ---------------------------------------------------------------------------
# fixture scaffolding
# ---------------------------------------------------------------------------

MINI_PROTOCOL = textwrap.dedent('''
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class OpSpec:
        name: str
        code: int
        cxx: str
        idempotent: bool
        doc: str = ""

    OPS = (
        OpSpec("barrier", 1, "kBarrier", False),
        OpSpec("get", 2, "kGet", True),
        OpSpec("fetch_add", 3, "kFetchAdd", False),
    )
    OP_CODES = {o.name: o.code for o in OPS}
    OP_NAMES = {o.code: o.name for o in OPS}
    RETRY_UNSAFE = frozenset(o.name for o in OPS if not o.idempotent)
''')

MINI_CC = textwrap.dedent('''
    // fixture control plane
    enum Op : uint8_t {
      kBarrier = 1, kGet = 2, kFetchAdd = 3,
    };
    struct Client {
      static bool IsDedupOp(uint8_t op) {
        switch (op) {
          case kBarrier:
          case kFetchAdd:
            return true;
          default:
            return false;
        }
      }
    };
''')


def make_proto_tree(tmp_path, cc=MINI_CC, proto=MINI_PROTOCOL):
    (tmp_path / "csrc").mkdir()
    (tmp_path / "bluefog_tpu" / "runtime").mkdir(parents=True)
    (tmp_path / "csrc" / "bf_runtime.cc").write_text(cc)
    (tmp_path / "bluefog_tpu" / "runtime" / "protocol.py").write_text(proto)
    return str(tmp_path)


MINI_CONFIG = textwrap.dedent('''
    import dataclasses

    @dataclasses.dataclass(frozen=True)
    class Knob:
        name: str
        type: str
        default: object
        doc: str
        scope: str = "python"

    KNOBS = (
        Knob("BLUEFOG_DEMO_TIMEOUT", "float", 30.0, "demo timeout"),
        Knob("BLUEFOG_DEMO_FLAG", "bool", False, "demo flag"),
    )
''')


def make_knob_tree(tmp_path, reader_src, config=MINI_CONFIG):
    rt = tmp_path / "bluefog_tpu" / "runtime"
    rt.mkdir(parents=True)
    (rt / "config.py").write_text(config)
    (tmp_path / "bluefog_tpu" / "reader.py").write_text(reader_src)
    docs = tmp_path / "docs"
    docs.mkdir()
    import importlib

    importlib.reload(knob_check)
    table = knob_check.render_knob_table(
        {k.name: k for k in _load_knobs(str(rt / "config.py"))})
    (docs / "env_variables.md").write_text(
        "# Environment variables\n\n" + table)
    return str(tmp_path)


def _load_knobs(path):
    import importlib.util

    spec = importlib.util.spec_from_file_location("_fix_cfg", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.modules.pop(spec.name, None)
    return mod.KNOBS


def findings_for(diags, path_part):
    return [d for d in diags if path_part in d.path]


# ---------------------------------------------------------------------------
# protocol analyzer fixtures
# ---------------------------------------------------------------------------

def test_protocol_clean_fixture(tmp_path):
    root = make_proto_tree(tmp_path)
    assert protocol_check.check(root) == []


def test_protocol_missing_python_mirror(tmp_path):
    cc = MINI_CC.replace("kFetchAdd = 3,", "kFetchAdd = 3, kNewOp = 4,")
    root = make_proto_tree(tmp_path, cc=cc)
    diags = protocol_check.check(root)
    assert any("kNewOp" in d.message and "no row" in d.message
               for d in diags)
    d = next(d for d in diags if "kNewOp" in d.message)
    assert d.path.endswith("bf_runtime.cc") and d.line > 1


def test_protocol_missing_cxx_mirror(tmp_path):
    proto = MINI_PROTOCOL.replace(
        'OpSpec("fetch_add", 3, "kFetchAdd", False),',
        'OpSpec("fetch_add", 3, "kFetchAdd", False),\n'
        '    OpSpec("new_op", 4, "kNewOp", True),')
    root = make_proto_tree(tmp_path, proto=proto)
    diags = protocol_check.check(root)
    assert any("missing from the C++ enum" in d.message for d in diags)


def test_protocol_code_mismatch(tmp_path):
    cc = MINI_CC.replace("kFetchAdd = 3", "kFetchAdd = 9")
    root = make_proto_tree(tmp_path, cc=cc)
    diags = protocol_check.check(root)
    assert any("desync" in d.message for d in diags)


def test_protocol_out_of_numeric_order(tmp_path):
    cc = MINI_CC.replace("kBarrier = 1, kGet = 2, kFetchAdd = 3,",
                         "kBarrier = 1, kFetchAdd = 3, kGet = 2,")
    root = make_proto_tree(tmp_path, cc=cc)
    diags = protocol_check.check(root)
    assert any("numeric order" in d.message for d in diags)


def test_protocol_retry_unsafe_not_in_dedup(tmp_path):
    # fetch_add declared retry-unsafe in Python but dropped from IsDedupOp:
    # the exact "ships retry-unsafe" hole the analyzer exists for
    cc = MINI_CC.replace("      case kFetchAdd:\n", "")
    root = make_proto_tree(tmp_path, cc=cc)
    diags = protocol_check.check(root)
    assert any("missing from IsDedupOp" in d.message
               and "applied twice" in d.message for d in diags)


def test_protocol_dedup_of_idempotent_op(tmp_path):
    cc = MINI_CC.replace("case kBarrier:", "case kBarrier:\n      case kGet:")
    root = make_proto_tree(tmp_path, cc=cc)
    diags = protocol_check.check(root)
    assert any("kGet" in d.message and "declared idempotent" in d.message
               for d in diags)


# ---------------------------------------------------------------------------
# knob analyzer fixtures
# ---------------------------------------------------------------------------

def test_knobs_clean_fixture(tmp_path):
    root = make_knob_tree(tmp_path, textwrap.dedent('''
        import os
        t = float(os.environ.get("BLUEFOG_DEMO_TIMEOUT", "30"))
        f = os.environ.get("BLUEFOG_DEMO_FLAG", "0") == "1"
    '''))
    assert knob_check.check(root) == []


def test_knobs_undeclared_read(tmp_path):
    root = make_knob_tree(tmp_path, textwrap.dedent('''
        import os
        x = os.environ.get("BLUEFOG_NOT_DECLARED", "1")
    '''))
    diags = knob_check.check(root)
    hits = findings_for(diags, "reader.py")
    assert hits and "undeclared knob BLUEFOG_NOT_DECLARED" in hits[0].message
    assert hits[0].line == 3


def test_knobs_contradicting_default(tmp_path):
    root = make_knob_tree(tmp_path, textwrap.dedent('''
        import os
        t = float(os.environ.get("BLUEFOG_DEMO_TIMEOUT", "45"))
    '''))
    diags = knob_check.check(root)
    hits = findings_for(diags, "reader.py")
    assert hits and "contradicts the registry default" in hits[0].message
    assert "45" in hits[0].message and hits[0].line == 3


def test_knobs_subscript_and_membership_reads_are_seen(tmp_path):
    root = make_knob_tree(tmp_path, textwrap.dedent('''
        import os
        if "BLUEFOG_MYSTERY" in os.environ:
            y = os.environ["BLUEFOG_MYSTERY2"]
    '''))
    diags = knob_check.check(root)
    msgs = "\n".join(d.message for d in findings_for(diags, "reader.py"))
    assert "BLUEFOG_MYSTERY" in msgs and "BLUEFOG_MYSTERY2" in msgs


def test_knobs_writes_are_ignored(tmp_path):
    root = make_knob_tree(tmp_path, textwrap.dedent('''
        import os
        os.environ["BLUEFOG_SOME_WRITE"] = "1"
        del os.environ["BLUEFOG_SOME_WRITE"]
    '''))
    assert findings_for(knob_check.check(root), "reader.py") == []


def test_knobs_stale_docs_table(tmp_path):
    root = make_knob_tree(tmp_path, "x = 1\n")
    docs = os.path.join(root, "docs", "env_variables.md")
    with open(docs) as f:
        text = f.read()
    with open(docs, "w") as f:
        f.write(text.replace("demo timeout", "stale words"))
    diags = knob_check.check(root)
    assert any("stale" in d.message for d in diags)
    # --write-docs repairs it
    knob_check.write_docs(root)
    assert knob_check.check(root) == []


# ---------------------------------------------------------------------------
# lock analyzer fixtures
# ---------------------------------------------------------------------------

def make_lock_tree(tmp_path, src):
    pkg = tmp_path / "bluefog_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(src)
    return str(tmp_path)


def test_locks_clean_fixture(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading
        a_mu = threading.Lock()
        b_mu = threading.Lock()

        def fine():
            with a_mu:
                with b_mu:
                    pass

        def also_fine():
            with a_mu:
                with b_mu:
                    pass
    '''))
    assert lock_check.check(root) == []


def test_locks_order_inversion(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading
        a_mu = threading.Lock()
        b_mu = threading.Lock()

        def one():
            with a_mu:
                with b_mu:
                    pass

        def other():
            with b_mu:
                with a_mu:
                    pass
    '''))
    diags = lock_check.check(root)
    assert any("lock-order inversion" in d.message for d in diags)
    d = next(d for d in diags if "inversion" in d.message)
    assert d.path.endswith("mod.py") and d.line > 0
    assert "a_mu" in d.message and "b_mu" in d.message


def test_locks_interprocedural_inversion(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading
        a_mu = threading.Lock()
        b_mu = threading.Lock()

        def helper():
            with b_mu:
                pass

        def one():
            with a_mu:
                helper()

        def other():
            with b_mu:
                with a_mu:
                    pass
    '''))
    diags = lock_check.check(root)
    assert any("inversion" in d.message for d in diags)


def test_locks_blocking_call_under_local_lock(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading
        state_mu = threading.Lock()

        def risky(client):
            with state_mu:
                client.barrier("default")
    '''))
    diags = lock_check.check(root)
    assert any("blocking" in d.message and "barrier" in d.message
               for d in diags)


def test_locks_blocking_waiver_honored(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading
        state_mu = threading.Lock()

        def deliberate(client):
            with state_mu:
                # bfcheck: ok-blocking-under-lock (fixture reason)
                client.barrier("default")
    '''))
    assert lock_check.check(root) == []


def test_locks_joinless_daemon_thread(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading

        def spawn():
            threading.Thread(target=print, daemon=True).start()
    '''))
    diags = lock_check.check(root)
    assert any("daemon thread" in d.message for d in diags)
    d = next(d for d in diags if "daemon" in d.message)
    assert d.line == 5


def test_locks_daemon_with_join_is_fine(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading

        class Loop:
            def start(self):
                self._t = threading.Thread(target=print, daemon=True)
                self._t.start()

            def stop(self):
                self._t.join(timeout=2.0)
    '''))
    assert lock_check.check(root) == []


def test_locks_daemon_waiver_honored(tmp_path):
    root = make_lock_tree(tmp_path, textwrap.dedent('''
        import threading

        def spawn():
            # bfcheck: ok-daemon-no-join (fixture: exits with the process)
            threading.Thread(target=print, daemon=True).start()
    '''))
    assert lock_check.check(root) == []


# ---------------------------------------------------------------------------
# lint fallback fixtures
# ---------------------------------------------------------------------------

def test_lint_unused_import(tmp_path):
    pkg = tmp_path / "bluefog_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text("import os\nimport sys\nprint(sys.argv)\n")
    diags = lint_check.check(str(tmp_path))
    assert any("'os' imported but unused" in d.message for d in diags)
    assert not any("'sys'" in d.message for d in diags)


def test_lint_noqa_and_future_exempt(tmp_path):
    pkg = tmp_path / "bluefog_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "from __future__ import annotations\n"
        "import os  # noqa: F401\n")
    assert lint_check.check(str(tmp_path)) == []


def test_lint_duplicate_definition(tmp_path):
    pkg = tmp_path / "bluefog_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f():\n    return 1\n\n\ndef f():\n    return 2\n")
    diags = lint_check.check(str(tmp_path))
    assert any("redefinition of 'f'" in d.message for d in diags)
    d = next(d for d in diags if "redefinition" in d.message)
    assert d.line == 5


# ---------------------------------------------------------------------------
# protocol module invariants (cheap, no fixtures)
# ---------------------------------------------------------------------------

def test_protocol_table_internally_consistent():
    from bluefog_tpu.runtime import protocol

    codes = [o.code for o in protocol.OPS]
    assert len(codes) == len(set(codes))
    assert codes == sorted(codes)
    assert protocol.RETRY_UNSAFE == {
        "barrier", "unlock", "fetch_add", "append_bytes",
        "append_bytes_tagged", "take_bytes", "put_bytes_part",
        "repl_apply"}
    assert protocol.spec("barrier").cxx == "kBarrier"
    with pytest.raises(KeyError):
        protocol.spec("nope")


def test_native_op_names_derive_from_protocol():
    from bluefog_tpu.runtime import native, protocol

    assert native._OP_NAMES is protocol.OP_NAMES
    assert native.ControlPlaneClient._OP_APPEND_BYTES == \
        protocol.OP_CODES["append_bytes"]


# ---------------------------------------------------------------------------
# metrics analyzer fixtures
# ---------------------------------------------------------------------------

MINI_METRICS = textwrap.dedent('''
    _HELP_EXACT = {
        "opt.step": "optimizer step counter",
    }
    _HELP_PREFIX = (
        ("win.", "window op latency"),
    )
    _PREFIX_FAMILIES = ("opt", "win")
''')

MINI_TS = textwrap.dedent('''
    TS_BINDINGS = (
        ("opt.step", "gauge", "last"),
    )
    DERIVED_SERIES = ("opt.mixing_rate",)
    RATE_SERIES = ("opt.step",)


    class Rule:
        def __init__(self, name, series, op, threshold, for_sec, doc=""):
            pass


    DEFAULT_RULES = (
        Rule("straggler", "opt.step.rate", "<=", 0.0, 30.0),
    )
''')


def make_metrics_tree(tmp_path, user_src="", metrics=MINI_METRICS,
                      ts=MINI_TS):
    rt = tmp_path / "bluefog_tpu" / "runtime"
    rt.mkdir(parents=True)
    (rt / "metrics.py").write_text(metrics)
    (rt / "timeseries.py").write_text(ts)
    if user_src:
        (tmp_path / "bluefog_tpu" / "user.py").write_text(user_src)
    return str(tmp_path)


def test_metrics_clean_fixture(tmp_path):
    root = make_metrics_tree(tmp_path, textwrap.dedent('''
        from .runtime import metrics as _metrics

        _metrics.counter("opt.step").inc()
        _metrics.gauge("win.depth").set(1)
        _metrics.histogram("cp.lag", doc="per-site doc wins")
    '''), metrics=MINI_METRICS.replace(
        '_PREFIX_FAMILIES = ("opt", "win")',
        '_PREFIX_FAMILIES = ("opt", "win", "cp")'))
    assert metrics_check.check(root) == []


def test_metrics_undeclared_prefix_family(tmp_path):
    root = make_metrics_tree(tmp_path, textwrap.dedent('''
        from .runtime import metrics as _metrics

        _metrics.counter("rogue.hits", doc="has help, wrong family")
    '''))
    diags = metrics_check.check(root)
    assert len(diags) == 1
    assert "undeclared prefix family 'rogue'" in diags[0].message
    assert diags[0].path.endswith("user.py") and diags[0].line > 0


def test_metrics_missing_help(tmp_path):
    root = make_metrics_tree(tmp_path, textwrap.dedent('''
        from .runtime import metrics as _metrics

        _metrics.gauge("opt.mystery")
    '''))
    diags = metrics_check.check(root)
    assert len(diags) == 1
    assert "no HELP text" in diags[0].message


def test_metrics_doc_kwarg_and_prefix_rule_satisfy_help(tmp_path):
    root = make_metrics_tree(tmp_path, textwrap.dedent('''
        from .runtime import metrics as _metrics

        _metrics.gauge("opt.novel", doc="documented at the site")
        _metrics.histogram("win.put_sec")  # prefix rule covers win.*
    '''))
    assert metrics_check.check(root) == []


def test_metrics_waiver_suppresses(tmp_path):
    root = make_metrics_tree(tmp_path, textwrap.dedent('''
        from .runtime import metrics as _metrics

        # bfcheck: ok-metrics (fixture justification)
        _metrics.gauge("opt.mystery")
    '''))
    assert metrics_check.check(root) == []


def test_metrics_binding_names_unknown_instrument(tmp_path):
    root = make_metrics_tree(tmp_path, ts=MINI_TS.replace(
        '("opt.step", "gauge", "last"),',
        '("opt.step", "gauge", "last"),\n'
        '    ("opt.typo_gauge", "gauge", "last"),'))
    diags = metrics_check.check(root)
    assert len(diags) == 1
    assert "TS_BINDINGS names 'opt.typo_gauge'" in diags[0].message


def test_metrics_rule_names_unknown_series(tmp_path):
    root = make_metrics_tree(tmp_path, ts=MINI_TS.replace(
        'Rule("straggler", "opt.step.rate", "<=", 0.0, 30.0),',
        'Rule("straggler", "opt.step.rate", "<=", 0.0, 30.0),\n'
        '    Rule("bogus", "opt.nonexistent", ">", 1.0, 5.0),'))
    diags = metrics_check.check(root)
    assert len(diags) == 1
    assert "alert rule 'bogus'" in diags[0].message
    assert "opt.nonexistent" in diags[0].message


def test_metrics_rate_suffix_resolves_only_rate_series(tmp_path):
    # .rate of a non-RATE_SERIES member is a finding
    root = make_metrics_tree(tmp_path, ts=MINI_TS.replace(
        'Rule("straggler", "opt.step.rate", "<=", 0.0, 30.0),',
        'Rule("straggler", "opt.step.rate", "<=", 0.0, 30.0),\n'
        '    Rule("gone", "opt.mixing_rate.rate", ">", 1.0, 5.0),'))
    diags = metrics_check.check(root)
    assert len(diags) == 1
    assert "alert rule 'gone'" in diags[0].message


# ---------------------------------------------------------------------------
# litter analyzer fixtures
# ---------------------------------------------------------------------------

def test_litter_clean_fixture(tmp_path):
    (tmp_path / "bluefog_tpu").mkdir()
    (tmp_path / "csrc").mkdir()
    assert litter_check.check(str(tmp_path)) == []


def test_litter_flags_flight_dump_at_root(tmp_path):
    (tmp_path / "bluefog_tpu").mkdir()
    (tmp_path / "csrc").mkdir()
    (tmp_path / "bf_flight_0.json").write_text("{}")
    diags = litter_check.check(str(tmp_path))
    assert len(diags) == 1
    assert diags[0].path == "bf_flight_0.json"
    assert "BLUEFOG_FLIGHT_DIR" in diags[0].message


def test_litter_ignores_dumps_below_root(tmp_path):
    # dumps inside a subdirectory (a configured flight dir, a fixture
    # tree) are exactly where dumps belong — only the root is litter
    (tmp_path / "bluefog_tpu").mkdir()
    (tmp_path / "dumps").mkdir()
    (tmp_path / "dumps" / "bf_flight_3.json").write_text("{}")
    assert litter_check.check(str(tmp_path)) == []
