"""Expert parallelism: SPMD Switch routing vs the dense oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu import parallel as bfp
from bluefog_tpu.parallel import expert as ep

from conftest import cpu_devices

E = 8


def make_moe(batch=8, seq=4, d=16, d_ff=32, seed=0):
    model = ep.SwitchFFN(num_experts=E, d_ff=d_ff)
    x = jax.random.normal(jax.random.PRNGKey(seed), (batch, seq, d))
    params = model.init(jax.random.PRNGKey(1), x)["params"]
    return model, params, x


def test_ep_matches_dense_oracle():
    model, params, x = make_moe()
    oracle = model.apply({"params": params}, x)
    mesh = ep.ep_mesh(E, cpu_devices(8))
    # capacity_factor=E guarantees no token drops -> exact equality
    out, aux = ep.ep_apply(params, x, mesh, capacity_factor=E)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), atol=1e-4)
    assert aux.shape == (E,)
    assert np.isfinite(np.asarray(aux)).all()


def test_ep_capacity_drops_overflow_tokens():
    model, params, x = make_moe()
    # zero gate: uniform probs, argmax -> expert 0 for every token
    params = dict(params, gate=jnp.zeros_like(params["gate"]))
    mesh = ep.ep_mesh(E, cpu_devices(8))
    out, aux = ep.ep_apply(params, x, mesh, capacity_factor=1.0)
    # per device: T=4 local tokens all routed to expert 0, capacity
    # ceil(1.0 * 4 / 8) = 1 -> exactly 1 token per device survives
    flat = np.asarray(out).reshape(-1, out.shape[-1])
    nonzero_rows = (np.abs(flat) > 0).any(axis=1).sum()
    assert nonzero_rows == E  # one surviving token per device
    # uniform-to-one-expert routing: switch aux loss = E * 1 * (1/E) = 1
    np.testing.assert_allclose(np.asarray(aux), 1.0, atol=1e-5)


def test_ep_survivors_match_oracle_scaling():
    model, params, x = make_moe()
    params = dict(params, gate=jnp.zeros_like(params["gate"]))
    mesh = ep.ep_mesh(E, cpu_devices(8))
    # big capacity: every token survives even though all hit expert 0
    out, _ = ep.ep_apply(params, x, mesh, capacity_factor=float(E * E))
    oracle = model.apply({"params": dict(params)}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), atol=1e-4)


def test_ep_validations():
    model, params, x = make_moe()
    mesh = ep.ep_mesh(E, cpu_devices(8))
    with pytest.raises(ValueError, match="experts"):
        ep.ep_apply({**params, "up": params["up"][:4]}, x, mesh)
    with pytest.raises(ValueError, match="divide"):
        ep.ep_apply(params, x[:6], mesh)
    with pytest.raises(ValueError, match="devices"):
        ep.ep_mesh(16, cpu_devices(8))


@pytest.mark.slow  # 40 jitted shard_map training steps, minutes on CPU mesh
def test_ep_training_converges():
    """Gradients flow through the sparse dispatch: a Switch classifier
    trained expert-parallel converges (short version of examples/moe.py)."""
    import optax

    mesh = ep.ep_mesh(E, cpu_devices(8))
    d, classes = 8, 8
    key = jax.random.PRNGKey(0)
    centers = jax.random.normal(key, (classes, d)) * 3.0
    x = (centers[:, None, :]
         + 0.3 * jax.random.normal(jax.random.PRNGKey(1), (classes, 16, d)))
    y = jnp.broadcast_to(jnp.arange(classes)[:, None], (classes, 16))
    moe = ep.SwitchFFN(num_experts=E, d_ff=32)
    params = {
        "moe": moe.init(jax.random.PRNGKey(2), x)["params"],
        "head": 0.1 * jax.random.normal(jax.random.PRNGKey(3), (d, classes)),
    }

    def loss_fn(p, batch):
        bx, by = batch
        h, aux = ep.ep_apply(p["moe"], bx, mesh, capacity_factor=4.0)
        logits = (bx + h) @ p["head"]
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, by).mean()
        return ce + 0.01 * aux.mean()

    opt = optax.adam(3e-2)
    state = opt.init(params)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))
    losses = []
    for step in range(40):
        loss, grads = grad_fn(params, (x, y))
        if step == 0:
            # the stated claim, pinned directly: gradients reach every MoE
            # param THROUGH the sparse dispatch (a dead ep_apply would leave
            # the residual head to learn alone and still drop the loss)
            for name in ("gate", "up", "down"):
                g = np.asarray(grads["moe"][name])
                assert np.abs(g).max() > 0, f"no gradient reached moe/{name}"
        updates, state = opt.update(grads, state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0], losses[::10]


def test_moe_lm_ep_apply_matches_dense_oracle():
    """The expert-parallel MoE TransformerLM (shard_map over the expert
    axis, all_to_all dispatch inside every MoE block) computes exactly the
    dense oracle's forward when capacity guarantees no token drops."""
    import dataclasses

    from bluefog_tpu.models import MoETransformerLM

    E = 8
    mesh = bfp.ep_mesh(E, cpu_devices(E))
    model = MoETransformerLM(
        vocab_size=64, num_experts=E, num_layers=2, num_heads=2,
        d_model=32, d_ff=64, moe_every=2, expert_axis="expert",
        capacity_factor=float(E))  # no drops -> exact parity
    toks = jax.random.randint(jax.random.PRNGKey(3), (E, 12), 0, 64)
    params = bfp.ep_lm_init(model, jax.random.PRNGKey(0), toks)
    dense = dataclasses.replace(model, expert_axis=None)
    want = dense.apply({"params": params}, toks)
    got, aux = bfp.ep_lm_apply(model, params, toks, mesh)
    # atol: the shard_map all_to_all path and the dense oracle reassociate
    # the same sums differently, and backend-dependent codegen (cpu vs the
    # axon/tpu platform, when registered) shifts the rounding further —
    # observed up to 3.4e-5 on unit-scale logits (VERDICT r4 suite status).
    # Parity here means "same math", not "same rounding": 1e-4 on O(1)
    # logits is far below any routing or combine error.
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-4, rtol=1e-4)
    assert np.isfinite(float(aux)) and float(aux) > 0.0


def test_moe_lm_ep_training_converges():
    """jax.grad through the shard_mapped MoE loss: expert-sharded up/down
    grads + replicated dense grads drive a real training loop downhill."""
    import optax

    from bluefog_tpu.models import MoETransformerLM

    E = 4
    mesh = bfp.ep_mesh(E, cpu_devices(4))
    model = MoETransformerLM(
        vocab_size=32, num_experts=E, num_layers=2, num_heads=2,
        d_model=32, d_ff=64, moe_every=2, expert_axis="expert",
        capacity_factor=float(E))
    rng = np.random.RandomState(0)
    toks = jnp.asarray(rng.randint(0, 32, (4, 16)))
    batch = (toks, jnp.roll(toks, -1, axis=1))
    params = bfp.ep_lm_init(model, jax.random.PRNGKey(0), toks)
    loss_fn = bfp.ep_lm_loss_fn(model, mesh)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p, s):
        loss, g = jax.value_and_grad(loss_fn)(p, batch)
        updates, s = opt.update(g, s, p)
        return optax.apply_updates(p, updates), s, loss

    losses = []
    for _ in range(30):
        params, opt_state, loss = step(params, opt_state)
        losses.append(float(loss))
    assert losses[-1] < 0.6 * losses[0], losses[::10]
    # the expert grads really were per-expert: up/down shards differ
    up = np.asarray(
        params["block_1"]["moe"]["up"])
    assert up.shape[0] == E
    assert not np.allclose(up[0], up[1])
