"""Convergence tests for the decentralized optimization algorithms.

The analog of running the reference's richest demo
(/root/reference/examples/pytorch_optimization.py) end to end: every
algorithm must drive each rank's iterate to the *centralized* optimum of the
partitioned problem, which is what distinguishes exact methods (exact
diffusion, gradient tracking, push-DIGing) from plain diffusion's bias.
"""

import sys
from pathlib import Path

import jax.numpy as jnp
import pytest

import bluefog_tpu as bf

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))
import optimization as opt  # noqa: E402


@pytest.fixture(scope="module")
def problem():
    """A ring-topology linear-regression instance plus its true optimum.

    Module-scoped: one init + one 400-iteration centralized baseline shared
    by every algorithm test (each test's own loop is read-only w.r.t. the
    problem). Iteration budgets below are sized from measured convergence
    (exact diffusion reaches 2e-5 by iteration 100 on this instance) —
    dispatch-per-iteration on the single-core CI box is what makes these
    the suite's hottest tests.
    """
    from conftest import cpu_devices
    bf.init(devices=cpu_devices(8))
    size = bf.size()
    opt.set_example_topology("ring")
    X, y = opt.generate_data(
        __import__("jax").random.PRNGKey(7), size, 20, 5,
        task="linear_regression")
    grad_fn = opt.make_grad_fn(X, y, "linear_regression", rho=1e-2)
    w_opt = opt.distributed_grad_descent(grad_fn, size, 5, maxite=400,
                                         alpha=0.1)
    # sanity: the baseline itself is at a stationary point of the average loss
    g = bf.allreduce(grad_fn(w_opt), average=True)
    assert float(jnp.linalg.norm(g)) < 1e-4
    yield grad_fn, w_opt, size
    bf.shutdown()


def _assert_converged(w, w_opt, mse, tol):
    # every rank reaches the centralized optimum, not its local one
    assert float(jnp.max(jnp.linalg.norm(w - w_opt, axis=(1, 2)))) < tol
    # and the error actually decreased over the run
    assert mse[-1] < mse[0] * 1e-1 or mse[0] < tol


def test_exact_diffusion_converges(problem):
    grad_fn, w_opt, size = problem
    w, mse = opt.exact_diffusion(grad_fn, w_opt, size, 5, maxite=100,
                                 alpha=0.1)
    _assert_converged(w, w_opt, mse, tol=1e-3)


def test_gradient_tracking_converges(problem):
    grad_fn, w_opt, size = problem
    w, mse = opt.gradient_tracking(grad_fn, w_opt, size, 5, maxite=150,
                                   alpha=0.05)
    _assert_converged(w, w_opt, mse, tol=1e-3)


@pytest.mark.slow  # win-op dispatch per iteration; push-sum mechanics are
# fast-covered by test_hosted_windows + test_fusion's fused push-sum
def test_push_diging_converges(problem):
    grad_fn, w_opt, size = problem
    w, mse = opt.push_diging(grad_fn, w_opt, size, 5, maxite=150, alpha=0.05)
    _assert_converged(w, w_opt, mse, tol=1e-3)


def test_plain_diffusion_is_biased_but_close(problem):
    """Diffusion converges to a neighborhood (not exactly) of the optimum."""
    grad_fn, w_opt, size = problem
    w, mse = opt.diffusion(grad_fn, w_opt, size, 5, maxite=150, alpha=0.05)
    # with a constant step size diffusion has O(alpha) bias: near, not exact
    assert float(jnp.max(jnp.linalg.norm(w - w_opt, axis=(1, 2)))) < 0.5


def test_gradient_tracking_overlap_is_nonblocking(problem):
    """The two handles coexist in flight — the reference's :327-333 pattern."""
    grad_fn, w_opt, size = problem
    w = jnp.zeros((size, 5, 1))
    q = grad_fn(w)
    h1 = bf.neighbor_allreduce_nonblocking(w, name="overlap.w")
    h2 = bf.neighbor_allreduce_nonblocking(q, name="overlap.q")
    assert h1 != h2
    out_w = bf.synchronize(h1)
    out_q = bf.synchronize(h2)
    assert out_w.shape == w.shape and out_q.shape == q.shape
