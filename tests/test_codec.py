"""Compressed gossip wire (ISSUE r15): codecs + error feedback + pinning.

Codec-level contracts (roundtrip error bounds, the self-describing
payload grammar), the deposit wire with codecs on (records through a real
control-plane server, decoded at the drain), the pinned
``BLUEFOG_WIN_CODEC=none`` byte-identical legacy wire, the top-k +
error-feedback convergence-parity oracle vs the uncompressed optimizer,
push-sum mass conservation under quantization via the r10 gauges, and the
plane planner's post-codec size floor.
"""

import os
import socket
import struct

import numpy as np
import pytest

import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu.ops import codec as cd
from bluefog_tpu.ops import fusion as _fusion
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.ops.plan import PlanePlanner
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import metrics as bf_metrics
from bluefog_tpu.runtime import native

from conftest import cpu_devices

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


# ---------------------------------------------------------------------------
# codec-level contracts (no mesh needed)
# ---------------------------------------------------------------------------

def test_int8_roundtrip_error_bound():
    rng = np.random.RandomState(0)
    x = (rng.randn(20_000) * rng.uniform(0.1, 50)).astype(np.float32)
    c = cd.Int8Codec()
    enc = c.encode(x)
    dec = c.decode(enc, np.float32, x.size)
    # per-block bound: half an int8 step of that block's amax
    block = 4096
    for b in range(0, x.size, block):
        seg = x[b:b + block]
        bound = np.abs(seg).max() / 127.0 * 0.5 + 1e-7
        assert np.abs(dec[b:b + block] - seg).max() <= bound * 1.01
    # ~4x smaller than the raw f32 payload (+ per-block scale overhead)
    assert enc.nbytes < x.nbytes / 3.5


def test_fp8_roundtrip_error_bound():
    rng = np.random.RandomState(1)
    x = (rng.randn(10_000) * 3).astype(np.float32)
    c = cd.Fp8Codec()
    enc = c.encode(x)
    dec = c.decode(enc, np.float32, x.size)
    # e4m3 keeps ~3 mantissa bits: elementwise relative error <= ~6.25%,
    # plus an absolute floor from the smallest representable step
    amax = np.abs(x).max()
    err = np.abs(dec - x)
    assert np.all(err <= np.maximum(np.abs(x) * 0.0825, amax / 448.0))
    assert enc.nbytes < x.nbytes / 3.5


def test_topk_keeps_largest_exactly():
    rng = np.random.RandomState(2)
    x = rng.randn(1000).astype(np.float32)
    c = cd.TopKCodec(0.1)
    enc = c.encode(x)
    dec = c.decode(enc, np.float32, x.size)
    k = 100
    top = np.argsort(np.abs(x))[-k:]
    np.testing.assert_array_equal(dec[top], x[top])  # kept values exact
    rest = np.setdiff1d(np.arange(x.size), top)
    np.testing.assert_array_equal(dec[rest], 0.0)    # everything else 0
    assert enc.nbytes == 4 + 8 * k


def test_topk_decode_rejects_out_of_range_index():
    c = cd.TopKCodec(0.5)
    enc = c.encode(np.ones(16, np.float32))
    with pytest.raises(ValueError, match="beyond"):
        c.decode(enc, np.float32, 4)


def test_resolve_grammar(caplog):
    assert cd.resolve(None) is None
    assert cd.resolve("none") is None
    assert isinstance(cd.resolve("int8"), cd.Int8Codec)
    assert isinstance(cd.resolve("fp8"), cd.Fp8Codec)
    t = cd.resolve("topk:0.05")
    assert isinstance(t, cd.TopKCodec) and t.frac == 0.05
    assert cd.resolve("topk").frac == 0.01
    # typo degrades to the EXACT legacy wire, never a half-configured codec
    assert cd.resolve("in8") is None
    assert cd.by_id(cd.CODEC_INT8).cid == cd.CODEC_INT8
    with pytest.raises(ValueError, match="unknown wire codec"):
        cd.by_id(9)


def test_codec_block_knob_is_self_describing(monkeypatch):
    """Origin and owner may disagree on BLUEFOG_WIN_CODEC_BLOCK: the block
    size rides the payload, so decode never consults the environment."""
    x = np.arange(10_000, dtype=np.float32)
    monkeypatch.setenv("BLUEFOG_WIN_CODEC_BLOCK", "256")
    enc = cd.Int8Codec().encode(x)
    monkeypatch.setenv("BLUEFOG_WIN_CODEC_BLOCK", "8192")
    dec = cd.Int8Codec().decode(enc, np.float32, x.size)
    assert np.abs(dec - x).max() <= x.max() / 127.0 * 0.5 + 1e-6


def test_fusion_pack_row_codec_hooks():
    """pack_row/unpack_row accept a codec: the encode/decode insertion
    point the compressed wire documents (ops/fusion.py)."""
    leaves = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.ones((4,), np.float32)]
    spec = _fusion.make_spec([x[None] for x in leaves])
    c = cd.Int8Codec()
    enc = _fusion.pack_row(leaves, spec, codec=c)
    assert enc.dtype == np.uint8
    out = _fusion.unpack_row(enc, spec, codec=c)
    # both leaves share one quantization block: the bound is the PACKED
    # row's amax, not each leaf's own
    bound = max(np.abs(np.concatenate(
        [x.reshape(-1) for x in leaves])).max() / 127.0 * 0.5, 1e-6)
    for got, want in zip(out, leaves):
        assert got.shape == want.shape
        assert np.abs(got - want).max() <= bound * 1.01


def test_quantize_blend_matches_wire_grid():
    rng = np.random.RandomState(3)
    xs = rng.randn(512).astype(np.float32)
    x = jnp.asarray(xs)
    amax = float(np.abs(xs).max())
    y8 = np.asarray(cd.quantize_blend(x, cd.CODEC_INT8))
    assert np.abs(y8 - xs).max() <= amax / 127.0 * 0.51
    yf = np.asarray(cd.quantize_blend(x, cd.CODEC_FP8))
    # e4m3: ~6.25% relative error, absolute floor one smallest step
    assert np.all(np.abs(yf - xs) <=
                  np.maximum(np.abs(xs) * 0.0825, amax / 448.0))
    # top-k / none: identity (no dense-exchange analog)
    assert cd.quantize_blend(x, cd.CODEC_TOPK) is x
    assert cd.quantize_blend(x, cd.CODEC_NONE) is x


def test_pack_deposit_codec_header_layout():
    """The codec id rides the mode byte's high nibble + an extension
    header; codec_id=0 emits the LEGACY record layout byte for byte."""
    payload = np.arange(8, dtype=np.float32)
    legacy = win_ops._pack_deposit(win_ops._DEP_ACC, 1, 2.5, payload)
    assert bytes(legacy[0]) == struct.pack("<BBdI", 1, 1, 2.5, 1)
    enc = cd.Int8Codec().encode(payload)
    recs = win_ops._pack_deposit(win_ops._DEP_PUT, 0, 0.0, enc,
                                 codec_id=cd.CODEC_INT8, wt=0.25)
    mode, has_p, pc, nchunks = struct.unpack_from("<BBdI", recs[0])
    assert mode == (cd.CODEC_INT8 << win_ops._DEP_CODEC_SHIFT)
    wt, nbytes = struct.unpack_from(
        "<dQ", recs[0], win_ops._DEP_HDR)
    assert wt == 0.25 and nbytes == enc.nbytes
    assert b"".join(bytes(c) for c in recs[1:]) == enc.tobytes()


def test_planner_size_floor_sees_post_codec_bytes():
    """Satellite: the plane planner's static size estimate shrinks with
    the codec's nominal ratio, and ingested attribution bytes (already
    on-wire) are consumed as-is."""
    edges = [(0, 1)]
    owner = {0: 0, 1: 0}
    # 1 MB row, 0.5 MB floor: raw wire clears the floor -> compiled
    raw = PlanePlanner(2, edges, owner, row_bytes=1 << 20,
                       min_bytes=1 << 19)
    assert (0, 1) in raw.partition().compiled
    # int8 wire ships ~26% of the row: below the floor -> hosted residual
    q = PlanePlanner(2, edges, owner, row_bytes=1 << 20,
                     min_bytes=1 << 19,
                     wire_scale=cd.Int8Codec().nominal_ratio)
    assert (0, 1) in q.partition().hosted
    # measured attribution overrides the static estimate verbatim
    q.ingest_attribution({
        "schema_version": 1,
        "ranks": {"0": {"edges": {"0->1": {"bytes": float(1 << 20),
                                           "wire_sec_est": 0.01}}}},
    })
    assert (0, 1) in q.partition().compiled


def test_window_codec_scales_planner_estimate(bf_hosted_auto):
    """End-to-end: a window created under auto + int8 hands the planner
    the discounted wire estimate."""
    assert bf.win_create(jnp.ones((8, 64)), "cx.plan")
    win = win_ops._get_window("cx.plan")
    assert win._planner is not None
    assert win._planner.wire_scale == cd.Int8Codec().nominal_ratio
    assert win._planner.edge_cost(next(iter(win._planner.edges))) == \
        pytest.approx(64 * 4 * cd.Int8Codec().nominal_ratio)
    bf.win_free("cx.plan")


# ---------------------------------------------------------------------------
# hosted-plane wire: fixtures
# ---------------------------------------------------------------------------

def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _hosted_env(extra=None):
    env = {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(_free_port()),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
    }
    env.update(extra or {})
    return env


@pytest.fixture()
def bf_hosted():
    """bf over 8 CPU devices, control plane + forced hosted window plane.

    The codec is read at win_create time, so individual tests set
    BLUEFOG_WIN_CODEC (monkeypatch) before creating their windows."""
    env = _hosted_env()
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active()
    yield bf
    bf.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    cp.reset_for_test()


@pytest.fixture()
def bf_hosted_auto():
    """Hosted window WITH the per-edge planner (the hybrid harness shape)
    and the int8 codec configured."""
    env = _hosted_env({"BLUEFOG_WIN_PLANE": "auto",
                       "BLUEFOG_WIN_CODEC": "int8"})
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    yield bf
    bf.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    cp.reset_for_test()


def _remote_halves(win):
    """Shrink ownership to ranks 0-3 so puts to 4-7 ride the REAL server
    wire (the world-1 harness otherwise folds everything locally)."""
    win.owned = [0, 1, 2, 3]
    win.host.owned = set(win.owned)


def _restore_owned(win):
    win.owned = list(range(8))
    win.host.owned = set(win.owned)


# ---------------------------------------------------------------------------
# pinned legacy wire: BLUEFOG_WIN_CODEC=none is the r14 format, byte for byte
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [None, "none"])
def test_codec_none_wire_byte_identical(bf_hosted, monkeypatch, spec):
    """Unset AND explicit `none` must reproduce the r14 deposit records
    byte for byte: header `<BBdI` with a bare mode byte, payload = the
    weighted contribution in the wire dtype, no extension header."""
    if spec is None:
        monkeypatch.delenv("BLUEFOG_WIN_CODEC", raising=False)
    else:
        monkeypatch.setenv("BLUEFOG_WIN_CODEC", spec)
    x = jnp.asarray(np.random.RandomState(0).randn(8, 33).astype(np.float32))
    assert bf.win_create(x, "cx.pin", zero_init=True)
    win = win_ops._get_window("cx.pin")
    assert win.codec is None
    _remote_halves(win)
    try:
        bf.win_put(x, "cx.pin")
    finally:
        _restore_owned(win)
    cl = cp.client()
    xs = np.asarray(x)
    checked = 0
    for dst in range(4, 8):
        for src in win.in_neighbors[dst]:
            if src >= 4:
                continue
            k = win.layout.slot_of[dst][src]
            recs = cl.take_bytes(win._dep_key(dst, k))
            assert len(recs) == 2  # header record + one payload chunk
            # strip the server-prefixed i64 tag; the rest is the r14 wire
            assert recs[0][win_ops._DEP_TAG:] == struct.pack(
                "<BBdI", win_ops._DEP_PUT, 0, 0.0, 1)
            assert recs[1][win_ops._DEP_TAG:] == \
                (xs[src] * np.float32(1.0)).astype(np.float32).tobytes()
            checked += 1
    assert checked >= 4
    bf.win_free("cx.pin")


def test_int8_deposits_ride_encoded_wire(bf_hosted, monkeypatch):
    """With int8 on, server records carry the codec header + encoded
    payload (fewer on-wire bytes), and the drain decodes them into the
    mailbox exactly as the origin's own decode estimate."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")
    elems = 16_384
    x = jnp.asarray(np.random.RandomState(1).randn(8, elems).astype(
        np.float32))
    assert bf.win_create(x, "cx.i8", zero_init=True)
    win = win_ops._get_window("cx.i8")
    assert isinstance(win.codec, cd.Int8Codec)
    _remote_halves(win)
    try:
        bf.win_put(x, "cx.i8")
    finally:
        _restore_owned(win)
    cl = cp.client()
    xs = np.asarray(x)
    # peek one mailbox: on-wire bytes ~1/4 of the raw row
    dst = next(d for d in range(4, 8)
               if any(s < 4 for s in win.in_neighbors[d]))
    src = next(s for s in win.in_neighbors[dst] if s < 4)
    k = win.layout.slot_of[dst][src]
    recs = cl.take_bytes(win._dep_key(dst, k))
    wire_bytes = sum(len(r) - win_ops._DEP_TAG for r in recs)
    assert wire_bytes < elems * 4 / 3.5
    # re-inject and drain: the fold equals the origin-side estimate
    cl.append_bytes_tagged_many(
        [win._dep_key(dst, k)] * len(recs),
        [bytes(r[win_ops._DEP_TAG:]) for r in recs],
        [int.from_bytes(r[:win_ops._DEP_TAG], "little") for r in recs])
    win._drain_deposits()
    c = cd.Int8Codec()
    est = c.decode(c.encode(xs[src]), np.float32, elems)
    np.testing.assert_allclose(win._mail_rows[dst][k], est, rtol=1e-6,
                               atol=1e-6)
    bf.win_free("cx.i8")


def test_local_folds_match_wire_numerics(bf_hosted, monkeypatch):
    """Single-controller hosted windows fold the DECODED estimate locally,
    so a world-1 harness sees exactly the numerics a cross-controller
    wire produces — win_update matches the quantized oracle."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")
    x = jnp.asarray(np.random.RandomState(2).randn(8, 4096).astype(
        np.float32))
    assert bf.win_create(x, "cx.loc")
    bf.win_put(x, "cx.loc")
    got = np.asarray(bf.win_update("cx.loc"))
    topo = bf.load_topology()
    xs = np.asarray(x)
    c = cd.Int8Codec()
    est = {r: c.decode(c.encode(xs[r]), np.float32, 4096) for r in range(8)}
    for r in range(8):
        nbrs = bf.topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        want = u * xs[r] + u * sum(est[s] for s in nbrs)
        np.testing.assert_allclose(got[r], want, rtol=1e-5, atol=1e-5)
    # codec telemetry moved: raw > wire, ratio gauge ~4x
    snap = bf_metrics.snapshot()
    raw = snap["counters"].get("win.codec.raw_bytes", 0)
    wire = snap["counters"].get("win.codec.wire_bytes", 0)
    assert raw > wire > 0
    assert snap["gauges"].get("win.codec.ratio", 0) > 3.0
    bf.win_free("cx.loc")


def test_chunked_codec_deposit_reassembles(bf_hosted, monkeypatch):
    """A multi-chunk ENCODED deposit (encoded bytes > the chunk cap)
    reassembles by the extension header's byte count — not the row size —
    and folds the decoded payload once, exactly."""
    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")
    elems = 400_000  # 1.6 MB raw -> ~413 KB encoded -> 7 chunks of 64 KiB
    x = jnp.zeros((8, elems), jnp.float32)
    assert bf.win_create(x, "cx.chunk", zero_init=True)
    win = win_ops._get_window("cx.chunk")
    contrib = np.arange(elems, dtype=np.float32)
    c = cd.Int8Codec()
    enc = c.encode(contrib)
    assert enc.nbytes > 5 * (1 << 16)
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    recs = win_ops._pack_deposit(win_ops._DEP_ACC, 0, 0.0, enc,
                                 codec_id=cd.CODEC_INT8, wt=2.0)
    assert len(recs) > 3
    cl = cp.client()
    cl.append_bytes_tagged_many([win._dep_key(dst, k)] * len(recs), recs,
                                win_ops._deposit_tags(1, len(recs)))
    win._drain_deposits()
    est = c.decode(enc, np.float32, elems) * 2.0
    np.testing.assert_allclose(win._mail_rows[dst][k], est, rtol=1e-5,
                               atol=1e-5)
    bf.win_free("cx.chunk")


def test_published_rows_ride_state_codec(bf_hosted, monkeypatch):
    """Quantization codecs compress the published 'exposed window' copy
    (the other half of win_update's wire, and the whole of win_get's
    pull): the stored blob is magic-framed and ~4x smaller, and every
    reader decodes it back within the quantization bound."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")
    elems = 8192
    x = jnp.asarray(np.random.RandomState(5).randn(8, elems).astype(
        np.float32))
    assert bf.win_create(x, "cx.pub")
    win = win_ops._get_window("cx.pub")
    raw = cp.client().get_bytes(win._self_key(2))
    assert len(raw) < elems * 4 / 3.5  # compressed on the server
    assert struct.unpack_from("<I", raw, 0)[0] == win_ops._PUB_MAGIC
    got = win._read_remote_selves([2])[0]
    bound = np.abs(np.asarray(x)[2]).max() / 127.0 * 0.51
    assert np.abs(got - np.asarray(x)[2]).max() <= bound
    also = win.read_published_row(2)
    np.testing.assert_array_equal(also, got)
    bf.win_free("cx.pub")


def test_published_rows_none_raw_topk_int8_fallback(bf_hosted, monkeypatch):
    """Codec ``none`` keeps the raw byte-identical publish. Top-k cannot
    carry absolute state (a sparse snapshot would zero the unsent
    coordinates for every reader), and publishing RAW made win_get/pull
    pay full bytes under the one codec that compresses the deposit wire
    hardest — it now falls back to INT8 absolute-state payloads behind
    the same magic framing (ISSUE r17 satellite; the reader dispatches
    on the payload's own codec id). Byte-count asserted: the stored blob
    is ~4x smaller than the raw row."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "none")
    x = jnp.asarray(np.arange(8 * 16, dtype=np.float32).reshape(8, 16))
    assert bf.win_create(x, "cx.rawpub.none")
    win = win_ops._get_window("cx.rawpub.none")
    raw = cp.client().get_bytes(win._self_key(1))
    assert raw == np.asarray(x)[1].tobytes()
    bf.win_free("cx.rawpub.none")

    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "topk:0.1")
    elems = 8192
    xb = jnp.asarray(np.random.RandomState(7).randn(8, elems).astype(
        np.float32))
    assert bf.win_create(xb, "cx.rawpub.topk")
    win = win_ops._get_window("cx.rawpub.topk")
    assert win.codec is not None and win.codec.cid == cd.CODEC_TOPK
    raw = cp.client().get_bytes(win._self_key(1))
    # int8 fallback framing: magic header + int8 codec id + ~n/4 bytes
    assert struct.unpack_from("<IB", raw, 0)[:2] == \
        (win_ops._PUB_MAGIC, cd.CODEC_INT8)
    assert len(raw) < elems * 4 / 3.5, \
        f"top-k publish still ships ~raw bytes ({len(raw)} for {elems * 4})"
    got = win._read_remote_selves([1])[0]
    bound = np.abs(np.asarray(xb)[1]).max() / 127.0 * 0.51
    assert np.abs(got - np.asarray(xb)[1]).max() <= bound
    bf.win_free("cx.rawpub.topk")


# ---------------------------------------------------------------------------
# top-k + error feedback: convergence parity vs the uncompressed oracle
# ---------------------------------------------------------------------------

def _run_quadratic(steps=40, width=64):
    target = jnp.asarray(np.linspace(-2.0, 2.0, width, dtype=np.float32))

    def loss(params, batch):
        return jnp.sum((params["w"] - target) ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss)
    state = opt.init({"w": jnp.zeros(width)})
    batch = jnp.zeros((8, 1))
    losses = []
    for _ in range(steps):
        state, m = opt.step(state, batch)
        losses.append(float(np.asarray(m["loss"]).mean()))
    resid = opt.ef_residual_norm()
    opt.free()
    return np.asarray(losses), resid


def test_topk_ef_convergence_parity(bf_hosted, monkeypatch):
    """CHOCO/EF-SGD contract: the top-k + error-feedback optimizer tracks
    the uncompressed loss trajectory within tolerance — unsent
    coordinates are delayed by the delta/residual mechanism, not lost —
    and the residual norm stays bounded. (A raw overwrite top-k, the
    scheme the delta construction replaces, plateaus an order of
    magnitude higher — measured while building this test.)"""
    monkeypatch.delenv("BLUEFOG_WIN_CODEC", raising=False)
    base, resid0 = _run_quadratic()
    assert resid0 == 0.0  # no codec -> no residual
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "topk:0.5")
    comp, resid = _run_quadratic()
    # both descend to near-zero on the quadratic
    assert base[-1] < 0.01 * base[0]
    assert comp[-1] < 0.01 * comp[0], (base[-1], comp[-1])
    # trajectory parity: compressed loss stays within a band of the
    # uncompressed one at every step (normalized by the initial loss)
    gap = np.abs(comp - base) / base[0]
    assert gap.max() < 0.10, gap.max()
    assert np.isfinite(resid)


def test_int8_convergence_parity(bf_hosted, monkeypatch):
    """Quantization parity is much tighter than top-k: int8 per-block
    rounding tracks the uncompressed trajectory to a fraction of a
    percent of the initial loss at every step."""
    monkeypatch.delenv("BLUEFOG_WIN_CODEC", raising=False)
    base, _ = _run_quadratic(steps=20)
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")
    comp, resid = _run_quadratic(steps=20)
    assert resid == 0.0  # quantization runs without error feedback
    gap = np.abs(comp - base) / base[0]
    assert gap.max() < 0.01, gap.max()


def test_ef_residual_held_alongside_window(bf_hosted, monkeypatch):
    """The error-feedback residual lives next to the fused flat window:
    non-zero after a compressed gossip step, in the window's acc dtype,
    one row per owned rank, and the residual_norm gauge mirrors it."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "topk:0.1")

    def loss(params, batch):
        return jnp.sum(params["w"] ** 2)

    opt = bf.DistributedWinPutOptimizer(
        optax.sgd(0.1),
        loss_fn=lambda p, b: jnp.sum((p["w"] - 1.0) ** 2))
    state = opt.init({"w": jnp.zeros(32)})
    state, _ = opt.step(state, jnp.zeros((8, 1)))
    win = win_ops._get_window(opt._win_names[0])
    assert win.codec is not None and win.codec.error_feedback
    assert set(win._ef_rows) == set(win.owned)
    norm = opt.ef_residual_norm()
    assert norm > 0.0
    snap = bf_metrics.snapshot()
    assert snap["gauges"].get("win.codec.residual_norm", 0.0) > 0.0
    opt.free()


# ---------------------------------------------------------------------------
# push-sum: quantize the numerator, ship p exact
# ---------------------------------------------------------------------------

def test_pushsum_mass_conserved_under_int8(bf_hosted, monkeypatch):
    """The mass-conserving push-sum rule: deposits quantize the NUMERATOR
    while the associated-p channel ships exact (f64 in the header), so
    the r10 gauges stay green — sum(mass) == sum(minted) — and the
    de-biased estimate still lands near the true average."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")

    def loss(params, batch):
        return jnp.sum((params["w"] - 3.0) ** 2)

    opt = bf.DistributedPushSumOptimizer(optax.sgd(0.0), loss_fn=loss)
    state = opt.init({"w": jnp.linspace(0.0, 7.0, 8)[:, None]
                      * jnp.ones((1, 16))})
    # rank-divergent start: replicate() broadcast identical rows, so
    # spread them manually for a real consensus problem
    for _ in range(6):
        state, _ = opt.step(state, jnp.zeros((8, 1)))
    snap = bf_metrics.snapshot()
    mass = snap["gauges"]["pushsum.mass"]
    minted = snap["gauges"]["pushsum.minted"]
    assert mass == pytest.approx(minted, abs=1e-9)  # p is EXACT: 8 == 8
    assert mass == pytest.approx(8.0, abs=1e-9)
    win = win_ops._get_window(opt._win_names[0])
    p = win.host.read_p()
    assert np.sum(p) == pytest.approx(8.0, abs=1e-9)
    opt.free()


def test_pushsum_invariant_win_ops_under_fp8(bf_hosted, monkeypatch):
    """Raw win-op push-sum loop under fp8: p mass exactly 8 every round,
    value mass conserved within quantization tolerance."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "fp8")
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = jnp.arange(8.0).reshape(8, 1) + 1.0
        assert bf.win_create(x, "cx.ps", zero_init=True)
        topo = bf.load_topology()
        outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
                for r in range(8)}
        sw = {r: 1.0 / (outd[r] + 1) for r in range(8)}
        dw = {r: {d: 1.0 / (outd[r] + 1)
                  for d in bf.topology_util.out_neighbor_ranks(topo, r)}
              for r in range(8)}
        val = x
        for _ in range(4):
            bf.win_accumulate(val, "cx.ps", self_weight=sw, dst_weights=dw,
                              require_mutex=True)
            val = bf.win_update_then_collect("cx.ps")
            p = bf.win_associated_p_all("cx.ps")
            assert abs(p.sum() - 8.0) < 1e-9  # p NEVER compresses
            # numerator mass: conserved up to fp8 relative error per hop
            assert abs(float(np.asarray(val).sum()) - 36.0) < 36.0 * 0.1
        bf.win_free("cx.ps")
    finally:
        bf.turn_off_win_ops_with_associated_p()


# ---------------------------------------------------------------------------
# attribution: flow events carry on-wire (post-codec) bytes
# ---------------------------------------------------------------------------

def test_edge_flow_events_report_wire_bytes(bf_hosted, monkeypatch):
    """Satellite: the `edge.<src>.<dst>` flow events must record the
    POST-codec payload size — what step_attribution sums and the plane
    planner ingests — not the raw row size."""
    from bluefog_tpu.runtime import flight

    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "int8")
    elems = 8192
    x = jnp.asarray(np.random.RandomState(3).randn(8, elems).astype(
        np.float32))
    assert bf.win_create(x, "cx.flow", zero_init=True)
    win = win_ops._get_window("cx.flow")
    _remote_halves(win)
    try:
        bf.win_put(x, "cx.flow")
    finally:
        _restore_owned(win)
    rec = flight.recorder()
    snap = rec.snapshot()
    names = snap["names"]
    ev = snap["events"]
    edge_bytes = [a for kind, n, a in zip(ev["kind"], ev["name"], ev["a"])
                  if kind == flight.FLOW_S
                  and names[n].startswith("edge.")]
    assert edge_bytes, "no edge flow events recorded"
    raw = elems * 4
    assert all(0 < b < raw / 3.0 for b in edge_bytes[-4:]), \
        (edge_bytes[-4:], raw)
    # server mailboxes still hold the (undelivered) deposits; clean up
    bf.win_free("cx.flow")


# ---------------------------------------------------------------------------
# per-edge codecs (ISSUE r16): grammar, mixed wire, runtime switching
# ---------------------------------------------------------------------------

def test_resolve_edge_spec_grammar():
    base, over = cd.resolve_edge_spec("none;0>1=int8;2>3=topk:0.05")
    assert base is None
    assert isinstance(over[(0, 1)], cd.Int8Codec)
    assert isinstance(over[(2, 3)], cd.TopKCodec)
    assert over[(2, 3)].frac == 0.05
    # a bare single-codec spec parses exactly as before
    base, over = cd.resolve_edge_spec("int8")
    assert isinstance(base, cd.Int8Codec) and over == {}
    assert cd.resolve_edge_spec(None) == (None, {})
    # per-edge `none` under a compressed base: the raw-escape override
    base, over = cd.resolve_edge_spec("int8;1>0=none")
    assert isinstance(base, cd.Int8Codec) and over[(1, 0)] is None
    # malformed terms warn-skip; the rest of the spec survives
    base, over = cd.resolve_edge_spec("none;garbage;0-1=int8;3>4=fp8")
    assert base is None and set(over) == {(3, 4)}


def test_per_edge_codec_mixed_wire(bf_hosted, monkeypatch):
    """`none;0>1=int8`: every fold raw EXCEPT the overridden edge, whose
    contribution is the int8 decode estimate — the same single-edge
    escalation the tuner actuates, configured from the env grammar."""
    monkeypatch.setenv("BLUEFOG_WIN_CODEC", "none;0>1=int8")
    x = jnp.asarray(np.random.RandomState(5).randn(8, 4096).astype(
        np.float32))
    assert bf.win_create(x, "cx.pe")
    win = win_ops._get_window("cx.pe")
    assert win.codec is None
    assert isinstance(win.codec_for(0, 1), cd.Int8Codec)
    assert win.codec_for(0, 2) is None
    bf.win_put(x, "cx.pe")
    got = np.asarray(bf.win_update("cx.pe"))
    topo = bf.load_topology()
    xs = np.asarray(x)
    c = cd.Int8Codec()
    est01 = c.decode(c.encode(xs[0]), np.float32, 4096)
    for r in range(8):
        nbrs = bf.topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        want = u * xs[r] + u * sum(
            (est01 if (s, r) == (0, 1) else xs[s]) for s in nbrs)
        np.testing.assert_allclose(got[r], want, rtol=1e-5, atol=1e-5)
    # the overridden edge actually compressed: quantized, not equal
    assert np.abs(est01 - xs[0]).max() > 0
    bf.win_free("cx.pe")


def test_set_edge_codec_runtime_switch_and_rebase(bf_hosted, monkeypatch):
    """The tuner's codec lever end to end: switching one edge to an EF
    codec in put mode REBASES (full row through the state codec, fold
    PUT), the following put ships a delta that tightens the receiver
    estimate, and switching back to the base codec clears the override
    table — the wire is structurally back to the pre-switch shape."""
    monkeypatch.delenv("BLUEFOG_WIN_CODEC", raising=False)
    x = jnp.asarray(np.random.RandomState(6).randn(8, 4096).astype(
        np.float32))
    assert bf.win_create(x, "cx.sw")
    win = win_ops._get_window("cx.sw")
    assert win.codec is None and not win._edge_codec
    # no-op switch: same effective codec -> False, nothing recorded
    assert win.set_edge_codec(0, 1, "none") is False
    assert win.set_edge_codec(0, 1, "topk:0.5") is True
    assert isinstance(win.codec_for(0, 1), cd.TopKCodec)
    reb0 = bf_metrics.snapshot()["counters"].get(
        "win.codec.edge_rebase", 0)
    bf.win_put(x, "cx.sw")  # first EF put: rebase send
    assert bf_metrics.snapshot()["counters"]["win.codec.edge_rebase"] \
        == reb0 + 1
    assert (0, 1) in win._ef_edge_ref
    k = win.layout.slot_of[1][0]
    xs = np.asarray(x)
    gap_rebase = np.abs(win._mail_rows[1][k] - xs[0]).max()
    assert gap_rebase > 0  # int8 state-codec rebase: quantized
    bf.win_put(x, "cx.sw")  # second put: delta integrates on top
    gap_delta = np.abs(win._mail_rows[1][k] - xs[0]).max()
    assert gap_delta < gap_rebase  # the delta TIGHTENED the estimate
    assert win.ef_edge_residual_norm(0, 1) >= 0.0
    # switch back to the window codec: override table empties, put-mode
    # reference dropped (the next full PUT supersedes it)
    assert win.set_edge_codec(0, 1, None) is True
    assert not win._edge_codec and (0, 1) not in win._ef_edge_ref
    bf.win_put(x, "cx.sw")
    np.testing.assert_array_equal(win._mail_rows[1][k], xs[0])
    bf.win_free("cx.sw")


def test_pushsum_mass_exact_across_edge_codec_switches(bf_hosted,
                                                       monkeypatch):
    """Acceptance pin (ISSUE r16): push-sum mass stays EXACT while the
    tuner switches per-edge codecs mid-run. The associated-p channel
    ships exact under every codec, and the numerator obeys
    delivered + weighted-residual-in-flight == minted at every round —
    EF residuals HOLD mass across none -> topk -> int8 -> none switches,
    never lose it."""
    monkeypatch.delenv("BLUEFOG_WIN_CODEC", raising=False)
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = jnp.arange(8.0).reshape(8, 1) + 1.0  # total mass 36
        assert bf.win_create(x, "cx.msw", zero_init=True)
        win = win_ops._get_window("cx.msw")
        topo = bf.load_topology()
        outn = {r: bf.topology_util.out_neighbor_ranks(topo, r)
                for r in range(8)}
        sw = {r: 1.0 / (len(outn[r]) + 1) for r in range(8)}
        dw = {r: {d: 1.0 / (len(outn[r]) + 1) for d in outn[r]}
              for r in range(8)}

        def residual_mass():
            # weighted by the edge weight the eventual delivery will
            # carry (deposits ship wt * base; residuals track unweighted)
            return sum(dw[s][d] * float(rows.sum())
                       for (s, d), rows in win._ef_edge_rows.items())

        switches = {1: [((0, 1), "topk:0.5"), ((2, 3), "topk:0.5")],
                    2: [((0, 1), "int8")],
                    3: [((0, 1), "none"), ((2, 3), "none")]}
        val = x
        for rnd in range(5):
            for (s, d), spec in switches.get(rnd, ()):
                assert win.set_edge_codec(s, d, spec) is True
            bf.win_accumulate(val, "cx.msw", self_weight=sw,
                              dst_weights=dw, require_mutex=True)
            val = bf.win_update_then_collect("cx.msw")
            p = bf.win_associated_p_all("cx.msw")
            assert abs(p.sum() - 8.0) < 1e-9  # p NEVER compresses
            total = float(np.asarray(val, np.float64).sum())
            assert abs(total + residual_mass() - 36.0) < 1e-3, \
                (rnd, total, residual_mass())
        # after the switch back to raw, no residual mass remains in
        # flight: the uncompressed wire flushed it all
        assert residual_mass() == 0.0
        bf.win_free("cx.msw")
    finally:
        bf.turn_off_win_ops_with_associated_p()
