"""Live telemetry plane: ring history, estimators, rules, consumers.

Covers the r18 acceptance surface:

  * multi-resolution ring history (wrap, downsampling, window/rate/trend)
    and the delta-encoded ``bf.ts.<rank>`` wire format;
  * the alert-rule grammar + engine (fire after a sustained breach, flight
    instant + counter, clear);
  * per-edge estimators fed from REAL flight-ring flow events, and the
    consumer-side cross-rank flow matching;
  * the convergence gauges end to end on a 4-rank consensus workload: the
    streamed consensus distance matches a numpy oracle per step and decays
    toward 0, with a sub-1 mixing-rate estimate;
  * live per-edge transit vs the postmortem ``step_attribution`` flow
    pairing on the same run (within 20%);
  * ``bfrun --top`` rendering every rank from outside the mesh and naming
    a silent (stale-stream) rank.
"""

import contextlib
import io
import json
import os
import socket
import time

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import flight as flight_mod
from bluefog_tpu.runtime import metrics as metrics_mod
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime import timeseries as ts
from bluefog_tpu.runtime.state import _global_state

from conftest import cpu_devices


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# ring history
# ---------------------------------------------------------------------------

def test_tier_wraps_and_aggregates():
    t = ts._Tier(1.0, 8, "last")
    for i in range(20):
        t.add(1000.0 + i, float(i))
    times, vals = t.samples()
    # 8 flushed ring slots + the in-progress slot
    assert len(times) == 9
    assert vals[-1] == 19.0
    assert times[0] == 1011.0  # oldest surviving slot after the wrap

    m = ts._Tier(10.0, 4, "mean")
    for i in range(10):
        m.add(2000.0 + i, float(i))  # one 10 s slot
    _, vals = m.samples()
    assert vals[-1] == pytest.approx(4.5)  # mean of 0..9

    mx = ts._Tier(1.0, 4, "max")
    mx.add(1.0, 3.0)
    mx.add(1.2, 7.0)
    mx.add(1.4, 5.0)
    _, vals = mx.samples()
    assert vals[-1] == 7.0


def test_series_window_rate_trend():
    s = ts.Series("t.x", "counter", "last")
    for i in range(600):  # 10 min at 1 Hz: outruns the 1 s tier's ring
        s.add(5000.0 + i, float(10 * i))
    t, v = s.window(30)
    assert t[0] >= s.last_t - 30
    assert s.rate(60) == pytest.approx(10.0, rel=0.05)
    assert s.trend(120) == pytest.approx(10.0, rel=0.1)
    # a span longer than the 1 s tier falls back to a coarser tier
    t, v = s.window(500)
    assert t[-1] - t[0] >= 300


def test_mixing_rate_fit_from_decay():
    store = ts.TimeSeriesStore()
    d = store.series("opt.consensus_dist")
    for i in range(12):
        d.add(7000.0 + i, 100.0 * (0.7 ** i))
    store._derive(7012.0)
    assert store._series["opt.mixing_rate"].last_v == \
        pytest.approx(0.7, rel=0.05)
    # positive distance + decaying => not stalled
    assert store._series["opt.consensus_stalled"].last_v == 0.0


# ---------------------------------------------------------------------------
# publication wire format
# ---------------------------------------------------------------------------

def test_pack_unpack_roundtrip_and_bad_magic():
    doc = {"schema": 1, "rank": 3, "series": {"a": {"v": [1.5]}}}
    blob = ts.pack_doc(doc)
    assert blob[:4] == b"BFT1"
    assert ts.unpack_doc(blob) == doc
    with pytest.raises(ValueError):
        ts.unpack_doc(b"NOPE" + blob[4:])


def test_build_doc_delta_and_latest_row():
    store = ts.TimeSeriesStore()
    s = store.series("opt.step")
    for i in range(5):
        s.add(9000.0 + i, float(i))
    doc1 = store.build_doc(0, 0, 9005.0, 1.0)
    assert "opt.step" in doc1["series"]
    n1 = len(doc1["series"]["opt.step"]["v"])
    assert n1 >= 4
    # no new samples: the delta is empty but the constant-size `latest`
    # row still carries the current value (late-joining readers)
    doc2 = store.build_doc(0, 0, 9006.0, 1.0)
    assert "opt.step" not in doc2["series"]
    assert doc2["latest"]["opt.step"][1] == 4.0
    acc = ts.HistoryAccumulator()
    acc.update(0, ts.unpack_doc(ts.pack_doc(doc2)))
    assert acc.latest(0, "opt.step") == 4.0
    # the delta arrays reconstruct the timestamps
    acc2 = ts.HistoryAccumulator()
    acc2.update(0, doc1)
    hist = acc2.series[(0, "opt.step")]
    assert [round(t) for t, _ in hist][-2:] == [9003, 9004]


def test_full_publication_carries_tier_history():
    store = ts.TimeSeriesStore()
    s = store.series("opt.step")
    for i in range(120):
        s.add(10000.0 + i, float(i))
    doc = store.build_doc(0, 0, 10120.0, 1.0)  # seq 0 => full
    assert "hist" in doc and "opt.step" in doc["hist"]
    assert "10" in doc["hist"]["opt.step"]  # the 10 s downsampled tier


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------

def test_parse_rules_grammar_override_off_malformed():
    rules = {r.name: r for r in ts.parse_rules(
        "wal_lag:cp.repl_lag>100:for=5,mass_drift:off,garbage,"
        "custom:opt.step.rate<0.5:for=2")}
    assert rules["wal_lag"].threshold == 100.0
    assert rules["wal_lag"].for_sec == 5.0
    assert "mass_drift" not in rules
    assert rules["custom"].series == "opt.step.rate"
    assert rules["custom"].op == "<"
    # defaults survive untouched
    assert "straggler" in rules
    assert ts.parse_rules(None) == ts.DEFAULT_RULES


def test_rule_engine_fires_after_sustain_and_clears():
    store = ts.TimeSeriesStore()
    store._rules = ts.parse_rules("wal_lag:cp.repl_lag>100:for=5")
    store._rule_state = {r.name: ts._RuleState() for r in store._rules}
    lag = store.series("cp.repl_lag", "gauge", "max")
    fired0 = metrics_mod.counter("alert.fired").value
    # breach below the sustain window: no alert
    lag.add(1000.0, 500.0)
    store._evaluate_rules(1000.0)
    lag.add(1003.0, 500.0)
    store._evaluate_rules(1003.0)
    assert store.active_alerts() == []
    # sustained past for=5: fires once (counter + flight instant)
    lag.add(1006.0, 500.0)
    store._evaluate_rules(1006.0)
    active = store.active_alerts()
    assert [a["name"] for a in active] == ["wal_lag"]
    assert metrics_mod.counter("alert.fired").value == fired0 + 1
    store._evaluate_rules(1007.0)  # still active: no double fire
    assert metrics_mod.counter("alert.fired").value == fired0 + 1
    # condition clears
    lag.add(1008.0, 0.0)
    store._evaluate_rules(1008.0)
    assert store.active_alerts() == []
    # the fire left a flight instant behind
    snap = flight_mod.recorder().snapshot()
    names = snap["names"]
    assert any(names[n] == "alert.wal_lag"
               for n in snap["events"]["name"]
               if 0 <= n < len(names))


def test_sampler_records_bindings_and_rates():
    metrics_mod.gauge("opt.step").set(40.0)
    metrics_mod.counter("win.drain_bytes").inc(1000)
    store = ts.TimeSeriesStore()
    store.sample(now=2000.0)
    metrics_mod.gauge("opt.step").set(50.0)
    metrics_mod.counter("win.drain_bytes").inc(3000)
    store.sample(now=2002.0)
    assert store._series["opt.step"].last_v == 50.0
    assert store._series["opt.step.rate"].last_v == pytest.approx(5.0)
    assert store._series["win.drain_bytes.rate"].last_v == \
        pytest.approx(1500.0)


# ---------------------------------------------------------------------------
# per-edge estimators + consumer-side matching
# ---------------------------------------------------------------------------

def test_edge_estimator_from_real_flight_ring():
    rec = flight_mod.recorder()
    store = ts.TimeSeriesStore()
    store._scan_cursor = getattr(rec, "_n", 0)  # only our events
    nid = rec.intern("edge.0.1")
    did = rec.intern("drain.0")
    for fid in (901, 902, 903):
        rec.rec(flight_mod.FLOW_S, nid, 4096.0, fid)
        rec.rec(flight_mod.FLOW_F, did, 4096.0, fid)
    store.sample(now=3000.0)
    est = store.edges()["0->1"]
    assert est.deposits == 3
    assert est.bytes == pytest.approx(3 * 4096.0)
    p50, p99 = est.percentiles()
    assert p50 is not None and p50 >= 0.0 and p99 >= p50


def test_accumulator_matches_flows_across_ranks():
    acc = ts.HistoryAccumulator()
    acc.update(0, {"seq": 1, "ts": 100.0, "series": {}, "edges": {},
                   "flows": {"starts": [[7, 1_000_000, 512, 0, 2]],
                             "finishes": []}})
    acc.update(2, {"seq": 1, "ts": 100.0, "series": {}, "edges": {},
                   "flows": {"starts": [],
                             "finishes": [[7, 1_002_500]]}})
    p50, p99 = acc.edge_transit("0->2")
    assert p50 == pytest.approx(2500.0)
    assert p99 == pytest.approx(2500.0)


def test_silent_rank_detection_and_top_rendering():
    acc = ts.HistoryAccumulator()
    now = time.time()
    for r in (0, 1, 3):
        store = ts.TimeSeriesStore()
        store.series("opt.step").add(now, 10.0 + r)
        acc.update(r, store.build_doc(r, 0, now, 1.0))
    # rank 2 published long ago: stale stream
    old = ts.TimeSeriesStore()
    old.series("opt.step").add(now - 120, 3.0)
    doc = old.build_doc(2, 0, now - 120, 1.0)
    acc.update(2, doc)
    assert acc.silent_ranks(4, now) == [2]
    frame = ts.format_top(acc, 4, now=now)
    assert "SILENT rank(s): [2]" in frame
    for r in (0, 1, 3):
        assert f"\n  {r:>4} " in frame or f" {10.0 + r:.0f}" in frame
    assert ts.sparkline([1, 2, 3]) != ""
    assert ts.sparkline([]) == ""


# ---------------------------------------------------------------------------
# end-to-end: the 4-rank consensus workload
# ---------------------------------------------------------------------------

@pytest.fixture()
def bf_hosted_ts(monkeypatch, tmp_path):
    if native.load() is None:
        pytest.skip("native runtime unavailable")
    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
        "BLUEFOG_METRICS_INTERVAL": "1",
        "BLUEFOG_TS_INTERVAL": "1",
        "BLUEFOG_FLIGHT_DIR": str(tmp_path),
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(4))
    assert cp.active()
    yield bf
    bf.shutdown()
    cp.reset_for_test()


def _consensus_job(bf_, steps=6, dim=16, seed=0):
    """A 4-rank win-put consensus workload: per-rank perturbed params,
    zero loss — gossip alone drives them together. Returns (opt, gauge
    readings per step, numpy oracle distances per step)."""
    import jax.numpy as jnp
    import optax

    from bluefog_tpu import optimizers as opt_mod
    from bluefog_tpu.ops import windows as win_mod

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf_.DistributedWinPutOptimizer(optax.sgd(0.1), zloss,
                                         window_prefix="ts.cons")
    state = opt.init({"w": jnp.ones((dim,), jnp.float32)})
    rng = np.random.default_rng(seed)
    noise = rng.normal(size=(4, dim)).astype(np.float32)
    pert = state.params["w"] + bf_.shard_rank_stacked(
        bf_.mesh(), jnp.asarray(noise))
    state = opt_mod.TrainState(
        {"w": pert}, state.opt_state, state.model_state)

    win = win_mod._get_window(opt._win_names[0])
    n = win.size
    W = np.zeros((n, n))
    for r in range(n):
        u = 1.0 / (len(win.in_neighbors[r]) + 1)
        W[r, r] = u
        for s in win.in_neighbors[r]:
            W[r, s] = u
    X = np.asarray(pert, np.float64)
    gauges, oracle = [], []
    for _ in range(steps):
        # defeat the gauge's ~1 Hz cadence gate: the oracle wants a
        # reading at EVERY step
        opt._consensus_t = 0.0
        # oracle BEFORE the step: distance to the combine-weighted
        # neighbor mean from the pre-gossip rows
        d2 = []
        for r in range(n):
            nbrs = win.in_neighbors[r]
            mean = np.mean([X[s] for s in nbrs], axis=0)
            d2.append(np.sum((X[r] - mean) ** 2))
        oracle.append(float(np.sqrt(np.mean(d2))))
        state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))
        gauges.append(metrics_mod.gauge("opt.consensus_dist").value)
        ts.maybe_sample(force=True, publish=True)
        X = W @ X
    return opt, gauges, oracle


def test_consensus_gauge_matches_oracle_and_decays(bf_hosted_ts):
    """Acceptance: the streamed consensus-distance gauge equals the numpy
    oracle (combine-weighted neighbor-mean distance) within tolerance at
    every step and decays toward 0; the fitted mixing rate lands in
    (0, 1)."""
    opt, gauges, oracle = _consensus_job(bf_hosted_ts, steps=6)
    try:
        for got, want in zip(gauges, oracle):
            assert got == pytest.approx(want, rel=1e-3, abs=1e-9)
        assert gauges[-1] < 0.2 * gauges[0]  # decays toward 0
        assert gauges[-1] == min(gauges)
        # the STREAMED series agrees with the gauge trail
        acc = ts.HistoryAccumulator()
        doc = ts.read_rank(cp.client(), 0)
        assert doc is not None
        acc.update(0, doc)
        vals = acc.values(0, "opt.consensus_dist", last=16)
        assert vals, "no consensus series streamed"
        assert vals[-1] == pytest.approx(gauges[-1], rel=1e-4)
        # effective mixing rate: fitted from the decay, strictly < 1
        mix = acc.latest(0, "opt.mixing_rate")
        assert mix is not None and 0.0 < mix < 1.0
    finally:
        opt.free()


def test_push_sum_skips_consensus_gauge(bf_hosted_ts):
    import jax.numpy as jnp
    import optax

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    metrics_mod.gauge("opt.consensus_dist").set(-1.0)  # sentinel
    opt = bf_hosted_ts.DistributedPushSumOptimizer(
        optax.sgd(0.1), zloss, window_prefix="ts.ps")
    state = opt.init({"w": jnp.ones((8,), jnp.float32)})
    try:
        state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))
        assert metrics_mod.gauge("opt.consensus_dist").value == -1.0
    finally:
        opt.free()


def test_live_transit_agrees_with_postmortem(bf_hosted_ts, monkeypatch,
                                             tmp_path):
    """Acceptance: per-edge deposit→drain transit from the LIVE series
    agrees with the postmortem step_attribution flow pairing over the
    same run within 20% (they observe the same ring; the live side keeps
    a bounded percentile window)."""
    import sys

    import jax.numpy as jnp

    from bluefog_tpu.ops import windows as win_mod

    bf_ = bf_hosted_ts
    st = _global_state()
    x = bf_.shard_rank_stacked(bf_.mesh(), jnp.ones((4, 512)))
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [0, 1])
    assert bf_.win_create(x, "ts.flow", zero_init=True)
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [2, 3])
    win_b = win_mod.Window("ts.flow", np.ones((4, 512), np.float32),
                           zero_init=True)
    store = ts.store()
    for _ in range(6):
        bf_.win_put(x, "ts.flow")
        with win_b.state_mu:
            win_b._drain_deposits()
    ts.maybe_sample(force=True, publish=True)

    # live side: estimator percentiles from the published stream
    acc = ts.HistoryAccumulator()
    doc = ts.read_rank(cp.client(), 0)
    assert doc is not None
    acc.update(0, doc)
    live_edges = {e for e in acc.edges[0]}
    assert live_edges, "no live edges"

    # postmortem side: flow pairs over the SAME ring, via the script's
    # loader (the machine interface the planner consumes)
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        import step_attribution
    finally:
        sys.path.pop(0)
    dump = flight_mod.build_dump("test")
    pairs = step_attribution.flow_pairs({0: dump})
    for edge in live_edges:
        p50, _ = acc.edge_transit(edge)
        post = pairs.get(edge)
        assert post is not None, f"postmortem lost edge {edge}"
        med = sorted(post["transit_us"])[len(post["transit_us"]) // 2]
        assert p50 == pytest.approx(med, rel=0.2), \
            f"edge {edge}: live p50 {p50} vs postmortem median {med}"
        est = acc.edges[0][edge]
        assert est["bytes"] == pytest.approx(post["bytes"], rel=0.2)
    st.windows.pop("ts.flow", None)


def test_top_renders_all_ranks_and_names_silent(bf_hosted_ts):
    """Acceptance: ``bfrun --top`` renders all 4 ranks from OUTSIDE the
    mesh (raw client) and names a rank whose stream went stale — the
    SIGKILL detector (obs-smoke kills a real publisher process; here the
    stale stream is synthesized for tier-1 speed)."""
    import jax.numpy as jnp
    import optax

    bf_ = bf_hosted_ts

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf_.DistributedWinPutOptimizer(optax.sgd(0.1), zloss,
                                         window_prefix="ts.top")
    state = opt.init({"w": jnp.ones((8,), jnp.float32)})
    state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))
    ts.maybe_sample(force=True, publish=True)
    cl = cp.client()
    now = time.time()
    # ranks 1..3 publish via raw stores (the external-controller shape);
    # rank 2's stream is STALE — its "process" died
    for r, age in ((1, 0.0), (2, 300.0), (3, 0.0)):
        store = ts.TimeSeriesStore()
        store.series("opt.step").add(now - age, 5.0)
        cl.put_bytes(ts.TS_KEY_FMT.format(rank=r), ts.pack_doc(
            store.build_doc(r, 0, now - age, 1.0)))

    from bluefog_tpu import launcher

    class _Args:
        cp = f"127.0.0.1:{os.environ['BLUEFOG_CP_PORT']}"
        top = True
        once = True
        world = 4
        interval = 2.0

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = launcher._top(_Args())
    assert rc == 0
    text = out.getvalue()
    assert "4 rank(s)" in text
    for r in range(4):
        assert f"\n  {r:>4} " in text, f"rank {r} missing:\n{text}"
    assert "SILENT rank(s): [2]" in text
    opt.free()


def test_status_strict_flags_sustained_shard_drift(bf_hosted_ts):
    """--status --strict exits 2 when the streamed
    win.shard_stale_drops.rate series shows ≥3 consecutive positive
    samples (sustained rotation drift), and stays 0 on a healthy job."""
    import jax.numpy as jnp
    import optax

    bf_ = bf_hosted_ts

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf_.DistributedPushSumOptimizer(optax.sgd(0.1), zloss,
                                          window_prefix="ts.drift")
    state = opt.init({"w": jnp.ones((8,), jnp.float32)})
    for _ in range(2):
        state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))
    metrics_mod.publish_now()
    ts.maybe_sample(force=True, publish=True)

    from bluefog_tpu import launcher

    class _Args:
        cp = f"127.0.0.1:{os.environ['BLUEFOG_CP_PORT']}"
        status = True
        strict = True

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = launcher._status(_Args())
    assert rc == 0, out.getvalue()

    # synthesize a sustained-drift stream for rank 0 (the wire guard
    # discarding every deposit — win.shard_stale_drops velocity > 0)
    store = ts.TimeSeriesStore()
    now = time.time()
    s = store.series("win.shard_stale_drops.rate", "gauge", "mean")
    for i in range(5):
        s.add(now - 5 + i, 2.0)
    cp.client().put_bytes(ts.TS_KEY_FMT.format(rank=0), ts.pack_doc(
        store.build_doc(0, 0, now, 1.0)))
    err = io.StringIO()
    out = io.StringIO()
    with contextlib.redirect_stdout(out), \
            contextlib.redirect_stderr(err):
        rc = launcher._status(_Args())
    assert rc == 2, err.getvalue()
    assert "shard-rotation drift" in err.getvalue()
    opt.free()


def test_alerts_key_published_when_rule_fires(bf_hosted_ts):
    """A firing rule publishes under bf.alerts.<rank> (zlib JSON) and
    rides the next bf.ts delta's alerts field."""
    import zlib

    store = ts.store()
    store._rules = ts.parse_rules("wal_lag:cp.repl_lag>100:for=0")
    store._rule_state = {r.name: ts._RuleState() for r in store._rules}
    metrics_mod.gauge("cp.repl_lag").set(5000.0)
    ts.maybe_sample(force=True, publish=True)
    ts.maybe_sample(force=True, publish=True)  # sustain >= for=0, fire
    doc = ts.read_rank(cp.client(), 0)
    assert doc is not None
    assert any(a["name"] == "wal_lag" for a in doc.get("alerts", []))
    blob = cp.client().get_bytes(ts.ALERTS_KEY_FMT.format(rank=0))
    alerts = json.loads(zlib.decompress(bytes(blob)).decode())
    assert alerts and alerts[0]["name"] == "wal_lag"
    metrics_mod.gauge("cp.repl_lag").set(0.0)


def test_knob_disable_turns_plane_off(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TS_DISABLE", "1")
    assert not ts.enabled()
    ts.maybe_sample(force=True, publish=True)  # no-op, no raise
    monkeypatch.delenv("BLUEFOG_TS_DISABLE")
    assert ts.enabled()
