"""Self-tuning controller (ISSUE r16): the decision table as a unit.

``Tuner.decide`` is a pure function of (Snapshot, hysteresis state), so
every row of the decision table — slow-edge codec escalation, pressure
de-escalation, straggler demotion, recovery promotion — runs here over
synthetic series with no control plane, no windows, no clock. The
epoch-fence, dwell, and sustained-breach gates are pinned the same way,
plus the three safety contracts the ISSUE names: BLUEFOG_TUNE=0 touches
NOTHING (byte-identical off path), every actuation is fenced on the
membership epoch, and demote -> promote restores the weight matrix
EXACTLY (the topology round-trip).
"""

import json

import networkx as nx
import numpy as np
import pytest

from bluefog_tpu import topology_util as tu
from bluefog_tpu.runtime import metrics as bf_metrics
from bluefog_tpu.runtime import tuner


RULES = dict(tuner.DEFAULT_RULES, slow_for=10.0, straggler_for=10.0,
             dwell=30.0)


@pytest.fixture(autouse=True)
def _fresh_tuner_state():
    tuner.reset_for_job()
    yield
    tuner.reset_for_job()


def _tuner(rank=0, world=4, **over):
    return tuner.Tuner(rank, world, rules=dict(RULES, **over))


def _snap(now, edges=None, stragglers=(), alerts=(), ef_norm=0.0,
          owned=(0,), epoch=0, rank=0):
    return tuner.Snapshot(
        now=now, epoch=epoch, rank=rank, owned=set(owned),
        edges={e: tuner.EdgeSample(*v) if isinstance(v, tuple)
               else tuner.EdgeSample(bps=v)
               for e, v in (edges or {}).items()},
        stragglers=set(stragglers), alerts=set(alerts), ef_norm=ef_norm)


def _apply(t, snap):
    out = t.decide(snap)
    for d in out:
        t.note_applied(d, snap.now)
    return out


# ---------------------------------------------------------------------------
# rules grammar
# ---------------------------------------------------------------------------

def test_parse_tune_rules_grammar():
    assert tuner.parse_tune_rules(None) == tuner.DEFAULT_RULES
    r = tuner.parse_tune_rules("slow_ratio=0.3, dwell=5")
    assert r["slow_ratio"] == 0.3 and r["dwell"] == 5.0
    assert r["slow_for"] == tuner.DEFAULT_RULES["slow_for"]
    # unknown keys and malformed values warn-skip (config never crashes)
    r = tuner.parse_tune_rules("bogus=1,slow_for=abc,keep_in=2")
    assert r["slow_for"] == tuner.DEFAULT_RULES["slow_for"]
    assert r["keep_in"] == 2.0 and "bogus" not in r


# ---------------------------------------------------------------------------
# codec lever: escalation / de-escalation
# ---------------------------------------------------------------------------

def test_slow_edge_escalates_ladder_after_sustained_breach():
    t = _tuner()
    edges = {(0, 1): 10.0, (0, 2): 1000.0, (2, 3): 1100.0}
    # first sighting starts the breach clock: no move yet
    assert _apply(t, _snap(0.0, edges)) == []
    # still breaching but not yet slow_for seconds: no move
    assert _apply(t, _snap(5.0, edges)) == []
    # sustained past slow_for: ONE rung up (none -> int8)
    out = _apply(t, _snap(11.0, edges))
    assert [(d.lever, d.target, d.action, d.arg) for d in out] == \
        [("codec", (0, 1), "escalate", "int8")]
    # dwell: the same edge cannot move again for dwell seconds, but the
    # breach clock keeps running underneath
    assert _apply(t, _snap(12.0, edges)) == []
    assert _apply(t, _snap(40.0, edges)) == []   # 29 s since the move
    # dwell expired + breach still sustained: the next rung (topk)
    out = _apply(t, _snap(45.0, edges))
    assert [(d.target, d.arg) for d in out] == [((0, 1), "topk:0.01")]
    # top of the ladder: no further escalation ever
    assert _apply(t, _snap(100.0, edges)) == []
    assert _apply(t, _snap(111.0, edges)) == []


def test_breach_clock_resets_when_edge_recovers():
    t = _tuner()
    slow = {(0, 1): 10.0, (0, 2): 1000.0, (2, 3): 1000.0}
    fast = {(0, 1): 900.0, (0, 2): 1000.0, (2, 3): 1000.0}
    _apply(t, _snap(0.0, slow))
    _apply(t, _snap(8.0, fast))    # recovered before slow_for: clock off
    assert _apply(t, _snap(11.0, slow)) == []  # new clock starts HERE
    assert _apply(t, _snap(20.0, slow)) == []
    out = _apply(t, _snap(22.0, slow))
    assert len(out) == 1 and out[0].target == (0, 1)


def test_only_owned_out_edges_escalate():
    t = _tuner()
    edges = {(3, 1): 10.0, (0, 2): 1000.0, (2, 3): 1100.0}
    _apply(t, _snap(0.0, edges, owned=(0,)))
    # (3,1) is slow but rank 3 is not ours: rank 3's controller owns it
    assert _apply(t, _snap(11.0, edges, owned=(0,))) == []


def test_absolute_floor_and_transit_p99_triggers():
    t = _tuner(min_bps=500.0)
    edges = {(0, 1): 400.0, (0, 2): 600.0, (2, 3): 650.0}
    _apply(t, _snap(0.0, edges))
    out = _apply(t, _snap(11.0, edges))
    assert [d.target for d in out] == [(0, 1)] and "floor" in out[0].reason
    t2 = _tuner(transit_p99_ms=50.0)
    edges = {(0, 1): (1000.0, 80_000.0), (0, 2): (1000.0, 1000.0),
             (2, 3): (1000.0, 900.0)}
    _apply(t2, _snap(0.0, edges))
    out = _apply(t2, _snap(11.0, edges))
    assert [d.target for d in out] == [(0, 1)]
    assert "p99" in out[0].reason


def test_deescalation_on_consensus_stall_and_ef_pressure():
    t = _tuner()
    t._level[(0, 1)] = 2  # already at topk
    t._level[(0, 2)] = 1  # at int8
    out = _apply(t, _snap(0.0, alerts={"consensus_stall"}))
    # every raised level walks ONE rung back
    assert sorted((d.target, d.arg) for d in out
                  if d.action == "deescalate") == \
        [((0, 1), "int8"), ((0, 2), None)]
    assert t._level == {(0, 1): 1}  # int8 edge fell off the ladder
    # EF-residual pressure triggers the same path (after dwell)
    t2 = _tuner(deesc_norm=5.0)
    t2._level[(0, 1)] = 1
    out = _apply(t2, _snap(0.0, ef_norm=9.0))
    assert [(d.target, d.action) for d in out] == [((0, 1), "deescalate")]
    assert t2._level == {}
    # below the norm threshold: nothing moves
    t2._level[(0, 1)] = 1
    assert _apply(t2, _snap(100.0, ef_norm=1.0)) == []


# ---------------------------------------------------------------------------
# in-degree lever: demote / promote
# ---------------------------------------------------------------------------

def test_straggler_demotes_then_promotes_on_recovery():
    t = _tuner()
    _apply(t, _snap(0.0, stragglers={3}))
    assert t._demoted == {}
    out = _apply(t, _snap(11.0, stragglers={3}))
    assert [(d.lever, d.target, d.action) for d in out] == \
        [("indegree", 3, "demote")]
    assert 3 in t._demoted
    # still a straggler: no repeat demotion (already demoted)
    assert _apply(t, _snap(12.0, stragglers={3})) == []
    # recovery must be SUSTAINED too — and the promote respects dwell
    assert _apply(t, _snap(45.0, stragglers=set())) == []
    out = _apply(t, _snap(56.0, stragglers=set()))
    assert [(d.target, d.action) for d in out] == [(3, "promote")]
    assert t._demoted == {}


def test_straggler_relapse_resets_recovery_clock():
    t = _tuner()
    t._demoted[3] = frozenset({(1, 3)})
    t._last_act[("indegree", 3)] = -100.0
    _apply(t, _snap(0.0, stragglers=set()))   # recovery clock starts
    _apply(t, _snap(5.0, stragglers={3}))     # relapse: clock resets
    assert _apply(t, _snap(12.0, stragglers=set())) == []  # fresh clock
    out = _apply(t, _snap(23.0, stragglers=set()))
    assert [(d.target, d.action) for d in out] == [(3, "promote")]


def test_demote_targets_keep_fastest_in_edges():
    t = _tuner(keep_in=1)

    class _W:
        in_neighbors = {3: [0, 1, 2]}
    import bluefog_tpu.runtime.state as _state
    st = _state._global_state()
    old = dict(st.windows)
    st.windows.clear()
    st.windows["w"] = _W()
    try:
        snap = _snap(0.0, {(0, 3): 50.0, (1, 3): 900.0, (2, 3): 200.0})
        drops = t._demote_targets(snap, 3)
        # keeps the fastest in-edge (1->3); drops the rest
        assert sorted(drops) == [(0, 3), (2, 3)]
        t2 = _tuner(keep_in=2)
        assert sorted(t2._demote_targets(snap, 3)) == [(0, 3)]
    finally:
        st.windows.clear()
        st.windows.update(old)


# ---------------------------------------------------------------------------
# the tick: epoch fence, single-controller application, off path
# ---------------------------------------------------------------------------

def test_epoch_fence_defers_decision_racing_rejoin(monkeypatch):
    """A membership-epoch bump (death/rejoin) between the sensor snapshot
    and the actuation defers the decision: it was derived against a stale
    edge set, and the next tick re-decides against the new membership."""
    import bluefog_tpu.runtime.heartbeat as hb

    monkeypatch.setenv("BLUEFOG_TUNE", "1")
    t = _tuner()
    t._breach[("straggler", 3)] = -100.0  # sustained long ago
    snap = _snap(0.0, stragglers={3}, epoch=5)
    monkeypatch.setattr(t, "gather", lambda cl=None, now=None: snap)
    monkeypatch.setattr(hb, "membership_epoch", lambda: 6)  # mid-decision
    deferred0 = bf_metrics.counter("tune.deferred").value
    applied = t.tick(cl=None, now=0.0)
    assert applied == []
    assert bf_metrics.counter("tune.deferred").value == deferred0 + 1
    assert t._demoted == {}                    # state untouched
    assert ("indegree", 3) not in t._last_act  # dwell NOT burned
    assert t._decisions[-1]["status"] == "deferred"


def test_single_controller_demotion_applies_through_tick(monkeypatch):
    import bluefog_tpu.runtime.heartbeat as hb

    monkeypatch.setenv("BLUEFOG_TUNE", "1")
    t = _tuner(rank=0, world=4)
    t._breach[("straggler", 3)] = -100.0
    snap = _snap(0.0, stragglers={3}, epoch=0)
    monkeypatch.setattr(t, "gather", lambda cl=None, now=None: snap)
    monkeypatch.setattr(hb, "membership_epoch", lambda: 0)
    monkeypatch.setattr(hb, "dead_controllers", lambda: set())
    monkeypatch.setattr(t, "_demote_targets",
                        lambda s, p: [(0, 3), (2, 3)])
    applied = t.tick(cl=None, now=0.0)
    assert [(d.lever, d.action) for d in applied] == [("indegree",
                                                       "demote")]
    # the optimizers' accessor sees it immediately (no KV, no epoch wait)
    assert tuner.demoted_edges() == frozenset({(0, 3), (2, 3)})
    # recovery: sustained non-straggler past dwell -> promote, set empties
    snap2 = _snap(45.0, stragglers=set(), epoch=0)
    monkeypatch.setattr(t, "gather", lambda cl=None, now=None: snap2)
    assert t.tick(cl=None, now=45.0) == []  # recovery clock starts
    snap3 = _snap(56.0, stragglers=set(), epoch=0)
    monkeypatch.setattr(t, "gather", lambda cl=None, now=None: snap3)
    applied = t.tick(cl=None, now=56.0)
    assert [(d.action) for d in applied] == ["promote"]
    assert tuner.demoted_edges() == frozenset()


def test_tune_off_touches_nothing(monkeypatch):
    """BLUEFOG_TUNE=0 (the default): demoted_edges() is the empty set
    with ZERO control-plane traffic, maybe_tick never builds the
    singleton — the untuned build's wire is byte-identical by
    construction because no tuner code path runs at all."""
    import bluefog_tpu.runtime.control_plane as cp

    monkeypatch.delenv("BLUEFOG_TUNE", raising=False)

    def _boom(*a, **k):  # any control-plane touch is a failure
        raise AssertionError("tuner touched the control plane while off")

    monkeypatch.setattr(cp, "active", _boom)
    monkeypatch.setattr(cp, "client", _boom)
    assert tuner.enabled() is False
    assert tuner.demoted_edges() == frozenset()
    tuner.maybe_tick(cl=None)
    assert tuner._singleton is None  # never even constructed


def test_maybe_tick_interval_gated(monkeypatch):
    monkeypatch.setenv("BLUEFOG_TUNE", "1")
    monkeypatch.setenv("BLUEFOG_TUNE_INTERVAL", "100")
    t = _tuner()
    calls = []
    monkeypatch.setattr(t, "tick",
                        lambda cl=None, now=None: calls.append(now))
    t.maybe_tick(cl=None, now=1000.0)
    assert calls == [1000.0]
    t._last_tick = 1000.0
    t.maybe_tick(cl=None, now=1050.0)   # inside the interval: gated
    assert calls == [1000.0]
    t.maybe_tick(cl=None, now=1101.0)
    assert calls == [1000.0, 1101.0]


def test_decision_trail_document_shape(monkeypatch):
    """The bf.tune.<rank> document --top renders: codec levels in the
    `s>d` grammar, demoted map, bounded decision ring."""
    monkeypatch.setenv("BLUEFOG_TUNE", "1")
    t = _tuner()
    t._level[(0, 1)] = 1
    t._demoted[3] = frozenset({(0, 3)})
    t._record(tuner.Decision("codec", (0, 1), "escalate", "int8", "slow"),
              1.0, "applied")
    wrote = {}

    class _Cl:
        def put_bytes(self, key, blob):
            wrote[key] = blob
    t._publish_trail(_Cl(), now=2.0)
    doc = json.loads(wrote["bf.tune.0"].decode())
    assert doc["levels"] == {"0>1": "int8"}
    assert doc["demoted"] == {"3": [[0, 3]]}
    assert doc["decisions"][-1]["action"] == "escalate"
    assert doc["decisions"][-1]["target"] == [0, 1]


# ---------------------------------------------------------------------------
# optimizers: healed tables honor demoted edges
# ---------------------------------------------------------------------------

def test_healed_tables_treat_demoted_edges_like_dead_for_that_column():
    """The demotion's runtime realization: the demoted edge drops from
    the receiver's column (renormalized — convex combination preserved)
    AND from the sender's table (the skipped send is where the wire
    bytes are actually saved). Other columns never move."""
    from bluefog_tpu import optimizers as O

    class _Win:
        size = 4
        out_neighbors = {0: [1], 1: [2], 2: [3], 3: [0]}
        in_neighbors = {0: [3], 1: [0], 2: [1], 3: [2]}

    win = _Win()
    demoted = frozenset({(2, 3)})
    sw, nw = O._healed_recv_weights(win, set(), None, None, demoted)
    assert nw[3] == {} and sw[3] == 1.0   # only in-edge demoted: self-only
    assert nw[1] == {0: 0.5} and sw[1] == 0.5  # untouched column
    send = O._healed_send_table(win, set(), None, demoted)
    assert send[2] == {} and send[1] == {2: 1.0}
    # custom weights: the demoted column renormalizes to its old total
    nbr_w = {r: {p: 0.5 for p in win.in_neighbors[r]} for r in range(4)}
    sw2, nw2 = O._healed_recv_weights(win, set(), 0.5, nbr_w, demoted)
    assert sw2[3] == pytest.approx(1.0) and nw2[3] == {}
    assert sw2[1] == pytest.approx(0.5)
    assert nw2[1] == {0: pytest.approx(0.5)}
    # demotion composes with a dead set
    sw3, nw3 = O._healed_recv_weights(win, {0}, None, None, demoted)
    assert nw3[1] == {} and nw3[3] == {}


# ---------------------------------------------------------------------------
# topology: demote -> promote restores W exactly
# ---------------------------------------------------------------------------

def test_demote_preserves_column_sums_and_composes():
    G = tu.ExponentialTwoGraph(8)
    W0 = nx.to_numpy_array(G)
    Gd = tu.demote_in_edges(G, 3, {1, 2})
    Wd = nx.to_numpy_array(Gd)
    # only column 3 changed; its sum is preserved exactly
    np.testing.assert_allclose(np.delete(Wd, 3, axis=1),
                               np.delete(W0, 3, axis=1))
    assert Wd[:, 3].sum() == pytest.approx(W0[:, 3].sum(), abs=1e-12)
    assert Wd[1, 3] == 0.0 and Wd[2, 3] == 0.0
    assert Wd[3, 3] > W0[3, 3]  # renormalized onto the survivors
    # composes: a second rank's demotion re-derives from the ORIGINAL
    Gdd = tu.demote_in_edges(Gd, 5, {4})
    Wdd = nx.to_numpy_array(Gdd)
    np.testing.assert_allclose(Wdd[:, 3], Wd[:, 3])
    assert Wdd[:, 5].sum() == pytest.approx(W0[:, 5].sum(), abs=1e-12)


def test_demote_promote_roundtrip_restores_w_exactly():
    """The acceptance pin: promote(demote(G)) == G, bit for bit — the
    controller's recovery path leaves NO residue in the mixing matrix."""
    G = tu.ExponentialTwoGraph(8)
    W0 = nx.to_numpy_array(G)
    Gd = tu.demote_in_edges(G, 3, {1, 2})
    Gp = tu.promote_rank(Gd, 3)
    np.testing.assert_array_equal(nx.to_numpy_array(Gp), W0)
    assert "_bf_demote" not in Gp.graph or \
        not Gp.graph["_bf_demote"]["demoted"]
    # partial promotion: rank 5's demotion survives rank 3's recovery
    Gd2 = tu.demote_in_edges(Gd, 5, {4})
    Gp2 = tu.promote_rank(Gd2, 3)
    W2 = nx.to_numpy_array(Gp2)
    np.testing.assert_allclose(W2[:, 3], W0[:, 3])
    assert W2[4, 5] == 0.0
    # promoting a never-demoted rank is the identity (idempotent)
    assert tu.promote_rank(G, 2) is G


def test_demote_never_drops_self_loop_and_guards_empty_column():
    G = tu.RingGraph(4, connect_style=1)  # single-direction ring
    # rank 1's only real in-edge is 2->1; self in the drop set: ignored
    Gd = tu.demote_in_edges(G, 1, {2, 1})
    Wd = nx.to_numpy_array(Gd)
    assert Wd[1, 1] == pytest.approx(nx.to_numpy_array(G)[:, 1].sum())
    # dropping EVERY in-edge of a rank with no self-weight must raise,
    # not silently zero the column
    W = np.array([[0.0, 1.0], [1.0, 0.0]])
    G2 = nx.from_numpy_array(W, create_using=nx.DiGraph)
    with pytest.raises(ValueError, match="renormalize"):
        tu.demote_in_edges(G2, 1, {0})
