"""Pallas flash-attention kernel tests.

CPU coverage runs the kernel in interpret mode (pallas has no CPU lowering);
the TPU test compiles the REAL kernel — this is the path that caught the
missing vma declaration on pallas_call out_shape, which interpret mode
masks entirely (the kernel 'worked' on CPU while failing to lower on
hardware).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from bluefog_tpu.parallel import ring_attention
from bluefog_tpu.parallel.context import reference_attention
from bluefog_tpu.parallel.flash import flash_attention


def _qkv(B=1, S=256, H=2, D=128, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(3), 3)
    return (jax.random.normal(k1, (B, S, H, D), dtype),
            jax.random.normal(k2, (B, S, H, D), dtype),
            jax.random.normal(k3, (B, S, H, D), dtype))


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_dense_interpret(causal):
    q, k, v = _qkv()
    got = flash_attention(q, k, v, causal=causal, interpret=True)
    want = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-3, rtol=2e-3)


def test_ring_attention_flash_path_interpret(bf8):
    """The flash kernel inside the sharded ring exchange (8-way CPU mesh)."""
    import bluefog_tpu as bf

    q, k, v = _qkv(S=512)
    mesh = bf.mesh()
    got = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True,
                         interpret=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-3, rtol=2e-3)


def _tpu_devices():
    try:
        return jax.devices("tpu")
    except RuntimeError:
        return []


@pytest.mark.slow
@pytest.mark.skipif(not _tpu_devices(), reason="no TPU available")
def test_flash_compiles_on_real_tpu():
    """Compile + execute the real kernel (no interpret) on the TPU chip,
    inside a 1-device shard_map ring — the vma-carrying path."""
    dev = _tpu_devices()[0]
    mesh = Mesh(np.array([dev]), ("rank",))
    q, k, v = _qkv(S=512, dtype=jnp.bfloat16)
    got = ring_attention(q, k, v, mesh=mesh, causal=True, use_flash=True)
    want = reference_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=3e-2, rtol=3e-2)


@pytest.mark.slow  # kernel-vs-dense VJP kept in the full suite
def test_flash_gradient_matches_dense():
    """flash_attention differentiates: grads match the dense oracle (the
    backward is the VJP of the checkpointed blockwise twin)."""
    B, S, H, D = 1, 64, 2, 8
    keys = jax.random.split(jax.random.PRNGKey(5), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in keys)

    def flash_loss(q, k, v):
        out = flash_attention(q, k, v, causal=True, interpret=True)
        return jnp.sum(out * jnp.cos(out))

    def dense_loss(q, k, v):
        out = reference_attention(q, k, v, causal=True)
        return jnp.sum(out * jnp.cos(out))

    gf = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-5,
            err_msg=f"grad mismatch for {name}")


def test_blockwise_twin_matches_kernel_values():
    from bluefog_tpu.parallel.flash import _blockwise_attention

    B, S, H, D = 2, 32, 2, 8
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, D), jnp.float32) for kk in keys)
    a = flash_attention(q, k, v, causal=True, interpret=True)
    b = _blockwise_attention(q, k, v, causal=True, tk=8)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_ring_flash_gradients_match_einsum_ring(bf8):
    """The flash ring differentiates: its custom VJP (the einsum-ring twin)
    yields the same gradients as differentiating the einsum ring directly."""
    import bluefog_tpu as bf

    q, k, v = _qkv(S=64, D=8)
    mesh = bf.mesh()

    def loss(use_flash):
        def f(q, k, v):
            out = ring_attention(q, k, v, mesh=mesh, causal=True,
                                 use_flash=use_flash, interpret=use_flash)
            return jnp.sum(out * jnp.sin(out))
        return f

    gf = jax.grad(loss(True), argnums=(0, 1, 2))(q, k, v)
    ge = jax.grad(loss(False), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, ge, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5,
                                   err_msg=f"ring grad mismatch for {name}")
