"""Window / one-sided gossip tests (model: test/torch_win_ops_test.py)."""

import numpy as np
import jax.numpy as jnp
import pytest

from bluefog_tpu import topology_util


def rank_tensor(n=8, shape=(4,)):
    base = jnp.arange(n, dtype=jnp.float32).reshape((n,) + (1,) * len(shape))
    return jnp.broadcast_to(base, (n,) + shape)


class TestWinLifecycle:
    def test_create_free(self, bf8):
        assert bf8.win_create(rank_tensor(), "w1")
        assert not bf8.win_create(rank_tensor(), "w1")  # duplicate rejected
        assert bf8.win_free("w1")
        assert not bf8.win_free("w1")

    def test_free_all(self, bf8):
        bf8.win_create(rank_tensor(), "a")
        bf8.win_create(rank_tensor(), "b")
        assert bf8.win_free()
        assert bf8.win_create(rank_tensor(), "a")

    def test_update_unknown_window(self, bf8):
        with pytest.raises(ValueError, match="does not exist"):
            bf8.win_update("nope")


class TestWinUpdate:
    def test_update_initial_is_neighbor_avg(self, bf8):
        # buffers initialize to local tensor value (zero_init=False), so the
        # first win_update without any put returns the original tensor
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w")
        out = bf8.win_update("w")
        np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-6)

    def test_put_then_update_neighbor_avg(self, bf8):
        # parity: torch_win_ops_test.py win_put tests — after every rank
        # puts, win_update gives the uniform neighbor average
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w")
        assert bf8.win_put(x, "w")
        out = bf8.win_update("w")
        for r in range(8):
            exp = (r + (r - 1) % 8 + (r + 1) % 8) / 3.0
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_zero_init(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w", zero_init=True)
        out = bf8.win_update("w")  # neighbors contribute zeros
        for r in range(8):
            np.testing.assert_allclose(np.asarray(out[r]), r / 3.0, atol=1e-5)

    def test_partial_put_weights(self, bf8):
        # put only to the right neighbor with weight 2.0
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w", zero_init=True)
        bf8.win_put(x, "w", dst_weights={r: {(r + 1) % 8: 2.0} for r in range(8)})
        out = bf8.win_update("w", self_weight=0.5,
                             neighbor_weights={r: {(r - 1) % 8: 0.25}
                                               for r in range(8)})
        for r in range(8):
            exp = 0.5 * r + 0.25 * 2.0 * ((r - 1) % 8)
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_update_clone_leaves_window(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        bf8.win_create(rank_tensor(), "w", zero_init=True)
        out1 = bf8.win_update("w", clone=True)
        out2 = bf8.win_update("w", clone=True)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2))

    def test_update_then_collect(self, bf8):
        # sums self + all buffers, then resets buffers
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w", zero_init=True)
        bf8.win_put(x, "w")
        out = bf8.win_update_then_collect("w")
        for r in range(8):
            exp = r + (r - 1) % 8 + (r + 1) % 8
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)
        # buffers were reset: a second collect returns just the stored value
        out2 = bf8.win_update_then_collect("w")
        np.testing.assert_allclose(np.asarray(out2), np.asarray(out), atol=1e-5)


class TestWinAccumulate:
    def test_accumulate_sums(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w", zero_init=True)
        bf8.win_accumulate(x, "w")
        bf8.win_accumulate(x, "w")
        out = bf8.win_update("w", self_weight=0.0,
                             neighbor_weights={r: {s: 1.0 for s in
                                                   bf8.in_neighbor_ranks(r)}
                                               for r in range(8)})
        for r in range(8):
            exp = 2.0 * ((r - 1) % 8 + (r + 1) % 8)
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_self_weight_scaling(self, bf8):
        # push-sum style: self down-weight after the send
        bf8.set_topology(topology_util.RingGraph(8, connect_style=2))
        x = jnp.ones((8, 2))
        bf8.win_create(x, "w", zero_init=True)
        bf8.win_accumulate(x, "w", self_weight=0.5,
                           dst_weights={r: {(r + 1) % 8: 0.5} for r in range(8)})
        out = bf8.win_update_then_collect("w")
        # everyone had 1, kept .5, received .5 -> total restored to 1
        np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-6)


class TestWinGet:
    def test_get_pulls_current_values(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w", zero_init=True)
        assert bf8.win_get("w")
        out = bf8.win_update("w")
        for r in range(8):
            exp = (r + (r - 1) % 8 + (r + 1) % 8) / 3.0
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)

    def test_get_src_weights(self, bf8):
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w", zero_init=True)
        bf8.win_get("w", src_weights={r: {(r - 1) % 8: 2.0} for r in range(8)})
        out = bf8.win_update("w", self_weight=1.0,
                             neighbor_weights={r: {(r - 1) % 8: 1.0}
                                               for r in range(8)})
        for r in range(8):
            exp = r + 2.0 * ((r - 1) % 8)
            np.testing.assert_allclose(np.asarray(out[r]), exp, atol=1e-5)


class TestWinVersions:
    def test_version_counting(self, bf8):
        # parity: torch_win_ops_test.py:268,557 version counter checks
        bf8.set_topology(topology_util.RingGraph(8))
        x = rank_tensor()
        bf8.win_create(x, "w")
        assert bf8.get_win_version("w", rank=0) == {1: 0, 7: 0}
        bf8.win_put(x, "w")
        assert bf8.get_win_version("w", rank=0) == {1: 1, 7: 1}
        bf8.win_put(x, "w")
        assert bf8.get_win_version("w", rank=0) == {1: 2, 7: 2}
        bf8.win_update("w")
        assert bf8.get_win_version("w", rank=0) == {1: 0, 7: 0}


class TestWinMutex:
    def test_mutex_context(self, bf8):
        bf8.win_create(rank_tensor(), "w")
        with bf8.win_mutex("w"):
            pass
        with bf8.win_mutex("w", for_self=True):
            pass
        with bf8.win_mutex("w", ranks=[2, 5]):
            pass

    def test_win_lock(self, bf8):
        bf8.win_create(rank_tensor(), "w")
        with bf8.win_lock("w"):
            pass
        with pytest.raises(ValueError):
            with bf8.win_lock("nope"):
                pass

    def test_mutex_blocks_concurrent_update(self, bf8):
        import threading

        bf8.win_create(rank_tensor(), "w")
        order = []

        def holder():
            with bf8.win_mutex("w", ranks=list(range(8))):
                order.append("acquired")
                ev.wait(timeout=5)

        ev = threading.Event()
        t = threading.Thread(target=holder)
        t.start()
        while not order:
            pass
        # update with require_mutex must wait until the holder releases
        done = []

        def updater():
            bf8.win_update("w", require_mutex=True)
            done.append(True)

        t2 = threading.Thread(target=updater)
        t2.start()
        t2.join(timeout=0.3)
        assert not done, "win_update should be blocked by held mutexes"
        ev.set()
        t2.join(timeout=5)
        t.join(timeout=5)
        assert done


class TestPushSum:
    def test_associated_p_invariant(self, bf8):
        # parity: torch_win_ops_test.py:762-845 push-sum invariants —
        # sum of p stays n, and x/p converges to the true average.
        bf8.set_topology(topology_util.ExponentialTwoGraph(8))
        bf8.turn_on_win_ops_with_associated_p()
        try:
            x = rank_tensor()
            bf8.win_create(x, "ps", zero_init=True)
            rng = np.random.RandomState(0)
            cur = x
            for it in range(50):
                # each rank picks one out-neighbor: send half mass there
                dst_w = {}
                for r in range(8):
                    outs = bf8.out_neighbor_ranks(r)
                    dst_w[r] = {outs[it % len(outs)]: 0.5}
                bf8.win_accumulate(cur, "ps", self_weight=0.5,
                                   dst_weights=dst_w, require_mutex=True)
                cur = bf8.win_update_then_collect("ps")
            p = bf8.win_associated_p_all("ps")
            np.testing.assert_allclose(p.sum(), 8.0, atol=1e-6)
            ratio = np.asarray(cur)[:, 0] / p
            np.testing.assert_allclose(ratio, 3.5, atol=1e-2)
        finally:
            bf8.turn_off_win_ops_with_associated_p()


def test_win_put_integer_window_fractional_weights(bf8):
    # Regression: fractional edge weights on an integer window must not
    # truncate in the mailbox (mail stores f32; cast happens at win_update).
    import jax.numpy as jnp
    import numpy as np
    x = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32)[:, None] * 8, (8, 4))
    assert bf8.win_create(x, "int_win")
    bf8.win_put(x, "int_win", dst_weights={r: 0.5 for r in range(8)})
    out = bf8.win_update("int_win", self_weight=0.0,
                         neighbor_weights={r: {s: 1.0 for s in
                             bf8.in_neighbor_ranks(r)} for r in range(8)})
    got = np.asarray(out)
    # rank r receives 0.5 * x[src] summed over its in-neighbors
    for r in range(8):
        srcs = bf8.in_neighbor_ranks(r)
        expect = sum(0.5 * s * 8 for s in srcs)
        np.testing.assert_allclose(got[r], int(expect) * np.ones(4), atol=1)
    assert out.dtype == jnp.int32
    bf8.win_free("int_win")
