"""Non-power-of-2 world sizes: n ∈ {3, 5, 6, 7, 12} (VERDICT r4 #2).

Every other suite runs at n = 8 (and one file at 4), so the circulant shift
decomposition, the expo graphs' ``_is_power_of`` row patterns, the dynamic
iterators' modular arithmetic, hierarchical machine splits, and the window
mailbox ``d_max`` layouts were never exercised off the power-of-2 lattice.
The reference ran its whole suite at arbitrary ``np`` (its CI used np=2 and
np=4, reference Makefile:1); a silent wrong-neighbor bug at odd n would have
passed our suite while failing the reference's. This file is the sweep that
closes that hole: every static graph's neighbor average is checked against
the independently computed ``W.T @ x`` oracle, the dynamic iterators against
a global send/recv consistency audit, and windows against ragged in-degrees
(star: center d=n-1, leaves d=1).
"""

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu import topology as topology_util

from conftest import cpu_devices

SIZES = [3, 5, 6, 7, 12]

GRAPHS = {
    "expo2": topology_util.ExponentialTwoGraph,
    "expo3": lambda n: topology_util.ExponentialGraph(n, base=3),
    "symexpo": topology_util.SymmetricExponentialGraph,
    "mesh2d": topology_util.MeshGrid2DGraph,
    "star": topology_util.StarGraph,
    "ring": topology_util.RingGraph,
    "full": topology_util.FullyConnectedGraph,
}


@pytest.fixture(params=SIZES)
def bfn(request):
    n = request.param
    bf.init(devices=cpu_devices(n))
    yield bf, n
    bf.shutdown()


def rank_x(n, width=3):
    # distinct per-rank values, not symmetric around anything
    return np.arange(n, dtype=np.float32)[:, None] * np.ones(
        (1, width), np.float32) + 0.25


@pytest.mark.parametrize("gname", sorted(GRAPHS))
def test_static_graph_neighbor_allreduce_exact(bfn, gname):
    """All 7 graph families at every odd/composite n: the compiled circulant
    plan must reproduce W.T @ x exactly (weighted topology path)."""
    b, n = bfn
    b.set_topology(GRAPHS[gname](n), is_weighted=True)
    W = topology_util.weight_matrix(b.load_topology())
    # sanity on the family itself: weights into each rank sum to 1
    np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=1e-12)
    x = rank_x(n)
    out = np.asarray(b.neighbor_allreduce(x))
    np.testing.assert_allclose(out, W.T @ x, atol=1e-5)


@pytest.mark.parametrize("gname", sorted(GRAPHS))
def test_static_graph_uniform_weights_exact(bfn, gname):
    """The unweighted path (uniform 1/(d+1) averaging) at odd n."""
    b, n = bfn
    b.set_topology(GRAPHS[gname](n), is_weighted=False)
    topo = b.load_topology()
    x = rank_x(n)
    out = np.asarray(b.neighbor_allreduce(x))
    for r in range(n):
        nbrs = topology_util.in_neighbor_ranks(topo, r)
        want = (x[r] + sum(x[s] for s in nbrs)) / (len(nbrs) + 1)
        np.testing.assert_allclose(out[r], want, atol=1e-5)


def test_dynamic_one_peer_exact(bfn):
    """GetDynamicSendRecvRanks at odd n: per-step send/recv consistency
    across ALL ranks plus exact neighbor_allreduce values each step."""
    b, n = bfn[0], bfn[1]
    topo = topology_util.ExponentialTwoGraph(n)
    gens = [topology_util.GetDynamicSendRecvRanks(topo, r) for r in range(n)]
    x = rank_x(n)
    for _ in range(2 * n + 1):  # cover the full schedule cycle at odd n
        steps = [next(g) for g in gens]
        sends = {r: steps[r][0] for r in range(n)}
        recvs = {r: steps[r][1] for r in range(n)}
        # global consistency audit: r sends to s <=> s receives from r
        for r in range(n):
            for s in sends[r]:
                assert r in recvs[s], (r, s, sends, recvs)
            for s in recvs[r]:
                assert r in sends[s], (r, s, sends, recvs)
        nw = {r: {src: 0.5 for src in recvs[r]} for r in range(n)}
        sw = {r: 1.0 - 0.5 * len(recvs[r]) for r in range(n)}
        got = np.asarray(b.neighbor_allreduce(
            x, self_weight=sw, neighbor_weights=nw, send_neighbors=sends,
            enable_topo_check=False))
        want = np.stack([
            sw[r] * x[r] + sum(0.5 * x[s] for s in recvs[r])
            for r in range(n)])
        np.testing.assert_allclose(got, want, atol=1e-5)
        x = want


@pytest.mark.parametrize("world,local", [(6, 3), (12, 3), (12, 4)])
def test_inner_outer_iterators_consistency(world, local):
    """The machine-granularity iterators at non-power-of-2 local/machine
    counts: the log2(local_size-2) / log2(num_machines-1) arithmetic
    (topology.py:433-434) must still yield a globally consistent one-peer
    schedule — every send has a matching recv at every step."""
    iters = {
        "ring": [topology_util.GetInnerOuterRingDynamicSendRecvRanks(
            world, local, r) for r in range(world)],
        "expo2": [topology_util.GetInnerOuterExpo2DynamicSendRecvRanks(
            world, local, r) for r in range(world)],
    }
    for name, gens in iters.items():
        for step in range(3 * local * max(1, world // local)):
            steps = [next(g) for g in gens]
            for r in range(world):
                (send,), (recv,) = steps[r]
                assert 0 <= send < world and send != r, (name, step, r, send)
                assert steps[send][1][0] == r, (
                    f"{name} step {step}: {r} sends to {send}, but {send} "
                    f"expects recv from {steps[send][1][0]}")
                assert steps[recv][0][0] == r, (
                    f"{name} step {step}: {r} recvs from {recv}, but {recv} "
                    f"sends to {steps[recv][0][0]}")


@pytest.mark.parametrize("world,local", [(6, 3), (12, 3)])
def test_exp2_machine_iterator_consistency(world, local):
    """GetExp2DynamicSendRecvMachineRanks at 2 and 4 machines with odd
    local_size: machine send/recv pairing is mutual every step."""
    num_machines = world // local
    gens = {}
    for m in range(num_machines):
        r = m * local  # local_rank 0 on each machine
        gens[m] = topology_util.GetExp2DynamicSendRecvMachineRanks(
            world, local, r, 0)
    for step in range(2 * num_machines + 1):
        steps = {m: next(g) for m, g in gens.items()}
        for m in range(num_machines):
            (send,), (recv,) = steps[m]
            assert steps[send][1][0] == m, (step, m, send, steps)
            assert steps[recv][0][0] == m, (step, m, recv, steps)


def test_hierarchical_local_size_3():
    """Hierarchical neighbor allreduce with 2 machines x 3 ranks: local
    averaging then cross-machine combine, exact values."""
    bf.init(devices=cpu_devices(6), local_size=3)
    try:
        x = rank_x(6)
        out = np.asarray(bf.hierarchical_neighbor_allreduce(x))
        m0, m1 = x[:3].mean(axis=0), x[3:].mean(axis=0)
        want = (m0 + m1) / 2.0
        np.testing.assert_allclose(out, np.tile(want, (6, 1)), atol=1e-5)
    finally:
        bf.shutdown()


def test_window_ragged_in_degrees(bfn):
    """Star windows at odd n: the center's mailbox uses d_max = n-1 slots,
    leaves use 1 of d_max — put + update must still be exact."""
    b, n = bfn
    b.set_topology(topology_util.StarGraph(n))
    topo = b.load_topology()
    x = rank_x(n, width=2)
    assert b.win_create(x, "odd.star", zero_init=True)
    try:
        b.win_put(x, "odd.star")
        out = np.asarray(b.win_update("odd.star"))
        for r in range(n):
            nbrs = topology_util.in_neighbor_ranks(topo, r)
            want = (x[r] + sum(x[s] for s in nbrs)) / (len(nbrs) + 1)
            np.testing.assert_allclose(out[r], want, atol=1e-5)
    finally:
        b.win_free("odd.star")


def test_window_dynamic_partial_destinations(bfn):
    """Partial-destination puts at odd n over expo2: only the chosen edge
    set lands, with per-edge weights."""
    b, n = bfn
    b.set_topology(topology_util.ExponentialTwoGraph(n))
    topo = b.load_topology()
    x = rank_x(n, width=2)
    assert b.win_create(x, "odd.dyn", zero_init=True)
    try:
        # each rank puts only to its FIRST out-neighbor, weight 2.0
        dsts = {r: {topology_util.out_neighbor_ranks(topo, r)[0]: 2.0}
                for r in range(n)}
        b.win_put(x, "odd.dyn", dst_weights=dsts)
        out = np.asarray(b.win_update("odd.dyn"))
        for r in range(n):
            nbrs = topology_util.in_neighbor_ranks(topo, r)
            contrib = {s: (2.0 * x[s] if dsts[s].get(r) else 0.0 * x[s])
                       for s in nbrs}
            want = (x[r] + sum(contrib.values())) / (len(nbrs) + 1)
            np.testing.assert_allclose(out[r], want, atol=1e-5)
    finally:
        b.win_free("odd.dyn")


def test_allreduce_allgather_odd_sizes(bfn):
    """The global collectives are size-agnostic too (sanity at odd n)."""
    b, n = bfn
    x = rank_x(n)
    np.testing.assert_allclose(
        np.asarray(b.allreduce(x, average=True)),
        np.tile(x.mean(axis=0), (n, 1)), atol=1e-5)
    gathered = np.asarray(b.allgather(x))
    # rank-stacked view: every rank's row carries the full gathered concat
    assert gathered.shape == (n, n * 3)
    np.testing.assert_allclose(gathered[0], x.reshape(-1), atol=1e-6)
