"""Tensor parallelism: exactness vs the single-device oracle, real sharding.

The TP layout is a GSPMD hint — correctness must never depend on it. These
tests assert (a) TP logits match a plain single-device apply, (b) weights
are ACTUALLY distributed per the Megatron rules, (c) gradients inherit the
param shardings, (d) indivisible dims fall back to replicated and stay
correct.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bluefog_tpu import parallel as bfp
from bluefog_tpu.models import TransformerLM

from conftest import cpu_devices


def make_lm(heads=4, d_model=32, d_ff=64, vocab=64, layers=2):
    model = TransformerLM(vocab_size=vocab, num_layers=layers,
                          num_heads=heads, d_model=d_model, d_ff=d_ff)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, vocab)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    return model, params, tokens


@pytest.mark.slow  # exactness kept in the full suite
def test_tp_matches_single_device():
    model, params, tokens = make_lm()
    oracle = model.apply({"params": params}, tokens)

    mesh = bfp.tp_mesh(2, 4, cpu_devices(8))
    tp_params = bfp.tp_shard_params(params, mesh)
    out = bfp.tp_apply(model, tp_params, tokens, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), atol=1e-4)


def test_tp_params_actually_sharded():
    model, params, tokens = make_lm()
    mesh = bfp.tp_mesh(2, 4, cpu_devices(8))
    tp_params = bfp.tp_shard_params(params, mesh)

    qkv = tp_params["block_0"]["qkv"]["kernel"]     # column-parallel
    down = tp_params["block_0"]["down"]["kernel"]   # row-parallel
    norm = tp_params["final_norm"]["scale"]         # replicated
    # 4-way model sharding: each device holds a 1/4 slice
    assert {s.data.shape for s in qkv.addressable_shards} == \
        {(qkv.shape[0], qkv.shape[1] // 4)}
    assert {s.data.shape for s in down.addressable_shards} == \
        {(down.shape[0] // 4, down.shape[1])}
    assert all(s.data.shape == norm.shape for s in norm.addressable_shards)


def test_tp_grads_inherit_shardings():
    model, params, tokens = make_lm()
    mesh = bfp.tp_mesh(2, 4, cpu_devices(8))
    tp_params = bfp.tp_shard_params(params, mesh)
    targets = jnp.roll(tokens, -1, axis=1)
    loss_fn = bfp.tp_loss_fn(model, mesh)
    # pin grads to the param layout (the training-loop pattern: stable
    # layouts step over step); XLA is otherwise free to re-layout outputs
    out_sh = jax.tree_util.tree_map(lambda p: p.sharding, tp_params)
    grads = jax.jit(jax.grad(loss_fn), out_shardings=out_sh)(
        tp_params, (tokens, targets))
    for p_leaf, g_leaf in zip(jax.tree_util.tree_leaves(tp_params),
                              jax.tree_util.tree_leaves(grads)):
        assert g_leaf.sharding.is_equivalent_to(p_leaf.sharding, p_leaf.ndim)
    # and the loss is the oracle's loss
    oracle = loss_fn(params, (tokens, targets))
    got = loss_fn(tp_params, (tokens, targets))
    np.testing.assert_allclose(float(got), float(oracle), atol=1e-5, rtol=1e-5)


def test_tp_indivisible_falls_back_replicated():
    # d_ff=62 is not divisible by the 4-way model axis: up/down kernels
    # must silently replicate, everything else stays sharded and correct.
    model, params, tokens = make_lm(d_ff=62)
    oracle = model.apply({"params": params}, tokens)
    mesh = bfp.tp_mesh(2, 4, cpu_devices(8))
    tp_params = bfp.tp_shard_params(params, mesh)
    up = tp_params["block_0"]["up"]["kernel"]
    assert all(s.data.shape == up.shape for s in up.addressable_shards)
    out = bfp.tp_apply(model, tp_params, tokens, mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(oracle), atol=1e-4)


def test_tp_mesh_validates_device_count():
    with pytest.raises(ValueError, match="devices"):
        bfp.tp_mesh(4, 4, cpu_devices(8))
