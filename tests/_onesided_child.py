"""Child program for the one-sided window-transport tests (2 processes).

Launched twice via ``python -m bluefog_tpu.launcher -np 2 --coordinator ...``,
2 forced CPU devices each: a 2-controller, size-4 job over a ring. Proves the
VERDICT-r2 #1 property: window gossip progresses on one controller while the
other is asleep mid-step — the reference's passive-target one-sidedness
(mpi_controller.cc:953-1034) over the host tensor transport.

Phase A (sleeping target): process 1 sleeps; process 0 completes 5 rounds of
win_put + win_update in bounded time and with exact values. Process 1 then
wakes, drains the deposits, and checks ITS exact values.

Phase B (skewed push-sum): process 0 gossips 30 rounds at full speed while
process 1 crawls through 8 slow rounds; process 0 must finish first (no rate
coupling), and after a final coordinated drain the push-sum invariants hold
globally: sum of numerators == sum of inputs, sum of p == world size.
"""

import time

import numpy as np

import jax

import bluefog_tpu as bf
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane


def owned_rows(arr, owned):
    rows = {}
    for s in arr.addressable_shards:
        rows[s.index[0].start or 0] = np.asarray(s.data)[0]
    return {r: rows[r] for r in owned}


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == 4
    bf.set_topology(bf.topology_util.RingGraph(4))
    assert control_plane.active()
    cl = control_plane.client()

    x_np = (np.arange(4, dtype=np.float32) + 1.0).reshape(4, 1)

    # ---- Phase A: target asleep ----------------------------------------
    assert bf.win_create(x_np, "os.a", zero_init=True)
    win = win_ops._get_window("os.a")
    assert win.hosted, "multi-controller windows must use the hosted plane"
    assert win.owned == ([0, 1] if pid == 0 else [2, 3]), win.owned

    if pid == 1:
        time.sleep(6.0)  # asleep "inside its step"
        # woke up: drain the deposits process 0 made while we slept
        got = owned_rows(bf.win_update("os.a"), [2, 3])
        # ring in-edges: 2 <- {1, 3}, 3 <- {2, 0}; only cross-process
        # sources (1 -> 2, 0 -> 3) deposited; same-process sources slept.
        np.testing.assert_allclose(got[2], (x_np[2] + x_np[1]) / 3.0,
                                   rtol=1e-6)
        np.testing.assert_allclose(got[3], (x_np[3] + x_np[0]) / 3.0,
                                   rtol=1e-6)
    else:
        t0 = time.monotonic()
        for _ in range(5):
            bf.win_put(x_np, "os.a")
        got = owned_rows(bf.win_update("os.a"), [0, 1])
        dt = time.monotonic() - t0
        # the whole gossip ran while the peer slept: bounded time, no
        # dependence on the peer's dispatch
        assert dt < 4.0, f"one-sided gossip took {dt:.1f}s with peer asleep"
        np.testing.assert_allclose(got[0], (x_np[0] + x_np[1]) / 3.0,
                                   rtol=1e-6)
        np.testing.assert_allclose(got[1], (x_np[1] + x_np[0]) / 3.0,
                                   rtol=1e-6)
        print(f"PHASE_A_BOUNDED {dt:.2f}", flush=True)
    bf.barrier()
    bf.win_free("os.a")

    # ---- Phase B: skewed-speed push-sum --------------------------------
    bf.turn_on_win_ops_with_associated_p()
    assert bf.win_create(x_np, "os.ps", zero_init=True)
    topo = bf.load_topology()
    outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
            for r in range(4)}
    sw = {r: 1.0 / (outd[r] + 1) for r in range(4)}
    dw = {r: {d: 1.0 / (outd[r] + 1)
              for d in bf.topology_util.out_neighbor_ranks(topo, r)}
          for r in range(4)}
    owned = [0, 1] if pid == 0 else [2, 3]
    est = {r: float(x_np[r, 0]) for r in owned}

    rounds = 30 if pid == 0 else 8
    for i in range(rounds):
        if pid == 1:
            time.sleep(0.4)  # the deliberately slow controller
        p_all = bf.win_associated_p_all("os.ps")
        numer = np.zeros((4, 1), np.float32)
        for r in owned:
            numer[r, 0] = est[r] * p_all[r]
        bf.win_accumulate(numer, "os.ps", self_weight=sw, dst_weights=dw,
                          require_mutex=True)
        collected = owned_rows(
            bf.win_update_then_collect("os.ps"), owned)
        p_new = bf.win_associated_p_all("os.ps")
        for r in owned:
            est[r] = float(collected[r][0]) / p_new[r]
    if pid == 0:
        # the fast controller must NOT have been rate-limited by the slow
        # one: the slow loop takes >= 8 * 0.4s and we finish well before it
        assert cl.get("os.b.done") == 0, \
            "fast controller finished after the slow one — gossip is coupled"
        print("PHASE_B_UNCOUPLED", flush=True)
    else:
        cl.put("os.b.done", 1)
    bf.barrier()

    # final coordinated drain: all in-flight deposits fold, then the global
    # invariants must hold exactly
    collected = owned_rows(bf.win_update_then_collect("os.ps"), owned)
    part = sum(float(collected[r][0]) for r in owned)
    control_plane.put_float(cl, f"os.b.part.{pid}", part)
    bf.barrier()
    if pid == 0:
        total = sum(control_plane.get_float(cl, f"os.b.part.{i}")
                    for i in range(2))
        p_final = bf.win_associated_p_all("os.ps")
        assert abs(total - 10.0) < 1e-3, f"mass not conserved: {total}"
        assert abs(p_final.sum() - 4.0) < 1e-9, f"p mass: {p_final}"
        print(f"PHASE_B_INVARIANT {total:.4f}", flush=True)
    bf.barrier()
    bf.win_free("os.ps")
    bf.turn_off_win_ops_with_associated_p()
    bf.shutdown()
    print(f"CHILD_OK {pid}", flush=True)


if __name__ == "__main__":
    main()
