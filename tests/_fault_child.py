"""Child program for the peer-crash fault-injection test.

Launched (twice) by tests/test_launcher.py::test_peer_crash_detected via
``bfrun -np 2 --coordinator ...``. Process 1 hard-crashes mid-job
(``os._exit`` — no announce, no atexit, the SIGKILL shape of failure);
process 0 must DETECT the silent death through the heartbeat monitor
(``bf.dead_controllers()``) within the configured timeout instead of
hanging in a collective, then leave without waiting on the corpse.
"""

import os
import time

import numpy as np

import jax

import bluefog_tpu as bf


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == 4, bf.size()

    # both controllers do one real cross-process collective first, proving
    # the job was healthy before the injected fault
    x = bf.shard_rank_stacked(bf.mesh(), np.ones((4, 2), np.float32))
    y = bf.allreduce(x)
    jax.block_until_ready(y)
    print(f"HEALTHY {pid}", flush=True)

    if pid == 1:
        # the fault: die silently — no announce_shutdown, no atexit hooks
        os._exit(17)

    # survivor: poll the failure detector (never an unbounded collective —
    # that would hang on the corpse, which detection exists to avoid)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if bf.dead_controllers() == {1}:
            print("SURVIVOR_DETECTED 1", flush=True)
            break
        assert not bf.shutdown_requested(), \
            "crash must be detected as a DEAD peer, not a coordinated shutdown"
        time.sleep(0.1)
    else:
        print("SURVIVOR_TIMEOUT", flush=True)
        os._exit(3)

    # bounded-wait synchronize (VERDICT-r2 #8): dispatch a collective that
    # can never complete (the peer is dead) in a side thread — some runtimes
    # block in dispatch itself — and require the deadline to fire with the
    # heartbeat's diagnosis instead of hanging forever
    import threading
    result = {}

    def doomed():
        try:
            h = bf.allreduce_nonblocking(x)
            bf.synchronize(h, timeout=5.0)
            result["outcome"] = "completed?!"
        except RuntimeError as e:
            result["outcome"] = "raised"
            result["msg"] = str(e)

    t = threading.Thread(target=doomed, daemon=True)
    t.start()
    t.join(25.0)
    if result.get("outcome") == "raised" and "DEAD" in result.get("msg", "") \
            and "[1]" in result["msg"]:
        print("SURVIVOR_SYNC_RAISED 1", flush=True)
        # skip graceful teardown: jax.distributed barriers would block on
        # the dead peer
        os._exit(0)
    print(f"SURVIVOR_SYNC_BAD {result}", flush=True)
    os._exit(4)


if __name__ == "__main__":
    main()
