"""Stall watchdog: unfinished handles are detected and warned about once.

Analog of the reference's CheckForStalledTensors (operations.cc:387-432):
a handle whose device work never completes must produce a warning naming
the op, exactly once per handle, and clear from the outstanding set when
it finishes.
"""

import logging
import time

import pytest

from bluefog_tpu.runtime import handles
from bluefog_tpu.runtime.logging import logger
from bluefog_tpu.runtime.watchdog import StallWatchdog


class _NeverReady:
    """Stands in for a device array whose future never resolves."""

    def is_ready(self):
        return False


class _Ready:
    def is_ready(self):
        return True


@pytest.fixture(autouse=True)
def _clean_handles():
    handles.clear()
    yield
    handles.clear()


def test_outstanding_tracks_only_unfinished():
    h1 = handles.allocate("op.stuck", _NeverReady())
    h2 = handles.allocate("op.done", _Ready())
    out = handles.outstanding()
    assert h1 in out and h2 not in out
    name, age = out[h1]
    assert name == "op.stuck" and age >= 0.0


class _Capture(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.records = []

    def emit(self, record):
        self.records.append(record)


def test_watchdog_warns_once_per_stalled_handle():
    # the package logger sets propagate=False, so capture with our own
    # handler rather than caplog
    cap = _Capture()
    logger.addHandler(cap)
    h = handles.allocate("op.hung", _NeverReady())
    wd = StallWatchdog(warning_sec=0.05, cycle_ms=1.0)  # poll floor is 1s
    try:
        wd.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not any(
                "op.hung" in r.getMessage() for r in cap.records):
            time.sleep(0.1)
        warns = [r for r in cap.records if "op.hung" in r.getMessage()]
        assert len(warns) == 1, f"expected one warning, got {len(warns)}"
        # further cycles must NOT re-warn the same handle
        time.sleep(2.2)
        warns = [r for r in cap.records if "op.hung" in r.getMessage()]
        assert len(warns) == 1
    finally:
        wd.stop()
        logger.removeHandler(cap)
    handles.synchronize(h)  # cleanup (plain object: block_until_ready no-op)


def test_warned_set_pruned_after_completion():
    """ISSUE r8 satellite: the once-warned set must not grow for the life
    of the job — entries for handles that completed (or were swept) are
    pruned, and a handle that re-enters the outstanding set after
    progressing warns again."""
    cap = _Capture()
    logger.addHandler(cap)
    never = _NeverReady()
    h = handles.allocate("op.leaky", never)
    wd = StallWatchdog(warning_sec=0.05, cycle_ms=1.0)
    try:
        wd.start()
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and h not in wd._warned:
            time.sleep(0.1)
        assert h in wd._warned
        # completing the op must eventually prune its warned entry
        never.is_ready = lambda: True
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and h in wd._warned:
            time.sleep(0.1)
        assert h not in wd._warned, "completed handle leaked in _warned"
        # a fresh stall of a RE-REGISTERED handle id warns again: simulate
        # the timed-out-synchronize path by re-allocating stalled work
        h2 = handles.allocate("op.leaky2", _NeverReady())
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not any(
                "op.leaky2" in r.getMessage() for r in cap.records):
            time.sleep(0.1)
        assert any("op.leaky2" in r.getMessage() for r in cap.records)
        handles.synchronize(h2)
    finally:
        wd.stop()
        logger.removeHandler(cap)
    handles.synchronize(h)


def test_stall_triggers_flight_dump(tmp_path, monkeypatch):
    """ISSUE r12: a watchdog-detected stall leaves a flight-recorder dump
    behind — the wedge may never raise a Python exception to dump on, so
    the watchdog is the trigger of last resort."""
    import json

    from bluefog_tpu.runtime import flight as flight_mod

    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("BLUEFOG_FLIGHT_MIN_INTERVAL", "0")
    flight_mod.reset_for_job()
    h = handles.allocate("op.wedged", _NeverReady())
    wd = StallWatchdog(warning_sec=0.05, cycle_ms=1.0)
    try:
        wd.start()
        dump_path = tmp_path / "bf_flight_0.json"
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not dump_path.exists():
            time.sleep(0.1)
        assert dump_path.exists(), "stall produced no flight dump"
        doc = json.loads(dump_path.read_text())
        assert doc["meta"]["reason"] == "watchdog-stall"
        names = doc["names"]
        instants = [names[n] for k, n in zip(doc["events"]["kind"],
                                             doc["events"]["name"])
                    if k == flight_mod.INSTANT]
        assert "fatal.watchdog.stall" in instants
    finally:
        wd.stop()
        flight_mod.reset_for_job()
    handles.synchronize(h)


def test_poll_and_synchronize_contract():
    h = handles.allocate("op.x", _Ready())
    assert handles.poll(h) is True
    handles.synchronize(h)
    with pytest.raises(ValueError):
        handles.poll(h)
    with pytest.raises(ValueError):
        handles.synchronize(h)
