"""Serving plane: snapshot wire format, the version fence's never-torn
property (SIGKILL mid-publish included), hot-swap, and admission control.

The fence contract under test (docs/serving.md): ``bf.serve.ver`` moves
ONLY after every shard of that version is on the wire, so a reader that
pulls the fence and then the fence's keys can never stitch two versions
together — a publisher killed between shard writes leaves the fence at
the last complete snapshot. The chaos publisher child
(``_serve_pub_child.py``) makes torn reads DETECTABLE: every element of
version v equals float(v), so any mix of versions fails an equality
check.
"""

import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

from bluefog_tpu.ops import codec as codec_mod
from bluefog_tpu.runtime import native
from bluefog_tpu.serving import snapshot as snap
from bluefog_tpu.serving.client import RequestShed, ServeClient

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable (no g++?)")

TESTS = Path(__file__).resolve().parent
PUB_CHILD = TESTS / "_serve_pub_child.py"


class FakeKV:
    """In-memory stand-in for the scalar+bytes KV surface the snapshot
    protocol uses (wire-free unit tests)."""

    def __init__(self):
        self.b = {}
        self.s = {}

    def put_bytes(self, k, v):
        self.b[k] = bytes(v)

    def get_bytes(self, k):
        return self.b.get(k, b"")

    def bytes_len(self, k):
        return len(self.b.get(k, b""))

    def put_bytes_many(self, ks, vs):
        for k, v in zip(ks, vs):
            self.put_bytes(k, v)

    def get_bytes_many(self, ks):
        return [self.get_bytes(k) for k in ks]

    def put(self, k, v):
        self.s[k] = int(v)

    def get(self, k):
        return self.s.get(k, 0)

    def put_max(self, k, v):
        self.s[k] = max(self.s.get(k, 0), int(v))
        return self.s[k]

    def fetch_add(self, k, d=1):
        old = self.s.get(k, 0)
        self.s[k] = old + d
        return old


def _leaves(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((17, 33)).astype(np.float32),
            rng.standard_normal((5,)).astype(np.float32),
            (rng.standard_normal((64, 8)) * 3).astype(np.float32)]


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def test_meta_boundaries_cover_and_balance():
    m = snap.SnapshotMeta.for_arrays(_leaves(), 4)
    assert m.boundaries[0] == 0 and m.boundaries[-1] == m.total
    sizes = np.diff(m.boundaries)
    assert sizes.min() >= 0 and sizes.max() - sizes.min() <= 1
    m2 = snap.SnapshotMeta.from_json(m.to_json())
    assert m2.boundaries == m.boundaries and m2.leaves == m.leaves


def test_meta_shards_clamped_to_elements():
    m = snap.SnapshotMeta([((2,), "float32")], 16)
    assert m.shards == 2  # never more pull units than elements


@pytest.mark.parametrize("spec", [None, "int8", "fp8"])
@pytest.mark.parametrize("shards", [1, 3, 5])
def test_shard_roundtrip_codecs(spec, shards):
    codec = codec_mod.state_codec_for(codec_mod.resolve(spec)) \
        if spec else None
    leaves = _leaves(7)
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=shards, codec=codec)
    pub.publish(leaves, 3)
    out, ver, wire = snap.fetch_snapshot(cl)
    assert ver == 3 and len(out) == len(leaves)
    tol = 0.0 if spec is None else (0.05 if spec == "int8" else 0.5)
    for a, b in zip(leaves, out):
        assert b.shape == a.shape
        np.testing.assert_allclose(a, b, atol=tol)
    if spec == "int8":
        raw = sum(a.nbytes for a in leaves)
        assert wire < raw / 3  # the compression the bench pins exactly


def test_decode_rejects_corruption():
    leaves = _leaves(1)
    cl = FakeKV()
    snap.SnapshotPublisher(cl, shards=2).publish(leaves, 1)
    meta = snap.fetch_meta(cl)
    key = snap.SNAP_KEY_FMT.format(ver=1, shard=0)
    good = cl.get_bytes(key)
    with pytest.raises(snap.SnapshotGone):
        snap.decode_shard(b"", meta, 0, 1)          # GC'd slot
    with pytest.raises(ValueError):
        snap.decode_shard(b"\x00" * len(good), meta, 0, 1)  # bad magic
    with pytest.raises(ValueError):
        snap.decode_shard(good, meta, 1, 1)         # wrong shard slot


# ---------------------------------------------------------------------------
# version fence + GC (wire-free)
# ---------------------------------------------------------------------------

def test_versions_are_monotone():
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=2)
    pub.publish(_leaves(), 5)
    with pytest.raises(ValueError):
        pub.publish(_leaves(), 5)
    with pytest.raises(ValueError):
        pub.publish(_leaves(), 4)
    pub.publish(_leaves(), 6)
    assert snap.current_version(cl) == 6


def test_gc_keeps_window_and_moves_floor():
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=2, keep=2)
    for v in (1, 2, 3, 4):
        pub.publish(_leaves(v), v)
    assert cl.get(snap.GC_FLOOR_KEY) == 3
    # retained versions still fetch pinned; GC'd ones raise SnapshotGone
    for v in (3, 4):
        out, got, _ = snap.fetch_snapshot(cl, ver=v)
        assert got == v
    for v in (1, 2):
        with pytest.raises(snap.SnapshotGone):
            snap.fetch_snapshot(cl, ver=v)


def test_partial_publish_invisible_behind_fence():
    """The core never-torn property, deterministically: version 2's
    shards land WITHOUT the fence moving (a publisher dying mid-publish)
    — readers keep resolving the complete version 1."""
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=3)
    one = [np.full(100, 1.0, np.float32)]
    pub.publish(one, 1)
    meta = snap.fetch_meta(cl)
    flat = snap.flatten_leaves([np.full(100, 2.0, np.float32)])
    # two of three shards of version 2 land; the fence write never comes
    for s in (0, 1):
        cl.put_bytes(snap.SNAP_KEY_FMT.format(ver=2, shard=s),
                     snap.encode_shard(flat, meta, s, 2))
    out, ver, _ = snap.fetch_snapshot(cl)
    assert ver == 1
    np.testing.assert_array_equal(out[0], one[0])


def test_fetch_retries_past_gc_race():
    """A reader holding fence v loses the GC race mid-pull: the pull
    returns empty slots, fetch re-reads the fence and succeeds at the
    current version instead of failing."""
    cl = FakeKV()
    pub = snap.SnapshotPublisher(cl, shards=2, keep=2)
    for v in (1, 2, 3):
        pub.publish([np.full(50, float(v), np.float32)], v)
    meta = snap.fetch_meta(cl)
    state = {"first": True}

    def racy_pull(keys):
        if state["first"]:
            state["first"] = False
            return [b""] * len(keys)  # version GC'd under the reader
        return cl.get_bytes_many(keys)

    out, ver, _ = snap.fetch_snapshot(cl, meta=meta, pull=racy_pull)
    assert ver == 3
    np.testing.assert_array_equal(out[0], np.full(50, 3.0, np.float32))


def test_read_serve_status_fields():
    cl = FakeKV()
    assert snap.read_serve_status(cl) is None  # no serving plane ever
    pub = snap.SnapshotPublisher(cl, shards=2)
    pub.publish(_leaves(), 7, step=7)
    st = snap.read_serve_status(cl)
    assert st["version"] == 7 and st["pub_step"] == 7
    assert st["shards"] == 2 and st["publish_lag_s"] < 5.0


# ---------------------------------------------------------------------------
# SIGKILL/churn chaos over a real control plane
# ---------------------------------------------------------------------------

def _fence_values_consistent(cl):
    """Fetch at the committed fence; every element must equal the version
    (how the child makes torn reads detectable)."""
    got = snap.fetch_snapshot(cl)
    if got is None:
        return 0
    out, ver, _ = got
    for leaf in out:
        np.testing.assert_array_equal(
            leaf, np.full(leaf.shape, float(ver), np.float32),
            err_msg=f"TORN READ at committed version {ver}")
    return ver


def test_sigkill_mid_publish_never_torn():
    """Version monotonicity + never-torn reads while the publisher is
    repeatedly SIGKILLed mid-publish (the inter-shard sleep makes the
    kill land between a shard write and the fence move with near
    certainty)."""
    with native.ControlPlaneServer(world=2) as srv:
        cl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        last_fence = 0
        next_ver = 1
        for era in range(4):
            proc = subprocess.Popen(
                [sys.executable, str(PUB_CHILD), "--port", str(srv.port),
                 "--start-ver", str(next_ver), "--shards", "4",
                 "--inter-shard-ms", "15"],
                stdout=subprocess.DEVNULL)
            time.sleep(0.25 + 0.07 * era)  # kill lands mid-publish
            proc.send_signal(signal.SIGKILL)
            proc.wait()
            fence = _fence_values_consistent(cl)
            assert fence >= last_fence, \
                f"fence regressed: {last_fence} -> {fence}"
            last_fence = fence
            next_ver = max(fence + 1, next_ver) + 2  # skip the torn slot
        assert last_fence > 0, "no snapshot ever committed"
        cl.close()


# ---------------------------------------------------------------------------
# serve client: hot-swap + admission control
# ---------------------------------------------------------------------------

def test_client_hot_swaps_on_version_bump(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SERVE_POLL_S", "0.05")
    with native.ControlPlaneServer(world=2) as srv:
        pcl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        pub = snap.SnapshotPublisher(pcl, shards=3)
        pub.publish([np.full(200, 1.0, np.float32)], 1)
        sc = ServeClient([("127.0.0.1", srv.port)],
                         model_fn=lambda params, xs: xs + params[0][0])
        try:
            assert sc.wait_ready(timeout=10), "first snapshot never pulled"
            assert sc.version() == 1
            out = sc.infer(np.zeros(3, np.float32), timeout=10)
            np.testing.assert_array_equal(out, np.ones(3, np.float32))
            pub.publish([np.full(200, 5.0, np.float32)], 2)
            deadline = time.monotonic() + 10
            while sc.version() < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sc.version() == 2, "client never hot-swapped"
            out = sc.infer(np.zeros(3, np.float32), timeout=10)
            np.testing.assert_array_equal(out, np.full(3, 5.0, np.float32))
            st = sc.stats()
            assert st["swaps"] >= 2 and st["requests"] == 2
        finally:
            sc.close()
        pcl.close()


def test_admission_gate_sheds_at_queue_cap(monkeypatch):
    monkeypatch.setenv("BLUEFOG_SERVE_POLL_S", "0.05")
    monkeypatch.setenv("BLUEFOG_SERVE_QUEUE_MAX", "2")
    monkeypatch.setenv("BLUEFOG_SERVE_QUEUE_SOFT", "1")
    monkeypatch.setenv("BLUEFOG_SERVE_BATCH", "1")
    release = threading.Event()

    def slow_model(params, xs):
        release.wait(timeout=30)
        return xs

    with native.ControlPlaneServer(world=2) as srv:
        pcl = native.ControlPlaneClient("127.0.0.1", srv.port, rank=0)
        snap.SnapshotPublisher(pcl, shards=1).publish(
            [np.zeros(10, np.float32)], 1)
        sc = ServeClient([("127.0.0.1", srv.port)], model_fn=slow_model)
        try:
            assert sc.wait_ready(timeout=10)
            futs = []
            shed = 0
            # one request parks in the batcher; two fill the queue; the
            # rest MUST shed (never hang, never grow the queue)
            for _ in range(8):
                try:
                    futs.append(sc.submit(np.zeros(2, np.float32)))
                except RequestShed as exc:
                    assert exc.gate == "queue_full"
                    shed += 1
                time.sleep(0.02)
            assert shed >= 1, "queue overflow never shed"
            assert sc.stats()["shed"] == shed
            release.set()
            for f in futs:
                np.testing.assert_array_equal(
                    f.result(timeout=10), np.zeros(2, np.float32))
        finally:
            release.set()
            sc.close()
        pcl.close()
