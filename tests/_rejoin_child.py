"""Child for the end-to-end quarantined-rejoin test (ISSUE r9).

Two phases against one long-lived control-plane server owned by the test:

* ``first`` (incarnation 0): trains a window optimizer for 3 steps, saves
  an orbax checkpoint, records the resulting parameters, exits.
* ``rejoin`` (BLUEFOG_INCARNATION=1): bf.init attaches with the bumped
  incarnation — the server fences the dead incarnation — and enters
  quarantine; the window optimizer's init runs the state transfer. With no
  live in-neighbor on another controller (world of one), it falls back to
  the newest checkpoint under BLUEFOG_CHECKPOINT_DIR, adopts its step
  counter, and completes quarantine (phase 2 visible in the KV).
"""

import os
import sys

import numpy as np

import jax.numpy as jnp
import optax

import bluefog_tpu as bf


def loss_fn(params, batch):
    return jnp.sum((params["w"] - 3.0) ** 2)


def main() -> int:
    phase, workdir = sys.argv[1], sys.argv[2]
    bf.init()
    assert bf.size() == 8, bf.size()
    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss_fn)
    state = opt.init({"w": jnp.ones((4,), jnp.float32)})
    batch = bf.replicate(jnp.zeros((1,), jnp.float32))

    from bluefog_tpu.runtime import control_plane as cp

    if phase == "first":
        for _ in range(3):
            state, _ = opt.step(state, batch)
        bf.checkpoint.save(os.path.join(workdir, "ck"), state, step=3)
        np.save(os.path.join(workdir, "params.npy"),
                np.asarray(state.params["w"]))
        print("FIRST_OK", flush=True)
    else:
        assert cp.incarnation() == 1, cp.incarnation()
        # opt.init above already ran the quarantined transfer: no remote
        # donor exists (this controller owns every rank), so it restored
        # the newest checkpoint and adopted its step counter.
        assert opt._counter == 3, opt._counter
        want = np.load(os.path.join(workdir, "params.npy"))
        got = np.asarray(state.params["w"])
        np.testing.assert_allclose(got, want, rtol=1e-6)
        cl = cp.client()
        assert cl.get("bf.inc.0") == 1
        assert cl.get("bf.q.0.1") == 2, "quarantine did not complete"
        from bluefog_tpu.runtime.heartbeat import quarantine_pending
        assert not quarantine_pending()
        # the rank trains on: a post-rejoin step must complete normally
        state2, _ = opt.step(state, batch)
        print("REJOIN_OK", flush=True)
    opt.free()
    bf.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
