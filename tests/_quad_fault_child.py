"""Child for the 1-of-4 peer-crash test (VERDICT r3 #4).

Four controllers, two devices each; controller 3 hard-crashes mid-job.
EVERY survivor (not just a designated watcher) must detect the silent
death via its heartbeat monitor, and a doomed collective must raise the
bounded-wait diagnosis naming the corpse instead of hanging — the
2-process `_fault_child` property at the reference CI's np=4 scale.
"""

import os
import time

import numpy as np

import jax

import bluefog_tpu as bf

N = 8


def main() -> None:
    bf.init()
    pid = jax.process_index("cpu")
    assert bf.size() == N, bf.size()

    x = bf.shard_rank_stacked(bf.mesh(), np.ones((N, 2), np.float32))
    y = bf.allreduce(x)
    jax.block_until_ready(y)
    print(f"HEALTHY {pid}", flush=True)

    if pid == 3:
        os._exit(17)  # silent: no announce, no atexit

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if bf.dead_controllers() == {3}:
            print(f"SURVIVOR_DETECTED {pid}", flush=True)
            break
        assert not bf.shutdown_requested(), \
            "crash must be a DEAD peer, not a coordinated shutdown"
        time.sleep(0.1)
    else:
        print(f"SURVIVOR_TIMEOUT {pid}", flush=True)
        os._exit(3)

    import threading
    result = {}

    def doomed():
        try:
            h = bf.allreduce_nonblocking(x)
            bf.synchronize(h, timeout=5.0)
            result["outcome"] = "completed?!"
        except RuntimeError as e:
            result["outcome"] = "raised"
            result["msg"] = str(e)

    t = threading.Thread(target=doomed, daemon=True)
    t.start()
    t.join(25.0)
    if not (result.get("outcome") == "raised"
            and "DEAD" in result.get("msg", "") and "[3]" in result["msg"]):
        print(f"SURVIVOR_SYNC_BAD {pid} {result}", flush=True)
        os._exit(4)
    print(f"SURVIVOR_SYNC_RAISED {pid}", flush=True)
    # Survivor rendezvous over the control plane before exiting: process 0
    # hosts BOTH the jax coordination service and the control-plane server,
    # and its exit makes the coordination client hard-kill any survivor
    # still mid-check ("leader task died"). Wait until all three survivors
    # have finished their assertions, give readers a beat, then leave —
    # skipping graceful teardown, whose barriers would block on the corpse.
    from bluefog_tpu.runtime import control_plane
    cl = control_plane.client()
    cl.put(f"qf.done.{pid}", 1)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if all(cl.get(f"qf.done.{i}") for i in range(3)):
            break
        time.sleep(0.05)
    if pid == 0:
        time.sleep(2.0)  # the server host leaves last
    os._exit(0)


if __name__ == "__main__":
    main()
