"""End-to-end test of the ResNet training-loop example (reference:
examples/pytorch_resnet.py): a short run must learn the synthetic task,
the LR schedule must ramp/decay like the reference's adjust_learning_rate,
and checkpoint/resume must round-trip through an epoch boundary.
"""

import sys
from pathlib import Path

import pytest

import bluefog_tpu as bf

from conftest import cpu_devices

sys.path.insert(0, str(Path(__file__).resolve().parent.parent / "examples"))
import resnet as resnet_example  # noqa: E402


def _args(**over):
    base = dict(
        model="resnet18", epochs=2, batch_size=4, val_batch_size=4,
        base_lr=0.004, warmup_epochs=2, steps_per_epoch=6, classes=4,
        image_size=32, dist_optimizer="neighbor_allreduce",
    )
    base.update(over)
    argv = []
    for k, v in base.items():
        argv += [f"--{k.replace('_', '-')}", str(v)]
    return resnet_example.parse_args(argv)


@pytest.mark.slow
def test_short_training_learns_and_checkpoints(tmp_path):
    args = _args(checkpoint_format=str(tmp_path / "ck-{epoch}"))
    try:
        history, state = resnet_example.train(args, devices=cpu_devices(8))
    finally:
        bf.shutdown()
    accs = [h[1] for h in history]
    # 4 well-separated gaussian classes: even 2 short epochs beat chance.
    # (Accuracy, not loss: with batch 4 the fresh-BN loss is noisy enough
    # that a 2-epoch loss comparison flakes while accuracy climbs.)
    assert accs[-1] > 1.0 / args.classes + 0.05, f"no learning: {accs}"
    assert accs[-1] >= accs[0] - 0.05, f"accuracy regressed: {accs}"
    assert (tmp_path / "ck-2").exists()

    # resume from epoch 2 and continue to epoch 3
    args2 = _args(epochs=3, resume_from=str(tmp_path / "ck-2"),
                  checkpoint_format=str(tmp_path / "ck-{epoch}"))
    try:
        history2, _ = resnet_example.train(args2, devices=cpu_devices(8))
    finally:
        bf.shutdown()
    assert len(history2) == 1  # exactly the remaining epoch ran
    assert (tmp_path / "ck-3").exists()


def test_lr_schedule_matches_reference_shape():
    """Warmup base->size*base over warmup_epochs, /10 at ABSOLUTE epochs
    30/60/80 (reference adjust_learning_rate: the boundaries do not shift
    by the warmup length)."""
    args = _args(base_lr=0.1, warmup_epochs=5, steps_per_epoch=10)
    sched = resnet_example.make_lr_schedule(args, size=8, steps_per_epoch=10)
    assert float(sched(0)) == pytest.approx(0.1, rel=1e-6)
    assert float(sched(50)) == pytest.approx(0.8, rel=1e-6)   # ramped to 8x
    assert float(sched(299)) == pytest.approx(0.8, rel=1e-6)  # epoch 29.9
    assert float(sched(301)) == pytest.approx(0.08, rel=1e-3)   # epoch 30
    assert float(sched(601)) == pytest.approx(0.008, rel=1e-3)  # epoch 60
    assert float(sched(801)) == pytest.approx(0.0008, rel=1e-3)  # epoch 80
