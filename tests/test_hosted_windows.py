"""Hosted (host-tensor-transport) window plane: single-process parity.

The hosted plane is the multi-controller default (one-sided gossip across
controllers; tests/_onesided_child.py proves the asynchrony end-to-end).
These tests force it in a world-1 job (``BLUEFOG_WIN_HOST_PLANE=1``) and pin
its numerics to the compiled collective plane's contracts: put/get/update
values, versions, push-sum invariants, and the window optimizers.
"""

import os
import socket

import numpy as np
import pytest

import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu.ops import windows as win_ops
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import native

from conftest import cpu_devices

pytestmark = pytest.mark.skipif(
    native.load() is None, reason="native runtime unavailable")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture()
def bf_hosted():
    """bf over 8 CPU devices, control plane + forced hosted window plane."""
    env = {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(_free_port()),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(8))
    assert cp.active()
    yield bf
    bf.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    cp.reset_for_test()


def _inject_deposit(cl, key, recs, seq=1):
    """Append one deposit's records the way a remote origin now does: each
    record tag-prefixed server-side (seq << 24 | index) so the drain can
    tell headers from orphaned continuations."""
    recs = list(recs)
    cl.append_bytes_tagged_many([key] * len(recs), recs,
                                win_ops._deposit_tags(seq, len(recs)))


def test_hosted_plane_selected(bf_hosted):
    assert bf.win_create(jnp.ones((8, 2)), "h.sel")
    win = win_ops._get_window("h.sel")
    assert win.hosted and win.owned == list(range(8))
    bf.win_free("h.sel")


def test_put_update_matches_collective_numerics(bf_hosted):
    x = jnp.arange(8.0).reshape(8, 1) + 1.0
    assert bf.win_create(x, "h.num")
    bf.win_put(x, "h.num")
    got = np.asarray(bf.win_update("h.num"))
    topo = bf.load_topology()
    expect = np.zeros((8, 1))
    for r in range(8):
        nbrs = bf.topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        expect[r] = u * (r + 1) + u * sum(s + 1.0 for s in nbrs)
    np.testing.assert_allclose(got, expect, rtol=1e-6)
    bf.win_free("h.num")


def test_versions_bump_and_reset(bf_hosted):
    x = jnp.ones((8, 3))
    assert bf.win_create(x, "h.ver")
    bf.win_put(x, "h.ver")
    bf.win_put(x, "h.ver")
    assert all(v == 2 for v in bf.get_win_version("h.ver", rank=3).values())
    bf.win_update("h.ver")
    for r in range(8):
        assert all(v == 0 for v in bf.get_win_version("h.ver", rank=r).values())
    bf.win_free("h.ver")


def test_get_pulls_published_tensors(bf_hosted):
    x = jnp.arange(8.0).reshape(8, 1) + 1.0
    assert bf.win_create(x, "h.get", zero_init=True)
    bf.win_get("h.get")
    got = np.asarray(bf.win_update("h.get"))
    topo = bf.load_topology()
    for r in range(8):
        nbrs = bf.topology_util.in_neighbor_ranks(topo, r)
        u = 1.0 / (len(nbrs) + 1)
        want = u * (r + 1) + u * sum(s + 1.0 for s in nbrs)
        np.testing.assert_allclose(got[r], want, rtol=1e-6)
    bf.win_free("h.get")


def test_accumulate_stacks_deposits(bf_hosted):
    x = jnp.ones((8, 2))
    assert bf.win_create(x, "h.acc", zero_init=True)
    bf.win_accumulate(x, "h.acc")
    bf.win_accumulate(x, "h.acc")
    got = np.asarray(bf.win_update(
        "h.acc", self_weight=0.0,
        neighbor_weights={r: {s: 1.0 for s in
                              win_ops._get_window("h.acc").in_neighbors[r]}
                          for r in range(8)}))
    topo = bf.load_topology()
    for r in range(8):
        indeg = len(bf.topology_util.in_neighbor_ranks(topo, r))
        np.testing.assert_allclose(got[r], 2.0 * indeg, rtol=1e-6)
    bf.win_free("h.acc")


def test_push_sum_invariant_hosted(bf_hosted):
    bf.turn_on_win_ops_with_associated_p()
    try:
        x = jnp.arange(8.0).reshape(8, 1) + 1.0
        assert bf.win_create(x, "h.ps", zero_init=True)
        topo = bf.load_topology()
        outd = {r: len(bf.topology_util.out_neighbor_ranks(topo, r))
                for r in range(8)}
        sw = {r: 1.0 / (outd[r] + 1) for r in range(8)}
        dw = {r: {d: 1.0 / (outd[r] + 1)
                  for d in bf.topology_util.out_neighbor_ranks(topo, r)}
              for r in range(8)}
        val = x
        for _ in range(5):
            bf.win_accumulate(val, "h.ps", self_weight=sw, dst_weights=dw,
                              require_mutex=True)
            val = bf.win_update_then_collect("h.ps")
            p = bf.win_associated_p_all("h.ps")
            assert abs(float(np.asarray(val).sum()) - 36.0) < 1e-3
            assert abs(p.sum() - 8.0) < 1e-9
        est = np.asarray(val)[:, 0] / p
        assert np.abs(est - 4.5).max() < 2.0
        bf.win_free("h.ps")
    finally:
        bf.turn_off_win_ops_with_associated_p()


def test_win_put_optimizer_over_hosted_plane(bf_hosted):
    """The window-optimizer gossip path (fusion pack -> win ops) runs
    unchanged over the hosted plane and still descends on the quadratic."""
    target = jnp.asarray([1.0, -2.0, 3.0])

    def loss(params, batch):
        return jnp.sum((params["w"] - target) ** 2)

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.05), loss_fn=loss)
    state = opt.init({"w": jnp.zeros(3)})
    batch = jnp.zeros((8, 1))
    l0 = None
    for i in range(20):
        state, m = opt.step(state, batch)
        if i == 0:
            l0 = float(np.asarray(m["loss"]).mean())
    lN = float(np.asarray(m["loss"]).mean())
    assert lN < 0.2 * l0, (l0, lN)
    w = np.asarray(state.params["w"])
    assert np.abs(w - np.asarray(target)[None]).max() < 0.5
    opt.free()


def test_concurrent_accumulates_preserve_mass(bf_hosted):
    """Mutex/state-lock correctness under real concurrency: worker threads
    fire win_accumulate (require_mutex) while the main thread repeatedly
    collects; every deposited unit of mass must end up in exactly one
    place — total collected + final drain == everything deposited."""
    import threading

    n = 8
    x = jnp.ones((n, 2))
    assert bf.win_create(x, "h.stress", zero_init=True)
    topo = bf.load_topology()
    indeg = {r: len(bf.topology_util.in_neighbor_ranks(topo, r))
             for r in range(n)}
    per_op_mass = float(sum(indeg.values()) * 2)  # ones into every edge slot

    ROUNDS = 6
    done = threading.Barrier(3)
    errors = []

    def worker():
        try:
            for _ in range(ROUNDS):
                bf.win_accumulate(x, "h.stress", require_mutex=True)
        except Exception as e:  # noqa: BLE001 - surfaced by the assert below
            errors.append(e)
        finally:
            done.wait(30)

    threads = [threading.Thread(target=worker) for _ in range(2)]
    for t in threads:
        t.start()
    collected = 0.0
    for _ in range(4):
        out = bf.win_update(
            "h.stress", self_weight=0.0,
            neighbor_weights={r: {s: 1.0 for s in
                                  bf.topology_util.in_neighbor_ranks(topo, r)}
                              for r in range(n)},
            reset=True, clone=True, require_mutex=True)
        collected += float(np.asarray(out).sum())
    done.wait(30)
    for t in threads:
        t.join(30)
    assert not errors, errors
    # final drain picks up whatever the last collects missed
    out = bf.win_update(
        "h.stress", self_weight=0.0,
        neighbor_weights={r: {s: 1.0 for s in
                              bf.topology_util.in_neighbor_ranks(topo, r)}
                          for r in range(n)},
        reset=True, clone=True, require_mutex=True)
    collected += float(np.asarray(out).sum())
    np.testing.assert_allclose(collected, 2 * ROUNDS * per_op_mass, rtol=1e-5)
    bf.win_free("h.stress")


def test_win_fence_folds_pending_deposits(bf_hosted):
    """win_fence (torch/mpi_win_ops.cc:714) closes the epoch: a deposit
    sitting in the server mailbox is folded into the owner's buffers at the
    fence, so the next win_update sees it without draining anything new."""
    x = jnp.zeros((8, 2))
    assert bf.win_create(x, "h.fence", zero_init=True)
    win = win_ops._get_window("h.fence")
    # an external origin's deposit: bump-then-append, like _hosted_exchange
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    cl = cp.client()
    cl.fetch_add(f"w.h.fence.v.{dst}.{k}", 1)
    contrib = np.full((2,), 7.0, np.float32)
    import struct as _st
    rec = _st.pack("<BBdI", 1, 0, 0.0, 1) + contrib.tobytes()
    _inject_deposit(cl, f"w.h.fence.dep.{dst}.{k}", [rec])
    assert bf.win_fence("h.fence")
    # deposit is now IN the owner's mailbox row, server box empty
    assert cl.take_bytes(f"w.h.fence.dep.{dst}.{k}") == []
    np.testing.assert_allclose(win._mail_rows[dst][k], contrib)
    # collective plane: fence is a plain barrier, still returns True
    bf.win_free("h.fence")


def test_strict_update_rejects_version0_deposit(bf_hosted, monkeypatch):
    """VERDICT r3 #7: under require_mutex + BLUEFOG_WIN_STRICT, a deposit
    whose version counter is still 0 (an origin that skipped the mutex
    protocol) is an ERROR at drain time, not a silent one-update-late
    consume. Opt-in via env: mixed advisory usage (non-mutex origins
    alongside a mutex-holding updater) is legal per the reference and must
    not crash by default."""
    monkeypatch.setenv("BLUEFOG_WIN_STRICT", "1")
    x = jnp.zeros((8, 2))
    assert bf.win_create(x, "h.strict", zero_init=True)
    win = win_ops._get_window("h.strict")
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    cl = cp.client()
    import struct as _st
    rec = _st.pack("<BBdI", 1, 0, 0.0, 1) + np.ones((2,), np.float32).tobytes()
    # no version bump: the origin "forgot" require_mutex's protocol
    _inject_deposit(cl, f"w.h.strict.dep.{dst}.{k}", [rec], seq=1)
    with pytest.raises(RuntimeError, match="version 0"):
        bf.win_update("h.strict", require_mutex=True)
    # the compliant ordering passes: bump precedes deposit
    cl.fetch_add(f"w.h.strict.v.{dst}.{k}", 1)
    _inject_deposit(cl, f"w.h.strict.dep.{dst}.{k}", [rec], seq=2)
    bf.win_update("h.strict", require_mutex=True)
    bf.win_free("h.strict")


def test_strict_mode_survives_concurrent_put_update(bf_hosted):
    """Hammer require_mutex put/update from two threads: the strict drain
    check must never fire (the mutex protocol really excludes), and no
    value is lost (every accumulate lands exactly once)."""
    import threading

    x = jnp.ones((8, 1))
    assert bf.win_create(x, "h.hammer", zero_init=True)
    errors = []

    def putter():
        try:
            for _ in range(15):
                bf.win_accumulate(x, "h.hammer", require_mutex=True)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    t = threading.Thread(target=putter)
    t.start()
    # Each accumulate stores x back as the window tensor (self_weight=1)
    # and deposits x[src]*1.0 along every out-edge; each collect returns
    # self + all deposits since the last collect and clears the buffers.
    # So sum over collects of (result - x) = one unit of mass per edge per
    # completed accumulate — deposits land exactly once, or the strict
    # check raises.
    deposited = 0.0
    for _ in range(30):
        got = np.asarray(bf.win_update_then_collect("h.hammer"))
        deposited += got.sum() - 8.0
        # collect folded deposits into self; restore the baseline so the
        # next round's accounting stays (result - x)
        win_ops._get_window("h.hammer").self_value = x
    t.join(60.0)
    assert not t.is_alive() and not errors, errors
    final = np.asarray(bf.win_update_then_collect("h.hammer"))
    deposited += final.sum() - 8.0
    topo = bf.load_topology()
    n_edges = sum(len(bf.topology_util.out_neighbor_ranks(topo, r))
                  for r in range(8))
    assert abs(deposited - 15 * n_edges) < 1e-3, (deposited, 15 * n_edges)
    bf.win_free("h.hammer")


# ---------------------------------------------------------------------------
# wire format (r5): dtype-true payloads + chunked deposits
# ---------------------------------------------------------------------------

def test_wire_dtype_rule():
    """Floating windows ship deposits in their OWN dtype (bf16 wire bytes
    halved vs the r4 acc-dtype format); integer windows keep the f32 acc
    dtype so fractional edge weights keep their accumulate semantics."""
    import ml_dtypes

    assert win_ops._win_wire_dtype(np.float32) == np.float32
    assert win_ops._win_wire_dtype(np.float64) == np.float64
    assert win_ops._win_wire_dtype(ml_dtypes.bfloat16) == ml_dtypes.bfloat16
    assert win_ops._win_wire_dtype(np.float16) == np.float16
    assert win_ops._win_wire_dtype(np.int32) == np.float32


def test_pack_deposit_chunking(monkeypatch):
    """_pack_deposit splits payloads at BLUEFOG_MAX_WIN_SENT_LENGTH (the
    reference's chunked-put knob, mpi_controller.cc:41-46) into one header
    record plus raw continuations that reassemble exactly."""
    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    payload = np.arange(50_000, dtype=np.float32)  # 200 KB
    recs = win_ops._pack_deposit(win_ops._DEP_ACC, 1, 2.5, payload)
    assert len(recs) == 5  # header record + ceil(200e3 / 64Ki) chunks
    import struct as _st
    mode, has_p, pc, nchunks = _st.unpack_from("<BBdI", recs[0])
    assert (mode, has_p, pc, nchunks) == (win_ops._DEP_ACC, 1, 2.5, 4)
    # payload chunks are ZERO-COPY views into the source buffer
    assert all(isinstance(c, memoryview) for c in recs[1:])
    assert b"".join(recs[1:]) == payload.tobytes()
    small = win_ops._pack_deposit(win_ops._DEP_PUT, 0, 0.0, b"abc")
    assert len(small) == 2 and bytes(small[1]) == b"abc"


def test_chunked_deposit_drain_reassembles(bf_hosted, monkeypatch):
    """A multi-chunk deposit appended to the server mailbox (as a remote
    origin would) is reassembled by the win_update drain and folded once,
    exactly."""
    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    elems = 40_000  # 160 KB of f32 -> 3 chunks
    x = jnp.zeros((8, elems), jnp.float32)
    assert bf.win_create(x, "h.chunk", zero_init=True)
    win = win_ops._get_window("h.chunk")
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    contrib = np.arange(elems, dtype=np.float32)
    cl = cp.client()
    cl.fetch_add(f"w.h.chunk.v.{dst}.{k}", 1)
    recs = win_ops._pack_deposit(win_ops._DEP_ACC, 0, 0.0, contrib)
    assert len(recs) == 4  # header + 3 chunks
    cl.append_bytes_tagged_many([f"w.h.chunk.dep.{dst}.{k}"] * len(recs),
                                recs, win_ops._deposit_tags(1, len(recs)))
    bf.win_update("h.chunk", self_weight=1.0,
                  neighbor_weights={r: {s: 1.0 for s in win.in_neighbors[r]}
                                    for r in range(8)},
                  reset=True)
    np.testing.assert_allclose(win._mail_rows[dst][k], 0.0)  # reset by update
    # fold happened BEFORE the combine: rank 0's row gained the contribution
    np.testing.assert_allclose(
        np.asarray(win.self_value)[0], contrib, rtol=1e-6)
    bf.win_free("h.chunk")


def test_bf16_deposit_wire_roundtrip(bf_hosted):
    """bf16 windows: a deposit packed in the bf16 wire dtype folds into the
    mailbox with f32 accumulation (the compiled plane's cast discipline)."""
    import ml_dtypes

    x = jnp.ones((8, 4), jnp.bfloat16)
    assert bf.win_create(x, "h.bf16", zero_init=True)
    win = win_ops._get_window("h.bf16")
    assert win_ops._win_wire_dtype(win.mail_dtype) == ml_dtypes.bfloat16
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    contrib = np.asarray([1.5, 2.5, 3.5, 4.5], ml_dtypes.bfloat16)
    cl = cp.client()
    cl.fetch_add(f"w.h.bf16.v.{dst}.{k}", 1)
    recs = win_ops._pack_deposit(win_ops._DEP_PUT, 0, 0.0, contrib)
    # 8 payload bytes on the wire, not 16 (the r4 f32 format)
    assert len(recs) == 2 and memoryview(recs[1]).nbytes == 8
    _inject_deposit(cl, f"w.h.bf16.dep.{dst}.{k}", recs)
    win._drain_deposits()
    np.testing.assert_allclose(
        np.asarray(win._mail_rows[dst][k], np.float32),
        np.asarray(contrib, np.float32))
    bf.win_free("h.bf16")


def test_clear_discards_orphaned_continuation_chunks(bf_hosted, monkeypatch):
    """ADVICE r5 medium: a win_free/win_fence clear that races a
    multi-chunk deposit consumes the deposit's PREFIX; the tail chunks
    land afterwards as orphans. The tagged drain must DISCARD them (by
    sequence id) and still fold the next complete deposit exactly — not
    misparse the tail as a header ("wire corruption" / drain timeout)."""
    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    elems = 40_000  # 160 KB f32 -> header record + 3 continuation chunks
    x = jnp.zeros((8, elems), jnp.float32)
    assert bf.win_create(x, "h.orph", zero_init=True)
    win = win_ops._get_window("h.orph")
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    key = f"w.h.orph.dep.{dst}.{k}"
    cl = cp.client()
    contrib = np.arange(elems, dtype=np.float32)
    recs = win_ops._pack_deposit(win_ops._DEP_ACC, 0, 0.0, contrib)
    assert len(recs) == 4
    # seq-7 deposit: the clear ate records 0-1 (header + first chunk);
    # only the orphaned TAIL is on the key
    tags = win_ops._deposit_tags(7, len(recs))
    cl.append_bytes_tagged_many([key] * 2, recs[2:], tags[2:])
    # seq-8 deposit lands complete afterwards
    _inject_deposit(cl, key, recs, seq=8)
    cl.fetch_add(f"w.h.orph.v.{dst}.{k}", 1)
    bf.win_update("h.orph", self_weight=1.0,
                  neighbor_weights={r: {s: 1.0 for s in win.in_neighbors[r]}
                                    for r in range(8)},
                  reset=True)
    # ONLY the complete deposit folded; the orphan tail vanished silently
    np.testing.assert_allclose(
        np.asarray(win.self_value)[0], contrib, rtol=1e-6)
    bf.win_free("h.orph")


def test_out_of_order_chunk_reassembly(bf_hosted, monkeypatch):
    """r7 striped wire: chunk records of one deposit may arrive in ANY
    order (they fan across the connection pool); the drain places each at
    its tag-index offset and folds the reassembled payload exactly. Only
    the header-before-chunks invariant is guaranteed by senders."""
    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    elems = 40_000  # 160 KB f32 -> header + 3 chunks
    x = jnp.zeros((8, elems), jnp.float32)
    assert bf.win_create(x, "h.ooo", zero_init=True)
    win = win_ops._get_window("h.ooo")
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    key = f"w.h.ooo.dep.{dst}.{k}"
    cl = cp.client()
    contrib = np.arange(elems, dtype=np.float32)
    recs = win_ops._pack_deposit(win_ops._DEP_ACC, 0, 0.0, contrib)
    tags = win_ops._deposit_tags(5, len(recs))
    assert len(recs) == 4
    # header first (the sender invariant), then the chunks REVERSED —
    # the last chunk lands before the drain has seen any full-size one
    order = [0, 3, 2, 1]
    cl.append_bytes_tagged_many([key] * len(order),
                                [recs[i] for i in order],
                                [tags[i] for i in order])
    win._drain_deposits()
    np.testing.assert_allclose(
        np.asarray(win._mail_rows[dst][k]), contrib, rtol=1e-6)
    bf.win_free("h.ooo")


def test_multi_origin_striped_deposit_stress(bf_hosted, monkeypatch):
    """r7 striped transport: TWO origins (each with its own striped
    connection pool) hammer ONE mailbox key with chunked deposits whose
    records fan out-of-order across the pool, concurrently with
    ``win_update`` drains and ``win_fence`` clears from the owner. Every
    deposited unit of mass must fold exactly once — a torn or misparsed
    record would break the count or raise — for 20 consecutive rounds.

    The origins tag their deposits in distinct namespaces
    (``_deposit_tags(origin=...)``), so the drain's supersession GC must
    not orphan one origin's in-flight deposit on seeing the other's."""
    import threading

    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    monkeypatch.setenv("BLUEFOG_CP_STRIPE_MIN_MB", "0.0625")  # 64 KiB
    monkeypatch.setenv("BLUEFOG_CP_STREAMS", "4")
    elems = 80_000  # 320 KB f32 -> header + 5 chunks, striped 4 ways
    x = jnp.zeros((8, elems), jnp.float32)
    assert bf.win_create(x, "h.multi", zero_init=True)
    win = win_ops._get_window("h.multi")
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    key = f"w.h.multi.dep.{dst}.{k}"
    contrib = np.ones(elems, np.float32)
    ROUNDS, DEPS = 20, 3
    nw = {r: {s: 1.0 for s in win.in_neighbors[r]} for r in range(8)}
    errors = []
    collected = 0.0
    starts = [threading.Event(), threading.Event()]
    done = threading.Event()
    acks = [threading.Event(), threading.Event()]

    def origin_loop(i):
        cl = cp.extra_client()
        try:
            assert cl.streams == 4
            seq = 0
            while not done.is_set():
                if not starts[i].wait(0.1):
                    continue
                starts[i].clear()
                for _ in range(DEPS):
                    recs = win_ops._pack_deposit(
                        win_ops._DEP_ACC, 0, 0.0, contrib)
                    seq += 1
                    cl.append_bytes_tagged_many(
                        [key] * len(recs), recs,
                        win_ops._deposit_tags(seq, len(recs),
                                              origin=i + 1))
                acks[i].set()
        except Exception as exc:  # noqa: BLE001 — surfaced below
            errors.append(exc)
        finally:
            cl.close()

    threads = [threading.Thread(target=origin_loop, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    try:
        for rnd in range(ROUNDS):
            for i in range(2):
                acks[i].clear()
                starts[i].set()
            # drains and a fence race the striped in-flight deposits
            out = bf.win_update("h.multi", self_weight=0.0,
                                neighbor_weights=nw, reset=True, clone=True)
            collected += float(np.asarray(out, np.float64).sum())
            bf.win_fence("h.multi")
            out = bf.win_update("h.multi", self_weight=0.0,
                                neighbor_weights=nw, reset=True, clone=True)
            collected += float(np.asarray(out, np.float64).sum())
            for i in range(2):
                assert acks[i].wait(60), f"origin {i} stalled (round {rnd})"
            assert not errors, errors
    finally:
        done.set()
        for t in threads:
            t.join(30)
    assert not errors, errors
    # final collect picks up whatever the in-loop drains missed
    out = bf.win_update("h.multi", self_weight=0.0, neighbor_weights=nw,
                        reset=True, clone=True)
    collected += float(np.asarray(out, np.float64).sum())
    # exactly once: 2 origins x ROUNDS x DEPS deposits of `elems` ones
    np.testing.assert_allclose(collected, 2 * ROUNDS * DEPS * elems,
                               rtol=1e-6)
    bf.win_free("h.multi")


def test_concurrent_clear_during_deposit_stress(bf_hosted, monkeypatch):
    """Advisory races must not crash: hammer a mailbox key with chunked
    deposits (sent in two halves to widen the race window) while the main
    thread repeatedly clears it mid-flight (the win_free/_clear take) and
    runs real drains. No exception anywhere, and the window stays usable."""
    import threading

    monkeypatch.setenv("BLUEFOG_MAX_WIN_SENT_LENGTH", str(1 << 16))
    monkeypatch.setenv("BLUEFOG_WIN_DRAIN_TIMEOUT", "30")
    elems = 40_000
    x = jnp.zeros((8, elems), jnp.float32)
    assert bf.win_create(x, "h.race", zero_init=True)
    win = win_ops._get_window("h.race")
    dst, src = 0, sorted(win.in_neighbors[0])[0]
    k = win.layout.slot_of[dst][src]
    key = f"w.h.race.dep.{dst}.{k}"
    cl = cp.client()
    contrib = np.ones(elems, np.float32)
    stop = threading.Event()
    errors = []

    def depositor():
        seq = 100
        try:
            while not stop.is_set():
                recs = win_ops._pack_deposit(
                    win_ops._DEP_ACC, 0, 0.0, contrib)
                tags = win_ops._deposit_tags(seq, len(recs))
                seq += 1
                # two halves: a clear between them orphans the tail
                cl.append_bytes_tagged_many([key] * 2, recs[:2], tags[:2])
                cl.append_bytes_tagged_many(
                    [key] * (len(recs) - 2), recs[2:], tags[2:])
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    t = threading.Thread(target=depositor)
    t.start()
    try:
        for i in range(40):
            if i % 3 == 0:
                cl.take_bytes(key)  # the _clear analog, mid-deposit
            else:
                win._drain_deposits()  # the win_update drain path
    finally:
        stop.set()
        t.join(timeout=30)
    assert not errors, errors
    # the window still works end to end: a fresh deposit folds exactly
    win._drain_deposits()  # consume any leftover complete deposits
    base = np.asarray(win._mail_rows[dst][k], np.float64).copy()
    _inject_deposit(cl, key, win_ops._pack_deposit(
        win_ops._DEP_ACC, 0, 0.0, contrib), seq=999)
    win._drain_deposits()
    np.testing.assert_allclose(
        np.asarray(win._mail_rows[dst][k], np.float64), base + 1.0,
        rtol=1e-6)
    assert np.all(np.isfinite(win._mail_rows[dst][k]))
    bf.win_free("h.race")
