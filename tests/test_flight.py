"""Flight recorder: ring, dumps, triggers, attribution, cluster retrieval.

Covers the r12 acceptance surface in-process:

  * ring mechanics — wraparound with a dropped count, name interning, the
    disable knob, the packed-tail roundtrip, rate limiting;
  * chrome conversion + merged cross-rank view: a deposit on "controller
    A" and its drain on "controller B" (the split-ownership trick from
    test_metrics) bind as a flow pair, and a fatal optimizer step's
    instant is present in the merged view;
  * step-time attribution: ``bf.step_report()`` phases cover the step
    span (10% bound) and scripts/step_attribution.py agrees;
  * triggers — fatal optimizer-step exceptions, the excepthook chain,
    and the ``bfrun --dump`` remote-trigger poll (faked KV);
  * ``bfrun --status --strict`` findings.

The watchdog-stall trigger lives in test_watchdog.py and the
PeerLostError-under-chaos trigger in test_chaos.py (riding `make chaos`
seed offsets).
"""

import json
import os
import socket
import sys

import numpy as np
import pytest

import bluefog_tpu as bf
from bluefog_tpu.runtime import control_plane as cp
from bluefog_tpu.runtime import flight as flight_mod
from bluefog_tpu.runtime import native
from bluefog_tpu.runtime.state import _global_state

from conftest import cpu_devices


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# ---------------------------------------------------------------------------
# ring mechanics
# ---------------------------------------------------------------------------

def test_ring_wraps_and_counts_drops():
    r = flight_mod.FlightRecorder(capacity=256)
    assert r.capacity == 256
    nid = r.intern("ev")
    for i in range(300):
        r.rec(flight_mod.INSTANT, nid, b=i)
    s = r.snapshot()
    assert s["recorded"] == 300
    assert s["dropped"] == 44
    assert len(s["events"]["kind"]) == 256
    # oldest surviving event is #44, newest #299, in order
    assert s["events"]["b"][0] == 44
    assert s["events"]["b"][-1] == 299
    ts = s["events"]["t_wall_us"]
    assert ts == sorted(ts)


def test_capacity_rounds_up_to_power_of_two():
    assert flight_mod.FlightRecorder(capacity=1000).capacity == 1024
    assert flight_mod.FlightRecorder(capacity=1).capacity == 256  # floor


def test_intern_is_stable_and_threadsafe_enough():
    r = flight_mod.FlightRecorder(capacity=256)
    a = r.intern("x")
    b = r.intern("y")
    assert r.intern("x") == a and r.intern("y") == b and a != b
    s = r.snapshot()
    assert s["names"] == ["x", "y"]


def test_disable_knob_installs_null_recorder(monkeypatch):
    monkeypatch.setenv("BLUEFOG_FLIGHT_DISABLE", "1")
    flight_mod.reset_for_job()
    try:
        r = flight_mod.recorder()
        r.begin("a")
        r.end("a")
        with r.span("b"):
            pass
        assert r.snapshot()["recorded"] == 0
        assert flight_mod.step_report() is None
    finally:
        monkeypatch.delenv("BLUEFOG_FLIGHT_DISABLE")
        flight_mod.reset_for_job()


def test_span_context_and_snapshot_kinds():
    r = flight_mod.FlightRecorder(capacity=256)
    with r.span("op", a=7.5, b=3):
        r.instant("mark")
    r.counter("gauge", 42)
    s = r.snapshot()
    kinds = s["events"]["kind"]
    assert kinds == [flight_mod.SPAN_B, flight_mod.INSTANT,
                     flight_mod.SPAN_E, flight_mod.COUNTER]
    assert s["events"]["a"][0] == 7.5 and s["events"]["b"][0] == 3


def test_record_hot_path_is_cheap():
    """In-suite sanity bound; the strict 1500 ns gate runs in
    `make flight-smoke` (CI boxes share cores with the runner)."""
    import timeit

    r = flight_mod.FlightRecorder(capacity=4096)
    nid = r.intern("bench")
    n = 20_000
    per = min(timeit.repeat("rec(3, nid)",
                            globals={"rec": r.rec, "nid": nid},
                            number=n, repeat=5)) / n
    assert per < 5e-6, f"ring record costs {per * 1e9:.0f} ns"


# ---------------------------------------------------------------------------
# dump document + packed tail
# ---------------------------------------------------------------------------

def test_pack_unpack_roundtrip_and_bad_magic():
    doc = flight_mod.build_dump("unit-test")
    blob = flight_mod.pack_dump(doc)
    back = flight_mod.unpack_dump(blob)
    assert back["meta"]["reason"] == "unit-test"
    assert back["events"] == doc["events"]
    with pytest.raises(ValueError):
        flight_mod.unpack_dump(b"XXXX" + blob[4:])
    with pytest.raises(ValueError):
        flight_mod.unpack_dump(b"")


def test_dump_rate_limit_and_force(tmp_path, monkeypatch):
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("BLUEFOG_FLIGHT_MIN_INTERVAL", "3600")
    flight_mod.reset_for_job()
    try:
        p1 = flight_mod.dump(reason="auto-1", publish=False, force=False)
        assert p1 is not None and os.path.exists(p1)
        # second automatic dump inside the window is suppressed...
        assert flight_mod.dump(reason="auto-2", publish=False,
                               force=False) is None
        # ...but an explicit dump goes through
        assert flight_mod.dump(reason="explicit", publish=False,
                               force=True) is not None
        assert json.load(open(p1))["meta"]["reason"] == "explicit"
    finally:
        flight_mod.reset_for_job()


def test_fatal_records_instant_then_dumps(tmp_path, monkeypatch):
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("BLUEFOG_FLIGHT_MIN_INTERVAL", "0")
    flight_mod.reset_for_job()
    try:
        path = flight_mod.fatal("unit", RuntimeError("boom"))
        doc = json.load(open(path))
        assert "RuntimeError: boom" in doc["meta"]["exception"]
        names = doc["names"]
        fatals = [i for k, n in zip(doc["events"]["kind"],
                                    doc["events"]["name"])
                  for i in ([n] if k == flight_mod.INSTANT else [])]
        assert any(names[n] == "fatal.unit" for n in fatals)
    finally:
        flight_mod.reset_for_job()


def test_excepthook_chain_dumps_and_calls_prev(tmp_path, monkeypatch):
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    monkeypatch.setenv("BLUEFOG_FLIGHT_MIN_INTERVAL", "0")
    flight_mod.reset_for_job()
    called = []
    prev_hook = sys.excepthook
    monkeypatch.setattr(sys, "excepthook",
                        lambda *a: called.append(a))
    monkeypatch.setattr(flight_mod, "_hook_installed", False)
    try:
        flight_mod.install_excepthook()
        assert sys.excepthook is not prev_hook
        exc = ValueError("unhandled")
        sys.excepthook(ValueError, exc, None)
        assert called, "previous hook not chained"
        dump = json.load(open(tmp_path / "bf_flight_0.json"))
        assert "unhandled" in dump["meta"]["exception"]
        # idempotent: a second install must not re-wrap
        hook = sys.excepthook
        flight_mod.install_excepthook()
        assert sys.excepthook is hook
    finally:
        flight_mod.reset_for_job()


# ---------------------------------------------------------------------------
# remote trigger poll (faked KV)
# ---------------------------------------------------------------------------

class _FakeKV:
    def __init__(self):
        self.kv = {}
        self.blobs = {}

    def get(self, key):
        return self.kv.get(key, 0)

    def put(self, key, value):
        self.kv[key] = int(value)

    def put_bytes(self, key, blob):
        self.blobs[key] = bytes(blob)


def test_remote_trigger_latches_then_fires(tmp_path, monkeypatch):
    monkeypatch.setenv("BLUEFOG_FLIGHT_DIR", str(tmp_path))
    flight_mod.reset_for_job()
    cl = _FakeKV()
    cl.kv[flight_mod.TRIGGER_KEY] = 7  # pre-existing trigger from the past
    try:
        # first poll only latches — a joining rank must not replay history
        assert flight_mod.poll_remote_trigger(cl) is False
        assert not cl.blobs
        # no movement -> no dump
        assert flight_mod.poll_remote_trigger(cl) is False
        # a bump fires exactly once and acks with the trigger value
        cl.kv[flight_mod.TRIGGER_KEY] = 8
        assert flight_mod.poll_remote_trigger(cl) is True
        assert cl.kv[flight_mod.ACK_KEY_FMT.format(rank=0)] == 8
        doc = flight_mod.unpack_dump(
            cl.blobs[flight_mod.DATA_KEY_FMT.format(rank=0)])
        assert doc["meta"]["reason"] == "remote-trigger #8"
        assert flight_mod.poll_remote_trigger(cl) is False
    finally:
        flight_mod.reset_for_job()


# ---------------------------------------------------------------------------
# attribution over synthetic events
# ---------------------------------------------------------------------------

def _synth_doc(events):
    """events: list of (kind, name, t_us, a, b) -> dump-doc shape."""
    names = []
    ids = {}
    cols = {"kind": [], "name": [], "t_wall_us": [], "a": [], "b": []}
    for kind, name, t, a, b in events:
        nid = ids.setdefault(name, len(names))
        if nid == len(names):
            names.append(name)
        cols["kind"].append(kind)
        cols["name"].append(nid)
        cols["t_wall_us"].append(float(t))
        cols["a"].append(float(a))
        cols["b"].append(int(b))
    return {"names": names, "events": cols}


def test_analyze_dump_phases_and_overlap_subtraction():
    B, E, S, F = (flight_mod.SPAN_B, flight_mod.SPAN_E, flight_mod.FLOW_S,
                  flight_mod.FLOW_F)
    doc = _synth_doc([
        (B, "opt.step", 0, 0, 5),
        (B, "opt.local", 0, 0, 0), (E, "opt.local", 100, 0, 0),
        (B, "opt.pack", 100, 0, 0), (E, "opt.pack", 200, 0, 0),
        (B, "opt.gossip", 200, 0, 0),
        (B, "win.wire", 200, 0, 0), (E, "win.wire", 400, 0, 0),
        (S, "edge.0.2", 390, 1000, 77),
        (S, "edge.0.3", 395, 3000, 78),
        # drain 400-700 with a nested fold 500-600: drain's exclusive
        # share is 200us, fold keeps its own 100
        (B, "win.drain", 400, 0, 0),
        (B, "win.fold", 500, 0, 0), (E, "win.fold", 600, 0, 0),
        (F, "drain.1", 600, 500, 99),
        (E, "win.drain", 700, 0, 0),
        (E, "opt.gossip", 700, 0, 0),
        (B, "opt.unpack", 700, 0, 0), (E, "opt.unpack", 800, 0, 0),
        (E, "opt.step", 1000, 0, 5),
    ])
    rep = flight_mod.analyze_dump(doc)
    assert rep["step"] == 5
    assert rep["step_sec"] == pytest.approx(1000e-6)
    ph = rep["phases"]
    assert ph["local"] == pytest.approx(100e-6)
    assert ph["pack"] == pytest.approx(100e-6)
    assert ph["wire"] == pytest.approx(200e-6)
    assert ph["drain"] == pytest.approx(200e-6)  # 300 minus nested fold
    assert ph["fold"] == pytest.approx(100e-6)
    assert ph["unpack"] == pytest.approx(100e-6)
    assert rep["other_sec"] == pytest.approx(200e-6)
    assert rep["coverage"] == pytest.approx(0.8)
    # per-edge totals + byte-weighted wire estimate
    assert rep["edges"]["0->2"]["bytes"] == 1000
    assert rep["edges"]["0->3"]["bytes"] == 3000
    assert rep["edges"]["0->3"]["wire_sec_est"] == \
        pytest.approx(0.75 * 200e-6)
    assert rep["drains"]["1"]["deposits"] == 1
    text = flight_mod.format_report(rep)
    assert "dominant" not in text  # dominance is the script's addition
    for token in ("pack", "wire", "drain", "fold", "edges"):
        assert token in text


def test_analyze_dump_needs_a_complete_step():
    doc = _synth_doc([(flight_mod.SPAN_B, "opt.step", 0, 0, 1)])
    assert flight_mod.analyze_dump(doc) is None
    assert flight_mod.analyze_dump(_synth_doc([])) is None


def test_chrome_events_and_merge():
    B, E, S = flight_mod.SPAN_B, flight_mod.SPAN_E, flight_mod.FLOW_S
    doc0 = _synth_doc([(B, "opt.step", 1000, 0, 1),
                       (S, "edge.0.1", 1500, 64, 42),
                       (E, "opt.step", 2000, 0, 1)])
    doc0["meta"] = {"rank": 0}
    doc1 = _synth_doc([(flight_mod.FLOW_F, "drain.0", 1800, 64, 42)])
    doc1["meta"] = {"rank": 1}
    merged = flight_mod.merge_dumps([doc0, doc1])
    # earliest event rebased to ts=0; clock anchors present per rank
    assert min(e["ts"] for e in merged if "ts" in e) == 0.0
    anchors = [e for e in merged if e["name"] == "bf.clock_sync_us"]
    assert {a["pid"] for a in anchors} == {0, 1}
    starts = {e["id"]: e for e in merged if e.get("ph") == "s"}
    finishes = {e["id"]: e for e in merged if e.get("ph") == "f"}
    assert set(starts) & set(finishes) == {42}
    assert starts[42]["pid"] == 0 and finishes[42]["pid"] == 1
    assert finishes[42]["ts"] >= starts[42]["ts"]
    metas = [e for e in merged if e.get("ph") == "M"]
    assert {m["pid"] for m in metas} == {0, 1}


# ---------------------------------------------------------------------------
# end-to-end over the hosted plane
# ---------------------------------------------------------------------------

@pytest.fixture()
def bf_hosted_flight(monkeypatch, tmp_path):
    """4-rank job, forced control plane + hosted plane, dumps to tmp."""
    if native.load() is None:
        pytest.skip("native runtime unavailable")
    port = _free_port()
    for k, v in {
        "BLUEFOG_CP_HOST": "127.0.0.1",
        "BLUEFOG_CP_PORT": str(port),
        "BLUEFOG_CP_WORLD": "1",
        "BLUEFOG_CP_RANK": "0",
        "BLUEFOG_WIN_HOST_PLANE": "1",
        "BLUEFOG_FLIGHT_DIR": str(tmp_path),
        "BLUEFOG_FLIGHT_MIN_INTERVAL": "0",
    }.items():
        monkeypatch.setenv(k, v)
    cp.reset_for_test()
    bf.init(devices=cpu_devices(4))
    assert cp.active()
    yield bf
    bf.shutdown()
    cp.reset_for_test()


def _run_winput_steps(bf_, steps=3):
    import jax.numpy as jnp
    import optax

    def loss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf_.DistributedWinPutOptimizer(optax.sgd(0.1), loss)
    state = opt.init({"w": jnp.ones((32,), jnp.float32)})
    for _ in range(steps):
        state, _ = opt.step(state, jnp.zeros((4, 1), jnp.float32))
    return opt, state


def test_step_report_covers_the_step(bf_hosted_flight):
    """Acceptance: the phase breakdown (with the explicit remainder) sums
    to the measured step time within 10%, and the drain/fold phases are
    real (non-zero) on a hosted window job."""
    opt, _ = _run_winput_steps(bf_hosted_flight, steps=3)
    try:
        rep = bf.step_report()
        assert rep is not None and rep["step"] == 3
        total = sum(rep["phases"].values()) + rep["other_sec"]
        assert abs(total - rep["step_sec"]) <= 0.10 * rep["step_sec"]
        assert rep["phases"]["drain"] > 0
        assert rep["phases"]["fold"] > 0
        assert rep["phases"]["local"] > 0
        assert rep["gossip_sec"] > 0
    finally:
        opt.free()


def test_step_attribution_script_over_dump(bf_hosted_flight, tmp_path):
    opt, _ = _run_winput_steps(bf_hosted_flight, steps=2)
    try:
        path = bf.flight_dump(path=str(tmp_path / "dump.json"))
        assert path is not None
        import subprocess

        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "step_attribution.py"), path, "--json"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        doc = json.loads(out.stdout)
        rep = doc["ranks"]["0"]
        assert rep["step"] == 2
        total = sum(rep["phases"].values()) + rep["other_sec"]
        assert abs(total - rep["step_sec"]) <= 0.10 * rep["step_sec"]
        # r17 schema-stable additive field: the sharded-window rotation
        # factor rides every dump (1 = unsharded, as here)
        assert doc["shard_factor"]["0"] == 1
    finally:
        opt.free()


def test_step_attribution_text_mode_annotates_sharded_rank(tmp_path):
    """r17 pinned the --json ``shard_factor`` field; this pins the TEXT
    mode's sharded-rank annotation — a dump whose metrics snapshot
    carries ``win.shard_factor`` > 1 must render the rotation-factor
    line (per-edge bytes are shard-sized), and an unsharded dump must
    not."""
    import subprocess

    B, E = flight_mod.SPAN_B, flight_mod.SPAN_E
    doc = _synth_doc([
        (B, "opt.step", 0, 0, 7),
        (flight_mod.FLOW_S, "edge.0.1", 100, 2048, 11),
        (E, "opt.step", 1000, 0, 7),
    ])
    doc["meta"] = {"rank": 0}
    doc["metrics"] = {"gauges": {"win.shard_factor": 4.0}}
    path = tmp_path / "sharded_dump.json"
    path.write_text(json.dumps(doc))
    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "step_attribution.py")
    out = subprocess.run([sys.executable, script, str(path)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "sharded window rotation: factor 4" in out.stdout
    assert "per-edge bytes below are shard-sized" in out.stdout
    # unsharded dump: no annotation line
    doc["metrics"] = {"gauges": {"win.shard_factor": 1.0}}
    path.write_text(json.dumps(doc))
    out = subprocess.run([sys.executable, script, str(path)],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "sharded window rotation" not in out.stdout


def test_fatal_step_dump_and_merged_flow_pair(bf_hosted_flight, tmp_path,
                                              monkeypatch):
    """The in-process analog of the kill-a-peer acceptance: controller A
    (owning ranks 0..1) deposits and then dies mid-gossip (injected fatal
    in its optimizer step); controller B (owning 2..3) drains. A's dump
    must exist, be parseable, and carry the fatal instant; the merged
    A+B view must contain >= 1 deposit->drain flow pair."""
    import jax.numpy as jnp

    from bluefog_tpu.ops import windows as win_mod

    bf_ = bf_hosted_flight
    st = _global_state()
    x = bf_.shard_rank_stacked(bf_.mesh(), np.ones((4, 16),
                                                   np.float32))

    # controller A owns 0..1; its win_put deposits into 2..3's mailboxes
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [0, 1])
    assert bf_.win_create(x, "fl.win", zero_init=True)
    win_a = st.windows["fl.win"]
    assert win_a.hosted and set(win_a.owned) == {0, 1}

    # controller B's window half must exist BEFORE A deposits (creation
    # defensively clears a crashed predecessor's pending records)
    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [2, 3])
    win_b = win_mod.Window("fl.win", np.ones((4, 16), np.float32),
                           zero_init=True)
    assert set(win_b.owned) == {2, 3}

    monkeypatch.setattr(cp, "owned_ranks", lambda devs, pid: [0, 1])
    bf_.win_put(x, "fl.win")

    # A "dies": a fatal error escapes its optimizer step -> dump A
    import optax

    def bad_loss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf_.DistributedWinPutOptimizer(optax.sgd(0.1), bad_loss)
    state = opt.init({"w": jnp.ones((8,), jnp.float32)})
    monkeypatch.setattr(opt, "_gossip",
                        lambda leaves: (_ for _ in ()).throw(
                            native.PeerLostError("peer 1 died", dead=[1])))
    with pytest.raises(native.PeerLostError):
        opt.step(state, jnp.zeros((4, 1), jnp.float32))
    dump_a_path = tmp_path / "bf_flight_0.json"
    assert dump_a_path.exists(), "fatal step left no dump"
    dump_a = json.load(open(dump_a_path))
    assert "PeerLostError" in dump_a["meta"]["exception"]
    names_a = dump_a["names"]
    assert any(names_a[n] == "fatal.opt.step"
               for k, n in zip(dump_a["events"]["kind"],
                               dump_a["events"]["name"])
               if k == flight_mod.INSTANT)

    # controller B: fresh recorder (its own "process") drains A's
    # deposits, dumps with rank identity 1
    flight_mod.reset_for_job()
    with win_b.state_mu:
        win_b._drain_deposits()
    dump_b = flight_mod.build_dump("drain-side")
    dump_b["meta"]["rank"] = 1

    merged = flight_mod.merge_dumps([dump_a, dump_b])
    starts = {e["id"]: e for e in merged if e.get("ph") == "s"}
    finishes = {e["id"]: e for e in merged if e.get("ph") == "f"}
    pairs = set(starts) & set(finishes)
    assert pairs, "no deposit->drain flow pair in the merged view"
    for fid in pairs:
        assert starts[fid]["pid"] == 0 and finishes[fid]["pid"] == 1
        assert finishes[fid]["ts"] >= starts[fid]["ts"]
    assert any(e.get("name") == "fatal.opt.step" for e in merged), \
        "fatal instant missing from the merged view"
    # cleanup: only the registered window (A's) is in the registry
    opt.free()


def test_bfrun_dump_external_process(bf_hosted_flight, tmp_path):
    """`bfrun --dump` from a separate process retrieves this job's packed
    tail over the control plane (watchdog-poll path: no peer monitor)."""
    import subprocess

    opt, _ = _run_winput_steps(bf_hosted_flight, steps=2)
    try:
        out_dir = tmp_path / "remote"
        env = dict(os.environ)
        out = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.launcher", "--dump",
             "--cp", f"127.0.0.1:{os.environ['BLUEFOG_CP_PORT']}",
             "--out", str(out_dir), "--dump-timeout", "30"],
            env=env, capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr + out.stdout
        doc = json.load(open(out_dir / "flight_0.json"))
        assert doc["meta"]["reason"].startswith("remote-trigger")
        assert doc["events"]["kind"], "remote tail is empty"
        merged = json.load(open(out_dir / "merged.json"))
        assert any(e.get("name") == "bf.clock_sync_us" for e in merged)
    finally:
        opt.free()


# ---------------------------------------------------------------------------
# bfrun --status --strict findings
# ---------------------------------------------------------------------------

def test_strict_findings_classification():
    from bluefog_tpu.launcher import _strict_findings

    healthy = {"ranks": {0: {"alive": True}}, "stragglers": [],
               "mass": {"conserved": True, "drift": 0.0,
                        "tolerance": 1e-12}}
    assert _strict_findings(healthy) == []
    sick = {"ranks": {0: {"alive": True}, 1: {"alive": False}},
            "stragglers": [2],
            "mass": {"conserved": False, "drift": -0.5,
                     "tolerance": 1e-12}}
    findings = _strict_findings(sick)
    assert len(findings) == 3
    assert any("stale/dead" in f for f in findings)
    assert any("straggler" in f for f in findings)
    assert any("mass drift" in f for f in findings)
    # mass=None (no push-sum job) is not a finding
    assert _strict_findings({"ranks": {}, "stragglers": [],
                             "mass": None}) == []


def test_launcher_parser_accepts_new_flags():
    from bluefog_tpu.launcher import build_parser

    args = build_parser().parse_args(["--status", "--strict"])
    assert args.status and args.strict
    args = build_parser().parse_args(
        ["--dump", "--cp", "h:1", "--out", "d", "--dump-timeout", "5"])
    assert args.dump and args.out == "d" and args.dump_timeout == 5.0
    args = build_parser().parse_args(
        ["--top", "--once", "--interval", "0.5", "--world", "4"])
    assert args.top and args.once
    assert args.interval == 0.5 and args.world == 4


def test_strict_findings_flag_under_replication_gauge():
    from bluefog_tpu.launcher import _strict_findings

    base = {"ranks": {}, "stragglers": [], "mass": None}
    assert _strict_findings({**base, "repl": None}) == []
    assert _strict_findings(
        {**base, "repl": {"lag": 10.0, "under_replicated": 0}}) == []
    findings = _strict_findings(
        {**base, "repl": {"lag": 10.0, "under_replicated": 2}})
    assert any("under-replicated" in f for f in findings)
