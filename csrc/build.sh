#!/bin/sh
# Build the native host-runtime extension (libbf_runtime.so).
# Invoked lazily by bluefog_tpu.runtime.native; safe to run by hand.
set -e
cd "$(dirname "$0")"
mkdir -p build
exec g++ -O2 -shared -fPIC -std=c++17 -pthread \
    -o build/libbf_runtime.so bf_runtime.cc
