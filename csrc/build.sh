#!/bin/sh
# Build the native host-runtime extension (libbf_runtime.so).
# Invoked lazily by bluefog_tpu.runtime.native; safe to run by hand.
#
# SANITIZE=thread|address builds an instrumented variant alongside the
# normal artifact (build/libbf_runtime.tsan.so / .asan.so) — used by
# `make tsan` / `make asan`, which point the Python runtime at it via
# BLUEFOG_NATIVE_SO (see docs/static_analysis.md).
set -e
cd "$(dirname "$0")"
mkdir -p build
case "${SANITIZE:-}" in
  thread)
    exec g++ -O1 -g -shared -fPIC -std=c++17 -pthread \
        -fsanitize=thread -fno-omit-frame-pointer \
        -o build/libbf_runtime.tsan.so bf_runtime.cc
    ;;
  address)
    exec g++ -O1 -g -shared -fPIC -std=c++17 -pthread \
        -fsanitize=address -fno-omit-frame-pointer \
        -o build/libbf_runtime.asan.so bf_runtime.cc
    ;;
  "")
    exec g++ -O2 -shared -fPIC -std=c++17 -pthread \
        -o build/libbf_runtime.so bf_runtime.cc
    ;;
  *)
    echo "build.sh: unknown SANITIZE='$SANITIZE' (thread|address)" >&2
    exit 2
    ;;
esac
