// bf_runtime: native host-runtime extension for bluefog_tpu.
//
// TPU-native analog of the reference's C++ host runtime. Two subsystems:
//
//  1. Timeline writer — chrome-tracing JSON streamed through an in-memory
//     queue to a dedicated writer thread (reference: common/timeline.{h,cc},
//     whose boost spsc_queue + WriterLoop this mirrors with a mutex-guarded
//     MPMC queue: producers here are arbitrary Python threads).
//
//  2. Control plane — small-scalar coordination protocols that XLA
//     collectives cannot express: distributed mutexes, fetch-and-op
//     counters (version windows / push-sum bookkeeping), named barriers,
//     and key-value scalar exchange. This is the analog of the reference's
//     MPI_Fetch_and_op spin-lock windows (mpi_controller.cc:1532-1602) and
//     version windows (mpi_controller.cc:1281-1393) for deployments with
//     one controller process per host, riding TCP/DCN instead of MPI RMA.
//
// Exposed as a C ABI consumed from Python via ctypes (no pybind11 in the
// image). Build: csrc/build.sh (g++ -O2 -shared -fPIC).

#include <arpa/inet.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#include <atomic>
#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <vector>

// ---------------------------------------------------------------------------
// Timeline
// ---------------------------------------------------------------------------

namespace {

struct TimelineEvent {
  std::string name;
  std::string cat;
  char phase;      // 'B', 'E', 'i', 'C' (counter), 's'/'f' (flow)
  int64_t ts_us;
  int tid;
  int64_t arg = 0;  // counter value ('C') or flow id ('s'/'f')
};

struct Timeline {
  FILE* f = nullptr;
  int pid = 0;
  std::mutex mu;
  std::condition_variable cv;
  std::deque<TimelineEvent> q;
  std::thread writer;
  bool closing = false;
  bool first = true;

  void WriterLoop() {
    std::fputs("[\n", f);
    for (;;) {
      std::deque<TimelineEvent> batch;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [&] { return closing || !q.empty(); });
        if (q.empty() && closing) break;
        batch.swap(q);
      }
      for (const auto& ev : batch) Write(ev);
      std::fflush(f);
    }
    std::fputs("\n]\n", f);
    std::fclose(f);
  }

  static void JsonEscape(const std::string& s, std::string* out) {
    for (char c : s) {
      if (c == '"' || c == '\\') { out->push_back('\\'); out->push_back(c); }
      else if ((unsigned char)c < 0x20) { out->append("?"); }
      else out->push_back(c);
    }
  }

  void Write(const TimelineEvent& ev) {
    std::string name, cat;
    JsonEscape(ev.name, &name);
    JsonEscape(ev.cat, &cat);
    if (!first) std::fputs(",\n", f);
    first = false;
    switch (ev.phase) {
      case 'B':
        std::fprintf(f,
            "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"B\", "
            "\"ts\": %lld, \"pid\": %d, \"tid\": %d}",
            name.c_str(), cat.c_str(), (long long)ev.ts_us, pid, ev.tid);
        break;
      case 'E':
        std::fprintf(f,
            "{\"ph\": \"E\", \"cat\": \"%s\", \"ts\": %lld, "
            "\"pid\": %d, \"tid\": %d}",
            cat.c_str(), (long long)ev.ts_us, pid, ev.tid);
        break;
      case 'C':
        // chrome counter track: one series named after the event
        std::fprintf(f,
            "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"C\", "
            "\"ts\": %lld, \"pid\": %d, \"tid\": %d, "
            "\"args\": {\"value\": %lld}}",
            name.c_str(), cat.c_str(), (long long)ev.ts_us, pid, ev.tid,
            (long long)ev.arg);
        break;
      case 's':
      case 'f':
        // flow events bind across processes by (cat, id) once per-rank
        // trace files are merged (scripts/merge_timelines.py); 'f' carries
        // binding point "e" so the arrow lands on the enclosing slice.
        std::fprintf(f,
            "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"%c\", "
            "\"id\": %lld%s, \"ts\": %lld, \"pid\": %d, \"tid\": %d}",
            name.c_str(), cat.c_str(), ev.phase, (long long)ev.arg,
            ev.phase == 'f' ? ", \"bp\": \"e\"" : "",
            (long long)ev.ts_us, pid, ev.tid);
        break;
      default:
        std::fprintf(f,
            "{\"name\": \"%s\", \"cat\": \"%s\", \"ph\": \"i\", \"s\": \"t\", "
            "\"ts\": %lld, \"pid\": %d, \"tid\": %d}",
            name.c_str(), cat.c_str(), (long long)ev.ts_us, pid, ev.tid);
    }
  }
};

}  // namespace

extern "C" {

void* bf_timeline_open(const char* path, int pid) {
  FILE* f = std::fopen(path, "w");
  if (!f) return nullptr;
  auto* tl = new Timeline();
  tl->f = f;
  tl->pid = pid;
  tl->writer = std::thread([tl] { tl->WriterLoop(); });
  return tl;
}

void bf_timeline_event2(void* handle, const char* name, const char* cat,
                        char phase, int64_t ts_us, int tid, int64_t arg) {
  auto* tl = static_cast<Timeline*>(handle);
  {
    std::lock_guard<std::mutex> lk(tl->mu);
    if (tl->closing) return;
    tl->q.push_back(TimelineEvent{name ? name : "", cat ? cat : "",
                                  phase, ts_us, tid, arg});
  }
  tl->cv.notify_one();
}

void bf_timeline_event(void* handle, const char* name, const char* cat,
                       char phase, int64_t ts_us, int tid) {
  bf_timeline_event2(handle, name, cat, phase, ts_us, tid, 0);
}

void bf_timeline_close(void* handle) {
  auto* tl = static_cast<Timeline*>(handle);
  {
    std::lock_guard<std::mutex> lk(tl->mu);
    tl->closing = true;
  }
  tl->cv.notify_one();
  tl->writer.join();
  delete tl;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Control plane
// ---------------------------------------------------------------------------
//
// Wire format (all little-endian, client -> server):
//   u32 payload_len | u8 op | i32 rank | u16 key_len | key bytes | i64 arg
//   [| data bytes — bulk ops only]
// Server -> client: u32 payload_len(=8) | i64 value
//   (take_bytes / get_bytes reply u32 payload_len | payload instead)
// Ops: 1=barrier 2=lock 3=unlock 4=fetch_add 5=put 6=get 7=shutdown
//      8=append_bytes 9=take_bytes 10=put_bytes 11=get_bytes.
// Barrier and lock block server-side (each connection owns a handler
// thread, the MPI "passive target" made explicit — cf. the reference's
// passive-recv thread design, nccl_controller.cc:1113-1238).
//
// The bulk-bytes ops are the host tensor transport for one-sided window
// gossip across controllers (the analog of the reference's passive-recv
// data path, nccl_controller.cc:1113-1238, with the server as the passive
// party): an origin controller APPENDs a deposit record addressed to a
// remote mailbox slot and returns immediately; the owning controller
// TAKEs (drains) its slot's records whenever it next runs win_update —
// the target's compute loop is never involved in the origin's progress.
// put_bytes/get_bytes hold each rank's published window tensor (the
// "exposed window" MPI_Win memory analog) for one-sided win_get.

namespace {

// Declared in NUMERIC order — this enum is the C++ half of the wire-protocol
// op table whose Python half is bluefog_tpu/runtime/protocol.py (OPS).
// scripts/bfcheck's `protocol` analyzer parses both and asserts a bijection
// (names, codes, and the IsDedupOp retry classification below), so keep the
// two in lockstep and the declarations in code order.
enum Op : uint8_t {
  kBarrier = 1, kLock = 2, kUnlock = 3, kFetchAdd = 4, kPut = 5, kGet = 6,
  kShutdown = 7, kAppendBytes = 8, kTakeBytes = 9, kPutBytes = 10,
  kGetBytes = 11, kBoxBytes = 12, kAppendBytesTagged = 13,
  // Striped bulk transfers (r7): one logical put/get split into byte ranges
  // carried CONCURRENTLY over a pool of connections (BLUEFOG_CP_STREAMS),
  // the single-TCP-stream escape Horovod/BytePS use for large tensors.
  //   kPutBytesPart: arg = (offset << 32) | total_len. Parts assemble in a
  //     per-key staging buffer; the completed buffer swaps into bytes_kv
  //     atomically, so readers never observe a torn value.
  //   kBytesLen: int reply = current bytes_kv[key] size (a striped reader
  //     learns the range to fan out before issuing kGetBytesPart reads).
  //   kGetBytesPart: arg = (offset << 32) | len; bulk reply = that slice.
  kPutBytesPart = 14, kBytesLen = 15, kGetBytesPart = 16,
  // Op-sequence preamble (r8, fault tolerance): a reply-less annotation the
  // client writes immediately before a NON-IDEMPOTENT op (or pipelined
  // batch): key = 8 raw bytes of the client's stable id, arg = batch
  // sequence number, data = u32 op count. The server dedups the following
  // `count` ops per (client, seq): a request retried after a lost reply is
  // answered from the recorded reply instead of being applied twice (the
  // reconnecting transport's exactly-once contract for fetch_add / append /
  // take / unlock / barrier / striped-put parts).
  kSeqPre = 17,
  // Incarnation registration (r9, elastic membership): key = 8 raw bytes of
  // the client's dedup id, arg = the process's incarnation number
  // (BLUEFOG_INCARNATION; a respawned rank attaches with the previous value
  // + 1). The server keeps a per-rank incarnation table: a registration
  // BELOW the table value is rejected with kStaleIncarnationReply (the
  // caller is a zombie of a restarted rank), a registration ABOVE it bumps
  // the table, garbage-collects the dead incarnation's server state (op-seq
  // dedup records, its origin-tagged mailbox records, any locks it held —
  // reusing the force-release epoch-bump path), and advances the
  // well-known membership-epoch counter. Every op on a registered
  // connection is fenced: once the rank's incarnation moves past the
  // connection's, the op is answered with the 4-byte kStaleFrame sentinel
  // instead of being applied.
  kAttach = 18,
  // Replication ops (sharded control plane): the client-side shard router
  // (runtime/router.py) replicates the membership-critical key families —
  // the membership epoch, per-rank incarnation mirrors, quarantine phases,
  // shutdown flags — onto EVERY shard so a shard SIGKILL cannot lose them.
  //   kPutMax: kv[key] = max(kv[key], arg); reply = the post-merge value.
  //     Monotone, commutative, idempotent — a delayed duplicate replica
  //     write can never regress a quarantine phase or incarnation mirror,
  //     which is exactly the property plain kPut lacks under failover
  //     reordering.
  //   kStats: bulk reply carrying this server's telemetry counter block
  //     (same layout as bf_cp_server_counters) so an external actor —
  //     `bfrun --status --cp a,b,...`, the soak harness — can merge
  //     per-shard views without owning the server handle.
  kPutMax = 19, kStats = 20,
  // Durable control plane (r16): per-shard WAL replication to the ring
  // successor + snapshot-based shard rejoin (chain replication in the
  // van Renesse & Schneider OSDI'04 shape, generalizing the kPutMax
  // monotone-merge pattern to a sequence-numbered mutation log).
  //   kReplApply: one WAL record from a shard server's replicator thread.
  //     key = the original key, arg = the WAL sequence number; the payload
  //     carries the original op, its argument, the reply the primary
  //     computed, the ORIGIN client's dedup identity (cid, seq, idx), and
  //     for appends the record bytes. The replica applies the mutation to
  //     its own store (routing sends the dead shard's keyspace here on
  //     failover, so promotion is a no-op) and, when the origin identity
  //     is present, records the reply in its dedup table under that
  //     identity — a client whose primary died mid-call redials the
  //     successor with the SAME kSeqPre (cid, seq) and is answered from
  //     the recording instead of double-applying. The op itself rides the
  //     replicator client's own kSeqPre dedup (IsDedupOp) so inter-shard
  //     wire drops cannot double-apply a record either.
  //   kSnapshot: point-in-time state pull (shard rejoin catch-up). arg = 0
  //     dumps everything; arg = (nshards << 32 | idx) filters to keys
  //     whose preferred shard (fnv64 % nshards) is idx. The bulk reply is
  //     a fence (the server's WAL seq at the cut) followed by typed
  //     records (kv / mailbox / lock / incarnation); serving a snapshot
  //     also re-arms this server's own replicator from the cut, so the
  //     requester sees snapshot + every later record — no gap.
  kReplApply = 21, kSnapshot = 22,
};

// Reply status codes shared with the Python layer (runtime/native.py):
// -1 = wire failure, -2 = mailbox byte cap. kDeadHolderReply wakes a
// blocked lock/barrier waiter whose holder/peer died (connection closed or
// lease expired) or whose bounded wait hit its deadline; Python surfaces it
// as PeerLostError instead of hanging forever.
constexpr int64_t kDeadHolderReply = -3;
// A request from a superseded incarnation (see kAttach). Int-reply ops can
// carry it in-band; ops with bulk replies are answered with the 4-byte
// kStaleFrame length sentinel instead (no payload follows), which is
// unambiguous on the wire: real replies are bounded by kMaxMsg (1 GiB).
// Python surfaces either as bf.StaleIncarnationError — typed and
// non-retryable, unlike a wire failure.
constexpr int64_t kStaleIncarnationReply = -4;
constexpr uint32_t kStaleFrame = 0xFFFFFFFEu;
// Quorum-lost rejection (r20 partition-aware fencing): a shard that cannot
// currently reach a commit quorum of its replica group refuses MUTATING ops
// instead of applying them locally (a silent local apply on the minority
// side of a partition is exactly how split-brain state is minted). Int-reply
// ops carry the code in-band (same convention as -3/-4); bulk-reply ops
// (kTakeBytes) answer with the kQuorumFrame length sentinel. Python surfaces
// either as bf.QuorumLostError — typed and non-retryable: reads still work,
// and the caller decides whether to wait out the partition.
constexpr int64_t kQuorumLostReply = -5;
constexpr uint32_t kQuorumFrame = 0xFFFFFFFDu;

double EnvSeconds(const char* name, double dflt) {
  const char* v = std::getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  double x = std::strtod(v, &end);
  return end == v ? dflt : x;
}

long long EnvInt(const char* name, long long dflt) {
  const char* v = std::getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  long long x = std::strtoll(v, &end, 10);
  return end == v ? dflt : x;
}

// -- deterministic fault injection (BLUEFOG_CP_FAULT) -----------------------
//
// Armed from Python (runtime/native.py parses the spec) via bf_cp_fault();
// OFF unless armed — the counters below are the only cost on the default
// path (one relaxed atomic load per client op). Drops trigger on a global
// client-op counter, alternating deterministically between
// request-never-arrives (shutdown before the frame completes, optionally
// truncated mid-frame) and reply-lost (shutdown after a complete send) —
// the two failure classes the reconnect + dedup machinery must survive.
std::atomic<long long> g_fault_drop_after{0};
std::atomic<int> g_fault_delay_ms{0};
std::atomic<int> g_fault_trunc{0};
std::atomic<long long> g_fault_seed{0};
std::atomic<long long> g_fault_ops{0};
std::atomic<long long> g_fault_drops{0};

// 0 = no fault this op, 1 = drop before the request completes,
// 2 = request delivered but the reply is lost.
int FaultNext() {
  long long da = g_fault_drop_after.load(std::memory_order_relaxed);
  if (da <= 0) return 0;
  long long n = g_fault_ops.fetch_add(1) + 1;
  if ((n + g_fault_seed.load(std::memory_order_relaxed)) % da != 0) return 0;
  g_fault_drops.fetch_add(1);
  return (((n + g_fault_seed.load(std::memory_order_relaxed)) / da) % 2 == 0)
             ? 2 : 1;
}

void FaultDelay() {
  int ms = g_fault_delay_ms.load(std::memory_order_relaxed);
  if (ms > 0) std::this_thread::sleep_for(std::chrono::milliseconds(ms));
}

// -- deterministic partition injector (BLUEFOG_CP_FAULT partition=...) ------
//
// Armed from Python via bf_cp_partition(): ports are assigned to groups and
// any client whose group differs from its target port's group fails at the
// socket layer — dials are refused and established connections are shut down
// at the next op, in BOTH directions (each side's outgoing clients enforce
// the cut against the other side's ports). Failures across the cut are
// classified as PARTITION-suspect, never as definitive death: the quorum
// layer must treat an unreachable-but-possibly-alive peer differently from
// one whose death is evidenced (ECONNREFUSED), or a minority side could
// count its unreachable majority as dead and keep serving — split-brain.
// The cut engages at start_after and heals at heal_after (wall-clock,
// matching the flight ring's time axis), so a soak can arm it from the
// environment before fork and have it fire and heal mid-run.
//
// Group resolution: normal clients use the process-default group
// (g_part_self_group, set when arming); replicator/rejoin clients override
// per-client with their OWN server's port group, which keeps an in-process
// multi-server ring test deterministic even though the globals are
// process-wide.
constexpr int kPartGroupUnset = -2000000000;  // client: use process default
std::atomic<int> g_part_armed{0};
std::mutex g_part_mu;  // guards the two fields below
std::map<int, int> g_part_port_group;
int g_part_self_group = -1;
std::atomic<long long> g_part_start_us{0};  // 0 = cut active immediately
std::atomic<long long> g_part_heal_us{0};   // 0 = never heals
std::atomic<long long> g_part_cuts{0};      // connects/ops failed by the cut

bool PartitionActiveNow() {
  if (!g_part_armed.load(std::memory_order_relaxed)) return false;
  long long now = std::chrono::duration_cast<std::chrono::microseconds>(
                      std::chrono::system_clock::now().time_since_epoch())
                      .count();
  long long s = g_part_start_us.load(std::memory_order_relaxed);
  if (s && now < s) return false;
  long long h = g_part_heal_us.load(std::memory_order_relaxed);
  if (h && now >= h) return false;
  return true;
}

int PartGroupOfPort(int port) {
  std::lock_guard<std::mutex> g(g_part_mu);
  auto it = g_part_port_group.find(port);
  return it == g_part_port_group.end() ? -1 : it->second;
}

int PartSelfGroup() {
  std::lock_guard<std::mutex> g(g_part_mu);
  return g_part_self_group;
}

// Is the edge (my_group -> port) across an active cut? `count` distinguishes
// enforcement sites (dials, op sends — telemetry-counted) from passive
// quorum-state probes.
bool PartitionCutFor(int my_group, int port, bool count = true) {
  if (!PartitionActiveNow()) return false;
  if (my_group < 0) return false;
  int tg = PartGroupOfPort(port);
  if (tg < 0 || tg == my_group) return false;
  if (count) g_part_cuts.fetch_add(1, std::memory_order_relaxed);
  return true;
}

// -- client telemetry counter block (r10 observability) ---------------------
//
// Process-global relaxed atomics, always on: the per-op cost is one to three
// relaxed fetch_adds next to a syscall-bound socket write — unmeasurable on
// the wire. Read (never reset) from Python via bf_cp_client_counters(); the
// metrics registry reports deltas against its own baseline.
constexpr int kOpSlots = 32;  // op codes are < 32; slot = op & 31
std::atomic<long long> g_cl_ops[kOpSlots];
std::atomic<long long> g_cl_bytes_out[kOpSlots];
std::atomic<long long> g_cl_bytes_in[kOpSlots];
std::atomic<long long> g_cl_redials{0};         // successful reconnects
std::atomic<long long> g_cl_redial_attempts{0}; // dials tried (incl. failed)
std::atomic<long long> g_cl_stale_frames{0};    // kStaleFrame verdicts seen
std::atomic<long long> g_cl_striped_xfers{0};   // whole striped put/get ops

inline void ClOut(uint8_t op, long long bytes) {
  g_cl_ops[op & 31].fetch_add(1, std::memory_order_relaxed);
  g_cl_bytes_out[op & 31].fetch_add(bytes, std::memory_order_relaxed);
}
inline void ClIn(uint8_t op, long long bytes) {
  g_cl_bytes_in[op & 31].fetch_add(bytes, std::memory_order_relaxed);
}

// -- transport flight ring (r12 observability) -------------------------------
//
// Fixed ring of transport-level events — redials, stale frames, striped
// transfers with per-stripe timings — read by Python (bf_flight_ring) and
// spliced into flight-recorder postmortem dumps (runtime/flight.py). The
// counters above say HOW MANY; this ring says WHEN, which is what a
// postmortem needs. Events are rare (reconnects and bulk ops, never the
// per-op path), so a mutex-guarded write is the simple correct choice.
// Timestamps are wall-clock microseconds: dumps merge across processes on
// the shared wall-clock axis without a per-process anchor.
constexpr long long kFlightRedialAttempt = 1;  // a = attempt index
constexpr long long kFlightRedial = 2;         // a = attempt index
constexpr long long kFlightStaleFrame = 3;
constexpr long long kFlightStripe = 4;         // a = bytes, b = micros
constexpr long long kFlightStripedXfer = 5;    // a = bytes, b = micros
constexpr long long kFlightFailover = 6;       // a = attempt index
constexpr int kFlightCap = 1024;  // power of two
struct FlightEv { long long t_us, kind, a, b; };
FlightEv g_flight[kFlightCap];
long long g_flight_n = 0;
std::mutex g_flight_mu;

long long WallNowUs() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::system_clock::now().time_since_epoch())
      .count();
}

void FlightRec(long long kind, long long a, long long b) {
  std::lock_guard<std::mutex> g(g_flight_mu);
  FlightEv& e = g_flight[g_flight_n & (kFlightCap - 1)];
  e.t_us = WallNowUs();
  e.kind = kind;
  e.a = a;
  e.b = b;
  ++g_flight_n;
}

// -- SHA-256 / HMAC-SHA256 (self-contained; no OpenSSL in the image) --------
//
// Used only for the connection handshake below — the analog of the
// reference's HMAC-signed driver/task messages
// (run/horovodrun/common/util/network.py:69-86), which reject any peer
// that does not hold the job's shared secret.

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_len = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
        0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t Rotr(uint32_t x, int n) { return (x >> n) | (x << (32 - n)); }

  void Block(const uint8_t* p) {
    static const uint32_t k[64] = {
        0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b,
        0x59f111f1, 0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01,
        0x243185be, 0x550c7dc3, 0x72be5d74, 0x80deb1fe, 0x9bdc06a7,
        0xc19bf174, 0xe49b69c1, 0xefbe4786, 0x0fc19dc6, 0x240ca1cc,
        0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da, 0x983e5152,
        0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
        0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc,
        0x53380d13, 0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85,
        0xa2bfe8a1, 0xa81a664b, 0xc24b8b70, 0xc76c51a3, 0xd192e819,
        0xd6990624, 0xf40e3585, 0x106aa070, 0x19a4c116, 0x1e376c08,
        0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a, 0x5b9cca4f,
        0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
        0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i)
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = Rotr(w[i - 15], 7) ^ Rotr(w[i - 15], 18) ^ (w[i - 15] >> 3);
      uint32_t s1 = Rotr(w[i - 2], 17) ^ Rotr(w[i - 2], 19) ^ (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t s1 = Rotr(e, 6) ^ Rotr(e, 11) ^ Rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + s1 + ch + k[i] + w[i];
      uint32_t s0 = Rotr(a, 2) ^ Rotr(a, 13) ^ Rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = s0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void Update(const void* data, size_t n) {
    const uint8_t* p = static_cast<const uint8_t*>(data);
    len += n;
    while (n) {
      size_t take = 64 - buf_len;
      if (take > n) take = n;
      std::memcpy(buf + buf_len, p, take);
      buf_len += take;
      p += take;
      n -= take;
      if (buf_len == 64) {
        Block(buf);
        buf_len = 0;
      }
    }
  }

  void Final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    Update(&pad, 1);
    uint8_t zero = 0;
    while (buf_len != 56) Update(&zero, 1);
    uint8_t lb[8];
    for (int i = 0; i < 8; ++i) lb[i] = uint8_t(bits >> (56 - 8 * i));
    Update(lb, 8);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

void HmacSha256(const std::string& key, const uint8_t* msg, size_t msg_len,
                uint8_t out[32]) {
  uint8_t k[64] = {0};
  if (key.size() > 64) {
    Sha256 kh;
    kh.Update(key.data(), key.size());
    kh.Final(k);
  } else {
    std::memcpy(k, key.data(), key.size());
  }
  uint8_t ipad[64], opad[64];
  for (int i = 0; i < 64; ++i) {
    ipad[i] = k[i] ^ 0x36;
    opad[i] = k[i] ^ 0x5c;
  }
  uint8_t inner[32];
  Sha256 hi;
  hi.Update(ipad, 64);
  hi.Update(msg, msg_len);
  hi.Final(inner);
  Sha256 ho;
  ho.Update(opad, 64);
  ho.Update(inner, 32);
  ho.Final(out);
}

bool ConstTimeEq(const uint8_t* a, const uint8_t* b, size_t n) {
  uint8_t acc = 0;
  for (size_t i = 0; i < n; ++i) acc |= a[i] ^ b[i];
  return acc == 0;
}

// Returns false when /dev/urandom can't supply n bytes. Callers must treat
// that as fatal for the handshake: a predictable nonce (e.g. from rand())
// would let a recorded HMAC response be replayed to authenticate without
// the secret, so there is deliberately NO degraded fallback.
bool RandomBytes(uint8_t* out, size_t n) {
  int fd = ::open("/dev/urandom", O_RDONLY);
  if (fd < 0) return false;
  size_t got = 0;
  while (got < n) {
    ssize_t r = ::read(fd, out + got, n - got);
    if (r <= 0) break;
    got += static_cast<size_t>(r);
  }
  ::close(fd);
  return got == n;
}

constexpr uint32_t kMaxMsg = 1u << 30;       // 1 GiB bulk-payload ceiling
// Per-reply ceiling for kTakeBytes: a drain takes at most this many payload
// bytes per call (plus one record, so a single oversized record still moves);
// the remainder stays queued and the client loops until empty. Keeps a long
// backlog from a sleeping controller from producing an unbounded reply.
constexpr size_t kMaxTakeReply = 64u << 20;  // 64 MiB

// Striped-put assembly state: parts land in a staging buffer; the LAST part
// to finish its copy swaps the buffer into bytes_kv, so concurrent readers
// only ever see complete values. One writer per key (the transport contract
// for bytes slots) keeps the out-of-lock memcpy below race-free.
struct PutStaging {
  std::string buf;
  int64_t got = 0;
};

// A held lock: owner rank + the connection that acquired it (force-released
// when that connection closes — the kernel closes a SIGKILLed process's
// sockets, so a dead holder's locks free within one RTT of the crash) + a
// lease as the backstop for wedged-but-connected holders. `epoch` bumps on
// every force-release so blocked waiters can tell a dead-holder wake from a
// normal handoff and surface it (kDeadHolderReply -> PeerLostError).
struct LockInfo {
  int rank = -1;
  int fd = -1;
  int64_t epoch = 0;
  std::chrono::steady_clock::time_point expiry{};
};

// Per-client dedup state for the reconnecting transport: the recorded
// replies of the client's most recent kSeqPre-annotated batch. A retry
// resends the whole batch under the same seq; already-applied ops replay
// from here (`ints`/`bulks` indexed by in-batch position), the remainder
// executes and appends. `inflight` marks an op a (possibly dead) handler is
// still executing, so a fast retry on a fresh connection waits for its
// recording instead of double-applying. Memory is bounded to ONE batch per
// client: arming a new seq resets the entry.
struct DedupEntry {
  uint64_t seq = ~0ull;
  std::vector<int64_t> ints;
  std::vector<std::string> bulks;
  std::vector<uint8_t> is_bulk;
  uint32_t inflight = 0xFFFFFFFFu;
  // Highest seq this client has FULLY completed on this server (advanced
  // when a newer seq re-arms the entry; seqs are monotone per client).
  // The WAL-replication apply uses it as a duplicate fence: chain commit
  // guarantees every *acked* op's record was applied on the replica
  // before the ack left the primary, so a kReplApply record arriving for
  // a batch at or below this watermark — or for an index this entry
  // already holds a reply for — is a late duplicate of an op the
  // failover retry already re-executed here, and must NOT apply.
  uint64_t done_below = 0;
};

// Client-side key routing hash, mirrored here for the kSnapshot filter
// (bluefog_tpu/runtime/router.py `_fnv64` is the Python original — a pure,
// stable function both sides must agree on).
uint64_t Fnv64(const std::string& key) {
  uint64_t h = 0xcbf29ce484222325ull;
  for (unsigned char b : key) {
    h ^= b;
    h *= 0x100000001b3ull;
  }
  return h;
}

// One WAL record: a mutation this shard applied to its routed state,
// queued (in apply order, seq assigned under the server mutex) for the
// replicator thread to stream to the ring successor. Carries the ORIGIN
// client's dedup identity so the replica can pre-record the reply —
// that is what keeps a failover retry exactly-once (see kReplApply).
struct ReplRecord {
  uint64_t seq = 0;       // this server's WAL sequence number
  uint8_t op = 0;         // original op (kPut/kFetchAdd/kAppendBytes/...)
  uint8_t record_reply = 0;  // take: replica assembles + records the haul
  int32_t rank = 0;       // origin client rank (dedup GC attribution)
  uint64_t cid = 0;       // origin dedup identity (0 = none armed)
  uint64_t cseq = 0;
  uint32_t cidx = 0;
  std::string key;
  int64_t arg = 0;        // original op argument (value/delta/tag/count)
  int64_t reply = 0;      // the reply the primary computed
  std::string data;       // append payload (stored-record bytes, verbatim)
};

// kReplApply payload header layout (little-endian), before the payload:
//   u8 op | u8 record_reply | i32 rank | u64 cid | u64 cseq | u32 cidx |
//   i64 arg | i64 reply
constexpr size_t kReplHdr = 1 + 1 + 4 + 8 + 8 + 4 + 8 + 8;

// Bounded condvar wait that stays visible to ThreadSanitizer. libstdc++
// lowers condition_variable::wait_for (steady_clock) to
// pthread_cond_clockwait, which older TSan runtimes (gcc 10's) do NOT
// intercept — the wait's internal mutex unlock/relock then goes unmodeled,
// the sanitizer's lock model corrupts, and `make tsan` floods with false
// "double lock of a mutex" cascades. wait_until against system_clock
// lowers to the intercepted pthread_cond_timedwait instead. Every caller
// is a predicate loop polling a few times per second (or stop()'s bounded
// drain), so a realtime clock jump at worst perturbs one poll interval.
inline void BoundedWaitMs(std::condition_variable& cv,
                          std::unique_lock<std::mutex>& lk, int ms) {
  cv.wait_until(lk, std::chrono::system_clock::now() +
                        std::chrono::milliseconds(ms));
}

struct ControlClient;  // replicator thread holds one (defined below)

// One outgoing replica stream (r20 quorum mode, R >= 3): a ring successor
// this shard streams its WAL to. All targets share the WAL deque; each
// keeps a send cursor and an acked watermark, and the deque is trimmed at
// the minimum acked over non-down targets. `state` encodes the evidence we
// hold about the peer:
//   kTgtLive    — streaming (or not yet contradicted)
//   kTgtSuspect — unreachable with NON-definitive evidence (timeout, reset,
//                 injected partition): the peer may be alive on the far
//                 side of a cut, so its queue share is RETAINED and the
//                 sender retries; it neither counts toward the commit
//                 quorum nor reduces the requirement.
//   kTgtDown    — definitive death evidence (ECONNREFUSED: the host is
//                 reachable and nothing listens) or an authoritative
//                 bf.cp.shard_dead flag: reduces the quorum requirement
//                 and releases its queue share. Re-armed only by the
//                 peer's own rejoin kSnapshot pull, never mid-stream.
constexpr int kTgtLive = 0;
constexpr int kTgtSuspect = 1;
constexpr int kTgtDown = 2;
struct ReplTarget {
  int idx = -1;          // ring index of the successor shard
  std::string host;
  int port = 0;
  int state = kTgtLive;  // guarded by server mu
  int refused = 0;       // consecutive ECONNREFUSED dials (2 -> down)
  uint64_t acked = 0;    // highest WAL seq this target acked
  uint64_t cursor = 0;   // highest WAL seq handed to this target's sender
  std::thread thread;
};

struct ControlServer {
  int listen_fd = -1;
  int world = 0;
  std::string secret;          // empty = unauthenticated (single-host dev)
  int64_t max_box_bytes = 0;   // per-mailbox byte cap; 0 = unlimited
  double lock_lease_sec = 60.0;     // BLUEFOG_CP_LOCK_LEASE (0 = no lease)
  double barrier_timeout_sec = 600; // BLUEFOG_CP_BARRIER_TIMEOUT
  std::thread accept_thread;
  std::vector<int> handler_fds;    // live connections only (pruned on close)
  int active_handlers = 0;         // guarded by mu; handlers are detached
  std::atomic<bool> stopping{false};
  // Lifetime: the server is shared between its owner (bf_cp_serve*) and
  // every detached handler thread. Each holds one reference; whoever drops
  // the LAST one deletes. A thread only drops its reference after it has
  // fully exited every mu/cv critical section, so the delete can never race
  // the tail of another thread's pthread_mutex_unlock (the classic mutex-
  // destruction hazard TSan flags when stop() deletes while a handler is
  // still inside its final unlock). Found by `make tsan`.
  std::atomic<int> refs{1};

  void Unref() {
    if (refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete this;
  }

  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, int64_t> kv;
  std::map<std::string, std::vector<std::string>> mailbox;  // append/take
  std::map<std::string, int64_t> box_bytes;                 // payload bytes
  // put/get bytes slots. shared_ptr values so a get can stream the bytes
  // to the socket WITHOUT holding the mutex (and without copying): the
  // reader pins the value; a concurrent put swaps in a fresh one.
  std::map<std::string, std::shared_ptr<const std::string>> bytes_kv;
  std::map<std::string, PutStaging> put_staging;            // striped puts
  std::map<std::string, LockInfo> locks;
  std::map<uint64_t, DedupEntry> dedup;            // client id -> last batch
  // Elastic-membership fencing (kAttach): authoritative per-rank
  // incarnation, the dedup client ids each rank's CURRENT incarnation
  // registered (cleared on bump so a zombie's dedup state cannot outlive
  // it), and a per-record origin tag mirror of every mailbox (the 7-bit
  // origin field of kAppendBytesTagged tags; -1 for untagged records) so an
  // incarnation bump can drop the dead incarnation's still-queued deposits.
  std::map<int, int64_t> incarnations;
  std::map<int, std::vector<uint64_t>> rank_cids;
  std::map<std::string, std::vector<int8_t>> mailbox_origin;
  std::map<std::string, int64_t> barrier_gen;      // barrier key -> generation
  std::map<std::string, int> barrier_count;

  // -- WAL replication to the ring successor (r16 durable control plane) --
  //
  // Ack-before-reply chain commit: a handler applies a mutating op under
  // `mu`, appends a WAL record (seq assigned under the same hold, so WAL
  // order == apply order), and blocks until the replicator thread has
  // streamed the record to the successor and seen its ack — only then is
  // the client's reply written. An acked write therefore lives on two
  // shards, and a SIGKILL of this one loses nothing that was acked.
  // When the successor stops answering (or the ack wait times out) the
  // plane DEGRADES to unreplicated — availability over replication —
  // queued and subsequent records are dropped (counted in wal_dropped),
  // and replication only resumes at the next kSnapshot cut (the rejoin /
  // resync fence), never mid-stream with a silent gap.
  bool repl_cfg = false;            // successor configured
  bool repl_live = false;           // currently replicating (guarded by mu)
  std::string repl_host;
  int repl_port = 0;
  int shard_count = 0;              // ring size / own index (kSnapshot
  int shard_idx = -1;               //   filter + scoped incarnation GC)
  double repl_wait_sec = 30.0;      // BLUEFOG_CP_REPL_TIMEOUT
  size_t repl_depth = 65536;        // BLUEFOG_CP_WAL_DEPTH (records)
  // WAL deque shared by every outgoing stream. shared_ptr records: in
  // quorum mode R-1 senders each walk the deque by cursor without copying
  // payloads; in chain mode the single ReplLoop batches them exactly as
  // the r16 wire did (same frames, same group-commit cut points).
  std::deque<std::shared_ptr<const ReplRecord>> repl_q;  // guarded by mu
  uint64_t wal_seq = 0;             // last record enqueued
  uint64_t wal_acked = 0;           // last QUORUM-committed record
  uint64_t wal_dropped_below = 0;   // degrade watermark (waiter escape)
  std::atomic<long long> wal_dropped{0};
  std::thread repl_thread;
  std::condition_variable repl_cv;  // queue arrivals + ack advances
  // -- quorum mode (r20, BLUEFOG_CP_REPLICATION >= 3) ----------------------
  // R-1 ring successors instead of one; commit = ack from effective_needed
  // targets where effective_needed = ceil(R/2) successor acks minus one per
  // target with DEFINITIVE death evidence (state down, or its
  // bf.cp.shard_dead flag odd). A suspect (partition-separated) target
  // neither counts nor reduces: with enough of them the shard falls below
  // quorum and the handler gate refuses mutating ops (kQuorumLostReply)
  // BEFORE applying them — the read-only minority side of a partition.
  // Chain mode (R = 2) keeps the r16 single-successor code path untouched.
  bool quorum_mode = false;         // repl_targets.size() >= 2
  int needed_base = 1;              // ceil(R/2) successor acks
  std::vector<std::unique_ptr<ReplTarget>> repl_targets;
  int listen_port = 0;              // own bound port (partition group key)
  std::atomic<long long> quorum_acks{0};        // target batch acks
  std::atomic<long long> partition_rejects{0};  // gate refusals
  std::set<int> repl_sources;       // distinct kReplApply source idxs (mu)
  // replica side: records at or below the fence are already folded into
  // the snapshot this server was loaded from (shard rejoin catch-up).
  // The fence is ONLY meaningful against the predecessor's CURRENT WAL
  // numbering — which is why a rejoining shard RESUMES its own wal_seq
  // from the fence its successor holds (served in the snapshot header,
  // adopted by bf_cp_server_load_snapshot): a restart back at zero would
  // put every new record at or below this stale fence, silently
  // dropped-and-acked. rejoin_pending gates incoming kReplApply records
  // during the window between the successor serving the snapshot (which
  // re-arms its stream) and THIS server loading it: records applied to
  // the still-empty store would land out of order with the snapshot's
  // contents, so they wait on the gate instead.
  // Keyed by SOURCE shard index (quorum mode: R-1 predecessors each stream
  // under their own numbering; -2 is the chain-mode / legacy single-stream
  // key, which keeps the R=2 wire and snapshot format byte-identical).
  std::map<int, uint64_t> repl_fence;
  bool rejoin_pending = false;
  std::atomic<long long> repl_applied_n{0};

  uint64_t FenceOf(int src) const {  // caller holds mu
    auto it = repl_fence.find(src);
    return it == repl_fence.end() ? 0 : it->second;
  }

  // Keyspaces this shard currently serves as FAILOVER primary (guarded
  // by mu), recomputed from the replicated bf.cp.shard_dead.<i> liveness
  // generations (odd = dead) every time one is written — directly, via
  // the WAL, or in a loaded snapshot. For each dead shard the ring is
  // walked past consecutive dead entries; the first live shard is the
  // failover primary routers send that keyspace to. Direct incarnation
  // GC must sweep these keyspaces too: their preferred shard is dead and
  // will never WAL the sweep, while this shard is their only live
  // server (the pseudo-record it WALs instead stays correct once the
  // dead shard rejoins by snapshot).
  std::set<int> fo_keyspaces;

  static bool IsDeadFlagKey(const std::string& k) {
    return k.rfind("bf.cp.shard_dead.", 0) == 0;
  }

  void RecomputeFoKeyspacesLocked() {
    fo_keyspaces.clear();
    if (shard_count <= 1 || shard_idx < 0) return;
    std::vector<bool> dead(static_cast<size_t>(shard_count), false);
    for (int i = 0; i < shard_count; ++i) {
      auto it = kv.find("bf.cp.shard_dead." + std::to_string(i));
      dead[static_cast<size_t>(i)] =
          it != kv.end() && (it->second % 2) == 1;
    }
    for (int i = 0; i < shard_count; ++i) {
      // a death claim about OURSELVES is spurious (we are running it)
      if (!dead[static_cast<size_t>(i)] || i == shard_idx) continue;
      int j = (i + 1) % shard_count;
      while (j != i && j != shard_idx && dead[static_cast<size_t>(j)])
        j = (j + 1) % shard_count;
      if (j == shard_idx) fo_keyspaces.insert(i);
    }
  }

  void ReplLoop();                     // chain mode (defined below)
  void ReplTargetLoop(ReplTarget* t);  // quorum mode, one per target

  bool DeadFlaggedLocked(int idx) {
    auto it = kv.find("bf.cp.shard_dead." + std::to_string(idx));
    return it != kv.end() && (it->second % 2) == 1;
  }

  // Current quorum requirement among non-down targets (caller holds mu):
  // ceil(R/2) successor acks, minus one per target with definitive death
  // evidence — a dead copy is unrecoverable mid-stream and must not be
  // waited for (the kill-pair survivor at R=3 has BOTH targets down and a
  // requirement of zero: it serves alone, which is the whole point).
  int EffectiveNeededLocked() {
    int needed = needed_base;
    for (auto& tp : repl_targets)
      if (tp->state == kTgtDown || DeadFlaggedLocked(tp->idx)) --needed;
    return needed < 0 ? 0 : needed;
  }

  // Quorum-mode commit watermark: the effective_needed-th largest per-
  // target acked seq (wal_seq itself when the requirement is zero).
  // Monotone — a target demotion never walks a committed seq back.
  void ReplRecomputeAckedLocked() {
    if (!quorum_mode) return;
    int needed = EffectiveNeededLocked();
    uint64_t newack;
    if (needed <= 0) {
      newack = wal_seq;
    } else {
      std::vector<uint64_t> acks;
      for (auto& tp : repl_targets)
        if (tp->state != kTgtDown) acks.push_back(tp->acked);
      if (static_cast<int>(acks.size()) < needed) return;
      std::sort(acks.begin(), acks.end(), std::greater<uint64_t>());
      newack = acks[needed - 1];
    }
    if (newack > wal_acked) {
      wal_acked = newack;
      repl_cv.notify_all();
    }
  }

  // Drop queue entries every non-down target has acked (caller holds mu).
  // A suspect target retains its share — it may be alive across a cut and
  // resume from its cursor at heal. All targets down is the quorum-mode
  // analog of chain degrade: nothing left to stream to.
  void ReplTrimLocked() {
    if (!quorum_mode) return;
    bool any = false;
    uint64_t m = ~0ull;
    for (auto& tp : repl_targets)
      if (tp->state != kTgtDown) {
        any = true;
        if (tp->acked < m) m = tp->acked;
      }
    if (!any) {
      wal_dropped_below = wal_seq;
      repl_live = false;
      repl_q.clear();
      repl_cv.notify_all();
      return;
    }
    repl_live = true;
    while (!repl_q.empty() && repl_q.front()->seq <= m) repl_q.pop_front();
  }

  // Definitive demotion of one target (caller holds mu): its unacked queue
  // share is surrendered (counted in wal_dropped) and the commit
  // requirement shrinks by one. Re-armed only by the peer's rejoin
  // kSnapshot pull — never mid-stream with a silent gap.
  void ReplDemoteLocked(ReplTarget* t) {
    if (t->state == kTgtDown) return;
    t->state = kTgtDown;
    if (wal_seq > t->acked)
      wal_dropped.fetch_add(static_cast<long long>(wal_seq - t->acked),
                            std::memory_order_relaxed);
    ReplTrimLocked();
    ReplRecomputeAckedLocked();
    repl_cv.notify_all();
  }

  // Can this shard currently commit a mutation? (caller holds mu; quorum
  // mode only — chain mode keeps r16's availability-over-replication
  // degrade.) Folds in two sensors so the verdict flips the moment the
  // world changes rather than one send-failure later: an armed partition
  // cut against a live target marks it suspect immediately, and an
  // authoritative dead flag on a suspect target demotes it (the flag is
  // the cluster's death verdict; staying suspect would pin the queue for
  // a peer that is gone).
  bool QuorumOkLocked() {
    if (!quorum_mode) return true;
    int my_group = PartGroupOfPort(listen_port);
    int needed = needed_base;
    int live = 0;
    for (auto& tp : repl_targets) {
      ReplTarget* t = tp.get();
      if (t->state == kTgtLive &&
          PartitionCutFor(my_group, t->port, /*count=*/false)) {
        t->state = kTgtSuspect;
        repl_cv.notify_all();
      }
      bool flagged = DeadFlaggedLocked(t->idx);
      if (flagged && t->state == kTgtSuspect) ReplDemoteLocked(t);
      if (t->state == kTgtDown || flagged) {
        --needed;
        continue;
      }
      if (t->state == kTgtLive) ++live;
    }
    if (needed < 0) needed = 0;
    return live >= needed;
  }

  // Degrade to unreplicated (caller holds mu; chain mode): drop the queue,
  // wake every ack waiter, and count what was lost. Replication resumes
  // only at the next kSnapshot cut.
  void ReplDegradeLocked() {
    wal_dropped_below = wal_seq;  // waiters at or below this never ack
    if (!repl_live && repl_q.empty()) return;
    repl_live = false;
    wal_dropped.fetch_add(static_cast<long long>(repl_q.size()),
                          std::memory_order_relaxed);
    repl_q.clear();
    repl_cv.notify_all();
  }

  // Append one WAL record (caller holds mu). Returns the record's seq to
  // wait on, or 0 when replication is off/degraded.
  uint64_t ReplEnqueueLocked(uint8_t op, const std::string& key, int64_t arg,
                             int64_t reply, std::string data, int rank,
                             uint64_t cid, uint64_t cseq, uint32_t cidx,
                             bool record_reply) {
    if (!repl_cfg) return 0;
    if (!repl_live) {
      wal_dropped.fetch_add(1, std::memory_order_relaxed);
      return 0;
    }
    if (repl_q.size() >= repl_depth) {
      if (!quorum_mode) {
        // WAL depth cap: a wedged successor must not grow this server's
        // memory without bound — degrade instead of blocking forever
        ReplDegradeLocked();
        wal_dropped.fetch_add(1, std::memory_order_relaxed);
        return 0;
      }
      // Quorum mode: the queue is pinned by its slowest non-down target
      // (typically partition-suspect). Depth is the durability budget for
      // riding out a cut; past it, demote the laggard(s) — bounded memory
      // beats an unbounded wait for a peer that may never come back.
      while (repl_q.size() >= repl_depth) {
        ReplTarget* worst = nullptr;
        for (auto& tp : repl_targets)
          if (tp->state != kTgtDown && (!worst || tp->acked < worst->acked))
            worst = tp.get();
        if (!worst) break;
        ReplDemoteLocked(worst);
      }
      if (!repl_live) {  // every target demoted: fully degraded
        wal_dropped.fetch_add(1, std::memory_order_relaxed);
        return 0;
      }
    }
    auto r = std::make_shared<ReplRecord>();
    r->seq = ++wal_seq;
    r->op = op;
    r->record_reply = record_reply ? 1 : 0;
    r->rank = rank;
    r->cid = cid;
    r->cseq = cseq;
    r->cidx = cidx;
    r->key = key;
    r->arg = arg;
    r->reply = reply;
    r->data = std::move(data);
    repl_q.push_back(std::move(r));
    repl_cv.notify_all();
    return wal_seq;
  }

  // Block until the successor acked `seq` — the chain-commit rule: the
  // client's reply must not be written before the record is durable on
  // the replica. Bounded by repl_wait_sec; on expiry the plane degrades
  // (the record may or may not have reached the replica — the dedup
  // identity it carries keeps even that case exactly-once).
  void ReplWaitAcked(uint64_t seq) {
    if (seq == 0) return;
    std::unique_lock<std::mutex> lk(mu);
    // steady_clock, like the lock-lease deadlines: a wall-clock step
    // (NTP correction) must neither spuriously degrade replication nor
    // stretch the bounded wait past BLUEFOG_CP_REPL_TIMEOUT.
    auto deadline = std::chrono::steady_clock::now() +
        std::chrono::duration_cast<std::chrono::steady_clock::duration>(
            std::chrono::duration<double>(repl_wait_sec));
    while (repl_live && wal_acked < seq && seq > wal_dropped_below &&
           !stopping.load()) {
      if (std::chrono::steady_clock::now() >= deadline) {
        if (quorum_mode) {
          // The commit quorum did not form in time (e.g. the partition
          // hit between the gate check and this wait). The op is already
          // applied locally and still queued for every surviving target,
          // so degrade-and-drop would be strictly worse — release the
          // reply under-replicated (counted) and let the streams catch
          // up, or the gate reject the next mutation.
          wal_dropped.fetch_add(1, std::memory_order_relaxed);
        } else {
          ReplDegradeLocked();
        }
        break;
      }
      BoundedWaitMs(repl_cv, lk, 200);
    }
  }

  // Telemetry counter block (r10): per-op dispatch counts plus the fault/
  // recovery events the Python metrics registry surfaces (lock force-
  // releases, barrier withdrawals, dedup replays, fenced ops). Relaxed
  // atomics — the aggregate gauges (mailbox depth/bytes, live connections)
  // are computed under `mu` by bf_cp_server_counters instead.
  std::atomic<long long> srv_ops[32] = {};
  std::atomic<long long> srv_lock_force_releases{0};
  std::atomic<long long> srv_barrier_withdrawals{0};
  std::atomic<long long> srv_dedup_replays{0};
  std::atomic<long long> srv_stale_rejects{0};

  // One counter-block layout, two readers: bf_cp_server_counters (the
  // in-process owner) and the kStats wire op (external per-shard view
  // mergers). Takes `mu` itself — callers must NOT hold it.
  // Slots [43..47] are the WAL-replication view (`bfrun --status
  // --strict` reports a degraded shard as under-replicated off them);
  // [48..52] the r20 quorum view: quorum_acks, partition_rejects,
  // replica_sources (distinct predecessors streaming in), quorum_state
  // (0 n/a, 1 held, 2 lost), repl_targets_live (outgoing live streams).
  static constexpr int kStatSlots = 32 + 21;

  int FillCounters(long long* out, int n) {
    if (!out || n < kStatSlots) return -1;
    for (int i = 0; i < 32; ++i)
      out[i] = srv_ops[i].load(std::memory_order_relaxed);
    long long recs = 0, rec_bytes = 0, held = 0, slots = 0, slot_bytes = 0;
    long long conns, kvn;
    long long wal_n, wal_ack, repl_st;
    long long srcs, q_st, tgt_live = 0;
    {
      std::lock_guard<std::mutex> lk(mu);
      conns = static_cast<long long>(handler_fds.size());
      for (const auto& it : mailbox)
        recs += static_cast<long long>(it.second.size());
      for (const auto& it : box_bytes) rec_bytes += it.second;
      for (const auto& it : locks)
        if (it.second.rank != -1) ++held;
      kvn = static_cast<long long>(kv.size());
      for (const auto& it : bytes_kv) {
        ++slots;
        if (it.second) slot_bytes += static_cast<long long>(it.second->size());
      }
      wal_n = static_cast<long long>(wal_seq);
      wal_ack = static_cast<long long>(wal_acked);
      if (quorum_mode) {
        bool all_live = true;
        for (const auto& tp : repl_targets) {
          if (tp->state == kTgtLive) ++tgt_live;
          else all_live = false;
        }
        repl_st = all_live ? 1 : 2;
      } else {
        repl_st = !repl_cfg ? 0 : (repl_live ? 1 : 2);
        if (repl_cfg && repl_live) tgt_live = 1;
      }
      srcs = static_cast<long long>(repl_sources.size());
      q_st = !quorum_mode ? 0 : (QuorumOkLocked() ? 1 : 2);
    }
    out[32] = conns;
    out[33] = recs;
    out[34] = rec_bytes;
    out[35] = held;
    out[36] = srv_lock_force_releases.load(std::memory_order_relaxed);
    out[37] = srv_barrier_withdrawals.load(std::memory_order_relaxed);
    out[38] = srv_dedup_replays.load(std::memory_order_relaxed);
    out[39] = srv_stale_rejects.load(std::memory_order_relaxed);
    out[40] = kvn;
    out[41] = slots;
    out[42] = slot_bytes;
    out[43] = wal_n;
    out[44] = wal_ack;
    out[45] = wal_dropped.load(std::memory_order_relaxed);
    out[46] = repl_st;  // 0 = off, 1 = live, 2 = degraded (under-replicated)
    out[47] = repl_applied_n.load(std::memory_order_relaxed);
    out[48] = quorum_acks.load(std::memory_order_relaxed);
    out[49] = partition_rejects.load(std::memory_order_relaxed);
    out[50] = srcs;
    out[51] = q_st;
    out[52] = tgt_live;
    return kStatSlots;
  }

  // Has the peer closed its end? Used by blocked lock/barrier waiters: the
  // protocol is strictly request-reply with one outstanding request per
  // connection, so readable-or-EOF while WE owe the reply can only mean the
  // connection died — the waiter abandons its wait (un-counting any barrier
  // arrival) instead of holding server state for a ghost.
  static bool PeerClosed(int fd) {
    char b;
    return ::recv(fd, &b, 1, MSG_PEEK | MSG_DONTWAIT) == 0;
  }

  // Force-release every lock held via `fd` (caller holds mu): the epoch
  // bump is what tells current waiters the holder died rather than
  // unlocked. Called when a connection closes for ANY reason — a crashed
  // peer, a fault-injected drop, or a clean client close while holding
  // (holder gone is holder gone).
  void ReleaseLocksOf(int fd) {
    bool released = false;
    for (auto& it : locks) {
      if (it.second.fd == fd && it.second.rank != -1) {
        it.second.rank = -1;
        it.second.fd = -1;
        ++it.second.epoch;
        released = true;
        srv_lock_force_releases.fetch_add(1, std::memory_order_relaxed);
        // WAL the force-release (arg = -1) so the replica's copy of the
        // lock frees too; fire-and-forget — cleanup paths must not block
        // on the successor (queue order still serializes it correctly
        // against any later grant of the same lock).
        ReplEnqueueLocked(kUnlock, it.first, -1, 1, std::string(), -1,
                          0, 0, 0, false);
      }
    }
    if (released) cv.notify_all();
  }

  // Garbage-collect everything the dead incarnation of `rank` could still
  // corrupt the job with (caller holds mu): its held locks force-release
  // (same epoch-bump wake as a connection close), its dedup batches are
  // erased (a zombie's recorded replies must not be replayed to the new
  // incarnation, and the table must not grow under restart churn), and its
  // origin-tagged mailbox records — deposits of STALE parameters the owner
  // never drained — are dropped with their byte accounting.
  // ``from_wal`` selects the mailbox sweep's scope. A DIRECT attach on a
  // replicating shard must only sweep mailboxes it is currently the
  // primary for — preferred shard == shard_idx, PLUS any keyspace it
  // serves as failover primary (fo_keyspaces: the preferred shard is
  // dead and will never WAL the sweep, while this shard is those boxes'
  // only live server — skipping them would let the owner later drain a
  // churned client's stale deposits, exactly what incarnation GC
  // exists to prevent). Replica-keyspace boxes of a LIVE predecessor
  // take every mutation — appends, counted-prefix drains, and this GC —
  // through the predecessor's ordered WAL alone, because a second
  // mutation source would misalign the counted-prefix take applies (a
  // drain of "first N records" erases the wrong N once the copies
  // disagree). The primary WALs its own GC as a pseudo-record, so the
  // replica applies it at the same sequence point (from_wal=true sweeps
  // everything — own-keyspace boxes were already swept by the direct
  // attach, and re-sweeping is idempotent). Unsharded/unconfigured
  // servers keep the full sweep.
  void GcIncarnationLocked(int rank, bool from_wal = false) {
    bool released = false;
    for (auto& it : locks) {
      if (it.second.rank == rank) {
        it.second.rank = -1;
        it.second.fd = -1;
        ++it.second.epoch;
        released = true;
        srv_lock_force_releases.fetch_add(1, std::memory_order_relaxed);
      }
    }
    auto rc = rank_cids.find(rank);
    if (rc != rank_cids.end()) {
      for (uint64_t cid : rc->second) dedup.erase(cid);
      rc->second.clear();
    }
    const bool scoped = !from_wal && shard_count > 1 && shard_idx >= 0;
    const int8_t origin = static_cast<int8_t>(rank & 0x7F);
    for (auto it = mailbox.begin(); it != mailbox.end();) {
      if (scoped) {
        const int pref = static_cast<int>(
            Fnv64(it->first) % static_cast<uint64_t>(shard_count));
        if (pref != shard_idx && fo_keyspaces.count(pref) == 0) {
          ++it;  // live predecessor's keyspace: its WAL sweeps the box
          continue;
        }
      }
      auto oi = mailbox_origin.find(it->first);
      auto& box = it->second;
      if (oi == mailbox_origin.end() || oi->second.size() != box.size()) {
        ++it;  // defensive: never drop records we cannot attribute
        continue;
      }
      auto& ov = oi->second;
      int64_t removed = 0;
      size_t w = 0;
      for (size_t i = 0; i < box.size(); ++i) {
        if (ov[i] == origin) {
          removed += static_cast<int64_t>(box[i].size());
          continue;
        }
        if (w != i) {
          box[w] = std::move(box[i]);
          ov[w] = ov[i];
        }
        ++w;
      }
      if (removed) {
        box.resize(w);
        ov.resize(w);
        box_bytes[it->first] -= removed;
      }
      if (box.empty()) {
        box_bytes.erase(it->first);
        mailbox_origin.erase(oi);
        it = mailbox.erase(it);
      } else {
        ++it;
      }
    }
    if (!from_wal)
      // pseudo-record: the replica runs the same GC at this WAL position
      ReplEnqueueLocked(kAttach, std::string(), rank, 1, std::string(),
                        rank, 0, 0, 0, false);
    if (released) cv.notify_all();
  }

  // Mutual challenge-response before any op is served: the server proves it
  // holds the secret too (a client must not leak window tensors to a rogue
  // listener), and an unauthenticated peer is disconnected before it can
  // touch locks, counters, or mailboxes. A bounded SO_RCVTIMEO keeps a
  // silent or legacy (no-handshake) client from parking the handler thread.
  bool Handshake(int fd) {
    if (secret.empty()) return true;
    timeval tv{10, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    uint8_t nonce_s[32];
    if (!RandomBytes(nonce_s, 32)) return false;  // fail closed, never rand()
    if (!WriteAll(fd, nonce_s, 32)) return false;
    uint8_t reply[64];  // client nonce || HMAC(secret, "c" || nonce_s)
    if (!ReadAll(fd, reply, 64)) return false;
    uint8_t expect[32], msg[33];
    msg[0] = 'c';
    std::memcpy(msg + 1, nonce_s, 32);
    HmacSha256(secret, msg, 33, expect);
    if (!ConstTimeEq(reply + 32, expect, 32)) return false;
    uint8_t proof[32];
    msg[0] = 's';
    std::memcpy(msg + 1, reply, 32);
    HmacSha256(secret, msg, 33, proof);
    if (!WriteAll(fd, proof, 32)) return false;
    timeval off{0, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
    return true;
  }

  // The per-connection request loop. Early `return` on ANY wire failure or
  // abandoned wait: Handle() below owns the close + lock-force-release +
  // registry cleanup, so no exit path can leak a held lock or a listed fd.
  void HandleLoop(int fd) {
    // dedup context armed by a kSeqPre preamble: the next `ded_left` ops
    // belong to batch (ded_cid, ded_seq), replayed/recorded per in-batch
    // index `ded_idx` (see DedupEntry).
    uint64_t ded_cid = 0, ded_seq = 0;
    uint32_t ded_left = 0, ded_idx = 0;
    // incarnation this connection registered via kAttach (< 0: unfenced —
    // legacy clients keep working; fencing is opt-in per connection)
    int conn_rank = -1;
    int64_t conn_inc = -1;
    for (;;) {
      uint32_t len;
      if (!ReadAll(fd, &len, 4)) return;
      if (len < 15 || len > kMaxMsg) return;
      std::vector<char> buf(len);
      if (!ReadAll(fd, buf.data(), len)) return;
      uint8_t op = buf[0];
      int32_t rank;
      std::memcpy(&rank, buf.data() + 1, 4);
      uint16_t klen;
      std::memcpy(&klen, buf.data() + 5, 2);
      if (7u + klen + 8u > len) return;
      std::string key(buf.data() + 7, klen);
      int64_t arg;
      std::memcpy(&arg, buf.data() + 7 + klen, 8);
      const char* data = buf.data() + 7 + klen + 8;
      size_t dlen = len - (7 + klen + 8);
      int64_t reply = 0;
      bool quit = false;
      bool replied = false;
      bool conn_abort = false;
      // WAL seq this request must see acked by the successor before its
      // reply is written (0 = nothing to replicate for this op)
      uint64_t repl_wait = 0;
      srv_ops[op & 31].fetch_add(1, std::memory_order_relaxed);

      // Rejoin gate: a restarted shard binds (and is dialable) BEFORE its
      // snapshot catch-up completes. EVERY op — a churned client's drain
      // as much as an incoming replication record — parks here until the
      // store is loaded: serving against the half-loaded store would
      // lose records now and resurrect them out of order later.
      if (op != kShutdown) {
        std::unique_lock<std::mutex> lk(mu);
        while (rejoin_pending && !stopping.load())
          BoundedWaitMs(cv, lk, 200);
      }

      // Incarnation fence: once this connection's registered incarnation is
      // superseded, NO op is applied — every request is answered with the
      // stale sentinel (reply-less kSeqPre is silently dropped, and any
      // armed dedup batch is disarmed: the zombie raises, it never retries).
      if (conn_inc >= 0) {
        bool is_stale;
        {
          std::lock_guard<std::mutex> lk(mu);
          auto it = incarnations.find(conn_rank);
          is_stale = it != incarnations.end() && it->second > conn_inc;
        }
        if (is_stale) {
          ded_left = 0;
          srv_stale_rejects.fetch_add(1, std::memory_order_relaxed);
          if (op == kSeqPre) continue;
          uint32_t f = kStaleFrame;
          if (!WriteAll(fd, &f, 4)) return;
          continue;
        }
      }

      if (op == kAttach) {
        // Register (rank, incarnation) for this connection. Replies the
        // rank's table value, or kStaleIncarnationReply for a zombie. A
        // bump GCs the dead incarnation's state, mirrors the new value
        // into the KV (bf.inc.<rank> — readable by the Python heartbeat
        // re-admission gate without a new query op), and advances the
        // membership epoch so optimizers rebuild their neighbor tables.
        bool stale_attach = false;
        {
          std::lock_guard<std::mutex> lk(mu);
          auto it = incarnations.find(rank);
          if (it != incarnations.end() && arg < it->second) {
            stale_attach = true;
            reply = kStaleIncarnationReply;
          } else {
            bool joined = it == incarnations.end() || arg > it->second;
            if (it == incarnations.end()) {
              incarnations[rank] = arg;
            } else if (arg > it->second) {
              GcIncarnationLocked(rank);
              it->second = arg;
            }
            if (klen == 8) {
              uint64_t cid;
              std::memcpy(&cid, key.data(), 8);
              rank_cids[rank].push_back(cid);
            }
            conn_rank = rank;
            conn_inc = arg;
            kv["bf.inc." + std::to_string(rank)] = arg;
            if (joined) {
              ++kv["bf.membership.epoch"];
              cv.notify_all();
            }
            reply = incarnations[rank];
          }
        }
        (void)stale_attach;
        uint32_t rlen = 8;
        char outb[12];
        std::memcpy(outb, &rlen, 4);
        std::memcpy(outb + 4, &reply, 8);
        if (!WriteAll(fd, outb, 12)) return;
        continue;
      }

      if (op == kSeqPre) {
        // reply-less annotation: arm dedup for the following `count` ops
        if (klen == 8) {
          std::memcpy(&ded_cid, key.data(), 8);
          ded_seq = static_cast<uint64_t>(arg);
          uint32_t count = 1;
          if (dlen >= 4) std::memcpy(&count, data, 4);
          ded_left = count;
          ded_idx = 0;
        }
        continue;
      }
      const bool ded = ded_left > 0;
      bool ded_recorded = false;

      auto ded_record = [&](int64_t v, const std::string* bulk) {
        std::lock_guard<std::mutex> lk(mu);
        DedupEntry& e = dedup[ded_cid];
        if (e.seq == ded_seq && e.ints.size() == ded_idx) {
          e.ints.push_back(v);
          e.is_bulk.push_back(bulk ? 1 : 0);
          e.bulks.emplace_back(bulk ? *bulk : std::string());
          e.inflight = 0xFFFFFFFFu;
          cv.notify_all();
        }
      };
      auto ded_abort = [&]() {
        std::lock_guard<std::mutex> lk(mu);
        DedupEntry& e = dedup[ded_cid];
        if (e.seq == ded_seq && e.inflight == ded_idx) {
          e.inflight = 0xFFFFFFFFu;
          cv.notify_all();
        }
      };

      if (ded) {
        // replay-or-arm: an op already recorded under (cid, seq, idx) is
        // answered from the record WITHOUT re-applying (the retried
        // request after a lost reply); an op a previous connection's
        // handler is still executing is awaited, then replayed.
        bool replay = false;
        int64_t replay_int = 0;
        std::string replay_bulk;
        bool replay_is_bulk = false;
        {
          std::unique_lock<std::mutex> lk(mu);
          DedupEntry& e = dedup[ded_cid];
          if (e.seq != ded_seq) {
            if (e.seq != ~0ull && e.seq > e.done_below)
              e.done_below = e.seq;  // the superseded batch completed
            e.seq = ded_seq;
            e.ints.clear();
            e.bulks.clear();
            e.is_bulk.clear();
            e.inflight = 0xFFFFFFFFu;
          }
          for (;;) {
            if (ded_idx < e.ints.size()) {
              replay = true;
              replay_is_bulk = e.is_bulk[ded_idx] != 0;
              if (replay_is_bulk) replay_bulk = e.bulks[ded_idx];
              else replay_int = e.ints[ded_idx];
              break;
            }
            if (e.inflight == ded_idx && !stopping.load()) {
              BoundedWaitMs(cv, lk, 200);
              continue;
            }
            e.inflight = ded_idx;  // we execute it
            break;
          }
        }
        if (replay) {
          srv_dedup_replays.fetch_add(1, std::memory_order_relaxed);
          bool ok;
          if (replay_is_bulk) {
            uint32_t rlen = static_cast<uint32_t>(replay_bulk.size());
            ok = WriteAll(fd, &rlen, 4) &&
                 (replay_bulk.empty() ||
                  WriteAll(fd, replay_bulk.data(), replay_bulk.size()));
          } else {
            uint32_t rlen = 8;
            char outb[12];
            std::memcpy(outb, &rlen, 4);
            std::memcpy(outb + 4, &replay_int, 8);
            ok = WriteAll(fd, outb, 12);
          }
          ++ded_idx;
          --ded_left;
          if (!ok) return;
          continue;
        }
      }

      // Partition-aware fence (r20, quorum mode only): a shard that cannot
      // reach its commit quorum refuses every MUTATING client op with a
      // typed rejection BEFORE applying it — never a silent local apply.
      // Reads keep working (the minority side is read-only, not dead), and
      // kReplApply is exempt: incoming WAL streams are the replication
      // mechanism itself, already serialized by their primary, and the
      // majority side must stay able to propagate dead flags through them.
      // Dead-flag writes themselves are NOT exempt: a minority shard that
      // could flag its unreachable peers dead would mint exactly the
      // split-brain this fence exists to prevent (on the majority side the
      // flag write passes because definitive down-evidence has already
      // reduced the requirement).
      bool is_gated_mut = false;
      switch (op) {
        case kPut: case kPutMax: case kFetchAdd: case kLock: case kUnlock:
        case kAppendBytes: case kAppendBytesTagged: case kTakeBytes:
        case kPutBytes: case kPutBytesPart:
          is_gated_mut = true;
          break;
        default:
          break;
      }
      if (is_gated_mut) {
        bool rejected = false;
        {
          std::lock_guard<std::mutex> lk(mu);
          if (quorum_mode && !QuorumOkLocked()) {
            rejected = true;
            partition_rejects.fetch_add(1, std::memory_order_relaxed);
          }
        }
        if (rejected) {
          if (op == kTakeBytes) {
            // bulk-reply op: answer with the length sentinel; the armed
            // dedup slot is aborted (not recorded), so a post-heal retry
            // re-executes rather than replaying the rejection.
            uint32_t f = kQuorumFrame;
            bool ok = WriteAll(fd, &f, 4);
            if (ded) {
              ded_abort();
              ++ded_idx;
              --ded_left;
            }
            if (!ok) return;
            continue;
          }
          reply = kQuorumLostReply;
          if (ded) {
            ded_record(reply, nullptr);
            ded_recorded = true;
            ++ded_idx;
            --ded_left;
          }
          uint32_t rlen = 8;
          char outb[12];
          std::memcpy(outb, &rlen, 4);
          std::memcpy(outb + 4, &reply, 8);
          if (!WriteAll(fd, outb, 12)) return;
          continue;
        }
      }

      switch (op) {
        case kBarrier: {
          std::unique_lock<std::mutex> lk(mu);
          int64_t gen = barrier_gen[key];
          if (++barrier_count[key] >= world) {
            barrier_count[key] = 0;
            barrier_gen[key] = gen + 1;
            cv.notify_all();
            reply = barrier_gen[key];
          } else {
            // Bounded wait (BLUEFOG_CP_BARRIER_TIMEOUT): a dead peer must
            // not park this handler forever — on expiry the arrival is
            // withdrawn and the waiter wakes with kDeadHolderReply
            // (Python: PeerLostError naming bf.dead_controllers()). A
            // waiter whose OWN client vanished withdraws silently so its
            // ghost arrival cannot complete a barrier for a peer that
            // will retry the op on a fresh connection.
            auto deadline = std::chrono::steady_clock::now() +
                std::chrono::duration_cast<
                    std::chrono::steady_clock::duration>(
                    std::chrono::duration<double>(barrier_timeout_sec));
            reply = kDeadHolderReply;
            for (;;) {
              if (stopping.load() || barrier_gen[key] != gen) {
                reply = barrier_gen[key];
                break;
              }
              if (std::chrono::steady_clock::now() >= deadline) {
                --barrier_count[key];
                srv_barrier_withdrawals.fetch_add(
                    1, std::memory_order_relaxed);
                break;
              }
              BoundedWaitMs(cv, lk, 200);
              if (barrier_gen[key] == gen && !stopping.load()) {
                lk.unlock();
                bool closed = PeerClosed(fd);
                lk.lock();
                if (closed && barrier_gen[key] == gen) {
                  --barrier_count[key];
                  srv_barrier_withdrawals.fetch_add(
                      1, std::memory_order_relaxed);
                  conn_abort = true;
                  break;
                }
              }
            }
          }
          break;
        }
        case kLock: {
          std::unique_lock<std::mutex> lk(mu);
          LockInfo& L = locks[key];
          const int64_t start_epoch = L.epoch;
          for (;;) {
            if (stopping.load()) {
              reply = 1;  // server dying: never block teardown
              break;
            }
            if (L.rank == -1 || L.rank == rank) {
              if (L.epoch != start_epoch) {
                // force-released while we waited: the holder's connection
                // closed or its lease expired. Don't silently enter the
                // possibly-torn critical section — wake with the dead-
                // holder status (lock left free; a fresh acquire works).
                reply = kDeadHolderReply;
                break;
              }
              L.rank = rank;  // grant (re-entrant per rank)
              L.fd = fd;
              if (lock_lease_sec > 0)
                L.expiry = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<
                        std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(lock_lease_sec));
              reply = 1;
              // WAL the grant: the replica adopts holder (+ a lease
              // stamped at apply time), so on failover the holder's
              // unlock lands on a lock it still owns and waiters queue
              // behind a real holder instead of PeerLostError
              repl_wait = ReplEnqueueLocked(kLock, key, rank, 1,
                                            std::string(), rank, 0, 0, 0,
                                            false);
              break;
            }
            if (lock_lease_sec > 0 &&
                std::chrono::steady_clock::now() >= L.expiry) {
              // lease backstop: holder connected but wedged past its lease
              L.rank = -1;
              L.fd = -1;
              ++L.epoch;
              srv_lock_force_releases.fetch_add(
                  1, std::memory_order_relaxed);
              cv.notify_all();
              reply = kDeadHolderReply;
              break;
            }
            BoundedWaitMs(cv, lk, 200);
            lk.unlock();
            bool closed = PeerClosed(fd);
            lk.lock();
            if (closed) {
              conn_abort = true;  // our own client vanished mid-wait
              break;
            }
          }
          break;
        }
        case kUnlock: {
          std::lock_guard<std::mutex> lk(mu);
          auto it = locks.find(key);
          if (it != locks.end() && it->second.rank == rank) {
            it->second.rank = -1;
            it->second.fd = -1;
            cv.notify_all();
            reply = 1;
            repl_wait = ReplEnqueueLocked(kUnlock, key, rank, 1,
                                          std::string(), rank,
                                          ded ? ded_cid : 0, ded_seq,
                                          ded_idx, false);
          } else {
            // not ours (anymore): the lease expired or a drop force-
            // released it mid-hold — the critical section was broken;
            // tell the caller instead of silently succeeding
            reply = kDeadHolderReply;
          }
          break;
        }
        case kFetchAdd: {
          std::lock_guard<std::mutex> lk(mu);
          int64_t& slot = kv[key];
          reply = slot;
          slot += arg;
          repl_wait = ReplEnqueueLocked(op, key, arg, reply, std::string(),
                                        rank, ded ? ded_cid : 0, ded_seq,
                                        ded_idx, false);
          break;
        }
        case kPut: {
          std::lock_guard<std::mutex> lk(mu);
          kv[key] = arg;
          if (IsDeadFlagKey(key)) RecomputeFoKeyspacesLocked();
          reply = 1;
          repl_wait = ReplEnqueueLocked(op, key, arg, reply, std::string(),
                                        rank, 0, 0, 0, false);
          break;
        }
        case kPutMax: {
          // replication merge: monotone max, so replica writes commute and
          // a late duplicate can never regress the value
          std::lock_guard<std::mutex> lk(mu);
          int64_t& slot = kv[key];
          if (arg > slot) slot = arg;
          // liveness generation writes (the router's death announcement /
          // a rejoiner's alive publish) re-derive which keyspaces this
          // shard serves as failover primary
          if (IsDeadFlagKey(key)) RecomputeFoKeyspacesLocked();
          reply = slot;
          repl_wait = ReplEnqueueLocked(op, key, arg, reply, std::string(),
                                        rank, 0, 0, 0, false);
          break;
        }
        case kStats: {
          // remote telemetry read: the same 43-slot counter block
          // bf_cp_server_counters fills, serialized little-endian for an
          // external merger (per-shard --status views, the soak harness)
          long long block[kStatSlots];
          FillCounters(block, kStatSlots);
          uint32_t rlen = static_cast<uint32_t>(8 * kStatSlots);
          if (!WriteAll(fd, &rlen, 4) ||
              !WriteAll(fd, block, sizeof(block)))
            return;
          replied = true;
          break;
        }
        case kGet: {
          std::lock_guard<std::mutex> lk(mu);
          reply = kv.count(key) ? kv[key] : 0;
          break;
        }
        case kAppendBytes:
        case kAppendBytesTagged: {
          // kAppendBytesTagged prefixes the stored record with the request's
          // 8-byte little-endian `arg` — the deposit tag (sequence id,
          // chunk index, chunk count) the window drain uses to discard
          // orphaned continuation chunks after a concurrent clear. The
          // prefix rides the copy the append makes anyway, so tagging is
          // free on the wire and in server memory (+8 bytes/record).
          //
          // The record copy happens OUTSIDE the server mutex: a 16 MB
          // chunk memcpy under the global lock would serialize every
          // other connection's handler behind it — on a contended host
          // that lock hold time IS the transport ceiling (PERF.md r7).
          const size_t extra = (op == kAppendBytesTagged) ? 8 : 0;
          std::string rec;
          rec.reserve(dlen + extra);
          if (extra) rec.append(reinterpret_cast<const char*>(&arg), 8);
          rec.append(data, dlen);
          std::lock_guard<std::mutex> lk(mu);
          auto& box = mailbox[key];
          int64_t& bytes = box_bytes[key];
          // Cap each mailbox (kMaxTakeReply bounds only the drain reply):
          // a crashed/stalled owner must not let depositors grow server
          // memory without limit. -2 tells the client "mailbox full" so it
          // can raise a targeted error instead of a wire failure.
          if (max_box_bytes > 0 &&
              bytes + static_cast<int64_t>(dlen + extra) > max_box_bytes &&
              !box.empty()) {
            reply = -2;
            break;
          }
          // WAL carries the STORED record verbatim (tag prefix included):
          // the replica pushes it as-is, so the two copies stay byte-
          // identical and counted-prefix drains align. One payload copy —
          // the replication-factor-2 cost.
          repl_wait = ReplEnqueueLocked(op, key, arg,
                                        static_cast<int64_t>(box.size() + 1),
                                        rec, rank, ded ? ded_cid : 0,
                                        ded_seq, ded_idx, false);
          box.emplace_back(std::move(rec));
          // Origin mirror for incarnation GC: tagged records carry the
          // 7-bit origin process id in tag bits 56..62; untagged are -1.
          mailbox_origin[key].push_back(
              op == kAppendBytesTagged
                  ? static_cast<int8_t>((static_cast<uint64_t>(arg) >> 56) &
                                        0x7F)
                  : static_cast<int8_t>(-1));
          bytes += static_cast<int64_t>(dlen + extra);
          reply = static_cast<int64_t>(box.size());
          break;
        }
        case kTakeBytes: {
          // Atomically drain (a bounded prefix, preserving deposit order):
          // reply is concat(u32 reclen | rec bytes ...).
          std::vector<std::string> records;
          {
            std::lock_guard<std::mutex> lk(mu);
            auto it = mailbox.find(key);
            if (it != mailbox.end()) {
              auto& box = it->second;
              size_t total = 0, i = 0;
              while (i < box.size()) {
                size_t next = total + 4 + box[i].size();
                if (i > 0 && next > kMaxTakeReply) break;
                total = next;
                ++i;
              }
              if (i >= box.size()) {
                records.swap(box);
                mailbox.erase(it);
                box_bytes.erase(key);
                mailbox_origin.erase(key);
              } else {
                records.assign(std::make_move_iterator(box.begin()),
                               std::make_move_iterator(box.begin() + i));
                box.erase(box.begin(), box.begin() + i);
                auto oi = mailbox_origin.find(key);
                if (oi != mailbox_origin.end() && oi->second.size() >= i)
                  oi->second.erase(oi->second.begin(),
                                   oi->second.begin() + i);
                int64_t taken = 0;
                for (const auto& r : records)
                  taken += static_cast<int64_t>(r.size());
                box_bytes[key] -= taken;
              }
              if (!records.empty())
                // WAL the drain as a counted prefix: the replica erases
                // the same N records from its byte-identical copy — and,
                // when the origin identity is armed, assembles THAT prefix
                // into a recorded reply first, so a take whose reply died
                // with this shard replays the exact haul on the successor
                // (zero lost deposits, not a one-cycle window).
                repl_wait = ReplEnqueueLocked(
                    kTakeBytes, key,
                    static_cast<int64_t>(records.size()),
                    static_cast<int64_t>(records.size()), std::string(),
                    rank, ded ? ded_cid : 0, ded_seq, ded_idx, ded);
            }
          }
          // chain-commit: the drain must be durable on the successor
          // before any byte of the reply reaches the client
          ReplWaitAcked(repl_wait);
          uint64_t total = 0;
          for (const auto& r : records) total += 4 + r.size();
          uint32_t rlen = static_cast<uint32_t>(total);
          if (ded) {
            // Dedup'd drains assemble the reply once so a retry after a
            // lost reply replays the SAME records instead of losing them
            // (mass conservation under connection drops). One extra
            // memcpy of the drained bytes vs the streaming path below;
            // BLUEFOG_CP_RETRIES=0 restores the copy-free wire exactly.
            std::string body;
            body.reserve(total);
            for (const auto& r : records) {
              uint32_t rl = static_cast<uint32_t>(r.size());
              body.append(reinterpret_cast<const char*>(&rl), 4);
              body.append(r);
            }
            ded_record(static_cast<int64_t>(records.size()), &body);
            ded_recorded = true;
            if (!WriteAll(fd, &rlen, 4) ||
                (!body.empty() && !WriteAll(fd, body.data(), body.size())))
              return;
            replied = true;
            break;
          }
          // Stream the reply straight from the taken records (they are
          // owned by this handler now — no lock needed, and no second
          // full-payload assembly copy; a 64 MB drain reply costs zero
          // server-side memcpys beyond the kernel's).
          if (!WriteAll(fd, &rlen, 4)) return;
          for (const auto& r : records) {
            uint32_t rl = static_cast<uint32_t>(r.size());
            if (!WriteAll(fd, &rl, 4) ||
                (!r.empty() && !WriteAll(fd, r.data(), r.size())))
              return;
          }
          replied = true;
          break;
        }
        case kPutBytes: {
          // Copy outside the mutex, swap inside: a 100 MB assign under
          // the global lock would stall every other handler for its
          // whole duration (readers still only ever observe complete
          // values — the pointer swap is atomic under the lock).
          auto val = std::make_shared<const std::string>(data, dlen);
          std::lock_guard<std::mutex> lk(mu);
          // WAL the value: published window rows live in bytes_kv, and
          // before this record class a shard death lost them until the
          // owner's next publish (ROADMAP "replicating published window
          // rows"). One payload copy — the same replication-factor-2
          // cost the mailbox pays.
          repl_wait = ReplEnqueueLocked(kPutBytes, key,
                                        static_cast<int64_t>(dlen), 1,
                                        std::string(data, dlen), rank,
                                        0, 0, 0, false);
          bytes_kv[key] = std::move(val);
          reply = 1;
          break;
        }
        case kGetBytes: {
          std::shared_ptr<const std::string> v;
          {
            std::lock_guard<std::mutex> lk(mu);
            auto it = bytes_kv.find(key);
            if (it != bytes_kv.end()) v = it->second;
          }
          // zero-copy reply: stream straight from the pinned value
          uint32_t rlen = v ? static_cast<uint32_t>(v->size()) : 0;
          if (!WriteAll(fd, &rlen, 4) ||
              (rlen && !WriteAll(fd, v->data(), rlen)))
            return;
          replied = true;
          break;
        }
        case kPutBytesPart: {
          // One stripe of a striped put: arg = (offset << 32) | total_len.
          // The payload copy runs OUTSIDE the server mutex so stripes on
          // parallel connections overlap; safety: the staging buffer is
          // never resized while same-total stripes are in flight (single
          // writer per key), and the swap below only fires after every
          // stripe's copy has been counted in — the last counter is the
          // copier itself, so no copy can still be running at swap time.
          uint64_t a = static_cast<uint64_t>(arg);
          size_t off = static_cast<size_t>(a >> 32);
          size_t total = static_cast<size_t>(a & 0xFFFFFFFFu);
          if (off + dlen > total || total > kMaxMsg) {
            reply = -1;
            break;
          }
          if (total == 0) {
            std::lock_guard<std::mutex> lk(mu);
            bytes_kv[key] = std::make_shared<const std::string>();
            repl_wait = ReplEnqueueLocked(kPutBytes, key, 0, 1,
                                          std::string(), rank,
                                          0, 0, 0, false);
            reply = 1;
            break;
          }
          char* dst = nullptr;
          {
            std::lock_guard<std::mutex> lk(mu);
            PutStaging& st = put_staging[key];
            if (st.buf.size() != total) {
              st.buf.assign(total, '\0');
              st.got = 0;
            }
            dst = &st.buf[0];
          }
          if (dlen) std::memcpy(dst + off, data, dlen);
          {
            std::lock_guard<std::mutex> lk(mu);
            auto it = put_staging.find(key);
            if (it != put_staging.end()) {
              it->second.got += static_cast<int64_t>(dlen);
              if (it->second.got >= static_cast<int64_t>(total)) {
                auto val = std::make_shared<const std::string>(
                    std::move(it->second.buf));
                // WAL the ASSEMBLED value once, at the stripe that
                // completed it (the same visibility point readers get):
                // a striped publish replicates as one kPutBytes record
                repl_wait = ReplEnqueueLocked(
                    kPutBytes, key, static_cast<int64_t>(val->size()), 1,
                    *val, rank, 0, 0, 0, false);
                bytes_kv[key] = std::move(val);
                put_staging.erase(it);
              }
            }
          }
          reply = 1;
          break;
        }
        case kBytesLen: {
          std::lock_guard<std::mutex> lk(mu);
          auto it = bytes_kv.find(key);
          reply = (it == bytes_kv.end() || !it->second)
                      ? 0
                      : static_cast<int64_t>(it->second->size());
          break;
        }
        case kGetBytesPart: {
          // Ranged read: arg = (offset << 32) | len; reply is the slice
          // clamped to the stored value (empty when offset is past the
          // end), streamed zero-copy from the pinned value.
          uint64_t a = static_cast<uint64_t>(arg);
          size_t off = static_cast<size_t>(a >> 32);
          size_t want = static_cast<size_t>(a & 0xFFFFFFFFu);
          std::shared_ptr<const std::string> v;
          {
            std::lock_guard<std::mutex> lk(mu);
            auto it = bytes_kv.find(key);
            if (it != bytes_kv.end()) v = it->second;
          }
          size_t n = 0;
          if (v && off < v->size()) {
            size_t avail = v->size() - off;
            n = want < avail ? want : avail;
          }
          uint32_t rlen = static_cast<uint32_t>(n);
          if (!WriteAll(fd, &rlen, 4) ||
              (n && !WriteAll(fd, v->data() + off, n)))
            return;
          replied = true;
          break;
        }
        case kBoxBytes: {
          // Current payload bytes pending in the named mailbox. Lets a
          // single-writer origin pre-check the byte cap per DEPOSIT so a
          // multi-record deposit is never torn by a mid-sequence -2 (the
          // drain only shrinks the box, so the check is race-free for the
          // key's one writer).
          std::lock_guard<std::mutex> lk(mu);
          auto it = box_bytes.find(key);
          reply = it == box_bytes.end() ? 0 : it->second;
          break;
        }
        case kReplApply: {
          // One WAL record from the predecessor shard's replicator: apply
          // the mutation to OUR store (failover routes the dead shard's
          // keyspace here, so promotion needs no copy) and pre-record the
          // origin client's reply under its dedup identity. Never
          // re-enqueued into our own WAL: replication factor is 2, and
          // direct ops we serve post-failover chain onward naturally.
          if (dlen < kReplHdr + 2) {
            reply = -1;
            break;
          }
          const uint8_t rop = static_cast<uint8_t>(data[0]);
          const bool rrec = data[1] != 0;
          int32_t orank;
          uint64_t ocid, ocseq;
          uint32_t ocidx;
          int64_t oarg, oreply;
          std::memcpy(&orank, data + 2, 4);
          std::memcpy(&ocid, data + 6, 8);
          std::memcpy(&ocseq, data + 14, 8);
          std::memcpy(&ocidx, data + 22, 4);
          std::memcpy(&oarg, data + 26, 8);
          std::memcpy(&oreply, data + 34, 8);
          // The record KEY rides the body, length-prefixed — never the
          // multi-op frame key: that batch joins keys with '\n', and a
          // control-plane key embeds user-derived queue/collective names
          // which may themselves contain a newline. Framing the key here
          // keeps the batch split-proof for every possible key.
          uint16_t rklen;
          std::memcpy(&rklen, data + kReplHdr, 2);
          if (kReplHdr + 2 + static_cast<size_t>(rklen) > dlen) {
            reply = -1;
            break;
          }
          const std::string rkey(data + kReplHdr + 2, rklen);
          const char* pay = data + kReplHdr + 2 + rklen;
          const size_t pn = dlen - kReplHdr - 2 - rklen;
          std::lock_guard<std::mutex> lk(mu);
          const uint64_t rseq = static_cast<uint64_t>(arg);
          // Source identity rides the frame rank: a quorum-mode (R >= 3)
          // replicator dials with rank -(100 + source_shard_idx) so R-1
          // incoming streams keep independent fences under independent
          // WAL numberings; the chain-mode replicator's -2 is the legacy
          // single-stream key (R=2 wire byte-identical).
          const int rsrc = rank <= -100 ? (-rank - 100) : -2;
          repl_sources.insert(rsrc);
          if (rseq <= FenceOf(rsrc)) {  // already folded into our snapshot
            reply = 1;
            break;
          }
          // Duplicate fence vs failover retries: up to a pipeline window
          // of WAL records can still be in flight from a SIGKILLed
          // predecessor while its clients' retries already landed here
          // and re-executed the same (cid, seq, idx) ops fresh. Chain
          // commit means every *acked* op's record applied before its
          // ack, so a record for a batch this client has completed here
          // (done_below) or an index we already hold a reply for is a
          // late duplicate — skip the mutation entirely.
          if (ocid != 0) {
            auto dit = dedup.find(ocid);
            if (dit != dedup.end() &&
                (ocseq <= dit->second.done_below ||
                 (dit->second.seq == ocseq &&
                  (dit->second.ints.size() > ocidx ||
                   // the retry is EXECUTING this very op right now (its
                   // mutating cases always run to completion and record)
                   dit->second.inflight == ocidx)))) {
              reply = 1;
              break;
            }
          }
          repl_applied_n.fetch_add(1, std::memory_order_relaxed);
          std::string bulk;
          bool has_bulk = false;
          switch (rop) {
            case kPut:
              kv[rkey] = oarg;
              if (IsDeadFlagKey(rkey)) RecomputeFoKeyspacesLocked();
              break;
            case kPutMax: {
              int64_t& slot = kv[rkey];
              if (oarg > slot) slot = oarg;
              if (IsDeadFlagKey(rkey)) RecomputeFoKeyspacesLocked();
              break;
            }
            case kFetchAdd:
              kv[rkey] += oarg;
              break;
            case kAppendBytes:
            case kAppendBytesTagged:
              mailbox[rkey].emplace_back(pay, pn);
              mailbox_origin[rkey].push_back(
                  rop == kAppendBytesTagged
                      ? static_cast<int8_t>(
                            (static_cast<uint64_t>(oarg) >> 56) & 0x7F)
                      : static_cast<int8_t>(-1));
              box_bytes[rkey] += static_cast<int64_t>(pn);
              break;
            case kTakeBytes: {
              auto it = mailbox.find(rkey);
              if (it != mailbox.end()) {
                auto& box = it->second;
                size_t n = static_cast<size_t>(oarg);
                if (n > box.size()) n = box.size();
                if (rrec) {
                  for (size_t i = 0; i < n; ++i) {
                    uint32_t rl = static_cast<uint32_t>(box[i].size());
                    bulk.append(reinterpret_cast<const char*>(&rl), 4);
                    bulk.append(box[i]);
                  }
                  has_bulk = true;
                }
                int64_t taken = 0;
                for (size_t i = 0; i < n; ++i)
                  taken += static_cast<int64_t>(box[i].size());
                box.erase(box.begin(), box.begin() + n);
                auto oi = mailbox_origin.find(rkey);
                if (oi != mailbox_origin.end() && oi->second.size() >= n)
                  oi->second.erase(oi->second.begin(),
                                   oi->second.begin() + n);
                box_bytes[rkey] -= taken;
                if (box.empty()) {
                  mailbox.erase(it);
                  box_bytes.erase(rkey);
                  mailbox_origin.erase(rkey);
                }
              } else if (rrec) {
                has_bulk = true;  // record the (empty) haul faithfully
              }
              break;
            }
            case kLock: {
              LockInfo& L = locks[rkey];
              L.rank = static_cast<int>(oarg);
              L.fd = -1;  // no local connection: lease is the backstop
              if (lock_lease_sec > 0)
                L.expiry = std::chrono::steady_clock::now() +
                    std::chrono::duration_cast<
                        std::chrono::steady_clock::duration>(
                        std::chrono::duration<double>(lock_lease_sec));
              cv.notify_all();
              break;
            }
            case kUnlock: {
              auto it = locks.find(rkey);
              if (it != locks.end() &&
                  (oarg < 0 || it->second.rank == static_cast<int>(oarg))) {
                it->second.rank = -1;
                it->second.fd = -1;
                if (oarg < 0) ++it->second.epoch;  // force-release
                cv.notify_all();
              }
              break;
            }
            case kPutBytes:
              // published window rows (and any raw byte value): the
              // replica adopts the whole value — failover serves
              // win_get/rejoin reads with no re-derivation gap
              bytes_kv[rkey] =
                  std::make_shared<const std::string>(pay, pn);
              break;
            case kAttach:  // pseudo-record: incarnation GC at this point
              GcIncarnationLocked(static_cast<int>(oarg), true);
              break;
            default:
              break;
          }
          if (ocid != 0) {
            // pre-record the origin's reply: its failover retry arrives
            // with the SAME kSeqPre (cid, seq) and replays from here.
            // Only move the entry FORWARD — a late record from an older
            // batch applied its mutation above but must not clobber the
            // newer batch's recording (its reply will never be asked
            // for again).
            const bool fresh = dedup.find(ocid) == dedup.end();
            DedupEntry& e = dedup[ocid];
            if (fresh) rank_cids[orank].push_back(ocid);
            if (e.seq != ocseq &&
                (e.seq == ~0ull || fresh || ocseq > e.seq)) {
              if (e.seq != ~0ull && e.seq > e.done_below)
                e.done_below = e.seq;  // ordered stream: prior batches
              e.seq = ocseq;           // are fully reflected here
              e.ints.clear();
              e.bulks.clear();
              e.is_bulk.clear();
              e.inflight = 0xFFFFFFFFu;
            }
            if (e.seq == ocseq && e.ints.size() == ocidx) {
              e.ints.push_back(has_bulk ? 0 : oreply);
              e.is_bulk.push_back(has_bulk ? 1 : 0);
              e.bulks.emplace_back(std::move(bulk));
            }
          }
          reply = 1;
          break;
        }
        case kSnapshot: {
          // Point-in-time state pull (shard rejoin catch-up). arg packs
          // bit 62 = "requester is OUR stream receiver, re-arm from this
          // cut", bits 32..61 = filter shard count, bits 0..31 = filter
          // index. The blob header carries (a) OUR wal_seq — the fence
          // the requester adopts against the stream WE send it — and
          // (b) OUR repl_fence — the position the requester's own WAL
          // numbering must RESUME from when we are its receiver (a
          // restart back at zero would put every new record at or below
          // the stale fence we hold: silently dropped-and-acked, lost
          // on the requester's next death).
          const uint64_t filt = static_cast<uint64_t>(arg);
          const bool rearm = ((filt >> 62) & 1u) != 0;
          const uint64_t fn = (filt >> 32) & 0x3FFFFFFFu;
          const uint64_t fi = filt & 0xFFFFFFFFu;
          std::string blob;
          {
            std::lock_guard<std::mutex> lk(mu);
            auto want = [&](const std::string& k) {
              return fn == 0 || Fnv64(k) % fn == fi;
            };
            auto put_rec = [&](uint8_t type, const std::string& k,
                               int64_t a, const char* p, size_t n) {
              blob.push_back(static_cast<char>(type));
              uint16_t kl = static_cast<uint16_t>(k.size());
              blob.append(reinterpret_cast<const char*>(&kl), 2);
              blob.append(k);
              blob.append(reinterpret_cast<const char*>(&a), 8);
              uint32_t pl = static_cast<uint32_t>(n);
              blob.append(reinterpret_cast<const char*>(&pl), 4);
              if (n) blob.append(p, n);
            };
            uint64_t fence = wal_seq;
            blob.append(reinterpret_cast<const char*>(&fence), 8);
            // The resume position is per SOURCE stream: a quorum-mode
            // rejoiner identifies itself via its frame rank (-(100+idx))
            // and gets the fence of ITS stream into us; legacy pulls get
            // the single chain-stream fence (key -2).
            const int snap_src = rank <= -100 ? (-rank - 100) : -2;
            uint64_t resume = FenceOf(snap_src);
            blob.append(reinterpret_cast<const char*>(&resume), 8);
            for (const auto& it : kv)
              if (want(it.first))
                put_rec(0, it.first, it.second, nullptr, 0);
            for (const auto& it : mailbox) {
              if (!want(it.first)) continue;
              auto oi = mailbox_origin.find(it.first);
              for (size_t i = 0; i < it.second.size(); ++i) {
                int64_t origin = -1;
                if (oi != mailbox_origin.end() && i < oi->second.size())
                  origin = oi->second[i];
                put_rec(1, it.first, origin, it.second[i].data(),
                        it.second[i].size());
              }
            }
            for (const auto& it : locks)
              if (it.second.rank != -1 && want(it.first))
                put_rec(2, it.first, it.second.rank, nullptr, 0);
            for (const auto& it : incarnations)
              put_rec(3, std::to_string(it.first), it.second, nullptr, 0);
            for (const auto& it : bytes_kv)
              if (it.second && want(it.first))
                put_rec(4, it.first,
                        static_cast<int64_t>(it.second->size()),
                        it.second->data(), it.second->size());
            // Re-arm OUR degraded outgoing stream ONLY when the requester
            // declares itself that stream's receiver (the rejoin pull of
            // OUR keyspace by our ring successor): it loads this very
            // cut, so cut + resumed records are gap-free. Any other pull
            // — a rejoiner fetching its own keyspace from its successor,
            // a diagnostic ControlPlaneClient.snapshot() — must NOT
            // resume the stream: the real receiver never loaded this
            // cut, and the records dropped while degraded would become
            // exactly the silent mid-stream gap degrade exists to
            // prevent. The flag rides the pull itself (not a separate
            // op) so cut and re-arm stay atomic under one mutex hold.
            if (rearm && repl_cfg) {
              if (quorum_mode) {
                // Re-arm exactly the requester's target stream: it loads
                // this very cut, so cut + resumed records are gap-free
                // for THAT copy; the other streams are untouched.
                for (auto& tp : repl_targets) {
                  if (tp->idx != snap_src) continue;
                  tp->state = kTgtLive;
                  tp->refused = 0;
                  tp->acked = wal_seq;   // the cut carries everything prior
                  tp->cursor = wal_seq;  // resume with the next record
                  ReplTrimLocked();
                  ReplRecomputeAckedLocked();
                  repl_cv.notify_all();
                }
              } else if (!repl_live) {
                repl_live = true;  // resync point: stream resumes from here
                repl_cv.notify_all();
              }
            }
          }
          uint32_t rlen = static_cast<uint32_t>(blob.size());
          if (!WriteAll(fd, &rlen, 4) ||
              (!blob.empty() && !WriteAll(fd, blob.data(), blob.size())))
            return;
          replied = true;
          break;
        }
        case kShutdown:
          quit = true;
          reply = 1;
          break;
        default:
          break;
      }
      if (conn_abort) {
        // abandoned wait (our client's connection is gone): leave no
        // dedup in-flight marker behind — the retry must re-execute
        if (ded) ded_abort();
        return;
      }
      // chain-commit barrier: a mutating op's reply leaves this server
      // only after the successor acked its WAL record (no-op when
      // replication is off, degraded, or the op was read-only)
      ReplWaitAcked(repl_wait);
      if (!replied) {
        // record BEFORE the reply write: a reply lost on the wire must
        // find its value here when the client retries
        if (ded) {
          ded_record(reply, nullptr);
          ded_recorded = true;
        }
        uint32_t rlen = 8;
        char out[12];
        std::memcpy(out, &rlen, 4);
        std::memcpy(out + 4, &reply, 8);
        if (!WriteAll(fd, out, 12)) return;
      } else if (ded && !ded_recorded) {
        ded_abort();  // idempotent bulk op under a batch preamble
      }
      if (ded) {
        ++ded_idx;
        --ded_left;
      }
      if (quit) {
        stopping.store(true);
        cv.notify_all();
        return;
      }
    }
  }

  void Handle(int fd) {
    if (Handshake(fd)) HandleLoop(fd);
    // Single cleanup point for EVERY exit path: force-release the locks
    // this connection held (epoch bump wakes + flags waiters), prune the
    // fd from the live registry, and let stop() know we are gone. The fd
    // closes INSIDE the locked section, after the lock scan — were it
    // closed first, a new connection could recycle the number and acquire
    // a lock this scan would then wrongly force-release.
    std::lock_guard<std::mutex> lk(mu);
    ReleaseLocksOf(fd);
    handler_fds.erase(
        std::remove(handler_fds.begin(), handler_fds.end(), fd),
        handler_fds.end());
    ::close(fd);
    --active_handlers;
    cv.notify_all();
  }

  static bool SendBytesReply(int fd, const std::string& payload) {
    uint32_t rlen = static_cast<uint32_t>(payload.size());
    if (!WriteAll(fd, &rlen, 4)) return false;
    return payload.empty() || WriteAll(fd, payload.data(), payload.size());
  }

  static bool ReadAll(int fd, void* p, size_t n) {
    char* c = static_cast<char*>(p);
    while (n) {
      ssize_t r = ::recv(fd, c, n, 0);
      if (r <= 0) return false;
      c += r;
      n -= r;
    }
    return true;
  }

  static bool WriteAll(int fd, const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    while (n) {
      ssize_t r = ::send(fd, c, n, MSG_NOSIGNAL);
      if (r <= 0) return false;
      c += r;
      n -= r;
    }
    return true;
  }

  void AcceptLoop() {
    for (;;) {
      int fd = ::accept(listen_fd, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::lock_guard<std::mutex> lk(mu);
      if (stopping.load()) {
        ::close(fd);
        break;
      }
      handler_fds.push_back(fd);
      ++active_handlers;
      refs.fetch_add(1, std::memory_order_relaxed);
      // Detached: the reconnecting transport churns connections, and a
      // joinable-thread-per-connection vector would grow for the job's
      // lifetime. stop() instead waits on active_handlers == 0. The
      // Unref() after Handle() returns is the handler's LAST access to
      // the server — it runs outside every critical section.
      std::thread([this, fd] { Handle(fd); Unref(); }).detach();
    }
  }
};

struct ControlClient {
  int fd = -1;
  int rank = 0;
  std::mutex mu;
  // Reconnect state (r8): enough to redial + re-handshake transparently.
  std::string host;
  int port = 0;
  std::string secret;
  int sockbuf = 0;
  uint64_t cid = 0;       // stable dedup identity across reconnects
  uint64_t next_seq = 1;  // batch sequence counter (guarded by mu)
  int retries = 3;        // BLUEFOG_CP_RETRIES (0 disables reconnects)
  int backoff_ms = 50;    // BLUEFOG_CP_BACKOFF_MS, doubling, capped at 2 s
  // Incarnation fencing (kAttach): < 0 = unfenced. Once the server marks
  // this client stale (its rank re-registered with a newer incarnation),
  // every op fails fast with kStaleIncarnationReply instead of retrying —
  // a zombie must stop touching shared state, not reconnect harder.
  int64_t incarnation = -1;
  bool stale = false;  // guarded by mu
  // Ring-successor failover target (r16 durable sharded plane). When the
  // primary's redial fails, later attempts dial the successor instead and
  // STICK there — crucially on the same ControlClient, so the retried
  // request goes out under the SAME kSeqPre (cid, seq) the primary saw,
  // and the successor (whose dedup table the primary's WAL pre-populated)
  // replays the recorded reply instead of double-applying. fo_active is
  // read lock-free by the router's health probe (it must not contend
  // with a blocking op holding `mu`).
  // (r20) The failover CHAIN generalizes the single successor: when R-1
  // successors hold the dead primary's keyspace, a redial failure walks
  // the chain PAST runs of consecutive dead shards — still on the same
  // ControlClient, so the same (cid, seq) reaches whichever live replica
  // answers, and its WAL-primed dedup table keeps the retry exactly-once.
  // fo_active holds 0 (primary) or 1 + index of the chain entry stuck to.
  std::string fo_host;
  int fo_port = 0;
  std::vector<std::pair<std::string, int>> fo_chain;  // guarded by mu
  std::atomic<int> fo_active{0};
  // Partition-injector group: INT_MIN = resolve to the process default at
  // call time (normal clients); replicator/rejoin clients pin their OWN
  // server's port group so an in-process multi-server ring partitions
  // deterministically. cur_port tracks the endpoint `fd` currently points
  // at (primary or a chain entry) — the cut is evaluated per edge.
  int part_group = kPartGroupUnset;
  int cur_port = 0;

  int EffGroup() {
    return part_group == kPartGroupUnset ? PartSelfGroup() : part_group;
  }

  // Register (rank, incarnation) on the CURRENT connection (caller holds
  // mu). Returns 1 on success, kStaleIncarnationReply when superseded
  // (also latches `stale`), -1 on wire failure, 0 when unfenced.
  int64_t SendAttach() {
    if (incarnation < 0) return 0;
    std::vector<char> buf;
    std::string key(reinterpret_cast<const char*>(&cid), 8);
    Encode(&buf, kAttach, key, incarnation);
    if (!ControlServer::WriteAll(fd, buf.data(), buf.size())) return -1;
    int64_t reply;
    if (!ReadReply(&reply)) return -1;
    if (reply == kStaleIncarnationReply) {
      stale = true;
      return kStaleIncarnationReply;
    }
    return 1;
  }

  // Ops whose effect must be applied exactly once: a retry after a lost
  // reply goes out under a kSeqPre annotation so the server can replay the
  // recorded reply instead of re-applying. This switch mirrors the
  // `idempotent=False` rows of bluefog_tpu/runtime/protocol.py (bfcheck
  // asserts the two sets are equal). Everything else (get/put/
  // bytes_len/ranged get/put_bytes/lock) is idempotent and retries raw —
  // a redundant lock re-grant is absorbed by per-rank re-entrancy, and a
  // dropped connection's locks were force-released server-side anyway.
  static bool IsDedupOp(uint8_t op) {
    switch (op) {
      case kBarrier:
      case kUnlock:
      case kFetchAdd:
      case kAppendBytes:
      case kAppendBytesTagged:
      case kTakeBytes:
      case kPutBytesPart:
      case kReplApply:
        return true;
      default:
        return false;
    }
  }

  void EncodePre(std::vector<char>* buf, uint64_t seq, uint32_t count) {
    std::string key(reinterpret_cast<const char*>(&cid), 8);
    Encode(buf, kSeqPre, key, static_cast<int64_t>(seq), &count, 4);
  }

  uint64_t AllocSeq(uint8_t op) {
    return (retries > 0 && IsDedupOp(op)) ? next_seq++ : 0;
  }

  // Send the (already framed) request bytes, honoring the armed fault
  // injector: fault 1 kills the connection before the request completes
  // (optionally after a deliberate half-frame write), fault 2 delivers it
  // but loses the reply. Both surface as a wire failure to the caller, so
  // the reconnect + dedup path is exercised exactly as by a real drop.
  bool SendFault(const std::vector<char>& buf, int fault) {
    // Partition cut on an ESTABLISHED connection: every op funnels through
    // here, so shutting the socket down at the next use cuts both
    // directions lazily (the far side's own clients do the same against
    // our ports). Surfaces as a wire failure — and the redial fails at
    // DialAndHandshake's cut check, classified partition-suspect.
    if (PartitionCutFor(EffGroup(), cur_port ? cur_port : port)) {
      ::shutdown(fd, SHUT_RDWR);
      return false;
    }
    if (fault == 1) {
      if (g_fault_trunc.load(std::memory_order_relaxed) && buf.size() > 8)
        ControlServer::WriteAll(fd, buf.data(), buf.size() / 2);
      ::shutdown(fd, SHUT_RDWR);
      return false;
    }
    if (!ControlServer::WriteAll(fd, buf.data(), buf.size())) return false;
    if (fault == 2) {
      ::shutdown(fd, SHUT_RDWR);
      return false;
    }
    return true;
  }

  // Redial + re-handshake after a wire failure, with capped exponential
  // backoff. Caller holds mu. Returns false when this attempt's dial
  // failed (the retry loop decides whether to try again).
  bool Reconnect(int attempt);

  // Client half of ControlServer::Handshake (mutual): prove we hold the
  // secret, then verify the server's proof over OUR nonce so window bytes
  // are never sent to a listener that merely accepted the TCP connect.
  static bool Handshake(int fd, const std::string& secret) {
    if (secret.empty()) return true;
    timeval tv{10, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
    uint8_t nonce_s[32];
    if (!ControlServer::ReadAll(fd, nonce_s, 32)) return false;
    uint8_t out[64], msg[33];
    if (!RandomBytes(out, 32)) return false;  // nonce_c; fail closed
    msg[0] = 'c';
    std::memcpy(msg + 1, nonce_s, 32);
    HmacSha256(secret, msg, 33, out + 32);
    if (!ControlServer::WriteAll(fd, out, 64)) return false;
    uint8_t proof[32], expect[32];
    if (!ControlServer::ReadAll(fd, proof, 32)) return false;
    msg[0] = 's';
    std::memcpy(msg + 1, out, 32);
    HmacSha256(secret, msg, 33, expect);
    if (!ConstTimeEq(proof, expect, 32)) return false;
    timeval off{0, 0};
    ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &off, sizeof(off));
    return true;
  }

  void Encode(std::vector<char>* buf, uint8_t op, const std::string& key,
              int64_t arg, const void* data = nullptr, size_t dlen = 0) {
    uint16_t klen = static_cast<uint16_t>(key.size());
    uint32_t len = static_cast<uint32_t>(1 + 4 + 2 + klen + 8 + dlen);
    size_t base = buf->size();
    buf->resize(base + 4 + len);
    std::memcpy(buf->data() + base, &len, 4);
    (*buf)[base + 4] = static_cast<char>(op);
    std::memcpy(buf->data() + base + 5, &rank, 4);
    std::memcpy(buf->data() + base + 9, &klen, 2);
    std::memcpy(buf->data() + base + 11, key.data(), klen);
    std::memcpy(buf->data() + base + 11 + klen, &arg, 8);
    if (dlen) std::memcpy(buf->data() + base + 11 + klen + 8, data, dlen);
  }

  bool ReadReply(int64_t* reply) {
    uint32_t rlen;
    if (!ControlServer::ReadAll(fd, &rlen, 4)) return false;
    if (rlen == kStaleFrame) {
      // fenced: the server refused the op (no payload follows). Latch the
      // flag so every later op fails fast without touching the wire.
      stale = true;
      g_cl_stale_frames.fetch_add(1, std::memory_order_relaxed);
      FlightRec(kFlightStaleFrame, 0, 0);
      *reply = kStaleIncarnationReply;
      return true;
    }
    if (rlen == kQuorumFrame) {
      // below-quorum rejection of a bulk-reply op: typed, not latched —
      // the shard recovers when the partition heals.
      *reply = kQuorumLostReply;
      return true;
    }
    if (rlen != 8) return false;
    return ControlServer::ReadAll(fd, reply, 8);
  }

  int64_t Call(uint8_t op, const std::string& key, int64_t arg,
               const void* data = nullptr, size_t dlen = 0) {
    std::lock_guard<std::mutex> lk(mu);
    if (stale) return kStaleIncarnationReply;
    const uint64_t seq = AllocSeq(op);
    for (int attempt = 0;; ++attempt) {
      std::vector<char> buf;
      if (seq) EncodePre(&buf, seq, 1);
      Encode(&buf, op, key, arg, data, dlen);
      if (SendFault(buf, FaultNext())) {
        ClOut(op, static_cast<long long>(buf.size()));
        FaultDelay();
        int64_t reply;
        if (ReadReply(&reply)) {
          ClIn(op, 12);
          return reply;
        }
      }
      if (attempt >= retries)
        return stale ? kStaleIncarnationReply : -1;
      // a failed dial burns the attempt, it does not abort the loop —
      // the NEXT attempt may reach the ring-successor failover target
      if (!Reconnect(attempt) && stale) return kStaleIncarnationReply;
    }
  }

  // Bulk-reply call (take_bytes / get_bytes): returns a malloc'd payload the
  // caller frees with bf_cp_free; length via *out_len; -1 on wire failure.
  // take_bytes is non-idempotent (the drain consumes records): it rides the
  // dedup preamble so a retried take replays the server-recorded reply.
  int64_t CallBytes(uint8_t op, const std::string& key, void** out,
                    int64_t* out_len, int64_t arg = 0) {
    std::lock_guard<std::mutex> lk(mu);
    if (stale) return kStaleIncarnationReply;
    const uint64_t seq = AllocSeq(op);
    for (int attempt = 0;; ++attempt) {
      std::vector<char> buf;
      if (seq) EncodePre(&buf, seq, 1);
      Encode(&buf, op, key, arg);
      if (SendFault(buf, FaultNext())) {
        ClOut(op, static_cast<long long>(buf.size()));
        FaultDelay();
        uint32_t rlen;
        bool got = ControlServer::ReadAll(fd, &rlen, 4);
        if (got && rlen == kStaleFrame) {
          stale = true;
          g_cl_stale_frames.fetch_add(1, std::memory_order_relaxed);
      FlightRec(kFlightStaleFrame, 0, 0);
          return kStaleIncarnationReply;
        }
        if (got && rlen == kQuorumFrame) return kQuorumLostReply;
        if (got && rlen <= kMaxMsg) {
          char* payload = static_cast<char*>(std::malloc(rlen ? rlen : 1));
          if (!payload) return -1;
          if (!rlen || ControlServer::ReadAll(fd, payload, rlen)) {
            ClIn(op, 4LL + rlen);
            *out = payload;
            *out_len = rlen;
            return rlen;
          }
          std::free(payload);
        }
      }
      if (attempt >= retries)
        return stale ? kStaleIncarnationReply : -1;
      // a failed dial burns the attempt, it does not abort the loop —
      // the NEXT attempt may reach the ring-successor failover target
      if (!Reconnect(attempt) && stale) return kStaleIncarnationReply;
    }
  }

  // Bulk-reply call that lands DIRECTLY in the caller's buffer (the striped
  // kGetBytesPart read path): no malloc, no extra copy — each pool
  // connection streams its range straight into its slice of the
  // preallocated result. Returns bytes read, or -1 on wire failure /
  // oversized reply. Ranged reads are idempotent: plain retry.
  int64_t CallBytesInto(uint8_t op, const std::string& key, int64_t arg,
                        void* dst, size_t cap) {
    std::lock_guard<std::mutex> lk(mu);
    if (stale) return kStaleIncarnationReply;
    for (int attempt = 0;; ++attempt) {
      std::vector<char> buf;
      Encode(&buf, op, key, arg);
      if (SendFault(buf, FaultNext())) {
        ClOut(op, static_cast<long long>(buf.size()));
        FaultDelay();
        uint32_t rlen;
        if (ControlServer::ReadAll(fd, &rlen, 4)) {
          if (rlen == kStaleFrame) {
            stale = true;
            g_cl_stale_frames.fetch_add(1, std::memory_order_relaxed);
      FlightRec(kFlightStaleFrame, 0, 0);
            return kStaleIncarnationReply;
          }
          if (rlen > cap) return -1;  // oversized: a real protocol error
          if (!rlen || ControlServer::ReadAll(fd, dst, rlen)) {
            ClIn(op, 4LL + rlen);
            return rlen;
          }
        }
      }
      if (attempt >= retries)
        return stale ? kStaleIncarnationReply : -1;
      // a failed dial burns the attempt, it does not abort the loop —
      // the NEXT attempt may reach the ring-successor failover target
      if (!Reconnect(attempt) && stale) return kStaleIncarnationReply;
    }
  }

  // Pipelined payload-carrying batch (kAppendBytes / kPutBytes): frame all
  // n requests, write them back-to-back, then drain the n int replies. One
  // round-trip's latency for a whole window op's deposits, and large
  // payloads stream straight from the caller's buffers (no client-side
  // copy at all — `datas[i]` may point anywhere, e.g. into a live numpy
  // array, so a 100 MB deposit costs zero Python-side memcpys).
  //
  // In-flight replies are BOUNDED at kMaxInflight: the server replies 12
  // bytes per request as it consumes them, and a batch large enough that
  // the unread replies fill both socket buffers would park the server's
  // send while the client is still blocked writing payload — a mutual-
  // blocking deadlock (fine-grained BLUEFOG_MAX_WIN_SENT_LENGTH chunking
  // times high out-degree reaches tens of thousands of records). Every
  // already-written request's reply is guaranteed to arrive, so draining
  // down to the bound mid-batch can stall only until the server catches
  // up — never forever.
  //
  // `args` (optional): per-request int64 argument — the deposit tag for
  // kAppendBytesTagged. When null, the payload length is sent (the
  // original framing; the server ignores the field for untagged ops).
  int64_t CallBytesMultiOutV(uint8_t op, const char* keys_nl,
                             const void* const* datas, const int64_t* lens,
                             const int64_t* args, int64_t* out, int n) {
    std::lock_guard<std::mutex> lk(mu);
    if (stale) return kStaleIncarnationReply;
    // One dedup seq covers the WHOLE batch (count = n): on a wire failure
    // the entire batch is resent under the same seq, the server replays
    // the already-applied prefix from its recording, and only the
    // remainder executes — no append is ever double-applied.
    const uint64_t seq = AllocSeq(op);
    auto attempt = [&](int fault) -> bool {
      const char* p = keys_nl;
      // Small records coalesce into one send buffer (fewer syscalls);
      // large ones are written directly from the source to skip the memcpy.
      constexpr size_t kCoalesce = 4u << 20;
      constexpr int kMaxInflight = 128;
      std::vector<char> buf;
      bool first_send = true;
      long long wire = 0;
      auto send = [&](const std::vector<char>& b) -> bool {
        wire += static_cast<long long>(b.size());
        if (first_send) {
          first_send = false;
          return SendFault(b, fault);
        }
        return ControlServer::WriteAll(fd, b.data(), b.size());
      };
      if (seq) EncodePre(&buf, seq, static_cast<uint32_t>(n));
      int replies_read = 0;
      bool delayed = false;
      auto drain_to = [&](int target) -> bool {
        if (!delayed) {
          delayed = true;
          FaultDelay();
        }
        for (; replies_read < target; ++replies_read) {
          int64_t reply;
          if (!ReadReply(&reply)) return false;
          if (out) out[replies_read] = reply;
        }
        return true;
      };
      for (int i = 0; i < n; ++i) {
        const char* e = std::strchr(p, '\n');
        std::string key = e ? std::string(p, e - p) : std::string(p);
        size_t dlen = static_cast<size_t>(lens[i]);
        int64_t arg = args ? args[i] : lens[i];
        if (dlen <= kCoalesce) {
          Encode(&buf, op, key, arg, datas[i], dlen);
        } else {
          Encode(&buf, op, key, arg);  // header only, then stream payload
          // fix the frame length to include the payload we stream below
          uint32_t flen;
          size_t hdr = 4 + 1 + 4 + 2 + key.size() + 8;
          std::memcpy(&flen, buf.data() + buf.size() - hdr, 4);
          flen += static_cast<uint32_t>(dlen);
          std::memcpy(buf.data() + buf.size() - hdr, &flen, 4);
          if (!send(buf)) return false;
          buf.clear();
          if (!ControlServer::WriteAll(fd, datas[i], dlen)) return false;
          wire += static_cast<long long>(dlen);
        }
        p = e ? e + 1 : p + key.size();
        if (i + 1 - replies_read > kMaxInflight) {
          // flush coalesced frames first: a reply only arrives once its
          // request has actually reached the server
          if (!buf.empty()) {
            if (!send(buf)) return false;
            buf.clear();
          }
          if (!drain_to(i + 1 - kMaxInflight)) return false;
        }
      }
      if (!buf.empty() && !send(buf)) return false;
      if (!drain_to(n)) return false;
      ClOut(op, wire);
      ClIn(op, 12LL * n);
      return true;
    };
    for (int a = 0;; ++a) {
      if (attempt(FaultNext())) return n;
      if (a >= retries)
        return stale ? kStaleIncarnationReply : -1;
      if (!Reconnect(a) && stale) return kStaleIncarnationReply;
    }
  }

  // Pipelined bulk-reply batch (kTakeBytes / kGetBytes): one round-trip for
  // n keys; replies are concatenated as (u64 len | payload)* in a single
  // malloc'd buffer the caller frees with bf_cp_free.
  int64_t CallBytesMultiIn(uint8_t op, const char* keys_nl, int n, void** out,
                           int64_t* out_len) {
    std::lock_guard<std::mutex> lk(mu);
    if (stale) return kStaleIncarnationReply;
    const uint64_t seq = AllocSeq(op);  // multi-take: batch-level dedup
    bool qlost = false;
    auto attempt = [&](int fault) -> bool {
      std::vector<char> buf;
      if (seq) EncodePre(&buf, seq, static_cast<uint32_t>(n));
      const char* p = keys_nl;
      for (int i = 0; i < n; ++i) {
        const char* e = std::strchr(p, '\n');
        std::string key = e ? std::string(p, e - p) : std::string(p);
        Encode(&buf, op, key, 0);
        p = e ? e + 1 : p + key.size();
      }
      if (!SendFault(buf, fault)) return false;
      ClOut(op, static_cast<long long>(buf.size()));
      FaultDelay();
      // Grow the result with realloc doubling and read replies straight
      // into it: no shadow buffer, so a 100 MB drain holds 100-ish MB
      // once, not twice (this is the bulk data plane being optimized).
      size_t cap = 1 << 16, used = 0;
      char* payload = static_cast<char*>(std::malloc(cap));
      if (!payload) return false;
      for (int i = 0; i < n; ++i) {
        uint32_t rlen;
        if (!ControlServer::ReadAll(fd, &rlen, 4)) {
          std::free(payload);
          return false;
        }
        if (rlen == kStaleFrame) {
          // fenced mid-batch: latch and fail the whole call typed — the
          // retry loop below sees the flag and stops.
          stale = true;
          g_cl_stale_frames.fetch_add(1, std::memory_order_relaxed);
      FlightRec(kFlightStaleFrame, 0, 0);
          std::free(payload);
          return false;
        }
        if (rlen == kQuorumFrame) {
          // below-quorum mid-batch: fail typed, no retry. While a shard
          // is below quorum EVERY gated op rejects, so there is no mixed
          // partial-drain to lose — the batch keys all route to the same
          // shard and reject together.
          qlost = true;
          std::free(payload);
          return false;
        }
        if (rlen > kMaxMsg) {
          std::free(payload);
          return false;
        }
        size_t need = used + 8 + rlen;
        if (need > cap) {
          while (cap < need) cap *= 2;
          char* grown = static_cast<char*>(std::realloc(payload, cap));
          if (!grown) {
            std::free(payload);
            return false;
          }
          payload = grown;
        }
        uint64_t rl64 = rlen;
        std::memcpy(payload + used, &rl64, 8);
        used += 8;
        if (rlen && !ControlServer::ReadAll(fd, payload + used, rlen)) {
          std::free(payload);
          return false;
        }
        used += rlen;
      }
      ClIn(op, static_cast<long long>(used) + 4LL * n);
      *out = payload;
      *out_len = static_cast<int64_t>(used);
      return true;
    };
    for (int a = 0;; ++a) {
      if (attempt(FaultNext())) return n;
      if (qlost) return kQuorumLostReply;
      if (stale || a >= retries)
        return stale ? kStaleIncarnationReply : -1;
      if (!Reconnect(a) && stale) return kStaleIncarnationReply;
    }
  }

  // Pipelined batch: send every request, then drain every reply. The server
  // handles one connection sequentially, so replies arrive in order; this
  // turns n key operations into one round-trip's worth of latency.
  int64_t CallMulti(uint8_t op, const char* keys_nl, const int64_t* args,
                    int64_t* out, int n) {
    std::lock_guard<std::mutex> lk(mu);
    if (stale) return kStaleIncarnationReply;
    const uint64_t seq = AllocSeq(op);  // fetch_add_many: batch-level dedup
    auto attempt = [&](int fault) -> bool {
      std::vector<char> buf;
      if (seq) EncodePre(&buf, seq, static_cast<uint32_t>(n));
      const char* p = keys_nl;
      for (int i = 0; i < n; ++i) {
        const char* e = std::strchr(p, '\n');
        std::string key = e ? std::string(p, e - p) : std::string(p);
        Encode(&buf, op, key, args ? args[i] : 0);
        p = e ? e + 1 : p + key.size();
      }
      if (!SendFault(buf, fault)) return false;
      ClOut(op, static_cast<long long>(buf.size()));
      FaultDelay();
      for (int i = 0; i < n; ++i) {
        int64_t reply;
        if (!ReadReply(&reply)) return false;
        if (out) out[i] = reply;
      }
      ClIn(op, 12LL * n);
      return true;
    };
    for (int a = 0;; ++a) {
      if (attempt(FaultNext())) return n;
      if (a >= retries)
        return stale ? kStaleIncarnationReply : -1;
      if (!Reconnect(a) && stale) return kStaleIncarnationReply;
    }
  }
};

}  // namespace

// Apply SO_SNDBUF/SO_RCVBUF when requested (0 keeps the OS default). Set on
// the LISTEN socket so accepted connections inherit it; on client sockets
// before connect so the window scale is negotiated with it in effect.
static void SetSockBuf(int fd, int bytes) {
  if (bytes <= 0) return;
  ::setsockopt(fd, SOL_SOCKET, SO_SNDBUF, &bytes, sizeof(bytes));
  ::setsockopt(fd, SOL_SOCKET, SO_RCVBUF, &bytes, sizeof(bytes));
}

namespace {

// Dial + TCP_NODELAY + mutual HMAC handshake; -1 on any failure. The one
// connect path shared by first connects and transparent reconnects, so a
// rebuilt stream is exactly as authenticated as the original.
// Dial-failure classification (r20): the quorum layer must distinguish
// DEFINITIVE death evidence from can't-tell unreachability — they move a
// replica target to different states (down vs suspect; see ReplTarget).
constexpr int kDialOk = 0;
constexpr int kDialRefused = 1;    // ECONNREFUSED: host up, listener gone
constexpr int kDialPartition = 2;  // injected cut (or unreachable route)
constexpr int kDialOther = 3;

int DialAndHandshake(const std::string& host, int port,
                     const std::string& secret, int sockbuf,
                     int part_group = -1, int* why = nullptr) {
  if (why) *why = kDialOther;
  if (PartitionCutFor(part_group, port)) {
    if (why) *why = kDialPartition;
    return -1;
  }
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  SetSockBuf(fd, sockbuf);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1 ||
      ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0) {
    if (why)
      *why = errno == ECONNREFUSED
                 ? kDialRefused
                 : (errno == EHOSTUNREACH || errno == ENETUNREACH ||
                            errno == ETIMEDOUT
                        ? kDialPartition
                        : kDialOther);
    ::close(fd);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  if (!ControlClient::Handshake(fd, secret)) {
    ::close(fd);
    return -1;
  }
  if (why) *why = kDialOk;
  return fd;
}

bool ControlClient::Reconnect(int attempt) {
  if (retries <= 0 || host.empty()) return false;
  if (fd >= 0) {
    ::close(fd);
    fd = -1;
  }
  long long ms = static_cast<long long>(backoff_ms)
                 << (attempt < 6 ? attempt : 6);
  if (ms > 2000) ms = 2000;
  if (ms > 0) std::this_thread::sleep_for(std::chrono::milliseconds(ms));
  g_cl_redial_attempts.fetch_add(1, std::memory_order_relaxed);
  FlightRec(kFlightRedialAttempt, attempt, 0);
  // Failover policy: a redial always tries the primary first (a transient
  // wire drop with the primary alive must never trigger failover — the
  // fresh dial succeeds and the op retries in place). Only when the
  // primary's dial has failed on TWO consecutive attempts — one refused
  // dial can be a connect-storm backlog overflow on a perfectly live
  // server, two spanning a backoff interval mean its listener is gone —
  // does the attempt fall through to the ring successor, and the
  // redirect then STICKS: the rejoin path hands out fresh clients for a
  // revived shard, so a redirected client never flaps back mid-stream
  // (flapping would tear the kSeqPre dedup continuity that keeps
  // failover retries exactly-once).
  // (r20) fo_chain generalizes the single successor to the R-1 replicas
  // of the primary's keyspace, in ring order: the walk starts at the
  // sticky position and only ever moves FORWARD past dead replicas (a
  // walk-back would tear the kSeqPre dedup continuity exactly like
  // flapping to a revived primary would).
  const int g = EffGroup();
  int cur = fo_active.load(std::memory_order_relaxed);  // 0 = primary
  int nfd = -1;
  int landed = cur;
  int landed_port = 0;
  if (cur == 0) {
    nfd = DialAndHandshake(host, port, secret, sockbuf, g);
    landed_port = port;
  } else if (cur <= static_cast<int>(fo_chain.size())) {
    nfd = DialAndHandshake(fo_chain[cur - 1].first, fo_chain[cur - 1].second,
                           secret, sockbuf, g);
    landed_port = fo_chain[cur - 1].second;
  }
  if (nfd < 0 && attempt >= 1) {
    for (int k = cur == 0 ? 1 : cur + 1;
         k <= static_cast<int>(fo_chain.size()) && nfd < 0; ++k) {
      nfd = DialAndHandshake(fo_chain[k - 1].first, fo_chain[k - 1].second,
                             secret, sockbuf, g);
      if (nfd >= 0) {
        landed = k;
        landed_port = fo_chain[k - 1].second;
      }
    }
  }
  if (nfd < 0) return false;
  fd = nfd;
  cur_port = landed_port;
  if (landed != cur) {
    fo_active.store(landed, std::memory_order_relaxed);
    FlightRec(kFlightFailover, attempt, 0);
  }
  g_cl_redials.fetch_add(1, std::memory_order_relaxed);
  FlightRec(kFlightRedial, attempt, 0);
  // A rebuilt stream must re-register its incarnation before any op rides
  // it — an unregistered reconnect would dodge the server's fence. A stale
  // verdict here latches `stale` and fails the reconnect: the caller's op
  // then returns kStaleIncarnationReply instead of retrying forever.
  if (incarnation >= 0 && SendAttach() != 1) {
    ::close(fd);
    fd = -1;
    return false;
  }
  return true;
}

// The WAL replicator: one thread per server, draining the ordered record
// queue to the ring successor in batches (group commit — concurrent
// handlers' ack waits overlap one inter-shard round-trip). The kReplApply
// batch rides the replicator client's own kSeqPre dedup, so inter-shard
// wire drops cannot double-apply a record. A send failure degrades the
// plane (records dropped, waiters woken) until the next kSnapshot cut
// re-arms it — never a silent mid-stream gap.
// Shared by both replicator modes: build the kReplApply batch frames for
// `batch` and ship them over `cl`. Returns true when every record acked.
static bool ShipReplBatch(
    ControlClient* cl,
    const std::vector<std::shared_ptr<const ReplRecord>>& batch) {
  const int n = static_cast<int>(batch.size());
  std::string keys;
  std::vector<std::string> bodies(static_cast<size_t>(n));
  std::vector<const void*> ptrs(static_cast<size_t>(n));
  std::vector<int64_t> lens(static_cast<size_t>(n));
  std::vector<int64_t> args(static_cast<size_t>(n));
  std::vector<int64_t> out(static_cast<size_t>(n));
  for (int i = 0; i < n; ++i) {
    const ReplRecord& r = *batch[static_cast<size_t>(i)];
    // The frame keys stay EMPTY ('\n' separators only): the record
    // key rides the body, length-prefixed, because the multi-op key
    // string splits on '\n' and control-plane keys embed
    // user-derived names that may contain one — a newline key would
    // shift every later record in the batch onto the wrong key.
    if (i) keys.push_back('\n');
    std::string& b = bodies[static_cast<size_t>(i)];
    b.reserve(kReplHdr + 2 + r.key.size() + r.data.size());
    b.push_back(static_cast<char>(r.op));
    b.push_back(static_cast<char>(r.record_reply));
    b.append(reinterpret_cast<const char*>(&r.rank), 4);
    b.append(reinterpret_cast<const char*>(&r.cid), 8);
    b.append(reinterpret_cast<const char*>(&r.cseq), 8);
    b.append(reinterpret_cast<const char*>(&r.cidx), 4);
    b.append(reinterpret_cast<const char*>(&r.arg), 8);
    b.append(reinterpret_cast<const char*>(&r.reply), 8);
    uint16_t kl = static_cast<uint16_t>(r.key.size());
    b.append(reinterpret_cast<const char*>(&kl), 2);
    b.append(r.key);
    b.append(r.data);
    ptrs[static_cast<size_t>(i)] = b.data();
    lens[static_cast<size_t>(i)] = static_cast<int64_t>(b.size());
    args[static_cast<size_t>(i)] = static_cast<int64_t>(r.seq);
  }
  return cl->CallBytesMultiOutV(kReplApply, keys.c_str(), ptrs.data(),
                                lens.data(), args.data(), out.data(),
                                n) == n;
}

// Build a replicator client around an already-dialed fd. `rank` identifies
// the SOURCE stream to the receiver (-2 chain mode; -(100+idx) quorum
// mode) and `group` pins the partition group of the OWNING server.
static ControlClient* MakeReplClient(int nfd, const std::string& host,
                                     int port, const std::string& secret,
                                     int rank, int group) {
  auto* cl = new ControlClient();
  cl->fd = nfd;
  cl->rank = rank;
  cl->host = host;
  cl->port = port;
  cl->cur_port = port;
  cl->part_group = group;
  cl->secret = secret;
  cl->retries = static_cast<int>(EnvInt("BLUEFOG_CP_RETRIES", 3));
  if (cl->retries < 0) cl->retries = 0;
  cl->backoff_ms = static_cast<int>(EnvInt("BLUEFOG_CP_BACKOFF_MS", 50));
  if (cl->backoff_ms < 0) cl->backoff_ms = 0;
  uint8_t idb[8];
  if (RandomBytes(idb, 8)) {
    std::memcpy(&cl->cid, idb, 8);
  } else {
    static std::atomic<uint64_t> ctr{1};
    cl->cid = (static_cast<uint64_t>(::getpid()) << 32) ^ ctr.fetch_add(1);
  }
  return cl;
}

void ControlServer::ReplLoop() {
  ControlClient* cl = nullptr;
  std::vector<std::shared_ptr<const ReplRecord>> batch;
  for (;;) {
    batch.clear();
    {
      std::unique_lock<std::mutex> lk(mu);
      while (!stopping.load() && repl_q.empty())
        BoundedWaitMs(repl_cv, lk, 200);
      if (stopping.load()) break;
      batch.assign(repl_q.begin(), repl_q.end());
      repl_q.clear();
    }
    if (cl == nullptr) {
      int nfd = DialAndHandshake(repl_host, repl_port, secret, 0,
                                 PartGroupOfPort(listen_port));
      if (nfd >= 0)
        cl = MakeReplClient(nfd, repl_host, repl_port, secret, -2,
                            PartGroupOfPort(listen_port));
    }
    bool ok = cl != nullptr && ShipReplBatch(cl, batch);
    {
      std::lock_guard<std::mutex> lk(mu);
      if (ok) {
        wal_acked = batch.back()->seq;
      } else {
        wal_dropped.fetch_add(static_cast<long long>(batch.size()),
                              std::memory_order_relaxed);
        ReplDegradeLocked();
      }
      repl_cv.notify_all();
    }
    if (!ok && cl != nullptr) {
      ::close(cl->fd);
      delete cl;
      cl = nullptr;
    }
  }
  if (cl != nullptr) {
    ::close(cl->fd);
    delete cl;
  }
}

// Quorum-mode sender: one per target, all draining the shared WAL deque by
// per-target cursor. Group commit is preserved per stream (a batch is
// whatever accumulated since the last send), and wal_acked — the QUORUM
// watermark — advances via ReplRecomputeAckedLocked as per-target acks
// land, so concurrent handlers' commit waits overlap one inter-shard
// round-trip exactly as in chain mode. Failure classification drives the
// state machine: refused dials demote (definitive), partition/timeout
// failures suspend-and-retry with the queue share retained (the peer may
// be alive across the cut; heal resumes the stream from the cursor with
// no gap and no rejoin).
void ControlServer::ReplTargetLoop(ReplTarget* t) {
  ControlClient* cl = nullptr;
  auto drop_cl = [&] {
    if (cl) {
      ::close(cl->fd);
      delete cl;
      cl = nullptr;
    }
  };
  std::vector<std::shared_ptr<const ReplRecord>> batch;
  for (;;) {
    batch.clear();
    bool probe = false;  // suspect + idle: dial to detect heal
    {
      std::unique_lock<std::mutex> lk(mu);
      for (;;) {
        if (stopping.load()) {
          lk.unlock();
          drop_cl();
          return;
        }
        if (t->state == kTgtDown) {
          // parked until the peer's rejoin kSnapshot pull re-arms us
          if (cl) {
            lk.unlock();
            drop_cl();
            lk.lock();
            continue;
          }
          BoundedWaitMs(repl_cv, lk, 200);
          continue;
        }
        if (t->state == kTgtSuspect && cl != nullptr) {
          // the gate's partition sensing marked us suspect while the old
          // connection still stands — drop it (the cut would sever it at
          // next use anyway) so the probe dial below owns heal detection
          lk.unlock();
          drop_cl();
          lk.lock();
          continue;
        }
        if (!repl_q.empty() && repl_q.back()->seq > t->cursor) {
          for (const auto& r : repl_q)
            if (r->seq > t->cursor) batch.push_back(r);
          t->cursor = repl_q.back()->seq;
          break;
        }
        if (t->state == kTgtSuspect && cl == nullptr) {
          probe = true;
          break;
        }
        BoundedWaitMs(repl_cv, lk, 200);
      }
    }
    if (cl == nullptr) {
      const int group = PartGroupOfPort(listen_port);
      int why = kDialOther;
      int nfd = DialAndHandshake(t->host, t->port, secret, 0, group, &why);
      if (nfd >= 0) {
        cl = MakeReplClient(nfd, t->host, t->port, secret,
                            -(100 + shard_idx), group);
        std::lock_guard<std::mutex> lk(mu);
        t->refused = 0;
        if (t->state == kTgtSuspect) {
          t->state = kTgtLive;  // healed: stream resumes from the cursor
          repl_cv.notify_all();
        }
      } else {
        {
          std::lock_guard<std::mutex> lk(mu);
          if (!batch.empty())
            t->cursor = batch.front()->seq - 1;  // resend after recovery
          if (why == kDialRefused && ++t->refused >= 2) {
            // two refused dials spanning a backoff: the listener is gone
            ReplDemoteLocked(t);
          } else if (t->state == kTgtLive) {
            t->state = kTgtSuspect;
            ReplRecomputeAckedLocked();
            repl_cv.notify_all();
          }
        }
        // pace the redial; bounded so stop() joins promptly
        std::this_thread::sleep_for(std::chrono::milliseconds(200));
        continue;
      }
    }
    if (probe || batch.empty()) continue;
    bool ok = ShipReplBatch(cl, batch);
    {
      std::lock_guard<std::mutex> lk(mu);
      if (ok) {
        if (batch.back()->seq > t->acked) t->acked = batch.back()->seq;
        t->refused = 0;
        quorum_acks.fetch_add(1, std::memory_order_relaxed);
        if (t->state == kTgtSuspect) t->state = kTgtLive;
        ReplRecomputeAckedLocked();
        ReplTrimLocked();
        repl_cv.notify_all();
      } else {
        // Established-connection failure: reset/timeout/injected cut —
        // non-definitive. Rewind the cursor (the un-acked batch is still
        // in the deque: trim only advances past ALL non-down acks) and
        // let the dial path classify on the next pass.
        t->cursor = batch.front()->seq - 1;
        if (t->state == kTgtLive) {
          t->state = kTgtSuspect;
          ReplRecomputeAckedLocked();
        }
        repl_cv.notify_all();
      }
    }
    if (!ok) drop_cl();
  }
}

}  // namespace

extern "C" {

// Arm / disarm the deterministic fault injector (BLUEFOG_CP_FAULT; see
// runtime/native.py for the spec grammar). drop_after <= 0 disarms drops;
// counters reset on every call so a test's drop points are reproducible.
void bf_cp_fault(long long drop_after, int delay_ms, int trunc,
                 long long seed) {
  g_fault_drop_after.store(drop_after);
  g_fault_delay_ms.store(delay_ms);
  g_fault_trunc.store(trunc);
  g_fault_seed.store(seed);
  g_fault_ops.store(0);
  g_fault_drops.store(0);
}

long long bf_cp_fault_drops(void) { return g_fault_drops.load(); }
long long bf_cp_fault_ops(void) { return g_fault_ops.load(); }

// Arm the deterministic partition injector (BLUEFOG_CP_FAULT partition=
// grammar; see runtime/native.py). port_groups maps listener ports to
// sides: "port:group,port:group,...". self_group is the side THIS
// process's ordinary clients sit on (-1 = ungrouped: only server-side
// gates and group-bound replicator clients enforce the cut). The cut
// activates start_after_s seconds from now (<= 0: immediately) and heals
// itself heal_after_s seconds after activation (<= 0: only on an explicit
// heal/disarm). Re-arming resets the cut counter.
void bf_cp_partition(int self_group, const char* port_groups,
                     double start_after_s, double heal_after_s) {
  long long now = std::chrono::duration_cast<std::chrono::microseconds>(
                      std::chrono::system_clock::now().time_since_epoch())
                      .count();
  long long base =
      now + (start_after_s > 0
                 ? static_cast<long long>(start_after_s * 1e6)
                 : 0);
  {
    std::lock_guard<std::mutex> g(g_part_mu);
    g_part_port_group.clear();
    std::string s = port_groups ? port_groups : "";
    size_t pos = 0;
    while (pos < s.size()) {
      size_t end = s.find(',', pos);
      if (end == std::string::npos) end = s.size();
      std::string part = s.substr(pos, end - pos);
      pos = end + 1;
      if (part.empty()) continue;
      size_t c = part.find(':');
      if (c == std::string::npos) continue;
      int port = std::atoi(part.substr(0, c).c_str());
      int grp = std::atoi(part.substr(c + 1).c_str());
      if (port > 0) g_part_port_group[port] = grp;
    }
    g_part_self_group = self_group;
  }
  g_part_start_us.store(start_after_s > 0 ? base : 0);
  g_part_heal_us.store(
      heal_after_s > 0 ? base + static_cast<long long>(heal_after_s * 1e6)
                       : 0);
  g_part_cuts.store(0);
  g_part_armed.store(1);
}

// Heal the armed partition now (idempotent; the arm stays so the cut
// counter and the healed state remain observable).
void bf_cp_partition_heal(void) {
  if (!g_part_armed.load()) return;
  long long now = std::chrono::duration_cast<std::chrono::microseconds>(
                      std::chrono::system_clock::now().time_since_epoch())
                      .count();
  g_part_heal_us.store(now);
}

void bf_cp_partition_disarm(void) {
  g_part_armed.store(0);
  g_part_start_us.store(0);
  g_part_heal_us.store(0);
  std::lock_guard<std::mutex> g(g_part_mu);
  g_part_port_group.clear();
  g_part_self_group = -1;
}

int bf_cp_partition_active(void) { return PartitionActiveNow() ? 1 : 0; }
long long bf_cp_partition_cuts(void) { return g_part_cuts.load(); }

// Bind one CLIENT handle to a partition side, overriding the process
// default — an in-process multi-server test (or the soak's worker pool)
// places each client on the side of the shard it represents.
void bf_cp_client_set_group(void* h, int group) {
  auto* cl = static_cast<ControlClient*>(h);
  std::lock_guard<std::mutex> lk(cl->mu);
  cl->part_group = group;
}

// rejoin_pending != 0 arms the rejoin gate ATOMICALLY with the bind: the
// accept loop runs from construction, and a restarted shard must not
// serve a single op against its empty store before the snapshot lands
// and its own WAL stream is armed (bf_cp_server_set_successor opens it).
void* bf_cp_serve_auth3(int port, int world, const char* secret,
                        int64_t max_mailbox_bytes, int sockbuf_bytes,
                        int rejoin_pending) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  SetSockBuf(fd, sockbuf_bytes);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  // Deep accept backlog (clamped to somaxconn): the churn soak's
  // thousands of raw clients connect in a storm, and an overflowing
  // backlog refuses dials — which a failover-armed client would read as
  // the primary's death. The kernel clamp keeps this safe everywhere.
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) < 0 ||
      ::listen(fd, 4096) < 0) {
    ::close(fd);
    return nullptr;
  }
  auto* srv = new ControlServer();
  srv->listen_fd = fd;
  // The bound port (resolved for port 0) keys this server's partition
  // group: QuorumOkLocked and the replicator threads look it up to decide
  // which side of an armed cut this server sits on.
  {
    sockaddr_in bound{};
    socklen_t blen = sizeof(bound);
    if (::getsockname(fd, reinterpret_cast<sockaddr*>(&bound), &blen) == 0)
      srv->listen_port = ntohs(bound.sin_port);
  }
  srv->world = world;
  srv->secret = secret ? secret : "";
  srv->max_box_bytes = max_mailbox_bytes;
  srv->rejoin_pending = rejoin_pending != 0;
  // Leases/deadlines for the blocking primitives (docs/fault_tolerance.md):
  // bound every server-side wait so a dead peer can never park a handler —
  // or a healthy client — forever.
  srv->lock_lease_sec = EnvSeconds("BLUEFOG_CP_LOCK_LEASE", 60.0);
  srv->barrier_timeout_sec = EnvSeconds("BLUEFOG_CP_BARRIER_TIMEOUT", 600.0);
  srv->accept_thread = std::thread([srv] { srv->AcceptLoop(); });
  return srv;
}

void* bf_cp_serve_auth2(int port, int world, const char* secret,
                        int64_t max_mailbox_bytes, int sockbuf_bytes) {
  return bf_cp_serve_auth3(port, world, secret, max_mailbox_bytes,
                           sockbuf_bytes, 0);
}

void* bf_cp_serve_auth(int port, int world, const char* secret,
                       int64_t max_mailbox_bytes) {
  return bf_cp_serve_auth2(port, world, secret, max_mailbox_bytes, 0);
}

void* bf_cp_serve(int port, int world) {
  return bf_cp_serve_auth(port, world, "", 0);
}

int bf_cp_server_port(void* handle) {
  auto* srv = static_cast<ControlServer*>(handle);
  sockaddr_in addr{};
  socklen_t len = sizeof(addr);
  if (::getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr),
                    &len) < 0)
    return -1;
  return ntohs(addr.sin_port);
}

void bf_cp_server_stop(void* handle) {
  auto* srv = static_cast<ControlServer*>(handle);
  srv->stopping.store(true);
  srv->cv.notify_all();
  srv->repl_cv.notify_all();
  ::shutdown(srv->listen_fd, SHUT_RDWR);
  ::close(srv->listen_fd);
  srv->accept_thread.join();
  if (srv->repl_thread.joinable()) srv->repl_thread.join();
  // Quorum-mode per-target streams: the vector is append-only after
  // set_successors, so iterating without the mutex is safe here.
  for (auto& t : srv->repl_targets)
    if (t->thread.joinable()) t->thread.join();
  // Wake every blocked handler (recv returns 0 after shutdown; cv waiters
  // see `stopping`), then wait for the detached handlers to drain so the
  // server is quiescent when stop() returns. Freeing is NOT done here:
  // the owner merely drops its reference, and the last thread out —
  // usually this one, but a handler wedged past the grace (e.g. mid-write
  // to a jammed peer) finishes the job later — deletes the server. The
  // old direct `delete srv` could destroy the mutex while the final
  // handler was still inside its last pthread_mutex_unlock (caught by
  // `make tsan`); the refcount hand-off cannot.
  {
    std::unique_lock<std::mutex> lk(srv->mu);
    for (int fd : srv->handler_fds) ::shutdown(fd, SHUT_RDWR);
    srv->cv.wait_until(lk, std::chrono::system_clock::now() +
                               std::chrono::seconds(10),
                       [&] { return srv->active_handlers == 0; });
  }
  srv->Unref();
}

// Fault-injection kill hook: hard-drop every live client connection (the
// server keeps running). Clients observe exactly what a network partition /
// peer restart looks like and must transparently reconnect.
void bf_cp_server_drop_conns(void* handle) {
  auto* srv = static_cast<ControlServer*>(handle);
  std::lock_guard<std::mutex> lk(srv->mu);
  for (int fd : srv->handler_fds) ::shutdown(fd, SHUT_RDWR);
}

void* bf_cp_connect_auth2(const char* host, int port, int rank,
                          const char* secret, int sockbuf_bytes) {
  std::string h = host ? host : "";
  std::string s = secret ? secret : "";
  int fd = DialAndHandshake(h, port, s, sockbuf_bytes);
  if (fd < 0) return nullptr;
  auto* cl = new ControlClient();
  cl->fd = fd;
  cl->rank = rank;
  cl->host = h;
  cl->port = port;
  cl->cur_port = port;
  cl->secret = s;
  cl->sockbuf = sockbuf_bytes;
  cl->retries = static_cast<int>(EnvInt("BLUEFOG_CP_RETRIES", 3));
  if (cl->retries < 0) cl->retries = 0;
  cl->backoff_ms = static_cast<int>(EnvInt("BLUEFOG_CP_BACKOFF_MS", 50));
  if (cl->backoff_ms < 0) cl->backoff_ms = 0;
  // Stable dedup identity: survives reconnects for this client object.
  // urandom keeps ids from colliding across processes; the fallback mixes
  // pid + a process-local counter (collisions would only weaken dedup
  // between two clients of one buggy entropy-less host).
  uint8_t idb[8];
  if (RandomBytes(idb, 8)) {
    std::memcpy(&cl->cid, idb, 8);
  } else {
    static std::atomic<uint64_t> ctr{1};
    cl->cid = (static_cast<uint64_t>(::getpid()) << 32) ^ ctr.fetch_add(1);
  }
  return cl;
}

void* bf_cp_connect_auth(const char* host, int port, int rank,
                         const char* secret) {
  return bf_cp_connect_auth2(host, port, rank, secret, 0);
}

void* bf_cp_connect(const char* host, int port, int rank) {
  return bf_cp_connect_auth(host, port, rank, "");
}

// Register this client's (rank, incarnation) with the server (elastic
// membership fencing). 0 = registered; -4 = superseded (the caller is a
// zombie of a restarted rank — every later op on this client fails fast
// with the same code); -1 = wire failure. Re-sent automatically on every
// transparent reconnect.
int64_t bf_cp_attach(void* h, int64_t incarnation) {
  auto* cl = static_cast<ControlClient*>(h);
  std::lock_guard<std::mutex> lk(cl->mu);
  cl->incarnation = incarnation;
  cl->stale = false;
  int64_t r = cl->SendAttach();
  if (r == kStaleIncarnationReply) return r;
  if (r >= 0) return 0;
  for (int a = 0; a < cl->retries; ++a) {
    if (cl->Reconnect(a)) return 0;  // Reconnect re-attached successfully
    if (cl->stale) return kStaleIncarnationReply;
  }
  return -1;
}

// 1 once the server has fenced this client as a superseded incarnation.
// Lets Python distinguish a genuine -4 scalar value from the typed status.
int bf_cp_is_stale(void* h) {
  auto* cl = static_cast<ControlClient*>(h);
  std::lock_guard<std::mutex> lk(cl->mu);
  return cl->stale ? 1 : 0;
}

// -- WAL replication / rejoin (r16 durable control plane) -------------------

// Configure this server's ring successor and start the replicator thread.
// nshards/idx give the server its position in the ring (scoped incarnation
// GC + the kSnapshot filter). Reads BLUEFOG_CP_REPL_TIMEOUT (handler ack
// wait, seconds) and BLUEFOG_CP_WAL_DEPTH (queue cap, records) from the
// environment at call time. 0 on success, -1 when already configured.
int bf_cp_server_set_successor(void* h, const char* host, int port,
                               int nshards, int idx) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  if (srv->repl_cfg) return -1;
  srv->repl_host = host ? host : "";
  srv->repl_port = port;
  srv->shard_count = nshards;
  srv->shard_idx = idx;
  srv->repl_wait_sec = EnvSeconds("BLUEFOG_CP_REPL_TIMEOUT", 30.0);
  long long depth = EnvInt("BLUEFOG_CP_WAL_DEPTH", 65536);
  srv->repl_depth = depth > 0 ? static_cast<size_t>(depth) : 65536;
  srv->repl_cfg = true;
  srv->repl_live = true;
  srv->rejoin_pending = false;  // gate opens: every op is replicated now
  // the ring position is known only now: derive which keyspaces this
  // shard already serves as failover primary (liveness flags may have
  // arrived in a rejoin snapshot or as early direct writes)
  srv->RecomputeFoKeyspacesLocked();
  srv->cv.notify_all();
  srv->repl_thread = std::thread([srv] { srv->ReplLoop(); });
  return 0;
}

// Quorum generalization (R >= 3): spec is "sidx:host:port;sidx:host:port;..."
// naming this shard's R-1 ring successors. One entry degenerates to the
// legacy chain above (same thread, same wire — R=2 stays byte-identical).
// Two or more arm quorum mode: a dedicated stream thread per target, and
// the commit rule becomes ack-from-ceil(R/2) replicas (self included)
// before the primary replies — see ReplRecomputeAckedLocked.
int bf_cp_server_set_successors(void* h, const char* spec, int nshards,
                                int idx) {
  // Parse outside the server lock; reject malformed specs before arming.
  struct Tgt {
    int idx;
    std::string host;
    int port;
  };
  std::vector<Tgt> tgts;
  std::string s = spec ? spec : "";
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(';', pos);
    if (end == std::string::npos) end = s.size();
    std::string part = s.substr(pos, end - pos);
    pos = end + 1;
    if (part.empty()) continue;
    size_t c1 = part.find(':');
    size_t c2 = part.rfind(':');
    if (c1 == std::string::npos || c2 == c1) return -2;
    Tgt t;
    t.idx = std::atoi(part.substr(0, c1).c_str());
    t.host = part.substr(c1 + 1, c2 - c1 - 1);
    t.port = std::atoi(part.substr(c2 + 1).c_str());
    if (t.host.empty() || t.port <= 0 || t.idx < 0) return -2;
    tgts.push_back(std::move(t));
  }
  if (tgts.empty()) return -2;
  if (tgts.size() == 1)
    return bf_cp_server_set_successor(h, tgts[0].host.c_str(), tgts[0].port,
                                      nshards, idx);
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  if (srv->repl_cfg) return -1;
  srv->shard_count = nshards;
  srv->shard_idx = idx;
  srv->repl_wait_sec = EnvSeconds("BLUEFOG_CP_REPL_TIMEOUT", 30.0);
  long long depth = EnvInt("BLUEFOG_CP_WAL_DEPTH", 65536);
  srv->repl_depth = depth > 0 ? static_cast<size_t>(depth) : 65536;
  srv->quorum_mode = true;
  // R = targets + 1 copies (self is one). Commit waits for ceil(R/2)
  // REMOTE acks: at R=3 that is both successors, which is what makes an
  // even split (2|2 at n=4) leave BOTH sides below quorum instead of
  // minting two primaries. Definitive target deaths (down/dead-flagged)
  // subtract from this at commit time — see EffectiveNeededLocked.
  const int r = static_cast<int>(tgts.size()) + 1;
  srv->needed_base = (r + 1) / 2;
  if (srv->needed_base < 1) srv->needed_base = 1;
  srv->repl_cfg = true;
  srv->repl_live = true;
  srv->rejoin_pending = false;
  srv->RecomputeFoKeyspacesLocked();
  srv->cv.notify_all();
  for (const Tgt& t : tgts) {
    auto rt = std::make_unique<ReplTarget>();
    rt->idx = t.idx;
    rt->host = t.host;
    rt->port = t.port;
    ReplTarget* raw = rt.get();
    srv->repl_targets.push_back(std::move(rt));
    raw->thread = std::thread([srv, raw] { srv->ReplTargetLoop(raw); });
  }
  return 0;
}

// Arm the rejoin gate: incoming kReplApply records park until the
// catch-up completes (bf_cp_server_set_successor opens it). Call BEFORE
// pulling the snapshots — the ring predecessor re-arms its stream the
// moment it serves the receiver-flagged pull, and records applied
// before the load would interleave out of order.
void bf_cp_server_set_rejoin_pending(void* h) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  srv->rejoin_pending = true;
}

// Drop the whole store and re-arm the rejoin gate — the guarded in-place
// self-rejoin a shard performs after surviving on the minority side of a
// healed partition: its local state may have diverged from the quorum
// (acked ops the majority re-routed and re-decided), so it rebuilds from
// replica snapshots exactly like a restarted process would, without
// losing its listener or its clients' TCP endpoints. Barrier state is
// deliberately kept: live waiters hold handler threads, and barrier
// generations are not part of the replicated keyspace.
void bf_cp_server_reset_store(void* h) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  srv->kv.clear();
  srv->mailbox.clear();
  srv->mailbox_origin.clear();
  srv->box_bytes.clear();
  srv->bytes_kv.clear();
  srv->put_staging.clear();
  srv->locks.clear();
  srv->dedup.clear();
  srv->rank_cids.clear();
  srv->incarnations.clear();
  srv->repl_fence.clear();
  srv->fo_keyspaces.clear();
  srv->rejoin_pending = true;
  srv->cv.notify_all();
}

// Reopen the rejoin gate after an IN-PLACE self-rejoin (reset_store +
// snapshot catch-up on a server whose successor streams were already
// armed): set_successor(s) is one-shot, so the legacy gate-open path
// never runs again for this process.
void bf_cp_server_rejoin_done(void* h) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  srv->rejoin_pending = false;
  srv->RecomputeFoKeyspacesLocked();
  srv->cv.notify_all();
}

// Pull a point-in-time snapshot over a CLIENT handle (kSnapshot). filter
// packs (nshards << 32 | idx) to select one keyspace (0 = everything),
// plus bit 62 — set ONLY by the rejoin protocol when the caller is the
// serving shard's stream receiver, re-arming its degraded replicator
// from this exact cut. The malloc'd blob (freed with bf_cp_free) starts
// with the serving shard's WAL fence and the resume position it holds
// for its predecessor's stream. Returns blob length, or a negative
// status.
int64_t bf_cp_snapshot(void* h, int64_t filter, void** out,
                       int64_t* out_len) {
  return static_cast<ControlClient*>(h)->CallBytes(kSnapshot, "", out,
                                                   out_len, filter);
}

// Load a snapshot blob into THIS server's store (shard rejoin catch-up;
// call before announcing the shard alive). set_fence != 0 adopts the
// blob's WAL fence so replication records already folded into the
// snapshot are skipped when the predecessor's stream resumes — only
// meaningful when the SERVING shard is this server's ring predecessor
// (the fence is a position in ITS WAL). adopt_wal != 0 resumes THIS
// server's own WAL numbering from the fence the serving shard holds
// against our stream — only meaningful when the serving shard is our
// ring SUCCESSOR (our stream's receiver): restarting the numbering at
// zero would put every post-rejoin record at or below the receiver's
// stale fence, silently dropped-and-acked — lost on our next death.
// Returns the number of records applied, or -1 on a malformed blob.
// src_idx names WHICH incoming stream the blob's fence belongs to: the
// serving shard's ring index under quorum replication (its stream frames
// carry rank -(100+src_idx)), or -2 for the legacy chain stream. The
// repl_fence map is keyed the same way, so a rejoining shard can load one
// snapshot per predecessor and fence each stream independently.
long long bf_cp_server_load_snapshot2(void* h, const void* data,
                                      int64_t len, int set_fence,
                                      int adopt_wal, int src_idx) {
  auto* srv = static_cast<ControlServer*>(h);
  const char* p = static_cast<const char*>(data);
  if (len < 16) return -1;
  uint64_t fence, resume;
  std::memcpy(&fence, p, 8);
  std::memcpy(&resume, p + 8, 8);
  int64_t off = 16;
  long long applied = 0;
  std::lock_guard<std::mutex> lk(srv->mu);
  while (off < len) {
    if (off + 1 + 2 > len) return -1;
    uint8_t type = static_cast<uint8_t>(p[off]);
    uint16_t kl;
    std::memcpy(&kl, p + off + 1, 2);
    off += 3;
    if (off + kl + 8 + 4 > len) return -1;
    std::string key(p + off, kl);
    off += kl;
    int64_t a;
    std::memcpy(&a, p + off, 8);
    off += 8;
    uint32_t pl;
    std::memcpy(&pl, p + off, 4);
    off += 4;
    if (off + static_cast<int64_t>(pl) > len) return -1;
    switch (type) {
      case 0:
        srv->kv[key] = a;
        break;
      case 1:
        srv->mailbox[key].emplace_back(p + off, pl);
        srv->mailbox_origin[key].push_back(static_cast<int8_t>(a));
        srv->box_bytes[key] += static_cast<int64_t>(pl);
        break;
      case 2: {
        LockInfo& L = srv->locks[key];
        L.rank = static_cast<int>(a);
        L.fd = -1;  // holder's connection lived on the dead shard:
        if (srv->lock_lease_sec > 0)  // the lease is the backstop
          L.expiry = std::chrono::steady_clock::now() +
              std::chrono::duration_cast<
                  std::chrono::steady_clock::duration>(
                  std::chrono::duration<double>(srv->lock_lease_sec));
        break;
      }
      case 3:
        srv->incarnations[std::atoi(key.c_str())] = a;
        break;
      case 4:  // raw byte values (published window rows ride here)
        srv->bytes_kv[key] =
            std::make_shared<const std::string>(p + off, pl);
        break;
      default:
        break;  // forward compatibility: skip unknown record types
    }
    off += pl;
    ++applied;
  }
  if (set_fence) {
    uint64_t& f = srv->repl_fence[src_idx];
    if (fence > f) f = fence;  // newest fence wins across multi-source loads
  }
  if (adopt_wal) {
    srv->wal_seq = resume;
    srv->wal_acked = resume;
    srv->wal_dropped_below = resume;
  }
  // liveness flags may ride the snapshot KV records; fo_keyspaces is
  // re-derived now and again at set_successor (when the ring position
  // becomes known)
  srv->RecomputeFoKeyspacesLocked();
  // NOTE: the rejoin gate stays CLOSED — it opens when the successor
  // stream is armed (bf_cp_server_set_successor). Serving ops between
  // the load and the arm would ack them unreplicated: a router that
  // dialed this endpoint early (churned clients attach continuously)
  // would split the store from the rest of the ring.
  srv->cv.notify_all();
  return applied;
}

long long bf_cp_server_load_snapshot(void* h, const void* data,
                                     int64_t len, int set_fence,
                                     int adopt_wal) {
  return bf_cp_server_load_snapshot2(h, data, len, set_fence, adopt_wal, -2);
}

// Client-side failover redirect: name the ring successor this client may
// stick to when its primary stops answering (see ControlClient::Reconnect).
void bf_cp_set_failover(void* h, const char* host, int port) {
  auto* cl = static_cast<ControlClient*>(h);
  std::lock_guard<std::mutex> lk(cl->mu);
  cl->fo_host = host ? host : "";
  cl->fo_port = port;
  cl->fo_chain.clear();
  cl->fo_chain.emplace_back(host ? host : "", port);
}

// Multi-hop failover chain (quorum replication, R >= 3): spec is
// "host:port,host:port,..." naming the ring successors in walk order.
// Reconnect advances past runs of consecutive dead shards, sticking to
// the first chain entry that answers (see ControlClient::Reconnect).
void bf_cp_set_failover2(void* h, const char* spec) {
  auto* cl = static_cast<ControlClient*>(h);
  std::lock_guard<std::mutex> lk(cl->mu);
  cl->fo_chain.clear();
  std::string s = spec ? spec : "";
  size_t pos = 0;
  while (pos < s.size()) {
    size_t end = s.find(',', pos);
    if (end == std::string::npos) end = s.size();
    std::string part = s.substr(pos, end - pos);
    pos = end + 1;
    if (part.empty()) continue;
    size_t c = part.rfind(':');
    if (c == std::string::npos) continue;
    std::string host = part.substr(0, c);
    int port = std::atoi(part.substr(c + 1).c_str());
    if (!host.empty() && port > 0) cl->fo_chain.emplace_back(host, port);
  }
  if (!cl->fo_chain.empty()) {
    cl->fo_host = cl->fo_chain[0].first;
    cl->fo_port = cl->fo_chain[0].second;
  }
}

// 1 once this client permanently redirected to its failover target — the
// router's health probe reads it lock-free (it must not contend with a
// blocking op holding the client mutex).
int bf_cp_failed_over(void* h) {
  return static_cast<ControlClient*>(h)->fo_active.load(
      std::memory_order_relaxed);
}

// -- server-side introspection (tests assert the GC left nothing behind) ----

long long bf_cp_server_dedup_entries(void* h) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  return static_cast<long long>(srv->dedup.size());
}

long long bf_cp_server_mailbox_from(void* h, int origin) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  long long n = 0;
  for (const auto& it : srv->mailbox_origin)
    for (int8_t o : it.second)
      if (o == static_cast<int8_t>(origin & 0x7F)) ++n;
  return n;
}

long long bf_cp_server_incarnation(void* h, int rank) {
  auto* srv = static_cast<ControlServer*>(h);
  std::lock_guard<std::mutex> lk(srv->mu);
  auto it = srv->incarnations.find(rank);
  return it == srv->incarnations.end() ? -1
                                       : static_cast<long long>(it->second);
}

// -- telemetry counter reads (r10 observability) ----------------------------
//
// Fixed layouts consumed by runtime/native.py (client_stats/server wrapper);
// both return the number of slots filled so Python can stay forward-
// compatible with a longer block.
//
// Client block: [0..31] per-op-class request counts, [32..63] request bytes,
// [64..95] reply bytes, [96] redials (successful reconnects), [97] redial
// attempts, [98] stale frames observed, [99] whole striped transfers.
int bf_cp_client_counters(long long* out, int n) {
  const int want = 3 * kOpSlots + 4;
  if (!out || n < want) return -1;
  for (int i = 0; i < kOpSlots; ++i) {
    out[i] = g_cl_ops[i].load(std::memory_order_relaxed);
    out[kOpSlots + i] = g_cl_bytes_out[i].load(std::memory_order_relaxed);
    out[2 * kOpSlots + i] = g_cl_bytes_in[i].load(std::memory_order_relaxed);
  }
  out[96] = g_cl_redials.load(std::memory_order_relaxed);
  out[97] = g_cl_redial_attempts.load(std::memory_order_relaxed);
  out[98] = g_cl_stale_frames.load(std::memory_order_relaxed);
  out[99] = g_cl_striped_xfers.load(std::memory_order_relaxed);
  return want;
}

// Transport flight ring readout (runtime/flight.py splices this into
// postmortem dumps): copies up to max_events events oldest -> newest, four
// int64 per event [wall_us, kind, a, b]; returns the count copied. Kinds
// mirror the kFlight* constants above (native.py keeps the name table).
int bf_flight_ring(long long* out, int max_events) {
  if (!out || max_events <= 0) return 0;
  std::lock_guard<std::mutex> g(g_flight_mu);
  long long count = g_flight_n < kFlightCap ? g_flight_n : kFlightCap;
  if (count > max_events) count = max_events;
  long long start = g_flight_n - count;
  for (long long j = 0; j < count; ++j) {
    const FlightEv& e = g_flight[(start + j) & (kFlightCap - 1)];
    out[j * 4] = e.t_us;
    out[j * 4 + 1] = e.kind;
    out[j * 4 + 2] = e.a;
    out[j * 4 + 3] = e.b;
  }
  return static_cast<int>(count);
}

// Server block: [0..31] per-op dispatch counts, [32] live connections,
// [33] queued mailbox records, [34] queued mailbox payload bytes,
// [35] locks currently held, [36] lock force-releases, [37] barrier
// withdrawals, [38] dedup replays served, [39] fenced (stale) ops,
// [40] scalar kv entries, [41] bytes slots, [42] bytes-slot payload bytes.
int bf_cp_server_counters(void* h, long long* out, int n) {
  if (!h) return -1;
  return static_cast<ControlServer*>(h)->FillCounters(out, n);
}

// Remote counter read over the wire (kStats): same block as
// bf_cp_server_counters, but fetched through a CLIENT handle — how an
// external actor (bfrun --status over --cp a,b,..., the soak harness)
// reads a shard server it does not own. Returns slots filled, or a
// negative status on wire failure / fenced client.
int bf_cp_remote_stats(void* h, long long* out, int n) {
  if (!out || n <= 0) return -1;
  void* payload = nullptr;
  int64_t plen = 0;
  int64_t r = static_cast<ControlClient*>(h)->CallBytes(
      kStats, "", &payload, &plen);
  if (r < 0) return static_cast<int>(r);
  int got = static_cast<int>(plen / 8);
  if (got > n) got = n;
  std::memcpy(out, payload, static_cast<size_t>(got) * 8);
  std::free(payload);
  return got;
}

int64_t bf_cp_barrier(void* h, const char* key) {
  return static_cast<ControlClient*>(h)->Call(kBarrier, key, 0);
}
int64_t bf_cp_lock(void* h, const char* key) {
  return static_cast<ControlClient*>(h)->Call(kLock, key, 0);
}
int64_t bf_cp_unlock(void* h, const char* key) {
  return static_cast<ControlClient*>(h)->Call(kUnlock, key, 0);
}
int64_t bf_cp_fetch_add(void* h, const char* key, int64_t delta) {
  return static_cast<ControlClient*>(h)->Call(kFetchAdd, key, delta);
}
int64_t bf_cp_put(void* h, const char* key, int64_t value) {
  return static_cast<ControlClient*>(h)->Call(kPut, key, value);
}
int64_t bf_cp_put_max(void* h, const char* key, int64_t value) {
  return static_cast<ControlClient*>(h)->Call(kPutMax, key, value);
}
int64_t bf_cp_get(void* h, const char* key) {
  return static_cast<ControlClient*>(h)->Call(kGet, key, 0);
}
int64_t bf_cp_append_bytes(void* h, const char* key, const void* data,
                           int64_t len) {
  return static_cast<ControlClient*>(h)->Call(
      kAppendBytes, key, len, data, static_cast<size_t>(len));
}
int64_t bf_cp_take_bytes(void* h, const char* key, void** out,
                         int64_t* out_len) {
  return static_cast<ControlClient*>(h)->CallBytes(kTakeBytes, key, out,
                                                   out_len);
}
int64_t bf_cp_put_bytes(void* h, const char* key, const void* data,
                        int64_t len) {
  return static_cast<ControlClient*>(h)->Call(
      kPutBytes, key, len, data, static_cast<size_t>(len));
}
int64_t bf_cp_get_bytes(void* h, const char* key, void** out,
                        int64_t* out_len) {
  return static_cast<ControlClient*>(h)->CallBytes(kGetBytes, key, out,
                                                   out_len);
}
void bf_cp_free(void* p) { std::free(p); }

int64_t bf_cp_bytes_len(void* h, const char* key) {
  return static_cast<ControlClient*>(h)->Call(kBytesLen, key, 0);
}

// One stripe of a striped put/get (the Python pool drives one call per
// connection from its own thread; ctypes releases the GIL, so stripes
// genuinely overlap). Offsets/lengths pack into the op's i64 arg.
int64_t bf_cp_put_bytes_part(void* h, const char* key, int64_t offset,
                             int64_t total, const void* data, int64_t len) {
  int64_t arg = (offset << 32) | total;
  return static_cast<ControlClient*>(h)->Call(
      kPutBytesPart, key, arg, data, static_cast<size_t>(len));
}

int64_t bf_cp_get_bytes_part(void* h, const char* key, int64_t offset,
                             int64_t len, void* dst) {
  int64_t arg = (offset << 32) | len;
  return static_cast<ControlClient*>(h)->CallBytesInto(
      kGetBytesPart, key, arg, dst, static_cast<size_t>(len));
}

// Whole striped transfers driven natively: split the payload into nh
// contiguous ranges and move them concurrently, one connection per range
// (std::thread per extra stripe; the caller's thread carries stripe 0).
// Used for single-key bulk bodies — the raw put_bytes/get_bytes ceiling and
// the hosted window publish/fetch paths.
int64_t bf_cp_put_bytes_striped(void** handles, int nh, const char* key,
                                const void* data, int64_t len) {
  if (nh <= 0) return -1;
  g_cl_striped_xfers.fetch_add(1, std::memory_order_relaxed);
  long long xfer_t0 = WallNowUs();
  if (nh == 1 || len < nh) {
    int64_t r = bf_cp_put_bytes_part(handles[0], key, 0, len, data, len);
    FlightRec(kFlightStripedXfer, len, WallNowUs() - xfer_t0);
    return r;
  }
  int64_t per = (len + nh - 1) / nh;
  std::vector<std::thread> ts;
  std::atomic<bool> ok{true};
  auto run = [&](int i) {
    int64_t off = per * i;
    int64_t n = off + per > len ? len - off : per;
    if (n <= 0) return;
    long long t0 = WallNowUs();
    if (bf_cp_put_bytes_part(handles[i], key, off, len,
                             static_cast<const char*>(data) + off, n) < 0)
      ok.store(false);
    FlightRec(kFlightStripe, n, WallNowUs() - t0);
  };
  for (int i = 1; i < nh; ++i) ts.emplace_back(run, i);
  run(0);
  for (auto& t : ts) t.join();
  FlightRec(kFlightStripedXfer, len, WallNowUs() - xfer_t0);
  return ok.load() ? 1 : -1;
}

// Like MPI_Get against a concurrently-written window, a striped read racing
// an unsynchronized same-key writer has no atomicity guarantee across
// stripes (use the window mutexes for exclusion, as MPI RMA prescribes). A
// LENGTH change mid-read is detected (a stripe comes back short) and
// retried a few times; persistent churn returns -1.
int64_t bf_cp_get_bytes_striped(void** handles, int nh, const char* key,
                                void** out, int64_t* out_len) {
  if (nh <= 0) return -1;
  g_cl_striped_xfers.fetch_add(1, std::memory_order_relaxed);
  long long xfer_t0 = WallNowUs();
  for (int attempt = 0; attempt < 3; ++attempt) {
    int64_t total = bf_cp_bytes_len(handles[0], key);
    if (total < 0) return -1;
    char* payload = static_cast<char*>(std::malloc(total ? total : 1));
    if (!payload) return -1;
    std::atomic<bool> failed{false}, short_read{false};
    if (total > 0) {
      int64_t per = (total + nh - 1) / nh;
      std::vector<std::thread> ts;
      auto run = [&](int i) {
        int64_t off = per * i;
        int64_t n = off + per > total ? total - off : per;
        if (n <= 0) return;
        long long t0 = WallNowUs();
        int64_t got =
            bf_cp_get_bytes_part(handles[i], key, off, n, payload + off);
        if (got < 0)
          failed.store(true);
        else if (got != n)
          short_read.store(true);  // value shrank mid-read: retry
        FlightRec(kFlightStripe, n, WallNowUs() - t0);
      };
      for (int i = 1; i < nh; ++i) ts.emplace_back(run, i);
      run(0);
      for (auto& t : ts) t.join();
    }
    if (failed.load()) {
      std::free(payload);
      return -1;
    }
    if (short_read.load()) {
      std::free(payload);
      continue;
    }
    *out = payload;
    *out_len = total;
    FlightRec(kFlightStripedXfer, total, WallNowUs() - xfer_t0);
    return total;
  }
  return -1;
}
// Pipelined batch of n payload-carrying ops (kAppendBytes=8 / kPutBytes=10):
// keys newline-separated, payloads concatenated in `blob` with per-record
// lengths in `lens`; per-op int replies land in `out`.
// Scatter-gather batch: per-record payload POINTERS (no concatenation) —
// the zero-copy path for numpy-backed window deposits.
int64_t bf_cp_bytes_multi_outv(void* h, int op, const char* keys_nl,
                               const void* const* datas, const int64_t* lens,
                               int64_t* out, int n) {
  return static_cast<ControlClient*>(h)->CallBytesMultiOutV(
      static_cast<uint8_t>(op), keys_nl, datas, lens, nullptr, out, n);
}
// Tagged variant (kAppendBytesTagged=13): per-record int64 `tags` ride the
// request's arg field and are prefixed to the stored records server-side.
int64_t bf_cp_bytes_multi_outv_tagged(void* h, int op, const char* keys_nl,
                                      const void* const* datas,
                                      const int64_t* lens,
                                      const int64_t* tags,
                                      int64_t* out, int n) {
  return static_cast<ControlClient*>(h)->CallBytesMultiOutV(
      static_cast<uint8_t>(op), keys_nl, datas, lens, tags, out, n);
}
// Pipelined batch of n bulk-reply ops (kTakeBytes=9 / kGetBytes=11): one
// malloc'd (u64 len | payload)* buffer, freed with bf_cp_free.
int64_t bf_cp_bytes_multi_in(void* h, int op, const char* keys_nl, int n,
                             void** out, int64_t* out_len) {
  return static_cast<ControlClient*>(h)->CallBytesMultiIn(
      static_cast<uint8_t>(op), keys_nl, n, out, out_len);
}
// Pipelined batch of n same-op requests (newline-separated keys): one
// latency round-trip for n key operations. args/out may be null.
int64_t bf_cp_multi(void* h, int op, const char* keys_nl, const int64_t* args,
                    int64_t* out, int n) {
  return static_cast<ControlClient*>(h)->CallMulti(
      static_cast<uint8_t>(op), keys_nl, args, out, n);
}
void bf_cp_disconnect(void* h) {
  auto* cl = static_cast<ControlClient*>(h);
  ::close(cl->fd);
  delete cl;
}

}  // extern "C"
