"""ResNet training — the full training-loop port of the reference's
examples/pytorch_resnet.py (407 lines: warmup + piecewise LR decay, gradient
accumulation via --batches-per-allreduce, per-batch dynamic topology,
validation accuracy, checkpoint/resume).

TPU-native differences:
  * the dataset is a deterministic synthetic CIFAR-shaped mixture (class-
    conditioned gaussians) so the example is runnable with zero downloads;
    swap :func:`synthetic_dataset` for a real input pipeline in production;
  * the LR schedule is an optax schedule compiled INTO the fused train step
    (the reference mutates param_group["lr"] host-side per batch,
    pytorch_resnet.py:309-325) — same warmup 1x -> size-x ramp over
    ``--warmup-epochs`` then /10 decays at epochs 30/60/80;
  * gradient accumulation uses ``num_steps_per_communication`` (the
    framework's local-step knob, the analog of batches-per-allreduce);
  * checkpoints are orbax directories via bluefog_tpu.checkpoint (the
    reference saves torch .pth.tar from rank 0, :378-385).
"""

from __future__ import annotations

import argparse
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

import bluefog_tpu as bf


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet18",
                   choices=["resnet18", "resnet34", "resnet50"])
    p.add_argument("--epochs", type=int, default=90)
    p.add_argument("--batch-size", type=int, default=32,
                   help="per-rank training batch size")
    p.add_argument("--val-batch-size", type=int, default=32)
    p.add_argument("--base-lr", type=float, default=0.0125,
                   help="per-rank base learning rate (scaled by size)")
    p.add_argument("--warmup-epochs", type=float, default=5)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--wd", type=float, default=5e-5)
    p.add_argument("--batches-per-allreduce", type=int, default=1,
                   help="local steps per communication round")
    p.add_argument("--dist-optimizer", default="neighbor_allreduce",
                   choices=["neighbor_allreduce", "gradient_allreduce",
                            "allreduce", "win_put"])
    p.add_argument("--disable-dynamic-topology", action="store_true")
    p.add_argument("--checkpoint-format", default=None,
                   help="e.g. /tmp/ckpt-{epoch}; enables save per epoch")
    p.add_argument("--resume-from", default=None,
                   help="checkpoint directory to resume from")
    p.add_argument("--steps-per-epoch", type=int, default=40,
                   help="synthetic-data batches per epoch")
    p.add_argument("--classes", type=int, default=10)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--seed", type=int, default=42)
    return p.parse_args(argv)


def synthetic_dataset(key, n_ranks, batch, steps, image_size, classes,
                      centers=None):
    """Class-conditioned gaussian 'images': learnable, deterministic, tiny.

    Returns rank-stacked arrays [steps, n_ranks, batch, H, W, 3], labels
    [steps, n_ranks, batch], and the class centers — each rank sees a
    disjoint shard, like the reference's DistributedSampler split. Pass the
    TRAIN set's ``centers`` when building the validation set: train and val
    must sample the same class-conditional distribution.
    """
    kc, kx, kl = jax.random.split(key, 3)
    if centers is None:
        centers = jax.random.normal(kc, (classes, 3)) * 2.0
    labels = jax.random.randint(kl, (steps, n_ranks, batch), 0, classes)
    noise = jax.random.normal(kx, (steps, n_ranks, batch,
                                   image_size, image_size, 3))
    images = centers[labels][:, :, :, None, None, :] + noise
    return np.asarray(images, np.float32), np.asarray(labels, np.int32), centers


def make_lr_schedule(args, size, steps_per_epoch):
    """Warmup 1x -> size-x over warmup_epochs, then /10 at ABSOLUTE epochs
    30/60/80 (same boundaries as the reference's adjust_learning_rate,
    pytorch_resnet.py:305-325 — the decay epochs do not shift by warmup).
    """
    warmup_steps = max(int(args.warmup_epochs * steps_per_epoch), 1)
    peak = args.base_lr * size * args.batches_per_allreduce
    warmup = optax.linear_schedule(
        init_value=args.base_lr * args.batches_per_allreduce,
        end_value=peak, transition_steps=warmup_steps)

    def schedule(step):
        step = jnp.asarray(step)
        lr = jnp.where(step < warmup_steps, warmup(step), peak)
        n_decays = ((step >= 30 * steps_per_epoch).astype(jnp.float32)
                    + (step >= 60 * steps_per_epoch)
                    + (step >= 80 * steps_per_epoch))
        return lr * 10.0 ** (-n_decays)

    return schedule


def build(args, devices=None):
    bf.init(devices=devices)
    n = bf.size()
    model_cls = {"resnet18": bf.models.ResNet18,
                 "resnet34": bf.models.ResNet34,
                 "resnet50": bf.models.ResNet50}[args.model]
    model = model_cls(num_classes=args.classes)
    sample = jnp.zeros((args.batch_size, args.image_size, args.image_size, 3),
                       jnp.float32)
    variables = model.init(jax.random.PRNGKey(args.seed), sample, train=True)

    def loss_fn(p, ms, batch):
        images, labels = batch
        logits, updates = model.apply(
            {"params": p, "batch_stats": ms}, images, train=True,
            mutable=["batch_stats"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        acc = (logits.argmax(-1) == labels).mean()
        return loss, (updates["batch_stats"], {"acc": acc})

    schedule = make_lr_schedule(args, n, args.steps_per_epoch)
    base = optax.chain(
        optax.add_decayed_weights(args.wd),
        optax.sgd(schedule, momentum=args.momentum),
    )
    opts = {
        "neighbor_allreduce": bf.DistributedNeighborAllreduceOptimizer,
        "gradient_allreduce": bf.DistributedGradientAllreduceOptimizer,
        "allreduce": bf.DistributedAllreduceOptimizer,
        "win_put": bf.DistributedWinPutOptimizer,
    }
    opt = opts[args.dist_optimizer](base, loss_fn, with_model_state=True)
    opt.num_steps_per_communication = args.batches_per_allreduce

    state = opt.init(variables["params"], model_state=variables["batch_stats"])
    start_epoch = 0
    if args.resume_from:
        state, step = bf.checkpoint.restore(args.resume_from, template=state)
        start_epoch = int(step)
        print(f"resumed from {args.resume_from} at epoch {start_epoch}")
    return model, opt, state, start_epoch


def evaluate(model, state, images, labels):
    """Validation accuracy of each rank's model, then the rank-mean.

    The reference averages per-rank metrics with allreduce (:291-301).
    """
    params = state.params

    def apply_one(p, ms, x):
        return model.apply({"params": p, "batch_stats": ms}, x, train=False)

    accs = []
    for s in range(images.shape[0]):
        logits = jax.vmap(apply_one)(params, state.model_state,
                                     jnp.asarray(images[s]))
        accs.append(np.asarray(
            (logits.argmax(-1) == jnp.asarray(labels[s])).mean(axis=(1,))))
    per_rank = np.mean(np.stack(accs), axis=0)  # [n]
    return float(per_rank.mean()), per_rank


def train(args, devices=None):
    model, opt, state, start_epoch = build(args, devices)
    n = bf.size()
    key = jax.random.PRNGKey(args.seed)
    tr_images, tr_labels, centers = synthetic_dataset(
        key, n, args.batch_size, args.steps_per_epoch,
        args.image_size, args.classes)
    va_images, va_labels, _ = synthetic_dataset(
        jax.random.PRNGKey(args.seed + 1), n, args.val_batch_size,
        max(args.steps_per_epoch // 4, 1), args.image_size, args.classes,
        centers=centers)

    dynamic = (not args.disable_dynamic_topology and n > 1 and
               args.dist_optimizer == "neighbor_allreduce")
    if dynamic:
        gens = [bf.topology_util.GetDynamicSendRecvRanks(bf.load_topology(), r)
                for r in range(n)]

    sh = bf.rank_sharding(bf.mesh())
    history = []
    for epoch in range(start_epoch, args.epochs):
        t0 = time.perf_counter()
        losses = []
        # double-buffered host->device feeding: the copy of batch s+1 is in
        # flight while step s computes (bf.utils.prefetch_to_device)
        feed = bf.utils.prefetch_to_device(
            ((tr_images[s], tr_labels[s])
             for s in range(args.steps_per_epoch)), size=2, sharding=sh)
        for s in range(args.steps_per_epoch):
            if dynamic:
                sends = {r: next(g)[0] for r, g in enumerate(gens)}
                recv = {r: [] for r in range(n)}
                for src, dsts in sends.items():
                    for d in dsts:
                        recv[d].append(src)
                opt.send_neighbors = sends
                opt.self_weight = {r: 1.0 / (len(recv[r]) + 1)
                                   for r in range(n)}
                opt.neighbor_weights = {
                    r: {s_: 1.0 / (len(recv[r]) + 1) for s_ in recv[r]}
                    for r in range(n)}
            state, metrics = opt.step(state, next(feed))
            losses.append(float(np.asarray(metrics["loss"]).mean()))
        val_acc, _ = evaluate(model, state, va_images, va_labels)
        dt = time.perf_counter() - t0
        print(f"epoch {epoch}: loss {np.mean(losses):.4f} "
              f"val_acc {val_acc:.3f} ({dt:.1f}s)")
        history.append((np.mean(losses), val_acc))
        if args.checkpoint_format:
            path = args.checkpoint_format.format(epoch=epoch + 1)
            bf.checkpoint.save(path, state, step=epoch + 1)
    return history, state


if __name__ == "__main__":
    from bluefog_tpu.runtime.config import example_devices

    train(parse_args(), devices=example_devices())
