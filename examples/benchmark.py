"""Throughput benchmark — port of the reference harness.

Mirrors examples/pytorch_benchmark.py of the reference (arg surface at
:52-60; synthetic data; warmup then timed iterations of ``num_batches_per_iter``
batches; img/sec mean ± CI): ResNet on synthetic ImageNet-shaped batches, one
model replica per chip, the chosen distributed optimizer doing the
communication. The dynamic Expo-2 one-peer schedule is on by default exactly
like the reference (``--disable-dynamic-topology`` restores the static graph).

Run (single host, all chips):   python examples/benchmark.py
Simulated 8-device CPU mesh:    bfrun --simulate 8 -- python examples/benchmark.py \
                                    --model mlp --batch-size 8 --num-iters 3
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf


def parse_args():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet50",
                   choices=["resnet50", "resnet34", "resnet18", "vgg16",
                            "mlp", "lm"])
    p.add_argument("--batch-size", type=int, default=64,
                   help="per-chip batch size")
    p.add_argument("--num-warmup-batches", type=int, default=10)
    p.add_argument("--num-batches-per-iter", type=int, default=10)
    p.add_argument("--num-iters", type=int, default=10)
    p.add_argument("--dist-optimizer", default="neighbor_allreduce",
                   choices=["neighbor_allreduce", "allreduce",
                            "gradient_allreduce", "hierarchical_neighbor_allreduce",
                            "win_put", "push_sum", "pull_get",
                            "sharded_allreduce", "local"])
    p.add_argument("--disable-dynamic-topology", action="store_true",
                   help="use the static topology instead of the one-peer "
                        "dynamic Expo-2 schedule")
    p.add_argument("--image-size", type=int, default=224)
    return p.parse_args()


def make_model(args):
    if args.model == "mlp":
        model = bf.models.MLP(features=(512, 512, 10))
        sample = jnp.zeros((args.batch_size, 32, 32, 3), jnp.float32)
        classes = 10
    elif args.model == "lm":
        # LM-shaped param tree — embedding + attention-block + norm
        # leaves — the fixture the sharded-window partition rules are
        # exercised on (opt_matrix_bench --sharded, ISSUE r17)
        model = bf.models.TransformerLM(
            vocab_size=512, num_layers=2, num_heads=4, d_model=128,
            d_ff=512)
        sample = jnp.zeros((args.batch_size, 32), jnp.int32)
        classes = 512
    else:
        cls = {"resnet50": bf.models.ResNet50, "resnet34": bf.models.ResNet34,
               "resnet18": bf.models.ResNet18, "vgg16": bf.models.VGG16}[args.model]
        model = cls(num_classes=1000, dtype=jnp.bfloat16)
        sample = jnp.zeros(
            (args.batch_size, args.image_size, args.image_size, 3), jnp.float32)
        classes = 1000
    return model, sample, classes


def main():
    args = parse_args()
    bf.init()
    n = bf.size()
    model, sample, classes = make_model(args)
    rng = jax.random.PRNGKey(0)
    is_lm = args.model == "lm"
    has_bn = args.model not in ("mlp", "lm")
    variables = model.init(rng, sample) if is_lm else \
        model.init(rng, sample, train=True)

    if has_bn:
        # Dropout-bearing models (vgg16) train with their standard dropout
        # active, like the reference harness. Folding a traced value into
        # the key keeps mask generation inside the compiled step (a plain
        # closed-over key is a compile-time constant XLA could fold away),
        # so the measured compute matches a real training step.
        use_dropout = args.model == "vgg16"

        def loss_fn(p, ms, batch):
            images, labels = batch
            rngs = {"dropout": jax.random.fold_in(
                jax.random.PRNGKey(1), labels[0])} if use_dropout else None
            logits, updates = model.apply(
                {"params": p, "batch_stats": ms}, images, train=True,
                mutable=["batch_stats"], rngs=rngs)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, (updates["batch_stats"], {})
        kw = {"with_model_state": True}
    elif is_lm:
        def loss_fn(p, batch):
            tokens, labels = batch
            logits = model.apply({"params": p}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        kw = {}
    else:
        def loss_fn(p, batch):
            images, labels = batch
            logits = model.apply({"params": p}, images)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
        kw = {}

    base = optax.sgd(0.01, momentum=0.9)
    opts = {
        "neighbor_allreduce": bf.DistributedNeighborAllreduceOptimizer,
        "allreduce": bf.DistributedAllreduceOptimizer,
        "gradient_allreduce": bf.DistributedGradientAllreduceOptimizer,
        "sharded_allreduce": bf.DistributedShardedAllreduceOptimizer,
        "hierarchical_neighbor_allreduce":
            bf.DistributedHierarchicalNeighborAllreduceOptimizer,
        "win_put": bf.DistributedWinPutOptimizer,
        "pull_get": bf.DistributedPullGetOptimizer,
        "push_sum": bf.DistributedPushSumOptimizer,
        "local": bf.DistributedNeighborAllreduceOptimizer,
    }
    opt = opts[args.dist_optimizer](base, loss_fn, **kw)
    if args.dist_optimizer == "local":
        opt.num_steps_per_communication = 10**9

    state = opt.init(
        variables["params"],
        model_state=variables.get("batch_stats") if has_bn else None)

    if is_lm:
        images = jax.device_put(
            np.random.RandomState(0).randint(
                0, classes, size=(n, *sample.shape)).astype(np.int32),
            bf.rank_sharding(bf.mesh()))
        labels = jax.device_put(
            jnp.zeros((n, *sample.shape), jnp.int32),
            bf.rank_sharding(bf.mesh()))
    else:
        images = jax.device_put(
            np.random.RandomState(0).randn(
                n, *sample.shape).astype(np.float32),
            bf.rank_sharding(bf.mesh()))
        labels = jax.device_put(
            jnp.zeros((n, args.batch_size), jnp.int32),
            bf.rank_sharding(bf.mesh()))
    batch = (images, labels)

    dynamic = (not args.disable_dynamic_topology and
               args.dist_optimizer == "neighbor_allreduce" and n > 1)
    if dynamic:
        gens = [bf.topology_util.GetDynamicSendRecvRanks(bf.load_topology(), r)
                for r in range(n)]

    def set_dynamic():
        sends = {}
        for r, g in enumerate(gens):
            to, _ = next(g)
            sends[r] = to
        recv_from = {r: [] for r in range(n)}
        for s, dsts in sends.items():
            for d in dsts:
                recv_from[d].append(s)
        opt.send_neighbors = sends
        opt.self_weight = {r: 1.0 / (len(recv_from[r]) + 1) for r in range(n)}
        opt.neighbor_weights = {
            r: {s: 1.0 / (len(recv_from[r]) + 1) for s in recv_from[r]}
            for r in range(n)}

    last_metrics = [None]

    def one_step(st):
        if dynamic:
            set_dynamic()
        st, m = opt.step(st, batch)
        last_metrics[0] = m
        return st

    def sync():
        # host transfer = reliable completion barrier (remote-device tunnels
        # can return early from block_until_ready)
        float(np.asarray(last_metrics[0]["loss"])[0])

    print(f"Model: {args.model}, batch {args.batch_size}/chip, "
          f"{n} chip(s), optimizer={args.dist_optimizer}, "
          f"dynamic_topology={dynamic}")
    for _ in range(args.num_warmup_batches):
        state = one_step(state)
    sync()

    img_secs = []
    for i in range(args.num_iters):
        t0 = time.perf_counter()
        for _ in range(args.num_batches_per_iter):
            state = one_step(state)
        sync()
        dt = time.perf_counter() - t0
        rate = args.batch_size * args.num_batches_per_iter * n / dt
        img_secs.append(rate)
        print(f"Iter #{i}: {rate:.1f} img/sec total")

    mean = np.mean(img_secs)
    conf = 1.96 * np.std(img_secs)
    print(f"Total img/sec on {n} chip(s): {mean:.1f} +-{conf:.1f}")


if __name__ == "__main__":
    main()
