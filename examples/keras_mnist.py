"""Decentralized training of per-rank Keras models (bluefog_tpu.keras).

The reference's TF-frontend story on the Keras 3 JAX backend: per-rank
Keras replicas train on disjoint synthetic shards with the wrapped
optimizer averaging gradients across ranks (the reference TF
``DistributedOptimizer`` semantics), and every replica ends bit-close to
every other — data parallelism without a torch or TF runtime anywhere.

Run:  KERAS_BACKEND=jax bfrun --simulate 8 -- python examples/keras_mnist.py
"""

import os as _os
import sys as _sys

_os.environ.setdefault("KERAS_BACKEND", "jax")
_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import numpy as np

import keras

import bluefog_tpu as bf
import bluefog_tpu.keras as bfk


def main() -> None:
    bf.init()
    n = bf.size()
    rng = np.random.RandomState(0)
    # synthetic 8x8 "digits": each rank sees its own shard
    xs = rng.randn(n, 256, 64).astype(np.float32)
    w_true = rng.randn(64, 10).astype(np.float32)
    ys = np.argmax(np.einsum("rbd,dk->rbk", xs, w_true), axis=-1)

    models = []
    for r in range(n):
        keras.utils.set_random_seed(r)  # deliberately divergent init
        m = keras.Sequential([keras.layers.Dense(32, activation="relu"),
                              keras.layers.Dense(10)])
        m.build((None, 64))
        models.append(m)
    bfk.broadcast_variables(models, root_rank=0)
    opt = bfk.DistributedOptimizer(
        lambda: keras.optimizers.Adam(1e-2), models,
        communication_type="allreduce")

    loss_fn = keras.losses.SparseCategoricalCrossentropy(from_logits=True)

    # keras-on-jax exposes stateless_call: functional grads via jax. Build
    # ONE jitted grad function per replica up front — a fresh closure per
    # step would re-trace 8 ranks x 40 steps times.
    import jax

    def make_grad_fn(m):
        ntv = [v.value for v in m.non_trainable_variables]

        def jloss(tv, x, y):
            logits, _ = m.stateless_call(tv, ntv, x)
            return loss_fn(y, logits)

        return jax.jit(jax.grad(jloss))

    grad_fns = [make_grad_fn(m) for m in models]

    for step in range(40):
        grads_per_rank = [
            [np.asarray(g) for g in grad_fns[r](
                [v.value for v in models[r].trainable_variables],
                xs[r], ys[r])]
            for r in range(n)]
        opt.apply_stacked(grads_per_rank)

    # all replicas took identical mean-gradient steps from a common init:
    # they must agree, and fit their shards
    accs = []
    for r in range(n):
        pred = np.argmax(np.asarray(models[r](xs[r])), axis=-1)
        accs.append(float((pred == ys[r]).mean()))
    w0 = np.asarray(models[0].trainable_variables[0])
    spread = max(
        float(np.abs(np.asarray(m.trainable_variables[0]) - w0).max())
        for m in models)
    print(f"ranks: {n} (keras frontend), mean shard accuracy "
          f"{np.mean(accs):.3f}, replica spread {spread:.2e}")
    assert spread < 1e-5, spread
    assert np.mean(accs) > 0.55, accs
    print("KERAS TRAIN OK")


if __name__ == "__main__":
    main()
