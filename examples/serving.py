"""Train-while-serve: a gossip trainer publishing versioned snapshots
while a read-only client answers batched inference from them.

The trainer side is one knob: BLUEFOG_SERVE_PUBLISH_EVERY=N makes
controller 0 write its post-gossip model to the control plane as an
immutable, codec-compressed, shard-striped snapshot every N-th
communicating step, committed behind a monotone version fence so a
reader either sees a complete snapshot or the previous one — never a
torn mix (docs/serving.md).

The serving side never imports jax and never joins the mesh: it is a raw
control-plane attachment (the same kind ``bfrun --status`` uses), so it
runs on any host that can reach the control-plane address. Here both
sides share one process for a self-contained example; point
``bf.serve_client`` (or ``bfrun --serve``) at the job's address to run
them on different machines.

Run (CPU-simulated 8-device mesh):
    JAX_PLATFORMS='' XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        BLUEFOG_SERVE_PUBLISH_EVERY=1 python examples/serving.py
On a real TPU slice just run it plainly: ranks are the local chips.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("BLUEFOG_SERVE_PUBLISH_EVERY", "1")
os.environ.setdefault("BLUEFOG_SERVE_POLL_S", "0.2")
# single-host runs have no jax coordinator to derive the control-plane
# address from — pin one so rank 0 serves it in-process and the serving
# client below has somewhere to attach
if not os.environ.get("BLUEFOG_CP_HOST"):
    import socket as _socket
    _s = _socket.socket()
    _s.bind(("127.0.0.1", 0))
    os.environ.update({"BLUEFOG_CP_HOST": "127.0.0.1",
                       "BLUEFOG_CP_PORT": str(_s.getsockname()[1]),
                       "BLUEFOG_CP_WORLD": "1", "BLUEFOG_CP_RANK": "0"})
    _s.close()

import numpy as np

import jax.numpy as jnp
import optax

import bluefog_tpu as bf


def main() -> int:
    from bluefog_tpu.runtime.config import example_devices

    bf.init(devices=example_devices())
    print(f"ranks: {bf.size()}")

    # a tiny ridge-regression "model": one weight vector, least squares
    # against a fixed linear target, gossip-averaged every step
    dim = 512
    rng = np.random.default_rng(0)
    w_true = rng.normal(size=(dim,)).astype(np.float32)
    xs_train = rng.normal(size=(256, dim)).astype(np.float32)
    ys_train = xs_train @ w_true

    def loss(params, batch):
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2) + 1e-4 * jnp.sum(params["w"] ** 2)

    opt = bf.DistributedPushSumOptimizer(optax.adam(1e-2), loss,
                                         window_prefix="example.serve")
    state = opt.init({"w": jnp.zeros((dim,), jnp.float32)})

    # the serving client: model_fn(params, batch) over the SNAPSHOT
    # leaves (numpy, in tree order) — params[0] is "w", rank-stacked
    # (one row per rank; the rows gossip toward consensus, any serves)
    def model_fn(params, xs):
        return xs @ params[0].reshape(-1, dim)[0]

    host = os.environ.get("BLUEFOG_CP_HOST", "127.0.0.1")
    port = int(os.environ["BLUEFOG_CP_PORT"]) \
        if os.environ.get("BLUEFOG_CP_PORT") else None
    sc = bf.serve_client(model_fn,
                         endpoints=[(host, port)] if port else None)

    # train; the publisher hook ships a new snapshot every comm step and
    # the client hot-swaps behind our back
    batch = (jnp.asarray(xs_train), jnp.asarray(ys_train))
    for step in range(1, 21):
        state, metrics_out = opt.step(state, batch)
        if step == 1:
            ok = sc.wait_ready(timeout=30)
            if not ok:
                print("serving: no snapshot within 30 s", file=sys.stderr)
                return 1
        if step % 5 == 0:
            q = rng.normal(size=(4, dim)).astype(np.float32)
            preds = np.stack([sc.infer(q[i], timeout=10) for i in range(4)])
            err = float(np.max(np.abs(preds - q @ w_true)))
            st = sc.stats()
            print(f"step {step:2d}: serving v{st['version']} "
                  f"({st['swaps']} swaps, {st['batches']} batches) "
                  f"max |pred - true| = {err:.3f}")

    final_v = sc.version()
    sc.close()
    opt.free()
    bf.shutdown()
    ok = final_v >= 1
    print("SERVING OK" if ok else "SERVING FAILED (no snapshot version)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
