"""Average consensus via decentralized neighbor averaging.

TPU-native port of the reference example ``examples/pytorch_average_consensus.py``:
every rank starts with a random vector and repeatedly averages with its graph
neighbors until all ranks agree on the global mean.

Run (CPU-simulated 8-device mesh):
    JAX_PLATFORMS='' XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/average_consensus.py
On a real TPU slice just run it plainly: ranks are the local chips.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp

import bluefog_tpu as bf
from bluefog_tpu import topology_util


def main() -> int:
    from bluefog_tpu.runtime.config import example_devices

    bf.init(topology_util.ExponentialTwoGraph, devices=example_devices())
    n = bf.size()
    print(f"ranks: {n} on {bf.mesh().devices.flat[0].platform}")

    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (n, 1000))
    x = bf.shard_rank_stacked(bf.mesh(), x)
    target = jnp.mean(x, axis=0)  # consensus value: per-coordinate rank mean

    for step in range(60):
        x = bf.neighbor_allreduce(x, name=f"consensus.{step}")

    err = float(jnp.max(jnp.abs(x - target[None, :])))
    print(f"max deviation from rank-mean after 60 rounds: {err:.3e}")
    ok = err < 1e-4
    print("CONSENSUS OK" if ok else "CONSENSUS FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
