"""Expert-parallel Mixture-of-Experts training.

Trains a Switch-FFN classifier expert-parallel: one expert per device on an
("expert",) mesh, tokens dispatched with all_to_all, gradients flowing
through the sparse dispatch (bluefog_tpu.parallel.ep_apply is fully
differentiable — the routing one-hots are piecewise-constant, the gate
learns through the top-1 probability scaling, standard Switch semantics).

No reference analog (the reference is data-parallel only); this is the
expert-parallelism end-to-end demo, same spirit as examples/long_context_lm.py
for sequence parallelism.

Run:
    JAX_PLATFORMS='' XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/moe.py
"""

from __future__ import annotations

import argparse
import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import optax

from bluefog_tpu import parallel as bfp


def make_data(key, n_clusters=8, per=64, d=16):
    """Clustered inputs: an ideal router sends each cluster to one expert."""
    centers = jax.random.normal(key, (n_clusters, d)) * 3.0
    xs, ys = [], []
    for c in range(n_clusters):
        k = jax.random.fold_in(key, c + 1)
        xs.append(centers[c] + jax.random.normal(k, (per, d)) * 0.3)
        ys.append(jnp.full((per,), c, jnp.int32))
    return jnp.concatenate(xs), jnp.concatenate(ys)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--experts", type=int, default=8)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--aux-weight", type=float, default=0.01)
    args = p.parse_args()

    E, d, d_ff, classes, per = args.experts, 16, 64, 8, 64
    devices = jax.devices()
    if len(devices) < E:  # forced-CPU simulation: the default backend may
        devices = jax.devices("cpu")  # be a single real chip
    tokens = classes * per
    if tokens % E or len(devices) < E:
        usable = [e for e in (2, 4, 8, 16, 32)
                  if tokens % e == 0 and e <= len(devices)]
        hint = f"try --experts {usable}" if usable else (
            "run under JAX_PLATFORMS='' "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 for a "
            "simulated 8-device mesh")
        raise SystemExit(
            f"--experts {E} needs to divide the {tokens}-token dataset and "
            f"fit the {len(devices)} available devices ({hint})")
    mesh = bfp.ep_mesh(E, devices)
    print(f"experts: {E} on {mesh.devices.flat[0].platform}")

    key = jax.random.PRNGKey(0)
    x, y = make_data(key, n_clusters=classes, per=per, d=d)
    # [B, S, d] layout with B divisible by the expert axis
    x = x.reshape(E, -1, d)
    y = y.reshape(E, -1)

    moe = bfp.SwitchFFN(num_experts=E, d_ff=d_ff)
    params = {
        "moe": moe.init(jax.random.PRNGKey(1), x)["params"],
        "head": jax.random.normal(jax.random.PRNGKey(2), (d, classes)) * 0.1,
    }

    def loss_fn(params, batch):
        bx, by = batch
        h, aux = bfp.ep_apply(params["moe"], bx, mesh, capacity_factor=4.0)
        logits = (bx + h) @ params["head"]  # residual MoE + linear head
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits, by).mean()
        return ce + args.aux_weight * aux.mean()

    opt = optax.adam(3e-2)
    opt_state = opt.init(params)
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    losses = []
    for step in range(args.steps):
        loss, grads = grad_fn(params, (x, y))
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        losses.append(float(loss))
        if step % 10 == 0:
            print(f"step {step:3d}  loss {losses[-1]:.4f}")

    print(f"final loss: {losses[-1]:.4f} (from {losses[0]:.4f})")
    assert losses[-1] < 0.5 * losses[0], "MoE training failed to converge"
    print("MOE OK")

    # ---- part 2: the full MoE transformer LM (models.MoETransformerLM) --
    # Switch-FFN blocks INSIDE the LM, expert-sharded up/down weights,
    # next-token loss differentiated straight through the shard_map.
    from bluefog_tpu.models import MoETransformerLM

    lm = MoETransformerLM(
        vocab_size=64, num_experts=E, num_layers=2, num_heads=2,
        d_model=32, d_ff=d_ff, expert_axis="expert")
    rng = jax.random.PRNGKey(7)
    toks = jax.random.randint(rng, (E, 16), 0, 64)
    batch = (toks, jnp.roll(toks, -1, axis=1))
    lm_params = bfp.ep_lm_init(lm, jax.random.PRNGKey(8), toks)
    lm_loss = bfp.ep_lm_loss_fn(lm, mesh, aux_weight=args.aux_weight)
    lm_opt = optax.adam(3e-3)
    lm_state = lm_opt.init(lm_params)
    lm_grad = jax.jit(jax.value_and_grad(lm_loss))
    lm_losses = []
    for step in range(args.steps):
        loss, grads = lm_grad(lm_params, batch)
        updates, lm_state = lm_opt.update(grads, lm_state, lm_params)
        lm_params = optax.apply_updates(lm_params, updates)
        lm_losses.append(float(loss))
        if step % 20 == 0:
            print(f"lm step {step:3d}  loss {lm_losses[-1]:.4f}")
    print(f"lm final loss: {lm_losses[-1]:.4f} (from {lm_losses[0]:.4f})")
    assert lm_losses[-1] < 0.7 * lm_losses[0], "MoE LM failed to converge"
    print("MOE_LM OK")


if __name__ == "__main__":
    main()
