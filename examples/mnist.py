"""Decentralized MNIST training — port of the reference example.

Mirrors examples/pytorch_mnist.py: a small conv net, each rank training on
its own shard of the data, parameters mixed by the chosen distributed
optimizer. Uses a synthetic MNIST-shaped dataset when torchvision-style data
is unavailable (this repo depends on nothing outside jax/flax/optax).

Run on a simulated mesh:  bfrun --simulate 8 -- python examples/mnist.py --epochs 1
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse

import numpy as np

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf


def synthetic_mnist(n_per_rank: int, size: int, seed: int = 0):
    """Class-structured fake MNIST: digits are noisy class-template images."""
    rng = np.random.RandomState(seed)
    templates = rng.rand(10, 28, 28).astype(np.float32)
    labels = rng.randint(0, 10, (size, n_per_rank))
    images = templates[labels] + 0.3 * rng.randn(
        size, n_per_rank, 28, 28).astype(np.float32)
    return images.astype(np.float32), labels.astype(np.int32)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--dist-optimizer", default="neighbor_allreduce",
                   choices=["neighbor_allreduce", "allreduce",
                            "gradient_allreduce"])
    p.add_argument("--samples-per-rank", type=int, default=2048)
    args = p.parse_args()

    bf.init()
    n = bf.size()
    model = bf.models.LeNet5()
    rng = jax.random.PRNGKey(42)
    params = model.init(rng, jnp.zeros((1, 28, 28, 1)))["params"]

    def loss_fn(p_, batch):
        x, y = batch
        logits = model.apply({"params": p_}, x[..., None])
        return optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()

    cls = {
        "neighbor_allreduce": bf.DistributedNeighborAllreduceOptimizer,
        "allreduce": bf.DistributedAllreduceOptimizer,
        "gradient_allreduce": bf.DistributedGradientAllreduceOptimizer,
    }[args.dist_optimizer]
    opt = cls(optax.sgd(args.lr, momentum=0.9), loss_fn)
    state = opt.init(params)

    images, labels = synthetic_mnist(args.samples_per_rank, n)
    steps = args.samples_per_rank // args.batch_size
    sh = bf.rank_sharding(bf.mesh())
    for epoch in range(args.epochs):
        losses = []
        for s in range(steps):
            lo, hi = s * args.batch_size, (s + 1) * args.batch_size
            batch = (
                jax.device_put(jnp.asarray(images[:, lo:hi]), sh),
                jax.device_put(jnp.asarray(labels[:, lo:hi]), sh),
            )
            state, m = opt.step(state, batch)
            losses.append(float(np.mean(np.asarray(m["loss"]))))
        print(f"epoch {epoch}: mean loss {np.mean(losses):.4f}")

    # evaluate consensus model (rank 0's copy after a final average)
    final = bf.allreduce_parameters(state.params)
    p0 = bf.unreplicate(final)
    logits = model.apply({"params": p0}, jnp.asarray(images[0][..., None]))
    acc = float(np.mean(np.argmax(np.asarray(logits), -1) == labels[0]))
    print(f"train-shard accuracy of consensus model: {acc:.3f}")


if __name__ == "__main__":
    main()
