"""Long-context LM training with ring-attention sequence parallelism.

The sequence dimension is sharded across all chips: each holds S/n tokens,
K/V blocks rotate around the ICI ring (`bluefog_tpu.parallel.ring_attention`),
so the trainable context length scales linearly with the mesh size. This is
the capability the reference framework never had (it predates attention);
here it rides the same ring machinery as `neighbor_allreduce`.

Run (simulated 8-device mesh):
    bfrun --simulate 8 -- python examples/long_context_lm.py --seq-len 512

``--attention flash`` instead trains full-sequence on ONE chip through the
pallas flash kernel (custom VJP, no [S, S] scores in either direction) —
the single-device long-context path for when a mesh isn't available.
"""

from __future__ import annotations

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

import bluefog_tpu as bf
from bluefog_tpu import parallel as bfp
from bluefog_tpu.models import TransformerLM


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--num-layers", type=int, default=4)
    p.add_argument("--num-heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--attention", default="ring",
                   choices=["ring", "ulysses", "flash"])
    args = p.parse_args()

    bf.init()
    n = bf.size()
    if args.attention != "flash" and args.seq_len % n:
        raise SystemExit(f"--seq-len must be divisible by {n} chips")

    attn_fn = None
    if args.attention == "flash":
        from functools import partial
        from bluefog_tpu.parallel.flash import flash_attention
        # real pallas kernel on TPU, interpret mode on CPU dev boxes /
        # --simulate runs (no Mosaic lowering off-TPU)
        attn_fn = partial(flash_attention, causal=True,
                          interpret=jax.default_backend() != "tpu")
    model = TransformerLM(
        vocab_size=args.vocab, num_layers=args.num_layers,
        num_heads=args.num_heads, d_model=args.d_model,
        d_ff=4 * args.d_model, dtype=jnp.bfloat16, attn_fn=attn_fn)

    rng = np.random.RandomState(0)
    # synthetic "copy task"-flavored data: next token = current + 1 mod V
    start = rng.randint(0, args.vocab, (args.batch_size, 1))
    tokens = (start + np.arange(args.seq_len)) % args.vocab
    tokens = jnp.asarray(tokens, jnp.int32)
    targets = jnp.roll(tokens, -1, axis=1)

    params = model.init(jax.random.PRNGKey(0), tokens[:, : args.seq_len])["params"]
    if args.attention == "flash":
        def loss_fn(p_, batch):
            x, y = batch
            logits = model.apply({"params": p_}, x)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
    else:
        loss_fn = bfp.cp_loss_fn(model, kind=args.attention)
    opt = optax.adam(3e-3)
    opt_state = opt.init(params)

    @jax.jit
    def step(p_, s_, batch):
        l, g = jax.value_and_grad(loss_fn)(p_, batch)
        updates, s_ = opt.update(g, s_, p_)
        return optax.apply_updates(p_, updates), s_, l

    if args.attention == "flash":
        # no sequence sharding: one chip owns the full context (the kernel,
        # not the mesh, is what makes the length affordable)
        print(f"seq {args.seq_len} full-sequence on one chip, flash attention")
    else:
        print(f"{n} chip(s), seq {args.seq_len} ({args.seq_len // n}/chip), "
              f"{args.attention} attention")
    t0 = time.time()
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, (tokens, targets))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {float(loss):.4f}")
    print(f"{args.steps} steps in {time.time() - t0:.1f}s; "
          f"final loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
