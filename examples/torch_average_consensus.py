"""Average consensus driven from a LIVE torch loop (bluefog_tpu.torch).

The reference's ``pytorch_average_consensus.py`` in this framework's torch
frontend: per-rank torch tensors, repeated neighbor averaging over the
default Expo-2 topology, convergence to the global mean — no jax code in
user sight; the compiled SPMD collectives run underneath.

Run:  bfrun --simulate 8 -- python examples/torch_average_consensus.py
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import torch

import bluefog_tpu as bf
import bluefog_tpu.torch as bft


def main() -> None:
    bf.init()
    n = bf.size()
    torch.manual_seed(0)
    x = torch.randn(n, 1000)  # rank-stacked: row r is rank r's vector
    target = x.mean(dim=0, keepdim=True)
    for i in range(60):
        x = bft.neighbor_allreduce(x)
    dev = float((x - target).abs().max())
    print(f"ranks: {n} (torch frontend)")
    print(f"max deviation from rank-mean after 60 rounds: {dev:.3e}")
    assert dev < 1e-4, dev
    print("TORCH CONSENSUS OK")


if __name__ == "__main__":
    main()
