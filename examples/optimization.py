"""Decentralized optimization algorithms on the TPU-native BlueFog API.

Re-creation of the reference's richest capability demo
(/root/reference/examples/pytorch_optimization.py:178-427): solving a
regularized regression problem whose data is partitioned across ranks with

  * diffusion                  (Sayed, "Adaptive networks", 2014)
  * exact diffusion            (Yuan et al., 2018, Alg. 1)
  * gradient tracking          (Nedic et al., 2017, Alg. 1)
  * push-DIGing                (Nedic et al., 2017, Alg. 2)

and verifying each against the centralized optimum obtained by distributed
gradient descent.  The port is deliberately idiomatic for this framework:
every per-rank quantity is a *rank-stacked* array ``[size, ...]`` and each
communication round is one SPMD program over the device mesh, so "each rank
runs the recursion" becomes plain array code with no per-rank Python loop.

Gradient tracking keeps the reference's signature overlap pattern — two
concurrent nonblocking ``neighbor_allreduce`` calls in flight while the new
local gradient is computed (reference :327-333).  Push-DIGing keeps the
reference's combo-vector trick (u, y, and the push-sum weight travel as one
window tensor so they can never de-synchronize, reference :378-396) and runs
on one-sided ``win_accumulate`` + ``win_update_then_collect``.

Deviation from the reference, on purpose: the l2 regularizer is the smooth
``0.5*rho*||w||^2`` rather than the reference's non-smooth ``0.5*rho*||w||``,
so the global optimum is the unique zero-gradient point and autodiff is
defined at the w=0 start.
"""

from __future__ import annotations

import argparse
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import bluefog_tpu as bf
from bluefog_tpu import topology_util


# ---------------------------------------------------------------------------
# data + objective
# ---------------------------------------------------------------------------

def generate_data(key, size: int, m: int, n: int,
                  task: str = "logistic_regression"):
    """Per-rank synthetic data, rank-stacked: X [size, m, n], y [size, m, 1]."""
    kx, kw, ky = jax.random.split(key, 3)
    X = jax.random.normal(kx, (size, m, n))
    if task == "logistic_regression":
        w0 = jax.random.normal(kw, (size, n, 1))
        p = 1.0 / (1.0 + jnp.exp(X @ w0))
        y = (jax.random.uniform(ky, (size, m, 1)) < p).astype(X.dtype)
        y = 2.0 * y - 1.0
    elif task == "linear_regression":
        x_o = jax.random.normal(kw, (size, n, 1))
        noise = 0.1 * jax.random.normal(ky, (size, m, 1))
        y = X @ x_o + noise
    else:
        raise NotImplementedError(
            "task must be linear_regression or logistic_regression")
    return X, y


def make_grad_fn(X, y, task: str, rho: float) -> Callable:
    """Stacked gradient: [size, n, 1] weights -> [size, n, 1] local grads.

    X/y are pinned to the rank mesh first so every eager recursion step and
    the jitted gradient run on the mesh backend (NOT the default device,
    which may be a different accelerator in mixed-backend environments).
    """
    X, y = bf.shard_rank_stacked(bf.mesh(), (X, y))

    def local_loss(Xr, yr, wr):
        if task == "logistic_regression":
            data = jnp.mean(jnp.log1p(jnp.exp(-yr * (Xr @ wr))))
            reg = 0.5 * rho * jnp.sum(wr * wr)
        else:
            r = Xr @ wr - yr
            data = 0.5 * jnp.mean(r * r)
            reg = 0.5 * rho * jnp.sum(wr * wr)
        return data + reg

    def total(w_stacked):
        return jnp.sum(jax.vmap(local_loss)(X, y, w_stacked))

    return jax.jit(jax.grad(total))


def _zeros(size: int, n: int):
    """Rank-mesh-pinned [size, n, 1] zeros (numpy -> direct mesh placement)."""
    return bf.shard_rank_stacked(bf.mesh(), np.zeros((size, n, 1), np.float32))


# ---------------------------------------------------------------------------
# baseline: distributed gradient descent (the centralized optimum)
# ---------------------------------------------------------------------------

def distributed_grad_descent(grad_fn, size: int, n: int, maxite: int = 500,
                             alpha: float = 1e-1):
    """x^{k+1} = x^k - alpha * allreduce(local_grad); reference :124-164."""
    w = _zeros(size, n)
    for _ in range(maxite):
        g = bf.allreduce(grad_fn(w), average=True, name="gradient")
        w = w - alpha * g
    return w


# ---------------------------------------------------------------------------
# the decentralized algorithms
# ---------------------------------------------------------------------------

def diffusion(grad_fn, w_opt, size: int, n: int, maxite: int = 500,
              alpha: float = 1e-1) -> Tuple[jnp.ndarray, List[float]]:
    """w^{k+1} = neighbor_allreduce(w^k - alpha*grad); reference :178-212."""
    w = _zeros(size, n)
    mse = []
    for _ in range(maxite):
        phi = w - alpha * grad_fn(w)
        w = bf.neighbor_allreduce(phi, name="diffusion.w")
        mse.append(float(jnp.linalg.norm(w[0] - w_opt[0])))
    return w, mse


def _abar_weights(size: int):
    """Recv weights of (A + I)/2 for the current topology, per rank."""
    topo = bf.load_topology()
    self_w: Dict[int, float] = {}
    nbr_w: Dict[int, Dict[int, float]] = {}
    for r in range(size):
        sw, nw = topology_util.GetRecvWeights(topo, r)
        self_w[r] = (sw + 1.0) / 2.0
        nbr_w[r] = {src: v / 2.0 for src, v in nw.items()}
    return self_w, nbr_w


def exact_diffusion(grad_fn, w_opt, size: int, n: int, maxite: int = 500,
                    alpha: float = 1e-1, use_Abar: bool = True):
    """psi/phi/combine recursion of Yuan et al. 2018; reference :232-281.

    With ``use_Abar`` the combination matrix is (A+I)/2, passed as explicit
    per-rank self/neighbor weights.
    """
    if use_Abar:
        self_w, nbr_w = _abar_weights(size)
    else:
        self_w, nbr_w = None, None
    w = _zeros(size, n)
    psi_prev = w
    mse = []
    for _ in range(maxite):
        psi = w - alpha * grad_fn(w)
        phi = psi + w - psi_prev
        w = bf.neighbor_allreduce(
            phi, self_weight=self_w, neighbor_weights=nbr_w,
            name="exact_diffusion.w")
        psi_prev = psi
        mse.append(float(jnp.linalg.norm(w[0] - w_opt[0])))
    return w, mse


def gradient_tracking(grad_fn, w_opt, size: int, n: int, maxite: int = 500,
                      alpha: float = 1e-1):
    """Nedic et al. 2017 Alg. 1; reference :305-347.

    The two neighbor_allreduce calls are launched nonblocking and stay in
    flight while the new local gradient is computed — the same
    communication/compute overlap the reference demonstrates (:327-333).
    """
    w = _zeros(size, n)
    q = grad_fn(w)            # q^0 = grad(w^0)
    grad_prev = q
    mse = []
    for _ in range(maxite):
        w_handle = bf.neighbor_allreduce_nonblocking(w, name="gt.w")
        q_handle = bf.neighbor_allreduce_nonblocking(q, name="gt.q")
        w = bf.synchronize(w_handle) - alpha * q
        grad = grad_fn(w)     # overlaps with the q exchange
        q = bf.synchronize(q_handle) + grad - grad_prev
        grad_prev = grad
        mse.append(float(jnp.linalg.norm(w[0] - w_opt[0])))
    return w, mse


def push_diging(grad_fn, w_opt, size: int, n: int, maxite: int = 500,
                alpha: float = 1e-1):
    """Nedic et al. 2017 Alg. 2 over one-sided windows; reference :364-427.

    u (the iterate), y (the tracked gradient), and the push-sum weight v
    travel as one combo window tensor [size, 2n+1, 1].  Each round every
    rank accumulates w/(2*outdegree) into its out-neighbors' mailboxes,
    keeps w/2 itself (``self_weight=0.5`` — the window analog of the
    reference's in-place ``w.div_(2)``), and collects.
    """
    topo = bf.load_topology()
    out_nbrs = {r: topology_util.out_neighbor_ranks(topo, r)
                for r in range(size)}
    dst_weights = {
        r: {dst: 1.0 / (2.0 * len(out_nbrs[r])) for dst in out_nbrs[r]}
        for r in range(size)
    }

    w = _zeros(size, 2 * n + 1)
    x = _zeros(size, n)
    grad = grad_fn(x)
    w = w.at[:, n:2 * n].set(grad)
    w = w.at[:, -1].set(1.0)
    grad_prev = grad

    bf.win_create(w, name="w_buff", zero_init=True)
    mse = []
    try:
        for _ in range(maxite):
            bf.barrier()
            w = w.at[:, :n].add(-alpha * w[:, n:2 * n])
            bf.win_accumulate(
                w, name="w_buff", self_weight=0.5, dst_weights=dst_weights,
                require_mutex=True)
            bf.barrier()
            w = bf.win_update_then_collect(name="w_buff")

            x = w[:, :n] / w[:, -1:]
            grad = grad_fn(x)
            w = w.at[:, n:2 * n].add(grad - grad_prev)
            grad_prev = grad
            mse.append(float(jnp.linalg.norm(x[0] - w_opt[0])))
        bf.barrier()
        w = bf.win_update_then_collect(name="w_buff")
        x = w[:, :n] / w[:, -1:]
    finally:
        bf.win_free("w_buff")
    return x, mse


ALGORITHMS = {
    "diffusion": diffusion,
    "exact_diffusion": exact_diffusion,
    "gradient_tracking": gradient_tracking,
    "push_diging": push_diging,
}


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def set_example_topology(name: str) -> None:
    size = bf.size()
    if name == "mesh":
        bf.set_topology(topology_util.MeshGrid2DGraph(size), is_weighted=True)
    elif name == "expo2":
        bf.set_topology(topology_util.ExponentialGraph(size))
    elif name == "star":
        bf.set_topology(topology_util.StarGraph(size), is_weighted=True)
    elif name == "ring":
        bf.set_topology(topology_util.RingGraph(size))
    else:
        raise NotImplementedError(
            "topology must be one of mesh, star, ring, expo2")


def run(method: str = "exact_diffusion", task: str = "logistic_regression",
        topology: str = "ring", maxite: int = 500, alpha: float = 1e-1,
        rho: float = 1e-2, m: int = 20, n: int = 5, seed: int = 123417):
    """Build the problem, solve it centrally and decentrally, report both."""
    size = bf.size()
    set_example_topology(topology)

    X, y = generate_data(jax.random.PRNGKey(seed), size, m, n, task=task)
    grad_fn = make_grad_fn(X, y, task, rho)

    w_opt = distributed_grad_descent(grad_fn, size, n, maxite=maxite,
                                     alpha=alpha)
    g_opt = bf.allreduce(grad_fn(w_opt), average=True)
    print(f"[DG] global grad norm: {float(jnp.linalg.norm(g_opt[0])):.3e} "
          f"local grad norm: {float(jnp.linalg.norm(grad_fn(w_opt)[0])):.3e}")

    algo = ALGORITHMS[method]
    w, mse = algo(grad_fn, w_opt, size, n, maxite=maxite, alpha=alpha)

    g = bf.allreduce(grad_fn(w), average=True)
    print(f"[{method}] final ||w - w_opt||: {mse[-1]:.3e} "
          f"global grad norm: {float(jnp.linalg.norm(g[0])):.3e}")
    return w, w_opt, mse


def main() -> None:
    parser = argparse.ArgumentParser(
        description="Decentralized optimization algorithms (TPU-native)")
    parser.add_argument("--method", default="exact_diffusion",
                        choices=sorted(ALGORITHMS))
    parser.add_argument("--task", default="logistic_regression",
                        choices=["logistic_regression", "linear_regression"])
    parser.add_argument("--topology", default="ring",
                        choices=["mesh", "star", "ring", "expo2"])
    parser.add_argument("--max-iter", type=int, default=500)
    parser.add_argument("--lr", type=float, default=1e-1)
    parser.add_argument("--save-plot-file", default=None,
                        help="optional path for a semilogy convergence plot")
    args = parser.parse_args()

    from bluefog_tpu.runtime.config import example_devices
    bf.init(devices=example_devices())
    print(f"ranks: {bf.size()} on {bf.mesh().devices.flat[0].platform}")
    _, _, mse = run(method=args.method, task=args.task,
                    topology=args.topology, maxite=args.max_iter,
                    alpha=args.lr)
    if args.save_plot_file:
        try:
            import matplotlib
            matplotlib.use("Agg")
            import matplotlib.pyplot as plt
            plt.semilogy(np.maximum(np.asarray(mse), 1e-16))
            plt.xlabel("iteration")
            plt.ylabel("|| w - w* ||")
            plt.savefig(args.save_plot_file)
            plt.close()
        except ImportError:
            print("matplotlib unavailable; skipping plot")


if __name__ == "__main__":
    main()
