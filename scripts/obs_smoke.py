#!/usr/bin/env python
"""Live-telemetry-plane smoke test (`make obs-smoke`).

A 2-rank in-process job with the control plane + hosted window plane
forced on, asserting the acceptance surface of the streaming
time-series plane (docs/observability.md) end to end:

  * sampling is near-free: one :meth:`Series.add` (three tier stores)
    costs < 2 µs — the per-record budget that keeps always-on sampling
    honest;
  * a win-put optimizer job leaves a non-empty, unpackable delta stream
    under ``bf.ts.<rank>`` with step cadence, consensus distance, and
    per-edge estimators populated;
  * ``bfrun --top --once`` renders every rank from a SEPARATE process
    (raw client, no mesh join) and — after a SIGKILLed publisher child's
    stream goes stale — names the silent rank;
  * ``scripts/ts_export.py`` emits parseable JSON-lines and lint-clean
    OpenMetrics from the same stream;
  * ``step_attribution --live`` answers per-edge bytes without a dump.

Exits non-zero (with a message) on any violated assertion.
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import time
import timeit

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

_s = socket.socket()
_s.bind(("127.0.0.1", 0))
PORT = _s.getsockname()[1]
_s.close()

os.environ.update({
    "BLUEFOG_CP_HOST": "127.0.0.1",
    "BLUEFOG_CP_PORT": str(PORT),
    "BLUEFOG_CP_WORLD": "1",
    "BLUEFOG_CP_RANK": "0",
    "BLUEFOG_WIN_HOST_PLANE": "1",
    "BLUEFOG_METRICS_INTERVAL": "1",
    "BLUEFOG_TS_INTERVAL": "1",
})

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import bluefog_tpu as bf  # noqa: E402
from bluefog_tpu.runtime import timeseries as ts_mod  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check(cond, msg):
    if not cond:
        print(f"obs-smoke FAILED: {msg}", file=sys.stderr)
        sys.exit(1)


def microbench_record_ns() -> float:
    """Per-call cost of one Series.add (all three tiers) — the
    'per-record sampling cost' the ISSUE bounds at 2 µs. Same de-noising
    as metrics_smoke: unrolled calls, min over many short windows."""
    s = ts_mod.Series("smoke.bench", "gauge", "last")
    unroll = 10
    n = 1_000
    stmt = ";".join(["add(1234.5, 1.0)"] * unroll)
    best = min(timeit.repeat(stmt, globals={"add": s.add},
                             number=n, repeat=50)) / (n * unroll)
    return best * 1e9


def main() -> int:
    # 1) the per-record sampling budget
    ns = microbench_record_ns()
    print(f"series record: {ns:.0f} ns/record")
    check(ns < 2000.0, f"Series.add costs {ns:.0f} ns (budget 2000)")

    # 2) a real 2-rank hosted job streaming bf.ts.0
    bf.init(devices=jax.devices("cpu")[:2])

    def zloss(p, b):
        return 0.0 * jnp.sum(p["w"])

    opt = bf.DistributedWinPutOptimizer(optax.sgd(0.1), zloss,
                                        window_prefix="obs.wp")
    state = opt.init({"w": jnp.ones((64,), jnp.float32)})
    for i in range(6):
        opt._consensus_t = 0.0  # defeat the ~1 Hz gauge cadence gate
        state, _ = opt.step(state, jnp.zeros((2, 1), jnp.float32))
        ts_mod.maybe_sample(force=True, publish=True)
        time.sleep(0.05)

    from bluefog_tpu.runtime import control_plane as cp

    # feed the per-edge estimators: a split-ownership window (the
    # test_metrics flow-pair harness) — the origin half owns rank 0 and
    # deposits to rank 1 over the REAL server; the owner half drains, so
    # both flow ends (edge.0.1 start, drain finish) land in this
    # process's flight ring and the live transit estimator matches them
    import numpy as np
    from bluefog_tpu.ops import windows as win_mod
    from bluefog_tpu.runtime.state import _global_state

    st = _global_state()
    x = bf.shard_rank_stacked(bf.mesh(), jnp.ones((2, 256)))
    orig_owned = cp.owned_ranks
    try:
        cp.owned_ranks = lambda devs, pid: [0]
        check(bf.win_create(x, "obs.flow", zero_init=True),
              "win_create failed")
        cp.owned_ranks = lambda devs, pid: [1]
        win_b = win_mod.Window("obs.flow", np.ones((2, 256), np.float32),
                               zero_init=True)
        for _ in range(4):
            bf.win_put(x, "obs.flow")
            with win_b.state_mu:
                win_b._drain_deposits()
    finally:
        cp.owned_ranks = orig_owned
    ts_mod.maybe_sample(force=True, publish=True)

    blob = cp.client().get_bytes(ts_mod.TS_KEY_FMT.format(rank=0))
    check(len(blob) > 0, "no bf.ts.0 publication")
    acc = ts_mod.HistoryAccumulator()
    doc = ts_mod.read_rank(cp.client(), 0)
    check(doc is not None, "bf.ts.0 blob does not unpack")
    acc.update(0, doc)
    check(acc.latest(0, "opt.step") == 6.0,
          f"streamed opt.step wrong: {acc.latest(0, 'opt.step')}")
    check(acc.latest(0, "opt.consensus_dist") is not None,
          "no consensus-distance series streamed")
    edges = acc.edges.get(0) or {}
    check("0->1" in edges, f"no per-edge estimator for 0->1: {edges}")
    check(edges["0->1"]["deposits"] >= 4 and edges["0->1"]["bytes"] > 0,
          f"edge estimator undercounted: {edges['0->1']}")
    p50, _ = acc.edge_transit("0->1")
    check(p50 is not None and p50 > 0,
          f"no live transit estimate for 0->1 (p50 {p50})")

    # 3) bfrun --top --once from a separate process (raw client)
    env = dict(os.environ)
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--top", "--once"],
        env=env, capture_output=True, text=True, timeout=120)
    print(out.stdout, end="")
    check(out.returncode == 0, f"bfrun --top failed: {out.stderr}")
    check("rank" in out.stdout and re.search(r"^\s+0\s", out.stdout,
                                             re.M),
          f"--top output missing rank rows: {out.stdout!r}")
    check("edges (live)" in out.stdout, "--top missing the edge matrix")

    # 4) SIGKILL a publisher child for a second rank; --top names it
    child = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "scripts", "_ts_pub_child.py"),
         "127.0.0.1", str(PORT), "1", "0.2"],
        env=env, stdout=subprocess.PIPE, text=True)
    line = child.stdout.readline()
    check(line.startswith("TS_CHILD_READY"), f"publisher child: {line!r}")
    time.sleep(0.6)  # a few publications land
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher", "--top", "--once",
         "--world", "2"],
        env=env, capture_output=True, text=True, timeout=120)
    check(out.returncode == 0, f"--top (2 ranks) failed: {out.stderr}")
    check("SILENT" not in out.stdout,
          f"rank 1 wrongly silent while its publisher lives: "
          f"{out.stdout!r}")
    child.send_signal(signal.SIGKILL)
    child.wait()
    time.sleep(1.2)  # > 3 x the child's 0.2 s interval (floor applies)
    deadline = time.monotonic() + 30
    named = False
    while time.monotonic() < deadline:
        out = subprocess.run(
            [sys.executable, "-m", "bluefog_tpu.launcher", "--top",
             "--once", "--world", "2"],
            env=env, capture_output=True, text=True, timeout=120)
        if "SILENT" in out.stdout and "[1]" in out.stdout:
            named = True
            break
        time.sleep(0.5)
    check(named, f"--top never named the SIGKILLed rank SILENT: "
          f"{out.stdout!r}")
    print("SIGKILLed publisher named SILENT — ok")

    # 5) ts_export: JSON lines parse; OpenMetrics lints
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ts_export.py"),
         "--cp", f"127.0.0.1:{PORT}", "--world", "1"],
        env=env, capture_output=True, text=True, timeout=120)
    check(out.returncode == 0, f"ts_export jsonl failed: {out.stderr}")
    rows = [json.loads(line) for line in out.stdout.splitlines() if line]
    check(rows, "ts_export emitted no samples")
    check(any(r.get("series") == "opt.step" for r in rows),
          "ts_export missing opt.step samples")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ts_export.py"),
         "--cp", f"127.0.0.1:{PORT}", "--world", "1",
         "--format", "openmetrics"],
        env=env, capture_output=True, text=True, timeout=120)
    check(out.returncode == 0, f"ts_export openmetrics failed: "
          f"{out.stderr}")
    lines = out.stdout.strip().splitlines()
    check(lines and lines[-1] == "# EOF", "OpenMetrics not EOF-terminated")
    sample_re = re.compile(
        r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+-]+( \d+)?$")
    for line in lines[:-1]:
        if line.startswith("# TYPE"):
            check(re.match(r"^# TYPE [a-zA-Z_:][a-zA-Z0-9_:]* gauge$",
                           line), f"bad TYPE line: {line!r}")
        elif line.startswith("#"):
            check(line.startswith("# HELP "), f"bad comment: {line!r}")
        else:
            check(sample_re.match(line), f"bad sample line: {line!r}")

    # 6) step_attribution --live: per-edge bytes without a dump
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "step_attribution.py"),
         "--live", "--cp", f"127.0.0.1:{PORT}", "--json"],
        env=env, capture_output=True, text=True, timeout=120)
    check(out.returncode == 0, f"step_attribution --live failed: "
          f"{out.stderr}")
    rep = json.loads(out.stdout)
    check(rep.get("live") and rep.get("edges"),
          f"--live report has no edges: {rep}")

    opt.free()
    bf.shutdown()
    print("obs-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
