"""Benchmark the full distributed-optimizer matrix (VERDICT r4 #3).

Runs examples/benchmark.py across every ``--dist-optimizer`` mode on the
8-device CPU-simulated mesh (relative step cost, same tiny MLP model), the
comparison the reference published as its own benchmark harness
(examples/pytorch_benchmark.py:52-60). Results go to stdout as one JSON
line per mode; PERF.md records the table.

Usage:  python scripts/opt_matrix_bench.py [--chip] [--quick] [--modes ...]
  --chip:  additionally run the single-chip-meaningful modes on the real
           TPU (resnet50, batch 64) — at n=1 collectives are degenerate, so
           this isolates per-mode dispatch overhead on the real device.
  --quick: 1 warmup / 2 batches / 1 iter per mode — the CI smoke setting
           (tests/test_benchmark_smoke.py); exercises every mode's full
           launch+step path in seconds, numbers NOT meaningful for PERF.md.
  --hybrid: sweep the window-plane policy x overlap matrix (ISSUE r13) on
           the single-host multi-controller harness (world-1 control plane,
           forced-hosted window, static exp2 topology — every edge
           compiled-eligible under `auto`): `hosted` is the mailbox-plane
           baseline, `auto` the per-edge hybrid plane, `auto`+overlap the
           double-buffered residual. Auto rows report `speedup_vs_hosted`;
           the acceptance bar is >= 1.5x. Then replays the plane
           equivalence suite (tests/test_win_planes.py) so the speedup and
           the bit-exactness/mass-conservation proofs come from one run.
  --sharded: sweep BLUEFOG_WIN_SHARD x BLUEFOG_WIN_CODEC (SHARD_SWEEP)
           over the win_put optimizer on the world-1 hosted harness with
           the LM-shaped model (--model lm: embedding + attention-block +
           norm leaves), so the partition rules are exercised on
           realistic shapes. NOTE the world-1 harness has no
           cross-controller wire, so `speedup_vs_s1` < 1 isolates the
           HOST-SIDE rotation cost (pack/scatter + smaller-buffer op
           overhead); the wire win itself is win_microbench --sharded's
           counter-delta-verified 4-process measurement
           (docs/sharded_windows.md).
  --codec: sweep BLUEFOG_WIN_CODEC (none, int8, fp8, topk:0.01) over the
           win_put optimizer on the same world-1 hosted-window harness
           (plane pinned to `hosted`). NOTE the world-1 harness has no
           cross-controller wire — every deposit folds locally — so this
           sweep isolates the HOST-SIDE codec cost (encode + decode per
           gossip step, `speedup_vs_none` < 1 by construction); the wire
           win itself is win_microbench --codec's 4-process measurement
           (docs/compression.md).
"""

import argparse
import json
import os
import re
import socket
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent

# Flight dumps from a bench run (deliberate fault probes included) land in
# a tempdir instead of littering the CWD, the same default the test
# suite's conftest applies; an explicit BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

MODES = [
    "neighbor_allreduce", "allreduce", "gradient_allreduce",
    "hierarchical_neighbor_allreduce", "sharded_allreduce",
    "win_put", "push_sum", "pull_get", "local",
]
# window modes drive the hosted plane through a control plane even in one
# process; at n=1-chip they still exercise the full op path
CHIP_MODES = ["gradient_allreduce", "neighbor_allreduce", "win_put"]

RATE_RE = re.compile(r"Total img/sec on \d+ chip\(s\): ([0-9.]+) \+-([0-9.]+)")


def run_mode(mode: str, simulate: int, extra=(), quick: bool = False) -> dict:
    # CPU-mesh rows must not depend on the accelerator tunnel: pin the
    # platform so simulated children skip the TPU-plugin probe (a
    # multi-minute per-process timeout when the tunnel is down).
    env = dict(os.environ, JAX_PLATFORMS="cpu") if simulate else None
    cmd = [sys.executable, "-m", "bluefog_tpu.launcher"]
    if simulate:
        cmd += ["--simulate", str(simulate)]
    reps = ("1", "2", "1") if quick else ("3", "5", "3")
    cmd += ["--", sys.executable, str(REPO / "examples" / "benchmark.py"),
            "--model", "mlp", "--batch-size", "8",
            "--num-warmup-batches", reps[0], "--num-batches-per-iter",
            reps[1], "--num-iters", reps[2], "--dist-optimizer", mode,
            *extra]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=env)
    m = RATE_RE.search(r.stdout)
    if r.returncode != 0 or not m:
        return {"mode": mode, "error": (r.stdout + r.stderr)[-500:]}
    return {"mode": mode, "img_per_sec": float(m.group(1)),
            "ci": float(m.group(2))}


# (plane, overlap) sweep of the hybrid harness; "hosted"/ov0 is the baseline
HYBRID_SWEEP = [("hosted", "0"), ("auto", "0"), ("auto", "1")]

# wire-codec sweep on the forced-hosted harness; "none" is the baseline
CODEC_SWEEP = ["none", "int8", "fp8", "topk:0.01"]

# sharded-window sweep (ISSUE r17): shard factor x codec, on the
# LM-shaped param tree fixture (examples/benchmark.py --model lm:
# embedding + attention-block + norm leaves) so the partition rules are
# exercised on realistic shapes; S=1 is the per-codec baseline
SHARD_SWEEP = [(1, "none"), (2, "none"), (4, "none"),
               (1, "int8"), (4, "int8")]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_hybrid_mode(mode: str, plane: str, overlap: str,
                    quick: bool = False) -> dict:
    """One benchmark child on the world-1 control-plane harness with the
    window plane pinned: the hosted window is forced (legacy knob) so the
    same mailbox machinery serves as baseline (`hosted`) and as the hybrid
    residual (`auto`) — only the plane policy and overlap knob move."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        BLUEFOG_CP_HOST="127.0.0.1", BLUEFOG_CP_PORT=str(_free_port()),
        BLUEFOG_CP_WORLD="1", BLUEFOG_CP_RANK="0",
        BLUEFOG_WIN_HOST_PLANE="1", BLUEFOG_WIN_PLANE=plane,
        BLUEFOG_WIN_OVERLAP=overlap)
    env.pop("BLUEFOG_CP_FAULT", None)  # never bench under fault injection
    cmd = [sys.executable, "-m", "bluefog_tpu.launcher",
           "--simulate", "8", "--"]
    reps = ("1", "2", "1") if quick else ("3", "5", "3")
    cmd += [sys.executable, str(REPO / "examples" / "benchmark.py"),
            "--model", "mlp", "--batch-size", "8",
            "--num-warmup-batches", reps[0], "--num-batches-per-iter",
            reps[1], "--num-iters", reps[2], "--dist-optimizer", mode,
            "--disable-dynamic-topology"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=env)
    m = RATE_RE.search(r.stdout)
    base = {"mode": mode, "plane": plane, "overlap": int(overlap)}
    if r.returncode != 0 or not m:
        return {**base, "error": (r.stdout + r.stderr)[-500:]}
    return {**base, "img_per_sec": float(m.group(1)),
            "ci": float(m.group(2))}


def run_hybrid(modes, quick: bool) -> int:
    rc = 0
    for mode in modes:
        baseline = None
        for plane, overlap in HYBRID_SWEEP:
            res = run_hybrid_mode(mode, plane, overlap, quick=quick)
            res["where"] = "cpu-mesh-8dev-mlp-b8-cp1-hosted-win"
            if "error" in res:
                rc = 1
            elif plane == "hosted":
                baseline = res["img_per_sec"]
            elif baseline:
                res["speedup_vs_hosted"] = round(
                    res["img_per_sec"] / baseline, 2)
            print(json.dumps(res), flush=True)
    # the acceptance criterion couples the speedup to the equivalence
    # proofs: replay the plane suite in the same run
    t = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_win_planes.py", "-q"],
        capture_output=True, text=True, timeout=1200, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    print(json.dumps({
        "mode": "win_planes_equivalence",
        "passed": t.returncode == 0,
        "tail": t.stdout.strip().splitlines()[-1] if t.stdout else ""}),
        flush=True)
    return rc or int(t.returncode != 0)


def run_codec_mode(mode: str, codec: str, quick: bool = False) -> dict:
    """One benchmark child on the world-1 hosted-window harness with the
    wire codec pinned: the plane is forced `hosted` so every gossip byte
    rides the mailbox wire the codec compresses (the plane policy stays
    out of the comparison)."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        BLUEFOG_CP_HOST="127.0.0.1", BLUEFOG_CP_PORT=str(_free_port()),
        BLUEFOG_CP_WORLD="1", BLUEFOG_CP_RANK="0",
        BLUEFOG_WIN_PLANE="hosted")
    if codec != "none":
        env["BLUEFOG_WIN_CODEC"] = codec
    else:
        env.pop("BLUEFOG_WIN_CODEC", None)
    env.pop("BLUEFOG_CP_FAULT", None)  # never bench under fault injection
    cmd = [sys.executable, "-m", "bluefog_tpu.launcher",
           "--simulate", "8", "--"]
    reps = ("1", "2", "1") if quick else ("3", "5", "3")
    cmd += [sys.executable, str(REPO / "examples" / "benchmark.py"),
            "--model", "mlp", "--batch-size", "8",
            "--num-warmup-batches", reps[0], "--num-batches-per-iter",
            reps[1], "--num-iters", reps[2], "--dist-optimizer", mode,
            "--disable-dynamic-topology"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=env)
    m = RATE_RE.search(r.stdout)
    base = {"mode": mode, "codec": codec}
    if r.returncode != 0 or not m:
        return {**base, "error": (r.stdout + r.stderr)[-500:]}
    return {**base, "img_per_sec": float(m.group(1)),
            "ci": float(m.group(2))}


def run_codecs(modes, quick: bool) -> int:
    rc = 0
    for mode in modes:
        baseline = None
        for codec in CODEC_SWEEP:
            res = run_codec_mode(mode, codec, quick=quick)
            res["where"] = "cpu-mesh-8dev-mlp-b8-cp1-hosted-win"
            if "error" in res:
                rc = 1
            elif codec == "none":
                baseline = res["img_per_sec"]
            elif baseline:
                res["speedup_vs_none"] = round(
                    res["img_per_sec"] / baseline, 2)
            print(json.dumps(res), flush=True)
    return rc


def run_sharded_mode(mode: str, shard: int, codec: str,
                     quick: bool = False) -> dict:
    """One benchmark child on the world-1 hosted-window harness with the
    shard factor (and optionally the wire codec) pinned, over the
    LM-shaped model so the partition rules cut realistic leaves
    (embedding rows, qkv/mlp matrices, whole norm scales)."""
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        BLUEFOG_CP_HOST="127.0.0.1", BLUEFOG_CP_PORT=str(_free_port()),
        BLUEFOG_CP_WORLD="1", BLUEFOG_CP_RANK="0",
        BLUEFOG_WIN_PLANE="hosted")
    if shard > 1:
        env["BLUEFOG_WIN_SHARD"] = str(shard)
    else:
        env.pop("BLUEFOG_WIN_SHARD", None)
    if codec != "none":
        env["BLUEFOG_WIN_CODEC"] = codec
    else:
        env.pop("BLUEFOG_WIN_CODEC", None)
    env.pop("BLUEFOG_CP_FAULT", None)  # never bench under fault injection
    cmd = [sys.executable, "-m", "bluefog_tpu.launcher",
           "--simulate", "8", "--"]
    reps = ("1", "2", "1") if quick else ("3", "5", "3")
    cmd += [sys.executable, str(REPO / "examples" / "benchmark.py"),
            "--model", "lm", "--batch-size", "8",
            "--num-warmup-batches", reps[0], "--num-batches-per-iter",
            reps[1], "--num-iters", reps[2], "--dist-optimizer", mode,
            "--disable-dynamic-topology"]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=900,
                       cwd=REPO, env=env)
    m = RATE_RE.search(r.stdout)
    base = {"mode": mode, "shard": shard, "codec": codec}
    if r.returncode != 0 or not m:
        return {**base, "error": (r.stdout + r.stderr)[-500:]}
    return {**base, "img_per_sec": float(m.group(1)),
            "ci": float(m.group(2))}


def run_sharded(modes, quick: bool) -> int:
    rc = 0
    for mode in modes:
        baselines = {}
        for shard, codec in SHARD_SWEEP:
            res = run_sharded_mode(mode, shard, codec, quick=quick)
            res["where"] = "cpu-mesh-8dev-lm-b8-cp1-hosted-win"
            if "error" in res:
                rc = 1
            elif shard == 1:
                baselines[codec] = res["img_per_sec"]
            elif baselines.get(codec):
                res["speedup_vs_s1"] = round(
                    res["img_per_sec"] / baselines[codec], 2)
            print(json.dumps(res), flush=True)
    return rc


def run_chip_mode(mode: str) -> dict:
    cmd = [sys.executable, str(REPO / "examples" / "benchmark.py"),
           "--model", "resnet50", "--batch-size", "64",
           "--num-warmup-batches", "5", "--num-batches-per-iter", "5",
           "--num-iters", "3", "--dist-optimizer", mode]
    r = subprocess.run(cmd, capture_output=True, text=True, timeout=1800,
                       cwd=REPO)
    m = RATE_RE.search(r.stdout)
    if r.returncode != 0 or not m:
        return {"mode": mode, "error": (r.stdout + r.stderr)[-500:]}
    return {"mode": mode, "img_per_sec": float(m.group(1)),
            "ci": float(m.group(2))}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--chip", action="store_true")
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--hybrid", action="store_true")
    ap.add_argument("--codec", action="store_true")
    ap.add_argument("--sharded", action="store_true")
    ap.add_argument("--modes", nargs="*", default=None)
    args = ap.parse_args()
    rc = 0
    if args.sharded:
        return run_sharded(args.modes or ["win_put"], quick=args.quick)
    if args.codec:
        return run_codecs(args.modes or ["win_put"], quick=args.quick)
    if args.hybrid:
        return run_hybrid(args.modes or ["win_put"], quick=args.quick)
    if args.chip:
        for mode in (args.modes or CHIP_MODES):
            res = run_chip_mode(mode)
            res["where"] = "tpu-1chip-resnet50-b64"
            print(json.dumps(res), flush=True)
            rc = rc or ("error" in res)
    else:
        for mode in (args.modes or MODES):
            extra = ()
            if mode != "neighbor_allreduce":
                # dynamic Expo-2 applies only to neighbor_allreduce; keep
                # the others on their natural static path
                extra = ("--disable-dynamic-topology",)
            res = run_mode(mode, simulate=8, extra=extra, quick=args.quick)
            res["where"] = "cpu-mesh-8dev-mlp-b8"
            print(json.dumps(res), flush=True)
            rc = rc or ("error" in res)
    return int(rc)


if __name__ == "__main__":
    sys.exit(main())
