#!/usr/bin/env python
"""Merge per-rank chrome-tracing timeline files into one trace.

Every bluefog_tpu timeline file is a self-contained chrome-tracing JSON
array whose timestamps count from a *per-process* perf_counter origin, so
two ranks' files cannot be overlaid as-is. Each trace's first event is a
clock-sync counter (``bf.clock_sync_us``, runtime/timeline.py) carrying
the wall-clock microseconds at its capture timestamp; this script shifts
every file onto the common wall-clock axis (rebased so the earliest event
sits at ts=0), concatenates the event arrays, and adds process_name
metadata per pid.

After the merge, the hosted window plane's flow events (``cat:
"bf.flow"``, ids = deposit-tag sequences) bind across processes: a
``win_put`` deposit on rank A draws an arrow to its drain inside rank B's
``win_update`` in chrome://tracing / Perfetto.

Usage:
    python scripts/merge_timelines.py /tmp/tl_0.json /tmp/tl_1.json ... \
        [-o merged.json]
"""

from __future__ import annotations

import argparse
import json
import sys

CLOCK_SYNC = "bf.clock_sync_us"


def load_events(path: str) -> list:
    with open(path) as f:
        events = json.load(f)
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a chrome-tracing event array")
    return events


def wall_offset_us(events: list, path: str):
    """wall_us - trace_ts for this file (from its clock-sync counter), or
    None when the anchor is missing (old build / truncated file) — the
    caller warns and leaves that file's timestamps unshifted rather than
    silently misaligning every rank."""
    for ev in events:
        if ev.get("name") == CLOCK_SYNC and ev.get("ph") == "C":
            value = ev.get("args", {}).get("value")
            if value is None:
                break
            return float(value) - float(ev.get("ts", 0.0))
    return None


def merge(paths) -> list:
    per_file = []
    for p in paths:
        events = load_events(p)
        off = wall_offset_us(events, p)
        if off is None:
            print(
                f"WARNING: {p}: no '{CLOCK_SYNC}' clock-sync anchor "
                "(produced by an old build, or the trace was truncated "
                "before its first event) — leaving its timestamps "
                "UNSHIFTED; cross-rank ordering against this file is not "
                "meaningful", file=sys.stderr)
        per_file.append((p, events, off))
    anchored = [off for _, _, off in per_file if off is not None]
    base = min(anchored) if anchored else 0.0
    merged = []
    pids = set()
    for path, events, off in per_file:
        shift = (off - base) if off is not None else 0.0
        for ev in events:
            if "ts" in ev:
                ev = dict(ev)
                ev["ts"] = float(ev["ts"]) + shift
            merged.append(ev)
            if "pid" in ev:
                pids.add(ev["pid"])
    merged.sort(key=lambda e: e.get("ts", 0.0))
    for pid in sorted(pids):
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "args": {"name": f"bluefog rank {pid}"}})
    return merged


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("files", nargs="+", help="per-rank timeline JSON files")
    ap.add_argument("-o", "--output", default="merged_timeline.json")
    args = ap.parse_args(argv)
    merged = merge(args.files)
    with open(args.output, "w") as f:
        json.dump(merged, f)
    flows = sum(1 for e in merged if e.get("ph") in ("s", "f"))
    print(f"merged {len(args.files)} trace(s), {len(merged)} events "
          f"({flows} flow events) -> {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
