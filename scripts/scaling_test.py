"""Rank-scaling probe: throughput vs simulated mesh size per optimizer.

Analog of the reference's scripts/pytorch_opt_linear_speedup_test.py:
run the benchmark harness at 1/2/4/8 ranks (each in its own process via
``bfrun --simulate N`` — the device count is fixed at backend init) and
report total img/s, so collective overhead growth with rank count is
visible at a glance. CPU-mesh numbers regression-track the *overhead
scaling*, not absolute TPU speed.

Usage: python scripts/scaling_test.py [--model mlp] [--ranks 1 2 4 8]
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys
import tempfile
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent



# Flight dumps from a bench run land in a tempdir instead of littering
# the CWD (conftest's default for the test suite); an explicit
# BLUEFOG_FLIGHT_DIR still wins.
os.environ.setdefault("BLUEFOG_FLIGHT_DIR",
                      tempfile.mkdtemp(prefix="bf_flight_"))

def run_one(ranks: int, model: str, dist_opt: str, batch: int) -> float:
    env = os.environ.copy()
    # scrub anything that would make the child join a stale distributed
    # job or foreign control plane instead of benchmarking a local mesh
    for k in ("XLA_FLAGS", "JAX_PLATFORMS", "BLUEFOG_TIMELINE",
              "JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
              "JAX_PROCESS_ID", "BLUEFOG_CP_HOST", "BLUEFOG_CP_PORT"):
        env.pop(k, None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-m", "bluefog_tpu.launcher",
         "--simulate", str(ranks), "--",
         sys.executable, str(REPO / "examples" / "benchmark.py"),
         "--model", model, "--batch-size", str(batch),
         "--num-warmup-batches", "2", "--num-batches-per-iter", "5",
         "--num-iters", "3", "--dist-optimizer", dist_opt],
        env=env, capture_output=True, text=True, timeout=900,
    )
    if out.returncode != 0:
        raise RuntimeError(f"ranks={ranks} failed:\n{out.stdout}{out.stderr}")
    m = re.search(r"Total img/sec on \d+ chip\(s\):\s*([0-9.]+)", out.stdout)
    assert m, out.stdout
    return float(m.group(1))


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="mlp")
    p.add_argument("--dist-optimizer", default="neighbor_allreduce")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--ranks", type=int, nargs="+", default=[1, 2, 4, 8])
    args = p.parse_args()

    base = None
    print(f"model={args.model} optimizer={args.dist_optimizer} "
          f"batch={args.batch_size}/rank")
    print("NOTE: simulated ranks SHARE the host's cores, so the ideal is a "
          "FLAT total (100% retention), not an Nx speedup; the retention "
          "column isolates partitioning+collective+dispatch overhead.")
    print(f"{'ranks':>6} {'total img/s':>12} {'retention':>10}")
    for n in args.ranks:
        rate = run_one(n, args.model, args.dist_optimizer, args.batch_size)
        if base is None:
            base = rate
        print(f"{n:>6} {rate:>12.1f} {100 * rate / base:>9.0f}%")


if __name__ == "__main__":
    main()
