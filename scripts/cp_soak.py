#!/usr/bin/env python
"""Control-plane churn soak: N shard servers vs hundreds of raw clients.

Scenario coverage no unit test reaches (ROADMAP "Control-plane scale-out +
1000-rank soak"): 500-1000 lightweight raw clients — no JAX anywhere in
this harness — hammering heartbeats, locks, fetch_add counters, and
deposit/drain cycles against a SHARDED control plane while the harness
SIGKILLs a server mid-run and (with ``--churn``) rolls clients through
incarnation-bumped reattach cycles. Asserted invariants:

* **health convergence** — after the kill, every client's router converges
  on the same dead-shard set (peer-published failover flags + its own
  detection), and a fresh probe sees every client's final heartbeat;
* **exactly-once counters** — each client's private counter hands out
  contiguous pre-add values within an ownership era (a dedup failure
  would duplicate or skip); across the failover boundary the era resets
  at most once, exactly when ownership moved;
* **conserved deposit mass** — per client, bytes acked == bytes drained
  + bytes lost, and bytes can only be lost by the kill landing between
  an append-ack and the drain (at most one cycle per client per kill);
* **bounded server memory** — surviving servers' VmRSS stays under
  ``--rss-limit-mb`` despite the churn (dedup GC + incarnation GC work).

Invocations:
    python scripts/cp_soak.py --clients 500 --churn      # the ROADMAP soak
    python scripts/cp_soak.py --quick                    # make soak-smoke
"""

from __future__ import annotations

import argparse
import os
import random
import signal
import subprocess
import sys
import threading
import time
import types

# Lean bootstrap (no jax): register dummy parent packages so the runtime
# modules import without executing bluefog_tpu/__init__.
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_ROOT, "bluefog_tpu")
sys.path.insert(0, _ROOT)
for _name, _path in (("bluefog_tpu", _PKG),
                     ("bluefog_tpu.runtime", os.path.join(_PKG, "runtime"))):
    if _name not in sys.modules:
        _mod = types.ModuleType(_name)
        _mod.__path__ = [_path]
        sys.modules[_name] = _mod

from bluefog_tpu.runtime.native import (  # noqa: E402
    ControlPlaneClient, PeerLostError, load)
from bluefog_tpu.runtime.router import ShardRouter  # noqa: E402

SHARD_SERVER = os.path.join(_PKG, "runtime", "shard_server.py")


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--shards", type=int, default=2)
    p.add_argument("--clients", type=int, default=128)
    p.add_argument("--duration", type=float, default=30.0,
                   help="seconds of load (the kill lands mid-way)")
    p.add_argument("--churn", action="store_true",
                   help="clients periodically close and reattach with a "
                        "bumped incarnation (elastic-membership churn)")
    p.add_argument("--kill-shard", type=int, default=None,
                   help="shard index to SIGKILL mid-run (default: the "
                        "last shard; negative disables the kill)")
    p.add_argument("--rss-limit-mb", type=float, default=512.0)
    p.add_argument("--record-bytes", type=int, default=2048,
                   help="max deposit record size")
    p.add_argument("--quick", action="store_true",
                   help="smoke preset (<= 60 s): 64 clients, 2 shards, "
                        "~18 s of load, churn on, one injected kill")
    args = p.parse_args(argv)
    if args.quick:
        args.shards = 2
        args.clients = min(args.clients, 64)
        args.duration = min(args.duration, 18.0)
        args.churn = True
    if args.kill_shard is None:
        args.kill_shard = args.shards - 1
    return args


def spawn_shard(index: int, world: int):
    proc = subprocess.Popen(
        [sys.executable, SHARD_SERVER, "--port", "0", "--world", str(world),
         "--shard", str(index)],
        stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    if not line.startswith("BF_SHARD_READY"):
        raise RuntimeError(f"shard {index} failed to start: {line!r}")
    return proc, int(line.split()[1])


def vm_rss_mb(pid: int) -> float:
    try:
        with open(f"/proc/{pid}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


class Worker(threading.Thread):
    """One raw client: heartbeat + counter + lock + deposit/drain loop."""

    def __init__(self, wid: int, endpoints, deadline: float, churn: bool,
                 record_bytes: int) -> None:
        super().__init__(daemon=True, name=f"soak-{wid}")
        self.wid = wid
        self.endpoints = endpoints
        self.deadline = deadline
        self.churn = churn
        self.rng = random.Random(1000 + wid)
        self.record_bytes = max(64, record_bytes)
        self.inc = 0
        self.errors: list = []
        # ledgers
        self.ops = 0
        self.acked_bytes = 0
        self.drained_bytes = 0
        self.lost_bytes = 0
        self.lost_cycles = 0
        self.reattaches = 0
        self.peer_lost = 0
        self.last_hb = 0
        self.dead_seen: set = set()
        self.counter_eras = 1
        self.counter_acks = 0

    def _attach(self) -> ShardRouter:
        # Same contract as control_plane.attach: retry the connect for a
        # bounded window — a reattach can land in the instant AFTER a
        # shard died but BEFORE any survivor published its dead flag, and
        # the strict router correctly refuses until the flag appears.
        deadline = time.monotonic() + 10.0
        while True:
            try:
                return ShardRouter(self.endpoints, self.wid, streams=1,
                                   incarnation=self.inc)
            except OSError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.1)

    def run(self) -> None:  # noqa: C901 — the soak loop is one scenario
        ckey = f"soak.ctr.{self.wid}"
        box = f"soak.box.{self.wid}"
        hb = f"soak.hb.{self.wid}"
        try:
            r = self._attach()
        except Exception as exc:  # noqa: BLE001 — recorded, fails the soak
            self.errors.append(f"attach: {exc!r}")
            return
        expected = None
        cur_owner = r.owner_of(ckey)
        next_churn = time.monotonic() + self.rng.uniform(4.0, 8.0)
        next_poll = time.monotonic() + self.rng.uniform(0.5, 1.5)
        try:
            while time.monotonic() < self.deadline:
                self.ops += 1
                # heartbeat
                self.last_hb += 1
                r.put(hb, self.last_hb)
                # exactly-once counter, era-checked: within one ownership
                # era the pre-add values must be contiguous (a dedup slip
                # duplicates or skips); a failover resets the era because
                # the dead shard's counter state died with it
                owner = r.owner_of(ckey)
                if owner != cur_owner:
                    cur_owner, expected = owner, None
                    self.counter_eras += 1
                pre = r.fetch_add(ckey, 1)
                self.counter_acks += 1
                owner2 = r.owner_of(ckey)
                if owner2 != cur_owner:
                    cur_owner, expected = owner2, pre + 1
                    self.counter_eras += 1
                elif expected is None:
                    expected = pre + 1
                else:
                    if pre != expected:
                        self.errors.append(
                            f"counter era violation: pre={pre} "
                            f"expected={expected}")
                    expected = pre + 1
                # occasional contended lock (typed degradation tolerated)
                if self.ops % 7 == 0:
                    lk = f"soak.lock.{self.wid % 8}"
                    try:
                        r.lock(lk)
                        r.unlock(lk)
                    except PeerLostError:
                        self.peer_lost += 1
                # deposit/drain cycle with a mass ledger: bytes can only
                # be lost when the kill lands between ack and drain
                nrec = self.rng.randint(1, 4)
                blobs = [bytes([self.rng.randint(0, 255)]) *
                         self.rng.randint(64, self.record_bytes)
                         for _ in range(nrec)]
                replies = r.append_bytes_many([box] * nrec, blobs)
                cycle_acked = sum(
                    len(b) for b, rep in zip(blobs, replies) if rep >= 1)
                self.acked_bytes += cycle_acked
                drained = sum(len(x) for lst in r.take_bytes_many([box])
                              for x in lst)
                self.drained_bytes += drained
                if drained < cycle_acked:
                    self.lost_bytes += cycle_acked - drained
                    self.lost_cycles += 1
                elif drained > cycle_acked:
                    self.errors.append(
                        f"drained {drained} > acked {cycle_acked} "
                        "(duplicated deposit records)")
                now = time.monotonic()
                if now >= next_poll:
                    self.dead_seen |= r.poll_shard_health()
                    next_poll = now + self.rng.uniform(0.5, 1.5)
                if self.churn and now >= next_churn:
                    # elastic churn: the respawn path — close, bump the
                    # incarnation, reattach (servers fence the zombie and
                    # GC its dedup/mailbox state on every shard)
                    r.close()
                    self.inc += 1
                    r = self._attach()
                    cur_owner, expected = r.owner_of(ckey), None
                    self.reattaches += 1
                    next_churn = now + self.rng.uniform(4.0, 8.0)
            self.dead_seen |= r.poll_shard_health()
        except Exception as exc:  # noqa: BLE001 — recorded, fails the soak
            self.errors.append(f"loop died at op {self.ops}: {exc!r}")
        finally:
            try:
                r.close()
            except Exception:  # noqa: BLE001 — teardown
                pass


def main(argv=None) -> int:
    args = parse_args(argv)
    if load() is None:
        print("cp_soak: native runtime unavailable", file=sys.stderr)
        return 1
    t0 = time.time()
    os.environ.setdefault("BLUEFOG_CP_BACKOFF_MS", "20")
    servers = [spawn_shard(i, 1) for i in range(args.shards)]
    endpoints = [("127.0.0.1", port) for _, port in servers]
    print(f"cp_soak: {args.shards} shard(s) up "
          f"({','.join(str(p) for _, p in servers)}); "
          f"{args.clients} client(s), {args.duration:.0f}s"
          + (", churn" if args.churn else "")
          + (f", SIGKILL shard {args.kill_shard} mid-run"
             if args.kill_shard >= 0 else ""))

    deadline = time.monotonic() + args.duration
    workers = [Worker(i, endpoints, deadline, args.churn, args.record_bytes)
               for i in range(args.clients)]
    for w in workers:
        w.start()

    killed = None
    if 0 <= args.kill_shard < args.shards:
        time.sleep(args.duration * 0.45)
        victim, _ = servers[args.kill_shard]
        victim.send_signal(signal.SIGKILL)
        victim.wait()
        killed = args.kill_shard
        print(f"cp_soak: SIGKILLed shard {killed} at t+{args.duration * 0.45:.0f}s")

    for w in workers:
        w.join(timeout=args.duration + 120)
    stuck = [w.wid for w in workers if w.is_alive()]

    failures: list = []
    if stuck:
        failures.append(f"{len(stuck)} client(s) never finished: "
                        f"{stuck[:10]}")
    for w in workers:
        for e in w.errors:
            failures.append(f"client {w.wid}: {e}")
        if w.lost_cycles > (1 if killed is not None else 0):
            failures.append(
                f"client {w.wid}: {w.lost_cycles} lossy deposit cycles "
                "(only the kill window may lose one)")
        if w.acked_bytes != w.drained_bytes + w.lost_bytes:
            failures.append(
                f"client {w.wid}: mass leak — acked {w.acked_bytes} != "
                f"drained {w.drained_bytes} + lost {w.lost_bytes}")
        if killed is not None and not stuck and \
                w.dead_seen != {killed} and killed not in w.dead_seen:
            failures.append(
                f"client {w.wid}: never converged on dead shard "
                f"{killed} (saw {sorted(w.dead_seen)})")

    # fresh probe: health view converges from the outside too, and every
    # client's final heartbeat reads back through failover routing
    probe = ShardRouter(endpoints, 10 ** 6, streams=1, lenient=True)
    probe.poll_shard_health()
    if killed is not None and killed not in probe.dead_shards():
        failures.append(
            f"probe router did not converge on dead shard {killed}")
    finished = [w for w in workers if not w.is_alive() and not w.errors]
    hb_vals = probe.get_many([f"soak.hb.{w.wid}" for w in finished])
    hb_bad = sum(1 for w, v in zip(finished, hb_vals) if v != w.last_hb)
    # a heartbeat written to the victim's keyspace JUST before the kill is
    # allowed to be stale only if the client never wrote again after
    # failover — it always does (the loop outlives the kill), so mismatch
    # means failover routing diverged between writer and prober
    if hb_bad:
        failures.append(f"{hb_bad} final heartbeat(s) unreadable through "
                        "failover routing")

    rss = {i: vm_rss_mb(proc.pid) for i, (proc, _) in enumerate(servers)
           if i != killed}
    for i, mb in rss.items():
        if mb > args.rss_limit_mb:
            failures.append(f"shard {i} RSS {mb:.0f} MB exceeds the "
                            f"{args.rss_limit_mb:.0f} MB bound")

    total_ops = sum(w.ops for w in workers)
    total_acked = sum(w.acked_bytes for w in workers)
    total_lost = sum(w.lost_bytes for w in workers)
    lossy = sum(w.lost_cycles for w in workers)
    print(f"cp_soak: {total_ops} cycles, "
          f"{sum(w.counter_acks for w in workers)} counter acks "
          f"({sum(w.counter_eras for w in workers)} eras), "
          f"{total_acked / 1e6:.1f} MB deposited, "
          f"{total_lost} B lost in {lossy} kill-window cycle(s), "
          f"{sum(w.reattaches for w in workers)} churn reattaches, "
          f"{sum(w.peer_lost for w in workers)} typed PeerLost, "
          f"survivor RSS {max(rss.values()):.0f} MB, "
          f"wall {time.time() - t0:.1f}s")

    for i, (proc, _) in enumerate(servers):
        if proc.poll() is None:
            proc.terminate()
    for proc, _ in servers:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

    if failures:
        print("cp_soak: FAIL", file=sys.stderr)
        for f in failures[:40]:
            print(f"  {f}", file=sys.stderr)
        return 1
    print("cp_soak: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
